"""Micro-timings: flash kernel, matmuls, CE, on the real chip."""
import sys, time, math, functools
import numpy as np
import jax, jax.numpy as jnp

sys.path.insert(0, "/root/repo")
B, S, NH, D, H, V = 32, 1024, 12, 64, 768, 50304

def _sync(r):
    leaves = jax.tree.leaves(r)
    for x in leaves:
        np.asarray(x.ravel()[0])

def timeit(f, *args, n=10, warm=2):
    for _ in range(warm):
        r = f(*args)
    _sync(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*args)
    _sync(r)
    return (time.perf_counter() - t0) / n

k = jax.random.PRNGKey(0)
q = jax.random.normal(k, (B, S, NH, D), jnp.bfloat16)
kk = jax.random.normal(k, (B, S, NH, D), jnp.bfloat16)
v = jax.random.normal(k, (B, S, NH, D), jnp.bfloat16)

from hetu_tpu.ops.pallas.flash_attention import flash_attention

fwd = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
t = timeit(fwd, q, kk, v)
fl = 2 * 2 * B * NH * S * S * D / 2 * 1.0  # qk+pv, causal half
print(f"flash fwd: {t*1e3:.2f}ms ({fl/t/1e12:.1f} Tf/s eff)")

def fb(q, k, v):
    return jax.grad(lambda q, k, v: flash_attention(q, k, v, causal=True)
                    .astype(jnp.float32).sum(), argnums=(0, 1, 2))(q, k, v)
fbj = jax.jit(fb)
t = timeit(fbj, q, kk, v)
print(f"flash fwd+bwd(grad only): {t*1e3:.2f}ms (flops~{3.5*fl/t/1e12:.1f} Tf/s eff)")

# matmul floor: the per-layer matmuls fwd
a = jax.random.normal(k, (B * S, H), jnp.bfloat16)
w1 = jax.random.normal(k, (H, 3 * H), jnp.bfloat16)
w2 = jax.random.normal(k, (H, H), jnp.bfloat16)
w3 = jax.random.normal(k, (H, 4 * H), jnp.bfloat16)
w4 = jax.random.normal(k, (4 * H, H), jnp.bfloat16)
mm = jax.jit(lambda a: ((a @ w1)[:, :H] @ w2) + (jax.nn.gelu(a @ w3) @ w4))
t = timeit(mm, a)
fl = 2 * B * S * H * (3 * H + H + 4 * H + 4 * H)
print(f"layer-matmuls fwd: {t*1e3:.2f}ms ({fl/t/1e12:.1f} Tf/s eff)")

# lm head + CE variants
x = jax.random.normal(k, (B * S, H), jnp.bfloat16)
wv = jax.random.normal(k, (H, V), jnp.bfloat16)
lbl = jnp.asarray(np.random.RandomState(0).randint(0, V, (B * S,)), jnp.int32)

def ce_plain(x, wv):
    lg = (x @ wv).astype(jnp.float32)
    lp = jax.nn.log_softmax(lg, -1)
    return -jnp.mean(jnp.take_along_axis(lp, lbl[:, None], 1))
g1 = jax.jit(jax.grad(ce_plain, argnums=(0, 1)))
t = timeit(g1, x, wv)
fl = 3 * 2 * B * S * H * V
print(f"CE plain fwd+bwd: {t*1e3:.2f}ms ({fl/t/1e12:.1f} Tf/s eff)")

def ce_chunk(x, wv):
    CH = 16
    xc = x.reshape(CH, (B * S) // CH, H)
    lc = lbl.reshape(CH, (B * S) // CH)
    def body(c, op):
        xx, ll = op
        lg = (xx @ wv).astype(jnp.float32)
        lse = jax.nn.logsumexp(lg, -1)
        picked = jnp.take_along_axis(lg, ll[:, None], 1)[:, 0]
        return c + jnp.sum(lse - picked), None
    tot, _ = jax.lax.scan(body, 0.0, (xc, lc))
    return tot / (B * S)
g2 = jax.jit(jax.grad(ce_chunk, argnums=(0, 1)))
t = timeit(g2, x, wv)
print(f"CE chunk16 fwd+bwd: {t*1e3:.2f}ms ({fl/t/1e12:.1f} Tf/s eff)")
