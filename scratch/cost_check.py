"""Scratch: compare predict_cost vs compiled.cost_analysis() on the
gate executables (the tuning loop for the ±10% cross-check)."""
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

from hetu_tpu.analysis.cli import build_gate_executables
from hetu_tpu.analysis.cost import predict_cost
from hetu_tpu.graph.graph import get_executable

names = build_gate_executables()
for name in names:
    h = get_executable(name)
    r = predict_cost(h, xla=True)
    fd, bd = r.xla_flops_delta(), r.xla_bytes_delta()
    print(f"{name:28s} flops {r.cmp_flops + r.cmp_transcendentals:>12.0f} "
          f"xla {r.xla['flops'] + r.xla['transcendentals']:>12.0f} "
          f"d {('%+.1f%%' % (100 * fd)) if fd is not None else 'n/a':>8s}  "
          f"bytes {r.cmp_bytes:>11.0f} xla {r.xla['bytes_accessed']:>11.0f} "
          f"d {('%+.1f%%' % (100 * bd)) if bd is not None else 'n/a':>8s}  "
          f"within={r.xla_within()}")
    print(f"  {r.summary()}")
