import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.models.generate import generate
from hetu_tpu.serving import EngineCluster

cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
                max_seq_len=64, sp=False, dropout=0.0)
ht.set_seed(3)
with ht.graph("eager", create_new=True):
    model = GPTLMHeadModel(cfg)
    model.logits(np.zeros((1, 4), np.int32))
    state = {k: np.asarray(v) for k, v in model.state_dict().items()}

rng = np.random.RandomState(0)
prompts = [rng.randint(1, 97, size=n).tolist() for n in (5, 9, 12, 7)]
NEW = 6

def solo(p):
    return np.asarray(generate(state, cfg, np.asarray([p], np.int32),
                               NEW, temperature=0.0))[0, len(p):].tolist()

want = [solo(p) for p in prompts]

# --- replicated mode ---
clock = [0.0]
cl = EngineCluster(state, cfg, num_replicas=2, name="smoke",
                   num_pages=16, page_size=8, max_batch=4, chunk_size=8,
                   time_fn=lambda: clock[0], heartbeat_interval=0.05,
                   ttl=60.0)
reqs = [cl.add_request(p, NEW, arrival_time=0.0) for p in prompts]
n = 0
while cl.has_work and n < 200:
    cl.step(); clock[0] += 1.0; n += 1
out = {r.req_id: r.out_tokens for r in reqs}
assert all(out[i] == want[i] for i in range(len(prompts))), (out, want)
print("replicated OK", {i: len(out[i]) for i in out})
print("summary:", {k: v for k, v in cl.metrics_summary().items()
                   if k in ("requests_completed", "cluster_routed",
                            "prefix_cache_hit_rate", "alive_replicas")})
txt = cl.metrics_text()
assert 'replica="r0"' in txt and 'replica="r1"' in txt
cl.close()

# --- disaggregated mode ---
clock2 = [0.0]
cl2 = EngineCluster(state, cfg, num_replicas=2, mode="disaggregated",
                    num_prefill=1, name="smoke2",
                    num_pages=16, page_size=8, max_batch=4, chunk_size=8,
                    time_fn=lambda: clock2[0], heartbeat_interval=0.05,
                    ttl=60.0)
reqs2 = [cl2.add_request(p, NEW, arrival_time=float(i))
         for i, p in enumerate(prompts)]
n = 0
while cl2.has_work and n < 300:
    cl2.step(); clock2[0] += 1.0; n += 1
out2 = {r.req_id: r.out_tokens for r in reqs2}
assert all(out2[i] == want[i] for i in range(len(prompts))), (out2, want)
ms = cl2.metrics_summary()
print("disagg OK; handoffs:", ms["cluster_handoffs"],
      "payload:", ms["handoff_payload_bytes"],
      "pred_s:", ms["handoff_predicted_s"])
assert ms["cluster_handoffs"] == len(prompts)
assert len(cl2.transport.records) == len(prompts)
assert all(r["predicted_s"] > 0 for r in cl2.transport.records)

# rule check on the decode replica
from hetu_tpu import analysis
rep = analysis.analyze_registered("smoke2@r1/")
print("decode replica findings:", rep.total_findings if hasattr(rep, "total_findings") else
      sum(len(e.findings) for e in rep.executables.values()))
for name, e in rep.executables.items():
    for f in e.findings:
        print("  !", name, f)
cl2.close()
print("ALL SMOKE OK")
