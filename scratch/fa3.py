"""fa3: fwd with 2D lse output + single fused bwd kernel (dq,dk,dv).

Correctness vs dense, then timing, at S=1024 (the fused bwd needs
the whole sequence as one VMEM block; S=2048 fp32 scores ~16MB
exceed VMEM — the landed kernel tiles instead).
"""
import functools, math, sys, time
import numpy as np
import jax, jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MASKV = -0.7 * float(jnp.finfo(jnp.float32).max)
LANES = 128


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, bq, bk, num_kv):
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)

    @pl.when(kv_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        run = kv_idx * bk <= q_idx * bq + bq - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_idx * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kv_idx * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(cols <= rows, s, MASKV)
        m_prev = m_ref[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_cur[:, None])
        alpha = jnp.exp(m_prev - m_cur)
        l_new = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_cur[:, None], m_ref.shape)

    @pl.when(kv_idx == num_kv - 1)
    def _fin():
        l = l_ref[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l[:, None]).astype(o_ref.dtype)
        m = m_ref[:, 0]
        lse = jnp.where(l == 0.0, -jnp.inf, m + jnp.log(safe_l))
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def flash_fwd(q, k, v, scale, causal, bq=1024, bk=1024):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    bq = min(bq, sq); bk = min(bk, sk)
    num_q, num_kv = sq // bq, sk // bk
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, num_kv=num_kv)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bq, 8), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(qr, kr, vr)
    out = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return out, lse


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                dq_ref, dk_ref, dv_ref, dq_acc, dk_acc, dv_acc,
                *, scale, causal, bq, bk, num_q, num_kv):
    # grid: (bh, kv_idx, q_idx) -- q innermost so dk/dv accumulate in VMEM;
    # dq is accumulated into an HBM-aliased output via input_output_aliasing?
    # Simpler: grid (bh, q_idx, kv_idx) accumulates dq in VMEM; dk/dv use
    # atomic-free revisit -> needs num_q==1 or num_kv==1 for single-kernel.
    # Here: designed for the common num_q==num_kv==1 fast path.
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    o = o_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        rows = lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(cols <= rows, s, MASKV)
    lse_col = lse_ref[0, :, 0][:, None]     # [bq, 1] sublane-major
    p = jnp.exp(s - lse_col)
    p = jnp.where(jnp.isfinite(lse_col), p, 0.0)
    # delta = rowsum(do * o) computed in-kernel
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=1)
    dv_acc[:] = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None]) * scale
    dsl = ds.astype(q.dtype)
    dq_ref[0] = jax.lax.dot_general(
        dsl, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_ref[0] = jax.lax.dot_general(
        dsl, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)
    dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def flash_bwd_fused(q, k, v, o, lse, do, scale, causal):
    """Single-kernel bwd; requires sq == sk == block (full-seq blocks)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    assert sq == sk
    bq = bk = sq
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    dor = do.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    outr = o.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kernel = functools.partial(_bwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, num_q=1, num_kv=1)
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(b * h,),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((1, bq, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((1, bq, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((1, bq, 8), lambda bh: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda bh: (bh, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
    )(qr, kr, vr, dor, outr, lse)
    dq = dq.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    dk = dk.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    dv = dv.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash(q, k, v, scale, causal):
    out, _ = flash_fwd(q, k, v, scale, causal)
    return out

def _f(q, k, v, scale, causal):
    out, lse = flash_fwd(q, k, v, scale, causal)
    return out, (q, k, v, out, lse)

def _b(scale, causal, res, g):
    q, k, v, out, lse = res
    return flash_bwd_fused(q, k, v, out, lse, g, scale, causal)

flash.defvjp(_f, _b)


if __name__ == "__main__":
    B, S, NH, D = 32, 1024, 12, 64
    REP = 20
    key = jax.random.PRNGKey(0)

    def _sync(r):
        for x in jax.tree.leaves(r):
            np.asarray(x.ravel()[0])

    def timeit_rep(body, carry, n=3, warm=1):
        @jax.jit
        def run(c):
            def step(c, _):
                return body(c), None
            c, _ = lax.scan(step, c, None, length=REP)
            return c
        for _ in range(warm):
            r = run(carry)
        _sync(r)
        t0 = time.perf_counter()
        for _ in range(n):
            r = run(carry)
        _sync(r)
        return (time.perf_counter() - t0) / (n * REP)

    scale = 1.0 / math.sqrt(D)

    # correctness: fwd + grads vs dense on small case
    Bs, Ss, Hs = 2, 512, 2
    qs = jax.random.normal(jax.random.PRNGKey(1), (Bs, Ss, Hs, D), jnp.float32)
    ks = jax.random.normal(jax.random.PRNGKey(2), (Bs, Ss, Hs, D), jnp.float32)
    vs = jax.random.normal(jax.random.PRNGKey(3), (Bs, Ss, Hs, D), jnp.float32)

    def dense(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        qi = lax.broadcasted_iota(jnp.int32, (Ss, Ss), 0)
        ki = lax.broadcasted_iota(jnp.int32, (Ss, Ss), 1)
        s = jnp.where(ki <= qi, s, -jnp.inf)
        p = jax.nn.softmax(s, -1).astype(v.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    def lf(f):
        def g(q, k, v):
            o = f(q, k, v)
            return jnp.sum(o.astype(jnp.float32) * jnp.cos(jnp.arange(o.size, dtype=jnp.float32).reshape(o.shape)))
        return g
    g1 = jax.jit(jax.grad(lf(lambda q, k, v: flash(q, k, v, scale, True)), argnums=(0, 1, 2)))(qs, ks, vs)
    g2 = jax.jit(jax.grad(lf(dense), argnums=(0, 1, 2)))(qs, ks, vs)
    for name, a, bb in zip("qkv", g1, g2):
        err = float(jnp.max(jnp.abs(a - bb)))
        rel = err / float(jnp.max(jnp.abs(bb)))
        print(f"d{name} max abs err {err:.5f} rel {rel:.6f}")

    q = jax.random.normal(key, (B, S, NH, D), jnp.bfloat16)
    k = jax.random.normal(key, (B, S, NH, D), jnp.bfloat16)
    v = jax.random.normal(key, (B, S, NH, D), jnp.bfloat16)
    fl = 2 * 2 * B * NH * S * S * D / 2

    t = timeit_rep(lambda c: flash(c, k, v, scale, True), q)
    print(f"fa3 fwd: {t*1e3:.2f}ms ({fl/t/1e12:.1f} Tf/s)")
    def gr(c):
        g = jax.grad(lambda q: flash(q, k, v, scale, True)
                     .astype(jnp.float32).sum())(c)
        return g.astype(jnp.bfloat16)
    t = timeit_rep(gr, q)
    print(f"fa3 fwd+bwd: {t*1e3:.2f}ms")
