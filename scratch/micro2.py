"""Micro-timings v2: repeat work inside ONE jit call via lax.scan to
amortize the axon-relay round-trip latency."""
import sys, time, math, functools
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

sys.path.insert(0, "/root/repo")
B, S, NH, D, H, V = 32, 1024, 12, 64, 768, 50304
REP = 20

def _sync(r):
    for x in jax.tree.leaves(r):
        np.asarray(x.ravel()[0])

def timeit_rep(make_body, carry_init, n=3, warm=1):
    """body: carry -> carry; scanned REP times inside one jit."""
    @jax.jit
    def run(c):
        def step(c, _):
            return make_body(c), None
        c, _ = lax.scan(step, c, None, length=REP)
        return c
    for _ in range(warm):
        r = run(carry_init)
    _sync(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = run(carry_init)
    _sync(r)
    return (time.perf_counter() - t0) / (n * REP)

k = jax.random.PRNGKey(0)
q = jax.random.normal(k, (B, S, NH, D), jnp.bfloat16)
kk = jax.random.normal(k, (B, S, NH, D), jnp.bfloat16)
v = jax.random.normal(k, (B, S, NH, D), jnp.bfloat16)

# relay floor
t = timeit_rep(lambda c: c + 1.0, jnp.float32(0), n=3)
print(f"relay floor per jit call: measured-per-rep {t*1e6:.1f}us")

from hetu_tpu.ops.pallas.flash_attention import flash_attention

t = timeit_rep(lambda c: flash_attention(c, kk, v, causal=True), q)
fl = 2 * 2 * B * NH * S * S * D / 2
print(f"flash fwd: {t*1e3:.2f}ms ({fl/t/1e12:.1f} Tf/s eff; ideal@50%mxu {fl/98.5e12*1e3:.2f}ms)")

def gradq(c):
    g = jax.grad(lambda q: flash_attention(q, kk, v, causal=True)
                 .astype(jnp.float32).sum())(c)
    return g.astype(jnp.bfloat16)
t = timeit_rep(gradq, q)
print(f"flash fwd+bwd: {t*1e3:.2f}ms")

# stock jax flash attention for comparison
try:
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as stock_flash, BlockSizes)
    qh = q.transpose(0, 2, 1, 3)
    kh = kk.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    t = timeit_rep(lambda c: stock_flash(c, kh, vh, causal=True), qh)
    print(f"stock flash fwd: {t*1e3:.2f}ms ({fl/t/1e12:.1f} Tf/s eff)")
    def sgradq(c):
        g = jax.grad(lambda q: stock_flash(q, kh, vh, causal=True)
                     .astype(jnp.float32).sum())(c)
        return g.astype(jnp.bfloat16)
    t = timeit_rep(sgradq, qh)
    print(f"stock flash fwd+bwd: {t*1e3:.2f}ms")
except ImportError as e:
    print("no stock flash:", e)

# layer matmul floor
a = jax.random.normal(k, (B * S, H), jnp.bfloat16)
w1 = jax.random.normal(k, (H, 3 * H), jnp.bfloat16)
w3 = jax.random.normal(k, (H, 4 * H), jnp.bfloat16)
w4 = jax.random.normal(k, (4 * H, H), jnp.bfloat16)
def mmbody(a):
    h = jax.nn.gelu(a @ w3)
    return (h @ w4).astype(jnp.bfloat16)
t = timeit_rep(mmbody, a)
fl = 2 * B * S * H * 8 * H
print(f"mlp fwd (up+gelu+down): {t*1e3:.2f}ms ({fl/t/1e12:.1f} Tf/s eff)")

# CE variants
x = jax.random.normal(k, (B * S, H), jnp.bfloat16)
wv = jax.random.normal(k, (H, V), jnp.bfloat16) * 0.02
lbl = jnp.asarray(np.random.RandomState(0).randint(0, V, (B * S,)), jnp.int32)

def ce_plain(x, wv):
    lg = (x @ wv).astype(jnp.float32)
    lp = jax.nn.log_softmax(lg, -1)
    return -jnp.mean(jnp.take_along_axis(lp, lbl[:, None], 1))
def ce_b16(x, wv):
    lg = x @ wv  # bf16 stored
    m = jnp.max(lg, -1)
    lse = jnp.log(jnp.sum(jnp.exp(lg.astype(jnp.float32) - m[:, None].astype(jnp.float32)), -1)) + m.astype(jnp.float32)
    picked = jnp.take_along_axis(lg, lbl[:, None], 1)[:, 0].astype(jnp.float32)
    return jnp.mean(lse - picked)
for name, fn in (("plain-f32", ce_plain), ("bf16-logits", ce_b16)):
    def body(carry, fn=fn):
        # keep BOTH grads live in the scan carry (a zero-multiply invites
        # XLA to DCE the dw computation and time only fwd+dx)
        xc, gw_prev = carry
        gx, gw = jax.grad(fn, argnums=(0, 1))(xc, wv)
        return ((gx + xc).astype(jnp.bfloat16),
                (gw + gw_prev.astype(jnp.float32)).astype(jnp.bfloat16))
    t = timeit_rep(body, (x, jnp.zeros_like(wv)))
    fl = 3 * 2 * B * S * H * V
    print(f"CE {name} fwd+dx+dw: {t*1e3:.2f}ms ({fl/t/1e12:.1f} Tf/s eff)")
