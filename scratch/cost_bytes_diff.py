"""Scratch: per-opcode byte-mass diff — my walk vs the compiled HLO's
non-fused instructions (operands+outputs from inline types)."""
import os
import re
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
from collections import defaultdict

from hetu_tpu.analysis.cli import build_gate_executables
from hetu_tpu.analysis.cost import cost_walk
from hetu_tpu.graph.graph import get_executable

DT = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
      "s32": 4, "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
      "pred": 1, "f8e4m3fn": 1, "f8e5m2": 1, "c64": 8, "c128": 16}

TYPED = re.compile(r"(\w+)\[([\d,]*)\](?:\{[\d,]*\})?")


def nbytes(dt, sh):
    n = 1
    for x in sh.split(","):
        if x:
            n *= int(x)
    return n * DT.get(dt, 4)


def hlo_bytes_by_op(txt):
    """Per-opcode operand+output bytes over NON-fused instructions."""
    out = defaultdict(float)
    in_fused = False
    for line in txt.splitlines():
        ls = line.strip()
        if ls.endswith("{") and "(" in ls:
            in_fused = ls.lstrip("%").startswith(("fused", "region"))
            # region_ = while/cond bodies: DO count those (XLA does)
            if ls.lstrip("%").startswith("region"):
                in_fused = False
            continue
        if ls == "}":
            continue
        m = re.match(r"(?:ROOT )?%?[\w.\-]+ = (\w+)\[([\d,]*)\]"
                     r"(?:\{[\d,:A-Z()]*\})? ([\w.\-]+)\((.*)", ls)
        if m is None or in_fused:
            continue
        odt, osh, op, rest = m.groups()
        if op in ("parameter", "constant", "get-tuple-element", "tuple",
                  "bitcast", "after-all"):
            continue
        b = nbytes(odt, osh)
        for am in TYPED.finditer(rest.split("),")[0] if op != "fusion"
                                 else rest):
            adt, ash = am.groups()
            if adt in DT or adt in ("f32", "s32"):
                b += nbytes(adt, ash)
        out[op] += b
    return out


SCALES = {"gate_train/plan0": 0.125, "gate_tp/plan0": 0.125,
          "gate_moe/plan0": 0.125, "gate_serving/unified": 1.0,
          "gate_pipe_mpmd/pipe0-stage1": 0.25}

build_gate_executables()
for name in (sys.argv[1:] or ("gate_serving/unified", "gate_moe/plan0",
                              "gate_tp/plan0")):
    h = get_executable(name)
    txt = h.compiled_text()
    xla = hlo_bytes_by_op(txt)
    w = cost_walk(h.jaxpr, scale=SCALES.get(name, 1.0), upcast=True,
                  multiply_trips=False)
    mine = defaultdict(float)
    for e in w.entries:
        mine[e.prim] += e.bytes * e.count
    print(f"\n=== {name} ===   mine {sum(mine.values()):.0f}  "
          f"xla-est {sum(xla.values()):.0f}")
    print("  XLA side (non-fused op masses):")
    for op, b in sorted(xla.items(), key=lambda kv: -kv[1])[:14]:
        print(f"    {op:24s} {b:>11.0f}")
    print("  my side:")
    for op, b in sorted(mine.items(), key=lambda kv: -kv[1])[:14]:
        print(f"    {op:24s} {b:>11.0f}")
