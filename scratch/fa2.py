"""Experimental flash attention kernel variants for perf tuning.

Variants controlled by flags:
- no seg operands when unused (always here)
- diag: specialize diagonal vs fully-visible blocks (skip mask compute)
- bq/bk block sizes
"""
import functools, math, sys, time
import numpy as np
import jax, jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)
LANES = 128


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, bq, bk, num_kv, diag_spec):
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)

    @pl.when(kv_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        run = kv_idx * bk <= q_idx * bq + bq - 1

    def _body(mask_needed):
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if mask_needed:
            rows = q_idx * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kv_idx * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(cols <= rows, s, DEFAULT_MASK_VALUE)
        m_prev = m_ref[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_cur[:, None])
        alpha = jnp.exp(m_prev - m_cur)
        l_new = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_cur[:, None], m_ref.shape)

    if causal and diag_spec:
        # diagonal (partially masked) blocks need the iota mask; fully
        # visible blocks below the diagonal skip it
        is_diag = (kv_idx * bk + bk - 1) > (q_idx * bq)

        @pl.when(run & is_diag)
        def _c1():
            _body(True)

        @pl.when(run & jnp.logical_not(is_diag))
        def _c2():
            _body(False)
    else:
        @pl.when(run)
        def _c():
            _body(causal)

    @pl.when(kv_idx == num_kv - 1)
    def _fin():
        l = l_ref[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l[:, None]).astype(o_ref.dtype)
        m = m_ref[:, 0]
        lse = jnp.where(l == 0.0, -jnp.inf, m + jnp.log(safe_l))
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def flash_fwd(q, k, v, scale, causal, bq=512, bk=512, diag_spec=True,
              dimsem=False):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    bq = min(bq, sq); bk = min(bk, sk)
    num_q, num_kv = sq // bq, sk // bk
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, num_kv=num_kv,
                               diag_spec=diag_spec)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bq, LANES), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
        if dimsem else None,
    )(qr, kr, vr)
    out = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return out, lse


if __name__ == "__main__":
    B, S, NH, D = 32, 1024, 12, 64
    REP = 20
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, S, NH, D), jnp.bfloat16)
    k = jax.random.normal(key, (B, S, NH, D), jnp.bfloat16)
    v = jax.random.normal(key, (B, S, NH, D), jnp.bfloat16)

    def _sync(r):
        for x in jax.tree.leaves(r):
            np.asarray(x.ravel()[0])

    def timeit_rep(body, carry, n=3, warm=1):
        @jax.jit
        def run(c):
            def step(c, _):
                return body(c), None
            c, _ = lax.scan(step, c, None, length=REP)
            return c
        for _ in range(warm):
            r = run(carry)
        _sync(r)
        t0 = time.perf_counter()
        for _ in range(n):
            r = run(carry)
        _sync(r)
        return (time.perf_counter() - t0) / (n * REP)

    scale = 1.0 / math.sqrt(D)
    fl = 2 * 2 * B * NH * S * S * D / 2

    # correctness check vs dense
    def dense(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        qi = lax.broadcasted_iota(jnp.int32, (S, S), 0)
        ki = lax.broadcasted_iota(jnp.int32, (S, S), 1)
        s = jnp.where(ki <= qi, s, -jnp.inf)
        p = jax.nn.softmax(s, -1).astype(v.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)
    qs, ks, vs = q[:2, :, :2], k[:2, :, :2], v[:2, :, :2]
    o1, _ = jax.jit(lambda q, k, v: flash_fwd(q, k, v, scale, True))(qs, ks, vs)
    o2 = jax.jit(dense)(qs, ks, vs)
    err = float(jnp.max(jnp.abs(o1.astype(jnp.float32) - o2.astype(jnp.float32))))
    print(f"max err vs dense: {err:.4f}")

    for bq, bk, ds, sem in ((1024, 1024, True, False),
                            (1024, 1024, True, True),
                            (512, 1024, True, True),
                            (512, 512, True, True),
                            (512, 512, False, True)):
        try:
            t = timeit_rep(
                lambda c, bq=bq, bk=bk, ds=ds, sem=sem: flash_fwd(
                    c, k, v, scale, True, bq, bk, ds, sem)[0], q)
            print(f"fwd bq={bq} bk={bk} diag={ds} sem={sem}: {t*1e3:.2f}ms "
                  f"({fl/t/1e12:.1f} Tf/s)")
        except Exception as e:
            print(f"fwd bq={bq} bk={bk} diag={ds} sem={sem}: FAIL {type(e).__name__}: {e}")

    # splash attention reference
    try:
        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as sk, splash_attention_mask as sm)
        mask = sm.MultiHeadMask(
            [sm.CausalMask((S, S)) for _ in range(NH)])
        kernel = sk.make_splash_mha_single_device(mask=mask)
        qh = q.transpose(0, 2, 1, 3) * scale
        kh = k.transpose(0, 2, 1, 3)
        vh = v.transpose(0, 2, 1, 3)
        vm = jax.vmap(kernel)
        t = timeit_rep(lambda c: vm(c, kh, vh).astype(jnp.bfloat16), qh)
        print(f"splash fwd: {t*1e3:.2f}ms ({fl/t/1e12:.1f} Tf/s)")
        def sg(c):
            g = jax.grad(lambda q: vm(q, kh, vh).astype(jnp.float32).sum())(c)
            return g.astype(jnp.bfloat16)
        t = timeit_rep(sg, qh)
        print(f"splash fwd+bwd: {t*1e3:.2f}ms")
    except Exception as e:
        import traceback; traceback.print_exc()
