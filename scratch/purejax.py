"""Pure-JAX GPT-2 floor: same math as bench config, raw jax.jit + optax-free
adam, no graph engine. Variants: base, flash, fusedce, flash_fusedce, remat
"""
import sys, time, functools, math
import numpy as np
import jax, jax.numpy as jnp

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "base"

V, H, L, NH, S, B = 50304, 768, 12, 12, 1024, 32
D = H // NH
key = jax.random.PRNGKey(0)

def init():
    ks = jax.random.split(key, 100)
    p = {}
    p["wte"] = jax.random.normal(ks[0], (V, H), jnp.float32) * 0.02
    p["wpe"] = jax.random.normal(ks[1], (S, H), jnp.float32) * 0.02
    p["lm_head"] = jax.random.normal(ks[2], (H, V), jnp.float32) * 0.02
    p["lnf_g"] = jnp.ones((H,)); p["lnf_b"] = jnp.zeros((H,))
    blocks = []
    for i in range(L):
        k = jax.random.split(ks[3 + i], 8)
        blocks.append(dict(
            qkv_w=jax.random.normal(k[0], (H, 3 * H), jnp.float32) * 0.02,
            qkv_b=jnp.zeros((3 * H,)),
            out_w=jax.random.normal(k[1], (H, H), jnp.float32) * 0.01,
            out_b=jnp.zeros((H,)),
            up_w=jax.random.normal(k[2], (H, 4 * H), jnp.float32) * 0.02,
            up_b=jnp.zeros((4 * H,)),
            dn_w=jax.random.normal(k[3], (4 * H, H), jnp.float32) * 0.01,
            dn_b=jnp.zeros((H,)),
            ln1_g=jnp.ones((H,)), ln1_b=jnp.zeros((H,)),
            ln2_g=jnp.ones((H,)), ln2_b=jnp.zeros((H,)),
        ))
    p["blocks"] = blocks
    return jax.tree.map(lambda x: x.astype(jnp.bfloat16), p)

def ln(x, g, b):
    m = jnp.mean(x.astype(jnp.float32), -1, keepdims=True)
    v = jnp.var(x.astype(jnp.float32), -1, keepdims=True)
    return ((x - m) * jax.lax.rsqrt(v + 1e-5) * g + b).astype(x.dtype)

def attn_xla(q, k, v):
    # [B,S,NH,D]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (1.0 / math.sqrt(D))
    qi = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    ki = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    s = jnp.where(ki <= qi, s.astype(jnp.float32), -jnp.inf)
    p = jax.nn.softmax(s, -1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)

use_flash = VARIANT in ("flash", "flash_fusedce", "flash_remat")
if use_flash:
    sys.path.insert(0, "/root/repo")
    from hetu_tpu.ops.pallas.flash_attention import flash_attention

def block_fwd(x, bp):
    h = ln(x, bp["ln1_g"], bp["ln1_b"])
    qkv = h @ bp["qkv_w"] + bp["qkv_b"]
    q, k, v = jnp.split(qkv, 3, -1)
    q = q.reshape(B, S, NH, D); k = k.reshape(B, S, NH, D)
    v = v.reshape(B, S, NH, D)
    if use_flash:
        a = flash_attention(q, k, v, causal=True)
    else:
        a = attn_xla(q, k, v)
    a = a.reshape(B, S, H)
    x = x + a @ bp["out_w"] + bp["out_b"]
    h = ln(x, bp["ln2_g"], bp["ln2_b"])
    h = jax.nn.gelu(h @ bp["up_w"] + bp["up_b"])
    x = x + h @ bp["dn_w"] + bp["dn_b"]
    return x

fused_ce = VARIANT in ("fusedce", "flash_fusedce")

def loss_fn(p, ids, labels):
    x = p["wte"][ids] + p["wpe"][None, :S]
    for bp in p["blocks"]:
        x = block_fwd(x, bp)
    x = ln(x, p["lnf_g"], p["lnf_b"])
    if fused_ce:
        # chunked CE: never materialize full [B*S, V] logits at once
        xf = x.reshape(B * S, H)
        lf = labels.reshape(B * S)
        CH = 8
        xc = xf.reshape(CH, (B * S) // CH, H)
        lc = lf.reshape(CH, (B * S) // CH)
        def body(c, op):
            xx, ll = op
            lg = (xx @ p["lm_head"]).astype(jnp.float32)
            lse = jax.nn.logsumexp(lg, -1)
            picked = jnp.take_along_axis(lg, ll[:, None], 1)[:, 0]
            return c + jnp.sum(lse - picked), None
        tot, _ = jax.lax.scan(body, 0.0, (xc, lc))
        return tot / (B * S)
    lg = (x @ p["lm_head"]).astype(jnp.float32)
    lp = jax.nn.log_softmax(lg, -1)
    picked = jnp.take_along_axis(lp, labels[..., None], -1)[..., 0]
    return -jnp.mean(picked)

def adam_update(p, g, m, v, step):
    b1, b2, eps, lr = 0.9, 0.999, 1e-8, 1e-4
    m = jax.tree.map(lambda m_, g_: b1 * m_ + (1 - b1) * g_.astype(jnp.float32), m, g)
    v = jax.tree.map(lambda v_, g_: b2 * v_ + (1 - b2) * jnp.square(g_.astype(jnp.float32)), v, g)
    def upd(p_, m_, v_):
        mh = m_ / (1 - b1 ** step); vh = v_ / (1 - b2 ** step)
        return (p_.astype(jnp.float32) - lr * mh / (jnp.sqrt(vh) + eps)).astype(p_.dtype)
    return jax.tree.map(upd, p, m, v), m, v

@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def train_step(p, m, v, step, ids, labels):
    lval, g = jax.value_and_grad(loss_fn)(p, ids, labels)
    p, m, v = adam_update(p, g, m, v, step)
    return p, m, v, lval

p = init()
m = jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), p)
v = jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), p)
rng = np.random.RandomState(0)
IDS = jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32)
LBL = jnp.roll(IDS, -1, 1)

t0 = time.perf_counter()
for i in range(2):
    p, m, v, lval = train_step(p, m, v, jnp.float32(i + 1), IDS, LBL)
np.asarray(lval)
t1 = time.perf_counter()
steps = 8
t0 = time.perf_counter()
for i in range(steps):
    p, m, v, lval = train_step(p, m, v, jnp.float32(i + 3), IDS, LBL)
np.asarray(lval)
dt = (time.perf_counter() - t0) / steps
tok = B * S / dt
# honest flops: matmul params (no embeddings) + attention
n_mat = H * 3 * H + H * H + H * 4 * H * 2
n_mat = n_mat * L + H * V
att = 12 * S * H * L // 2  # causal halves the realized flops
fl = (6 * n_mat + att) * tok
print(f"VARIANT={VARIANT} step={dt*1e3:.1f}ms tok/s={tok:,.0f} "
      f"honestMFU={fl/197e12:.3f} (compile {t1-t0:.0f}s)")
