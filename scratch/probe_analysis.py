"""Introspection probes behind hetu_tpu/analysis (jax 0.4.37 facts).

Run standalone; each section prints the fact the analyzer relies on:

1. collective primitive names in the jaxpr: psum / all_gather /
   all_to_all / reduce_scatter; shard_map carries params['jaxpr'] (raw
   Jaxpr) + params['mesh'] (axis sizes); pmean lowers to psum + div.
2. jax.named_scope lands on eqn.source_info.name_stack (comm_tag
   attribution channel) and source_info_util.user_frame gives file:line.
3. scan carries params['length'] (trip-count factor) and a ClosedJaxpr.
4. donation is visible as Lowered.args_info leaves (.donated) and as
   `tf.aliasing_output` in the StableHLO text.
5. GSPMD-inserted reshards (with_sharding_constraint -> all-gather) are
   ABSENT from lowered StableHLO and PRESENT in compiled post-SPMD HLO —
   the implicit-reshard rule diffs the two.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))


def f(x, y):
    with jax.named_scope("grad_comm/bucket0"):
        s = lax.psum(x, "dp")
    g = lax.all_gather(y, "dp", axis=0, tiled=True)
    a2a = lax.all_to_all(x.reshape(8, -1), "dp", split_axis=0,
                         concat_axis=0, tiled=False)
    rs = lax.psum_scatter(x, "dp", scatter_dimension=0, tiled=True)
    red = lax.pmean(jnp.sum(x), "dp")
    return s, g, a2a, rs, red


sm = shard_map(f, mesh=mesh, in_specs=(P(), P()),
               out_specs=(P(), P(), P(None), P(), P()), check_rep=False)
cj = jax.make_jaxpr(sm)(np.ones((64,), np.float32),
                        np.ones((4,), np.float32))
(smeqn,) = [e for e in cj.jaxpr.eqns if e.primitive.name == "shard_map"]
print("[1] shard_map mesh:", dict(smeqn.params["mesh"].shape))
for ie in smeqn.params["jaxpr"].eqns:
    print("   ", ie.primitive.name, "| ns:", str(ie.source_info.name_stack))

gj = jax.jit(lambda a, b: (a + b, b * 2), donate_argnums=(0,))
low = gj.lower(np.ones((8,), np.float32), np.ones((8,), np.float32))
print("[4] args_info donated:",
      [l.donated for l in jax.tree_util.tree_leaves(low.args_info)])
print("[4] aliasing in text:", "tf.aliasing_output" in low.as_text())


def g(x):
    x = lax.with_sharding_constraint(x, NamedSharding(mesh, P("dp", None)))
    h = x * 2.0
    h = lax.with_sharding_constraint(h, NamedSharding(mesh, P()))
    return h.sum()


low2 = jax.jit(g).lower(jax.ShapeDtypeStruct((16, 8), np.float32))
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from hetu_tpu.parallel.dstates import count_hlo_collectives  # noqa: E402

print("[5] lowered:", count_hlo_collectives(low2.as_text()))
print("[5] compiled:", count_hlo_collectives(low2.compile().as_text()))
