"""Ablation timing for bench perf work. Usage: python scratch/abl.py VARIANT
Variants: base, noflash, noloss, noattn, b64, b16
"""
import sys, time, os
import numpy as np

VARIANT = sys.argv[1] if len(sys.argv) > 1 else "base"
_KNOWN = {"base", "noflash", "noloss", "noattn", "b64", "b16"}
if VARIANT not in _KNOWN:
    sys.exit(f"unknown VARIANT {VARIANT!r}; pick one of {sorted(_KNOWN)}")

import jax
import jax.numpy as jnp
import hetu_tpu as ht
from hetu_tpu import optim, ops
from hetu_tpu.models import GPTConfig, GPTLMHeadModel

batch, seq, steps, warmup = 32, 1024, 8, 2
if VARIANT == "b64":
    batch = 64
if VARIANT == "b16":
    batch = 16

cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                num_heads=12, max_seq_len=1024, sp=False,
                dtype="bfloat16", position="learned",
                activation="gelu", norm="layernorm")

if VARIANT == "noflash":
    import importlib
    A = importlib.import_module("hetu_tpu.ops.attention")
    _orig = A.sdpa
    def sdpa_noflash(q, k, v, **kw):
        kw["use_flash"] = False
        return _orig(q, k, v, **kw)
    A.sdpa = sdpa_noflash

if VARIANT == "noattn":
    import hetu_tpu.models.gpt as G
    class NoAttn:
        def __init__(self, *a, **k): pass
    # replace attention output with identity: monkeypatch block fwd
    _orig_fwd = G.ParallelAttentionBlock.forward
    def fwd(self, x, seq_len):
        # identity-ish: q slice only; valid only for the MHA (non-GQA,
        # non-rotary) config above — assert so reuse fails loudly
        assert cfg.num_heads * cfg.head_dim == cfg.hidden_size \
            and cfg.position == "learned"
        return self.out(self.qkv(x)[..., :cfg.hidden_size])
    G.ParallelAttentionBlock.forward = fwd

with ht.graph("define_and_run", create_new=True) as g:
    ids = ht.placeholder("int32", (batch, seq), name="input_ids")
    labels = ht.placeholder("int32", (batch, seq), name="labels")
    model = GPTLMHeadModel(cfg)
    if VARIANT == "noloss":
        h = model.transformer(ids, seq_len=seq)
        loss = ops.reduce_mean(h * h)
    else:
        loss = model(ids, labels, seq_len=seq)
    train_op = optim.AdamOptimizer(lr=1e-4, weight_decay=0.01).minimize(loss)

    rng = np.random.RandomState(0)
    IDS = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    L = np.roll(IDS, -1, axis=1)

    def _sync():
        arrs = list(g._var_data.values())
        for arr in (arrs[0], arrs[-1]):
            np.asarray(arr.ravel()[0])

    t_c0 = time.perf_counter()
    for _ in range(warmup):
        g.run(loss, [loss, train_op], {ids: IDS, labels: L})
        _sync()
    t_c1 = time.perf_counter()
    t0 = time.perf_counter()
    for _ in range(steps):
        g.run(loss, [loss, train_op], {ids: IDS, labels: L})
    _sync()
    dt = (time.perf_counter() - t0) / steps

tok = batch * seq / dt
print(f"VARIANT={VARIANT} step={dt*1e3:.1f}ms tok/s={tok:,.0f} "
      f"(warmup+compile {t_c1-t_c0:.1f}s)")
