"""Scratch: solve for the collective-adjustment constants.

Per family: walk bytes (no adjustment), XLA target, and the collective
instr components split explicit/GSPMD, so
  target ≈ walk + 2·out_gspmd + E·ring_explicit + R·ring_gspmd
can be fit by hand.
"""
import os
import re
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

from collections import defaultdict

from hetu_tpu.analysis.cli import build_gate_executables
from hetu_tpu.analysis.cost import (cost_walk, xla_cost_stats,
                                    _COLLECTIVE_PRIM_NAMES, _HLO_WIDTH)
from hetu_tpu.graph.graph import get_executable

HLO_KIND = {"all-reduce": "all_reduce", "all-gather": "all_gather",
            "all-to-all": "all_to_all", "reduce-scatter": "reduce_scatter",
            "collective-permute": "ppermute"}
PRIM_KIND = {"psum": "all_reduce", "pmax": "all_reduce",
             "pmin": "all_reduce", "all_gather": "all_gather",
             "all_to_all": "all_to_all",
             "reduce_scatter": "reduce_scatter",
             "psum_scatter": "reduce_scatter", "ppermute": "ppermute"}

SCALES = {"gate_train/plan0": 0.125, "gate_tp/plan0": 0.125,
          "gate_moe/plan0": 0.125, "gate_serving/unified": 1.0,
          "gate_pipe_mpmd/pipe0-stage0": 0.25,
          "gate_pipe_mpmd/pipe0-stage1": 0.25,
          "gate_pipe_spmd/fwd": 1.0}

names = build_gate_executables()
rows = []
for name in names:
    h = get_executable(name)
    w = cost_walk(h.jaxpr, scale=SCALES.get(name, 1.0), upcast=True,
                  multiply_trips=False)
    xla = xla_cost_stats(h)
    txt = h.compiled_text()
    # per-kind HLO instrs
    pat = re.compile(
        r"= *(\w+)\[([\d,]*)\][^ ]* (all-reduce|all-gather|all-to-all|"
        r"reduce-scatter|collective-permute)(?:-start)?\(([^\n]*)")
    instrs = defaultdict(list)
    for m in pat.finditer(txt):
        dt, sh, op, rest = m.groups()
        nb = 1
        for x in sh.split(","):
            if x:
                nb *= int(x)
        nb *= _HLO_WIDTH.get(dt, 4)
        if op == "collective-permute":
            group = 2
        else:
            group = 1
            g = re.search(r"replica_groups=\{\{([\d,]+)\}", rest)
            if g:
                group = g.group(1).count(",") + 1
            else:
                g = re.search(r"replica_groups=\[(\d+),(\d+)\]", rest)
                if g:
                    group = int(g.group(2))
        instrs[HLO_KIND[op]].append((nb, group))
    # explicit counts from the walk
    expl = defaultdict(int)
    walk_coll = 0.0
    for e in w.entries:
        k = PRIM_KIND.get(e.prim)
        if k:
            expl[k] += e.count
            walk_coll += e.bytes * e.count
    out2_g = ring_e = ring_g = 0.0
    for k, lst in instrs.items():
        n_k = len(lst)
        e_k = min(expl.get(k, 0), n_k)
        fe = e_k / n_k if n_k else 0.0
        s2 = sum(2.0 * nb for nb, _g in lst)
        sr = sum(nb * (g - 1) for nb, g in lst)
        out2_g += (1 - fe) * s2
        ring_e += fe * sr
        ring_g += (1 - fe) * sr
    rows.append((name, w.bytes, xla["bytes_accessed"], out2_g, ring_e,
                 ring_g, walk_coll))
    print(f"{name:28s} walk={w.bytes:>11.0f} xla={xla['bytes_accessed']:>11.0f} "
          f"gap={xla['bytes_accessed'] - w.bytes:>11.0f} out2_g={out2_g:>9.0f} "
          f"ring_e={ring_e:>9.0f} ring_g={ring_g:>9.0f}")

print("\nfit grid (delta% per family; * = |delta| > max(10%, 256KB)):")
for E in (0.0, 0.5, 1.0, 1.5, 2.0):
    for R in (1.0, 2.0, 3.0, 4.0):
        bad = 0
        ds = []
        for name, wb, xb, o2, re_, rg, _wc in rows:
            pred = wb + o2 + E * re_ + R * rg
            d = (pred - xb) / xb
            ok = abs(pred - xb) <= max(0.1 * xb, 1 << 18)
            bad += (not ok)
            ds.append(f"{d * 100:+5.1f}{'*' if not ok else ' '}")
        print(f"E={E} R={R}: bad={bad}  " + " ".join(ds))
