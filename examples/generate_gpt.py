"""Train a tiny GPT, checkpoint it (in the background), restore, and
decode with the KV-cache generation engine.

The inference half of the reference's GPT recipe (its examples stop at
training; this closes the loop a switching user expects).  Self-checking:
trains on a periodic token stream and asserts the generated continuation
reproduces the period.

Run (CPU sim):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/generate_gpt.py
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import hetu_tpu as ht  # noqa: E402
from hetu_tpu import models, optim  # noqa: E402
from hetu_tpu.models import GPTConfig, GPTLMHeadModel  # noqa: E402
from hetu_tpu.utils.checkpoint import (load_checkpoint,  # noqa: E402
                                       save_checkpoint)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--ckpt", type=str, default="")
    ap.add_argument("--temperature", type=float, default=0.0)
    args = ap.parse_args()
    ckpt = args.ckpt or os.path.join(tempfile.mkdtemp(), "gpt")

    cfg = GPTConfig(vocab_size=16, hidden_size=args.hidden, num_layers=2,
                    num_heads=4, max_seq_len=32, sp=False, dropout=0.0,
                    position="learned", activation="gelu")
    period = np.array([3, 7, 1, 12], np.int32)
    data = np.tile(period, (8, 8))                       # [8, 32]

    ht.set_seed(0)
    with ht.graph("define_and_run", create_new=True) as g:
        ids = ht.placeholder("int32", (8, 32), name="ids")
        lbl = ht.placeholder("int32", (8, 32), name="lbl")
        model = GPTLMHeadModel(cfg)
        loss = model(ids, lbl)
        opt = optim.AdamOptimizer(lr=3e-3)
        train_op = opt.minimize(loss)
        feed = {ids: data, lbl: np.roll(data, -1, 1)}
        first = last = None
        for step in range(args.steps):
            out = g.run(loss, [loss, train_op], feed)
            v = float(np.asarray(out[0]))
            first = v if first is None else first
            last = v
        print(f"trained {args.steps} steps: loss {first:.3f} -> {last:.3f}")
        # background save: file IO overlaps the remaining work
        handle = save_checkpoint(model, opt, ckpt, step=args.steps,
                                 background=True)
        handle.wait(timeout=300)

    # fresh process-style restore: new graph, zeroed params, load, decode
    with ht.graph("define_and_run", create_new=True):
        model2 = GPTLMHeadModel(cfg)
        ids2 = ht.placeholder("int32", (1, 8), name="warm")
        model2.logits(ids2)  # materialize params
        ts = load_checkpoint(model2, None, ckpt)
        print(f"restored checkpoint at step {ts['step']}")
        state = {k: np.asarray(v) for k, v in model2.state_dict().items()}

    prompt = np.array([[3, 7, 1, 12, 3, 7]], np.int32)
    out = np.asarray(models.generate(state, cfg, prompt, 10,
                                     temperature=args.temperature))
    print("prompt      :", prompt[0].tolist())
    print("continuation:", out[0, prompt.shape[1]:].tolist())
    if args.temperature == 0.0:
        want = [period[(2 + i) % 4] for i in range(10)]
        assert out[0, prompt.shape[1]:].tolist() == want, "pattern lost"
        print("self-check OK: greedy decode reproduces the trained period")


if __name__ == "__main__":
    main()
