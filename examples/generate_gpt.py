"""Train a tiny GPT, checkpoint it (in the background), restore, and
decode with the KV-cache generation engine.

The inference half of the reference's GPT recipe (its examples stop at
training; this closes the loop a switching user expects).  Self-checking:
trains on a periodic token stream and asserts the generated continuation
reproduces the period.

Run (CPU sim):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/generate_gpt.py
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import hetu_tpu as ht  # noqa: E402
from hetu_tpu import models, optim  # noqa: E402
from hetu_tpu.models import GPTConfig, GPTLMHeadModel  # noqa: E402
from hetu_tpu.utils.checkpoint import (load_checkpoint,  # noqa: E402
                                       save_checkpoint)


def serve_demo(state, cfg, args):
    """Continuous-batching serving demo: N prompts with staggered
    wall-clock arrivals through hetu_tpu.serving.Engine; prints
    per-request TTFT/latency and aggregate tokens/s."""
    import time

    from hetu_tpu import obs
    from hetu_tpu.serving import Engine

    rng = np.random.RandomState(0)
    period = np.array([3, 7, 1, 12], np.int32)
    # --trace-out: record the full per-request trace plane and dump a
    # Perfetto-loadable chrome trace after the run (DESIGN.md §15)
    tracer = obs.SpanTracer() if args.trace_out else None
    # --spec-draft-layers N: speculative decoding behind a truncated
    # N-layer self-draft proposing --spec-k tokens per step
    # (DESIGN.md §20) — the temp-0 self-check below still holds
    # bit-for-bit, only the tokens-per-step cadence changes
    spec = None
    if args.spec_draft_layers > 0:
        from hetu_tpu.models import draft_state_from
        from hetu_tpu.serving import SpecConfig
        dstate, dcfg = draft_state_from(state, cfg,
                                        args.spec_draft_layers)
        spec = SpecConfig(dstate, dcfg, k=args.spec_k)
    eng = Engine(state, cfg, num_pages=64, page_size=8, max_batch=8,
                 prefix_cache=not args.no_prefix_cache, tracer=tracer,
                 spec=spec)
    n = args.serve_requests
    t0 = time.monotonic()
    reqs = []
    for i in range(n):
        plen = int(rng.choice([4, 6, 8]))
        phase = int(rng.randint(4))
        prompt = [int(period[(phase + j) % 4]) for j in range(plen)]
        reqs.append(eng.add_request(
            prompt, max_new_tokens=int(rng.randint(6, 14)),
            temperature=args.temperature, top_p=args.top_p, seed=i,
            arrival_time=time.monotonic() + i * args.serve_stagger))
    eng.run()
    wall = time.monotonic() - t0
    total_new = 0
    for r in reqs:
        ttft = r.first_token_time - r.submit_time
        lat = r.finish_time - r.submit_time
        total_new += r.n_generated
        print(f"req {r.req_id}: prompt {r.prompt_len:2d} tok, "
              f"+{r.n_generated:2d} new, ttft {ttft * 1e3:7.1f} ms, "
              f"latency {lat * 1e3:7.1f} ms, "
              f"preemptions {r.n_preemptions}")
        if args.temperature == 0.0:
            # the engine contract: continuous batching reproduces a solo
            # dense-cache generate() run bit-for-bit at temperature 0
            want = np.asarray(models.generate(
                state, cfg, np.asarray([r.prompt], np.int32),
                r.n_generated))[0, r.prompt_len:].tolist()
            assert r.out_tokens == want, (r.req_id, r.out_tokens, want)
    m = eng.metrics_summary()
    print(f"served {n} requests / {total_new} tokens in {wall:.2f}s "
          f"({total_new / wall:.1f} tok/s aggregate)")
    print(f"engine: {int(m['executable_calls'])} unified-step calls, "
          f"{int(m['preemptions'])} preemptions, "
          f"{int(m['compile_count'])} compiled executable(s), "
          f"{int(m['host_logit_fetches'])} host logit fetches, "
          f"ttft p90 {m['ttft']['p90'] * 1e3:.1f} ms")
    if spec is not None:
        print(f"speculative decoding: draft {args.spec_draft_layers} "
              f"of {cfg.num_layers} layers, k={args.spec_k}; "
              f"{int(m['spec_proposed'])} proposed / "
              f"{int(m['spec_accepted'])} accepted "
              f"(rate {m['spec_accept_rate']:.2f}), "
              f"{int(m['spec_bonus_tokens'])} bonus tokens, "
              f"{m['accepted_per_step']:.2f} accepted tokens/step")
    if not args.no_prefix_cache:
        print(f"prefix cache: hit rate "
              f"{m['prefix_cache_hit_rate']:.2f} "
              f"({int(m['prefix_cache_hits'])} hits / "
              f"{int(m['prefix_cache_misses'])} misses), "
              f"{int(m['prefix_cache_tokens_saved'])} prefill tokens "
              f"saved, {int(m['prefix_cache_evictions'])} evictions, "
              f"{int(m['prefix_cache_pages'])} pages cached")
    if args.temperature == 0.0:
        print("self-check OK: every served request matches its solo "
              "generate() run bit-for-bit")
    if tracer is not None:
        events = tracer.events()
        obs.write_chrome_trace(events, args.trace_out)
        print(f"\nper-request serving timelines (from the trace):")
        print(obs.timeline_summary(events))
        print("\npredicted-vs-observed reconciliation:")
        print(obs.reconcile(events).summary())
        print(f"\nwrote {len(events)} trace events to {args.trace_out} — "
              f"open it at https://ui.perfetto.dev (one track per "
              f"request)")


def cluster_demo(state, cfg, args):
    """Serving-cluster demo (``--replicas N``): staggered shared-prefix
    requests through ``serving.cluster.EngineCluster`` — prefix-aware
    routing over N replicas (disaggregated prefill/decode with
    ``--disaggregate``), per-replica hit rates, and ONE merged Perfetto
    trace with per-replica tracks plus the router's decision track."""
    import time

    from hetu_tpu import obs
    from hetu_tpu.serving import EngineCluster

    rng = np.random.RandomState(0)
    period = np.array([3, 7, 1, 12], np.int32)
    tracer = obs.SpanTracer() if args.trace_out else None
    mode = "disaggregated" if args.disaggregate else "replicated"
    cl = EngineCluster(state, cfg, num_replicas=args.replicas,
                       mode=mode, num_prefill=1, name="demo_cluster",
                       num_pages=64, page_size=8, max_batch=8,
                       prefix_cache=not args.no_prefix_cache,
                       tracer=tracer, ttl=30.0)
    n = args.serve_requests
    t0 = time.monotonic()
    header = [int(period[j % 4]) for j in range(8)]   # shared prefix
    # wave 1: one request carries the shared header into a replica's
    # prefix cache (and pays the compile)
    reqs = [cl.add_request(header + [int(period[0]), int(period[1])],
                           max_new_tokens=8,
                           temperature=args.temperature,
                           top_p=args.top_p, seed=0)]
    cl.run()
    # wave 2: staggered same-header arrivals — the router sends them
    # to the cache-holding replica (watch the `route` reasons)
    for i in range(1, n):
        tail = [int(period[(i + j) % 4]) for j in range(2)]
        reqs.append(cl.add_request(
            header + tail, max_new_tokens=int(rng.randint(6, 14)),
            temperature=args.temperature, top_p=args.top_p, seed=i,
            arrival_time=time.monotonic() + i * args.serve_stagger))
    cl.run()
    wall = time.monotonic() - t0
    total_new = sum(len(r.out_tokens) for r in reqs)
    for r in reqs:
        print(f"req {r.req_id}: prompt {len(r.prompt):2d} tok, "
              f"+{len(r.out_tokens):2d} new on replica {r.replica}"
              f" ({r.n_reroutes} reroutes)")
        if args.temperature == 0.0:
            want = np.asarray(models.generate(
                state, cfg, np.asarray([r.prompt], np.int32),
                len(r.out_tokens)))[0, len(r.prompt):].tolist()
            assert r.out_tokens == want, (r.req_id, r.out_tokens, want)
    ms = cl.metrics_summary()
    print(f"cluster served {n} requests / {total_new} tokens in "
          f"{wall:.2f}s over {ms['alive_replicas']} replicas "
          f"({mode}); fleet hit rate "
          f"{ms['prefix_cache_hit_rate']:.2f}, "
          f"{int(ms['prefix_cache_tokens_saved'])} prefill tokens "
          f"saved, {int(ms['cluster_handoffs'])} KV handoffs "
          f"({int(ms['handoff_payload_bytes'])} B priced at "
          f"{ms['handoff_predicted_s'] * 1e6:.1f} us on the wire)")
    for rid, facts in sorted(ms["per_replica"].items()):
        print(f"  {rid} [{facts['role']}]: hit rate "
              f"{facts['prefix_cache_hit_rate']:.2f}, "
              f"{facts['cached_pages']} cached pages")
    if args.temperature == 0.0:
        print("self-check OK: every routed request matches its solo "
              "generate() run bit-for-bit")
    if tracer is not None:
        events = tracer.events()
        obs.write_chrome_trace(events, args.trace_out)
        routes = [e for e in events if e.name == "route"]
        print(f"\nrouter decisions: "
              + ", ".join(f"req {e.attrs['req']}->r{e.attrs['replica']}"
                          f" ({e.attrs['reason']})" for e in routes))
        print(f"wrote {len(events)} trace events to {args.trace_out} — "
              f"one merged Perfetto timeline: r<i>/... tracks per "
              f"replica beside the router track")
    cl.close()


def slo_demo(state, cfg, args):
    """SLO traffic-plane demo (``--slo-demo``, DESIGN.md §22): mixed
    priority classes with a mid-trace burst through a 2-replica cluster
    managed by the autoscaler, with the host-RAM KV tier staging cold
    prefix pages — prints per-class latency tails against their
    targets, the scale events, and the host tier's accounting."""
    import time

    from hetu_tpu.serving import EngineCluster
    from hetu_tpu.serving.slo import (Autoscaler, DEFAULT_TARGETS,
                                      SLO_CLASSES)

    period = np.array([3, 7, 1, 12], np.int32)
    auto = Autoscaler(min_replicas=1, max_replicas=2, backlog_high=3,
                      backlog_low=0, hysteresis_steps=2,
                      cooldown_steps=8)
    cl = EngineCluster(state, cfg, num_replicas=2, name="slo_demo",
                       num_pages=64, page_size=8, max_batch=8,
                       coordinator=False, max_queue_depth=2,
                       autoscaler=auto,
                       host_tier=not args.no_prefix_cache,
                       prefix_cache=not args.no_prefix_cache)
    header = [int(period[j % 4]) for j in range(8)]
    # warm/compile in class batch (best-effort — no target to distort)
    cl.add_request(header + [3, 7], 2, slo_class="batch")
    cl.run()
    if not args.no_prefix_cache:
        # the cold sweep: warm header pages fall to host staging, the
        # same-header wave below pulls them back through the priced
        # transport instead of re-prefilling
        for r in cl.replicas:
            r.engine.prefix_cache.evict(64)
    t0 = time.monotonic()
    reqs = []
    for i in range(12):
        tail = [int(period[(i + j) % 4]) for j in range(2)]
        # sparse trough (the controller drains a replica), then a
        # dense interactive-heavy burst (it readmits it)
        dt = i * 0.04 if i < 4 else 0.16 + (i - 4) * 0.001
        c = SLO_CLASSES[(i + 2) % 3] if i < 4 \
            else ("interactive" if i % 2 else "standard")
        reqs.append(cl.add_request(header + tail, max_new_tokens=8,
                                   temperature=args.temperature,
                                   slo_class=c,
                                   arrival_time=t0 + dt))
    cl.run()
    ms = cl.metrics_summary()
    print("slo traffic plane:")
    for c in SLO_CLASSES:
        rs = [r for r in reqs if r.slo_class == c and r.token_times]
        if not rs:
            continue
        worst = max(r.token_times[0] - r.submit_time for r in rs)
        tgt = DEFAULT_TARGETS[c]["ttft_s"]
        bound = (f"(target {tgt * 1e3:.0f} ms)" if tgt
                 else "(best effort)")
        print(f"  {c:>11}: {len(rs):2d} reqs, worst ttft "
              f"{worst * 1e3:7.1f} ms {bound}")
    print(f"  scale events: {int(ms['scale_ups'])} up / "
          f"{int(ms['scale_downs'])} down; class inversions: "
          f"{int(ms['class_inversions'])}")
    print(f"  host tier: {int(ms['host_evictions'])} pages staged, "
          f"{int(ms['host_hits'])} refetched, "
          f"{int(ms['host_refetch_bytes'])} B back over the wire")
    if args.temperature == 0.0:
        for r in reqs:
            want = np.asarray(models.generate(
                state, cfg, np.asarray([r.prompt], np.int32),
                len(r.out_tokens)))[0, len(r.prompt):].tolist()
            assert r.out_tokens == want, (r.req_id, r.out_tokens, want)
        print("  self-check OK: scaling + host-tier round-trips kept "
              "every output bit-for-bit")
    cl.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--ckpt", type=str, default="")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-p", type=float, default=0.0,
                    help="nucleus sampling mass (on-device; 0 disables)")
    ap.add_argument("--serve", action="store_true",
                    help="after training, push staggered requests "
                         "through the continuous-batching engine")
    ap.add_argument("--serve-requests", type=int, default=6)
    ap.add_argument("--serve-stagger", type=float, default=0.05,
                    help="arrival spacing in seconds")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable copy-on-write prefix caching "
                         "(DESIGN.md §13; on by default)")
    ap.add_argument("--spec-draft-layers", type=int, default=0,
                    help="with --serve: speculative decoding with a "
                         "truncated N-layer self-draft (DESIGN.md "
                         "§20; 0 disables); prints the acceptance "
                         "rate, temp-0 output stays bit-for-bit")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens proposed per verify burst")
    ap.add_argument("--kv-latent-dim", type=int, default=0,
                    help="convert the restored checkpoint to MLA "
                         "compressed latent KV (DESIGN.md §21) before "
                         "decoding/serving; pages shrink to one "
                         "[latent_dim] stream per token.  Exact when "
                         ">= the joint kv rank (<= hidden size); "
                         "0 disables")
    ap.add_argument("--replicas", type=int, default=1,
                    help="with --serve: route the requests across N "
                         "engine replicas (serving.cluster, DESIGN.md "
                         "§17) and print per-replica hit rates")
    ap.add_argument("--disaggregate", action="store_true",
                    help="with --replicas N>=2: dedicated prefill/"
                         "decode replicas with priced KV-page handoff")
    ap.add_argument("--slo-demo", action="store_true",
                    help="mixed-class traffic through the autoscaled "
                         "2-replica cluster with the host-RAM KV tier "
                         "(DESIGN.md §22): per-class latency tails, "
                         "scale events, host-tier hit accounting")
    ap.add_argument("--trace-out", type=str, default="",
                    help="with --serve: trace the demo and write a "
                         "Perfetto-loadable chrome trace JSON here, "
                         "printing the per-request timeline summary")
    args = ap.parse_args()
    ckpt = args.ckpt or os.path.join(tempfile.mkdtemp(), "gpt")

    cfg = GPTConfig(vocab_size=16, hidden_size=args.hidden, num_layers=2,
                    num_heads=4, max_seq_len=32, sp=False, dropout=0.0,
                    position="learned", activation="gelu")
    period = np.array([3, 7, 1, 12], np.int32)
    data = np.tile(period, (8, 8))                       # [8, 32]

    ht.set_seed(0)
    with ht.graph("define_and_run", create_new=True) as g:
        ids = ht.placeholder("int32", (8, 32), name="ids")
        lbl = ht.placeholder("int32", (8, 32), name="lbl")
        model = GPTLMHeadModel(cfg)
        loss = model(ids, lbl)
        opt = optim.AdamOptimizer(lr=3e-3)
        train_op = opt.minimize(loss)
        feed = {ids: data, lbl: np.roll(data, -1, 1)}
        first = last = None
        for step in range(args.steps):
            out = g.run(loss, [loss, train_op], feed)
            v = float(np.asarray(out[0]))
            first = v if first is None else first
            last = v
        print(f"trained {args.steps} steps: loss {first:.3f} -> {last:.3f}")
        # background save: file IO overlaps the remaining work
        handle = save_checkpoint(model, opt, ckpt, step=args.steps,
                                 background=True)
        handle.wait(timeout=300)

    # fresh process-style restore: new graph, zeroed params, load, decode
    with ht.graph("define_and_run", create_new=True):
        model2 = GPTLMHeadModel(cfg)
        ids2 = ht.placeholder("int32", (1, 8), name="warm")
        model2.logits(ids2)  # materialize params
        # a demo checkpoint written moments ago has no generation
        # manifest to verify against — a deliberate raw load says so
        # (the unverified-restore rule forbids silent ones)
        ts = load_checkpoint(model2, None, ckpt, verify_exempt=True)
        print(f"restored checkpoint at step {ts['step']}")
        state = {k: np.asarray(v) for k, v in model2.state_dict().items()}

    if args.kv_latent_dim > 0:
        # weight-absorbed MLA conversion (DESIGN.md §21): everything
        # below — solo decode, the serving demo, the cluster demo —
        # runs on compressed latent KV pages from here on
        from hetu_tpu.models.gpt import mla_state_from
        full = 2 * cfg.kv_heads * cfg.head_dim
        state, cfg = mla_state_from(state, cfg,
                                    kv_latent_dim=args.kv_latent_dim)
        print(f"MLA conversion: {full} -> {args.kv_latent_dim} KV "
              f"floats per token per layer "
              f"({full / args.kv_latent_dim:.1f}x smaller pages)")

    prompt = np.array([[3, 7, 1, 12, 3, 7]], np.int32)
    out = np.asarray(models.generate(state, cfg, prompt, 10,
                                     temperature=args.temperature))
    print("prompt      :", prompt[0].tolist())
    print("continuation:", out[0, prompt.shape[1]:].tolist())
    if args.temperature == 0.0:
        want = [period[(2 + i) % 4] for i in range(10)]
        assert out[0, prompt.shape[1]:].tolist() == want, "pattern lost"
        print("self-check OK: greedy decode reproduces the trained period")

    if args.serve:
        if args.replicas > 1:
            cluster_demo(state, cfg, args)
        else:
            serve_demo(state, cfg, args)
    if args.slo_demo:
        slo_demo(state, cfg, args)


if __name__ == "__main__":
    main()
