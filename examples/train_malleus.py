"""Malleus end-to-end: elastic training with straggler injection,
profiling, re-solving, and live strategy hot-switch.

Counterpart of the reference's Malleus workflow
(``examples/malleus/pretrain_gpt.py`` + ``test_straggler_workload.py`` +
``test_accuracy.py``): train a GPT under an initial dp x tp layout,
inject a synthetic straggler workload mid-run, profile per-device step
ratios, re-solve the hetero layout with the StrategyModel (optionally
calibrated from live measurements via planner.profile_hardware), and
hot-switch parameters + optimizer states to the new layout without
losing training state.

Self-checking accuracy gate (the reference's ``test_accuracy``): the
loss stream must be continuous across the switch — the first loss after
the switch may not regress by more than a small epsilon vs the last loss
before it, and the final loss must be below the initial one.

Run (8 simulated devices):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python examples/train_malleus.py --steps 12
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def parse_args():
    p = argparse.ArgumentParser(description="Malleus elastic pretraining")
    p.add_argument("--vocab-size", type=int, default=256)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--switch-at", type=int, default=6)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--calibrate", action="store_true",
                   help="measure comm/compute constants first "
                        "(profile_hardware) instead of defaults")
    p.add_argument("--straggle", type=float, default=3.0,
                   help="slowdown ratio injected on device 0")
    return p.parse_args()


def main():
    args = parse_args()
    if args.steps <= args.switch_at + 2:
        raise SystemExit(
            f"--steps ({args.steps}) must exceed --switch-at + 2 "
            f"({args.switch_at + 2}): the run needs profile steps and at "
            "least one post-switch step for the accuracy gate")
    import jax
    import hetu_tpu as ht
    from jax.sharding import PartitionSpec as P
    from hetu_tpu import optim
    from hetu_tpu.elastic import Straggler, StragglerWorkload, StrategyModel
    from hetu_tpu.elastic.trainer import Trainer
    from hetu_tpu.models import GPTLMHeadModel, llama_config

    n_dev = min(8, len(jax.devices()))
    devices = jax.devices()[:n_dev]
    mesh = ht.create_mesh({"dp": n_dev // 2, "tp": 2}, devices)

    if args.calibrate:
        from hetu_tpu.planner import profile_and_calibrate
        cal = profile_and_calibrate(
            mesh=mesh, axis="tp", matmul_sizes=(256, 512),
            hbm_bytes=1 << 22, coll_sizes=(1 << 12, 1 << 15), reps=3)
        solver = StrategyModel.from_calibration(
            cal, num_devices=n_dev, num_layers=args.layers,
            batch=args.global_batch, seq=args.seq_len,
            hidden=args.hidden, ffn=4 * args.hidden)
        print(f"calibrated: layer_comm_cost={solver.layer_comm_cost:.4f} "
              f"pipeline_p2p_cost={solver.pipeline_p2p_cost:.4f}")
    else:
        solver = StrategyModel(num_devices=n_dev, num_layers=args.layers)

    cfg = llama_config(vocab_size=args.vocab_size, hidden_size=args.hidden,
                       num_layers=args.layers, num_heads=args.heads,
                       max_seq_len=args.seq_len, sp=False)
    rng = np.random.RandomState(0)
    with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
        ids = ht.parallel_placeholder(
            "int32", (args.global_batch, args.seq_len),
            pspec=P("dp", None), name="ids")
        lbl = ht.parallel_placeholder(
            "int32", (args.global_batch, args.seq_len),
            pspec=P("dp", None), name="lbl")
        model = GPTLMHeadModel(cfg)
        loss = model(ids, lbl)
        opt = optim.AdamOptimizer(lr=args.lr)
        train_op = opt.minimize(loss)

        # two fixed batches cycled (memorizable corpus -> the loss can
        # actually fall, which the accuracy gate below requires)
        batches = []
        for b in range(2):
            I = np.random.RandomState(b).randint(
                0, args.vocab_size,
                (args.global_batch, args.seq_len)).astype(np.int32)
            batches.append({ids: I, lbl: np.roll(I, -1, 1)})

        def data_provider(step):
            return batches[step % len(batches)]

        straggler = Straggler(n_dev)
        trainer = Trainer(g, loss, train_op, opt, data_provider, solver,
                          straggler=straggler, switch_threshold=0.02)

        # phase 1: homogeneous layout
        pre = trainer.train_steps(args.switch_at)
        print("pre-switch losses:", [round(x, 4) for x in pre])

        # inject a straggler (reference test_straggler_workload.py) and
        # retune from the *measured* profile
        ratios = [args.straggle] + [1.0] * (n_dev - 1)
        straggler.inject(StragglerWorkload(ratios))
        trainer.profile(steps=2)
        measured = straggler.read_profile()
        print("measured straggler ratios:", [round(r, 2) for r in measured])
        switched = trainer.retune(measured)
        print("retune -> switched:", switched,
              "| strategy:", trainer.current_strategy.describe()
              if trainer.current_strategy else None)

        # phase 2: continue training on the (possibly new) layout
        post = trainer.train_steps(args.steps - args.switch_at - 2)
        print("post-switch losses:", [round(x, 4) for x in post])

    # -- accuracy gates (reference examples/malleus/test_accuracy.py)
    all_losses = pre + post
    assert all(np.isfinite(all_losses)), all_losses
    # continuity: first post-switch loss must not regress vs the last
    # pre-switch loss by more than 10% of its magnitude
    assert post[0] <= pre[-1] + 0.1 * abs(pre[-1]), (pre[-1], post[0])
    assert all_losses[-1] < all_losses[0], all_losses
    hist = trainer.history
    print(f"malleus e2e OK: {all_losses[0]:.4f} -> {all_losses[-1]:.4f} | "
          f"switches recorded: {len(hist)}")


if __name__ == "__main__":
    main()
