"""Hydraulis end-to-end: variable-length LLM pretraining with
dispatch -> bucket packing -> packed (varlen) CP training.

Counterpart of the reference's Hydraulis workflow
(``examples/hydraulis/train_hetu.py`` + ``strategy/dynamic_pulp.py`` +
``data_utils/bucket.py``): a lognormal variable-length corpus is sorted
per global batch, dispatched across a strategy pool (MILP/greedy
makespan balancing), FFD-packed into per-strategy buckets, and trained
packed — segment ids give exact varlen masking through flash/ring
attention (the reference's cu_seqlens path), with CP (ring attention)
active when the mesh has a cp axis.

Self-checking: trains, prints losses, and verifies (a) every sequence is
dispatched exactly once, (b) packing stays within each strategy's
max_seqlen, (c) the packed loss stream is finite and trends down.

Run (8 simulated devices):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python examples/train_hydraulis.py --steps 8
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def parse_args():
    p = argparse.ArgumentParser(description="Hydraulis varlen pretraining")
    p.add_argument("--vocab-size", type=int, default=512)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--max-seqlen", type=int, default=256)
    p.add_argument("--global-batch", type=int, default=16)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--cp", type=int, default=2)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args()


def make_corpus(rng, n_docs, vocab, max_len):
    """Lognormal doc lengths (the reference's CommonCrawl-style skew)."""
    lens = np.clip(np.exp(rng.normal(4.2, 0.8, n_docs)).astype(int) + 8,
                   16, max_len)
    return [rng.randint(0, vocab, L).astype(np.int32) for L in lens]


def main():
    args = parse_args()
    import jax
    import hetu_tpu as ht
    from jax.sharding import PartitionSpec as P
    from hetu_tpu import optim
    from hetu_tpu.data.bucket import (Bucket, get_sorted_batch_and_len)
    from hetu_tpu.models import GPTLMHeadModel, llama_config
    from hetu_tpu.planner import (ChipSpec, ClusterSpec, DispatchStrategy,
                                  dynamic_dispatch)

    rng = np.random.RandomState(args.seed)
    n_dev = args.dp * args.cp * args.tp
    assert n_dev <= len(jax.devices()), \
        f"need {n_dev} devices, have {len(jax.devices())}"
    mesh = ht.create_mesh({"dp": args.dp, "cp": args.cp, "tp": args.tp},
                          jax.devices()[:n_dev])

    # -- strategy pool: a long-sequence tier and a short-sequence tier
    # (reference generate_strategy.py; coefficients here are the analytic
    # tp-scaled quadratic — profile_hardware can refit them)
    pool = [
        DispatchStrategy(tp=args.tp, pp=1, cp=args.cp, a=1e-9, b=1e-6,
                         c=1e-4, max_seqlen=args.max_seqlen),
        DispatchStrategy(tp=args.tp, pp=1, cp=1, a=4e-9, b=4e-6,
                         c=1e-4, max_seqlen=args.max_seqlen // 2),
    ]

    corpus = make_corpus(rng, args.global_batch * args.steps * 2,
                         args.vocab_size, args.max_seqlen)

    cfg = llama_config(vocab_size=args.vocab_size, hidden_size=args.hidden,
                       num_layers=args.layers, num_heads=args.heads,
                       max_seq_len=args.max_seqlen, sp=False,
                       cp_axis="cp")
    pad_id = 0

    with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
        # one placeholder shape per strategy tier (graph shape-buckets
        # re-use compiled plans across iterations)
        rows = args.global_batch  # fixed packed-row budget per tier
        feeds = {}
        for j, st in enumerate(pool):
            feeds[j] = (
                ht.parallel_placeholder("int32", (rows, st.max_seqlen),
                                        pspec=P("dp", None),
                                        name=f"ids{j}"),
                ht.parallel_placeholder("int32", (rows, st.max_seqlen),
                                        pspec=P("dp", None),
                                        name=f"lbl{j}"),
                ht.parallel_placeholder("int32", (rows, st.max_seqlen),
                                        pspec=P("dp", None),
                                        name=f"seg{j}"),
            )
        model = GPTLMHeadModel(cfg)
        losses_ops = {}
        opt = optim.AdamOptimizer(lr=args.lr)
        for j, (ids, lbl, seg) in feeds.items():
            loss = model(ids, lbl, segment_ids=seg)
            losses_ops[j] = (loss, opt.minimize(loss))

        step_losses = []
        for step in range(args.steps):
            batch_docs = [corpus[(step * args.global_batch + i)
                                 % len(corpus)]
                          for i in range(args.global_batch)]
            maxlen = max(len(d) for d in batch_docs)
            global_batch = np.full((len(batch_docs), maxlen), pad_id,
                                   np.int32)
            for i, d in enumerate(batch_docs):
                global_batch[i, :len(d)] = d
            sorted_batch, sorted_lens = get_sorted_batch_and_len(
                global_batch, pad_id)

            # dispatch sequences across the pool (makespan balancing)
            groups = dynamic_dispatch(pool, sorted_lens, use_ilp=False)
            assert sum(len(gr) for gr in groups) == len(sorted_lens), \
                "dispatch must cover every sequence exactly once"

            iter_losses = []
            for j, idxs in enumerate(groups):
                if not len(idxs):
                    continue
                st = pool[j]
                # FFD-pack this tier's sequences (alignment = 2*cp so
                # the SYM/ring split divides evenly)
                in_b = Bucket(pad_id, st.max_seqlen,
                              alignment=max(16, 2 * args.cp))
                lb_b = Bucket(pad_id, st.max_seqlen,
                              alignment=max(16, 2 * args.cp))
                for i in idxs:
                    n = int(sorted_lens[i])
                    seq = sorted_batch[i, :n]
                    in_b.add_data(seq[:-1], n - 1)
                    lb_b.add_data(seq[1:], n - 1)
                in_b.pack_data()
                lb_b.pack_data()
                packed = in_b.packed_batch
                labels = lb_b.packed_batch
                assert packed.shape[1] <= st.max_seqlen, \
                    f"packed width {packed.shape[1]} > {st.max_seqlen}"
                # segment ids from packed cu_seqlens; -1 on padding —
                # cu offsets are alignment-padded, so mark only each
                # doc's VALID span (alignment-gap positions stay -1 and
                # their labels -100: no training on padding)
                segs = np.full(packed.shape, -1, np.int32)
                for r, (cu, lens) in enumerate(zip(
                        in_b.packed_cu_seqlens_list,
                        in_b.packed_valid_lens_list)):
                    for d0 in range(len(lens)):
                        segs[r, cu[d0]:cu[d0] + lens[d0]] = d0
                lbls = np.where(segs >= 0, labels, -100).astype(np.int32)
                # fixed feed shape: pad rows + width to the tier budget
                IDS = np.full((rows, st.max_seqlen), pad_id, np.int32)
                LBL = np.full((rows, st.max_seqlen), -100, np.int32)
                SEG = np.full((rows, st.max_seqlen), -1, np.int32)
                r, w = packed.shape
                assert r <= rows, f"packed rows {r} > budget {rows}"
                IDS[:r, :w] = packed
                LBL[:r, :w] = lbls
                SEG[:r, :w] = segs
                ids_t, lbl_t, seg_t = feeds[j]
                loss, op = losses_ops[j]
                out = g.run(loss, [loss, op],
                            {ids_t: IDS, lbl_t: LBL, seg_t: SEG})
                iter_losses.append(float(np.asarray(out[0])))
            step_loss = float(np.mean(iter_losses))
            step_losses.append(step_loss)
            sizes = [len(gr) for gr in groups]
            print(f"step {step:3d} | loss {step_loss:.4f} | "
                  f"dispatch {sizes} | packed tiers "
                  f"{[pool[j].max_seqlen for j in range(len(pool))]}")

    assert all(np.isfinite(step_losses)), step_losses
    assert step_losses[-1] < step_losses[0], \
        f"loss did not decrease: {step_losses}"
    print(f"hydraulis e2e OK: {step_losses[0]:.4f} -> {step_losses[-1]:.4f}")


if __name__ == "__main__":
    main()
