"""CTR training entry point (WDL / DeepFM / DCN).

Counterpart of the reference's CTR recipes (``v1/examples/ctr/run_hetu.py``
over Criteo/Adult): synthetic Criteo-like data by default, pluggable
embedding backend — dense, HET-style cached (``--cached-embedding``), or
any compression method (``--compress hash|robe|tt|...``).

Run: JAX_PLATFORMS=cpu python examples/train_ctr.py --model deepfm \
         --steps 50 --cached-embedding
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

COMPRESSORS = {
    "hash": ("HashEmbedding", dict(table_size=1 << 14)),
    "compo": ("CompositionalEmbedding", dict(num_buckets=1 << 10)),
    "robe": ("ROBEEmbedding", dict(robe_size=1 << 16)),
    "dpq": ("DPQEmbedding", dict(num_codebooks=4, codebook_size=64)),
    "tt": ("TensorTrainEmbedding", dict(ranks=16)),
    "lowrank": ("LowRankEmbedding", dict(rank=8)),
    "quant": ("QuantizedEmbedding", dict(bits=8)),
}


def parse_args():
    p = argparse.ArgumentParser(description="CTR training")
    p.add_argument("--model", choices=["wdl", "deepfm", "dcn"],
                   default="wdl")
    p.add_argument("--vocab-size", type=int, default=100000)
    p.add_argument("--fields", type=int, default=26)
    p.add_argument("--dense", type=int, default=13)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--cached-embedding", action="store_true",
                   help="HET-style device cache over a host master table")
    p.add_argument("--cache-size", type=int, default=1 << 14)
    p.add_argument("--compress", choices=sorted(COMPRESSORS), default=None)
    return p.parse_args()


def main():
    args = parse_args()
    import hetu_tpu as ht
    from hetu_tpu import optim
    from hetu_tpu.models.ctr import DCN, DeepFM, WDL, ctr_loss

    rng = np.random.RandomState(0)
    n_samples = args.batch * 64
    ids_all = rng.randint(0, args.vocab_size,
                          (n_samples, args.fields)).astype(np.int32)
    dense_all = rng.randn(n_samples, args.dense).astype(np.float32)
    w = rng.randn(args.dense)
    labels_all = (dense_all @ w + 0.1 * rng.randn(n_samples) > 0) \
        .astype(np.float32)

    cls = {"wdl": WDL, "deepfm": DeepFM, "dcn": DCN}[args.model]
    with ht.graph("define_and_run", create_new=True) as g:
        emb = None
        if args.cached_embedding:
            from hetu_tpu.embedding import CachedEmbedding
            emb = CachedEmbedding(args.vocab_size, args.dim,
                                  cache_size=args.cache_size, policy="lfu")
        elif args.compress:
            import hetu_tpu.embedding as E
            cls_name, kw = COMPRESSORS[args.compress]
            emb = getattr(E, cls_name)(args.vocab_size, args.dim, **kw)
        sp = ht.placeholder("int32", (args.batch, args.fields), name="sp")
        dn = ht.placeholder("float32", (args.batch, args.dense), name="dn")
        lb = ht.placeholder("float32", (args.batch,), name="lb")
        model = cls(args.fields, args.vocab_size, embedding_dim=args.dim,
                    num_dense=args.dense, embedding=emb)
        loss = ctr_loss(model(sp, dn), lb)
        opt = optim.AdamOptimizer(lr=args.lr)
        train_op = opt.minimize(loss)
        if args.cached_embedding:
            emb.attach_optimizer(opt)
        for step in range(args.steps):
            s = (step * args.batch) % (n_samples - args.batch)
            ids = ids_all[s:s + args.batch]
            feed_ids = emb.prepare_batch(ids) if args.cached_embedding \
                else ids
            out = g.run(loss, [loss, train_op],
                        {sp: feed_ids, dn: dense_all[s:s + args.batch],
                         lb: labels_all[s:s + args.batch]})
            if (step + 1) % 10 == 0:
                print(f"step {step + 1:4d} | loss "
                      f"{float(np.asarray(out[0])):.4f}")
        if args.cached_embedding:
            emb.flush()
            print("cache:", emb.hit_info)


if __name__ == "__main__":
    main()
