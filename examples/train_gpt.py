"""GPT/LLaMA pre-training entry point.

Counterpart of the reference's canonical LLM pretrain script
(``examples/gpt/train_hetu.py``): argparse surface for model/parallel
config, ds_parallel_config JSON or (dp, tp, pp) flags, micro-batched
training with grad accumulation, AMP, checkpoint save/resume, and the
native prefetching dataloader.

Run (8 simulated devices):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python examples/train_gpt.py --dp 2 --tp 4 --steps 20 --hidden 128 \
      --layers 2 --seq-len 64

On a real TPU slice just drop the env overrides.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def parse_args():
    p = argparse.ArgumentParser(description="GPT/LLaMA pretraining")
    # model (reference train_hetu.py:479-588 surface)
    p.add_argument("--model", choices=["gpt", "llama"], default="gpt")
    p.add_argument("--vocab-size", type=int, default=50304)
    p.add_argument("--hidden", type=int, default=768)
    p.add_argument("--layers", type=int, default=12)
    p.add_argument("--heads", type=int, default=12)
    p.add_argument("--seq-len", type=int, default=1024)
    # parallel layout
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1, help="pipeline stages")
    p.add_argument("--sp", action="store_true", help="sequence parallel")
    p.add_argument("--grad-comm", choices=["fp32", "bf16", "int8"],
                   default=None,
                   help="explicit coalesced gradient sync transport "
                        "(None keeps the implicit GSPMD per-tensor sync)")
    p.add_argument("--flat-state", action="store_true",
                   help="flat dp-sharded optimizer state + reduce-"
                        "scatter-only sync (needs --grad-comm and "
                        "--zero 1/2/3; half the gradient wire bytes)")
    p.add_argument("--zero", type=int, default=0, choices=[0, 1, 2, 3],
                   help="ZeRO level for optimizer state/grad/param "
                        "sharding; 3 with --flat-state shards params AT "
                        "REST (1/dp fp32 masters only, just-in-time "
                        "bucket all-gather each step)")
    p.add_argument("--ds-config", type=str, default=None,
                   help="ds_parallel_config JSON path (overrides dp/tp/pp)")
    p.add_argument("--auto-parallel", action="store_true",
                   help="let the Galvatron-style planner pick "
                        "(dp, tp, pp, zero, micro-batch) for the visible "
                        "devices (overrides dp/tp/pp/zero flags)")
    p.add_argument("--calibrate", action="store_true",
                   help="with --auto-parallel: profile the live backend "
                        "(matmul/HBM/collectives) to calibrate the "
                        "planner's cost model first")
    # training
    p.add_argument("--global-batch", type=int, default=16)
    p.add_argument("--micro-batch", type=int, default=None)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--data", type=str, default=None,
                   help="token .npy file; synthetic data if omitted")
    p.add_argument("--save", type=str, default=None,
                   help="checkpoint dir (saved at the end)")
    p.add_argument("--load", type=str, default=None)
    p.add_argument("--log-every", type=int, default=5)
    p.add_argument("--trace-out", type=str, default=None,
                   help="trace the run (per-step feed/executable/commit "
                        "phase spans) and write a Perfetto-loadable "
                        "chrome trace JSON here")
    return p.parse_args()


def main():
    args = parse_args()
    import jax
    import hetu_tpu as ht
    from jax.sharding import PartitionSpec as P
    from hetu_tpu import optim
    from hetu_tpu.data import Dataloader, GPTSeqDataset
    from hetu_tpu.models import GPTConfig, GPTLMHeadModel, llama_config
    from hetu_tpu.utils import StepProfiler, get_logger

    log = get_logger("train_gpt")
    n_dev = len(jax.devices())
    dp, tp, pp, zero = args.dp, args.tp, args.pp, args.zero
    mk = llama_config if args.model == "llama" else GPTConfig
    cfg = mk(vocab_size=args.vocab_size, hidden_size=args.hidden,
             num_layers=args.layers, num_heads=args.heads,
             max_seq_len=args.seq_len, sp=args.sp,
             dtype="bfloat16" if args.bf16 else "float32")
    if args.auto_parallel:
        # closed planner loop (reference Galvatron
        # hybrid_parallel_config.py:13): search (pp, dp, tp, zero,
        # recompute, micro-batch) for THIS model on THESE devices
        from hetu_tpu.planner import (plan_for_gpt, plan_summary,
                                      profile_and_calibrate)
        cal = profile_and_calibrate(reps=3) if args.calibrate else None
        plan = plan_for_gpt(cfg, global_batch=args.global_batch,
                            seq=args.seq_len, n_chips=n_dev,
                            calibration=cal)
        summ = plan_summary(plan)
        dp, tp, pp = summ["dp"], summ["tp"], summ["pp"]
        zero = summ["zero"]
        if args.micro_batch is None and plan.micro_batch:
            args.micro_batch = plan.micro_batch
        log.info("auto-parallel plan: %s", json.dumps(summ))
    if args.ds_config:
        with open(args.ds_config) as f:
            cfg_json = json.load(f)
        ncfg = len(cfg_json["devices"])
        assert ncfg <= n_dev, f"config wants {ncfg} devices, have {n_dev}"
        from hetu_tpu.utils.ds_config import parse_layout
        dp, tp, pp, cfg_zero = parse_layout(cfg_json)
        zero = max(zero, int(cfg_zero))  # config may carry level 0-3
    assert dp * tp * pp <= n_dev, \
        f"dp*tp*pp={dp * tp * pp} > devices={n_dev}"

    if pp > 1:
        mesh = ht.create_mesh({"pp": pp, "dp": dp, "tp": tp},
                              jax.devices()[:dp * tp * pp])
    elif dp * tp > 1:
        mesh = ht.create_mesh({"dp": dp, "tp": tp},
                              jax.devices()[:dp * tp])
    else:
        mesh = None
    micro = args.micro_batch or max(1, args.global_batch // dp)
    num_micro = max(1, args.global_batch // (micro * dp))

    # data: token stream -> fixed windows through the native loader
    if args.data:
        tokens = np.load(args.data)
    else:
        rng = np.random.RandomState(0)
        tokens = rng.randint(0, args.vocab_size,
                             args.global_batch * args.seq_len * 64)
    ds = GPTSeqDataset(tokens, seq_len=args.seq_len)
    loader = Dataloader(ds, batch_size=args.global_batch, shuffle=True)

    batch_shape = (args.global_batch, args.seq_len)
    with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
        ids = ht.parallel_placeholder(
            "int32", batch_shape, pspec=P("dp", None) if mesh else None,
            name="input_ids")
        labels = ht.parallel_placeholder(
            "int32", batch_shape, pspec=P("dp", None) if mesh else None,
            name="labels")
        if pp > 1:
            from hetu_tpu.models.gpt_pipeline import GPTPipelineModel
            model = GPTPipelineModel(cfg, num_stages=pp)
            loss = model(ids, labels, num_micro_batches=num_micro)
        else:
            model = GPTLMHeadModel(cfg)
            loss = model(ids, labels)
        train_op = optim.AdamOptimizer(
            lr=args.lr, zero=zero, grad_comm=args.grad_comm,
            flat_state=args.flat_state).minimize(loss)
        if args.load:
            from hetu_tpu.utils.checkpoint import load_model
            load_model(model, args.load)
            log.info("resumed from %s", args.load)

        sp_prof = StepProfiler(warmup=2)
        tracer = None
        if args.trace_out:
            from hetu_tpu import obs
            tracer = obs.SpanTracer()
            obs.install_tracer(tracer)   # graph.run phases pick it up
        step = 0
        while step < args.steps:
            for batch in loader:
                if step >= args.steps:
                    break
                if isinstance(batch, tuple):   # python-fallback loader
                    x, y = batch
                else:                          # native matrix layout
                    x, y = batch[:, :args.seq_len], batch[:, args.seq_len:]
                with sp_prof:
                    # pp>1: micro-batching happens inside pipeline_spmd
                    out = g.run(loss, [loss, train_op], {ids: x, labels: y},
                                num_micro_batches=1 if pp > 1 else num_micro)
                step += 1
                if step % args.log_every == 0 or step == args.steps:
                    st = sp_prof.stats()
                    tput = (args.global_batch * args.seq_len
                            / st["mean"]) if st["mean"] else 0.0
                    print(f"step {step:5d} | loss "
                          f"{float(np.asarray(out[0])):.4f} | "
                          f"{st['mean'] * 1e3:.1f} ms/step | "
                          f"{tput_fmt(tput)}")
        if tracer is not None:
            from hetu_tpu import obs
            obs.install_tracer(None)
            obs.write_chrome_trace(tracer.events(), args.trace_out)
            print(obs.reconcile(tracer.events()).summary())
            print(f"wrote {len(tracer.events())} trace events to "
                  f"{args.trace_out} (open at https://ui.perfetto.dev)")
        if args.save:
            from hetu_tpu.utils.checkpoint import save_model
            d = os.path.dirname(os.path.abspath(args.save))
            os.makedirs(d, exist_ok=True)
            save_model(model, args.save)
            log.info("saved to %s", args.save)


def tput_fmt(tokens_per_s: float) -> str:
    if tokens_per_s >= 1e6:
        return f"{tokens_per_s / 1e6:.2f}M tok/s"
    return f"{tokens_per_s / 1e3:.1f}k tok/s"


if __name__ == "__main__":
    main()
