"""Flat dp-sharded optimizer state + reduce-scatter-only ZeRO-2 sync.

Pins down: the FlatStateLayout geometry (bucket/chunk identical to
reduce_scatter_coalesced, param index round-trip, uneven-size padding),
loss-equivalence of ``flat_state=True`` against the all-reduce baseline
across all three transports, the uneven-params chunk-padding case,
micro-batching / GRAD-level accumulation / clipping / weight decay
through the flat path, and the DistributedStates prediction of the new
collective shape (one reduce-scatter chain + one weight-dtype param
all-gather per bucket, ZERO gradient all-gathers).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import analysis, ops, optim
from hetu_tpu.optim import FlatStateLayout
from hetu_tpu.parallel import comm, create_mesh, dstates

UNEVEN = [(7, 5), (13,), (3,), (11, 3)]     # nothing divisible by dp=8


class TestFlatStateLayout:
    ENTRIES = [("a", (7, 5), "float32"), ("b", (13,), "float32"),
               ("c", (64,), "float32")]

    def test_geometry_matches_reduce_scatter(self):
        lay = FlatStateLayout(self.ENTRIES, device_num=8)
        numel = 7 * 5 + 13 + 64
        assert len(lay.buckets) == 1
        assert lay.chunks[0] == comm.quantized_chunk(numel, 8,
                                                     comm.INT8_BLOCK)
        assert lay.padded_sizes[0] == 8 * lay.chunks[0]
        # index walks the flatten order contiguously
        assert lay.index["a"] == (0, 0, 35, (7, 5))
        assert lay.index["b"] == (0, 35, 13, (13,))
        assert lay.index["c"] == (0, 48, 64, (64,))

    def test_pack_unpack_roundtrip_and_padding(self):
        lay = FlatStateLayout(self.ENTRIES, device_num=8)
        rng = np.random.RandomState(0)
        vals = {k: rng.randn(*shape).astype(np.float32)
                for k, shape, _ in self.ENTRIES}
        flats = lay.pack(vals)
        assert [int(f.shape[0]) for f in flats] == list(lay.padded_sizes)
        # padding lanes are exact zeros (inert through any update)
        numel = sum(v.size for v in vals.values())
        np.testing.assert_array_equal(np.asarray(flats[0])[numel:], 0.0)
        back = lay.unpack(flats)
        for k, v in vals.items():
            np.testing.assert_array_equal(np.asarray(back[k]), v)

    def test_dtype_separated_buckets(self):
        entries = [("a", (16,), "float32"), ("b", (16,), "bfloat16"),
                   ("c", (16,), "float32")]
        lay = FlatStateLayout(entries, device_num=8)
        assert len(lay.buckets) == 2
        assert {b.dtype for b in lay.buckets} == {"float32", "bfloat16"}

    def test_same_geometry(self):
        a = FlatStateLayout(self.ENTRIES, 8)
        b = FlatStateLayout(self.ENTRIES, 8)
        c = FlatStateLayout(self.ENTRIES, 4)
        assert a.same_geometry(b) and not a.same_geometry(c)
        assert not a.same_geometry(None)


def _train(devices8, grad_comm, flat=False, zero=None, nmb=1, steps=4,
           shapes=(), opt_cls=optim.AdamOptimizer,
           opt_kw=None, grad_runs=0):
    """Linear regression on the virtual-8 mesh (plus optional extra
    params of arbitrary ``shapes`` folded into the loss via mean(p^2),
    so every one receives gradients); returns (losses, graph,
    optimizer)."""
    if zero is None:
        zero = 2 if flat else 0
    mesh = create_mesh({"dp": 8}, devices8)
    with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
        x = ht.parallel_placeholder("float32", (16, 8),
                                    pspec=P("dp", None), name="x")
        y = ht.parallel_placeholder("float32", (16, 1),
                                    pspec=P("dp", None), name="y")
        rng = np.random.RandomState(7)
        w = ht.parameter((0.1 * rng.randn(8, 1)).astype(np.float32),
                         name="w")
        b = ht.parameter(np.zeros((1,), np.float32), name="b")
        extras = [ht.parameter(
            (0.1 * rng.randn(*s)).astype(np.float32), name=f"p{i}")
            for i, s in enumerate(shapes)]
        loss = ops.reduce_mean((ops.matmul(x, w) + b - y) ** 2)
        for p in extras:
            loss = loss + ops.reduce_mean(p ** 2)
        op = opt_cls(lr=1e-2, zero=zero, grad_comm=grad_comm,
                     flat_state=flat, **(opt_kw or {})).minimize(loss)
        X = np.random.RandomState(0).randn(16, 8).astype(np.float32)
        Y = np.random.RandomState(1).randn(16, 1).astype(np.float32)
        losses = []
        opt_obj = op.producer.attrs["optimizer"]
        for _ in range(grad_runs):
            g.run(loss, [loss, op], {x: X, y: Y}, run_level="grad")
        for _ in range(steps):
            o = g.run(loss, [loss, op], {x: X, y: Y},
                      num_micro_batches=nmb)
            losses.append(float(np.asarray(o[0])))
        return losses, g, opt_obj


class TestFlatZero2LossEquivalence:
    def test_fp32_flat_matches_implicit_exactly(self, devices8):
        base, g0, _ = _train(devices8, None)
        assert not g0._grad_comm_active
        got, g1, opt = _train(devices8, "fp32", flat=True)
        assert g1._grad_comm_active, g1._grad_comm_fallback
        np.testing.assert_allclose(got, base, rtol=1e-6)
        # the state really is flat and dp-sharded
        assert opt._flat_layout is not None
        assert set(opt._state) == {"step", "flat_master", "flat_m",
                                   "flat_v"}
        for buf in opt._state["flat_m"]:
            assert tuple(buf.sharding.spec) == ("dp",)

    @pytest.mark.parametrize("transport,tol", [("bf16", 5e-3),
                                               ("int8", 5e-3)])
    def test_quantized_flat_loss_curve(self, devices8, transport, tol):
        base, _, _ = _train(devices8, None)
        got, g, _ = _train(devices8, transport, flat=True)
        assert g._grad_comm_active, g._grad_comm_fallback
        np.testing.assert_allclose(got, base, rtol=tol)

    @pytest.mark.parametrize("transport", ["fp32", "int8"])
    def test_uneven_params_chunk_padding(self, devices8, transport):
        """Param sizes not divisible by dp=8: chunk boundaries land
        mid-parameter and the flat buffers carry real padding."""
        base, _, _ = _train(devices8, None, shapes=UNEVEN)
        got, g, opt = _train(devices8, transport, flat=True,
                             shapes=UNEVEN)
        assert g._grad_comm_active, g._grad_comm_fallback
        tol = 1e-6 if transport == "fp32" else 5e-3
        np.testing.assert_allclose(got, base, rtol=tol)
        lay = opt._flat_layout
        numel = 8 + 1 + sum(int(np.prod(s)) for s in UNEVEN)  # w, b, extras
        assert sum(lay.padded_sizes) > numel          # real padding
        assert all(sz % 8 == 0 for sz in lay.padded_sizes)

    def test_micro_batches_and_grad_accumulation(self, devices8):
        base, _, _ = _train(devices8, None)
        mb, g1, _ = _train(devices8, "fp32", flat=True, nmb=2)
        assert g1._grad_comm_active
        np.testing.assert_allclose(mb, base, rtol=1e-4)
        # GRAD-level runs keep the all-reduce sync and fold into the
        # flat UPDATE step; the equivalent baseline sees the same
        # accumulated gradient
        accum_base, _, _ = _train(devices8, "fp32", flat=False, zero=0,
                                  grad_runs=2, steps=2)
        accum_flat, g2, _ = _train(devices8, "fp32", flat=True,
                                   grad_runs=2, steps=2)
        assert g2._grad_comm_active
        np.testing.assert_allclose(accum_flat, accum_base, rtol=1e-5)

    def test_clip_and_weight_decay(self, devices8):
        base, _, _ = _train(devices8, "fp32", flat=False, zero=2,
                            opt_kw={"max_grad_norm": 0.5,
                                    "weight_decay": 0.1})
        got, g, _ = _train(devices8, "fp32", flat=True,
                           opt_kw={"max_grad_norm": 0.5,
                                   "weight_decay": 0.1})
        assert g._grad_comm_active
        np.testing.assert_allclose(got, base, rtol=1e-5)

    def test_adamw_and_sgd_momentum(self, devices8):
        for cls, kw in ((optim.AdamWOptimizer, {"weight_decay": 0.1}),
                        (optim.SGDOptimizer, {"momentum": 0.9})):
            base, _, _ = _train(devices8, None, opt_cls=cls, opt_kw=kw)
            got, g, _ = _train(devices8, "fp32", flat=True, opt_cls=cls,
                               opt_kw=kw)
            assert g._grad_comm_active, g._grad_comm_fallback
            np.testing.assert_allclose(got, base, rtol=1e-5,
                                       err_msg=cls.__name__)

    def test_external_param_write_supersedes_master(self, devices8):
        """reset_variable / load_model mid-training must win over the
        packed fp32 master: the step after the write trains from the
        written values, not from a stale master that would silently
        revert them (regression: graph._var_writes epoch)."""
        mesh = create_mesh({"dp": 8}, devices8)
        with ht.graph("define_and_run", create_new=True,
                      mesh=mesh) as g:
            x = ht.parallel_placeholder("float32", (16, 8),
                                        pspec=P("dp", None), name="x")
            y = ht.parallel_placeholder("float32", (16, 1),
                                        pspec=P("dp", None), name="y")
            W0 = np.linspace(-1, 1, 8).reshape(8, 1).astype(np.float32)
            w = ht.parameter(W0.copy(), name="w")
            loss = ops.reduce_mean((ops.matmul(x, w) - y) ** 2)
            op = optim.AdamOptimizer(lr=1e-2, zero=2, grad_comm="fp32",
                                     flat_state=True).minimize(loss)
            rng = np.random.RandomState(0)
            feed = {x: rng.randn(16, 8).astype(np.float32),
                    y: rng.randn(16, 1).astype(np.float32)}
            l1 = float(np.asarray(g.run(loss, [loss, op], feed)[0]))
            l2 = float(np.asarray(g.run(loss, [loss, op], feed)[0]))
            assert g._grad_comm_active and l2 < l1
            g.reset_variable(w, W0)            # external restore
            l3 = float(np.asarray(g.run(loss, [loss, op], feed)[0]))
            # loss computed from the RESTORED params, not a stale master
            np.testing.assert_allclose(l3, l1, rtol=1e-6)

    def test_unrelated_write_refreshes_only_written_master(self,
                                                           devices8):
        """reset_variable on ONE param must refresh only that param's
        master slice: other buckets keep their exact buffers (a blanket
        repack would round every bf16 param's fp32 master through the
        live values)."""
        mesh = create_mesh({"dp": 8}, devices8)
        with ht.graph("define_and_run", create_new=True,
                      mesh=mesh) as g:
            x = ht.parallel_placeholder("float32", (16, 8),
                                        pspec=P("dp", None), name="x")
            y = ht.parallel_placeholder("float32", (16, 1),
                                        pspec=P("dp", None), name="y")
            w = ht.parameter(np.linspace(-1, 1, 8).reshape(8, 1)
                             .astype(np.float32), name="w")
            b = ht.parameter(np.zeros((1,), np.float32), name="b")
            loss = ops.reduce_mean((ops.matmul(x, w) + b - y) ** 2)
            # 32-byte bucket cap: w (8 fp32 = 32 B) fills a bucket and
            # b lands in the NEXT one, so the refresh granularity is
            # observable per bucket
            opt = optim.AdamOptimizer(lr=1e-2, zero=2, grad_comm="fp32",
                                      flat_state=True,
                                      bucket_mb=32 / (1 << 20))
            op = opt.minimize(loss)
            rng = np.random.RandomState(0)
            feed = {x: rng.randn(16, 8).astype(np.float32),
                    y: rng.randn(16, 1).astype(np.float32)}
            g.run(loss, [loss, op], feed)
            g.run(loss, [loss, op], feed)
            assert g._grad_comm_active
            lay = opt._flat_layout
            assert lay.index[w.id][0] != lay.index[b.id][0]
            before = list(opt._state["flat_master"])
            g.reset_variable(b, np.ones((1,), np.float32))
            opt._ensure_flat_state(dict(g._var_data), [w, b], g)
            after = opt._state["flat_master"]
            bi_w, bi_b = lay.index[w.id][0], lay.index[b.id][0]
            assert after[bi_w] is before[bi_w]      # untouched bucket
            assert after[bi_b] is not before[bi_b]  # written param
            off, numel = lay.index[b.id][1], lay.index[b.id][2]
            np.testing.assert_array_equal(
                np.asarray(after[bi_b])[off:off + numel], 1.0)

    def test_flat_constructor_validation(self):
        with pytest.raises(ValueError, match="explicit grad-comm"):
            optim.AdamOptimizer(lr=1e-2, zero=2, flat_state=True)
        with pytest.raises(ValueError, match="ZeRO"):
            optim.AdamOptimizer(lr=1e-2, grad_comm="fp32",
                                flat_state=True)
        # ZeRO-3 on the flat layout is supported since PR 19 (params
        # sharded at rest, gathered just-in-time)
        opt = optim.AdamOptimizer(lr=1e-2, zero=3, grad_comm="fp32",
                                  flat_state=True)
        assert opt.zero == 3 and opt.flat_state

    def test_fallback_keeps_per_param_state(self, devices8):
        """On a mesh the explicit path rejects, a flat_state optimizer
        falls back to the implicit path with ordinary per-param state
        (recorded reason) instead of crashing."""
        mesh = create_mesh({"dp": 4, "tp": 2}, devices8)
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            x = ht.parallel_placeholder("float32", (8, 8),
                                        pspec=P("dp", None), name="x")
            y = ht.parallel_placeholder("float32", (8, 1),
                                        pspec=P("dp", None), name="y")
            w = ht.parameter(np.zeros((8, 1), np.float32), name="w")
            loss = ops.reduce_mean((ops.matmul(x, w) - y) ** 2)
            op = optim.AdamOptimizer(lr=1e-2, zero=2, grad_comm="fp32",
                                     flat_state=True).minimize(loss)
            rng = np.random.RandomState(0)
            g.run(loss, [loss, op],
                  {x: rng.randn(8, 8).astype(np.float32),
                   y: rng.randn(8, 1).astype(np.float32)})
            assert not g._grad_comm_active
            assert "pure-dp" in g._grad_comm_fallback
            opt = op.producer.attrs["optimizer"]
            assert "m" in opt._state          # per-param fallback state


class TestFlatEmission:
    """The lowered program contains EXACTLY the predicted sequence: one
    reduce-scatter chain + one weight-dtype param all-gather per bucket,
    zero gradient all-gathers."""

    @pytest.mark.parametrize("transport", ["fp32", "bf16", "int8"])
    def test_prediction_matches_emission(self, devices8, transport):
        _, g, _ = _train(devices8, transport, flat=True, steps=1)
        (handle,) = g.analysis_handles()
        gc = handle.meta["grad_comm"]
        assert gc["flat"] is True and gc["zero"] == 2
        assert handle.meta["allowed_gspmd"] == {}
        analysis.verify_grad_comm(handle)
        pred, extra = analysis.grad_comm_prediction(handle)
        # flat shape: no gradient all_gather; exactly one param gather
        # per bucket, riding the bucket (weight) dtype
        kinds = [p["kind"] for p in pred]
        gathers = [p for p in pred if p["kind"] == "all_gather"]
        assert len(gathers) == 1 and gathers[0]["dtype"] == "float32"
        if transport == "fp32":
            assert kinds.count("reduce_scatter") == 1
        # jaxpr inventory agrees kind-for-kind, and the param gather is
        # attributed param_comm (separable from gradient bytes)
        rep = analysis.analyze_handle(handle)
        want = dict(extra)
        for p in pred:
            want[p["kind"]] = want.get(p["kind"], 0) + 1
        assert rep.collective_counts() == want
        param_recs = [r for r in rep.records if "param_comm" in r.scope]
        assert len(param_recs) == 1
        assert param_recs[0].kind == "all_gather"
        grad_ag = [r for r in rep.records
                   if r.kind == "all_gather" and "grad_comm" in r.scope]
        assert grad_ag == []                  # ZERO gradient regathers
        # clean under every rule, including the new ZeRO-2 tripwire
        full = analysis.analyze_handle(handle, compile=True)
        assert full.findings == [], full.findings

    def test_flat_halves_gradient_wire_bytes(self, devices8):
        """Predicted gradient wire bytes (everything except the
        param_comm gather) drop 2x vs the all-reduce path at the same
        transport."""
        # model-scale tensors: chunk padding (256-element blocks x 8
        # ranks) is noise here, as on a real model — tiny toy tensors
        # would understate the ratio
        entries = [(f"g{i}", s, "float32")
                   for i, s in enumerate([(512, 512), (1024, 256),
                                          (4096,)])]
        for tr in ("fp32", "bf16", "int8"):
            ar = dstates.predict_grad_comm_collectives(entries, 8,
                                                       transport=tr)
            flat = dstates.predict_flat_update_collectives(entries, 8,
                                                           transport=tr)
            ar_bytes = sum(p["wire_bytes"] for p in ar)
            flat_grad = sum(p["wire_bytes"] for p in flat
                            if p["kind"] != "all_gather")
            assert ar_bytes / flat_grad >= 1.8, tr

    def test_clip_adds_one_allreduce_to_prediction(self, devices8):
        _, g, _ = _train(devices8, "fp32", flat=True, steps=1,
                         opt_kw={"max_grad_norm": 1.0})
        (handle,) = g.analysis_handles()
        assert handle.meta["grad_comm"]["clip"] is True
        analysis.verify_grad_comm(handle)      # psum counted via extra
        _, extra = analysis.grad_comm_prediction(handle)
        assert extra["all_reduce"] == 2        # loss pmean + clip psum
