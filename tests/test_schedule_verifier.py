"""Cross-rank collective-schedule verifier (ISSUE 20).

The verifier extracts per-rank symbolic communication schedules (ZeRO-3
front gathers, dp grad buckets, tp/cp collectives, pipeline p2p,
hot-switch repack transfers) and proves cross-rank consistency: the
full strategy grid verifies with ZERO violations, every seeded
divergence in the bug corpus is flagged by EXACTLY its rule with a
per-rank explanatory subtrace, the vacuity registry keeps each rule
honest about the op kinds it inspects, and the MPMD runtime's executed
p2p order matches the symbolic projection the verifier checks.
"""
import json
import os

import numpy as np
import pytest

from hetu_tpu.analysis.rules import RULES, SCHEDULE_RULE_OP_KINDS
from hetu_tpu.analysis.schedule import (COLLECTIVE_KINDS, P2P_KINDS,
                                        SCHEDULE_RULES, CommOp, ProgramSpec,
                                        _reference_spec, extract_schedules,
                                        seeded_bug_corpus, spec_from_meta,
                                        strategy_grid, verify_schedules)
from hetu_tpu.parallel.schedule import (generate_gpipe_schedule,
                                        generate_pipedream_flush_schedule,
                                        p2p_events)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# spec construction is symbolic and cheap; extraction happens in-test
GRID = list(strategy_grid())
CORPUS = seeded_bug_corpus()


def _load_baseline():
    with open(os.path.join(REPO, "ANALYSIS_BASELINE.json")) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# extraction: every op plane lands, in the documented order
# ---------------------------------------------------------------------------


class TestExtraction:
    def test_reference_spec_populates_every_plane(self):
        sched = extract_schedules(_reference_spec())
        assert sorted(sched) == list(range(8))
        tags = {o.tag for ops in sched.values() for o in ops}
        kinds = {o.kind for ops in sched.values() for o in ops}
        # ZeRO-3 front gathers lead every rank's program (PR 19's
        # at-rest sharding: weights materialize before any forward math)
        for r, ops in sched.items():
            assert ops and ops[0].kind == "all_gather"
            assert ops[0].tag == "param_gather", (r, ops[0])
        assert any(t.startswith("tp/") for t in tags)          # tp plane
        assert any(t.startswith("pipe") for t in tags)         # p2p plane
        assert any(t.startswith("grad_comm/") or t == "fetch/scalar"
                   for t in tags)                              # grad tail
        assert any(t.startswith("switch/repack/") for t in tags)
        assert {"send", "recv"} <= kinds
        assert verify_schedules(sched) == []

    def test_uneven_per_pipe_micro_batches_differ(self):
        """Malleus apportionment: pipe 0 runs 3 micro-batches, pipe 1
        runs 1 — their p2p inventories differ but still pair up."""
        sched = extract_schedules(_reference_spec())
        # rank = ((p*dp + d)*cp + c)*tp + t: stage outermost, so the
        # pipe index is the dp coordinate — pipe 1's stage 0 is rank 2
        pipe0 = [o for o in sched[0] if o.tag.startswith("pipe")]
        pipe1 = [o for o in sched[2] if o.tag.startswith("pipe")]
        assert len(pipe0) > len(pipe1) > 0

    def test_grad_plane_matches_optimizer_contract(self):
        """The schedule's grad ops ARE the optimizer's predicted step
        collectives — Optimizer.predicted_step_collectives is the single
        source of truth, so the two planes cannot drift."""
        from hetu_tpu.optim import AdamOptimizer
        spec = ProgramSpec(dp=2, zero=3, flat=True, transport="fp32")
        opt = AdamOptimizer(lr=1e-3, zero=3, grad_comm="fp32",
                            flat_state=True)
        preds, extra = opt.predicted_step_collectives(spec.entries,
                                                      spec.dp)
        want = [(p["kind"], int(p["payload_bytes"]), p["dtype"])
                for p in preds]
        want += [(k, 4, "float32") for k, n in sorted(extra.items())
                 for _ in range(int(n))]
        sched = extract_schedules(spec)
        for r, ops in sched.items():
            got = [(o.kind, o.payload_bytes, o.dtype) for o in ops]
            assert sorted(got) == sorted(want), r

    def test_ring_cp_emits_hop_chain(self):
        spec = ProgramSpec(dp=1, cp=4, cp_mode="ring", entries=())
        sched = extract_schedules(spec)
        hops = [o for o in sched[0] if o.kind == "ppermute"]
        # cp-1 hops per layer per phase (fwd+bwd), 2 layers, 2 mbs
        assert len(hops) == 3 * 2 * 2 * spec.num_micro_batches
        assert verify_schedules(sched) == []


class TestSpecFromMeta:
    def test_explicit_schedule_spec_wins(self):
        spec = spec_from_meta({"schedule_spec": {"dp": 2, "tp": 4},
                               "grad_comm": {"device_num": 8,
                                             "entries": []}}, {})
        assert (spec.dp, spec.tp) == (2, 4)

    def test_grad_comm_meta(self):
        meta = {"grad_comm": {"device_num": 4, "zero": 3, "flat": True,
                              "transport": "int8",
                              "entries": [("w", (8, 8), "float32")]}}
        spec = spec_from_meta(meta, {"tp": 2})
        assert (spec.dp, spec.tp, spec.zero, spec.flat) == (4, 2, 3, True)
        sched = extract_schedules(spec)
        assert len(sched) == 8 and verify_schedules(sched) == []

    def test_spmd_pipeline_meta_uses_mesh_extent(self):
        """The SPMD pipeline registration has no num_stages key — its
        stage count is the pp mesh extent (the PR 20 gate regression:
        gate_pipe_spmd must make a multi-rank claim)."""
        spec = spec_from_meta({"pipeline": {"pp_axis": "pp", "hops": 5}},
                              {"pp": 4})
        assert spec is not None and spec.pp == 4
        assert spec.pipeline_mode == "spmd"
        sched = extract_schedules(spec)
        assert len(sched) == 4
        assert any(o.kind == "ppermute" for o in sched[0])
        assert verify_schedules(sched) == []

    def test_no_multi_rank_claim_is_none(self):
        assert spec_from_meta({}, {}) is None
        assert spec_from_meta({"pipeline": {"num_stages": 1}}, {}) is None


# ---------------------------------------------------------------------------
# the clean grid: every strategy point verifies hang-free
# ---------------------------------------------------------------------------


class TestCleanGrid:
    def test_grid_spans_the_strategy_axes(self):
        labels = [l for l, _ in GRID]
        assert len(GRID) >= 40
        for probe in ("z0", "z2", "z3", "_spmd", "_mpmd", "_switch",
                      "cp2", "tp2", "pp2"):
            assert any(probe in l for l in labels), probe

    @pytest.mark.parametrize("label,spec", GRID,
                             ids=[l for l, _ in GRID])
    def test_grid_point_verifies_clean(self, label, spec):
        sched = extract_schedules(spec)
        assert sorted(sched) == list(range(spec.world))
        violations = verify_schedules(sched)
        assert violations == [], \
            [f"{v.rule}: {v.message}" for v in violations]


# ---------------------------------------------------------------------------
# seeded-bug corpus: each divergence found by EXACTLY its rule
# ---------------------------------------------------------------------------


class TestSeededCorpus:
    def test_corpus_covers_every_rule(self):
        assert len(CORPUS) >= 6
        assert {e["rule"] for e in CORPUS} == set(SCHEDULE_RULES)

    @pytest.mark.parametrize("entry", CORPUS,
                             ids=[e["name"] for e in CORPUS])
    def test_seeded_divergence_found_by_exactly_its_rule(self, entry):
        violations = verify_schedules(entry["schedules"])
        assert violations, entry["name"]
        assert {v.rule for v in violations} == {entry["rule"]}, \
            [f"{v.rule}: {v.message}" for v in violations]
        for v in violations:
            assert v.ranks and v.subtrace
            sub = v.format_subtrace()
            assert "rank " in sub and sub.count("rank ") >= 2, \
                "subtrace must show the divergent ranks side by side"


# ---------------------------------------------------------------------------
# vacuity: every schedule rule demonstrably sees its op kinds
# ---------------------------------------------------------------------------


def _gate_and_grid_kinds():
    kinds = set()
    for exe in _load_baseline().get("executables", {}).values():
        kinds |= set((exe.get("schedule") or {}).get("kinds", {}))
    for _, spec in GRID:
        for ops in extract_schedules(spec).values():
            kinds |= {o.kind for o in ops}
    return kinds


class TestVacuity:
    def test_registry_matches_rule_registry(self):
        assert set(SCHEDULE_RULE_OP_KINDS) == set(SCHEDULE_RULES)
        unknown = set(SCHEDULE_RULE_OP_KINDS) - set(RULES)
        assert not unknown, f"registry names unregistered rules: {unknown}"
        vocab = set(COLLECTIVE_KINDS) | set(P2P_KINDS) | {"copy"}
        for name, kinds in SCHEDULE_RULE_OP_KINDS.items():
            assert kinds and set(kinds) <= vocab, (name, kinds)

    @pytest.mark.parametrize("rule_name", sorted(SCHEDULE_RULE_OP_KINDS))
    def test_rule_is_not_vacuous_over_gate_and_grid(self, rule_name):
        """The op kinds a rule inspects occur in the frozen gate
        schedules or the strategy grid — otherwise its green verdict
        never saw its input."""
        seen = _gate_and_grid_kinds()
        assert seen, "no schedule kinds anywhere — extraction collapsed"
        assert seen & set(SCHEDULE_RULE_OP_KINDS[rule_name]), rule_name

    @pytest.mark.parametrize("rule_name", sorted(SCHEDULE_RULE_OP_KINDS))
    def test_rule_sees_its_kinds_in_its_corpus_entry(self, rule_name):
        entries = [e for e in CORPUS if e["rule"] == rule_name]
        assert entries, f"no corpus entry seeds {rule_name}"
        kinds = set(SCHEDULE_RULE_OP_KINDS[rule_name])
        for e in entries:
            got = {o.kind for ops in e["schedules"].values() for o in ops}
            assert got & kinds, (e["name"], rule_name)


# ---------------------------------------------------------------------------
# gate wiring: baseline sections + regression detection (satellite 5)
# ---------------------------------------------------------------------------


class TestGateWiring:
    def test_baseline_pins_schedule_coverage(self):
        exes = _load_baseline()["executables"]
        scheds = {n: e.get("schedule") for n, e in exes.items()}
        assert all(s is not None for s in scheds.values()), \
            [n for n, s in scheds.items() if s is None]
        claimed = {n: s for n, s in scheds.items() if s["ranks"] > 0}
        # the train, pipeline and MoE families all make multi-rank claims
        assert len(claimed) >= 4, sorted(claimed)
        for n, s in scheds.items():
            assert s["violations"] == 0, n
            assert s["rules_available"] == sorted(SCHEDULE_RULES), n
        for n, s in claimed.items():
            assert s["ops"] > 0 and s["kinds"], n

    def _report_with(self, schedule_meta):
        from hetu_tpu.analysis.report import (AnalysisReport,
                                              ExecutableReport)
        rep = AnalysisReport()
        rep.add(ExecutableReport(name="x", meta={"schedule":
                                                 schedule_meta}))
        return rep

    def _baseline_for(self, schedule_meta):
        rep = self._report_with(schedule_meta)
        return rep.to_dict()

    def test_new_violation_fails_the_gate(self):
        clean = {"ranks": 4, "ops": 40, "kinds": {"send": 20},
                 "collectives": 0, "p2p": 40, "switch": 0,
                 "violations": 0, "violation_rules": [],
                 "rules_available": sorted(SCHEDULE_RULES)}
        base = self._baseline_for(clean)
        dirty = dict(clean, violations=1,
                     violation_rules=["pipeline-deadlock"])
        probs = self._report_with(dirty).check_against_baseline(base)
        assert any("schedule violations regressed" in p for p in probs)

    def test_vanished_rule_fails_the_gate(self):
        pinned = {"ranks": 0, "ops": 0, "kinds": {}, "collectives": 0,
                  "p2p": 0, "switch": 0, "violations": 0,
                  "violation_rules": [],
                  "rules_available": sorted(SCHEDULE_RULES)
                  + ["ghost-rule"]}
        base = self._baseline_for(pinned)
        now = dict(pinned, rules_available=sorted(SCHEDULE_RULES))
        probs = self._report_with(now).check_against_baseline(base)
        assert any("vanished" in p and "ghost-rule" in p for p in probs)

    def test_collapsed_extraction_fails_the_gate(self):
        full = {"ranks": 8, "ops": 160, "kinds": {"send": 80},
                "collectives": 0, "p2p": 160, "switch": 0,
                "violations": 0, "violation_rules": [],
                "rules_available": sorted(SCHEDULE_RULES)}
        base = self._baseline_for(full)
        gone = dict(full, ranks=0, ops=0, kinds={}, p2p=0)
        probs = self._report_with(gone).check_against_baseline(base)
        assert any("collapsed" in p for p in probs)

    def test_cli_schedule_section_renders_verdict(self):
        import io
        from hetu_tpu.analysis.cli import schedule_section
        rep = self._report_with({
            "ranks": 8, "ops": 160, "kinds": {"send": 80, "recv": 80},
            "collectives": 0, "p2p": 160, "switch": 0, "violations": 0,
            "violation_rules": [],
            "rules_available": sorted(SCHEDULE_RULES)})
        buf = io.StringIO()
        schedule_section(rep, buf)
        out = buf.getvalue()
        assert "8 ranks" in out and "hang-free" in out
        rep2 = self._report_with({
            "ranks": 0, "ops": 0, "kinds": {}, "collectives": 0,
            "p2p": 0, "switch": 0, "violations": 0,
            "violation_rules": [],
            "rules_available": sorted(SCHEDULE_RULES)})
        buf2 = io.StringIO()
        schedule_section(rep2, buf2)
        assert "no multi-rank claim" in buf2.getvalue()

    @pytest.mark.lint_graph
    def test_schedule_gate_grid_and_corpus(self):
        """The tier-1 schedule gate: the full strategy grid verifies
        hang-free and every corpus divergence is caught by exactly its
        rule (the bench.py schedule_lint sweep, inline)."""
        dirty = []
        for label, spec in GRID:
            if verify_schedules(extract_schedules(spec)):
                dirty.append(label)
        assert dirty == []
        for e in CORPUS:
            vs = verify_schedules(e["schedules"])
            assert vs and {v.rule for v in vs} == {e["rule"]}, e["name"]


# ---------------------------------------------------------------------------
# planner hook: searched plans carry a hang-freedom verdict
# ---------------------------------------------------------------------------


class TestPlannerHook:
    def test_plan_summary_reports_hang_free(self):
        from hetu_tpu.planner import (plan_for_gpt, plan_summary,
                                      verify_plan_schedule)
        from hetu_tpu.models.gpt import llama_config
        cfg = llama_config(vocab_size=96, hidden_size=64, num_layers=4,
                           num_heads=4, max_seq_len=64)
        plan = plan_for_gpt(cfg, global_batch=8, seq=64, n_chips=8)
        assert verify_plan_schedule(plan) == []
        assert plan_summary(plan)["schedule_hang_free"] is True


# ---------------------------------------------------------------------------
# satellite 2: the MPMD runtime's executed p2p order matches the
# symbolic projection the verifier checks
# ---------------------------------------------------------------------------


def _tap_by_stage(runtime, num_pipes):
    S = runtime.num_stages
    out = [[[] for _ in range(S)] for _ in range(num_pipes)]
    for (d, k, p, s, m, peer) in runtime.p2p_log:
        out[p][s].append((d, k, m, peer))
    return out


def _assert_tap_matches(model, counts):
    rt = model.runtime
    got = _tap_by_stage(rt, len(rt.pipes))
    for p, m_p in enumerate(counts):
        want = p2p_events(rt._schedule(m_p))
        for s in range(rt.num_stages):
            assert got[p][s] == want[s], (p, s, got[p][s], want[s])


class TestMPMDTapMatchesProjection:
    """``p2p_events`` is the projection three consumers share: the
    schedule generator, the runtime tap, and the cross-rank verifier.
    A tap/projection divergence means the verifier proves the wrong
    program hang-free."""

    def _model(self, stage_layers, seed=3):
        from hetu_tpu.models.gpt import llama_config
        from hetu_tpu.models.gpt_mpmd import MPMDGPT
        cfg = llama_config(vocab_size=32, hidden_size=16, num_layers=3,
                           num_heads=2, max_seq_len=8, dtype="float32")
        return MPMDGPT(cfg, stage_layers=stage_layers, seed=seed)

    def _step(self, model, counts, seed=0):
        cfg = model.cfg
        rng = np.random.RandomState(seed)
        ids = rng.randint(0, cfg.vocab_size,
                          (sum(counts), cfg.max_seq_len)).astype(np.int32)
        data = model.split_micro_batches(ids, np.roll(ids, -1, axis=1),
                                         list(counts))
        model.train_step(data)
        return model

    def test_uneven_stages_and_malleus_counts(self):
        """2 pipes x 2 stages with UNEVEN per-stage layer counts [1, 2]
        and uneven micro-batch apportionment [3, 1]: the executed p2p
        log equals the 1F1B projection per (pipe, stage)."""
        model = self._model([[1, 2], [1, 2]])
        self._step(model, [3, 1])
        assert model.runtime.p2p_log, "tap recorded nothing"
        _assert_tap_matches(model, [3, 1])

    def test_tap_resets_and_tracks_reapportionment(self):
        """A second step with a different apportionment must match its
        OWN projection — the tap resets per train_step."""
        model = self._model([[1, 2], [1, 2]])
        self._step(model, [3, 1])
        self._step(model, [2, 2], seed=1)
        _assert_tap_matches(model, [2, 2])

    def test_mid_run_dp_resize_to_one_pipe(self):
        """The mid-run dp resize: the surviving single pipe absorbs the
        whole batch, and its executed order still matches the
        projection (the hot-switch path's post-resize invariant)."""
        model = self._model([[1, 2]], seed=5)
        self._step(model, [4])
        _assert_tap_matches(model, [4])

    def test_projection_covers_gpipe_too(self):
        """Projection sanity without a runtime: every send has exactly
        one matching recv on the peer stage, for both schedules."""
        for gen in (generate_pipedream_flush_schedule,
                    generate_gpipe_schedule):
            ev = p2p_events(gen(4, 6))
            sends = [(s, m, k, peer) for s, evs in enumerate(ev)
                     for (d, k, m, peer) in evs if d == "send"]
            recvs = [(peer, m, k, s) for s, evs in enumerate(ev)
                     for (d, k, m, peer) in evs if d == "recv"]
            assert sorted(sends) == sorted(recvs)
