"""Op parity tests vs torch/numpy oracles.

Mirrors the reference's test pattern (``tests/test_ops.py:1-60``): build a
small graph, compute forward + backward, ``np.allclose`` against torch.
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as F

import hetu_tpu as ht
from hetu_tpu import ops

RTOL, ATOL = 1e-4, 1e-5


def _np(x):
    return x.numpy() if hasattr(x, "numpy") else np.asarray(x)


class TestElementwise:
    @pytest.mark.parametrize("op,top", [
        (ops.add, torch.add), (ops.sub, torch.sub), (ops.mul, torch.mul),
        (ops.div, torch.div), (ops.maximum, torch.maximum),
        (ops.minimum, torch.minimum),
    ])
    def test_binary(self, op, top):
        rng = np.random.RandomState(0)
        a = rng.randn(4, 5).astype(np.float32)
        b = rng.rand(4, 5).astype(np.float32) + 0.5
        np.testing.assert_allclose(
            _np(op(a, b)), top(torch.tensor(a), torch.tensor(b)).numpy(),
            rtol=RTOL, atol=ATOL)

    def test_broadcast(self):
        a = np.random.randn(4, 5).astype(np.float32)
        b = np.random.randn(5).astype(np.float32)
        np.testing.assert_allclose(_np(ops.add(a, b)), a + b, rtol=RTOL)

    @pytest.mark.parametrize("op,top", [
        (ops.exp, torch.exp), (ops.tanh, torch.tanh),
        (ops.sigmoid, torch.sigmoid), (ops.relu, torch.relu),
        (ops.abs, torch.abs), (ops.neg, torch.neg),
    ])
    def test_unary(self, op, top):
        a = np.random.RandomState(1).randn(3, 7).astype(np.float32)
        np.testing.assert_allclose(
            _np(op(a)), top(torch.tensor(a)).numpy(), rtol=RTOL, atol=ATOL)

    def test_gelu(self):
        a = np.random.RandomState(2).randn(3, 7).astype(np.float32)
        np.testing.assert_allclose(
            _np(ops.gelu(a)), F.gelu(torch.tensor(a), approximate="tanh").numpy(),
            rtol=1e-3, atol=1e-4)

    def test_silu_swiglu(self):
        a = np.random.RandomState(3).randn(2, 8).astype(np.float32)
        np.testing.assert_allclose(
            _np(ops.silu(a)), F.silu(torch.tensor(a)).numpy(), rtol=RTOL,
            atol=ATOL)
        x1, x2 = np.split(a, 2, axis=-1)
        np.testing.assert_allclose(
            _np(ops.swiglu(a)), F.silu(torch.tensor(x1)).numpy() * x2,
            rtol=RTOL, atol=ATOL)


class TestMatmul:
    def test_matmul_variants(self):
        rng = np.random.RandomState(0)
        a = rng.randn(4, 6).astype(np.float32)
        b = rng.randn(6, 3).astype(np.float32)
        np.testing.assert_allclose(_np(ops.matmul(a, b)), a @ b, rtol=RTOL,
                                   atol=1e-4)
        np.testing.assert_allclose(_np(ops.matmul(a.T, b, trans_a=True)),
                                   a @ b, rtol=RTOL, atol=1e-4)
        np.testing.assert_allclose(_np(ops.matmul(a, b.T, trans_b=True)),
                                   a @ b, rtol=RTOL, atol=1e-4)

    def test_linear(self):
        rng = np.random.RandomState(0)
        x = rng.randn(5, 8).astype(np.float32)
        w = rng.randn(3, 8).astype(np.float32)
        b = rng.randn(3).astype(np.float32)
        np.testing.assert_allclose(_np(ops.linear(x, w, b)), x @ w.T + b,
                                   rtol=RTOL, atol=1e-4)

    def test_batch_matmul(self):
        rng = np.random.RandomState(0)
        a = rng.randn(2, 4, 6).astype(np.float32)
        b = rng.randn(2, 6, 3).astype(np.float32)
        np.testing.assert_allclose(_np(ops.matmul(a, b)), a @ b, rtol=RTOL,
                                   atol=1e-4)

    def test_einsum(self):
        rng = np.random.RandomState(0)
        a = rng.randn(2, 3, 4).astype(np.float32)
        b = rng.randn(2, 4, 5).astype(np.float32)
        np.testing.assert_allclose(
            _np(ops.einsum("bij,bjk->bik", a, b)),
            np.einsum("bij,bjk->bik", a, b), rtol=RTOL, atol=1e-4)


class TestNorms:
    def test_layer_norm(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 16).astype(np.float32)
        w = rng.rand(16).astype(np.float32)
        b = rng.randn(16).astype(np.float32)
        ref = F.layer_norm(torch.tensor(x), (16,), torch.tensor(w),
                           torch.tensor(b)).numpy()
        np.testing.assert_allclose(_np(ops.layer_norm(x, w, b)), ref,
                                   rtol=1e-3, atol=1e-4)

    def test_rms_norm(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 16).astype(np.float32)
        w = rng.rand(16).astype(np.float32)
        ref = F.rms_norm(torch.tensor(x), (16,), torch.tensor(w),
                         eps=1e-6).numpy()
        np.testing.assert_allclose(_np(ops.rms_norm(x, w)), ref, rtol=1e-3,
                                   atol=1e-4)

    def test_softmax(self):
        x = np.random.RandomState(0).randn(3, 9).astype(np.float32)
        np.testing.assert_allclose(
            _np(ops.softmax(x)), F.softmax(torch.tensor(x), -1).numpy(),
            rtol=RTOL, atol=ATOL)


class TestLosses:
    def test_softmax_ce_sparse(self):
        rng = np.random.RandomState(0)
        logits = rng.randn(6, 10).astype(np.float32)
        target = rng.randint(0, 10, (6,))
        ref = F.cross_entropy(torch.tensor(logits),
                              torch.tensor(target)).numpy()
        np.testing.assert_allclose(
            _np(ops.softmax_cross_entropy(logits, target)), ref, rtol=1e-4,
            atol=1e-5)

    def test_softmax_ce_ignore_index(self):
        rng = np.random.RandomState(0)
        logits = rng.randn(6, 10).astype(np.float32)
        target = rng.randint(0, 10, (6,))
        target[2] = -1
        ref = F.cross_entropy(torch.tensor(logits), torch.tensor(target),
                              ignore_index=-1).numpy()
        np.testing.assert_allclose(
            _np(ops.softmax_cross_entropy(logits, target, ignore_index=-1)),
            ref, rtol=1e-4, atol=1e-5)

    def test_mse_bce(self):
        rng = np.random.RandomState(0)
        p = rng.rand(5, 3).astype(np.float32)
        t = rng.rand(5, 3).astype(np.float32)
        np.testing.assert_allclose(
            _np(ops.mse_loss(p, t)),
            F.mse_loss(torch.tensor(p), torch.tensor(t)).numpy(), rtol=RTOL)
        np.testing.assert_allclose(
            _np(ops.binary_cross_entropy(p, t)),
            F.binary_cross_entropy(torch.tensor(p), torch.tensor(t)).numpy(),
            rtol=1e-3, atol=1e-4)

    def test_nll(self):
        rng = np.random.RandomState(0)
        logits = rng.randn(6, 10).astype(np.float32)
        lp = _np(ops.log_softmax(logits))
        target = rng.randint(0, 10, (6,))
        ref = F.nll_loss(torch.tensor(lp), torch.tensor(target)).numpy()
        np.testing.assert_allclose(_np(ops.nll_loss(lp, target)), ref,
                                   rtol=RTOL, atol=ATOL)


class TestConvPool:
    def test_conv2d(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        w = rng.randn(4, 3, 3, 3).astype(np.float32)
        b = rng.randn(4).astype(np.float32)
        ref = F.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                       stride=1, padding=1).numpy()
        np.testing.assert_allclose(_np(ops.conv2d(x, w, b, 1, 1)), ref,
                                   rtol=1e-3, atol=1e-3)

    def test_max_pool(self):
        x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
        ref = F.max_pool2d(torch.tensor(x), 2, 2).numpy()
        np.testing.assert_allclose(_np(ops.max_pool(x, 2, 2)), ref, rtol=RTOL)

    def test_avg_pool(self):
        x = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
        ref = F.avg_pool2d(torch.tensor(x), 2, 2).numpy()
        np.testing.assert_allclose(_np(ops.avg_pool(x, 2, 2)), ref, rtol=RTOL,
                                   atol=ATOL)


class TestShapes:
    def test_reshape_transpose_concat_split(self):
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        np.testing.assert_array_equal(_np(ops.reshape(x, (6, 4))),
                                      x.reshape(6, 4))
        np.testing.assert_array_equal(_np(ops.transpose(x, (1, 0, 2))),
                                      x.transpose(1, 0, 2))
        np.testing.assert_array_equal(_np(ops.concat([x, x], axis=1)),
                                      np.concatenate([x, x], 1))
        parts = ops.split(x, 2, axis=2)
        np.testing.assert_array_equal(_np(parts[0]), x[:, :, :2])
        np.testing.assert_array_equal(_np(parts[1]), x[:, :, 2:])

    def test_embedding(self):
        rng = np.random.RandomState(0)
        table = rng.randn(10, 4).astype(np.float32)
        ids = np.array([[1, 3], [7, 0]])
        np.testing.assert_array_equal(_np(ops.embedding_lookup(table, ids)),
                                      table[ids])

    def test_as_strided_vs_torch(self):
        import torch
        x = np.arange(24, dtype=np.float32)
        # overlapping sliding windows: shape (5, 4), stride (2, 1), offset 3
        want = torch.as_strided(torch.from_numpy(x), (5, 4), (2, 1), 3)
        got = _np(ops.as_strided(x.reshape(4, 6), (5, 4), (2, 1),
                                 storage_offset=3))
        np.testing.assert_array_equal(got, want.numpy())

    def test_triu_pad(self):
        x = np.ones((4, 4), np.float32)
        np.testing.assert_array_equal(_np(ops.triu(x)), np.triu(x))
        np.testing.assert_array_equal(
            _np(ops.pad(x, [(1, 1), (0, 0)])),
            np.pad(x, [(1, 1), (0, 0)]))


class TestAttention:
    def test_sdpa_vs_torch(self):
        rng = np.random.RandomState(0)
        b, s, h, d = 2, 16, 4, 8
        q = rng.randn(b, s, h, d).astype(np.float32)
        k = rng.randn(b, s, h, d).astype(np.float32)
        v = rng.randn(b, s, h, d).astype(np.float32)
        # torch expects [b, h, s, d]
        tq, tk, tv = (torch.tensor(x.transpose(0, 2, 1, 3))
                      for x in (q, k, v))
        ref = F.scaled_dot_product_attention(tq, tk, tv, is_causal=True)
        ref = ref.numpy().transpose(0, 2, 1, 3)
        out = _np(ops.attention(q, k, v, causal=True, use_flash=False))
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)

    def test_rotary(self):
        rng = np.random.RandomState(0)
        s, h, d = 8, 2, 16
        x = rng.randn(1, s, h, d).astype(np.float32)
        inv = 1.0 / (10000 ** (np.arange(0, d, 2) / d))
        ang = np.outer(np.arange(s), inv)
        cos = np.cos(np.concatenate([ang, ang], -1))[None, :, None, :]
        sin = np.sin(np.concatenate([ang, ang], -1))[None, :, None, :]
        out = _np(ops.rotary_embed(x, cos.astype(np.float32),
                                   sin.astype(np.float32)))
        # oracle: rotate_half convention (HF/llama)
        x1, x2 = x[..., :d // 2], x[..., d // 2:]
        rot = np.concatenate([-x2, x1], -1)
        np.testing.assert_allclose(out, x * cos + rot * sin, rtol=1e-4,
                                   atol=1e-5)


class TestGradients:
    def test_matmul_grad_vs_torch(self):
        rng = np.random.RandomState(0)
        a_np = rng.randn(4, 6).astype(np.float32)
        b_np = rng.randn(6, 3).astype(np.float32)
        with ht.graph("define_and_run", create_new=True) as g:
            a = ht.parameter(a_np, name="a")
            b = ht.parameter(b_np, name="b")
            loss = ops.reduce_sum(ops.mul(ops.matmul(a, b), ops.matmul(a, b)))
            grads = ht.gradients(loss, [a, b])
            ga, gb = g.run([grads[0], grads[1]])
        ta = torch.tensor(a_np, requires_grad=True)
        tb = torch.tensor(b_np, requires_grad=True)
        tl = ((ta @ tb) ** 2).sum()
        tl.backward()
        np.testing.assert_allclose(np.asarray(ga), ta.grad.numpy(), rtol=1e-3,
                                   atol=1e-3)
        np.testing.assert_allclose(np.asarray(gb), tb.grad.numpy(), rtol=1e-3,
                                   atol=1e-3)
