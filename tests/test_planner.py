"""Auto-parallel planner tests: native DP core vs Python fallback, the
Galvatron-style search engine, and the v1-style searching strategies."""
import numpy as np
import pytest

from hetu_tpu.csrc.build import load_dp_core
from hetu_tpu.planner import (ChipSpec, ClusterSpec, FlexFlowSearching,
                              GPipeSearching, LayerSpec, OptCNNSearching,
                              PipeDreamSearching, PipeOptSearching,
                              SearchEngine, Strategy,
                              solve_layer_strategies,
                              solve_pipeline_partition,
                              transformer_layer_spec)
from hetu_tpu.nn.parallel import config2ds


def _cluster(chips=8, hbm=95e9):
    return ClusterSpec(chip=ChipSpec(hbm_bytes=hbm), num_chips=chips)


class TestNativeCore:
    def test_native_library_builds(self):
        lib = load_dp_core()
        assert lib is not None, "g++ is available in this image; the " \
            "native DP core must build"

    def test_strategy_solver_native_matches_python(self):
        rng = np.random.RandomState(0)
        for _ in range(5):
            L, S, M = 6, 4, 16
            mem = rng.randint(0, 5, (L, S)).astype(np.int32)
            intra = rng.rand(L, S)
            inter = rng.rand(L, S, S) * 0.1
            cn, rn = solve_layer_strategies(mem, intra, inter, M,
                                            use_native=True)
            cp, rp = solve_layer_strategies(mem, intra, inter, M,
                                            use_native=False)
            assert np.isclose(cn, cp), (cn, cp)
            assert rn == rp

    def test_strategy_solver_respects_memory(self):
        # two strategies: fast-but-fat vs slow-but-lean
        L = 4
        mem = np.array([[4, 1]] * L, np.int32)
        intra = np.array([[1.0, 3.0]] * L)
        inter = np.zeros((L, 2, 2))
        # generous budget -> all fast
        c, r = solve_layer_strategies(mem, intra, inter, max_mem=17)
        assert r == [0] * L and np.isclose(c, 4.0)
        # tight budget -> forced lean
        c, r = solve_layer_strategies(mem, intra, inter, max_mem=5)
        assert r == [1] * L and np.isclose(c, 12.0)
        # infeasible
        c, r = solve_layer_strategies(mem, intra, inter, max_mem=2)
        assert r is None and np.isinf(c)

    def test_strategy_solver_transition_cost(self):
        # strategy switch costs 10 -> stick to one strategy even if the
        # per-layer optimum alternates
        L = 4
        mem = np.zeros((L, 2), np.int32)
        intra = np.array([[1.0, 1.1], [1.1, 1.0]] * 2)
        inter = np.zeros((L, 2, 2))
        for i in range(1, L):
            inter[i] = np.array([[0.0, 10.0], [10.0, 0.0]])
        _, r = solve_layer_strategies(mem, intra, inter, max_mem=1)
        assert len(set(r)) == 1  # no switching

    def test_pipeline_partition_native_matches_python(self):
        rng = np.random.RandomState(1)
        for _ in range(5):
            costs = rng.rand(12)
            comm = rng.rand(12) * 0.1
            bn, sn = solve_pipeline_partition(costs, 4, comm,
                                              use_native=True)
            bp, sp_ = solve_pipeline_partition(costs, 4, comm,
                                               use_native=False)
            assert np.isclose(bn, bp), (bn, bp)
            assert sn == sp_

    def test_pipeline_partition_balances(self):
        costs = [1.0] * 8
        bottleneck, stages = solve_pipeline_partition(costs, 4)
        assert [len(s) for s in stages] == [2, 2, 2, 2]
        assert np.isclose(bottleneck, 2.0)
        # uneven: one heavy layer gets isolated
        costs = [1.0, 1.0, 1.0, 10.0, 1.0, 1.0]
        _, stages = solve_pipeline_partition(costs, 3)
        heavy_stage = [s for s in stages if 3 in s][0]
        assert heavy_stage == [3]

    def test_pipeline_partition_covers_all_layers(self):
        _, stages = solve_pipeline_partition([1.0] * 7, 3)
        flat = [i for s in stages for i in s]
        assert flat == list(range(7))


def _gpt_layers(n=12, batch=8, seq=1024, hidden=1024):
    return [transformer_layer_spec(batch, seq, hidden, 4 * hidden,
                                   name=f"blocks{i}") for i in range(n)]


class TestSearchEngine:
    def test_finds_feasible_plan(self):
        eng = SearchEngine(_cluster(), _gpt_layers(), global_batch=64,
                           micro_batch=8)
        plan = eng.search()
        assert np.isfinite(plan.time) and plan.time > 0
        assert len(plan.layer_strategies) == 12
        assert sum(len(s) for s in plan.stages) == 12
        for st in plan.layer_strategies:
            assert st.dp * st.tp == 8 // plan.pp

    def test_tight_memory_forces_memory_savers(self):
        """On a tiny-HBM chip the plan must reach for recompute/zero/pp."""
        small = _cluster(hbm=3e9)
        eng = SearchEngine(small, _gpt_layers(hidden=2048), global_batch=64,
                           micro_batch=8)
        plan = eng.search()
        assert any(st.recompute or st.zero > 0
                   for st in plan.layer_strategies) or plan.pp > 1

    def test_infeasible_raises(self):
        nano = _cluster(hbm=1e6)  # 1 MB HBM: nothing fits
        eng = SearchEngine(nano, _gpt_layers(), global_batch=64,
                           micro_batch=8)
        with pytest.raises(RuntimeError, match="no feasible plan"):
            eng.search()

    def test_memory_cap_fed_by_analysis_backed_model(self):
        """ISSUE 8: the planner's HBM budget check runs on the numbers
        the static peak-HBM pass validated — a MemoryCalibration from
        ``calibrate_layer_memory`` (ratio of ``analysis.predict_memory``
        over the closed form on a lowered single-layer train-step
        probe) scales every ``layer_memory`` byte the DP solver sees."""
        from hetu_tpu.planner import (MemoryCalibration, layer_memory,
                                      calibrate_layer_memory)
        cal = calibrate_layer_memory()
        # the calibration really comes from the static pass: both sides
        # measured, scale is their ratio
        assert cal.static_bytes > 0 and cal.model_bytes > 0
        assert cal.scale == pytest.approx(
            cal.static_bytes / cal.model_bytes)
        spec = transformer_layer_spec(64, 1024, 1024, 4096, 2)
        base = layer_memory(spec, Strategy(), _cluster())
        got = layer_memory(spec, Strategy(), _cluster(), calibration=cal)
        assert got == pytest.approx(base * cal.scale)
        # the engine threads it into the budget check it hands the DP
        eng = SearchEngine(_cluster(), _gpt_layers(), global_batch=64,
                           micro_batch=8, memory_calibration=cal)
        assert eng.memory_calibration is cal
        plan = eng.search()
        assert np.isfinite(plan.time)

    def test_solver_rejects_plan_exceeding_static_peak(self):
        """ISSUE 8: a plan whose ANALYSIS-PREDICTED peak exceeds the
        chip HBM budget must be rejected even when the closed-form
        heuristic would have accepted it — the cap is enforced on the
        calibrated numbers."""
        from hetu_tpu.planner import MemoryCalibration
        cluster = _cluster(hbm=30e9)
        layers = _gpt_layers(hidden=2048)
        # uncalibrated closed form: fits comfortably
        eng = SearchEngine(cluster, layers, global_batch=64,
                           micro_batch=8, allow_recompute=False,
                           allow_zero=False)
        eng.search()
        # static pass says every layout needs 100x what the heuristic
        # thought: the same search must now reject every plan
        bloat = MemoryCalibration(scale=100.0, static_bytes=1,
                                  model_bytes=1.0)
        eng2 = SearchEngine(cluster, layers, global_batch=64,
                            micro_batch=8, allow_recompute=False,
                            allow_zero=False, memory_calibration=bloat)
        with pytest.raises(RuntimeError, match="no feasible plan"):
            eng2.search()

    def test_layer_time_fed_by_analysis_backed_model(self):
        """ISSUE 10: the planner's step-time scoring runs on the
        numbers the static cost pass validated — a TimeCalibration
        from ``calibrate_layer_time`` (ratio of
        ``analysis.predict_cost`` over the closed form on a lowered
        single-layer train-step probe) scales every ``layer_time``
        roofline the DP solver ranks with, exactly as
        ``calibrate_layer_memory`` does for bytes."""
        from hetu_tpu.planner import (TimeCalibration, calibrate_layer_time,
                                      layer_time)
        cal = calibrate_layer_time()
        # the calibration really comes from the static pass: counted
        # probe FLOPs are real (close to the closed form's 3x-fwd
        # estimate), and the scale is the measured ratio
        assert cal.static_s > 0 and cal.model_s > 0
        assert cal.scale == pytest.approx(cal.static_s / cal.model_s)
        assert cal.static_flops == pytest.approx(cal.model_flops,
                                                 rel=0.5)
        spec = transformer_layer_spec(64, 1024, 1024, 4096, 2)
        base = layer_time(spec, Strategy(), _cluster(),
                          include_grad_sync=False)
        got = layer_time(spec, Strategy(), _cluster(),
                         include_grad_sync=False, calibration=cal)
        assert got == pytest.approx(base * cal.scale)
        # comm terms are added AFTER the scaled roofline (the probe is
        # single-device: it cannot calibrate collectives)
        st = Strategy(dp=8)
        with_sync = layer_time(spec, st, _cluster(), calibration=cal)
        no_sync = layer_time(spec, st, _cluster(),
                             include_grad_sync=False, calibration=cal)
        from hetu_tpu.planner import grad_sync_time
        assert with_sync - no_sync == pytest.approx(
            grad_sync_time(spec, st, _cluster()))
        # the engine threads it into every candidate it scores
        eng = SearchEngine(_cluster(), _gpt_layers(), global_batch=64,
                           micro_batch=8,
                           time_calibration=TimeCalibration(scale=3.0))
        l0 = self_time = eng._layer_time(_gpt_layers()[0], Strategy())
        eng_plain = SearchEngine(_cluster(), _gpt_layers(),
                                 global_batch=64, micro_batch=8)
        assert self_time == pytest.approx(
            3.0 * eng_plain._layer_time(_gpt_layers()[0], Strategy()))
        assert np.isfinite(l0)

    def test_planner_beats_every_hand_written_gate_family_plan(self):
        """ISSUE 10 acceptance: the searched plan must beat (or tie)
        every hand-written gate-family layout on predicted step time,
        scored with the SAME calibrated model — the search covers a
        superset of the hand layouts, so losing to one would mean the
        scorer and the search disagree."""
        from hetu_tpu.models.gpt import GPTConfig
        from hetu_tpu.planner import hand_plan_times, plan_for_gpt
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=4,
                        num_heads=4, max_seq_len=64, dtype="bfloat16")
        # calibration=None keeps the test fast (no probe lowering);
        # both sides then score with the identical uncalibrated model,
        # which is the property under test
        plan = plan_for_gpt(cfg, global_batch=16, seq=64, n_chips=8,
                            memory_calibration=None,
                            time_calibration=None)
        hand = hand_plan_times(cfg, global_batch=16, seq=64, n_chips=8,
                               time_calibration=None)
        assert set(hand) == {"dp8_zero2_flat", "dp2_tp4_sp", "pp4_dp2",
                             "pp2_dp2_tp2"}
        for name, t in hand.items():
            assert plan.time <= t * (1 + 1e-9), (name, plan.time, t)

    def test_measured_links_feed_the_shared_alpha_beta_formulas(self):
        """ISSUE 10 satellite: Calibration.to_cluster_spec folds the
        measured per-link (alpha, beta) fits into the SAME formulas
        the solver and the analysis linter price collectives with."""
        from hetu_tpu.planner import (Calibration, all_gather_time,
                                      all_reduce_time, collective_time)
        cal = Calibration(matmul_flops={512: 50e12}, hbm_bw=500e9,
                          collectives={"all_reduce": (2e-6, 1e-9),
                                       "p2p": (1e-6, 5e-10)},
                          device_kind="v5p", platform="tpu")
        cluster = cal.to_cluster_spec(num_chips=4)
        assert cluster.link_alpha_beta["all_reduce"] == (2e-6, 1e-9)
        want = 2e-6 + 1e-9 * 1e6
        assert all_reduce_time(1e6, 4, cluster) == pytest.approx(want)
        assert collective_time("all_reduce", 1e6, 4, cluster) == \
            pytest.approx(want)
        # kinds without a fit keep the ring model
        ring = all_gather_time(1e6, 4, ClusterSpec(chip=cluster.chip,
                                                   num_chips=4))
        assert all_gather_time(1e6, 4, cluster) == pytest.approx(ring)
        # the chip side still folds the measured roofline numbers
        assert cluster.chip.hbm_bw == 500e9

    def test_plan_for_gpt_closes_the_loop(self):
        """plan_for_gpt: GPTConfig -> layer chain -> searched plan with a
        micro-batch sweep (the bench.py / train_gpt --auto-parallel entry,
        reference hybrid_parallel_config.py:13)."""
        from hetu_tpu.models.gpt import GPTConfig
        from hetu_tpu.planner import plan_for_gpt, plan_summary
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=1024, sp=False,
                        dtype="bfloat16")
        # single chip: the only legal layout
        p1 = plan_for_gpt(cfg, global_batch=32, seq=1024, n_chips=1)
        s1 = plan_summary(p1)
        assert (s1["pp"], s1["dp"], s1["tp"]) == (1, 1, 1)
        assert s1["micro_batch"] is not None
        assert 32 % s1["micro_batch"] == 0
        # 8 chips: plan must use the whole grid
        p8 = plan_for_gpt(cfg, global_batch=64, seq=1024, n_chips=8)
        s8 = plan_summary(p8)
        assert s8["pp"] * s8["dp"] * s8["tp"] == 8
        for key in ("zero", "recompute_layers", "est_step_time_ms",
                    "num_microbatches"):
            assert key in s8
        # calibration folds into the chip spec without breaking the search
        from hetu_tpu.planner import Calibration
        cal = Calibration(matmul_flops={1024: 100e12}, hbm_bw=700e9,
                          device_kind="v5 lite", platform="tpu")
        pc = plan_summary(plan_for_gpt(cfg, global_batch=32, seq=1024,
                                       n_chips=1, calibration=cal))
        assert (pc["pp"], pc["dp"], pc["tp"]) == (1, 1, 1)

    def test_ds_parallel_config_roundtrip(self):
        eng = SearchEngine(_cluster(), _gpt_layers(n=8), global_batch=64,
                           micro_batch=8)
        plan = eng.search()
        cfg = plan.to_ds_parallel_config()
        assert len(cfg["layers"]) == 8

        def _leaf_entries(d):
            if "type" in d:
                yield d
                return
            for v in d.values():
                if isinstance(v, dict):
                    yield from _leaf_entries(v)

        # every emitted per-weight entry parses through config2ds, with
        # the generator schema's shard dims (col-parallel dim 1,
        # row-parallel dim 0)
        for name, entry in cfg["layers"].items():
            leaves = list(_leaf_entries(entry))
            assert len(leaves) == 6  # ln1, qkv, dense, ln2, fc1, fc2
            for leaf in leaves:
                ds_union, dgs = config2ds(leaf)
                ds = ds_union.get(0)
                assert ds.device_num == len(dgs[0])
            assert entry["attn"]["qkv"]["split"].keys() <= {"1"}
            assert entry["attn"]["dense"]["split"].keys() <= {"0"}


class TestV1Strategies:
    def test_optcnn_prefers_tp_free_layers_consistent(self):
        layers = _gpt_layers(n=6, hidden=512)
        r = OptCNNSearching(layers, _cluster()).searching()
        assert len(r.strategies) == 6
        assert np.isfinite(r.cost)
        # all-devices factorization respected
        for st in r.strategies:
            assert st.dp * st.tp == 8

    def test_flexflow_beats_or_ties_worst_random(self):
        layers = _gpt_layers(n=6, hidden=512)
        ff = FlexFlowSearching(layers, _cluster(), round_budget=300, seed=3)
        r = ff.searching()
        # the MCMC result can't be worse than every candidate: compare
        # against the single worst uniform assignment
        worst = max(ff.simulate([st] * 6)
                    for st in ff._device_factor_candidates())
        assert r.cost <= worst + 1e-12

    def test_flexflow_close_to_optcnn_optimum(self):
        layers = _gpt_layers(n=6, hidden=512)
        opt = OptCNNSearching(layers, _cluster()).searching()
        ff = FlexFlowSearching(layers, _cluster(), round_budget=800,
                               seed=0).searching()
        assert ff.cost <= opt.cost * 1.5 + 1e-9

    def test_gpipe_contiguous_stages(self):
        layers = _gpt_layers(n=8, hidden=512)
        r = GPipeSearching(layers, _cluster(), num_stages=4).searching()
        assert r.stages is not None and len(r.stages) == 4
        flat = [i for s in r.stages for i in s]
        assert flat == list(range(8))

    def test_pipedream_replicates_heavy_stages(self):
        # one very heavy layer among light ones: PipeDream should give the
        # heavy layer('s stage) more devices
        layers = [transformer_layer_spec(8, 256, 256, 1024)
                  for _ in range(5)]
        layers.insert(2, transformer_layer_spec(8, 256, 1024, 8192))
        r = PipeDreamSearching(layers, _cluster(chips=4)).searching()
        repl = r.meta["replication"]
        heavy_stage = [k for k, sg in enumerate(r.stages) if 2 in sg][0]
        assert repl[heavy_stage] == max(repl)

    def test_pipeopt_picks_best_stage_count(self):
        layers = _gpt_layers(n=8, hidden=512)
        r = PipeOptSearching(layers, _cluster(),
                             stage_options=[1, 2, 4]).searching()
        per_stage_costs = [GPipeSearching(layers, _cluster(), p).searching().cost
                           for p in (1, 2, 4)]
        assert r.meta["num_stages"] in (1, 2, 4)
        assert np.isfinite(r.cost)
        assert r.cost <= min(per_stage_costs) + 1e-12
