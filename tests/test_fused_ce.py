"""Fused LM-head + CE (logits never materialized) tests.

Oracle: the unfused lm_head matmul -> softmax CE path (itself validated
against torch in test_ops.py).  The fused op is the round-3
scratch/purejax.py "fusedce" variant landed as a real op.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import ops, optim
from hetu_tpu.ops.fused_ce import fused_linear_cross_entropy

N, H, V = 64, 32, 97


def _data(seed=0, ignore_frac=0.0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(N, H), jnp.float32)
    w = jnp.asarray(rng.randn(V, H) * 0.05, jnp.float32)
    lbl = rng.randint(0, V, N)
    if ignore_frac:
        lbl[rng.rand(N) < ignore_frac] = -100
    return x, w, jnp.asarray(lbl, jnp.int32)


def _oracle(x, w, lbl, reduction="mean"):
    logits = (x @ w.T).astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    safe = jnp.clip(lbl, 0, V - 1)
    picked = jnp.take_along_axis(logits, safe[:, None], 1)[:, 0]
    valid = lbl != -100
    losses = jnp.where(valid, lse - picked, 0.0)
    if reduction == "mean":
        return jnp.sum(losses) / jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(losses)


class TestFusedLinearCE:
    @pytest.mark.parametrize("chunks", [1, 4, 8])
    def test_forward_matches_oracle(self, chunks):
        x, w, lbl = _data()
        got = fused_linear_cross_entropy(x, w, lbl, -100, chunks, "mean")
        want = _oracle(x, w, lbl)
        np.testing.assert_allclose(float(got), float(want), rtol=1e-5)

    def test_ignore_index(self):
        x, w, lbl = _data(ignore_frac=0.3)
        got = fused_linear_cross_entropy(x, w, lbl, -100, 4, "mean")
        want = _oracle(x, w, lbl)
        np.testing.assert_allclose(float(got), float(want), rtol=1e-5)

    @pytest.mark.parametrize("reduction", ["mean", "sum"])
    def test_grads_match_oracle(self, reduction):
        x, w, lbl = _data(ignore_frac=0.2)
        g1 = jax.grad(lambda x, w: fused_linear_cross_entropy(
            x, w, lbl, -100, 4, reduction), argnums=(0, 1))(x, w)
        g2 = jax.grad(lambda x, w: _oracle(x, w, lbl, reduction),
                      argnums=(0, 1))(x, w)
        for name, a, b in zip("xw", g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=f"d{name}")

    def test_non_divisible_chunks_fall_back(self):
        x, w, lbl = _data()
        # 7 does not divide 64 -> falls back to nearest divisor
        got = fused_linear_cross_entropy(x, w, lbl, -100, 7, "mean")
        want = _oracle(x, w, lbl)
        np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


@pytest.mark.slow
class TestModelFusedCE:
    def test_gpt_fused_ce_matches_unfused(self, devices8):
        """fused_lm_ce=True trains on the same trajectory as the unfused
        vocab-parallel CE path (tp-sharded lm_head under the mesh)."""
        from hetu_tpu.graph import ctor
        from hetu_tpu.models import GPTConfig, GPTLMHeadModel

        def train(fused):
            ctor._seed_counter[0] = 4242
            mesh = ht.create_mesh({"dp": 2, "tp": 4})
            cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                            num_heads=4, max_seq_len=32, sp=False,
                            fused_lm_ce=fused)
            with ht.graph("define_and_run", create_new=True,
                          mesh=mesh) as g:
                ids = ht.parallel_placeholder("int32", (4, 32),
                                              pspec=P("dp", None),
                                              name="ids")
                lbl = ht.parallel_placeholder("int32", (4, 32),
                                              pspec=P("dp", None),
                                              name="lbl")
                m = GPTLMHeadModel(cfg)
                loss = m(ids, lbl)
                op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
                rng = np.random.RandomState(0)
                I = rng.randint(0, 128, (4, 32)).astype(np.int32)
                L = np.roll(I, -1, 1)
                return [float(np.asarray(
                    g.run(loss, [loss, op], {ids: I, lbl: L})[0]))
                    for _ in range(4)]

        unfused = train(False)
        fused = train(True)
        np.testing.assert_allclose(unfused, fused, rtol=3e-4, atol=1e-5)

    def test_tied_embeddings_fused(self):
        from hetu_tpu.graph import ctor
        from hetu_tpu.models import GPTConfig, GPTLMHeadModel
        ctor._seed_counter[0] = 7
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=4, max_seq_len=16, sp=False,
                        tie_embeddings=True, fused_lm_ce=True)
        with ht.graph("define_and_run", create_new=True) as g:
            ids = ht.placeholder("int32", (2, 16), name="ids")
            lbl = ht.placeholder("int32", (2, 16), name="lbl")
            m = GPTLMHeadModel(cfg)
            loss = m(ids, lbl)
            op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            I = np.random.RandomState(0).randint(0, 64, (2, 16))
            I = I.astype(np.int32)
            losses = [float(np.asarray(g.run(
                loss, [loss, op], {ids: I, lbl: np.roll(I, -1, 1)})[0]))
                for _ in range(4)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], losses
