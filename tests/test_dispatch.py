"""Hydraulis-style dispatcher tests: cost-model fit, MILP/greedy dispatch,
micro-batch balancing, packing matrices, strategy-pool generation."""
import numpy as np
import pytest

from hetu_tpu.data import Bucket
from hetu_tpu.planner import (ChipSpec, ClusterSpec, DispatchStrategy,
                              batching_strategy, dynamic_dispatch,
                              fit_cost_model, generate_strategy_pool,
                              max_seqlen_for, solve_micro_batches)


class TestCostModel:
    def test_fit_recovers_coefficients(self):
        a, b, c = 2e-6, 3e-3, 0.5
        s = np.arange(128, 4096, 128)
        t = a * s**2 + b * s + c
        fa, fb, fc = fit_cost_model(s, t)
        assert np.isclose(fa, a, rtol=1e-6)
        assert np.isclose(fb, b, rtol=1e-6)
        assert np.isclose(fc, c, rtol=1e-4)

    def test_fit_with_noise(self):
        rng = np.random.RandomState(0)
        s = np.arange(128, 4096, 64)
        t = 1e-6 * s**2 + 1e-3 * s + 0.1 + rng.randn(len(s)) * 1e-3
        fa, fb, fc = fit_cost_model(s, t)
        assert np.isclose(fa, 1e-6, rtol=0.05)

    def test_batch_time_includes_pipeline_slots(self):
        st = DispatchStrategy(pp=4, a=0.0, b=1.0, c=0.0)
        # 1F1B: steady-state sum/pp + (pp-1)/pp * longest
        assert np.isclose(st.batch_time([10, 20]), 30 / 4 + 3 * 20 / 4)

    def test_pp_gets_throughput_credit(self):
        """Equal-hardware pp=8 and tp=8 groups must have comparable
        estimated throughput (1F1B steady state), not a ~pp gap."""
        tp8 = DispatchStrategy(tp=8, pp=1, a=1e-6 / 8, b=1e-3 / 8)
        pp8 = DispatchStrategy(tp=1, pp=8, a=1e-6, b=1e-3)
        lens = [1024] * 64
        ratio = pp8.batch_time(lens) / tp8.batch_time(lens)
        assert ratio < 1.5, ratio  # near parity, not ~8x


def _two_tier_pool():
    """A big-memory slow group and a small-memory fast group."""
    return [
        DispatchStrategy(tp=8, pp=1, a=1e-6, b=1e-3, max_seqlen=8192),
        DispatchStrategy(tp=2, pp=1, a=4e-6, b=4e-3, max_seqlen=2048),
    ]


class TestDynamicDispatch:
    def test_long_sequences_respect_eligibility(self):
        pool = _two_tier_pool()
        lens = np.array([8000, 4000, 1000, 900, 800, 700])
        for use_ilp in (False, None):
            groups = dynamic_dispatch(pool, lens, use_ilp=use_ilp)
            # sequences > 2048 must be in group 0
            assert 0 in groups[0] and 1 in groups[0]
            assert sum(len(g) for g in groups) == len(lens)

    def test_balances_makespan(self):
        pool = [DispatchStrategy(b=1.0, max_seqlen=100),
                DispatchStrategy(b=1.0, max_seqlen=100)]
        lens = np.array([10, 10, 10, 10, 10, 10])
        groups = dynamic_dispatch(pool, lens, use_ilp=False)
        assert len(groups[0]) == len(groups[1]) == 3

    def test_milp_not_worse_than_greedy(self):
        pool = _two_tier_pool()
        rng = np.random.RandomState(1)
        lens = rng.randint(100, 2000, 24)

        def makespan(groups):
            return max(pool[j].batch_time([lens[i] for i in g])
                       for j, g in enumerate(groups))

        greedy = dynamic_dispatch(pool, lens, use_ilp=False)
        milp = dynamic_dispatch(pool, lens, use_ilp=True)
        assert makespan(milp) <= makespan(greedy) * 1.01

    def test_impossible_sequence_raises(self):
        pool = [DispatchStrategy(max_seqlen=100)]
        with pytest.raises(ValueError, match="exceeds"):
            dynamic_dispatch(pool, np.array([500]))


class TestMicroBatching:
    def test_balanced_split(self):
        st = DispatchStrategy(b=1.0)
        lens = [100, 100, 100, 100, 50, 50, 50, 50]
        mbs = solve_micro_batches(lens, st, 4)
        assert len(mbs) == 4
        got = sorted(i for mb in mbs for i in mb)
        assert got == list(range(8))
        loads = [sum(lens[i] for i in mb) for mb in mbs]
        assert max(loads) <= 200  # perfectly balanceable

    def test_empty_group(self):
        st = DispatchStrategy()
        assert solve_micro_batches([], st, 4) == [[], [], [], []]


class TestBatchingMatrix:
    def test_matrix_feeds_bucket(self):
        lens = [100, 100, 60, 50, 200]
        mat = batching_strategy(lens, max_seqlen=256, alignment=64)
        assert mat.shape[1] == 5
        np.testing.assert_array_equal(mat.sum(axis=0), np.ones(5))
        # aligned row loads within capacity
        aligned = [(l + 63) // 64 * 64 for l in lens]
        for r in range(mat.shape[0]):
            assert sum(aligned[i] for i in range(5) if mat[r, i]) <= 256
        # feed into Bucket.pack_data
        b = Bucket(pad_token=0, max_seqlen=256, alignment=64)
        for n in lens:
            b.add_data(np.full(n, 9), n)
        b.pack_data(mat)
        assert b.packed_batch_size == mat.shape[0]


class TestStrategyPool:
    def test_pool_generation(self):
        cluster = ClusterSpec(chip=ChipSpec(), num_chips=8)
        pool = generate_strategy_pool(cluster, hidden=4096, num_layers=32)
        assert pool, "pool must not be empty"
        for st in pool:
            assert st.max_seqlen > 0
            assert st.tp * st.pp <= 8

    def test_more_parallelism_longer_sequences(self):
        cluster = ClusterSpec(chip=ChipSpec(), num_chips=8)
        m1 = max_seqlen_for(1, 1, cluster, hidden=8192, num_layers=48)
        m8 = max_seqlen_for(8, 1, cluster, hidden=8192, num_layers=48)
        assert m8 > m1
        mpp = max_seqlen_for(1, 8, cluster, hidden=8192, num_layers=48)
        assert mpp > m1
        m_base = max_seqlen_for(1, 1, cluster, hidden=2048, num_layers=24)
        m_cp = max_seqlen_for(1, 1, cluster, hidden=2048, num_layers=24,
                              cp=4)
        assert m_base > 0
        assert m_cp > m_base  # CP shards activations -> longer sequences

    def test_max_seqlen_bound_survives_aligned_packing(self):
        """Any admitted length must pack into rows of max_seqlen."""
        cluster = ClusterSpec(chip=ChipSpec(), num_chips=8)
        ms = max_seqlen_for(2, 1, cluster, hidden=4096, num_layers=32)
        assert ms % 128 == 0
        mat = batching_strategy([ms], max_seqlen=ms, alignment=128)
        assert mat.shape == (1, 1)

    def test_profiled_coeff_rescaled_per_layout(self):
        cluster = ClusterSpec(chip=ChipSpec(), num_chips=8)
        pool = generate_strategy_pool(cluster, hidden=2048, num_layers=16,
                                      layouts=[(1, 1), (8, 1)],
                                      flops_coeff=(1e-6, 1e-3, 0.0))
        t1 = float(pool[0].seq_time(1024))
        t8 = float(pool[1].seq_time(1024))
        assert np.isclose(t1 / t8, 8.0)

    def test_cp_divides_seq_time(self):
        a = DispatchStrategy(a=1e-6, b=1e-3, cp=1)
        b = DispatchStrategy(a=1e-6, b=1e-3, cp=4)
        assert np.isclose(float(a.seq_time(2048)) / float(b.seq_time(2048)),
                          4.0)

    def test_micro_batch_arity_fixed(self):
        st = DispatchStrategy(b=1.0)
        out = solve_micro_batches([100, 100], st, 4)
        assert len(out) == 4
        assert sorted(i for mb in out for i in mb) == [0, 1]

    def test_end_to_end_dispatch_flow(self):
        """pool -> dispatch -> micro-batch -> pack (the per-iteration
        Hydraulis flow)."""
        cluster = ClusterSpec(chip=ChipSpec(), num_chips=16)
        pool = generate_strategy_pool(cluster, hidden=2048, num_layers=16)
        rng = np.random.RandomState(2)
        lens = rng.randint(128, 4096, 32)
        lens = np.minimum(lens, max(s.max_seqlen for s in pool))
        groups = dynamic_dispatch(pool, lens, use_ilp=False)
        assert sum(len(g) for g in groups) == 32
        for st, g in zip(pool, groups):
            if not g:
                continue
            mbs = solve_micro_batches([lens[i] for i in g], st, 2)
            for mb in mbs:
                if mb:
                    glens = [lens[g[i]] for i in mb]
                    mat = batching_strategy(glens, max_seqlen=max(
                        (int(l) + 127) // 128 * 128 for l in glens))
                    assert mat.sum() == len(glens)


class TestStaticDispatch:
    def test_ranges_cover_and_respect_limits(self):
        from hetu_tpu.planner import static_dispatch
        pool = [DispatchStrategy(tp=8, b=1e-3, max_seqlen=8192),
                DispatchStrategy(tp=2, b=4e-3, max_seqlen=2048)]
        hist = [(256, 100), (1024, 50), (4096, 10), (8192, 2)]
        ranges = static_dispatch(pool, hist)
        assert len(ranges) == 2
        # long sequences must land in the big-memory strategy's range
        lo0, hi0 = ranges[0]
        assert hi0 >= 8192
        # every histogram length falls in exactly one range
        for s, _ in hist:
            hits = [j for j, (lo, hi) in enumerate(ranges) if lo < s <= hi]
            assert len(hits) == 1, (s, ranges)

    def test_static_balances_load(self):
        from hetu_tpu.planner import static_dispatch
        pool = [DispatchStrategy(b=1.0, max_seqlen=10000),
                DispatchStrategy(b=1.0, max_seqlen=10000)]
        hist = [(100, 10), (200, 10), (300, 10), (400, 10)]
        ranges = static_dispatch(pool, hist)
        loads = []
        for lo, hi in ranges:
            loads.append(sum(s * c for s, c in hist if lo < s <= hi))
        assert max(loads) < sum(s * c for s, c in hist)  # actually split

    def test_impossible_length_raises(self):
        from hetu_tpu.planner import static_dispatch
        pool = [DispatchStrategy(max_seqlen=100)]
        with pytest.raises(ValueError, match="exceeds"):
            static_dispatch(pool, [(500, 1)])
