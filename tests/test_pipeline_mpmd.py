"""MPMD hetero pipeline: 1F1B schedule, unequal stages, tied embeddings.

Covers VERDICT round-1 items 2/3/7(部分)/8: PipeDream-Flush bounded
in-flight, hetero stage_layers actually executing, per-pipeline
micro-batch counts, shared-embedding grad handling.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from hetu_tpu.models.gpt import GPTConfig, llama_config
from hetu_tpu.models.gpt_mpmd import MPMDGPT
from hetu_tpu.parallel.pipeline_mpmd import MPMDAdam
from hetu_tpu.parallel.schedule import (generate_gpipe_schedule,
                                        generate_pipedream_flush_schedule,
                                        max_in_flight, validate_schedule)


# full-model training loops: excluded from the dev fast path
pytestmark = pytest.mark.slow


def _cfg(**kw):
    kw.setdefault("vocab_size", 96)
    kw.setdefault("hidden_size", 48)
    kw.setdefault("num_layers", 8)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_seq_len", 16)
    kw.setdefault("dtype", "float32")
    return llama_config(**kw)


def _data(cfg, batch, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (batch, cfg.max_seq_len)
                      ).astype(np.int32)
    labels = np.roll(ids, -1, axis=1)
    return ids, labels


class TestSchedules:
    def test_1f1b_in_flight_bounded_by_depth(self):
        for S, M in [(2, 4), (4, 8), (4, 32), (8, 8)]:
            sched = generate_pipedream_flush_schedule(S, M)
            validate_schedule(sched, M)
            for s, tasks in enumerate(sched):
                assert max_in_flight(tasks) == min(M, S - s), (S, M, s)

    def test_gpipe_in_flight_is_m(self):
        sched = generate_gpipe_schedule(4, 8)
        validate_schedule(sched, 8)
        assert all(max_in_flight(t) == 8 for t in sched)


class TestHeteroPipelineEquivalence:
    def test_pp4_hetero_stage_layers_matches_pp1(self, devices8):
        """pp4 with stage_layers [1,1,3,3] on 4x2-device submeshes matches
        the same model on one device (VERDICT item 2 Done criterion)."""
        cfg = _cfg()
        ids, labels = _data(cfg, batch=8)

        ref = MPMDGPT(cfg, stage_layers=[[8]], seed=3)
        meshes = [[Mesh(np.array(devices8[2 * s:2 * s + 2]).reshape(1, 2),
                        ("dp", "tp")) for s in range(4)]]
        het = MPMDGPT(cfg, stage_layers=[[1, 1, 3, 3]], meshes=meshes,
                      seed=3)

        opt_r = MPMDAdam(ref.runtime, lr=1e-2)
        opt_h = MPMDAdam(het.runtime, lr=1e-2)
        losses_r, losses_h = [], []
        for step in range(4):
            d_r = ref.split_micro_batches(ids, labels, [4])
            d_h = het.split_micro_batches(ids, labels, [4])
            lr_, gr, _ = ref.train_step(d_r)
            lh_, gh, _ = het.train_step(d_h)
            losses_r.append(float(lr_))
            losses_h.append(float(lh_))
            opt_r.apply(gr)
            opt_h.apply(gh)
        np.testing.assert_allclose(losses_r, losses_h, rtol=2e-4)
        assert losses_r[-1] < losses_r[0]

    def test_1f1b_stash_below_gpipe_at_m8(self, devices8):
        """Memory assertion: 1F1B in-flight activation peak < GPipe's
        (VERDICT item 2 Done criterion)."""
        cfg = _cfg(num_layers=4)
        ids, labels = _data(cfg, batch=8)
        meshes = [[Mesh(np.array(devices8[2 * s:2 * s + 2]).reshape(1, 2),
                        ("dp", "tp")) for s in range(4)]]
        res = {}
        for sched in ("1f1b", "gpipe"):
            model = MPMDGPT(cfg, stage_layers=[[1, 1, 1, 1]], meshes=meshes,
                            schedule=sched, seed=0)
            data = model.split_micro_batches(ids, labels, [8])
            loss, _, stats = model.train_step(data)
            res[sched] = (loss, stats)
        # same math regardless of schedule
        np.testing.assert_allclose(res["1f1b"][0], res["gpipe"][0],
                                   rtol=1e-5)
        # stage 0 stash: 1F1B holds at most S, GPipe holds M
        s1 = res["1f1b"][1]
        sg = res["gpipe"][1]
        assert max(s1.stash_peak) <= 4
        assert max(sg.stash_peak) == 8
        assert max(s1.stash_peak_bytes) < max(sg.stash_peak_bytes)

    def test_hetero_dp_unequal_micro_batches(self, devices8):
        """Two pipelines with micro-batch counts [3, 1] (Malleus
        apportionment) match the single-pipeline run on the same global
        batch."""
        cfg = _cfg(num_layers=4)
        ids, labels = _data(cfg, batch=8)

        ref = MPMDGPT(cfg, stage_layers=[[4]], seed=1)
        d = ref.split_micro_batches(ids, labels, [4])
        _, gr, _ = ref.train_step(d)

        meshes = [
            [Mesh(np.array(devices8[0:2]).reshape(1, 2), ("dp", "tp")),
             Mesh(np.array(devices8[2:4]).reshape(1, 2), ("dp", "tp"))],
            [Mesh(np.array(devices8[4:6]).reshape(1, 2), ("dp", "tp")),
             Mesh(np.array(devices8[6:8]).reshape(1, 2), ("dp", "tp"))],
        ]
        het = MPMDGPT(cfg, stage_layers=[[2, 2], [1, 3]], meshes=meshes,
                      seed=1)
        dh = het.split_micro_batches(ids, labels, [3, 1])
        _, gh, _ = het.train_step(dh)

        # wte grad (stage 0) must match the reference run
        g_ref = np.asarray(gr[0][0]["wte"])
        g_het = np.asarray(jax.device_get(gh[0][0]["wte"]))
        np.testing.assert_allclose(g_ref, g_het, rtol=5e-4, atol=1e-6)
        # layer grads live at different (pipe, stage) per layout but agree
        g_ref3 = np.asarray(gr[0][0]["layer3"]["qkv"])
        g_het3 = np.asarray(jax.device_get(gh[1][1]["layer3"]["qkv"]))
        np.testing.assert_allclose(g_ref3, g_het3, rtol=5e-4, atol=1e-6)


class TestGPT2ArchAndTying:
    def test_gpt2_architecture_trains(self, devices8):
        """Real GPT-2: gelu+bias, layernorm, learned positions, GQA,
        dropout — pipelined (VERDICT item 8)."""
        cfg = GPTConfig(vocab_size=96, hidden_size=48, num_layers=4,
                        num_heads=4, num_kv_heads=2, max_seq_len=16,
                        activation="gelu", norm="layernorm",
                        position="learned", dropout=0.1, dtype="float32")
        ids, labels = _data(cfg, batch=4)
        meshes = [[Mesh(np.array(devices8[4 * s:4 * s + 4]).reshape(2, 2),
                        ("dp", "tp")) for s in range(2)]]
        model = MPMDGPT(cfg, stage_layers=[[2, 2]], meshes=meshes, seed=0)
        opt = MPMDAdam(model.runtime, lr=1e-2)
        losses = []
        for step in range(6):
            data = model.split_micro_batches(ids, labels, [2])
            loss, grads, _ = model.train_step(
                data, rng=jax.random.PRNGKey(step))
            opt.apply(grads)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_tied_embeddings_match_single_stage(self):
        """Tied wte across first/last stage: grads summed across stages
        (reference shared-weight p2p, executable_graph.cc:2312) — pp2
        must equal pp1 exactly."""
        cfg = _cfg(num_layers=2, tie_embeddings=True)
        ids, labels = _data(cfg, batch=4)

        one = MPMDGPT(cfg, stage_layers=[[2]], seed=5)
        two = MPMDGPT(cfg, stage_layers=[[1, 1]], seed=5)
        d1 = one.split_micro_batches(ids, labels, [2])
        d2 = two.split_micro_batches(ids, labels, [2])
        l1, g1, _ = one.train_step(d1)
        l2, g2, _ = two.train_step(d2)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        # single stage: wte and wte_head entries carry the summed grad
        np.testing.assert_allclose(np.asarray(g1[0][0]["wte"]),
                                   np.asarray(g2[0][0]["wte"]),
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(np.asarray(g2[0][0]["wte"]),
                                   np.asarray(g2[0][1]["wte_head"]),
                                   rtol=1e-6)

    def test_tied_training_keeps_copies_identical(self):
        cfg = _cfg(num_layers=2, tie_embeddings=True)
        ids, labels = _data(cfg, batch=4)
        model = MPMDGPT(cfg, stage_layers=[[1, 1]], seed=2)
        opt = MPMDAdam(model.runtime, lr=1e-2)
        for step in range(3):
            data = model.split_micro_batches(ids, labels, [2])
            _, grads, _ = model.train_step(data)
            opt.apply(grads)
        wte = np.asarray(model.runtime.pipes[0][0].params["wte"])
        head = np.asarray(model.runtime.pipes[0][1].params["wte_head"])
        np.testing.assert_allclose(wte, head, rtol=1e-6)


class TestElasticMPMD:
    def test_elastic_trainer_hetero_switch_preserves_training(self,
                                                              devices8):
        """Malleus end-to-end: straggler ratios re-solve to an unequal
        stage layout, the trainer migrates params+optimizer state, and
        the loss trajectory matches an unswitched run (same math)."""
        from hetu_tpu.elastic.mpmd_trainer import ElasticMPMDTrainer
        from hetu_tpu.elastic.strategy import StrategyModel

        cfg = _cfg(num_layers=8)
        ids, labels = _data(cfg, batch=4)

        def provider(step):
            return ids, labels

        def make(solver_kw=None):
            solver = StrategyModel(8, cfg.num_layers, num_micro_batches=2,
                                   tp_candidates=[2], pp_candidates=[4])
            return ElasticMPMDTrainer(cfg, solver, provider,
                                      devices=devices8, lr=1e-2, seed=7)

        base = make()
        l_base = base.train_steps(6)

        tr = make()
        l_pre = tr.train_steps(3)
        # device 0 becomes a 3x straggler: the re-solved plan must give
        # its stage fewer layers
        ratios = [3.0] + [1.0] * 7
        switched = tr.retune(ratios)
        assert switched, "expected a hetero re-layout"
        sl = tr.current_strategy.stage_layers[0]
        assert sl != [2, 2, 2, 2], sl
        assert sum(sl) == 8 and min(sl) >= 1
        l_post = tr.train_steps(3)
        np.testing.assert_allclose(l_pre + l_post, l_base, rtol=2e-4)
        assert tr.history and tr.history[0]["switch_seconds"] > 0


class TestInterleaved:
    """Megatron-style interleaved 1F1B with virtual pipeline stages
    (beyond the reference: GPipe + plain 1F1B only there)."""

    def test_schedule_valid_and_complete(self):
        from hetu_tpu.parallel.schedule import (
            generate_interleaved_1f1b_schedule, validate_schedule)
        for S, M, C in [(2, 4, 2), (2, 8, 2), (4, 8, 2), (2, 6, 3)]:
            sched = generate_interleaved_1f1b_schedule(S, M, C)
            assert len(sched) == S * C
            validate_schedule(sched, M)

    def test_non_divisible_m_falls_back(self):
        from hetu_tpu.parallel.schedule import (
            generate_interleaved_1f1b_schedule,
            generate_pipedream_flush_schedule, validate_schedule)
        sched = generate_interleaved_1f1b_schedule(2, 3, 2)
        validate_schedule(sched, 3)
        assert sched == generate_pipedream_flush_schedule(4, 3)

    def test_interleaved_matches_single_stage(self, devices8):
        """2 physical stages x 2 chunks (4 virtual stages, meshes
        repeating with period 2) trains identically to one stage."""
        cfg = _cfg()
        ids, labels = _data(cfg, batch=8)

        ref = MPMDGPT(cfg, stage_layers=[[8]], seed=3)
        phys = [Mesh(np.array(devices8[2 * s:2 * s + 2]).reshape(1, 2),
                     ("dp", "tp")) for s in range(2)]
        # virtual stage v = chunk*S + s -> meshes [p0, p1, p0, p1]
        meshes = [[phys[0], phys[1], phys[0], phys[1]]]
        inter = MPMDGPT(cfg, stage_layers=[[2, 2, 2, 2]], meshes=meshes,
                        schedule="interleaved", num_chunks=2, seed=3)

        opt_r = MPMDAdam(ref.runtime, lr=1e-2)
        opt_i = MPMDAdam(inter.runtime, lr=1e-2)
        losses_r, losses_i = [], []
        for step in range(3):
            d_r = ref.split_micro_batches(ids, labels, [4])
            d_i = inter.split_micro_batches(ids, labels, [4])
            lr_, gr, _ = ref.train_step(d_r)
            li_, gi, st = inter.train_step(d_i)
            losses_r.append(float(lr_))
            losses_i.append(float(li_))
            opt_r.apply(gr)
            opt_i.apply(gi)
        np.testing.assert_allclose(losses_r, losses_i, rtol=2e-4)
        assert losses_r[-1] < losses_r[0]
        assert st.num_tasks == 2 * 4 * 4  # F+B x M x virtual stages

    def test_unknown_schedule_rejected(self, devices8):
        import pytest
        cfg = _cfg()
        with pytest.raises(ValueError, match="unknown schedule"):
            MPMDGPT(cfg, stage_layers=[[8]], schedule="interleave")

    def test_bf16_grad_scale_accum_keeps_dtype(self):
        """The shared grad scale/accumulate jits must not promote bf16
        grads to f32 (a strongly-typed f32 scale factor would; MPMDGPT
        itself keeps f32 master params, but Stage is generic and bf16
        stages are the natural TPU use)."""
        from hetu_tpu.parallel.pipeline_mpmd import (_accum_grads,
                                                     _scale_grads)
        dp = {"w": jnp.ones((4, 4), jnp.bfloat16),
              "b": jnp.ones((4,), jnp.float32)}
        w = jnp.float32(0.25)
        scaled = _scale_grads(dp, w)
        assert scaled["w"].dtype == jnp.bfloat16
        assert scaled["b"].dtype == jnp.float32
        acc = _accum_grads(scaled, dp, w)
        assert acc["w"].dtype == jnp.bfloat16
        assert acc["b"].dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(acc["b"]), 0.5)
