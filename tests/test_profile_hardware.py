"""Measured hardware profiling (planner.profile_hardware) tests.

Counterpart of the reference's profile_hardware pass
(tools/Galvatron/galvatron/profile_hardware/profile_hardware.py): the
constants the planner and elastic solver consume must come from (or be
checkable against) live measurements, not datasheets.
"""
import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.planner import (Calibration, profile_and_calibrate,
                              profile_collectives, profile_hbm,
                              profile_matmul, validate_step_prediction)


@pytest.fixture(scope="module")
def calibration(devices8_module):
    mesh = ht.create_mesh({"x": 4}, devices8_module[:4])
    return profile_and_calibrate(
        mesh=mesh, axis="x", matmul_sizes=(256, 512), hbm_bytes=1 << 22,
        coll_sizes=(1 << 12, 1 << 14, 1 << 16), reps=3)


@pytest.fixture(scope="module")
def devices8_module():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8
    return devs[:8]


class TestProfiling:
    def test_matmul_and_hbm_positive(self, calibration):
        assert calibration.best_matmul_flops > 0
        assert calibration.hbm_bw > 0
        assert all(v > 0 for v in calibration.matmul_flops.values())

    def test_collective_fits(self, calibration):
        assert set(calibration.collectives) == {
            "all_reduce", "all_gather", "reduce_scatter", "p2p"}
        for name, (alpha, beta) in calibration.collectives.items():
            assert alpha >= 0 and beta >= 0, (name, alpha, beta)

    def test_chip_spec_folding(self, calibration):
        spec = calibration.to_chip_spec()
        # measured throughput = peak * efficiency by construction
        assert spec.peak_flops * spec.mxu_efficiency \
            == pytest.approx(calibration.best_matmul_flops, rel=1e-6)
        assert spec.hbm_bw == pytest.approx(calibration.hbm_bw)
        if calibration.collectives.get("all_reduce", (0, 0))[1] > 0:
            assert spec.ici_bw == pytest.approx(
                1.0 / calibration.collectives["all_reduce"][1])

    def test_elastic_constants_measured(self, calibration):
        consts = calibration.elastic_constants(batch=4, seq=128,
                                               hidden=128, ffn=512)
        assert consts["layer_comm_cost"] >= 0
        assert consts["pipeline_p2p_cost"] >= 0
        from hetu_tpu.elastic.strategy import StrategyModel
        sm = StrategyModel.from_calibration(
            calibration, num_devices=4, num_layers=8, batch=4, seq=128,
            hidden=128, ffn=512)
        assert sm.layer_comm_cost == consts["layer_comm_cost"]
        assert sm.pipeline_p2p_cost == consts["pipeline_p2p_cost"]

    def test_save_load_roundtrip(self, calibration, tmp_path):
        p = str(tmp_path / "calib.json")
        calibration.save(p)
        back = Calibration.load(p)
        assert back.matmul_flops == calibration.matmul_flops
        assert back.collectives == calibration.collectives
        assert back.hbm_bw == calibration.hbm_bw

    @pytest.mark.slow
    def test_step_prediction_closes_loop(self, calibration):
        """Predicted vs measured step time: the ratio must be finite and
        positive (on the CPU simulator only sanity is asserted; on real
        TPU the reference expects same-order-of-magnitude)."""
        r = validate_step_prediction(calibration, batch=2, seq=64,
                                     hidden=64, num_layers=2, vocab=128)
        assert r["measured_s"] > 0
        assert np.isfinite(r["predicted_s"]) and r["predicted_s"] > 0
        import jax
        if jax.devices()[0].platform == "tpu":
            assert 0.1 < r["ratio"] < 10.0, r
