"""Pipeline-parallelism tests (SPMD collective-permute pipelining).

Invariant (reference checks loss-curve equivalence across pp configs):
pp2 / pp4 training trajectories == pp1, including with dp/tp inside
stages and multiple micro-batches.
"""
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import optim
from hetu_tpu.graph import ctor
from hetu_tpu.models.gpt import llama_config
from hetu_tpu.models.gpt_pipeline import GPTPipelineModel


# full-model training loops: excluded from the dev fast path
pytestmark = pytest.mark.slow


def _train(mesh_shape, num_stages, steps=3, nmb=2, seed=555, mk=None,
           **cfg_kw):
    ctor._seed_counter[0] = seed
    mesh = ht.create_mesh(mesh_shape)
    mk = mk or llama_config
    kw = dict(vocab_size=64, hidden_size=32, num_layers=4,
              num_heads=4, max_seq_len=16, sp=False)
    kw.update(cfg_kw)
    cfg = mk(**kw)
    with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
        ids = ht.parallel_placeholder("int32", (8, 16), pspec=P("dp", None),
                                      name="ids")
        lbl = ht.parallel_placeholder("int32", (8, 16), pspec=P("dp", None),
                                      name="lbl")
        m = GPTPipelineModel(cfg, num_stages=num_stages)
        loss = m(ids, lbl, num_micro_batches=nmb)
        op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
        rng = np.random.RandomState(0)
        I = rng.randint(0, 64, (8, 16)).astype(np.int32)
        L = np.roll(I, -1, 1)
        return [float(np.asarray(g.run(loss, [loss, op],
                                       {ids: I, lbl: L})[0]))
                for _ in range(steps)]


class TestPipeline:
    def test_pp2_with_dp_tp_matches_pp1(self, devices8):
        base = _train({"pp": 1, "dp": 1, "tp": 1}, 1)
        pp2 = _train({"pp": 2, "dp": 2, "tp": 2}, 2)
        np.testing.assert_allclose(base, pp2, rtol=3e-3, atol=1e-4)

    def test_pp4_matches_pp1(self, devices8):
        base = _train({"pp": 1, "dp": 1, "tp": 1}, 1)
        pp4 = _train({"pp": 4, "dp": 2, "tp": 1}, 4)
        np.testing.assert_allclose(base, pp4, rtol=3e-3, atol=1e-4)

    def test_micro_batch_counts_agree(self, devices8):
        a = _train({"pp": 2, "dp": 1, "tp": 1}, 2, nmb=2)
        b = _train({"pp": 2, "dp": 1, "tp": 1}, 2, nmb=4)
        np.testing.assert_allclose(a, b, rtol=3e-3, atol=1e-4)

    def test_gpt2_blocks_pipeline(self, devices8):
        """GPT-2-style blocks (gelu/layernorm/learned positions, biases)
        pipeline too — the flagship bench config is no longer barred from
        pp (reference places the same blocks across stages regardless of
        architecture, examples/gpt/train_hetu.py:256)."""
        from hetu_tpu.models.gpt import GPTConfig
        base = _train({"pp": 1, "dp": 1, "tp": 1}, 1, mk=GPTConfig)
        pp2 = _train({"pp": 2, "dp": 2, "tp": 2}, 2, mk=GPTConfig)
        np.testing.assert_allclose(base, pp2, rtol=3e-3, atol=1e-4)

    def test_pp2_with_sp_matches_pp1(self, devices8):
        """Megatron-SP composes with pp (reference per-layer sp flag,
        parallel_multi_ds.py:156-170): the residual stream stays
        seq-sharded over tp inside pipeline stages."""
        base = _train({"pp": 1, "dp": 1, "tp": 1}, 1, sp=True)
        pp2 = _train({"pp": 2, "dp": 2, "tp": 2}, 2, sp=True)
        np.testing.assert_allclose(base, pp2, rtol=3e-3, atol=1e-4)

    def test_pp2_gqa_matches_pp1(self, devices8):
        """GQA (num_kv_heads < num_heads) trains through the pipelined
        blocks — pp no longer bars the GQA model family."""
        base = _train({"pp": 1, "dp": 1, "tp": 1}, 1, num_kv_heads=2)
        pp2 = _train({"pp": 2, "dp": 2, "tp": 2}, 2, num_kv_heads=2)
        np.testing.assert_allclose(base, pp2, rtol=3e-3, atol=1e-4)

    def test_pp2_moe_matches_pp1(self, devices8):
        """All-MoE stacks (moe_every=1) pipeline with the balance aux
        loss threaded through warmup/drain-masked pipeline ticks."""
        moe_kw = dict(num_experts=4, moe_top_k=2, moe_every=1,
                      moe_capacity_factor=2.0)
        base = _train({"pp": 1, "dp": 1, "tp": 1}, 1, **moe_kw)
        pp2 = _train({"pp": 2, "dp": 2, "tp": 2}, 2, **moe_kw)
        assert base[-1] < base[0]          # actually learning
        np.testing.assert_allclose(base, pp2, rtol=3e-3, atol=1e-4)

    def test_pp2_moe_ep_matches_pp1(self, devices8):
        """MoE + expert parallelism inside pipeline stages (pp2 x ep2)."""
        moe_kw = dict(num_experts=4, moe_top_k=2, moe_every=1,
                      moe_capacity_factor=2.0)
        base = _train({"pp": 1, "dp": 1, "tp": 1}, 1, **moe_kw)
        pp2 = _train({"pp": 2, "dp": 2, "ep": 2}, 2, ep_axis="ep",
                     **moe_kw)
        np.testing.assert_allclose(base, pp2, rtol=3e-3, atol=1e-4)

    def test_mixed_dense_moe_raises(self, devices8):
        mesh = ht.create_mesh({"pp": 2, "dp": 2, "tp": 2})
        cfg = llama_config(vocab_size=64, hidden_size=32, num_layers=4,
                           num_heads=4, max_seq_len=16, sp=False,
                           num_experts=4, moe_every=2)
        with ht.graph("define_and_run", create_new=True, mesh=mesh):
            with pytest.raises(NotImplementedError, match="moe_every"):
                GPTPipelineModel(cfg, num_stages=2)

    def test_layers_not_divisible_raises(self, devices8):
        mesh = ht.create_mesh({"pp": 4, "dp": 2, "tp": 1})
        cfg = llama_config(vocab_size=64, hidden_size=32, num_layers=6,
                           num_heads=4, max_seq_len=16, sp=False)
        with ht.graph("define_and_run", create_new=True, mesh=mesh):
            with pytest.raises(AssertionError):
                GPTPipelineModel(cfg, num_stages=4)
