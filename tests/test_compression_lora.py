"""Embedding-compression methods + LoRA tests."""
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import nn, ops, optim
from hetu_tpu.embedding import (AdaptiveEmbedding, ALPTEmbedding,
                                AutoDimEmbedding, AutoSrhEmbedding,
                                CompositionalEmbedding, DedupEmbedding,
                                DeepLightEmbedding, DHEEmbedding,
                                DPQEmbedding, HashEmbedding,
                                LowRankEmbedding, MGQEEmbedding,
                                MixedDimensionEmbedding, OptEmbedEmbedding,
                                PEPEmbedding, QuantizedEmbedding,
                                ROBEEmbedding, SparseEmbedding,
                                TensorTrainEmbedding)
from hetu_tpu.models.ctr import WDL, ctr_loss
from hetu_tpu.nn.lora import (LoRAColumnParallelLinear, LoRAEmbedding,
                              LoRARowParallelLinear,
                              mark_only_lora_trainable, merge_lora)

N, D = 64, 16


def _make(cls):
    if cls is DedupEmbedding:
        # 8-row blocks, half the blocks deduplicated away
        rng = np.random.RandomState(3)
        uniq = rng.randn(N // 2, D).astype(np.float32)
        remap = rng.randint(0, (N // 2) // 8, N // 8)
        return DedupEmbedding(uniq, remap, nemb_per_block=8,
                              num_embeddings=N)
    if cls is SparseEmbedding:
        dense = np.random.RandomState(4).randn(N, D).astype(np.float32)
        return SparseEmbedding(dense, nnz_per_row=4)
    if cls is AdaptiveEmbedding:
        remap = np.random.RandomState(5).permutation(N)
        return AdaptiveEmbedding(N, D, num_freq=16, num_rare=8,
                                 remap_indices=remap)
    if cls is AutoSrhEmbedding:
        groups = (np.arange(N) * 4) // N
        return AutoSrhEmbedding(N, D, nsplit=4, group_indices=groups)
    kwargs = {
        HashEmbedding: dict(table_size=16),
        CompositionalEmbedding: dict(num_buckets=8),
        ROBEEmbedding: dict(robe_size=256),
        DHEEmbedding: dict(num_hashes=8, hidden=32),
        DPQEmbedding: dict(num_codebooks=4, codebook_size=8),
        MGQEEmbedding: dict(num_codebooks=4, codebook_size=8,
                            cold_codebook_size=2),
        QuantizedEmbedding: dict(bits=8),
        TensorTrainEmbedding: dict(ranks=4),
        LowRankEmbedding: dict(rank=4),
        DeepLightEmbedding: dict(),
        PEPEmbedding: dict(),
        OptEmbedEmbedding: dict(),
        MixedDimensionEmbedding: dict(hot_fraction=0.25, cold_dim=4),
        AutoDimEmbedding: dict(candidate_dims=(2, 8)),
        ALPTEmbedding: dict(digit=8),
    }[cls]
    return cls(N, D, **kwargs)


ALL_METHODS = [HashEmbedding, CompositionalEmbedding, ROBEEmbedding,
               DHEEmbedding, DPQEmbedding, MGQEEmbedding,
               QuantizedEmbedding, TensorTrainEmbedding, LowRankEmbedding,
               DeepLightEmbedding, PEPEmbedding, OptEmbedEmbedding,
               MixedDimensionEmbedding, AutoDimEmbedding,
               AdaptiveEmbedding, ALPTEmbedding, AutoSrhEmbedding,
               DedupEmbedding]


class TestCompressionMethods:
    @pytest.mark.parametrize("cls", ALL_METHODS,
                             ids=[c.__name__ for c in ALL_METHODS])
    def test_forward_shape_and_grad(self, cls):
        """Every method: ids -> [B, F, D]; training moves its params."""
        from hetu_tpu.graph import ctor
        ctor._seed_counter[0] = 5
        ids = np.random.RandomState(0).randint(0, N, (4, 3)).astype(np.int32)
        with ht.graph("define_and_run", create_new=True) as g:
            emb = _make(cls)
            ph = ht.placeholder("int32", ids.shape, name="ids")
            out = emb(ph)
            assert tuple(out.shape) == (4, 3, D), cls.__name__
            loss = ops.reduce_mean((out - 1.0) ** 2)
            train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            l0 = None
            for _ in range(5):
                l, _ = g.run(loss, [loss, train_op], {ph: ids})
                l0 = l0 if l0 is not None else float(np.asarray(l))
            lN = float(np.asarray(l))
        assert np.isfinite(lN)
        assert lN < l0, f"{cls.__name__}: {l0} -> {lN}"

    @pytest.mark.parametrize("cls", [HashEmbedding, CompositionalEmbedding,
                                     ROBEEmbedding, TensorTrainEmbedding,
                                     LowRankEmbedding, DPQEmbedding])
    def test_actually_compresses(self, cls):
        with ht.graph("define_and_run", create_new=True):
            emb = _make(cls)
            assert emb.compression_ratio() > 1.5, \
                f"{cls.__name__} ratio {emb.compression_ratio()}"

    def test_same_id_same_embedding(self):
        """Determinism: repeated ids produce identical rows."""
        with ht.graph("define_and_run", create_new=True) as g:
            emb = _make(ROBEEmbedding)
            ph = ht.placeholder("int32", (4,), name="ids")
            out = emb(ph)
            (o,) = g.run(out, [out], {ph: np.array([5, 5, 9, 5], np.int32)})
        o = np.asarray(o)
        np.testing.assert_array_equal(o[0], o[1])
        np.testing.assert_array_equal(o[0], o[3])
        assert not np.array_equal(o[0], o[2])

    def test_deeplight_sparsity_ramp(self):
        with ht.graph("define_and_run", create_new=True) as g:
            emb = _make(DeepLightEmbedding)
            ph = ht.placeholder("int32", (8,), name="ids")
            emb.set_sparsity(0.75)
            out = emb(ph)
            (o,) = g.run(out, [out],
                         {ph: np.arange(8, dtype=np.int32)})
        frac_zero = float((np.asarray(o) == 0).mean())
        assert frac_zero >= 0.6  # ~75% pruned

    def test_dpq_codebooks_receive_gradient(self):
        """The deployed artifact (codebooks) must train, not just the
        training-time query table."""
        with ht.graph("define_and_run", create_new=True) as g:
            emb = _make(DPQEmbedding)
            ph = ht.placeholder("int32", (8,), name="ids")
            loss = ops.reduce_mean((emb(ph) - 1.0) ** 2)
            train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            b0 = np.asarray(g.get_tensor_value(emb.codebooks)).copy()
            for _ in range(5):
                g.run(loss, [train_op],
                      {ph: np.arange(8, dtype=np.int32)})
            b1 = np.asarray(g.get_tensor_value(emb.codebooks))
        assert np.abs(b1 - b0).max() > 0

    def test_quantized_step_size_trains(self):
        """ALPT: the learned quantization step must receive gradient."""
        with ht.graph("define_and_run", create_new=True) as g:
            emb = _make(QuantizedEmbedding)
            ph = ht.placeholder("int32", (8,), name="ids")
            loss = ops.reduce_mean((emb(ph) - 1.0) ** 2)
            train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            s0 = np.asarray(g.get_tensor_value(emb.step)).copy()
            for _ in range(5):
                g.run(loss, [train_op],
                      {ph: np.arange(8, dtype=np.int32)})
            s1 = np.asarray(g.get_tensor_value(emb.step))
        assert np.abs(s1[:8] - s0[:8]).max() > 0

    def test_deeplight_ramp_applies_mid_training(self):
        """set_sparsity AFTER the step is compiled must still take
        effect (sparsity is a graph variable, not a traced constant)."""
        with ht.graph("define_and_run", create_new=True) as g:
            emb = _make(DeepLightEmbedding)
            ph = ht.placeholder("int32", (8,), name="ids")
            out = emb(ph)
            ids = np.arange(8, dtype=np.int32)
            (o0,) = g.run(out, [out], {ph: ids})
            assert (np.asarray(o0) == 0).mean() < 0.1  # dense at start
            emb.set_sparsity(0.75)                     # ramp mid-training
            (o1,) = g.run(out, [out], {ph: ids})
            assert (np.asarray(o1) == 0).mean() >= 0.6

    def test_mgqe_cold_ids_use_fewer_codewords(self):
        with ht.graph("define_and_run", create_new=True) as g:
            emb = MGQEEmbedding(N, D, num_codebooks=2, codebook_size=8,
                                hot_fraction=0.1, cold_codebook_size=2)
            ph = ht.placeholder("int32", (N,), name="ids")
            out = emb(ph)
            (o,) = g.run(out, [out],
                         {ph: np.arange(N, dtype=np.int32)})
        o = np.asarray(o)
        # cold rows come from a pool of at most 2*2 codeword combos per
        # codebook pair -> at most 4 distinct cold embeddings
        cold = o[emb.hot_boundary:]
        assert len(np.unique(cold.round(5), axis=0)) <= 4

    def test_wdl_with_compressed_embedding(self):
        from hetu_tpu.graph import ctor
        ctor._seed_counter[0] = 3
        rng = np.random.RandomState(0)
        ids = rng.randint(0, N, (16, 5)).astype(np.int32)
        dense = rng.randn(16, 4).astype(np.float32)
        labels = (dense[:, 0] > 0).astype(np.float32)
        with ht.graph("define_and_run", create_new=True) as g:
            emb = CompositionalEmbedding(N, 8, num_buckets=8)
            sp = ht.placeholder("int32", ids.shape, name="sp")
            dn = ht.placeholder("float32", dense.shape, name="dn")
            lb = ht.placeholder("float32", labels.shape, name="lb")
            model = WDL(5, N, embedding_dim=8, num_dense=4, hidden=(16,),
                        embedding=emb)
            loss = ctr_loss(model(sp, dn), lb)
            train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            losses = []
            for _ in range(15):
                l, _ = g.run(loss, [loss, train_op],
                             {sp: ids, dn: dense, lb: labels})
                losses.append(float(np.asarray(l)))
        assert losses[-1] < losses[0]


class TestLoRA:
    def test_adapter_starts_as_identity(self):
        """B=0 at init: LoRA layer output == base layer output (seeds are
        consumed at materialization, so compare across fresh graphs)."""
        from hetu_tpu.graph import ctor
        X = np.random.RandomState(0).randn(4, 8).astype(np.float32)

        def run(cls, **kw):
            ctor._seed_counter[0] = 42
            with ht.graph("define_and_run", create_new=True) as g:
                layer = cls(8, 12, bias=True, **kw)
                ph = ht.placeholder("float32", X.shape, name="x")
                out = layer(ph)
                (o,) = g.run(out, [out], {ph: X})
            return np.asarray(o)

        o1 = run(nn.ColumnParallelLinear)
        o2 = run(LoRAColumnParallelLinear, rank=4)
        np.testing.assert_allclose(o1, o2, rtol=1e-5, atol=1e-6)

    def test_only_lora_params_train(self):
        X = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        with ht.graph("define_and_run", create_new=True) as g:
            lora = LoRAColumnParallelLinear(8, 12, rank=4)
            mark_only_lora_trainable(lora)
            ph = ht.placeholder("float32", X.shape, name="x")
            loss = ops.reduce_mean((lora(ph) - 1.0) ** 2)
            train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            w0 = np.asarray(g.get_tensor_value(lora.weight)).copy()
            a0 = np.asarray(g.get_tensor_value(lora.lora_A)).copy()
            losses = []
            for _ in range(10):
                l, _ = g.run(loss, [loss, train_op], {ph: X})
                losses.append(float(np.asarray(l)))
            w1 = np.asarray(g.get_tensor_value(lora.weight))
            a1 = np.asarray(g.get_tensor_value(lora.lora_A))
        np.testing.assert_array_equal(w0, w1)      # frozen
        assert np.abs(a1 - a0).max() > 0           # adapter trained
        assert losses[-1] < losses[0]

    def test_merge_matches_adapter_output(self):
        X = np.random.RandomState(1).randn(4, 8).astype(np.float32)
        with ht.graph("define_and_run", create_new=True) as g:
            lora = LoRARowParallelLinear(8, 6, rank=4, bias=False)
            mark_only_lora_trainable(lora)
            ph = ht.placeholder("float32", X.shape, name="x")
            out = lora(ph)
            loss = ops.reduce_mean((out - 1.0) ** 2)
            train_op = optim.AdamOptimizer(lr=5e-2).minimize(loss)
            for _ in range(5):
                g.run(loss, [train_op], {ph: X})
            (before,) = g.run(out, [out], {ph: X})
            merge_lora(lora, g)
            assert lora.merged
            out2 = lora(ph)
            (after,) = g.run(out2, [out2], {ph: X})
        np.testing.assert_allclose(np.asarray(before), np.asarray(after),
                                   rtol=1e-4, atol=1e-5)

    def test_lora_embedding(self):
        ids = np.arange(6, dtype=np.int32)
        with ht.graph("define_and_run", create_new=True) as g:
            emb = LoRAEmbedding(32, 8, rank=4)
            mark_only_lora_trainable(emb)
            ph = ht.placeholder("int32", ids.shape, name="ids")
            out = emb(ph)
            loss = ops.reduce_mean((out - 0.5) ** 2)
            train_op = optim.AdamOptimizer(lr=5e-2).minimize(loss)
            w0 = np.asarray(g.get_tensor_value(emb.weight)).copy()
            losses = []
            for _ in range(10):
                l, _ = g.run(loss, [loss, train_op], {ph: ids})
                losses.append(float(np.asarray(l)))
            w1 = np.asarray(g.get_tensor_value(emb.weight))
        np.testing.assert_array_equal(w0, w1)
        assert losses[-1] < losses[0]

    def test_lora_tp_matches_single_device(self, devices8):
        """LoRA fine-tuning under TP == single-device (same seeds)."""
        from hetu_tpu.graph import ctor
        X = np.random.RandomState(2).randn(8, 16).astype(np.float32)

        def run(mesh):
            ctor._seed_counter[0] = 321
            m = ht.create_mesh(mesh, None) if mesh else None
            with ht.graph("define_and_run", create_new=True,
                          mesh=m) as g:
                lora = LoRAColumnParallelLinear(16, 16, rank=4,
                                                gather_output=True)
                mark_only_lora_trainable(lora)
                ph = ht.parallel_placeholder(
                    "float32", X.shape,
                    pspec=P("dp", None) if m else None, name="x")
                loss = ops.reduce_mean((lora(ph) - 1.0) ** 2)
                train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
                out = []
                for _ in range(4):
                    l, _ = g.run(loss, [loss, train_op], {ph: X})
                    out.append(float(np.asarray(l)))
            return out

        l1 = run(None)
        l2 = run({"dp": 2, "tp": 4})
        np.testing.assert_allclose(l1, l2, rtol=1e-4, atol=1e-6)


class TestNewCompressionMethods:
    """Round-4 additions (adapt.py / alpt.py / autosrh.py /
    deduplication.py / sparse.py reference parity)."""

    def test_dedup_shares_block_storage(self):
        rng = np.random.RandomState(0)
        uniq = rng.randn(16, D).astype(np.float32)
        # blocks of 8 rows; logical blocks [0,1,2,3] -> unique [0,1,0,1]
        remap = np.array([0, 1, 0, 1])
        with ht.graph("define_and_run", create_new=True) as g:
            emb = DedupEmbedding(uniq, remap, nemb_per_block=8,
                                 num_embeddings=32)
            ph = ht.placeholder("int32", (4,), name="ids")
            out = emb(ph)
            # id 3 (block 0) and id 19 (block 2 -> same unique block 0)
            (val,) = g.run(out, [out],
                           {ph: np.array([3, 19, 8, 24], np.int32)})
        v = np.asarray(val)
        np.testing.assert_allclose(v[0], v[1])   # deduplicated rows equal
        np.testing.assert_allclose(v[2], v[3])

    def test_sparse_matches_pruned_dense(self):
        dense = np.random.RandomState(1).randn(N, D).astype(np.float32)
        with ht.graph("define_and_run", create_new=True) as g:
            emb = SparseEmbedding(dense, nnz_per_row=4)
            assert emb.compression_ratio() >= 2.0
            ph = ht.placeholder("int32", (8,), name="ids")
            out = emb(ph)
            ids = np.arange(8, dtype=np.int32)
            (val,) = g.run(out, [out], {ph: ids})
        v = np.asarray(val)
        # each row: exactly the 4 largest-|.| entries of dense, rest 0
        for r, i in enumerate(ids):
            keep = np.argsort(-np.abs(dense[i]))[:4]
            want = np.zeros(D, np.float32)
            want[keep] = dense[i, keep]
            np.testing.assert_allclose(v[r], want, rtol=1e-6)

    def test_autosrh_retrain_freezes_alpha(self):
        groups = (np.arange(N) * 4) // N
        from hetu_tpu.graph import ctor
        ctor._seed_counter[0] = 9
        ids = np.arange(8, dtype=np.int32)
        with ht.graph("define_and_run", create_new=True) as g:
            emb = AutoSrhEmbedding(N, D, nsplit=4, group_indices=groups,
                                   retrain=True)
            ph = ht.placeholder("int32", (8,), name="ids")
            out = emb(ph)
            loss = ops.reduce_mean((out - 1.0) ** 2)
            op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            a0 = np.asarray(g._materialize_var(emb.alpha)).copy()
            for _ in range(3):
                g.run(loss, [loss, op], {ph: ids})
            a1 = np.asarray(g.get_tensor_value(emb.alpha))
        np.testing.assert_allclose(a0, a1)  # alpha frozen under retrain
