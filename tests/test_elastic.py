"""Elastic engine (Malleus) tests: straggler profiling, strategy solving,
and Trainer-driven hot switching on the virtual 8-device mesh.

Mirrors the reference's elastic flow (python/elastic/engine/*,
examples/malleus/test_straggler_workload.py)."""
import os

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import optim
from hetu_tpu.elastic import (Straggler, StragglerWorkload, Strategy,
                              StrategyModel, Trainer)
from hetu_tpu.models import GPTConfig, GPTLMHeadModel


# ---------------------------------------------------------------------------
# Straggler
# ---------------------------------------------------------------------------

# full-model training loops: excluded from the dev fast path
pytestmark = pytest.mark.slow


def test_straggler_env_injection(monkeypatch):
    monkeypatch.setenv("HETU_TPU_STRAGGLER_RATIOS", "2.0,1.0,1.0,1.0")
    s = Straggler(4)
    assert s.read_profile() == [2.0, 1.0, 1.0, 1.0]


def test_straggler_workload_injection():
    s = Straggler(4)
    s.inject(StragglerWorkload([1.0, 1.0, 3.0, 1.0]))
    s.begin_profile()
    s.end_profile(steps=1)
    ratios = s.read_profile()
    assert ratios[2] == pytest.approx(3.0)
    assert min(ratios) == 1.0


def test_straggler_healthy_default():
    s = Straggler(8)
    assert s.read_profile() == [1.0] * 8


# ---------------------------------------------------------------------------
# StrategyModel
# ---------------------------------------------------------------------------

def test_tp_grouping_quarantines_stragglers():
    m = StrategyModel(num_devices=8, num_layers=8)
    ratios = [1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 2.0, 2.0]
    groups, times = m.solve_tp_arrangements(ratios, tp=2)
    # the two slow devices must share one group, not gate two groups
    slow_groups = [g for g in groups if 6 in g or 7 in g]
    assert len(slow_groups) == 1
    assert sorted(times) == [1.0, 1.0, 1.0, 2.0]


def test_layer_partition_favors_fast_stages():
    from hetu_tpu.elastic.strategy import _partition_layers
    layers, tmax = _partition_layers(12, [1.0, 2.0])  # stage1 2x slower
    assert sum(layers) == 12
    assert layers[0] > layers[1]          # fast stage takes more layers
    assert tmax == max(layers[0] * 1.0, layers[1] * 2.0)


def test_micro_batch_apportionment():
    from hetu_tpu.elastic.strategy import _apportion
    mb = _apportion(8, [1.0, 1.0])
    assert mb == [4, 4]
    mb = _apportion(9, [2.0, 1.0])
    assert sum(mb) == 9 and mb[0] > mb[1]


def test_make_plans_homogeneous_prefers_pure_dp():
    # healthy devices + comm overhead -> dp-only should win
    m = StrategyModel(num_devices=8, num_layers=8, num_micro_batches=4)
    plans = m.make_plans([1.0] * 8, top_k=0)
    assert plans
    best = plans[0]
    assert best.tp == 1 and best.pp == 1 and best.dp == 8
    assert all(sum(s) == 8 for s in best.stage_layers)


def test_make_plans_straggler_changes_layout():
    m = StrategyModel(num_devices=8, num_layers=8, num_micro_batches=4,
                      tp_candidates=[2], pp_candidates=[2])
    ratios = [1.0] * 6 + [3.0, 3.0]
    (plan,) = m.make_plans(ratios, top_k=1)
    assert plan.tp == 2 and plan.pp == 2 and plan.dp == 2
    # slow pair shares one tp group; the stage holding it gets fewer layers
    assert sorted(plan.device_order) == list(range(8))
    slow_stage_layers = None
    flat = plan.tp_group_times
    for p in range(plan.dp):
        for s in range(plan.pp):
            if flat[p * plan.pp + s] == 3.0:
                slow_stage_layers = plan.stage_layers[p][s]
    assert slow_stage_layers is not None
    assert slow_stage_layers < max(max(s) for s in plan.stage_layers)


def test_strategy_is_hetero():
    homo = Strategy(tp=2, pp=2, dp=2, device_order=list(range(8)),
                    stage_layers=[[4, 4], [4, 4]], micro_batches=[2, 2],
                    est_step_time=1.0)
    assert not homo.is_hetero
    uneven_mb = Strategy(tp=2, pp=2, dp=2, device_order=list(range(8)),
                         stage_layers=[[4, 4], [4, 4]], micro_batches=[3, 1],
                         est_step_time=1.0)
    assert uneven_mb.is_hetero
    uneven_layers = Strategy(tp=2, pp=2, dp=2, device_order=list(range(8)),
                             stage_layers=[[5, 3], [4, 4]],
                             micro_batches=[2, 2], est_step_time=1.0)
    assert uneven_layers.is_hetero


def test_trainer_hetero_error_policy(devices8):
    """hetero='error' refuses to silently project a hetero plan onto a
    rectangular SPMD mesh (routes users to ElasticMPMDTrainer)."""
    import pytest
    from hetu_tpu.elastic.trainer import Trainer
    trainer = Trainer.__new__(Trainer)
    trainer.hetero = "error"
    trainer.devices = list(devices8)
    trainer.graph = type("G", (), {"mesh": None})()
    hetero = Strategy(tp=1, pp=2, dp=4, device_order=list(range(8)),
                      stage_layers=[[5, 3], [4, 4], [4, 4], [4, 4]],
                      micro_batches=[1, 1, 1, 1], est_step_time=1.0)
    with pytest.raises(RuntimeError, match="ElasticMPMDTrainer"):
        trainer._apply_strategy(hetero)
    with pytest.raises(ValueError, match="hetero"):
        Trainer(graph=None, loss=None, train_op=None, optimizer=None,
                data_provider=None, solver=None, hetero="bogus")


def test_strategy_mesh_shape():
    s = Strategy(tp=2, pp=2, dp=2, device_order=list(range(8)),
                 stage_layers=[[4, 4], [4, 4]], micro_batches=[2, 2],
                 est_step_time=1.0)
    assert s.mesh_shape == {"pp": 2, "dp": 2, "tp": 2}
    # size-1 axes are kept: dropping them would strip axis names from param
    # specs on a switch and break a later switch back to tp>1
    s2 = Strategy(tp=1, pp=1, dp=8, device_order=list(range(8)),
                  stage_layers=[[8]] * 8, micro_batches=[1] * 8,
                  est_step_time=1.0)
    assert s2.mesh_shape == {"pp": 1, "dp": 8, "tp": 1}


def test_switch_to_dp_only_and_back_keeps_tp_sharding(devices8):
    # regression for the round-trip: tp=2 -> dp-only plan -> tp=2 again must
    # re-shard weights on tp, not leave them replicated
    mesh = ht.create_mesh({"pp": 1, "dp": 4, "tp": 2}, devices8)
    g, loss, train_op, opt, data = _build_training(mesh)
    trainer = Trainer(g, loss, train_op, opt, data,
                      StrategyModel(num_devices=8, num_layers=2,
                                    num_micro_batches=2,
                                    tp_candidates=[1, 2],
                                    pp_candidates=[1]),
                      num_micro_batches=2)
    trainer.train_steps(1)

    def tp_sharded_params():
        return [a for a in g._var_data.values()
                if any("tp" in ((e,) if isinstance(e, str) else (e or ()))
                       for e in (a.sharding.spec or []))]

    assert tp_sharded_params(), "model should start tp-sharded"
    trainer.retune([1.0] * 8)          # healthy -> dp-only wins
    assert trainer.current_strategy.tp == 1
    trainer.train_steps(1)
    # now force tp=2 back via candidates
    trainer.solver.tp_candidates = [2]
    trainer.retune([1.0] * 6 + [5.0, 5.0])
    assert trainer.current_strategy.tp == 2
    trainer.train_steps(1)
    assert tp_sharded_params(), "tp sharding must survive the round trip"


def test_straggler_kv_missing_host_treated_slow():
    class FakeKV:
        def __init__(self):
            self.d = {"straggler/0": "1.0"}

        def put(self, k, v):
            self.d[k] = v

        def get(self, k, timeout=None):
            return self.d.get(k)

    s = Straggler(4, kv_store=FakeKV(), host_id=0, devices_per_host=2)
    s._seconds_per_step = 1.0
    with pytest.warns(UserWarning, match="missing"):
        ratios = s.read_profile()
    # host 1 never reported -> its devices must look SLOW, not healthy
    assert ratios[2] > 5.0 and ratios[3] > 5.0
    assert ratios[0] == 1.0


# ---------------------------------------------------------------------------
# Trainer end-to-end on virtual devices
# ---------------------------------------------------------------------------

def _build_training(mesh, batch=8, seq=16):
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                    max_seq_len=seq, dtype="float32")
    g_ctx = ht.graph("define_and_run", create_new=True, mesh=mesh)
    g = g_ctx.__enter__()
    ids = ht.parallel_placeholder("int32", (batch, seq), pspec=P("dp", None),
                                  name="ids")
    labels = ht.parallel_placeholder("int32", (batch, seq),
                                     pspec=P("dp", None), name="labels")
    model = GPTLMHeadModel(cfg)
    loss = model(ids, labels)
    opt = optim.AdamOptimizer(lr=1e-2)
    train_op = opt.minimize(loss)
    g_ctx.__exit__()
    rng = np.random.RandomState(0)
    IDS = rng.randint(0, 64, (batch, seq)).astype(np.int32)
    L = np.roll(IDS, -1, 1)

    def data_provider(step):
        return {ids: IDS, labels: L}

    return g, loss, train_op, opt, data_provider


def test_trainer_elastic_switch(devices8, monkeypatch):
    mesh = ht.create_mesh({"dp": 4, "tp": 2}, devices8)
    g, loss, train_op, opt, data = _build_training(mesh)
    solver = StrategyModel(num_devices=8, num_layers=2, num_micro_batches=2,
                           tp_candidates=[1, 2, 4], pp_candidates=[1])
    trainer = Trainer(g, loss, train_op, opt, data, solver,
                      num_micro_batches=2)
    l0 = trainer.train_steps(3)
    # inject a straggler pair -> solver should pick tp=2 quarantine and the
    # trainer must live-switch the mesh (device permutation)
    monkeypatch.setenv("HETU_TPU_STRAGGLER_RATIOS",
                       "1.0,1.0,1.0,1.0,1.0,1.0,4.0,4.0")
    switched = trainer.retune()
    assert switched
    assert trainer.current_strategy is not None
    assert g.mesh is not None
    # training continues seamlessly on the new layout
    l1 = trainer.train_steps(3)
    assert all(np.isfinite(v) for v in l0 + l1)
    assert l1[-1] < l0[0]   # still learning after the switch
    assert trainer.history and trainer.history[-1]["switch_seconds"] >= 0


def test_trainer_no_switch_when_healthy(devices8):
    mesh = ht.create_mesh({"dp": 8}, devices8)
    g, loss, train_op, opt, data = _build_training(mesh)
    solver = StrategyModel(num_devices=8, num_layers=2, num_micro_batches=2,
                           tp_candidates=[1, 2], pp_candidates=[1])
    trainer = Trainer(g, loss, train_op, opt, data, solver,
                      num_micro_batches=2)
    trainer.train_steps(1)
    # healthy ratios: first retune adopts the solved plan (dp8); a second
    # retune with the same ratios must be a no-op
    trainer.retune([1.0] * 8)
    before = len(trainer.history)
    assert not trainer.retune([1.0] * 8)
    assert len(trainer.history) == before


def test_trainer_run_with_profile_interval(devices8):
    mesh = ht.create_mesh({"dp": 8}, devices8)
    g, loss, train_op, opt, data = _build_training(mesh)
    solver = StrategyModel(num_devices=8, num_layers=2, num_micro_batches=2,
                           tp_candidates=[1], pp_candidates=[1])
    trainer = Trainer(g, loss, train_op, opt, data, solver,
                      num_micro_batches=2)
    losses = trainer.run(6, profile_interval=3)
    assert len(losses) == 6
    assert losses[-1] < losses[0]


def test_assignment_search_beats_or_matches_round_robin():
    """The pattern-enumeration + swap search must never be worse than the
    plain round-robin assignment it replaced, and on a quarantine-shaped
    straggler pattern (one very slow device) it should strictly beat it —
    the reference's enumerate_pp_pattern motivation (strategy.py:562)."""
    m = StrategyModel(num_devices=8, num_layers=8, num_micro_batches=8,
                      tp_candidates=[1], pp_candidates=[2])
    # two stragglers of DIFFERENT severity: round-robin spreads them into
    # two pipelines (both slowed); quarantining them into one pipeline
    # that then receives few micro-batches is strictly better
    ratios = [1.0] * 6 + [2.0, 4.0]     # tp=1 pp=2 dp=4
    (plan,) = m.make_plans(ratios, top_k=1)

    # hand-computed round-robin baseline through the same evaluator
    groups, gtimes = m.solve_tp_arrangements(ratios, 1)
    order = sorted(range(len(groups)), key=lambda g: gtimes[g])
    rr = [[] for _ in range(4)]
    for i, g in enumerate(order):
        rr[i % 4].append(g)
    _, _, _, rr_step = m._eval_assignment(rr, gtimes, tp=1, pp=2, dp=4)
    assert plan.est_step_time <= rr_step + 1e-9
    # quarantining the slow device into one pipeline (which then gets
    # fewer micro-batches) must beat mixing it into a fast pipeline
    assert plan.est_step_time < rr_step - 1e-6
    assert min(plan.micro_batches) < max(plan.micro_batches)
