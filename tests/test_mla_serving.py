"""MLA compressed latent KV on the paged pool (ISSUE 16).

Covers the tentpole contracts:

- **converter** — ``mla_state_from`` emits the weight-absorbed schema
  (q / kv_a / k_up / v_up, no fused qkv) and is EXACT when the stacked
  per-head ``[W_k; W_v]`` rank fits the latent dim;
- **latent serving bit-for-bit** — a latent engine under the
  adversarial trace (small pool, chunked prefill, late arrivals,
  preemption asserted non-vacuous) reproduces latent solo
  ``generate()`` at temperature 0, for learned AND rotary (decoupled
  rope) configs;
- **composition, not forks** — prefix-cache CoW (warm hit vs cold
  bitwise, LRU eviction under pressure), speculative verify rows
  (temp-0 and sampled bitwise vs a non-spec latent engine), and
  disaggregated handoff/adoption (cluster vs monolithic bitwise) all
  ride latent pages unchanged;
- **layout safety** — ``PageTransport.inject`` refuses a cross-layout
  page stream; the prefix digest is layout-salted so latent and
  full-head replicas never cross-match;
- **quantized pages** — int8/nf4 latent pages (row absmax, one scale
  per cached token) round-trip within their error bounds and serve
  deterministically;
- **kernel parity** — the latent Pallas kernel (interpret mode on CPU)
  against the gather-dense latent reference, rope and quant variants
  included;
- **observability** — ``kv_bytes_per_token`` / ``kv_bytes_in_use``
  gauges, pool layout tags, and ``analysis/memory`` recognizing latent
  page shapes.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.models.generate import generate
from hetu_tpu.models.gpt import draft_state_from, mla_config, mla_state_from
from hetu_tpu.ops.quantization import dequantize_rows, quantize_rows
from hetu_tpu.ops.ragged_paged_attention import (
    latent_ragged_paged_attention_pallas,
    latent_ragged_paged_attention_reference)
from hetu_tpu.serving import Engine, EngineCluster
from hetu_tpu.serving.kv_pool import PagedKVPool, page_shape_bytes
from hetu_tpu.serving.prefix_cache import token_chain_hashes
from hetu_tpu.serving.spec import SpecConfig

CFG_KW = dict(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
              max_seq_len=64, sp=False, dropout=0.0)


def _build_state(cfg, seed=3):
    ht.set_seed(seed)
    with ht.graph("eager", create_new=True):
        model = GPTLMHeadModel(cfg)
        model.logits(np.zeros((1, 4), np.int32))
        state = {k: np.asarray(v) for k, v in model.state_dict().items()}
    return state


def _solo(state, cfg, prompt, n_new):
    return np.asarray(generate(state, cfg,
                               np.asarray([prompt], np.int32), n_new,
                               temperature=0.0))[0, len(prompt):].tolist()


def _make_engine(state, cfg, **kw):
    clock = [0.0]
    kw.setdefault("time_fn", lambda: clock[0])
    kw.setdefault("debug", True)
    eng = Engine(state, cfg, **kw)
    eng._test_clock = clock
    return eng


def _drain(eng, check=True):
    guard = 0
    while eng.has_work:
        eng.step()
        eng._test_clock[0] += 1.0
        guard += 1
        assert guard < 500, "engine failed to drain"
        if check:
            eng.pool.check_invariants()


@pytest.fixture(scope="module")
def mla():
    """Learned-position base checkpoint plus its latent conversion
    (d_c=16: a real 4x page compression, NOT full-rank — every serving
    contract below is vs the LATENT solo generate(), the bitwise
    reference the engine must reproduce)."""
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg, seed=3)
    lstate, lcfg = mla_state_from(state, cfg, kv_latent_dim=16)
    return state, cfg, lstate, lcfg


@pytest.fixture(scope="module")
def mla_rot():
    """Rotary base plus latent conversion with a decoupled rope stream
    (d_r=4): pages carry latent + rotated-key sidecars."""
    cfg = GPTConfig(position="rotary", norm="rmsnorm",
                    activation="swiglu", **CFG_KW)
    state = _build_state(cfg, seed=7)
    rstate, rcfg = mla_state_from(state, cfg, kv_latent_dim=16,
                                  kv_rope_dim=4)
    return rstate, rcfg


# ---------------------------------------------------------------------------
# config + converter
# ---------------------------------------------------------------------------


def test_config_validation_and_converter_schema(mla):
    state, cfg, lstate, lcfg = mla
    with pytest.raises(ValueError):
        GPTConfig(kv_rope_dim=8, **CFG_KW)      # rope dim needs MLA
    assert lcfg.is_mla and not cfg.is_mla
    assert lcfg.rope_dim == 0                   # learned: no rope stream
    assert mla_config(cfg, 16).kv_latent_dim == 16
    # weight-absorbed schema replaces the fused qkv per layer
    assert not any(".attn.qkv." in k for k in lstate)
    for i in range(cfg.num_layers):
        assert lstate[f"h{i}.attn.kv_a.weight"].shape == \
            (16, cfg.hidden_size)
        assert lstate[f"h{i}.attn.k_up.weight"].shape == \
            (cfg.num_heads, cfg.head_dim, 16)
        assert lstate[f"h{i}.attn.v_up.weight"].shape == \
            (cfg.num_heads, cfg.head_dim, 16)
    # rotary MLA pins the decoupled rope width
    rcfg = mla_config(GPTConfig(position="rotary", norm="rmsnorm",
                                activation="swiglu", **CFG_KW), 16,
                      kv_rope_dim=4)
    assert rcfg.rope_dim == 4


def test_converter_exact_when_rank_fits_latent(mla):
    """d_c = hidden: the stacked [W_k; W_v] SVD keeps every singular
    value, so the latent model IS the full-head model (fp rounding
    aside) — greedy decodes agree token for token."""
    state, cfg, _, _ = mla
    lstate, lcfg = mla_state_from(state, cfg,
                                  kv_latent_dim=cfg.hidden_size)
    rng = np.random.RandomState(2)
    for n in (5, 13, 22):
        pr = [int(t) for t in rng.randint(1, 90, size=n)]
        assert _solo(lstate, lcfg, pr, 10) == _solo(state, cfg, pr, 10)


# ---------------------------------------------------------------------------
# latent serving: the temp-0 bitwise acceptance trace
# ---------------------------------------------------------------------------


def test_latent_temp0_bitwise_under_pressure(mla):
    """The acceptance criterion: a latent engine on a tiny pool (forces
    recompute eviction, asserted non-vacuous), 4-token chunks, late
    arrivals — bit-for-bit the latent solo generate() run for every
    request."""
    _, _, lstate, lcfg = mla
    prompts = [[5, 17, 2, 9, 33, 12, 8, 1], [1, 1, 4, 44],
               [3, 2, 1, 9, 6, 5, 4]]
    want = [_solo(lstate, lcfg, pr, 10) for pr in prompts]
    eng = _make_engine(lstate, lcfg, num_pages=7, page_size=8,
                       max_batch=4, chunk_size=4)
    assert eng.pool.is_latent
    # d_c * f32 * num_layers (page_bytes spans every layer's stream)
    assert eng.pool.kv_bytes_per_token == 16 * 4 * lcfg.num_layers
    reqs = [eng.add_request(pr, 10, arrival_time=float(2 * i))
            for i, pr in enumerate(prompts)]
    _drain(eng)
    assert eng.counters["preemptions"].value >= 1, \
        "trace should exercise eviction; shrink the pool if not"
    for r, w in zip(reqs, want):
        assert r.out_tokens == w
    assert eng.pool.used_pages == 0
    assert eng.compile_count == 1
    assert eng.host_logit_fetches == 0


def test_latent_rotary_serving_bitwise(mla_rot):
    """Rotary MLA: the decoupled rope sidecar rides the v-page slot and
    serving still matches latent solo decode bit-for-bit."""
    rstate, rcfg = mla_rot
    rng = np.random.RandomState(4)
    prompts = [[int(t) for t in rng.randint(1, 90, size=n)]
               for n in (19, 4, 11)]
    want = [_solo(rstate, rcfg, pr, 6) for pr in prompts]
    eng = _make_engine(rstate, rcfg, num_pages=24, page_size=8,
                       max_batch=4, chunk_size=8)
    assert eng.pool.rope_dim == 4
    assert eng.pool.v_pages[0].shape[-1] == 4
    reqs = [eng.add_request(pr, 6, arrival_time=0.0) for pr in prompts]
    _drain(eng)
    for r, w in zip(reqs, want):
        assert r.out_tokens == w
    assert eng.compile_count == 1


# ---------------------------------------------------------------------------
# composition: prefix-cache CoW on latent pages
# ---------------------------------------------------------------------------


def test_latent_prefix_hit_vs_cold_bitwise(mla):
    """Shared-header burst through (a) a cold latent engine with the
    cache off and (b) a warm latent engine serving the header off
    cached pages: outputs match each other AND latent solo exactly."""
    _, _, lstate, lcfg = mla
    rng = np.random.RandomState(2)
    header = [int(t) for t in rng.randint(1, 90, size=16)]
    prompts = [header + [int(t) for t in rng.randint(1, 90, size=n)]
               for n in (3, 7, 5)]
    want = [_solo(lstate, lcfg, pr, 6) for pr in prompts]
    cold = _make_engine(lstate, lcfg, num_pages=24, page_size=8,
                        max_batch=4, chunk_size=8, prefix_cache=False)
    cold_reqs = [cold.add_request(p, 6, arrival_time=0.0)
                 for p in prompts]
    _drain(cold)
    assert cold.metrics_summary()["prefix_cache_hits"] == 0
    warm = _make_engine(lstate, lcfg, num_pages=24, page_size=8,
                        max_batch=4, chunk_size=8)
    warm.add_request(prompts[0], 6, arrival_time=0.0)
    _drain(warm)
    assert warm.pool.cached_pages > 0
    reqs = [warm.add_request(p, 6, arrival_time=warm._test_clock[0])
            for p in prompts]
    _drain(warm)
    for r, c, w in zip(reqs, cold_reqs, want):
        assert r.out_tokens == w
        assert c.out_tokens == w
    assert all(r.cached_tokens >= 16 for r in reqs)
    assert warm.compile_count == 1


def test_latent_prefix_eviction_and_preemption_pressure(mla):
    """The hard case with the cache ON: a pool small enough to force
    BOTH LRU cache eviction and recompute preemption (each asserted
    non-vacuous), shared headers, late arrivals — still bit-for-bit."""
    _, _, lstate, lcfg = mla
    rng = np.random.RandomState(8)
    header = [int(t) for t in rng.randint(1, 90, size=8)]
    prompts = [header + [int(t) for t in rng.randint(1, 90, size=n)]
               for n in (9, 2, 13, 5)]
    want = [_solo(lstate, lcfg, pr, 8) for pr in prompts]
    eng = _make_engine(lstate, lcfg, num_pages=7, page_size=8,
                       max_batch=3, chunk_size=4)
    eng.add_request(header + prompts[0][8:10], 2, arrival_time=0.0)
    _drain(eng)
    reqs = [eng.add_request(pr, 8, arrival_time=eng._test_clock[0] + i)
            for i, pr in enumerate(prompts)]
    _drain(eng)
    m = eng.metrics_summary()
    assert m["preemptions"] >= 1, \
        "trace should exercise preemption; shrink the pool if not"
    assert m["prefix_cache_evictions"] >= 1, \
        "trace should exercise cache eviction"
    assert m["prefix_cache_hits"] >= 1
    for r, w in zip(reqs, want):
        assert r.out_tokens == w
    assert eng.pool.used_pages == 0


# ---------------------------------------------------------------------------
# composition: speculative decoding verifies on latent pages
# ---------------------------------------------------------------------------


def test_latent_spec_bitwise_vs_nonspec_engine(mla):
    """MLA target + MLA self-draft: spec verify rows ride the latent
    unified step and outputs (greedy AND seeded-sampled rows) equal the
    non-spec latent engine token for token."""
    _, _, lstate, lcfg = mla
    dstate, dcfg = draft_state_from(lstate, lcfg, 1)
    assert dcfg.is_mla
    rng = np.random.RandomState(2)
    prompts = [[int(t) for t in rng.randint(1, 90, size=n)]
               for n in (23, 4, 17)]
    outs = {}
    for spec in (None, SpecConfig(dstate, dcfg, k=3)):
        eng = _make_engine(lstate, lcfg, num_pages=24, page_size=8,
                           max_batch=4, chunk_size=8, spec=spec)
        reqs = [eng.add_request(p, 8, arrival_time=float(2 * i))
                for i, p in enumerate(prompts)]
        sampled = eng.add_request(prompts[0], 8, temperature=0.7,
                                  top_p=0.9, top_k=40, seed=123,
                                  arrival_time=1.0)
        _drain(eng)
        assert eng.host_logit_fetches == 0
        if spec is not None:
            m = eng.metrics_summary()
            assert m["spec_accepted"] > 0, "speculation never engaged"
        outs[spec is None] = [r.out_tokens for r in reqs] + \
            [sampled.out_tokens]
    assert outs[True] == outs[False]


# ---------------------------------------------------------------------------
# composition: disaggregated handoff + adoption on latent pages
# ---------------------------------------------------------------------------


def test_latent_disaggregated_cluster_bitwise(mla):
    """Prefill on one latent replica, pages streamed to a latent decode
    replica, outputs bit-for-bit the monolithic latent engine — and
    every handoff is priced at the LATENT page size."""
    from hetu_tpu.serving.decode import build_unified_step_fn
    _, _, lstate, lcfg = mla
    shape = dict(page_size=8, max_batch=4, chunk_size=8,
                 prefill_rows=1, max_model_len=56)
    fn = build_unified_step_fn(
        lcfg, shape["max_batch"], shape["chunk_size"],
        shape["prefill_rows"],
        -(-shape["max_model_len"] // shape["page_size"]),
        shape["page_size"], use_kernel=False)
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 97, size=n).tolist()
               for n in (26, 18, 12, 22)]
    NEW = 8
    clock = [0.0]
    mono = Engine(lstate, lcfg, num_pages=12, name="mla_mono",
                  debug=True, time_fn=lambda: clock[0], step_fn=fn,
                  **shape)
    for i, p in enumerate(prompts):
        mono.add_request(p, NEW, arrival_time=float(i))
    while mono.has_work:
        mono.step()
        clock[0] += 1.0
    want = {i: list(mono.finished[i].out_tokens)
            for i in range(len(prompts))}
    assert want[0] == _solo(lstate, lcfg, prompts[0], NEW)

    cclock = [0.0]
    cl = EngineCluster(lstate, lcfg, step_fn=fn, num_replicas=2,
                       mode="disaggregated", num_prefill=1,
                       num_pages=12, name="mla_disagg",
                       coordinator=False, debug=True, ttl=3600.0,
                       time_fn=lambda: cclock[0], **shape)
    try:
        reqs = [cl.add_request(p, NEW, arrival_time=float(i))
                for i, p in enumerate(prompts)]
        n = 0
        while cl.has_work:
            cl.step()
            cclock[0] += 1.0
            n += 1
            assert n < 500, "cluster did not drain"
        ms = cl.metrics_summary()
        assert ms["cluster_handoffs"] == len(prompts)
        pb = cl.replicas[0].engine.pool.page_bytes
        # ps * d_c * f32 * layers: handoffs priced at LATENT page size
        assert pb == 8 * 16 * 4 * lcfg.num_layers
        for rec in cl.transport.records:
            assert rec["payload_bytes"] == rec["pages"] * pb
            assert rec["predicted_s"] > 0
        for r in reqs:
            assert r.out_tokens == want[r.req_id], \
                (r.req_id, r.out_tokens, want[r.req_id])
    finally:
        cl.close()


def test_transport_rejects_cross_layout_injection():
    """A latent page stream may not land in a full-head pool (or any
    other layout): inject() raises before touching destination KV."""
    from hetu_tpu.serving.cluster.transport import LocalPageTransport
    lat = PagedKVPool(num_layers=1, num_pages=4, page_size=4,
                      kv_heads=2, head_dim=4, latent_dim=8)
    full = PagedKVPool(num_layers=1, num_pages=4, page_size=4,
                      kv_heads=2, head_dim=4)
    tr = LocalPageTransport()
    staged = tr.extract(lat, lat.alloc(1))
    assert staged["layout"] == lat.layout_tag
    with pytest.raises(ValueError, match="layout mismatch"):
        tr.inject(full, staged, full.alloc(1), 0, 1, epoch=0)
    # same-layout injection lands and is priced at latent page bytes
    lat2 = PagedKVPool(num_layers=1, num_pages=4, page_size=4,
                       kv_heads=2, head_dim=4, latent_dim=8)
    rec = tr.inject(lat2, staged, lat2.alloc(1), 0, 1, epoch=0)
    assert rec["payload_bytes"] == lat.page_bytes


def test_chain_hash_layout_salt_diverges():
    """Layout-salted chain hashes share NO stamps with unsalted (or
    other-layout) hashes — a latent replica's digest can never match a
    full-head replica's prompt pages in the router."""
    toks = list(range(1, 33))
    plain = token_chain_hashes(toks, 8)
    lat = token_chain_hashes(toks, 8, layout=(1, 16, 0, 0, 4))
    full = token_chain_hashes(toks, 8, layout=(0, 4, 8, 0, 4))
    assert not set(plain) & set(lat)
    assert not set(lat) & set(full)
    assert lat == token_chain_hashes(toks, 8, layout=(1, 16, 0, 0, 4))


# ---------------------------------------------------------------------------
# pool layout + quantized pages
# ---------------------------------------------------------------------------


def test_pool_layouts_tags_and_bytes():
    kw = dict(num_layers=2, num_pages=6, page_size=4, kv_heads=2,
              head_dim=8)
    full = PagedKVPool(**kw)
    lat = PagedKVPool(latent_dim=16, **kw)
    rope = PagedKVPool(latent_dim=16, rope_dim=4, **kw)
    q8 = PagedKVPool(latent_dim=16, quant="int8", **kw)
    q4 = PagedKVPool(latent_dim=16, quant="nf4", **kw)
    # every layout gets a distinct tag (the digest salt / decode-cache key)
    tags = [p.layout_tag for p in (full, lat, rope, q8, q4)]
    assert len(set(tags)) == 5
    # page_bytes is THE shared helper applied to the live array shapes
    for p in (full, lat, rope, q8, q4):
        ks, vs = p.page_array_shapes()
        want = sum(page_shape_bytes(s, a.dtype)
                   for s, a in zip(ks, p.k_pages)) + \
            sum(page_shape_bytes(s, a.dtype)
                for s, a in zip(vs, p.v_pages))
        assert p.page_bytes == want
        assert p.kv_bytes_per_token * p.page_size == p.page_bytes
    L = kw["num_layers"]
    assert full.kv_bytes_per_token == 2 * 2 * 8 * 4 * L   # 2 streams
    assert lat.kv_bytes_per_token == 16 * 4 * L
    assert rope.kv_bytes_per_token == (16 + 4) * 4 * L
    assert q8.kv_bytes_per_token == (16 + 4) * L          # codes + scale
    assert q4.kv_bytes_per_token == (8 + 4) * L
    assert q8.k_pages[0].dtype == jnp.int8
    assert q4.k_pages[0].shape[-1] == 8                # packed pairs
    assert q8.v_pages[0].shape[-1] == 1                # absmax sidecar
    # the quant gate: latent-only, rope-free, even width
    with pytest.raises(ValueError):
        PagedKVPool(quant="int8", **kw)                # no latent
    with pytest.raises(ValueError):
        PagedKVPool(latent_dim=16, rope_dim=4, quant="int8", **kw)
    with pytest.raises(ValueError):
        PagedKVPool(latent_dim=15, quant="nf4", **kw)  # odd width


def test_quantize_rows_roundtrip_bounds():
    rng = np.random.RandomState(0)
    x = rng.randn(6, 16).astype(np.float32) * np.asarray(
        [0.1, 1.0, 10.0, 0.01, 3.0, 0.0], np.float32)[:, None]
    for quant, bound in (("int8", 1.0 / 127), ("nf4", 0.18)):
        codes, absmax = quantize_rows(jnp.asarray(x), quant)
        got = np.asarray(dequantize_rows(codes, absmax, quant, 16))
        err = np.abs(got - x).max(-1)
        tol = np.abs(x).max(-1) * bound + 1e-7
        assert (err <= tol).all(), (quant, err, tol)
    assert np.all(got[-1] == 0)                        # zero row exact


def test_quantized_latent_engine_deterministic(mla):
    """int8 latent pages: two fresh engines emit identical tokens (the
    quant path is deterministic end to end); nf4 serves the same trace;
    page_quant without MLA is refused."""
    state, cfg, lstate, lcfg = mla
    rng = np.random.RandomState(5)
    prompts = [[int(t) for t in rng.randint(1, 90, size=n)]
               for n in (14, 6)]
    runs = []
    for _ in range(2):
        eng = _make_engine(lstate, lcfg, num_pages=16, page_size=8,
                           max_batch=2, chunk_size=8, page_quant="int8")
        reqs = [eng.add_request(p, 8, arrival_time=0.0)
                for p in prompts]
        _drain(eng)
        assert eng.pool.quant == "int8"
        runs.append([r.out_tokens for r in reqs])
    assert runs[0] == runs[1]
    assert all(len(t) == 8 for t in runs[0])
    e4 = _make_engine(lstate, lcfg, num_pages=16, page_size=8,
                      max_batch=2, chunk_size=8, page_quant="nf4")
    r4 = [e4.add_request(p, 8, arrival_time=0.0) for p in prompts]
    _drain(e4)
    assert all(len(r.out_tokens) == 8 for r in r4)
    with pytest.raises(ValueError, match="MLA"):
        Engine(state, cfg, num_pages=8, page_size=8, max_batch=2,
               page_quant="int8")


# ---------------------------------------------------------------------------
# latent kernel parity (interpret mode)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("quant,d_r", [(None, 4), (None, 0),
                                       ("int8", 0), ("nf4", 0)])
def test_latent_kernel_matches_reference(quant, d_r):
    """Pallas latent ragged kernel (interpret mode) against the
    gather-dense latent reference: mixed chunks + decodes + padding
    rows, rope sidecar and quantized-page variants."""
    rng = np.random.RandomState(0)
    nh, d_c, num_pages, ps, maxp, max_q = 4, 16, 12, 8, 3, 8
    q_lens, ctx_lens = [1, 5, 0, 6], [13, 10, 0, 6]
    s = len(q_lens)
    cu = np.zeros(s + 1, np.int32)
    cu[1:] = np.cumsum(q_lens)
    t = int(cu[-1])
    q = jnp.asarray(rng.randn(t, nh, d_c + d_r), jnp.float32)
    lat = rng.randn(num_pages, ps, 1, d_c).astype(np.float32)
    scale_pages = None
    if quant:
        codes, absmax = quantize_rows(jnp.asarray(lat), quant)
        c_pages, scale_pages = codes, absmax
    else:
        c_pages = jnp.asarray(lat)
    r_pages = jnp.asarray(rng.randn(num_pages, ps, 1, d_r),
                          jnp.float32) if d_r else None
    perm = rng.permutation(np.arange(1, num_pages))
    pt = np.zeros((s, maxp), np.int32)
    k = 0
    for i in range(s):
        need = -(-ctx_lens[i] // ps)
        pt[i, :need] = perm[k:k + need]
        k += need
    args = (jnp.asarray(np.asarray(q_lens, np.int32)), jnp.asarray(cu),
            jnp.asarray(pt), jnp.asarray(np.asarray(ctx_lens, np.int32)))
    kw = dict(max_q=max_q, softmax_scale=(d_c + d_r) ** -0.5,
              scale_pages=scale_pages, quant=quant, latent_dim=d_c)
    ref = latent_ragged_paged_attention_reference(
        q, c_pages, r_pages, *args, **kw)
    got = latent_ragged_paged_attention_pallas(
        q, c_pages, r_pages, *args, interpret=True, **kw)
    mask = np.zeros(t, bool)
    for i in range(s):
        mask[int(cu[i]):int(cu[i]) + int(q_lens[i])] = True
    np.testing.assert_allclose(np.asarray(got)[mask],
                               np.asarray(ref)[mask],
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


def test_kv_byte_gauges_and_analysis_shapes(mla):
    _, _, lstate, lcfg = mla
    eng = _make_engine(lstate, lcfg, num_pages=8, page_size=8,
                       max_batch=2, chunk_size=8)
    eng.add_request([5, 17, 2, 9, 1, 3, 4, 8, 11], 4, arrival_time=0.0)
    _drain(eng)
    m = eng.metrics_summary()
    assert m["kv_bytes_per_token"] == eng.pool.kv_bytes_per_token == 128
    want = (eng.pool.num_usable - eng.pool.free_pages) * \
        eng.pool.page_bytes
    assert m["kv_bytes_in_use"] == want
    text = eng.metrics_text()
    assert "kv_bytes_per_token" in text and "kv_bytes_in_use" in text
    # analysis/memory classifies latent (and sidecar) page shapes
    from hetu_tpu.analysis.memory import _kv_page_shapes
    shapes = _kv_page_shapes({"pool": eng.pool})
    assert eng.pool.k_pages[0].shape in shapes
    assert eng.pool.v_pages[0].shape in shapes
    q8 = PagedKVPool(num_layers=1, num_pages=4, page_size=4,
                     kv_heads=2, head_dim=4, latent_dim=8, quant="int8")
    shapes = _kv_page_shapes({"pool": q8})
    assert (4, 4, 1, 8) in shapes and (4, 4, 1, 1) in shapes
