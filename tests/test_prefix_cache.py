"""Copy-on-write prefix caching over the paged KV pool (ISSUE 7).

Three layers of coverage:

- **pool partition** — the third page state (cached, read-only,
  refcounted) added to ``PagedKVPool``: legal/illegal transitions, the
  extended ``check_invariants`` partition, the reclaim hook;
- **index mechanics** — chained page hashing (``PrefixCache``):
  longest-prefix match, insertion with dedup, LRU leaf-first eviction,
  refcounts pinning pages against eviction — plus a randomized
  alloc/free/share/evict fuzz trace asserting the invariants at every
  step (no page is ever simultaneously free, allocated, and cached;
  refcounts return to zero);
- **engine contract** — temperature-0 outputs bit-for-bit identical
  between cache-hit and cache-cold runs (late arrivals, eviction
  pressure, and preemption asserted), hit rate 100% for identical
  page-aligned prefixes with ``compile_count <= 2``, prefill charged
  only the uncached suffix, LRU reclaim firing BEFORE recompute
  preemption, and ``reset_metrics`` zeroing the cache counters.
"""
import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.models.generate import generate
from hetu_tpu.serving import Engine, PagedKVPool, PrefixCache, Request

CFG_KW = dict(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
              max_seq_len=64, sp=False, dropout=0.0)


def _build_state(cfg, seed=3):
    ht.set_seed(seed)
    with ht.graph("eager", create_new=True):
        model = GPTLMHeadModel(cfg)
        model.logits(np.zeros((1, 4), np.int32))
        state = {k: np.asarray(v) for k, v in model.state_dict().items()}
    return state


def _solo(state, cfg, prompt, n_new):
    return np.asarray(generate(state, cfg,
                               np.asarray([prompt], np.int32), n_new,
                               temperature=0.0))[0, len(prompt):].tolist()


def _make_engine(state, cfg, **kw):
    clock = [0.0]
    kw.setdefault("time_fn", lambda: clock[0])
    kw.setdefault("debug", True)        # invariant checks on in tests
    eng = Engine(state, cfg, **kw)
    eng._test_clock = clock
    return eng


def _drain(eng):
    while eng.has_work:
        eng.step()
        eng._test_clock[0] += 1.0


def _pool(num_pages=10, page_size=4):
    return PagedKVPool(num_layers=1, num_pages=num_pages,
                       page_size=page_size, kv_heads=1, head_dim=4,
                       debug=True)


def _finished_req(pool, cache, rid, tokens, n_written=None):
    """Drive a fake request through alloc -> write -> on_finish so its
    full pages land in the index (no model involved)."""
    n = len(tokens) if n_written is None else n_written
    req = Request(req_id=rid, prompt=list(tokens), max_new_tokens=1)
    req.pages = pool.alloc(pool.pages_for(len(tokens)))
    assert req.pages is not None
    req.pos = n
    cache.on_finish(req)
    pool.check_invariants()
    cache.check_invariants()
    return req


# ---------------------------------------------------------------------------
# pool: the cached (read-only, refcounted) page state
# ---------------------------------------------------------------------------

class TestPoolCachedState:
    def test_transitions_and_refcounts(self):
        pool = _pool()
        (pg,) = pool.alloc(1)
        assert pool.refcount(pg) == 1           # exclusively owned
        pool.cache_page(pg)
        assert pool.refcount(pg) == 1           # cached, no sharers
        assert pool.cached_pages == 1 and pool.used_pages == 0
        pool.share_page(pg)
        pool.share_page(pg)
        assert pool.refcount(pg) == 3
        with pytest.raises(ValueError):          # still shared: not free
            pool.uncache_page(pg)
        pool.unshare_page(pg)
        pool.unshare_page(pg)
        pool.uncache_page(pg)
        assert pool.refcount(pg) == 0 and pg in pool._free
        pool.check_invariants()

    def test_illegal_transitions_raise(self):
        pool = _pool()
        (pg,) = pool.alloc(1)
        with pytest.raises(ValueError):          # not cached yet
            pool.share_page(pg)
        with pytest.raises(ValueError):
            pool.unshare_page(pg)
        with pytest.raises(ValueError):
            pool.uncache_page(pg)
        pool.cache_page(pg)
        with pytest.raises(ValueError):          # already cached
            pool.cache_page(pg)
        free_pg = pool._free[-1]
        with pytest.raises(ValueError):          # free page can't cache
            pool.cache_page(free_pg)

    def test_invariants_catch_partition_violations(self):
        pool = _pool()
        (pg,) = pool.alloc(1)
        pool.cache_page(pg)
        pool._free.append(pg)                   # corrupt: free AND cached
        with pytest.raises(AssertionError):
            pool.check_invariants()
        pool = _pool()
        (pg,) = pool.alloc(1)
        pool._cached[pg] = 0                    # allocated AND cached
        with pytest.raises(AssertionError):
            pool.check_invariants()

    def test_invariants_opt_in(self):
        """The O(num_pages) rebuild is skipped unless debug/force — a
        corrupted non-debug pool only trips under force=True."""
        pool = PagedKVPool(num_layers=1, num_pages=6, page_size=4,
                           kv_heads=1, head_dim=4)   # debug=False
        (pg,) = pool.alloc(1)
        pool._free.append(pg)                   # free AND allocated
        pool.check_invariants()                 # no-op: opt-in
        with pytest.raises(AssertionError):
            pool.check_invariants(force=True)

    def test_reclaim_hook_runs_before_alloc_fails(self):
        pool = _pool(num_pages=5)
        pages = pool.alloc(4)                   # pool now dry
        for pg in pages:
            pool.cache_page(pg)
        calls = []

        def reclaim(n):
            calls.append(n)
            for pg in pages[:n]:
                pool.uncache_page(pg)
            return n

        pool.set_reclaim(reclaim)
        got = pool.alloc(2)
        assert calls == [2] and got is not None and len(got) == 2
        pool.check_invariants()

    def test_reset_clears_cached_partition(self):
        pool = _pool()
        (pg,) = pool.alloc(1)
        pool.cache_page(pg)
        pool.reset()
        assert pool.cached_pages == 0
        assert pool.free_pages == pool.num_usable
        pool.check_invariants()


# ---------------------------------------------------------------------------
# index mechanics: chained hash, dedup, LRU eviction
# ---------------------------------------------------------------------------

class TestPrefixCacheIndex:
    def test_match_walks_chain_and_stops_at_divergence(self):
        pool = _pool(page_size=4)
        cache = PrefixCache(pool)
        _finished_req(pool, cache, 0, list(range(12)))  # pages 0-3,4-7,8-11
        assert len(cache) == 3
        # full match capped at (len-1)//ps: the last token stays uncached
        assert len(cache.match(list(range(12)))) == 2
        assert len(cache.match(list(range(13)))) == 3
        # divergence mid-chain stops the walk
        toks = list(range(8)) + [99, 99, 99, 99, 0]
        assert len(cache.match(toks)) == 2
        toks = [99] + list(range(1, 13))
        assert cache.match(toks) == []
        # sub-page prompts can never match
        assert cache.match(list(range(4))) == []

    def test_chained_key_rejects_same_page_different_prefix(self):
        """Two sequences sharing page-1 CONTENT but differing in page 0
        must not collide: the parent link chains the whole prefix into
        the key."""
        pool = _pool(page_size=4)
        cache = PrefixCache(pool)
        a = [1, 2, 3, 4, 5, 6, 7, 8, 0]
        b = [9, 9, 9, 9, 5, 6, 7, 8, 0]        # same 2nd page tokens
        _finished_req(pool, cache, 0, a)
        assert len(cache.match(b)) == 0        # page 0 diverges: no hit
        _finished_req(pool, cache, 1, b)
        assert len(cache) == 4                 # both [5,6,7,8] pages live
        assert len(cache.match(a)) == 2
        assert len(cache.match(b)) == 2

    def test_on_finish_dedups_against_existing_entries(self):
        pool = _pool(page_size=4)
        cache = PrefixCache(pool)
        toks = list(range(9))
        _finished_req(pool, cache, 0, toks)
        free_before = pool.free_pages
        _finished_req(pool, cache, 1, toks)    # identical content
        assert len(cache) == 2                 # nothing new inserted
        assert pool.free_pages == free_before  # duplicates+tail freed
        assert pool.cached_pages == 2

    def test_partial_tail_page_is_freed_not_cached(self):
        pool = _pool(page_size=4)
        cache = PrefixCache(pool)
        # 6 written tokens on 2 pages: page 1 only half full
        _finished_req(pool, cache, 0, list(range(6)))
        assert len(cache) == 1 and pool.cached_pages == 1

    def test_acquire_release_pins_against_eviction(self):
        pool = _pool(page_size=4)
        cache = PrefixCache(pool)
        _finished_req(pool, cache, 0, list(range(9)))
        req = Request(req_id=1, prompt=list(range(9)), max_new_tokens=1)
        entries = cache.acquire(req)
        assert [e.depth for e in entries] == [0, 1]
        assert all(e.refs == 1 for e in entries)
        assert cache.evictable_pages == 0
        assert cache.evict(5) == 0             # everything pinned
        cache.release(req)
        assert cache.evictable_pages == 2
        assert cache.evict(5) == 2
        assert pool.cached_pages == 0
        assert pool.free_pages == pool.num_usable
        pool.check_invariants()
        cache.check_invariants()

    def test_lru_evicts_leaf_first_oldest_first(self):
        pool = _pool(num_pages=12, page_size=4)
        cache = PrefixCache(pool)
        a = list(range(9))                     # chain A: 2 pages
        b = [50, 51, 52, 53, 0]                # chain B: 1 page
        _finished_req(pool, cache, 0, a)
        _finished_req(pool, cache, 1, b)
        # touch chain A: B becomes the LRU entry
        req = Request(req_id=2, prompt=a, max_new_tokens=1)
        cache.acquire(req)
        cache.release(req)
        assert cache.evict(1) == 1
        assert cache.match(b) == []            # B went first
        assert len(cache.match(a)) == 2
        # evicting A removes the LEAF (depth 1) before its parent
        assert cache.evict(1) == 1
        assert len(cache.match(a)) == 1
        cache.check_invariants()

    def test_preempted_rerun_releases_then_reacquires(self):
        pool = _pool(page_size=4)
        cache = PrefixCache(pool)
        _finished_req(pool, cache, 0, list(range(9)))
        req = Request(req_id=1, prompt=list(range(9)), max_new_tokens=1)
        e1 = cache.acquire(req)
        cache.release(req)                      # preemption path
        assert all(e.refs == 0 for e in e1)
        e2 = cache.acquire(req)                 # re-start re-pins
        assert [e.eid for e in e1] == [e.eid for e in e2]
        assert all(e.refs == 1 for e in e2)
        cache.release(req)
        cache.check_invariants()


# ---------------------------------------------------------------------------
# randomized fuzz: pool + cache bookkeeping under an adversarial trace
# ---------------------------------------------------------------------------

def test_fuzz_alloc_free_share_evict_invariants_hold():
    """Randomized alloc/free/finish(share-into-cache)/acquire/release/
    evict trace over PagedKVPool + PrefixCache.  After EVERY operation
    the partition invariants hold (no page simultaneously free,
    allocated, and cached); at the end all refcounts return to zero and
    every page returns to the free list."""
    rng = np.random.RandomState(7)
    pool = _pool(num_pages=17, page_size=4)
    cache = PrefixCache(pool)
    pool.set_reclaim(cache.evict)
    live = {}                                  # rid -> Request (allocated)
    holders = {}                               # rid -> Request (acquired)
    next_rid = 0
    for step in range(400):
        op = rng.randint(5)
        if op == 0:                            # start a request
            n_tok = int(rng.randint(1, 14))
            toks = [int(t) for t in rng.randint(0, 6, size=n_tok)]
            req = Request(req_id=next_rid, prompt=toks, max_new_tokens=1)
            next_rid += 1
            entries = cache.acquire(req)
            if entries:
                req.pages = [e.page for e in entries]
                req.shared_pages = len(entries)
                req.pos = len(entries) * pool.page_size
            got = pool.alloc(pool.pages_for(n_tok) - len(req.pages))
            if got is None:                    # rollback, like Engine._start
                cache.release(req)
            else:
                req.pages = req.pages + got
                live[req.req_id] = req
        elif op == 1 and live:                 # finish: insert into cache
            rid = list(live)[rng.randint(len(live))]
            req = live.pop(rid)
            req.pos = int(rng.randint(req.pos,
                                      len(req.pages) * pool.page_size + 1))
            cache.on_finish(req)
        elif op == 2 and live:                 # preempt: free + release
            rid = list(live)[rng.randint(len(live))]
            req = live.pop(rid)
            pool.free(req.pages[req.shared_pages:])
            cache.release(req)
            req.pages = []
            req.shared_pages = 0
        elif op == 3:                          # reader acquires a prefix
            n_tok = int(rng.randint(1, 14))
            toks = [int(t) for t in rng.randint(0, 6, size=n_tok)]
            req = Request(req_id=next_rid, prompt=toks, max_new_tokens=1)
            next_rid += 1
            if cache.acquire(req):
                holders[req.req_id] = req
        elif op == 4:
            if holders and rng.randint(2):     # reader leaves
                rid = list(holders)[rng.randint(len(holders))]
                cache.release(holders.pop(rid))
            else:
                cache.evict(int(rng.randint(1, 4)))
        pool.check_invariants()
        cache.check_invariants()
        # the three states partition: implied by check_invariants, but
        # assert the headline property explicitly
        free = set(pool._free)
        assert not (free & pool._allocated & set(pool._cached))
    for req in list(live.values()):
        pool.free(req.pages[req.shared_pages:])
        cache.release(req)
    for req in list(holders.values()):
        cache.release(req)
    assert cache.evictable_pages == len(cache)  # all refs back to zero
    cache.clear()
    assert len(cache) == 0 and pool.cached_pages == 0
    assert pool.free_pages == pool.num_usable
    pool.check_invariants()
    cache.check_invariants()


# ---------------------------------------------------------------------------
# engine contract: bit-for-bit reuse, hit rate, eviction-before-preemption
# ---------------------------------------------------------------------------

class TestEnginePrefixReuse:
    def test_cache_hit_bit_for_bit_vs_cold_and_solo(self):
        """Identical prompt set through (a) a cold engine with the cache
        disabled and (b) a warm engine serving everything off cached
        pages: outputs match each other AND solo generate() exactly."""
        cfg = GPTConfig(position="rotary", norm="rmsnorm",
                        activation="swiglu", **CFG_KW)
        state = _build_state(cfg, seed=7)
        rng = np.random.RandomState(2)
        header = [int(t) for t in rng.randint(1, 90, size=16)]
        prompts = [header + [int(t) for t in rng.randint(1, 90, size=n)]
                   for n in (3, 7, 5)]
        want = [_solo(state, cfg, pr, 6) for pr in prompts]
        cold = _make_engine(state, cfg, num_pages=24, page_size=8,
                            max_batch=4, chunk_size=8, prefix_cache=False)
        cold_reqs = [cold.add_request(p, 6, arrival_time=0.0)
                     for p in prompts]
        _drain(cold)
        assert cold.metrics_summary()["prefix_cache_hits"] == 0
        warm = _make_engine(state, cfg, num_pages=24, page_size=8,
                            max_batch=4, chunk_size=8)
        warm.add_request(prompts[0], 6, arrival_time=0.0)
        _drain(warm)
        assert warm.pool.cached_pages > 0
        reqs = [warm.add_request(p, 6, arrival_time=warm._test_clock[0])
                for p in prompts]
        _drain(warm)
        for r, c, w in zip(reqs, cold_reqs, want):
            assert r.out_tokens == w
            assert c.out_tokens == w
        assert all(r.cached_tokens >= 16 for r in reqs)
        assert warm.compile_count == 1

    def test_identical_page_aligned_prefix_hit_rate_100(self):
        """The CI pin: replaying identical prompts whose length spans
        full pages hits the cache on EVERY request (hit rate 1.0) and
        the engine still compiles at most 2 executables."""
        cfg = GPTConfig(position="learned", norm="layernorm",
                        activation="gelu", **CFG_KW)
        state = _build_state(cfg, seed=11)
        rng = np.random.RandomState(3)
        prompts = [[int(t) for t in rng.randint(1, 90, size=n)]
                   for n in (16, 24, 17)]       # > page_size each
        eng = _make_engine(state, cfg, num_pages=32, page_size=8,
                           max_batch=4, chunk_size=8)
        reqs = [eng.add_request(p, 4, arrival_time=0.0) for p in prompts]
        _drain(eng)
        want = [list(r.out_tokens) for r in reqs]
        eng.reset_metrics()
        replay = [eng.add_request(p, 4, arrival_time=eng._test_clock[0])
                  for p in prompts]
        _drain(eng)
        m = eng.metrics_summary()
        assert m["prefix_cache_hit_rate"] == 1.0
        assert m["prefix_cache_misses"] == 0
        # every full prompt page is reused: (len-1)//ps pages per prompt
        saved = sum((len(p) - 1) // 8 * 8 for p in prompts)
        assert m["prefix_cache_tokens_saved"] == saved
        assert m["compile_count"] <= 2 and eng.compile_count == 1
        for r, w in zip(replay, want):
            assert r.out_tokens == w

    def test_prefill_charged_only_for_uncached_suffix(self):
        """The scheduler starts prefill chunks at the cached boundary:
        the replay's prefill_tokens counter covers ONLY the uncached
        suffix, and the whole replay takes fewer executable calls."""
        cfg = GPTConfig(position="rotary", norm="rmsnorm",
                        activation="silu", **CFG_KW)
        state = _build_state(cfg, seed=9)
        rng = np.random.RandomState(4)
        prompt = [int(t) for t in rng.randint(1, 90, size=33)]
        eng = _make_engine(state, cfg, num_pages=24, page_size=8,
                           max_batch=2, chunk_size=8)
        eng.add_request(prompt, 4, arrival_time=0.0)
        _drain(eng)
        cold_m = eng.metrics_summary()
        assert cold_m["prefill_tokens"] == 33
        eng.reset_metrics()
        req = eng.add_request(prompt, 4, arrival_time=eng._test_clock[0])
        _drain(eng)
        m = eng.metrics_summary()
        # 33 tokens = 4 full pages + 1; the 4 full pages come cached
        assert req.cached_tokens == 32
        assert m["prefill_tokens"] == 33 - 32
        assert m["executable_calls"] < cold_m["executable_calls"]
        assert req.out_tokens == _solo(state, cfg, prompt, 4)

    def test_lru_reclaim_fires_before_recompute_preemption(self):
        """A full cache and a page-hungry arrival: the pool's reclaim
        hook LRU-evicts cached pages and the request runs WITHOUT any
        recompute preemption."""
        cfg = GPTConfig(position="learned", norm="layernorm",
                        activation="gelu", **CFG_KW)
        state = _build_state(cfg, seed=5)
        rng = np.random.RandomState(6)
        eng = _make_engine(state, cfg, num_pages=7, page_size=8,
                           max_batch=2, chunk_size=8)
        # fill the cache: two disjoint requests retire their pages in
        for n in (16, 17):
            pr = [int(t) for t in rng.randint(1, 90, size=n)]
            eng.add_request(pr, 3, arrival_time=eng._test_clock[0])
            _drain(eng)
        assert eng.pool.cached_pages >= 4
        assert eng.pool.free_pages < 5
        big = [int(t) for t in rng.randint(1, 90, size=30)]
        req = eng.add_request(big, 4, arrival_time=eng._test_clock[0])
        _drain(eng)
        m = eng.metrics_summary()
        assert m["prefix_cache_evictions"] >= 1
        assert m["preemptions"] == 0
        assert req.out_tokens == _solo(state, cfg, big, 4)

    def test_bit_for_bit_under_late_arrival_eviction_and_preemption(self):
        """The hard determinism case with the cache ON: small pool
        (forces BOTH cache eviction and recompute preemption), shared
        headers, late arrivals — every output still matches its solo
        run, and preempted requests re-attach through the cache."""
        cfg = GPTConfig(position="rotary", norm="rmsnorm",
                        activation="swiglu", **CFG_KW)
        state = _build_state(cfg, seed=13)
        rng = np.random.RandomState(8)
        header = [int(t) for t in rng.randint(1, 90, size=8)]
        prompts = [header + [int(t) for t in rng.randint(1, 90, size=n)]
                   for n in (9, 2, 13, 5)]
        want = [_solo(state, cfg, pr, 8) for pr in prompts]
        eng = _make_engine(state, cfg, num_pages=7, page_size=8,
                           max_batch=3, chunk_size=4)
        # warm the header into the cache, then hit it with a late-
        # arriving burst that overflows the 6-page pool
        eng.add_request(header + prompts[0][8:10], 2, arrival_time=0.0)
        _drain(eng)
        reqs = [eng.add_request(pr, 8,
                                arrival_time=eng._test_clock[0] + i)
                for i, pr in enumerate(prompts)]
        _drain(eng)
        m = eng.metrics_summary()
        assert m["preemptions"] >= 1, \
            "trace should exercise preemption; shrink the pool if not"
        assert m["prefix_cache_evictions"] >= 1, \
            "trace should exercise cache eviction"
        assert m["prefix_cache_hits"] >= 1
        for r, w in zip(reqs, want):
            assert r.out_tokens == w
        assert eng.pool.used_pages == 0
        assert eng.compile_count == 1

    def test_reset_metrics_zeroes_cache_counters(self):
        cfg = GPTConfig(position="learned", norm="layernorm",
                        activation="gelu", **CFG_KW)
        state = _build_state(cfg, seed=2)
        rng = np.random.RandomState(1)
        prompt = [int(t) for t in rng.randint(1, 90, size=17)]
        eng = _make_engine(state, cfg, num_pages=16, page_size=8,
                           max_batch=2, chunk_size=8)
        eng.add_request(prompt, 3, arrival_time=0.0)
        _drain(eng)
        eng.add_request(prompt, 3, arrival_time=eng._test_clock[0])
        _drain(eng)
        m = eng.metrics_summary()
        assert m["prefix_cache_hits"] == 1
        assert m["prefix_cache_misses"] == 1
        assert m["prefix_cache_tokens_saved"] == 16
        eng.reset_metrics()
        m = eng.metrics_summary()
        for k in ("prefix_cache_hits", "prefix_cache_misses",
                  "prefix_cache_tokens_saved", "prefix_cache_evictions"):
            assert m[k] == 0, k
        assert m["prefix_cache_hit_rate"] == 0.0
        # live state is NOT metrics: cached pages survive the reset
        assert m["prefix_cache_pages"] == eng.pool.cached_pages > 0

    def test_write_plan_never_targets_shared_pages(self):
        """CoW at the tap level: across a whole shared-header trace, no
        live row's KV write plan resolves to ANY cached page — the same
        property the ``cow-page-write`` analysis rule audits."""
        from hetu_tpu.serving.kv_pool import TRASH_PAGE
        cfg = GPTConfig(position="rotary", norm="rmsnorm",
                        activation="silu", **CFG_KW)
        state = _build_state(cfg, seed=17)
        rng = np.random.RandomState(9)
        header = [int(t) for t in rng.randint(1, 90, size=16)]
        eng = _make_engine(state, cfg, num_pages=16, page_size=8,
                           max_batch=4, chunk_size=8)
        # warm the shared header, then a concurrent burst: three
        # requests write their tails while all READ the cached pages
        eng.add_request(header + [44], 4, arrival_time=0.0)
        _drain(eng)
        for i in range(3):
            tail = [int(t) for t in rng.randint(1, 90, size=3 + i)]
            eng.add_request(header + tail, 4,
                            arrival_time=eng._test_clock[0])
        _drain(eng)
        assert eng.metrics_summary()["prefix_cache_hits"] >= 3
        ps = eng.pool.page_size
        checked = 0
        for rec in eng.tap:
            refs = rec["refcounts"]
            pt = np.asarray(rec["page_tables"])
            for row, pos, qlen in rec["rows"]:
                for t in range(int(qlen)):
                    pg = int(pt[int(row), (int(pos) + t) // ps])
                    if pg != TRASH_PAGE:
                        assert pg not in refs, \
                            f"write plan hit cached page {pg}"
                        checked += 1
        assert checked > 0

    def test_start_rollback_does_not_double_count(self):
        """When _start's residual alloc fails (page race after another
        start's eviction), the request is rolled back and retried — the
        retry is the SAME logical start, so hit/miss/tokens_saved
        counters must not count it twice."""
        cfg = GPTConfig(position="learned", norm="layernorm",
                        activation="gelu", **CFG_KW)
        state = _build_state(cfg, seed=8)
        rng = np.random.RandomState(5)
        prompt = [int(t) for t in rng.randint(1, 90, size=17)]
        eng = _make_engine(state, cfg, num_pages=8, page_size=8,
                           max_batch=2, chunk_size=8)
        eng.add_request(prompt, 3, arrival_time=0.0)
        _drain(eng)
        eng.reset_metrics()
        # pin every free page so the residual alloc must fail: the
        # cached prefix gets acquired, then rolled back
        hold = eng.pool.alloc(eng.pool.free_pages)
        req = eng.add_request(prompt, 3,
                              arrival_time=eng._test_clock[0])
        eng.queue.pop_ready(eng._test_clock[0])
        eng._start(req)
        m = eng.metrics_summary()
        assert req.state != "running" and req.pos == 0
        assert req.shared_pages == 0 and len(eng.queue) == 1
        assert m["prefix_cache_hits"] == 0
        assert m["prefix_cache_misses"] == 0
        assert m["prefix_cache_tokens_saved"] == 0
        eng.pool.free(hold)                 # race resolves: retry runs
        _drain(eng)
        m = eng.metrics_summary()
        assert m["prefix_cache_hits"] == 1
        assert m["prefix_cache_tokens_saved"] == 16
        assert req.out_tokens == _solo(state, cfg, prompt, 3)

    def test_cache_disabled_engine_unchanged(self):
        """prefix_cache=False keeps the PR 6 behavior: no cache object,
        no cached pages, pool drains back to fully free."""
        cfg = GPTConfig(position="learned", norm="layernorm",
                        activation="gelu", **CFG_KW)
        state = _build_state(cfg, seed=4)
        eng = _make_engine(state, cfg, num_pages=8, page_size=8,
                           max_batch=2, prefix_cache=False)
        assert eng.prefix_cache is None
        r = eng.add_request([5, 17, 2, 9, 1, 3, 4, 8, 11], 4,
                            arrival_time=0.0)
        _drain(eng)
        assert eng.pool.cached_pages == 0
        assert eng.pool.free_pages == eng.pool.num_usable
        assert r.out_tokens == _solo(state, cfg, list(r.prompt), 4)
