"""Collective primitive tests on the virtual 8-device mesh.

Validates our XLA-collective mapping of the reference's comm group interface
(``hetu/impl/communication/comm_group.h:27-144``) numerically.
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from hetu_tpu.parallel import comm, create_mesh
from hetu_tpu.parallel.comm import shard_map


def _run(mesh, fn, x, in_spec, out_spec):
    f = shard_map(fn, mesh, (in_spec,), out_spec)
    return jax.jit(f)(x)


class TestCollectives:
    def test_all_reduce(self, devices8):
        mesh = create_mesh({"x": 8}, devices8)
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = _run(mesh, lambda v: comm.all_reduce(v, "x"), x, P("x"), P("x"))
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))

    def test_all_gather(self, devices8):
        mesh = create_mesh({"x": 4}, devices8[:4])
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = _run(mesh, lambda v: comm.all_gather(v, "x", gather_dim=0),
                   x, P("x"), P(None))
        np.testing.assert_allclose(np.asarray(out), x)

    def test_reduce_scatter(self, devices8):
        mesh = create_mesh({"x": 4}, devices8[:4])
        # each shard holds full 4-vector; psum_scatter sums and splits
        x = np.tile(np.arange(4, dtype=np.float32), (4, 1)).reshape(16, 1)
        out = _run(mesh, lambda v: comm.reduce_scatter(v, "x", scatter_dim=0),
                   x, P("x"), P("x"))
        np.testing.assert_allclose(np.asarray(out).ravel(),
                                   np.arange(4, dtype=np.float32) * 4)

    def test_broadcast(self, devices8):
        mesh = create_mesh({"x": 4}, devices8[:4])
        x = np.arange(4, dtype=np.float32).reshape(4, 1)

        out = _run(mesh, lambda v: comm.broadcast(v, "x", root=2),
                   x, P("x"), P("x"))
        np.testing.assert_allclose(np.asarray(out).ravel(), np.full(4, 2.0))

    def test_ring_shift(self, devices8):
        mesh = create_mesh({"x": 4}, devices8[:4])
        x = np.arange(4, dtype=np.float32).reshape(4, 1)
        out = _run(mesh, lambda v: comm.ring_shift(v, "x", 1),
                   x, P("x"), P("x"))
        # shard i receives from i-1
        np.testing.assert_allclose(np.asarray(out).ravel(),
                                   np.array([3.0, 0.0, 1.0, 2.0]))

    def test_all_to_all(self, devices8):
        mesh = create_mesh({"x": 4}, devices8[:4])
        x = np.arange(16, dtype=np.float32).reshape(4, 4)
        # tiled all_to_all transposes the sharded dim: in sharded on dim0,
        # out sharded on dim1; global values unchanged
        out = _run(mesh, lambda v: comm.all_to_all(v, "x", split_dim=1,
                                                   concat_dim=0),
                   x, P("x", None), P(None, "x"))
        np.testing.assert_allclose(np.asarray(out), x)
