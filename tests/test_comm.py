"""Collective primitive tests on the virtual 8-device mesh.

Validates our XLA-collective mapping of the reference's comm group interface
(``hetu/impl/communication/comm_group.h:27-144``) numerically.
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from hetu_tpu.parallel import comm, create_mesh
from hetu_tpu.parallel.comm import shard_map


def _run(mesh, fn, x, in_spec, out_spec):
    f = shard_map(fn, mesh, (in_spec,), out_spec)
    return jax.jit(f)(x)


class TestCollectives:
    def test_all_reduce(self, devices8):
        mesh = create_mesh({"x": 8}, devices8)
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = _run(mesh, lambda v: comm.all_reduce(v, "x"), x, P("x"), P("x"))
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))

    def test_all_gather(self, devices8):
        mesh = create_mesh({"x": 4}, devices8[:4])
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = _run(mesh, lambda v: comm.all_gather(v, "x", gather_dim=0),
                   x, P("x"), P(None))
        np.testing.assert_allclose(np.asarray(out), x)

    def test_reduce_scatter(self, devices8):
        mesh = create_mesh({"x": 4}, devices8[:4])
        # each shard holds full 4-vector; psum_scatter sums and splits
        x = np.tile(np.arange(4, dtype=np.float32), (4, 1)).reshape(16, 1)
        out = _run(mesh, lambda v: comm.reduce_scatter(v, "x", scatter_dim=0),
                   x, P("x"), P("x"))
        np.testing.assert_allclose(np.asarray(out).ravel(),
                                   np.arange(4, dtype=np.float32) * 4)

    def test_broadcast(self, devices8):
        mesh = create_mesh({"x": 4}, devices8[:4])
        x = np.arange(4, dtype=np.float32).reshape(4, 1)

        out = _run(mesh, lambda v: comm.broadcast(v, "x", root=2),
                   x, P("x"), P("x"))
        np.testing.assert_allclose(np.asarray(out).ravel(), np.full(4, 2.0))

    def test_ring_shift(self, devices8):
        mesh = create_mesh({"x": 4}, devices8[:4])
        x = np.arange(4, dtype=np.float32).reshape(4, 1)
        out = _run(mesh, lambda v: comm.ring_shift(v, "x", 1),
                   x, P("x"), P("x"))
        # shard i receives from i-1
        np.testing.assert_allclose(np.asarray(out).ravel(),
                                   np.array([3.0, 0.0, 1.0, 2.0]))

    def test_all_to_all(self, devices8):
        mesh = create_mesh({"x": 4}, devices8[:4])
        x = np.arange(16, dtype=np.float32).reshape(4, 4)
        # tiled all_to_all transposes the sharded dim: in sharded on dim0,
        # out sharded on dim1; global values unchanged
        out = _run(mesh, lambda v: comm.all_to_all(v, "x", split_dim=1,
                                                   concat_dim=0),
                   x, P("x", None), P(None, "x"))
        np.testing.assert_allclose(np.asarray(out), x)


class TestSplitCollectives:
    """Unequal-subgroup split collectives (reference SplitAllReduce /
    SplitAllGather / SplitReduceScatter, ops/Communication.h:655-845) —
    oracle is the dense per-group numpy computation."""

    GROUPS = [[0, 1, 2], [3, 4, 5, 6, 7]]  # unequal 3 + 5

    def test_split_all_reduce_unequal(self, devices8):
        mesh = create_mesh({"x": 8}, devices8)
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = _run(mesh,
                   lambda v: comm.split_all_reduce(v, "x", self.GROUPS),
                   x, P("x"), P("x"))
        expect = np.zeros(8, np.float32)
        for g in self.GROUPS:
            expect[g] = sum(float(i) for i in g)
        np.testing.assert_allclose(np.asarray(out).ravel(), expect)

    def test_split_all_reduce_equal_groups(self, devices8):
        mesh = create_mesh({"x": 8}, devices8)
        groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = _run(mesh, lambda v: comm.split_all_reduce(v, "x", groups),
                   x, P("x"), P("x"))
        np.testing.assert_allclose(np.asarray(out).ravel(),
                                   np.array([6.0] * 4 + [22.0] * 4))

    def test_split_all_gather_unequal(self, devices8):
        mesh = create_mesh({"x": 8}, devices8)
        # each rank holds 2 rows; groups of 3 and 5 -> padded to 5*2 rows
        x = np.arange(16, dtype=np.float32).reshape(16, 1)
        f = shard_map(
            lambda v: comm.split_all_gather(v, "x", 0, self.GROUPS),
            create_mesh({"x": 8}, devices8), (P("x"),), P("x"))
        out = np.asarray(jax.jit(f)(x))          # [8 * 10, 1]
        out = out.reshape(8, 10)
        for g in self.GROUPS:
            rows = np.concatenate(
                [np.arange(2 * r, 2 * r + 2, dtype=np.float32) for r in g])
            for r in g:
                np.testing.assert_allclose(out[r, :len(rows)], rows)
                np.testing.assert_allclose(out[r, len(rows):], 0.0)

    def test_split_reduce_scatter_unequal(self, devices8):
        mesh = create_mesh({"x": 8}, devices8)
        # every rank holds a full 30-vector (divisible by 3 and 5);
        # rank r contributes r everywhere
        L = 30
        x = np.repeat(np.arange(8, dtype=np.float32), L).reshape(8 * L, 1)
        f = shard_map(
            lambda v: comm.split_reduce_scatter(v, "x", 0, self.GROUPS),
            mesh, (P("x"),), P("x"))
        out = np.asarray(jax.jit(f)(x)).reshape(8, -1)  # padded to L//3=10
        for g in self.GROUPS:
            gsum = sum(float(i) for i in g)
            chunk = L // len(g)
            for pos, r in enumerate(g):
                np.testing.assert_allclose(out[r, :chunk], gsum)
                np.testing.assert_allclose(out[r, chunk:], 0.0)

    def test_split_groups_must_partition(self, devices8):
        mesh = create_mesh({"x": 8}, devices8)
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        import pytest
        with pytest.raises(ValueError, match="partition"):
            _run(mesh,
                 lambda v: comm.split_all_reduce(v, "x", [[0, 1], [2, 3]]),
                 x, P("x"), P("x"))


class TestPartialReduce:
    """v1 PartialReduce (preduce.py:8): reduce over the ready subset."""

    def test_partial_mean_subset(self, devices8):
        mesh = create_mesh({"x": 8}, devices8)
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        ready = np.array([1, 0, 1, 1, 0, 0, 1, 0], np.float32).reshape(8, 1)

        def f(v, p):
            return comm.partial_reduce(v, "x", p[0, 0], op="mean")
        g = shard_map(f, mesh, (P("x"), P("x")), P("x"))
        out = np.asarray(jax.jit(g)(x, ready))
        want = (0 + 2 + 3 + 6) / 4.0  # mean over ready ranks
        np.testing.assert_allclose(out, np.full((8, 1), want))

    def test_partial_sum_all_ready_matches_psum(self, devices8):
        mesh = create_mesh({"x": 8}, devices8)
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        ones = np.ones((8, 1), np.float32)

        def f(v, p):
            return comm.partial_reduce(v, "x", p[0, 0], op="sum")
        g = shard_map(f, mesh, (P("x"), P("x")), P("x"))
        out = np.asarray(jax.jit(g)(x, ones))
        np.testing.assert_allclose(out, np.full((8, 1), 28.0))

    def test_partial_mean_none_ready_is_zero(self, devices8):
        mesh = create_mesh({"x": 8}, devices8)
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        zeros = np.zeros((8, 1), np.float32)

        def f(v, p):
            return comm.partial_reduce(v, "x", p[0, 0], op="mean")
        g = shard_map(f, mesh, (P("x"), P("x")), P("x"))
        out = np.asarray(jax.jit(g)(x, zeros))
        np.testing.assert_allclose(out, 0.0)  # count clamped to 1
