"""Test configuration: simulate an 8-device TPU-like mesh on CPU.

This is the multi-device simulation story SURVEY.md §4 calls for: all
DP/TP/PP/CP tests run on XLA's virtual host devices
(``--xla_force_host_platform_device_count=8``) with no hardware.
Must set env vars BEFORE jax initializes its backends.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402
import pytest  # noqa: E402

# jax may already have been imported by a sitecustomize (e.g. the axon TPU
# tunnel) with JAX_PLATFORMS baked in; backend init is lazy, so force the
# platform through the live config as well.
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs[:8]
