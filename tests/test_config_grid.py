"""Config-grid CI: every (dp, tp, pp) ds_parallel_config decomposition of
the 8-device mesh trains with the SAME loss trajectory as its 1-device
counterpart — the reference's ci_test sweep over
``tests/ci_test/ds_parallel_config/gpus8/*.json`` with loss-equivalence,
plus one HETERO layout driven from a hetero config JSON through the MPMD
runtime.

Every config goes through the JSON path (generate -> parse_layout ->
build), exactly like ``train_gpt.py --ds-config``.
"""
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import optim
from hetu_tpu.graph import ctor
from hetu_tpu.models.gpt import llama_config
from hetu_tpu.utils.ds_config import (generate_gpt_3d_config,
                                      generate_gpt_hetero_3d_config,
                                      parse_hetero_layout, parse_layout)

pytestmark = pytest.mark.slow

LAYERS, BATCH, SEQ, VOCAB = 4, 8, 16, 64

# all power-of-two (dp, tp, pp) decompositions of 8 chips with
# pp | LAYERS (the reference grid sweeps gpus8/*.json the same way)
GRID = [(dp, tp, pp)
        for pp in (1, 2, 4)
        for dp in (1, 2, 4, 8)
        for tp in (1, 2, 4, 8)
        if dp * tp * pp == 8 and BATCH % dp == 0]


def _train_from_config(cfg_json, steps=3, seed=4242):
    """The train_gpt --ds-config flow, in process: parse the JSON layout,
    build mesh + model (pipelined when pp > 1), train, return losses."""
    ctor._seed_counter[0] = seed
    import jax
    dp, tp, pp, zero = parse_layout(cfg_json)
    n = dp * tp * pp
    mesh = ht.create_mesh({"pp": pp, "dp": dp, "tp": tp},
                          jax.devices()[:n]) if pp > 1 else (
        ht.create_mesh({"dp": dp, "tp": tp}, jax.devices()[:n])
        if n > 1 else None)
    cfg = llama_config(vocab_size=VOCAB, hidden_size=32, num_layers=LAYERS,
                       num_heads=4, max_seq_len=SEQ, sp=False)
    with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
        ids = ht.parallel_placeholder(
            "int32", (BATCH, SEQ), pspec=P("dp", None) if mesh else None,
            name="ids")
        lbl = ht.parallel_placeholder(
            "int32", (BATCH, SEQ), pspec=P("dp", None) if mesh else None,
            name="lbl")
        if pp > 1:
            from hetu_tpu.models.gpt_pipeline import GPTPipelineModel
            m = GPTPipelineModel(cfg, num_stages=pp)
            loss = m(ids, lbl, num_micro_batches=2)
        else:
            from hetu_tpu.models import GPTLMHeadModel
            m = GPTLMHeadModel(cfg)
            loss = m(ids, lbl)
        op = optim.AdamOptimizer(lr=1e-2, zero=zero).minimize(loss)
        rng = np.random.RandomState(0)
        ids_np = rng.randint(0, VOCAB, (BATCH, SEQ)).astype(np.int32)
        lbl_np = np.roll(ids_np, -1, 1)
        return [float(np.asarray(
            g.run(loss, [loss, op], {ids: ids_np, lbl: lbl_np})[0]))
            for _ in range(steps)]


@pytest.fixture(scope="module")
def baselines():
    """1-device trajectories, one per model class (GPTLMHeadModel for
    pp=1 configs, GPTPipelineModel(num_stages=1) for pp>1 — matching
    init order so losses compare exactly)."""
    out = {}
    out["flat"] = _train_from_config(
        generate_gpt_3d_config(num_layers=LAYERS, dp=1, tp=1, pp=1,
                               zero=False))
    # pipelined-model baseline: same JSON path with pp=1 via the
    # pipelined class
    import jax
    ctor._seed_counter[0] = 4242
    cfg = llama_config(vocab_size=VOCAB, hidden_size=32, num_layers=LAYERS,
                       num_heads=4, max_seq_len=SEQ, sp=False)
    mesh1 = ht.create_mesh({"pp": 1, "dp": 1, "tp": 1}, jax.devices()[:1])
    with ht.graph("define_and_run", create_new=True, mesh=mesh1) as g:
        ids = ht.parallel_placeholder("int32", (BATCH, SEQ), name="ids")
        lbl = ht.parallel_placeholder("int32", (BATCH, SEQ), name="lbl")
        from hetu_tpu.models.gpt_pipeline import GPTPipelineModel
        m = GPTPipelineModel(cfg, num_stages=1)
        loss = m(ids, lbl, num_micro_batches=2)
        op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
        rng = np.random.RandomState(0)
        ids_np = rng.randint(0, VOCAB, (BATCH, SEQ)).astype(np.int32)
        lbl_np = np.roll(ids_np, -1, 1)
        out["pipelined"] = [float(np.asarray(
            g.run(loss, [loss, op], {ids: ids_np, lbl: lbl_np})[0]))
            for _ in range(3)]
    return out


class TestConfigGrid:
    @pytest.mark.parametrize("dp,tp,pp", GRID,
                             ids=[f"dp{d}tp{t}pp{p}" for d, t, p in GRID])
    def test_config_matches_single_device(self, dp, tp, pp, baselines,
                                          devices8):
        cfg_json = generate_gpt_3d_config(num_layers=LAYERS, dp=dp, tp=tp,
                                          pp=pp, zero=(dp > 1))
        got_dp, got_tp, got_pp, got_zero = parse_layout(cfg_json)
        assert (got_dp, got_tp, got_pp) == (dp, tp, pp)
        losses = _train_from_config(cfg_json)
        base = baselines["pipelined" if pp > 1 else "flat"]
        np.testing.assert_allclose(losses, base, rtol=3e-3, atol=1e-4)

    def test_hetero_config_matches_pp1(self, devices8):
        """A hetero layout (unequal per-stage dp x tp and layer counts)
        built FROM the hetero ds-config JSON trains through the MPMD
        runtime with the pp1 trajectory."""
        import jax
        from jax.sharding import Mesh
        from hetu_tpu.models.gpt_mpmd import MPMDGPT
        from hetu_tpu.parallel.pipeline_mpmd import MPMDAdam

        cfg = llama_config(vocab_size=96, hidden_size=48, num_layers=8,
                           num_heads=4, max_seq_len=16, dtype="float32")
        stages = [
            {"dp": 1, "tp": 4, "devices": [0, 1, 2, 3], "layers": [0, 2]},
            {"dp": 2, "tp": 2, "devices": [4, 5, 6, 7], "layers": [3, 7]},
        ]
        cfg_json = generate_gpt_hetero_3d_config(8, stages)
        parsed = parse_hetero_layout(cfg_json)
        assert parsed == stages, parsed

        rng = np.random.RandomState(0)
        ids = rng.randint(0, 96, (8, 16)).astype(np.int32)
        labels = np.roll(ids, -1, 1)

        ref = MPMDGPT(cfg, stage_layers=[[8]], seed=7)
        meshes = [[Mesh(np.array(jax.devices()[:4])[None, :].reshape(
            st["dp"], st["tp"]), ("dp", "tp")) if i == 0 else
            Mesh(np.array(jax.devices()[4:8]).reshape(
                st["dp"], st["tp"]), ("dp", "tp"))
            for i, st in enumerate(parsed)]]
        layer_counts = [st["layers"][1] - st["layers"][0] + 1
                        for st in parsed]
        het = MPMDGPT(cfg, stage_layers=[layer_counts], meshes=meshes,
                      seed=7)
        opt_r = MPMDAdam(ref.runtime, lr=1e-2)
        opt_h = MPMDAdam(het.runtime, lr=1e-2)
        lr_hist, lh_hist = [], []
        for _ in range(3):
            d_r = ref.split_micro_batches(ids, labels, [4])
            d_h = het.split_micro_batches(ids, labels, [4])
            l_r, g_r, _ = ref.train_step(d_r)
            l_h, g_h, _ = het.train_step(d_h)
            lr_hist.append(float(l_r))
            lh_hist.append(float(l_h))
            opt_r.apply(g_r)
            opt_h.apply(g_h)
        np.testing.assert_allclose(lr_hist, lh_hist, rtol=2e-4)
        assert lr_hist[-1] < lr_hist[0]
