"""Runtime trace plane (ISSUE 9): tracer semantics, Perfetto export
schema, Prometheus exposition, percentile interpolation, and the
lint_graph-marked per-request timeline gate.

The timeline gate is the serving contract the trace plane exists to
check: on an ADVERSARIAL trace (late arrivals + recompute preemption +
prefix-cache eviction under a starved page pool, synthetic clock) every
admitted request's ``queued``/``running`` state spans tile
``[submit, finish]`` gaplessly and every event timeline is monotonic —
a scheduling bug that loses a request mid-flight, or an instrumentation
bug that misses a transition, breaks the tiling.
"""
import json

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu import obs
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.obs import (NULL_TRACER, SpanTracer, chrome_trace,
                          events_to_jsonl, get_tracer, install_tracer,
                          reconcile, request_timelines, timeline_summary,
                          trace, validate_chrome_trace, write_jsonl)
from hetu_tpu.serving import Engine
from hetu_tpu.utils.metrics import (Counter, Gauge, Histogram,
                                    load_jsonl, make_instrument,
                                    render_prometheus)

CFG_KW = dict(vocab_size=61, hidden_size=32, num_layers=2, num_heads=4,
              max_seq_len=64, sp=False, dropout=0.0)


@pytest.fixture(scope="module")
def tiny_state():
    cfg = GPTConfig(**CFG_KW)
    ht.set_seed(7)
    with ht.graph("eager", create_new=True):
        model = GPTLMHeadModel(cfg)
        model.logits(np.zeros((1, 4), np.int32))
        state = {k: np.asarray(v) for k, v in model.state_dict().items()}
    return state, cfg


def _traced_engine(state, cfg, **kw):
    clock = [0.0]
    tracer = SpanTracer(time_fn=lambda: clock[0])
    kw.setdefault("time_fn", lambda: clock[0])
    eng = Engine(state, cfg, tracer=tracer, debug=True, **kw)
    return eng, tracer, clock


def _drain(eng, clock, tick=1.0, max_steps=500):
    steps = 0
    while eng.has_work and steps < max_steps:
        eng.step()
        clock[0] += tick
        steps += 1
    assert not eng.has_work, "engine failed to drain the trace"


# ---------------------------------------------------------------------------
# tracer semantics
# ---------------------------------------------------------------------------


def test_span_nesting_and_track_inheritance():
    tr = SpanTracer()
    with tr.span("outer", track="work", a=1) as outer:
        with tr.span("inner") as inner:
            assert inner.parent == "outer"
            assert inner.track == "work"       # inherited
        tr.instant("mark")                     # inherits track too
    assert outer.parent is None
    evs = tr.events()
    assert [e.name for e in evs] == ["inner", "mark", "outer"]
    assert all(e.track == "work" for e in evs)
    assert tr.open_count() == 0
    inner_ev = evs[0]
    outer_ev = evs[-1]
    assert outer_ev.ts <= inner_ev.ts
    assert inner_ev.end_ts <= outer_ev.end_ts + 1e-9


def test_ring_buffer_caps_and_counts_drops():
    tr = SpanTracer(capacity=8)
    for i in range(20):
        tr.instant(f"e{i}")
    evs = tr.events()
    assert len(evs) == 8
    assert tr.dropped == 12
    # oldest dropped, newest kept
    assert [e.name for e in evs] == [f"e{i}" for i in range(12, 20)]
    tr.clear()
    assert tr.events() == [] and tr.dropped == 0


def test_disabled_tracing_is_noop():
    # the shared null tracer records nothing and returns the shared
    # no-op span (no allocation per call)
    sp = NULL_TRACER.span("x", attr=1)
    with sp:
        NULL_TRACER.instant("y")
    assert sp is NULL_TRACER.begin("z")
    assert NULL_TRACER.events() == []
    # a real tracer switched off in place behaves the same without
    # losing its buffer
    tr = SpanTracer()
    tr.instant("kept")
    tr.enabled = False
    with tr.span("dropped"):
        tr.instant("dropped-too")
    tr.complete("dropped-three", 0.0, 1.0)
    assert [e.name for e in tr.events()] == ["kept"]


def test_out_of_order_end_tolerated():
    tr = SpanTracer()
    a = tr.begin("a")
    b = tr.begin("b")
    tr.end(a)          # ends b's scope implicitly, never raises
    assert tr.open_count() == 0
    assert [e.name for e in tr.events()] == ["a"]
    tr.end(b)          # already discarded: recorded as closed event
    assert len(tr.events()) == 2


def test_retroactive_complete_and_explicit_ts():
    tr = SpanTracer(time_fn=lambda: 100.0)
    tr.complete("past", ts=3.0, dur=2.0, track="t", k=1)
    tr.instant("then", ts=5.0, track="t")
    (c, i) = tr.events()
    assert (c.ts, c.dur, c.end_ts) == (3.0, 2.0, 5.0)
    assert i.ts == 5.0 and i.ph == "i"


def test_end_is_idempotent():
    tr = SpanTracer()
    sp = tr.begin("a")
    tr.end(sp)
    tr.end(sp)                 # finally-style re-end: no double commit
    assert len(tr.events()) == 1


def test_traced_run_failure_closes_spans(tiny_state):
    """A raising step must not leave the step span open on the thread
    stack (a retried training loop would otherwise nest every later
    span under the dead step)."""
    _, cfg = tiny_state
    ht.set_seed(0)
    with trace() as tr:
        with ht.graph("define_and_run", create_new=True,
                      prefix="obs_fail") as g:
            from hetu_tpu import optim
            ids = ht.placeholder("int32", (2, 8), name="ids")
            lbl = ht.placeholder("int32", (2, 8), name="lbl")
            model = GPTLMHeadModel(cfg)
            loss = model(ids, lbl)
            train_op = optim.AdamOptimizer(lr=1e-3).minimize(loss)
            data = np.zeros((2, 8), np.int32)
            with pytest.raises(AssertionError):
                # 3 micro-batches don't divide batch 2: raises inside
                # the traced feed phase
                g.run(loss, [loss, train_op], {ids: data, lbl: data},
                      num_micro_batches=3)
            assert tr.open_count() == 0
            g.run(loss, [loss, train_op], {ids: data, lbl: data})
            assert tr.open_count() == 0
    steps = [e for e in tr.events() if e.name in ("train_step",)]
    assert len(steps) == 2                   # failed + succeeded
    # the successful step's children nest under train_step, not under
    # a stale span leaked by the failed one
    ok_exec = [e for e in tr.events() if e.name == "executable"]
    assert len(ok_exec) == 1 and ok_exec[0].parent == "train_step"


def test_clear_executables_evicts_prediction_cache(tiny_state):
    """Retiring an engine (unregister_analysis / same-name rebuild)
    must drop its prediction-cache entry too — the cached handle's meta
    closes over the KV pool and would pin it forever."""
    from hetu_tpu.obs.reconcile import _PRED_CACHE, predicted_stats
    state, cfg = tiny_state
    eng = Engine(state, cfg, num_pages=16, page_size=8, max_batch=2,
                 name="obs_evict")
    assert predicted_stats("obs_evict/unified")["peak_hbm_bytes"] > 0
    assert "obs_evict/unified" in _PRED_CACHE
    eng.unregister_analysis()
    assert "obs_evict/unified" not in _PRED_CACHE


def test_trace_context_installs_and_restores():
    assert get_tracer() is NULL_TRACER
    with trace() as tr:
        assert get_tracer() is tr
        prev = install_tracer(None)
        assert prev is tr and get_tracer() is NULL_TRACER
        install_tracer(tr)
    assert get_tracer() is NULL_TRACER


# ---------------------------------------------------------------------------
# histogram percentile interpolation (satellite)
# ---------------------------------------------------------------------------


def test_percentile_linear_interpolation_pinned():
    h = Histogram("lat")
    for v in (1.0, 2.0, 3.0, 4.0):
        h.observe(v)
    # rank = p/100 * (n-1); linear between floor/ceil ranks
    assert h.percentile(0) == 1.0
    assert h.percentile(100) == 4.0
    assert h.percentile(50) == pytest.approx(2.5)
    assert h.percentile(90) == pytest.approx(3.7)
    assert h.percentile(99) == pytest.approx(3.97)
    # the old int(round(...)) nearest-index would give 3.0 / 4.0 / 4.0
    h2 = Histogram("one")
    h2.observe(5.0)
    assert h2.percentile(90) == 5.0
    assert Histogram("empty").percentile(90) == 0.0


def test_percentile_matches_numpy_linear():
    rng = np.random.RandomState(0)
    xs = rng.rand(37)
    h = Histogram("r")
    for v in xs:
        h.observe(float(v))
    for p in (10, 50, 90, 99):
        assert h.percentile(p) == pytest.approx(
            float(np.percentile(xs, p)), rel=1e-12)


# ---------------------------------------------------------------------------
# Prometheus text exposition (satellite)
# ---------------------------------------------------------------------------


def test_render_prometheus_round_trip():
    c = Counter("tokens_generated")
    c.inc(42)
    g = Gauge("page_utilization")
    g.set(0.625)
    h = Histogram("ttft", buckets=[0.1, 1.0])
    for v in (0.05, 0.5, 2.0, 3.0):
        h.observe(v)
    text = render_prometheus({"tokens_generated": c,
                              "page_utilization": g, "ttft": h})
    lines = [ln for ln in text.splitlines() if ln]
    assert "# TYPE tokens_generated counter" in lines
    assert "tokens_generated 42" in lines
    assert "page_utilization 0.625" in lines
    # histogram triple: cumulative buckets match bucket_counts exactly
    want = h.bucket_counts()
    got = {}
    for ln in lines:
        if ln.startswith("ttft_bucket"):
            le = ln.split('le="')[1].split('"')[0]
            got[le] = int(ln.split()[-1])
    assert got == {"0.1": 1, "1.0": 2, "+Inf": 4}
    assert got["+Inf"] == want["+Inf"] == h.count
    assert f"ttft_count {h.count}" in lines
    assert any(ln.startswith("ttft_sum") for ln in lines)
    # the no-op instrument exposes nothing (not fake zeros)
    assert render_prometheus(
        {"off": make_instrument("counter", "off", enabled=False)}) == ""


def test_engine_metrics_text(tiny_state):
    state, cfg = tiny_state
    eng = Engine(state, cfg, num_pages=16, page_size=8, max_batch=4)
    eng.add_request([5, 9, 2], 3, arrival_time=0.0)
    eng.run()
    text = eng.metrics_text()
    assert "# TYPE tokens_generated counter" in text
    assert "tokens_generated 3" in text
    assert 'ttft_bucket{le="+Inf"} 1' in text
    assert "ttft_count 1" in text
    assert "# TYPE page_utilization gauge" in text


# ---------------------------------------------------------------------------
# chrome trace schema from a real serving run
# ---------------------------------------------------------------------------


def test_chrome_trace_schema_from_serving_run(tiny_state):
    state, cfg = tiny_state
    eng, tracer, clock = _traced_engine(state, cfg, num_pages=16,
                                        page_size=8, max_batch=4)
    rng = np.random.RandomState(1)
    for i in range(3):
        eng.add_request(rng.randint(1, 61, size=5).tolist(), 4,
                        arrival_time=float(i))
    _drain(eng, clock)
    events = tracer.events()
    assert tracer.open_count() == 0          # all spans properly closed
    doc = chrome_trace(events)
    validate_chrome_trace(doc)               # pid/tid/ts/ph on EVERY event
    txt = json.dumps(doc)                    # must be pure-JSON clean
    doc2 = json.loads(txt)
    # per-request tracks present as named thread rows
    names = [ev["args"]["name"] for ev in doc2["traceEvents"]
             if ev["ph"] == "M" and ev["name"] == "thread_name"]
    for i in range(3):
        assert f"req {i}" in names
    assert "engine" in names and "scheduler" in names
    # every request has a complete lifecycle in the trace
    tls = request_timelines(events)
    for i in range(3):
        kinds = [e.name for e in tls[i]]
        assert kinds[0] == "enqueue" and kinds[-1] == "finish"
        assert "queued" in kinds and "running" in kinds \
            and "admit" in kinds and "prefill_chunk" in kinds
        assert sum(1 for k in kinds if k == "token") == 4
    # unified_step spans carry the reconciliation join key + predictions
    un = [e for e in events if e.name == "unified_step"]
    assert un and all(e.attrs["exec"] == "serving/unified" for e in un)
    assert all(e.attrs.get("predicted_peak_hbm_bytes", 0) > 0
               for e in un)
    assert timeline_summary(events)          # renders without error


def test_jsonl_journal_round_trips(tmp_path, tiny_state):
    state, cfg = tiny_state
    eng, tracer, clock = _traced_engine(state, cfg, num_pages=16,
                                        page_size=8, max_batch=2)
    eng.add_request([3, 1, 4], 2, arrival_time=0.0)
    _drain(eng, clock)
    path = str(tmp_path / "journal.jsonl")
    write_jsonl(tracer.events(), path)
    back = load_jsonl(path)                  # utils.metrics reader
    assert len(back) == len(tracer.events())
    assert [r["step"] for r in back] == list(range(len(back)))
    assert all({"name", "track", "ph", "ts", "attrs"} <= set(r)
               for r in back)
    assert events_to_jsonl(tracer.events())[0]["step"] == 0


def test_untraced_engine_stays_silent(tiny_state):
    state, cfg = tiny_state
    eng = Engine(state, cfg, num_pages=16, page_size=8, max_batch=2)
    assert eng.tracer is NULL_TRACER
    eng.add_request([2, 4], 2, arrival_time=0.0)
    eng.run()
    assert NULL_TRACER.events() == []


# ---------------------------------------------------------------------------
# the gapless-timeline CI gate (lint_graph)
# ---------------------------------------------------------------------------


@pytest.mark.lint_graph
def test_adversarial_trace_timelines_gapless(tiny_state):
    """Late arrivals + preemption + prefix-cache eviction under a
    starved pool: every admitted request's state spans must tile
    [submit, finish] with no gap and its event stream must be
    time-monotonic."""
    state, cfg = tiny_state
    eng, tracer, clock = _traced_engine(
        state, cfg, num_pages=10, page_size=4, max_batch=3,
        chunk_size=8, prefill_rows=1, prefix_cache=True)
    rng = np.random.RandomState(2)
    shared = rng.randint(1, 61, size=8).tolist()     # cacheable header
    arrivals = [0.0, 0.0, 2.0, 6.0, 9.0, 13.0]
    for i, at in enumerate(arrivals):
        prompt = shared[:4] + rng.randint(1, 61, size=4).tolist() \
            if i % 2 else shared
        eng.add_request(prompt, 8, arrival_time=at)
    _drain(eng, clock)
    # the trace must actually be adversarial, or the gate is vacuous
    m = eng.metrics_summary()
    assert m["preemptions"] >= 1, "pool never starved: gate is vacuous"
    assert m["prefix_cache_evictions"] >= 1, \
        "cache never evicted: gate is vacuous"
    assert len(eng.finished) == len(arrivals)
    timelines = request_timelines(tracer.events())
    for rid, req in eng.finished.items():
        evs = timelines[rid]
        # monotonic: events ordered by start, intervals inside the life
        ts = [e.ts for e in evs]
        assert ts == sorted(ts), f"req {rid}: non-monotonic timeline"
        assert evs[0].name == "enqueue" and evs[0].ts == req.submit_time
        assert evs[-1].name == "finish" \
            and evs[-1].ts == req.finish_time
        # gapless state tiling: queued/running segments chain exactly
        # from submit to finish (preemptions included)
        segs = [e for e in evs if e.ph == "X"
                and e.name in ("queued", "running")]
        assert segs[0].name == "queued" and segs[0].ts == req.submit_time
        for prev, nxt in zip(segs, segs[1:]):
            assert abs(nxt.ts - prev.end_ts) < 1e-9, \
                f"req {rid}: gap between {prev.name} and {nxt.name}"
            assert prev.name != nxt.name, \
                f"req {rid}: {prev.name} repeated without transition"
        assert segs[-1].name == "running" \
            and abs(segs[-1].end_ts - req.finish_time) < 1e-9
        # lifecycle counters agree with the trace
        assert sum(1 for e in evs if e.name == "preempt") \
            == req.n_preemptions
        assert sum(1 for e in evs if e.name == "token") \
            == req.n_generated
    # scheduler pack decisions stay inside the token budget
    packs = [e for e in tracer.events() if e.name == "pack"]
    assert packs
    for p in packs:
        assert p.attrs["tokens"] <= p.attrs["token_budget"]
        assert p.attrs["decode_slots"] <= eng.scheduler.max_batch
    # cache eviction shows up on the engine track
    assert any(e.name == "prefix_cache_evict" for e in tracer.events())


# ---------------------------------------------------------------------------
# predicted-vs-observed reconciliation
# ---------------------------------------------------------------------------


def test_reconcile_joins_two_executable_families(tiny_state):
    """Serving + a train step traced in one session: the report must
    join observed wall time against the static predictions for BOTH
    executable families (CPU-honest: the HBM column is n/a here)."""
    state, cfg = tiny_state
    with trace() as tr:
        # family 1: the serving unified step (ambient tracer picked up)
        eng = Engine(state, cfg, num_pages=16, page_size=8, max_batch=2,
                     name="obs_serving")
        eng.add_request([7, 3, 9, 1], 3, arrival_time=0.0)
        eng.run()
        # family 2: a train-step plan
        ht.set_seed(0)
        with ht.graph("define_and_run", create_new=True,
                      prefix="obs_train") as g:
            from hetu_tpu import optim
            ids = ht.placeholder("int32", (2, 8), name="ids")
            lbl = ht.placeholder("int32", (2, 8), name="lbl")
            model = GPTLMHeadModel(GPTConfig(**CFG_KW))
            loss = model(ids, lbl)
            opt = optim.AdamOptimizer(lr=1e-3)
            train_op = opt.minimize(loss)
            data = np.random.RandomState(0).randint(
                0, 61, size=(2, 8)).astype(np.int32)
            for _ in range(2):
                g.run(loss, [loss, train_op], {ids: data, lbl: data})
        rep = reconcile(tr.events())
    assert rep.families >= 2
    by_name = {r.executable: r for r in rep.rows}
    srv = by_name["obs_serving/unified"]
    trn = next(r for r in rep.rows if "obs_train" in r.executable)
    assert srv.calls >= 1 and srv.mean_wall_s > 0
    assert trn.calls == 2 and trn.total_wall_s > 0
    # static predictions joined per family
    assert srv.predicted_peak_hbm_bytes > 0
    assert trn.predicted_peak_hbm_bytes > 0
    assert srv.predicted_wire_bytes == 0     # single-device: zero-edge claim
    # CPU honesty: no allocator stats -> explicit n/a, never a fake pass
    assert srv.hbm_check == "n/a" and rep.observed_peak_hbm_bytes == 0
    assert "n/a" in rep.summary()
    # ISSUE 10: the step-time prediction joins as a RATIO-only column —
    # off-TPU the chip-spec model has no absolute meaning, so the table
    # reports wall/pred with no pass/fail verdict
    assert srv.predicted_step_s is not None and srv.predicted_step_s > 0
    assert trn.predicted_step_s is not None and trn.predicted_step_s > 0
    assert srv.wall_ratio == pytest.approx(
        srv.mean_wall_s / srv.predicted_step_s)
    assert trn.predicted_bound in ("compute", "hbm", "comm")
    summary = rep.summary()
    assert "wall/pred" in summary and "RATIO" in summary
    d = rep.to_dict()
    assert len(d["rows"]) == rep.families
    assert d["rows"][0]["predicted_step_s"] is not None
    json.dumps(d)                            # BENCH_OBS-serializable
