"""Data subsystem tests: native prefetch loader vs python path, dp
sharding, GPT datasets, and Hydraulis-style buckets."""
import json

import numpy as np
import pytest

from hetu_tpu.csrc.build import load_dataloader_core
from hetu_tpu.data import (Bucket, Dataloader, GPTJsonDataset, GPTSeqDataset,
                           TensorDataset, build_fake_batch_and_len,
                           get_input_and_label_buckets,
                           get_sorted_batch_and_len)


def _rows(n=32, d=6):
    return np.arange(n * d, dtype=np.int32).reshape(n, d)


class TestDataloader:
    def test_native_core_builds(self):
        assert load_dataloader_core() is not None

    def test_iterates_all_batches(self):
        dl = Dataloader(_rows(), batch_size=8)
        batches = list(dl)
        assert len(batches) == 4 == len(dl)
        got = np.concatenate(batches)
        np.testing.assert_array_equal(np.sort(got[:, 0]), _rows()[:, 0])

    def test_native_path_is_used_and_matches_python(self):
        data = _rows(40)
        nat = Dataloader(data, batch_size=8, use_native=True)
        py = Dataloader(data, batch_size=8, use_native=False)
        assert nat._lib is not None
        a = np.concatenate(list(nat))
        b = np.concatenate(list(py))
        np.testing.assert_array_equal(a, b)  # no shuffle: same order

    def test_shuffle_deterministic_per_seed_and_epoch(self):
        data = _rows(64)
        dl1 = Dataloader(data, batch_size=8, shuffle=True, seed=7)
        dl2 = Dataloader(data, batch_size=8, shuffle=True, seed=7)
        e1a, e2a = list(dl1), list(dl2)
        for x, y in zip(e1a, e2a):
            np.testing.assert_array_equal(x, y)
        # second epoch reshuffles
        e1b = list(dl1)
        assert any((x != y).any() for x, y in zip(e1a, e1b))
        # shuffled set == original set
        got = np.concatenate(e1a)
        np.testing.assert_array_equal(np.sort(got[:, 0]), data[:, 0])

    def test_dp_sharding_disjoint_and_complete(self):
        data = _rows(48)
        shards = []
        for r in range(4):
            dl = Dataloader(data, batch_size=4).set_dp_rank(r, 4)
            shards.append(np.concatenate(list(dl))[:, 0])
        allv = np.concatenate(shards)
        assert len(allv) == 48
        np.testing.assert_array_equal(np.sort(allv), data[:, 0])
        for i in range(4):
            for j in range(i + 1, 4):
                assert not set(shards[i]) & set(shards[j])

    def test_drop_last_and_partial(self):
        data = _rows(30)
        assert len(list(Dataloader(data, batch_size=8))) == 3
        dl = Dataloader(data, batch_size=8, drop_last=False)
        batches = list(dl)
        assert len(batches) == 4 and len(batches[-1]) == 6

    def test_tuple_dataset_python_path(self):
        xs = np.arange(20, dtype=np.float32).reshape(10, 2)
        ys = np.arange(10, dtype=np.int32)
        dl = Dataloader(TensorDataset(xs, ys), batch_size=5)
        for bx, by in dl:
            assert bx.shape == (5, 2) and by.shape == (5,)

    def test_native_prefetch_many_epochs(self):
        """Stress the background thread lifecycle."""
        data = _rows(16)
        dl = Dataloader(data, batch_size=4, shuffle=True, use_native=True)
        for _ in range(5):
            assert len(list(dl)) == 4


class TestGPTDatasets:
    def test_seq_dataset_windows(self):
        toks = np.arange(100)
        ds = GPTSeqDataset(toks, seq_len=16)
        x, y = ds[0]
        np.testing.assert_array_equal(x, np.arange(16))
        np.testing.assert_array_equal(y, np.arange(1, 17))
        x2, y2 = ds[1]
        np.testing.assert_array_equal(x2, np.arange(16, 32))
        mat = ds.as_matrix()
        assert mat.shape == (len(ds), 32)

    def test_seq_dataset_through_native_loader(self):
        ds = GPTSeqDataset(np.arange(1000), seq_len=32)
        dl = Dataloader(ds, batch_size=4, use_native=True)
        for row in dl:
            x, y = row[:, :32], row[:, 32:]
            np.testing.assert_array_equal(x + 1, y)

    def test_json_dataset(self, tmp_path):
        p = tmp_path / "docs.jsonl"
        with open(p, "w") as f:
            for t in ["hello world", "foo bar baz", "x"]:
                f.write(json.dumps({"content": t}) + "\n")
        tok = lambda s: [ord(c) for c in s]  # noqa: E731
        ds = GPTJsonDataset(str(p), "content", seq_len=8, tokenizer=tok,
                            pad_id=0)
        assert len(ds) == 3
        assert ds[0].shape == (8,)
        assert ds[2][0] == ord("x") and ds[2][1] == 0  # padded


class TestBuckets:
    def test_pad_data(self):
        b = Bucket(pad_token=0, max_seqlen=16, alignment=8)
        b.add_data(np.arange(1, 6), 5)
        b.add_data(np.arange(1, 11), 10)
        b.pad_data()
        assert b.padded_batch.shape == (2, 16)
        assert (b.padded_batch[0, 5:] == 0).all()
        np.testing.assert_array_equal(b.padded_cu_seqlens_list[0], [0, 5])

    def test_pack_data_greedy(self):
        b = Bucket(pad_token=0, max_seqlen=32, alignment=8)
        for n in (30, 8, 8, 8, 6):
            b.add_data(np.full(n, 7), n)
        b.pack_data()
        # 30 alone (aligned 32); 8+8+8+6 -> aligned 8*4 = 32 fits one row
        assert b.packed_batch_size == 2
        assert b.packed_batch.shape == (2, 32)
        total_valid = sum((row != 0).sum() for row in b.packed_batch)
        assert total_valid == 30 + 8 + 8 + 8 + 6
        # cu_seqlens aligned and monotone
        for cu in b.packed_cu_seqlens_list:
            assert (np.diff(cu) > 0).all()
            assert (cu[1:-1] % 8 == 0).all()

    def test_pack_with_option_matrix(self):
        b = Bucket(pad_token=0, max_seqlen=32, alignment=8)
        for n in (8, 8, 8):
            b.add_data(np.full(n, 3), n)
        mat = np.array([[1, 0, 1], [0, 1, 0]])
        b.pack_data(mat)
        assert b.packed_batch_size == 2
        assert (b.packed_batch[0] != 0).sum() == 16
        assert (b.packed_batch[1] != 0).sum() == 8

    def test_sorted_batch(self):
        batch, lens = build_fake_batch_and_len([9, 3, 6], pad_token=0)
        sb, sl = get_sorted_batch_and_len(batch, 0)
        np.testing.assert_array_equal(sl, [3, 6, 9])
        assert (sb[0] != 0).sum() == 3

    def test_input_label_buckets(self):
        batch, _ = build_fake_batch_and_len([10, 8], pad_token=0)
        ib, lb = get_input_and_label_buckets(batch, 0, [0, 1], 16,
                                             alignment=4)
        ib.pad_data()
        lb.pad_data()
        # labels are inputs shifted by one
        np.testing.assert_array_equal(ib.padded_batch[0, 1:9],
                                      lb.padded_batch[0, :8])
        np.testing.assert_array_equal(ib.padded_cu_seqlens_list[0], [0, 9])

    def test_too_long_sequence_rejected(self):
        b = Bucket(pad_token=0, max_seqlen=8, alignment=8)
        with pytest.raises(AssertionError, match="exceeds"):
            b.add_data(np.arange(20), 20)

    def test_overfull_option_matrix_rejected(self):
        b = Bucket(pad_token=0, max_seqlen=16, alignment=8)
        for n in (8, 8, 8):
            b.add_data(np.full(n, 3), n)
        with pytest.raises(ValueError, match="exceeds"):
            b.pack_data(np.array([[1, 1, 1]]))

    def test_pad_token_in_vocab_uses_prefix_length(self):
        # a real 0 mid-sequence must not shrink the valid length
        batch = np.array([[5, 0, 7, 3, 0, 0]])  # valid prefix = 4
        sb, sl = get_sorted_batch_and_len(batch, pad_token=0)
        np.testing.assert_array_equal(sl, [4])
        ib, lb = get_input_and_label_buckets(batch, 0, [0], 8, alignment=4)
        ib.pad_data()
        np.testing.assert_array_equal(ib.padded_batch[0, :3], [5, 0, 7])
