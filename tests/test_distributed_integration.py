"""Real multi-process jax.distributed integration.

The reference CI launches N actual worker processes through pssh + gRPC
and trains (`tests/ci_test/scripts/pssh_train_hetu.sh`,
`python/hetu/rpc/pssh_start.py:19`).  Counterpart here: the Launcher
spawns REAL python processes; each bootstraps ``jax.distributed`` through
the coordinator (rendezvous + KV address exchange in
``rpc.coordinator.distributed_init``), forms a global dp mesh (one CPU
device per process, gloo collectives), and trains a tiny data-parallel
model.  The loss trajectory must equal the single-process oracle, and a
worker crash before init must be healed by the launcher restart budget.

Workers run with ``PALLAS_AXON_POOL_IPS=""`` so the axon TPU plugin is
never registered in them (it hijacks every python process otherwise and
wedges distributed init — and worker processes must never dial the TPU
relay anyway).
"""
import json
import os
import sys

import numpy as np
import pytest

from hetu_tpu.rpc.launcher import Launcher

pytestmark = pytest.mark.slow


WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
import numpy as np

rank_env = os.environ["HETU_TPU_WORKER_RANK"]
crash_marker = os.environ.get("ITEST_CRASH_MARKER", "")
if crash_marker and rank_env == "1" and not os.path.exists(crash_marker):
    # simulate a worker lost before distributed init; the launcher's
    # restart budget must revive it and the job must still complete
    open(crash_marker, "w").close()
    sys.exit(1)

from hetu_tpu.rpc.coordinator import distributed_init
addr = os.environ["HETU_TPU_COORDINATOR"]
n = int(os.environ["HETU_TPU_NUM_WORKERS"])
client = distributed_init(addr, num_hosts=n, uid=f"worker-{{rank_env}}")

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

assert jax.process_count() == n, jax.process_count()
assert jax.process_index() == client.rank, (jax.process_index(), client.rank)
devs = jax.devices()
assert len(devs) == n, devs  # one CPU device per process, globally visible

mesh = Mesh(np.array(devs), ("dp",))
rank = client.rank
per = 4
rng = np.random.RandomState(0)
X = rng.randn(per * n, 8).astype(np.float32)
Y = rng.randn(per * n, 1).astype(np.float32)
W0 = rng.randn(8, 1).astype(np.float32)

dsh = NamedSharding(mesh, P("dp"))
Xg = jax.make_array_from_process_local_data(dsh, X[rank * per:(rank + 1) * per])
Yg = jax.make_array_from_process_local_data(dsh, Y[rank * per:(rank + 1) * per])
W = jax.device_put(W0, NamedSharding(mesh, P()))

@jax.jit
def step(W, X, Y):
    l, g = jax.value_and_grad(lambda W: jnp.mean((X @ W - Y) ** 2))(W)
    return l, W - 0.1 * g

losses = []
for _ in range(4):
    l, W = step(W, Xg, Yg)
    losses.append(float(l))   # replicated scalar; grad psum rode gloo

out_dir = os.environ["ITEST_OUT_DIR"]
with open(os.path.join(out_dir, f"losses_{{rank}}.json"), "w") as f:
    json.dump(losses, f)
client.barrier("done", world_size=n, timeout=120)
client.exit()
"""


def _oracle_losses(n, per=4, steps=4):
    rng = np.random.RandomState(0)
    X = rng.randn(per * n, 8).astype(np.float32)
    Y = rng.randn(per * n, 1).astype(np.float32)
    W = rng.randn(8, 1).astype(np.float32)
    losses = []
    for _ in range(steps):
        E = X @ W - Y
        losses.append(float(np.mean(E ** 2)))
        W = W - 0.1 * (2.0 / X.shape[0]) * (X.T @ E)
    return losses


def _run(tmp_path, n, crash=False, max_restarts=0):
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo="/root/repo"))
    env = {
        "PALLAS_AXON_POOL_IPS": "",   # never register the TPU plugin
        "JAX_PLATFORMS": "cpu",
        # override conftest's 8-device flag the pytest process exported:
        # each worker contributes exactly ONE device to the global mesh
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "ITEST_OUT_DIR": str(tmp_path),
    }
    if crash:
        env["ITEST_CRASH_MARKER"] = str(tmp_path / "crashed")
    with Launcher([sys.executable, str(script)], num_workers=n,
                  max_restart_times=max_restarts, env=env) as l:
        ok = l.monitor(poll=0.2, timeout=300)
    losses = []
    for r in range(n):
        p = tmp_path / f"losses_{r}.json"
        assert p.exists(), f"rank {r} left no losses"
        losses.append(json.loads(p.read_text()))
    return ok, losses, l.events


class TestMultiProcessTraining:
    def test_dp_training_matches_single_process(self, tmp_path):
        """4 real processes bootstrap jax.distributed via the coordinator
        and train; every rank's (replicated) loss trajectory equals the
        single-process oracle."""
        n = 4
        ok, losses, _ = _run(tmp_path, n)
        assert ok == n
        oracle = _oracle_losses(n)
        for r in range(n):
            np.testing.assert_allclose(losses[r], oracle, rtol=1e-5,
                                       atol=1e-6)
        assert losses[0][-1] < losses[0][0]   # actually trained

    def test_worker_crash_is_restarted_and_job_completes(self, tmp_path):
        """Rank 1 dies before distributed init; the launcher restarts it
        (uid-keyed rank recycling) and the whole job still trains to the
        oracle trajectory."""
        n = 2
        ok, losses, events = _run(tmp_path, n, crash=True, max_restarts=1)
        assert ok == n
        assert any(e["event"] == "restart" and e["rank"] == 1
                   for e in events), events
        oracle = _oracle_losses(n)
        for r in range(n):
            np.testing.assert_allclose(losses[r], oracle, rtol=1e-5,
                                       atol=1e-6)
