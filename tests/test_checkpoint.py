"""Checkpoint subsystem tests: whole-file, quantized, split (ds-aware),
full model+optimizer checkpoints with resharding, HF converters.

Mirrors the reference's checkpoint capability surface
(python/hetu/utils/checkpoint/ht_safetensors.py:234,446,913,18-35,100).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.utils.checkpoint import (
    save_model, load_model, save_split, load_split,
    save_checkpoint, load_checkpoint,
    hf_gpt2_to_ht, ht_to_hf_gpt2,
    megatron_qkv_to_interleaved, interleaved_qkv_to_megatron)
from hetu_tpu.ops.quantization import (
    quantize_4bit, dequantize_4bit, quantize_int8, dequantize_int8)


def _tiny_cfg(**kw):
    d = dict(vocab_size=96, hidden_size=32, num_layers=2, num_heads=4,
             max_seq_len=16, dropout=0.0, dtype="float32")
    d.update(kw)
    return GPTConfig(**d)


class TestQuantization:
    def test_nf4_roundtrip_accuracy(self):
        rng = np.random.RandomState(0)
        x = rng.randn(64, 64).astype(np.float32) * 0.02
        packed, absmax = quantize_4bit(x, "nf4", blocksize=64)
        back = np.asarray(dequantize_4bit(packed, absmax, x.shape, "nf4", 64))
        assert back.shape == x.shape
        # nf4 quantization error should be small relative to scale
        err = np.abs(back - x).mean() / (np.abs(x).mean() + 1e-8)
        assert err < 0.2

    def test_fp4_roundtrip_shape(self):
        x = np.random.RandomState(1).randn(33, 17).astype(np.float32)
        packed, absmax = quantize_4bit(x, "fp4", blocksize=64)
        back = np.asarray(dequantize_4bit(packed, absmax, x.shape, "fp4", 64))
        assert back.shape == x.shape
        assert np.corrcoef(back.ravel(), x.ravel())[0, 1] > 0.9

    def test_int8_roundtrip(self):
        x = np.random.RandomState(2).randn(100).astype(np.float32)
        q, absmax = quantize_int8(x, blocksize=256)
        back = np.asarray(dequantize_int8(q, absmax, x.shape, 256))
        assert np.abs(back - x).max() < 0.05


class TestSaveLoadModel:
    def test_roundtrip(self, tmp_path):
        with ht.graph("define_and_run", create_new=True):
            model = GPTLMHeadModel(_tiny_cfg())
            ids = ht.placeholder("int32", (2, 16))
            model.logits(ids)  # build graph so params materialize
            state0 = model.state_dict()
            save_model(model, str(tmp_path / "m.safetensors"))
            # perturb then load back
            for n, p in model.named_parameters():
                p.graph.reset_variable(p, np.zeros(p.shape, np.float32))
            load_model(model, str(tmp_path / "m.safetensors"))
            state1 = model.state_dict()
        for k in state0:
            np.testing.assert_allclose(np.asarray(state0[k], np.float32),
                                       np.asarray(state1[k], np.float32),
                                       rtol=1e-6, atol=1e-6)

    def test_quantized_save(self, tmp_path):
        with ht.graph("define_and_run", create_new=True):
            model = GPTLMHeadModel(_tiny_cfg())
            ids = ht.placeholder("int32", (2, 16))
            model.logits(ids)
            state0 = model.state_dict()
            save_model(model, str(tmp_path / "q.safetensors"), quantize="nf4")
            load_model(model, str(tmp_path / "q.safetensors"))
            state1 = model.state_dict()
        # 4-bit roundtrip: correlated, not exact
        w0 = np.asarray(state0["transformer.wte.weight"], np.float32)
        w1 = np.asarray(state1["transformer.wte.weight"], np.float32)
        assert np.corrcoef(w0.ravel(), w1.ravel())[0, 1] > 0.98

    def test_bf16_transfer_save(self, tmp_path):
        with ht.graph("define_and_run", create_new=True):
            model = GPTLMHeadModel(_tiny_cfg())
            ids = ht.placeholder("int32", (2, 16))
            model.logits(ids)
            save_model(model, str(tmp_path / "b.safetensors"),
                       dtype="bfloat16")
            load_model(model, str(tmp_path / "b.safetensors"))


class TestSplit:
    def test_numshard_roundtrip(self, tmp_path):
        state = {"a": np.arange(24, dtype=np.float32).reshape(6, 4),
                 "b": np.float32(3.5) * np.ones((3,), np.float32),
                 "scalar": np.array(7, np.int32)}
        save_split(state, str(tmp_path / "ck"), num_shards=4)
        back = load_split(str(tmp_path / "ck"))
        for k in state:
            np.testing.assert_array_equal(back[k], state[k])

    def test_sharded_jax_array_save(self, tmp_path, devices8):
        mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
        x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        xs = jax.device_put(x, NamedSharding(mesh, P("dp", "tp")))
        save_split({"w": xs}, str(tmp_path / "ck"))
        back = load_split(str(tmp_path / "ck"))
        np.testing.assert_array_equal(back["w"], np.asarray(x))

    def test_reshard_on_load(self, tmp_path, devices8):
        # save under one layout, load under another
        mesh_a = Mesh(np.array(devices8).reshape(8, 1), ("dp", "tp"))
        x = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
        xa = jax.device_put(x, NamedSharding(mesh_a, P("dp", None)))
        save_split({"w": xa}, str(tmp_path / "ck"))
        back = load_split(str(tmp_path / "ck"))
        mesh_b = Mesh(np.array(devices8).reshape(2, 4), ("dp", "tp"))
        xb = jax.device_put(jnp.asarray(back["w"]),
                            NamedSharding(mesh_b, P(None, "tp")))
        np.testing.assert_array_equal(np.asarray(xb), np.asarray(x))


class TestFullCheckpoint:
    def test_model_opt_roundtrip(self, tmp_path):
        with ht.graph("define_and_run", create_new=True) as g:
            cfg = _tiny_cfg()
            model = GPTLMHeadModel(cfg)
            ids = ht.placeholder("int32", (2, 16))
            labels = ht.placeholder("int32", (2, 16))
            loss = model(ids, labels)
            opt = ht.optim.AdamOptimizer(lr=1e-3)
            train_op = opt.minimize(loss)
            rng = np.random.RandomState(0)
            feed = {ids: rng.randint(0, 96, (2, 16)),
                    labels: rng.randint(0, 96, (2, 16))}
            for _ in range(2):
                g.run(loss, [loss, train_op], feed)
            state0 = model.state_dict()
            m0 = {k: np.asarray(jax.device_get(v)) for k, v in
                  (opt._state.get("m") or {}).items()}
            save_checkpoint(model, opt, str(tmp_path / "full"), step=2)

            # wreck state, then restore
            for n, p in model.named_parameters():
                p.graph.reset_variable(p, np.zeros(p.shape, np.float32))
            opt._state = {}
            ts = load_checkpoint(model, opt, str(tmp_path / "full"))
            assert ts["step"] == 2
            state1 = model.state_dict()
            for k in state0:
                np.testing.assert_allclose(
                    np.asarray(state0[k], np.float32),
                    np.asarray(state1[k], np.float32), rtol=1e-6, atol=1e-6)
            assert "m" in opt._state and len(opt._state["m"]) == len(m0)
            for tid, arr in m0.items():
                np.testing.assert_allclose(
                    np.asarray(jax.device_get(opt._state["m"][tid])), arr,
                    rtol=1e-6, atol=1e-6)
            # training continues after restore
            g.run(loss, [loss, train_op], feed)


class TestConverters:
    def test_megatron_interleave_roundtrip(self):
        nh, hd, hid = 4, 8, 32
        w = np.random.RandomState(0).randn(3 * nh * hd, hid).astype(np.float32)
        inter = megatron_qkv_to_interleaved(w, nh)
        back = interleaved_qkv_to_megatron(inter, nh)
        np.testing.assert_array_equal(back, w)

    def test_hf_gpt2_roundtrip(self):
        h, nh, L, V, S = 32, 4, 2, 96, 16
        rng = np.random.RandomState(0)
        hf = {"transformer.wte.weight": rng.randn(V, h).astype(np.float32),
              "transformer.wpe.weight": rng.randn(S, h).astype(np.float32),
              "transformer.ln_f.weight": np.ones(h, np.float32),
              "transformer.ln_f.bias": np.zeros(h, np.float32)}
        for i in range(L):
            p = f"transformer.h.{i}"
            hf[f"{p}.ln_1.weight"] = np.ones(h, np.float32)
            hf[f"{p}.ln_1.bias"] = np.zeros(h, np.float32)
            hf[f"{p}.ln_2.weight"] = np.ones(h, np.float32)
            hf[f"{p}.ln_2.bias"] = np.zeros(h, np.float32)
            hf[f"{p}.attn.c_attn.weight"] = rng.randn(h, 3 * h).astype(
                np.float32)
            hf[f"{p}.attn.c_attn.bias"] = rng.randn(3 * h).astype(np.float32)
            hf[f"{p}.attn.c_proj.weight"] = rng.randn(h, h).astype(np.float32)
            hf[f"{p}.attn.c_proj.bias"] = rng.randn(h).astype(np.float32)
            hf[f"{p}.mlp.c_fc.weight"] = rng.randn(h, 4 * h).astype(
                np.float32)
            hf[f"{p}.mlp.c_fc.bias"] = rng.randn(4 * h).astype(np.float32)
            hf[f"{p}.mlp.c_proj.weight"] = rng.randn(4 * h, h).astype(
                np.float32)
            hf[f"{p}.mlp.c_proj.bias"] = rng.randn(h).astype(np.float32)
        ht_state = hf_gpt2_to_ht(hf)
        assert ht_state["transformer.h.0.attn.qkv.weight"].shape == (3 * h, h)
        back = ht_to_hf_gpt2(ht_state)
        for k, v in hf.items():
            np.testing.assert_allclose(back[k], v, rtol=1e-6)

    def test_hf_load_into_model(self, tmp_path):
        """An hf-converted state dict loads into the real model."""
        with ht.graph("define_and_run", create_new=True):
            cfg = _tiny_cfg(activation="gelu", norm="layernorm",
                            position="learned", tie_embeddings=True)
            model = GPTLMHeadModel(cfg)
            ids = ht.placeholder("int32", (2, 16))
            model.logits(ids)
            state = model.state_dict()
            hf = ht_to_hf_gpt2(state)
            ht_state = hf_gpt2_to_ht(hf)
            missing, unexpected = model.load_state_dict(ht_state,
                                                        strict=False)
        assert not [m for m in missing if "wpe" not in m]


class TestAsyncSave:
    def test_async_roundtrip_sharded(self, tmp_path, devices8):
        from hetu_tpu.utils.checkpoint import save_split_async
        mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
        x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        xs = jax.device_put(x, NamedSharding(mesh, P("dp", "tp")))
        h = save_split_async({"w": xs}, str(tmp_path / "ck"))
        h.wait(timeout=60)
        assert h.done()
        back = load_split(str(tmp_path / "ck"))
        np.testing.assert_array_equal(back["w"], np.asarray(x))

    def test_async_snapshot_survives_donation(self, tmp_path):
        """The snapshot is taken before returning: donating the buffer
        right after the call must not corrupt the checkpoint."""
        from hetu_tpu.utils.checkpoint import save_split_async
        x = jnp.arange(32, dtype=jnp.float32)
        h = save_split_async({"w": x}, str(tmp_path / "ck"))
        # donate + overwrite x's buffer immediately
        f = jax.jit(lambda v: v * 0 - 1, donate_argnums=0)
        jax.block_until_ready(f(x))
        h.wait(timeout=60)
        back = load_split(str(tmp_path / "ck"))
        np.testing.assert_array_equal(back["w"],
                                      np.arange(32, dtype=np.float32))

    def test_async_numshard_and_error_surfacing(self, tmp_path):
        from hetu_tpu.utils.checkpoint import save_split_async
        state = {"a": np.arange(24, dtype=np.float32).reshape(6, 4)}
        h = save_split_async(state, str(tmp_path / "ck"), num_shards=2)
        h.wait(timeout=60)
        back = load_split(str(tmp_path / "ck"))
        np.testing.assert_array_equal(back["a"], state["a"])
        # a writer-thread failure (unserializable dtype) surfaces on wait()
        import pytest
        h2 = save_split_async({"bad": np.array([object()], dtype=object)},
                              str(tmp_path / "ck2"))
        with pytest.raises(BaseException):
            h2.wait(timeout=60)


def test_background_checkpoint_roundtrip(tmp_path):
    """save_checkpoint(background=True): training continues while the
    writer thread archives; the checkpoint matches the snapshot."""
    with ht.graph("define_and_run", create_new=True) as g:
        cfg = _tiny_cfg()
        model = GPTLMHeadModel(cfg)
        ids = ht.placeholder("int32", (2, 16))
        labels = ht.placeholder("int32", (2, 16))
        loss = model(ids, labels)
        opt = ht.optim.AdamOptimizer(lr=1e-2)
        train_op = opt.minimize(loss)
        rng = np.random.RandomState(0)
        feed = {ids: rng.randint(0, 96, (2, 16)),
                labels: rng.randint(0, 96, (2, 16))}
        g.run(loss, [loss, train_op], feed)
        snap = {k: np.asarray(v, np.float32)
                for k, v in model.state_dict().items()}
        h = save_checkpoint(model, opt, str(tmp_path / "bg"), step=1,
                            background=True)
        # keep training while the writer runs (params update underneath)
        for _ in range(3):
            g.run(loss, [loss, train_op], feed)
        h.wait(timeout=120)
        for n, p in model.named_parameters():
            p.graph.reset_variable(p, np.zeros(p.shape, np.float32))
        ts = load_checkpoint(model, opt, str(tmp_path / "bg"))
        assert ts["step"] == 1
        state1 = model.state_dict()
        for k in snap:
            np.testing.assert_allclose(
                snap[k], np.asarray(state1[k], np.float32),
                rtol=1e-6, atol=1e-6)


def test_resave_into_existing_dir_drops_stale_marker(tmp_path):
    """Re-saving over an old checkpoint must remove the previous commit
    marker before tensor data changes (crash-safety contract)."""
    import os
    with ht.graph("define_and_run", create_new=True) as g:
        cfg = _tiny_cfg()
        model = GPTLMHeadModel(cfg)
        ids = ht.placeholder("int32", (2, 16))
        labels = ht.placeholder("int32", (2, 16))
        loss = model(ids, labels)
        opt = ht.optim.AdamOptimizer(lr=1e-3)
        train_op = opt.minimize(loss)
        rng = np.random.RandomState(0)
        feed = {ids: rng.randint(0, 96, (2, 16)),
                labels: rng.randint(0, 96, (2, 16))}
        g.run(loss, [loss, train_op], feed)
        d = str(tmp_path / "re")
        save_checkpoint(model, opt, d, step=1)
        assert os.path.exists(os.path.join(d, "trainer_state.json"))
        g.run(loss, [loss, train_op], feed)
        h = save_checkpoint(model, opt, d, step=2, background=True)
        h.wait(timeout=120)
        ts = load_checkpoint(model, opt, d)
        assert ts["step"] == 2


def test_sgd_checkpoint_without_step_backfills(tmp_path):
    """Pre-step-counter SGD checkpoints (no 'step' state) must restore
    and keep training (the counter is backfilled, not KeyError'd)."""
    with ht.graph("define_and_run", create_new=True) as g:
        cfg = _tiny_cfg()
        model = GPTLMHeadModel(cfg)
        ids = ht.placeholder("int32", (2, 16))
        labels = ht.placeholder("int32", (2, 16))
        loss = model(ids, labels)
        opt = ht.optim.SGDOptimizer(lr=0.1, momentum=0.9)
        train_op = opt.minimize(loss)
        rng = np.random.RandomState(0)
        feed = {ids: rng.randint(0, 96, (2, 16)),
                labels: rng.randint(0, 96, (2, 16))}
        g.run(loss, [loss, train_op], feed)
        # simulate a legacy restore: state with velocity but NO step
        opt._state.pop("step", None)
        out = g.run(loss, [loss, train_op], feed)
        assert np.isfinite(float(np.asarray(out[0])))
        assert "step" in opt._state


class TestFlatStateCheckpoint:
    """Flat dp-sharded optimizer state (flat_state=True) checkpoints are
    PER-PARAMETER keyed through the param->(offset, length) index, so
    they interchange with flat_state=False and across dp sizes — both
    directions asserted by continuing training and matching the loss
    curve exactly (fp32 flat math == per-param math)."""

    def _train(self, devices8, flat, steps, load_from=None, dp=8,
               zero=2, opt_cls=None, **opt_kw):
        from hetu_tpu.graph import ctor
        from hetu_tpu.models import GPTLMHeadModel, llama_config
        from hetu_tpu.parallel import create_mesh
        ctor._seed_counter[0] = 777        # identical init across runs
        mesh = create_mesh({"dp": dp}, devices8[:dp])
        cfg = llama_config(vocab_size=64, hidden_size=32, num_layers=1,
                           num_heads=4, max_seq_len=16, sp=False)
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            ids = ht.parallel_placeholder("int32", (8, 16),
                                          pspec=P("dp", None), name="ids")
            labels = ht.parallel_placeholder("int32", (8, 16),
                                             pspec=P("dp", None),
                                             name="labels")
            model = GPTLMHeadModel(cfg)
            loss = model(ids, labels)
            opt = (opt_cls or ht.optim.AdamOptimizer)(
                lr=1e-2, zero=zero, grad_comm="fp32", flat_state=flat,
                **opt_kw)
            train_op = opt.minimize(loss)
            if load_from is not None:
                from hetu_tpu.utils.checkpoint import load_checkpoint
                load_checkpoint(model, opt, load_from)
            rng = np.random.RandomState(0)
            IDS = rng.randint(0, 64, (8, 16)).astype(np.int32)
            feed = {ids: IDS, labels: np.roll(IDS, -1, axis=1)}
            losses = []
            for _ in range(steps):
                out = g.run(loss, [loss, train_op], feed)
                losses.append(float(np.asarray(out[0])))
            assert g._grad_comm_active, g._grad_comm_fallback
            return losses, model, opt, g

    def test_flat_to_per_param_roundtrip(self, devices8, tmp_path):
        # flat trains 2 + 2 steps; the 2-step checkpoint restores into a
        # flat_state=False optimizer whose continuation matches exactly
        _, model, opt, _ = self._train(devices8, flat=True, steps=2)
        d = str(tmp_path / "flat_ck")
        save_checkpoint(model, opt, d, step=2)
        ref, _, _, _ = self._train(devices8, flat=True, steps=4)
        cont, _, opt2, _ = self._train(devices8, flat=False, steps=2,
                                       load_from=d)
        np.testing.assert_allclose(cont, ref[2:], rtol=1e-6)
        # the per-param reader got real momentum, not zeros
        assert any(float(np.abs(np.asarray(jax.device_get(a))).max()) > 0
                   for a in opt2._state["m"].values())

    def test_per_param_to_flat_roundtrip(self, devices8, tmp_path):
        _, model, opt, _ = self._train(devices8, flat=False, steps=2)
        d = str(tmp_path / "pp_ck")
        save_checkpoint(model, opt, d, step=2)
        ref, _, _, _ = self._train(devices8, flat=False, steps=4)
        cont, _, opt2, _ = self._train(devices8, flat=True, steps=2,
                                       load_from=d)
        np.testing.assert_allclose(cont, ref[2:], rtol=1e-6)
        # the graft landed in the packed buffers, not a fresh zero init
        lay = opt2._flat_layout
        assert lay is not None
        m = lay.unpack(opt2._state["flat_m"])
        assert any(float(np.abs(np.asarray(v)).max()) > 0
                   for v in m.values())

    def test_flat_checkpoint_across_dp_sizes(self, devices8, tmp_path):
        """dp=8 flat checkpoint restores into a dp=4 flat run: chunk
        geometry differs, the per-param index bridges it (equal-size
        shards mean the loss curve continues identically)."""
        _, model, opt, _ = self._train(devices8, flat=True, steps=2)
        d = str(tmp_path / "dp8_ck")
        save_checkpoint(model, opt, d, step=2)
        ref, _, _, _ = self._train(devices8, flat=True, steps=4)
        cont, _, opt4, _ = self._train(devices8, flat=True, steps=2,
                                       load_from=d, dp=4)
        np.testing.assert_allclose(cont, ref[2:], rtol=1e-6)
        assert opt4._flat_layout.device_num == 4

    def test_stale_master_never_survives_per_param_training(
            self, devices8, tmp_path):
        """flat save -> per-param restore -> train -> save -> flat
        restore must continue from the TRAINED params.  SGD's
        ``dict(opt_state)`` carry would otherwise keep the restored
        fp32 master riding through per-param steps, and the second flat
        restore would silently revert the weights to the first
        checkpoint (regression: _ensure_state now drops the slot)."""
        import hetu_tpu.optim as optim_mod
        sgd = optim_mod.SGDOptimizer
        _, model, opt, _ = self._train(devices8, flat=True, steps=2,
                                       opt_cls=sgd, momentum=0.9)
        d1 = str(tmp_path / "s1")
        save_checkpoint(model, opt, d1, step=2)
        # per-param continuation, 2 steps, then re-save
        _, model2, opt2, _ = self._train(devices8, flat=False, steps=2,
                                         load_from=d1, opt_cls=sgd,
                                         momentum=0.9)
        assert "master" not in opt2._state     # dropped at first use
        d2 = str(tmp_path / "s2")
        save_checkpoint(model2, opt2, d2, step=4)
        assert not any(k.startswith("opt.master.")
                       for k in load_split(d2))
        # reference: uninterrupted flat run; flat restore of the
        # re-saved checkpoint continues it (no weight reversion)
        ref, _, _, _ = self._train(devices8, flat=True, steps=6,
                                   opt_cls=sgd, momentum=0.9)
        cont, _, _, _ = self._train(devices8, flat=True, steps=2,
                                    load_from=d2, opt_cls=sgd,
                                    momentum=0.9)
        np.testing.assert_allclose(cont, ref[4:], rtol=1e-6)

    def test_zero3_checkpoint_roundtrips_through_per_param(
            self, devices8, tmp_path):
        """flat ZeRO-3 -> per-param -> flat ZeRO-2: the params-sharded-
        at-rest checkpoint is per-parameter keyed like every other, so
        it chains through any reader and the loss curve never forks
        (save-time ``get_tensor_value`` refreshes the stale working
        params from the flat master first)."""
        _, model, opt, _ = self._train(devices8, flat=True, steps=2,
                                       zero=3)
        d1 = str(tmp_path / "z3_ck")
        save_checkpoint(model, opt, d1, step=2)
        state = load_split(d1)
        assert not any("flat_" in k for k in state)
        ref, _, _, _ = self._train(devices8, flat=True, steps=6, zero=3)
        # hop 1: per-param reader continues the curve
        _, model2, opt2, _ = self._train(devices8, flat=False, steps=2,
                                         zero=0, load_from=d1)
        d2 = str(tmp_path / "pp_ck")
        save_checkpoint(model2, opt2, d2, step=4)
        # hop 2: flat ZeRO-2 reader continues from the re-save
        cont, _, _, _ = self._train(devices8, flat=True, steps=2,
                                    zero=2, load_from=d2)
        np.testing.assert_allclose(cont, ref[4:], rtol=1e-6)

    def test_zero3_dp8_checkpoint_restores_at_dp4(self, devices8,
                                                  tmp_path):
        """A dp=8 ZeRO-3 checkpoint restores into dp=4 runs: chunk
        quantization differs, the per-param index bridges it, and the
        ZeRO-3 continuation is BITWISE the ZeRO-2 continuation (same
        fp32 master, same collectives modulo the gather's position)."""
        _, model, opt, _ = self._train(devices8, flat=True, steps=2,
                                       zero=3)
        d = str(tmp_path / "z3_dp8_ck")
        save_checkpoint(model, opt, d, step=2)
        c2, _, _, _ = self._train(devices8, flat=True, steps=2, zero=2,
                                  load_from=d, dp=4)
        c3, _, opt4, _ = self._train(devices8, flat=True, steps=2,
                                     zero=3, load_from=d, dp=4)
        assert c2 == c3            # bitwise, not merely close
        assert opt4._flat_layout.device_num == 4

    def test_adafactor_flat_checkpoint_preserves_factored_stats(
            self, devices8, tmp_path):
        """Adafactor's per-bucket factored row/col EMAs ride the
        checkpoint as ``opt.fac_row@@leaf*`` entries and regraft on
        restore, so a flat continuation is bitwise the uninterrupted
        run."""
        af = ht.optim.AdafactorOptimizer
        kw = dict(opt_cls=af, min_dim_size_to_factor=16)
        _, model, opt, _ = self._train(devices8, flat=True, steps=2,
                                       **kw)
        d = str(tmp_path / "af_ck")
        save_checkpoint(model, opt, d, step=2)
        assert any(k.startswith("opt.fac_row@@leaf")
                   for k in load_split(d))
        ref, _, _, _ = self._train(devices8, flat=True, steps=4, **kw)
        cont, _, opt2, _ = self._train(devices8, flat=True, steps=2,
                                       load_from=d, **kw)
        assert cont == ref[2:]     # factored stats survived: bitwise
        assert any(float(np.abs(np.asarray(v)).max()) > 0
                   for v in opt2._state["fac_row"])

    def test_flat_checkpoint_is_per_param_keyed(self, devices8,
                                                tmp_path):
        """The file format carries opt.m.<name>/opt.v.<name>/opt.master
        .<name> entries in original param shapes — no flat buffers."""
        _, model, opt, _ = self._train(devices8, flat=True, steps=1)
        d = str(tmp_path / "keyed_ck")
        save_checkpoint(model, opt, d, step=1)
        state = load_split(d)
        names = dict(model.named_parameters())
        some = next(iter(names))
        for slot in ("m", "v", "master"):
            key = f"opt.{slot}.{some}"
            assert key in state, sorted(state)[:8]
            assert state[key].shape == tuple(names[some].concrete_shape())
        assert not any("flat_" in k for k in state)
        assert "opt.step" in state
