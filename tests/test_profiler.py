"""Profiler subsystem tests: op-level replay profiling, step timing,
memory snapshots, logging/timing utils."""
import json
import os

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu import ops, optim
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.utils import (TIK, TOK, MemoryProfiler, OpProfiler,
                            StepProfiler, Timer, device_memory_stats,
                            get_logger, set_log_level)


def _tiny_gpt_graph():
    cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                    num_heads=2, max_seq_len=8, dtype="float32")
    g_ctx = ht.graph("define_and_run", create_new=True)
    g = g_ctx.__enter__()
    ids = ht.placeholder("int32", (2, 8), name="ids")
    labels = ht.placeholder("int32", (2, 8), name="labels")
    model = GPTLMHeadModel(cfg)
    loss = model(ids, labels)
    g_ctx.__exit__(None, None, None)
    rng = np.random.RandomState(0)
    feed = {ids: rng.randint(0, 32, (2, 8)).astype(np.int32),
            labels: rng.randint(0, 32, (2, 8)).astype(np.int32)}
    return g, loss, feed


class TestOpProfiler:
    def test_profiles_every_op(self):
        g, loss, feed = _tiny_gpt_graph()
        prof = OpProfiler(g)
        records = prof.profile([loss], feed, warmup=0, iters=1)
        assert len(records) > 10
        types = {r["op_type"] for r in records}
        assert "matmul" in types or "linear" in types
        assert all(r["time"] >= 0 for r in records)
        assert prof.total() > 0

    def test_aggregations(self):
        g, loss, feed = _tiny_gpt_graph()
        prof = OpProfiler(g)
        prof.profile([loss], feed, warmup=0, iters=1)
        by_type = prof.by_type()
        assert abs(sum(by_type.values()) - prof.total()) < 1e-9
        by_group = prof.by_group(depth=1)
        assert by_group
        s = prof.summary(top=5)
        assert "total" in s and "ms" in s

    def test_profile_result_matches_run(self):
        """Replay must produce the same loss value as graph.run."""
        g, loss, feed = _tiny_gpt_graph()
        (ref,) = g.run(loss, [loss], feed)
        prof = OpProfiler(g)
        records = prof.profile([loss], feed, warmup=0, iters=1)
        assert records  # replay executed


class TestStepProfiler:
    def test_discards_warmup(self):
        sp = StepProfiler(warmup=2)
        for _ in range(5):
            with sp:
                pass
        assert sp.stats()["steps"] == 3
        assert sp.stats()["mean"] >= 0

    def test_empty_stats(self):
        assert StepProfiler().stats()["steps"] == 0


class TestMemoryProfiler:
    def test_disabled_by_default(self, monkeypatch):
        monkeypatch.delenv("HETU_TPU_MEMORY_PROFILE", raising=False)
        mp = MemoryProfiler()
        assert mp.snapshot("x") == {}
        assert mp.snapshots == []

    def test_env_enabled_logs_jsonl(self, tmp_path, monkeypatch):
        log = tmp_path / "mem.jsonl"
        monkeypatch.setenv("HETU_TPU_MEMORY_PROFILE", "MICRO_BATCH")
        monkeypatch.setenv("HETU_TPU_MEMORY_LOG_FILE", str(log))
        mp = MemoryProfiler()
        mp.snapshot("fwd_begin", micro_batch_id=0)
        mp.snapshot("fwd_end", micro_batch_id=0)
        lines = [json.loads(l) for l in open(log)]
        assert len(lines) == 2
        assert lines[0]["tag"] == "fwd_begin"
        assert "bytes_in_use" in lines[0]
        assert mp.peak() >= 0

    def test_device_memory_stats_keys(self):
        st = device_memory_stats()
        assert set(st) == {"bytes_in_use", "peak_bytes_in_use",
                           "bytes_limit"}


class TestLoggingUtils:
    def test_tik_tok(self):
        TIK("t")
        dt = TOK("t")
        assert dt >= 0
        with pytest.raises(KeyError):
            TOK("never-started")

    def test_timer_context(self):
        with Timer("x") as t:
            sum(range(1000))
        assert t.seconds > 0

    def test_log_level_env(self, monkeypatch):
        import logging
        from hetu_tpu.utils import logging_utils
        monkeypatch.setenv("HETU_TPU_LOG_LEVEL", "DEBUG")
        logging_utils._loggers.pop("envtest", None)
        lg = get_logger("envtest")
        assert lg.level == logging.DEBUG
        set_log_level("ERROR", "envtest")
        assert lg.level == logging.ERROR


class TestRuntimeMemorySnapshots:
    """Per-micro-batch (MPMD) / per-step (SPMD) memory snapshots, enabled
    by HETU_TPU_MEMORY_PROFILE (reference executable_graph.cc:1738-1761
    MICRO_BATCH level)."""

    def test_spmd_step_snapshot(self, monkeypatch, tmp_path):
        import hetu_tpu as ht
        from hetu_tpu import ops, optim
        log = str(tmp_path / "mem.jsonl")
        monkeypatch.setenv("HETU_TPU_MEMORY_PROFILE", "MICRO_BATCH")
        monkeypatch.setenv("HETU_TPU_MEMORY_LOG_FILE", log)
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (4, 8), name="x")
            w = ht.parameter(np.zeros((8, 4), np.float32), (8, 4), name="w")
            loss = ops.reduce_mean(ops.matmul(x, w) ** 2 + 1.0)
            op = optim.SGDOptimizer(lr=0.1).minimize(loss)
            X = np.random.RandomState(0).randn(4, 8).astype(np.float32)
            g.run(loss, [loss, op], {x: X})
            g.run(loss, [loss, op], {x: X})
        assert g._memory_profiler is not None
        snaps = g._memory_profiler.snapshots
        assert len(snaps) == 2 and all(s["tag"] == "step" for s in snaps)
        import json as _json
        lines = [_json.loads(l) for l in open(log)]
        assert len(lines) == 2

    @pytest.mark.slow
    def test_mpmd_per_microbatch_snapshots(self, monkeypatch, devices8):
        monkeypatch.setenv("HETU_TPU_MEMORY_PROFILE", "MICRO_BATCH")
        monkeypatch.delenv("HETU_TPU_MEMORY_LOG_FILE", raising=False)
        from jax.sharding import Mesh
        from tests.test_pipeline_mpmd import _cfg, _data
        from hetu_tpu.models.gpt_mpmd import MPMDGPT
        cfg = _cfg(num_layers=4)
        ids, labels = _data(cfg, batch=4)
        meshes = [[Mesh(np.array(devices8[2 * s:2 * s + 2]).reshape(1, 2),
                        ("dp", "tp")) for s in range(2)]]
        model = MPMDGPT(cfg, stage_layers=[[2, 2]], meshes=meshes, seed=0)
        runtime = model.runtime
        data = model.split_micro_batches(ids, labels, [2])
        _, _, stats = runtime.train_step(data)
        snaps = runtime.memory_profiler.snapshots
        # one snapshot per executed task, tagged pipe/stage/kind + mb id
        assert len(snaps) == stats.num_tasks
        assert all(s["micro_batch_id"] >= 0 for s in snaps)
        tags = {s["tag"] for s in snaps}
        assert any(t.endswith(".F") for t in tags)
        assert any(t.endswith(".B") for t in tags)


class TestCostAnalysis:
    """XLA cost analysis of the compiled step (in-program metrics,
    reference op TimeCost / CUDAProfiler counters)."""

    def test_flops_reported_and_scale(self):
        import hetu_tpu as ht
        from hetu_tpu import ops, optim

        def step_flops(n):
            with ht.graph("define_and_run", create_new=True) as g:
                x = ht.placeholder("float32", (8, n), name="x")
                w = ht.parameter(np.zeros((n, n), np.float32), (n, n),
                                 name="w")
                loss = ops.reduce_mean(ops.matmul(x, w) ** 2)
                op = optim.SGDOptimizer(lr=0.1).minimize(loss)
                assert g.cost_analysis() is None  # nothing ran yet
                X = np.random.RandomState(0).randn(8, n).astype(np.float32)
                g.run(loss, [loss, op], {x: X})
                costs = g.cost_analysis()
            assert costs is not None and "flops" in costs
            return float(costs["flops"])

        f64, f128 = step_flops(64), step_flops(128)
        assert f64 > 0
        # quadrupling the weight quadruples the dominant matmul flops
        assert f128 > 3.0 * f64, (f64, f128)
