"""Static peak-HBM model tests (ISSUE 8): the liveness walker's seeded
cases, the four memory lint rules firing exactly once with hints, and
the XLA cross-check on real probes.

Walker contracts demonstrated here:
(a) dropping a donation raises the predicted peak by ~the buffer size,
    and the ``donation-miss`` finding agrees with the peak delta;
(b) wrapping the repeated block in ``jax.checkpoint`` lowers the
    predicted peak and makes ``remat-opportunity`` stop firing;
(c) scan body temporaries peak once — they do not accumulate x trips.
"""
import os
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_tpu import analysis
from hetu_tpu.analysis import analyze_handle, predict_memory, run_rules
from hetu_tpu.analysis.memory import (MemoryReport, has_remat_region,
                                      liveness_walk,
                                      parse_input_output_aliases)
from hetu_tpu.graph.graph import clear_executables, register_executable


def _sds(shape, dtype=np.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _register(name, fn, args, **meta):
    meta.setdefault("mesh_axes", {})
    meta.setdefault("params", [])
    meta.setdefault("allowed_gspmd", None)
    clear_executables(name)
    return register_executable(name, fn, args, meta)


def _fired(rep, rule):
    return [f for f in rep.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# (a) donation drop: peak delta ~ buffer size, agrees with donation-miss
# ---------------------------------------------------------------------------

class TestDonationPeak:
    def test_dropping_donation_raises_peak_by_buffer_size(self):
        def f(x, delta):
            return x + delta

        args = (_sds((256, 1024)), _sds((1024,)))
        buf = 256 * 1024 * 4
        h_don = _register("t_mem/don", jax.jit(f, donate_argnums=(0,)),
                          args)
        h_not = _register("t_mem/nodon", jax.jit(f), args)
        m_don = predict_memory(h_don)
        m_not = predict_memory(h_not)
        # the donated run writes the output in place; dropping the
        # donation costs ~one fresh output buffer
        delta = m_not.peak_bytes - m_don.peak_bytes
        assert 0.9 * buf <= delta <= 1.1 * buf, (delta, buf)
        assert m_don.output_extra_bytes == 0
        assert m_not.output_extra_bytes == buf

        # ...and donation-miss names the same bytes: the rule and the
        # memory model agree on what the dropped donation costs
        rep = analyze_handle(h_not,
                             options={"donation_bytes_threshold": 1024})
        fired = _fired(rep, "donation-miss")
        assert len(fired) == 1
        (claimed,) = [int(s) for s in
                      re.findall(r"\((\d+) B", fired[0].message)]
        assert abs(claimed - delta) <= 0.1 * buf

    def test_alias_table_silences_false_positive(self):
        """Satellite: outputs XLA ALREADY absorbed (per the compiled
        ``input_output_alias`` table) must stop producing shape-matched
        donation-miss candidates — the shape/dtype guess alone cannot
        see a second output slot being written in place."""
        from types import SimpleNamespace as NS
        from hetu_tpu.analysis import donation_candidates

        leaf = lambda donated: NS(shape=(1024,), dtype=np.float32,
                                  donated=donated)
        args_info = (leaf(True), leaf(False))
        out_avals = (jax.ShapeDtypeStruct((1024,), np.float32),
                     jax.ShapeDtypeStruct((1024,), np.float32))
        # shape-only guess: donated arg0 retires ONE of the two output
        # slots, the second still looks reusable -> arg1 flagged
        assert len(donation_candidates(args_info, out_avals,
                                       min_bytes=1024)) == 1
        # XLA's table says BOTH outputs are already written in place
        # (e.g. an in-place scatter chain): nothing left to reuse
        assert donation_candidates(args_info, out_avals, min_bytes=1024,
                                   alias_pairs=[(0, 0), (1, 0)]) == []
        # table with one absorbed slot: the other stays a candidate
        assert len(donation_candidates(args_info, out_avals,
                                       min_bytes=1024,
                                       alias_pairs=[(0, 0)])) == 1

    def test_dropped_donation_still_retires_slot_with_table(self):
        """A donation XLA DROPPED (absent from a non-empty alias table)
        must still claim its shape-matched output slot: the user already
        donated for that output, so the same-shaped neighbour is not a
        candidate — the decode tokens/pos pattern with a table present."""
        from types import SimpleNamespace as NS
        from hetu_tpu.analysis import donation_candidates

        leaf = lambda shape, donated: NS(shape=shape, dtype=np.float32,
                                         donated=donated)
        # param 0: donated, sig S, donation dropped by XLA
        # param 1: un-donated, sig S (the would-be false positive)
        # param 2: donated, sig T, honored (output 1 <- param 2)
        args_info = (leaf((1024,), True), leaf((1024,), False),
                     leaf((2048,), True))
        out_avals = (jax.ShapeDtypeStruct((1024,), np.float32),
                     jax.ShapeDtypeStruct((2048,), np.float32))
        assert donation_candidates(args_info, out_avals, min_bytes=1024,
                                   alias_pairs=[(1, 2)]) == []

    def test_alias_table_parses_from_real_compile(self):
        """The parser must read jax's actual compiled HLO, not just the
        seeded text fixture."""
        f = jax.jit(lambda a: a * 2.0, donate_argnums=(0,))
        text = f.lower(_sds((64, 64))).compile().as_text()
        assert parse_input_output_aliases(text) == [(0, 0)]

    def test_parse_input_output_aliases(self):
        text = ("HloModule m, input_output_alias={ {0}: (2, {}, "
                "may-alias), {1}: (0, {}, must-alias) }")
        assert parse_input_output_aliases(text) == [(0, 2), (1, 0)]
        assert parse_input_output_aliases("HloModule m") == []


# ---------------------------------------------------------------------------
# (b) remat lowers the predicted peak; remat-opportunity stops firing
# ---------------------------------------------------------------------------

def _chain_step(remat: bool, blocks: int = 4, depth: int = 4,
                h: int = 256, b: int = 512):
    # each block holds `depth` internal MATERIALIZED activations (dot
    # outputs the backward consumes directly — the walk prices fusible
    # values at zero by design); checkpointing a block trades those for
    # its one boundary (the classic nn-layer remat shape)
    def block(x, ws):
        for w in ws:
            x = x @ w
        return x

    blk = jax.checkpoint(block) if remat else block

    def loss(params, x):
        for ws in params:
            x = blk(x, ws)
        return jnp.mean(x ** 2)

    def step(params, x):
        return jax.grad(loss)(params, x)

    args = (tuple(tuple(_sds((h, h)) for _ in range(depth))
                  for _ in range(blocks)), _sds((b, h)))
    # registered as a train step: remat-opportunity only applies where
    # a backward holds saved activations (the rule guards on ctx.train)
    return _register(f"t_mem/chain_{'remat' if remat else 'plain'}",
                     jax.jit(step), args, train=True)


class TestRematPeak:
    def test_remat_lowers_predicted_peak(self):
        m_plain = predict_memory(_chain_step(remat=False))
        m_remat = predict_memory(_chain_step(remat=True))
        # the plain chain holds every layer's saved activations across
        # the whole forward; checkpointing trades them for recompute
        assert m_remat.activation_peak_bytes \
            < 0.7 * m_plain.activation_peak_bytes, \
            (m_remat.activation_peak_bytes, m_plain.activation_peak_bytes)
        assert m_remat.peak_bytes < m_plain.peak_bytes

    def test_remat_opportunity_fires_once_then_stops(self):
        opts = {"remat_min_bytes": 1 << 16,
                "remat_activation_fraction": 0.3}
        rep = analyze_handle(_chain_step(remat=False), options=opts)
        fired = _fired(rep, "remat-opportunity")
        assert len(fired) == 1, rep.findings
        assert "jax.checkpoint" in fired[0].hint
        # the walk sees the remat regions -> already covered, silent
        rep2 = analyze_handle(_chain_step(remat=True), options=opts)
        assert not _fired(rep2, "remat-opportunity"), rep2.findings

    def test_remat_opportunity_silent_on_inference(self):
        """No backward pass -> jax.checkpoint reclaims nothing; the
        rule must not advise remat on inference-only executables even
        when materialized temps dominate the peak."""
        def fwd(params, x):
            for ws in params:
                for w in ws:
                    x = x @ w
            return x

        h, b = 256, 512
        args = (tuple(tuple(_sds((h, h)) for _ in range(4))
                      for _ in range(4)), _sds((b, h)))
        hdl = _register("t_mem/chain_infer", jax.jit(fwd), args)
        opts = {"remat_min_bytes": 1 << 16,
                "remat_activation_fraction": 0.3}
        rep = analyze_handle(hdl, options=opts)
        assert not _fired(rep, "remat-opportunity"), rep.findings

    def test_has_remat_region(self):
        assert has_remat_region(_chain_step(remat=True).jaxpr)
        assert not has_remat_region(_chain_step(remat=False).jaxpr)


# ---------------------------------------------------------------------------
# (c) scan body temporaries peak once, not x trips
# ---------------------------------------------------------------------------

class TestScanPeak:
    def test_scan_temporaries_do_not_accumulate_across_trips(self):
        w = jnp.zeros((256, 256), np.float32)

        def f(n):
            def body(c, _):
                t = c @ w              # 64KB body temporary
                return jnp.tanh(t), jnp.sum(t)
            def g(x):
                return jax.lax.scan(body, x, None, length=n)
            return jax.make_jaxpr(g)(jnp.zeros((64, 256), np.float32))

        p2 = liveness_walk(f(2)).peak
        p16 = liveness_walk(f(16)).peak
        # the body temp is per-trip scratch: 8x the trips must not move
        # the peak (stacked ys are scalars here)
        assert p16 <= p2 * 1.05 + 1024, (p2, p16)
        assert p2 > 0

    def test_final_carry_aliases_running_carry(self):
        """The scan's carry output reuses the running carry buffer —
        it must not be double counted as fresh memory."""
        def g(x):
            def body(c, _):
                return jnp.tanh(c), None
            c, _ = jax.lax.scan(body, x, None, length=4)
            return jnp.sum(c)

        big = jax.make_jaxpr(g)(jnp.zeros((512, 512), np.float32))
        # carry is 1MB; the walk's peak must stay ~one carry, not two
        assert liveness_walk(big).peak <= 1.5 * 512 * 512 * 4


# ---------------------------------------------------------------------------
# memory lint rules: each fires exactly once on a seeded violation
# ---------------------------------------------------------------------------

class TestMemoryRules:
    def _handle(self):
        def f(x, d):
            return jnp.tanh(x @ d)
        return _register("t_mem/rules", jax.jit(f),
                         (_sds((256, 256)), _sds((256, 256))))

    def test_peak_memory_regression_fires_once(self):
        h = self._handle()
        mem = predict_memory(h)
        rep = analyze_handle(h, options={
            "baseline_peak_bytes": {h.name: mem.peak_bytes // 2},
            "memory_tolerance": 0.1})
        fired = _fired(rep, "peak-memory-regression")
        assert len(fired) == 1, rep.findings
        assert "--update-baseline" in fired[0].hint
        # frozen at the actual peak: silent
        rep2 = analyze_handle(h, options={
            "baseline_peak_bytes": {h.name: mem.peak_bytes}})
        assert not _fired(rep2, "peak-memory-regression")

    def test_oom_risk_fires_once(self):
        h = self._handle()
        rep = analyze_handle(h, options={"hbm_budget_bytes": 1024.0,
                                         "hbm_usable_fraction": 1.0})
        fired = _fired(rep, "oom-risk")
        assert len(fired) == 1, rep.findings
        assert fired[0].severity == "error"
        # the hint names the dominant buffer class's remedy
        dom = predict_memory(h).dominant_kind()
        assert dom in fired[0].message
        assert fired[0].hint
        rep2 = analyze_handle(h, options={"hbm_budget_bytes": 95e9})
        assert not _fired(rep2, "oom-risk")

    def test_replicated_state_under_shard_fires_once(self):
        def step(p, m, v, x):
            g = x * 0.1
            nm = 0.9 * m + 0.1 * g
            nv = 0.99 * v + 0.01 * g * g
            return p - 1e-3 * nm / (jnp.sqrt(nv) + 1e-8), nm, nv

        s = _sds((512, 512))
        kinds = ("param", "opt-state", "opt-state", "feed")
        h = _register(
            "t_mem/repstate", jax.jit(step), (s, s, s, s),
            mesh_axes={"dp": 8}, dp_axis="dp", zero=0, flat_state=False,
            arg_divisors=(1, 1, 1, 8), arg_kinds=kinds)
        rep = analyze_handle(h, options={"param_bytes_threshold": 1 << 20})
        fired = _fired(rep, "replicated-state-under-shard")
        assert len(fired) == 1, rep.findings
        assert "zero" in fired[0].hint.lower()
        # zero=1 contracts the state to be dp-sharded: silent
        h2 = _register(
            "t_mem/repstate_z1", jax.jit(step), (s, s, s, s),
            mesh_axes={"dp": 8}, dp_axis="dp", zero=1, flat_state=False,
            arg_divisors=(1, 8, 8, 8), arg_kinds=kinds)
        rep2 = analyze_handle(
            h2, options={"param_bytes_threshold": 1 << 20})
        assert not _fired(rep2, "replicated-state-under-shard")
        # dp=1 mesh: nothing to shard over, silent
        h3 = _register(
            "t_mem/repstate_dp1", jax.jit(step), (s, s, s, s),
            mesh_axes={"dp": 1}, dp_axis="dp", zero=0, flat_state=False,
            arg_divisors=(1, 1, 1, 1), arg_kinds=kinds)
        rep3 = analyze_handle(
            h3, options={"param_bytes_threshold": 1 << 20})
        assert not _fired(rep3, "replicated-state-under-shard")


# ---------------------------------------------------------------------------
# resident accounting + XLA cross-check on a real probe
# ---------------------------------------------------------------------------

class TestResidentAndXla:
    def test_arg_divisors_shard_resident_bytes(self):
        def f(w, x):
            return x @ w

        h = _register("t_mem/shard", jax.jit(f),
                      (_sds((1024, 1024)), _sds((8, 1024))),
                      arg_divisors=(8, 1), arg_kinds=("param", "feed"))
        mem = predict_memory(h)
        assert mem.by_kind["param"] == 1024 * 1024 * 4 // 8
        assert mem.by_kind["feed"] == 8 * 1024 * 4

    def test_resident_model_is_exact_vs_xla_arguments(self):
        """The resident side of the model must match XLA's own
        ``argument_size_in_bytes`` EXACTLY on an Adam-style fused train
        step — every input leaf's bytes, donation-independent.  (The
        ±10% whole-peak acceptance criterion is pinned per gate family
        by the CI gate itself; the attention probe's fusible softmax
        residuals are a documented model gap on the temp side.)"""
        from hetu_tpu.planner.cost_model import calibrate_layer_memory
        cal = calibrate_layer_memory(xla_check=True)
        assert cal.xla_bytes is not None and cal.xla_bytes > 0
        assert cal.static_bytes > 0
        assert cal.scale == pytest.approx(
            cal.static_bytes / cal.model_bytes)

        def f(x, d):
            return jnp.tanh(x @ d)
        h = _register("t_mem/xla_args", jax.jit(f),
                      (_sds((256, 256)), _sds((256, 256))))
        mem = predict_memory(h, xla=True)
        assert mem.xla is not None
        assert mem.resident_bytes == mem.xla["argument"]

    def test_report_json_shape(self):
        h = self_handle = _register(
            "t_mem/json", jax.jit(lambda x: x * 2.0), (_sds((64, 64)),))
        mem = predict_memory(h, xla=True)
        d = mem.to_dict(buffers=True)
        assert d["peak_bytes"] == mem.peak_bytes
        assert "by_kind" in d and "xla_total_bytes" in d
        assert isinstance(d["top_buffers"], list)
