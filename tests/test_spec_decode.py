"""Draft-model speculative decoding on the unified serving step
(ISSUE 15).

The strongest gate this repo has — temp-0 serving output bit-for-bit
equal to solo ``generate()`` — applied to the flashiest feature:

- **temp-0 bitwise** — speculative output equals non-speculative (and
  therefore solo ``generate()``) under late arrivals, preemption
  (asserted non-vacuous) and prefix-cache eviction;
- **sampled-mode determinism** — the coupled leftover-distribution
  acceptance draws the SAME ``(seed, index)``-keyed choice the per-row
  sampler draws, so sampled spec output is bitwise the non-spec sampled
  output across every k / chunk size / batching;
- **degenerate drafts** — a draft identical to the target accepts
  everything; a head-negated draft accepts nothing — output identical
  either way, only the tokens-per-step cadence changes;
- **compile pin** — spec engine = exactly 4 programs (unified + draft
  prefill/propose/insert) over an adversarial mixed spec/non-spec
  trace, ``host_logit_fetches == 0``;
- **KV-rewind honesty** — the real engine tap satisfies the
  ``spec-rewind-leak`` rule (rewinds asserted non-vacuous) and the
  seeded violation fires exactly once;
- **metrics** — spec counters + derived rates, ``reset_metrics``
  zeroing, and the cluster-merged Prometheus exposition.
"""
import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.models import (GPTConfig, GPTLMHeadModel, draft_config,
                             draft_state_from)
from hetu_tpu.models.generate import generate
from hetu_tpu.serving import Engine, SpecConfig

CFG_KW = dict(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
              max_seq_len=64, sp=False, dropout=0.0)


def _build_state(cfg, seed=3):
    ht.set_seed(seed)
    with ht.graph("eager", create_new=True):
        model = GPTLMHeadModel(cfg)
        model.logits(np.zeros((1, 4), np.int32))
        state = {k: np.asarray(v) for k, v in model.state_dict().items()}
    return state


def _solo(state, cfg, prompt, n_new):
    return np.asarray(generate(state, cfg,
                               np.asarray([prompt], np.int32), n_new,
                               temperature=0.0))[0, len(prompt):].tolist()


def _make_engine(state, cfg, **kw):
    clock = [0.0]
    kw.setdefault("time_fn", lambda: clock[0])
    kw.setdefault("debug", True)
    eng = Engine(state, cfg, **kw)
    eng._test_clock = clock
    return eng


def _drain(eng, check=True):
    guard = 0
    while eng.has_work:
        eng.step()
        eng._test_clock[0] += 1.0
        guard += 1
        assert guard < 500, "engine failed to drain"
        if check:
            eng.pool.check_invariants()


@pytest.fixture(scope="module")
def gpt():
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg, seed=11)
    dstate, dcfg = draft_state_from(state, cfg, 1)
    return state, cfg, dstate, dcfg


# ---------------------------------------------------------------------------
# temp-0 bitwise under the adversarial trace
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prefix_cache", [False, True])
def test_spec_temp0_bitwise_under_pressure(gpt, prefix_cache):
    """The acceptance criterion: a tiny pool (forces recompute
    preemption, asserted non-vacuous; with the cache on, LRU eviction
    too), staggered arrivals, chunked prefill — speculative output is
    bit-for-bit the solo generate() run for every request."""
    state, cfg, dstate, dcfg = gpt
    prompts = [[5, 17, 2, 9, 33, 12, 8, 1], [1, 1, 4, 44],
               [3, 2, 1, 9, 6, 5, 4]]
    want = [_solo(state, cfg, p, 14) for p in prompts]
    eng = _make_engine(state, cfg, num_pages=6, page_size=8,
                       max_batch=4, chunk_size=4,
                       prefix_cache=prefix_cache,
                       spec=SpecConfig(dstate, dcfg, k=3))
    reqs = [eng.add_request(p, 14, arrival_time=float(i))
            for i, p in enumerate(prompts)]
    _drain(eng)
    m = eng.metrics_summary()
    assert m["preemptions"] >= 1, \
        "trace should exercise eviction; shrink the pool if not"
    if prefix_cache:
        assert m["prefix_cache_evictions"] >= 1
    assert m["spec_accepted"] > 0, "speculation never engaged"
    assert m["spec_accepted"] < m["spec_proposed"], \
        "no rejection: the rewind path is untested"
    assert m["host_logit_fetches"] == 0
    for r, w in zip(reqs, want):
        assert r.out_tokens == w
    assert eng.pool.used_pages == 0


def test_spec_matches_nonspec_engine_exactly(gpt):
    """Spec vs non-spec ENGINE (not just solo generate): identical
    outputs and identical per-request token values on a mixed trace
    with a mid-flight arrival."""
    state, cfg, dstate, dcfg = gpt
    rng = np.random.RandomState(2)
    prompts = [[int(t) for t in rng.randint(1, 90, size=n)]
               for n in (23, 4, 17)]
    outs = {}
    for spec in (None, SpecConfig(dstate, dcfg, k=4)):
        eng = _make_engine(state, cfg, num_pages=24, page_size=8,
                           max_batch=4, chunk_size=8, spec=spec)
        reqs = [eng.add_request(p, 8, arrival_time=float(2 * i))
                for i, p in enumerate(prompts)]
        _drain(eng)
        outs[spec is None] = [r.out_tokens for r in reqs]
    assert outs[True] == outs[False]


# ---------------------------------------------------------------------------
# sampled mode: the coupled acceptance is bitwise with non-spec
# ---------------------------------------------------------------------------

def test_sampled_mode_bitwise_across_k_chunk_and_batching(gpt):
    """Sampled verify rows accept iff the draft matches the position's
    own (seed, index)-keyed choice — the coupled form of
    leftover-distribution rejection sampling — so sampled spec output
    is not merely deterministic: it equals non-speculative sampled
    serving bit-for-bit, for every k, chunk size and batch mix."""
    state, cfg, dstate, dcfg = gpt
    prompt = [5, 17, 2, 9, 1]
    ref = None
    configs = [(None, dict(chunk_size=8, max_batch=2))]
    for k in (1, 3):
        configs += [(SpecConfig(dstate, dcfg, k=k),
                     dict(chunk_size=4, max_batch=4)),
                    (SpecConfig(dstate, dcfg, k=k),
                     dict(chunk_size=8, max_batch=2))]
    for spec, kw in configs:
        eng = _make_engine(state, cfg, num_pages=16, page_size=8,
                           spec=spec, **kw)
        if kw["max_batch"] == 4:            # mixed greedy/sampled batch
            eng.add_request([3, 2, 1], 8, arrival_time=0.0)
        req = eng.add_request(prompt, 8, temperature=0.7, top_p=0.9,
                              top_k=40, seed=123, arrival_time=0.0)
        _drain(eng)
        assert eng.host_logit_fetches == 0
        if ref is None:
            ref = list(req.out_tokens)
        assert list(req.out_tokens) == ref, (spec and spec.k, kw)


# ---------------------------------------------------------------------------
# degenerate drafts
# ---------------------------------------------------------------------------

def test_all_accepted_draft_equals_target(gpt):
    """Draft == target: every proposal matches the target argmax, so
    every burst commits k + 1 tokens — acceptance 100%, output still
    bitwise, cadence > 1 token per step.  The generation is long
    enough to chain several fully-accepted bursts: the rate only stays
    1.0 if the draft cache is seamless across bursts (the propose
    warm-up re-writes d_K's slot — without it, every full acceptance
    left one garbage position in the draft context and the rate
    decayed with length)."""
    state, cfg, _, _ = gpt
    eng = _make_engine(state, cfg, num_pages=24, page_size=8,
                       max_batch=2, chunk_size=8,
                       spec=SpecConfig(dict(state), cfg, k=4))
    req = eng.add_request([5, 17, 2, 9], 21, arrival_time=0.0)
    _drain(eng)
    m = eng.metrics_summary()
    assert req.out_tokens == _solo(state, cfg, [5, 17, 2, 9], 21)
    assert m["spec_accepted"] == m["spec_proposed"] > 0
    assert m["spec_accept_rate"] == 1.0
    assert m["accepted_per_step"] > 1.0


def test_all_rejected_draft_still_bitwise(gpt):
    """A head-negated draft proposes the target's argMIN: every
    proposal rejects, every verify emits exactly the bonus token — the
    degenerate 1-token-per-step cadence with UNCHANGED output."""
    state, cfg, _, _ = gpt
    head = [k for k in state if "lm_head" in k][0]
    neg = dict(state)
    neg[head] = -np.asarray(state[head])
    eng = _make_engine(state, cfg, num_pages=24, page_size=8,
                       max_batch=2, chunk_size=8,
                       spec=SpecConfig(neg, cfg, k=4))
    req = eng.add_request([5, 17, 2, 9], 9, arrival_time=0.0)
    _drain(eng)
    m = eng.metrics_summary()
    assert req.out_tokens == _solo(state, cfg, [5, 17, 2, 9], 9)
    assert m["spec_accepted"] == 0 and m["spec_proposed"] > 0
    # every token except the first (emitted by the prompt's prefill
    # chunk, before speculation engages) and the last (remaining
    # budget 1: a plain decode, nothing left to draft) is a bonus
    assert m["spec_bonus_tokens"] == len(req.out_tokens) - 2


def test_eos_mid_burst_and_max_new_cap(gpt):
    """Commit caps inside one verify burst: an accepted draft equal to
    eos finishes the request mid-burst (later accepted tokens are
    discarded), and max_new_tokens truncates a burst that would
    overshoot."""
    state, cfg, _, _ = gpt
    prompt = [5, 17, 2, 9]
    w6 = _solo(state, cfg, prompt, 6)
    eng = _make_engine(state, cfg, num_pages=24, page_size=8,
                       max_batch=2, chunk_size=8,
                       spec=SpecConfig(dict(state), cfg, k=4))
    req = eng.add_request(prompt, 6, eos_token_id=w6[2],
                          arrival_time=0.0)
    _drain(eng)
    assert req.out_tokens == w6[:3]
    eng = _make_engine(state, cfg, num_pages=24, page_size=8,
                       max_batch=2, chunk_size=8,
                       spec=SpecConfig(dict(state), cfg, k=4))
    req = eng.add_request(prompt, 2, arrival_time=0.0)
    _drain(eng)
    assert req.out_tokens == w6[:2]


# ---------------------------------------------------------------------------
# compile pin + host fetches (CI)
# ---------------------------------------------------------------------------

@pytest.mark.lint_graph
def test_spec_compile_count_pinned_mixed_trace(gpt):
    """Over an adversarial mixed spec/non-spec trace (greedy + sampled
    requests, short + long prompts, late arrivals, preemption) the spec
    engine compiles EXACTLY 4 programs — the unified step plus the
    draft prefill/propose/insert — read from the real jit caches, so a
    silent retrace in either model trips this."""
    state, cfg, dstate, dcfg = gpt
    rng = np.random.RandomState(5)
    eng = _make_engine(state, cfg, num_pages=9, page_size=8,
                       max_batch=4, chunk_size=8,
                       spec=SpecConfig(dstate, dcfg, k=3))
    for i in range(9):
        n = int(rng.randint(2, 30))
        pr = [int(t) for t in rng.randint(1, 90, size=n)]
        eng.add_request(pr, int(rng.randint(2, 8)),
                        temperature=0.5 if i % 3 == 0 else 0.0,
                        top_p=0.9 if i % 3 == 0 else 0.0,
                        seed=i, arrival_time=float(i))
    _drain(eng)
    m = eng.metrics_summary()
    assert m["preemptions"] >= 1              # trace is adversarial
    assert m["spec_accepted"] > 0             # speculation engaged
    assert eng.compile_count == 4
    for key in ("unified", "draft_prefill", "draft_propose",
                "draft_insert"):
        fn = eng._compiled[key]
        if hasattr(fn, "_cache_size"):
            assert fn._cache_size() == 1, key
    assert m["host_logit_fetches"] == 0
    assert len(eng.finished) == 9


def test_preempted_speculating_request_resumes_drafting(gpt):
    """Preemption invalidates the draft cache; on re-admission the
    request re-prefills the draft and keeps speculating — a second
    draft_prefill for the same request, with output unchanged.  The
    preemption is applied through the engine's own eviction mechanics
    mid-speculation (a pool race rarely lands one exactly there)."""
    state, cfg, dstate, dcfg = gpt
    prompt = [5, 17, 2, 9]
    want = _solo(state, cfg, prompt, 16)
    eng = _make_engine(state, cfg, num_pages=16, page_size=8,
                       max_batch=2, chunk_size=8,
                       spec=SpecConfig(dstate, dcfg, k=3))
    req = eng.add_request(prompt, 16, arrival_time=0.0)
    while req.n_generated < 6:          # actively speculating by now
        eng.step()
        eng._test_clock[0] += 1.0
    assert eng.spec.prefills >= 1
    assert eng.spec._valid.get(req.req_id)
    # what Engine.step does for an evicted request, applied directly
    eng.scheduler.preempt(req)
    eng.spec.release(req)
    assert req.req_id not in eng.spec._slot   # slot really freed
    eng.running.remove(req)
    eng.queue.push(req)
    eng.counters["preemptions"].inc()
    if eng.tap is not None:
        eng.tap.append({"kind": "kv_drop", "req": req.req_id})
    pre = eng.spec.prefills
    _drain(eng)
    assert req.n_preemptions == 1
    assert eng.spec.prefills == pre + 1, \
        "resumed request never re-prefilled its draft cache"
    assert req.out_tokens == want


def test_page_squeeze_sheds_drafts_before_eviction():
    """A speculative burst that needs an extra page must never fund it
    by evicting another request: shedding the drafts is free (the
    request degrades to a plain decode this step), eviction costs a
    whole re-prefill.  Regression for the review finding where the
    shed branch was unreachable whenever a victim existed."""
    from hetu_tpu.serving import PagedKVPool, Request, Scheduler
    from hetu_tpu.serving.request import RUNNING
    pool = PagedKVPool(num_layers=1, num_pages=4, page_size=4,
                       kv_heads=1, head_dim=4)
    sched = Scheduler(pool, max_batch=2, chunk=4, prefill_rows=1)
    sched.verify_slots, sched.spec_width = 2, 4
    pa = pool.alloc(2)
    pb = pool.alloc(1)                  # free list now empty
    a = Request(req_id=0, prompt=[1] * 7, max_new_tokens=8,
                arrival_time=0.0)
    a.tokens = [1] * 8
    a.pos = 7                           # decode fits its 2 pages...
    a.pages = pa
    a.spec_drafts = [2, 3, 4]           # ...the burst needs a third
    b = Request(req_id=1, prompt=[1] * 3, max_new_tokens=4,
                arrival_time=1.0)
    b.tokens = [1] * 4
    b.pos = 3
    b.pages = pb
    a.state = b.state = RUNNING
    kept, evicted = sched.ensure_decode_pages([a, b])
    assert evicted == []                # nobody paid for the burst
    assert a.spec_drafts == []          # the burst was shed instead
    assert kept == [a, b]
    assert a.pages == pa and b.pages == pb


# ---------------------------------------------------------------------------
# KV-rewind honesty: the lint on real and seeded taps
# ---------------------------------------------------------------------------

def test_spec_rewind_leak_rule_clean_on_real_trace(gpt):
    """The real engine tap — with non-vacuous rewinds — satisfies the
    spec-rewind-leak contract, and the cow/trash rules still hold on
    verify-row write plans that cross page boundaries."""
    from hetu_tpu.analysis.rules import AnalysisContext, run_rules
    state, cfg, dstate, dcfg = gpt
    eng = _make_engine(state, cfg, num_pages=24, page_size=4,
                       max_batch=4, chunk_size=8,
                       spec=SpecConfig(dstate, dcfg, k=6))
    rng = np.random.RandomState(3)
    reqs = [eng.add_request(
        [int(t) for t in rng.randint(1, 90, size=7)], 12,
        arrival_time=0.0) for _ in range(3)]
    _drain(eng)
    for r in reqs:
        assert r.out_tokens == _solo(state, cfg, r.prompt, 12)
    tap = list(eng.tap)
    assert any(rec.get("kind") == "spec_rewind" for rec in tap), \
        "no rewind in the trace: the rule run is vacuous"
    ctx = AnalysisContext(
        name="t_spec", serving={"pool": eng.pool, "tap": tap})
    assert not run_rules(ctx, only=["spec-rewind-leak"])
    assert not run_rules(ctx, only=["trash-page-write"])
    assert not run_rules(ctx, only=["cow-page-write"])


def test_spec_rewind_leak_rule_fires_once_per_seed():
    """Seeded violation: a read past the rewound watermark before the
    re-write fires exactly once; the exempt record, a boundary-exact
    rewrite, and a kv_drop reset all stay silent."""
    from hetu_tpu.analysis.rules import AnalysisContext, run_rules
    tap = [
        {"kind": "unified", "reads": [(7, 0, 8, 8)]},
        {"kind": "spec_rewind", "req": 7, "valid_upto": 5,
         "written_upto": 8},
        # gap: resumes at 6 leaving stale position 5 in the window
        {"kind": "unified", "reads": [(7, 6, 2, 8)]},
    ]
    fired = run_rules(AnalysisContext(name="t", serving={"tap": tap}),
                      only=["spec-rewind-leak"])
    assert len(fired) == 1
    assert fired[0].severity == "error" and "req7" in fired[0].subject
    assert "rejected-draft KV" in fired[0].message
    assert fired[0].hint
    # exemption: the offending record flagged rewind_exempt
    tap_ex = [tap[0], tap[1], dict(tap[2], rewind_exempt=True)]
    assert not run_rules(
        AnalysisContext(name="t2", serving={"tap": tap_ex}),
        only=["spec-rewind-leak"])
    # clean: the next burst re-writes from the boundary exactly
    tap_ok = [tap[0], tap[1],
              {"kind": "unified", "reads": [(7, 5, 3, 8)]}]
    assert not run_rules(
        AnalysisContext(name="t3", serving={"tap": tap_ok}),
        only=["spec-rewind-leak"])
    # preemption (kv_drop) resets the watermark: re-prefill from 0
    tap_drop = [tap[0], tap[1], {"kind": "kv_drop", "req": 7},
                {"kind": "unified", "reads": [(7, 0, 4, 4)]}]
    assert not run_rules(
        AnalysisContext(name="t4", serving={"tap": tap_drop}),
        only=["spec-rewind-leak"])


# ---------------------------------------------------------------------------
# metrics: counters, reset, cluster-merged exposition
# ---------------------------------------------------------------------------

def test_spec_metrics_reset_and_prometheus(gpt):
    state, cfg, dstate, dcfg = gpt
    eng = _make_engine(state, cfg, num_pages=16, page_size=8,
                       max_batch=2, chunk_size=8,
                       spec=SpecConfig(dstate, dcfg, k=3))
    eng.add_request([5, 17, 2, 9], 8, arrival_time=0.0)
    _drain(eng)
    m = eng.metrics_summary()
    assert m["spec_proposed"] > 0
    assert 0.0 <= m["spec_accept_rate"] <= 1.0
    assert m["accepted_per_step"] > 0
    text = eng.metrics_text()
    for name in ("spec_proposed", "spec_accepted", "spec_bonus_tokens"):
        assert name in text
    eng.reset_metrics()
    m = eng.metrics_summary()
    assert m["spec_proposed"] == 0 and m["spec_accepted"] == 0
    assert m["spec_bonus_tokens"] == 0
    assert m["spec_accept_rate"] == 0.0 and m["accepted_per_step"] == 0.0


def test_spec_counters_in_cluster_merged_exposition(gpt):
    """The PR 11 cluster plane passes spec straight through: counters
    sum in metrics_summary and appear per replica in the merged
    Prometheus exposition; reset zeroes the merged view too."""
    from hetu_tpu.serving import EngineCluster
    state, cfg, dstate, dcfg = gpt
    clock = [0.0]
    cl = EngineCluster(state, cfg, num_replicas=2, name="spec_cl_t",
                       num_pages=16, page_size=8, max_batch=4,
                       chunk_size=8, time_fn=lambda: clock[0],
                       ttl=3600.0, spec=SpecConfig(dstate, dcfg, k=3))
    try:
        r1 = cl.add_request([5, 17, 2, 9, 1, 4, 8], max_new_tokens=6)
        r2 = cl.add_request([3, 2, 1, 9], max_new_tokens=6)
        guard = 0
        while cl.has_work:
            cl.step()
            clock[0] += 1.0
            guard += 1
            assert guard < 200
        for r, n in ((r1, 7), (r2, 4)):
            assert r.out_tokens == _solo(state, cfg, r.prompt, 6)
        ms = cl.metrics_summary()
        assert ms["spec_proposed"] > 0
        text = cl.metrics_text()
        assert "spec_proposed" in text and 'replica="r0"' in text
        for rep in cl.replicas:
            rep.engine.reset_metrics()
            # the engine-level view zeroes...
            assert rep.engine.metrics_summary()["spec_proposed"] == 0
        # ...the merged exposition now reports per-replica zeros...
        for line in cl.metrics_text().splitlines():
            if line.startswith("spec_proposed{"):
                assert line.rstrip().endswith(" 0")
        # ...and the CLUSTER sum banks the pre-reset epoch (PR 11's
        # reset-robust contract: a replica reset never loses history)
        assert cl.metrics_summary()["spec_proposed"] == \
            ms["spec_proposed"]
    finally:
        cl.close()
