"""Coalesced + quantized gradient collectives (reference AllReduceCoalesce,
comm_group.h:27-144; EQuARX quantized allreduce, PAPERS.md).

Pins down: bucket planning, bit-exactness of the fused fp32 path against
per-tensor psum, the loss-equivalence tolerance tiers of the bf16/int8
transports, the split-group variants, the DistributedStates prediction of
the emitted collective sequence, and the graph-level explicit grad-comm
path (optimizer grad_comm= wiring).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import ops, optim
from hetu_tpu.parallel import comm, create_mesh, dstates
from hetu_tpu.parallel.comm import shard_map

SHAPES = [(64, 32), (32,), (128, 8), (7, 5), (256,)]


def _grads(seed=0, shapes=SHAPES, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return [rng.randn(*s).astype(dtype) for s in shapes]


def _mesh8(devices8):
    return create_mesh({"dp": 8}, devices8)


def _run_sync(mesh, fn, arrays):
    reps = tuple(P() for _ in arrays)
    return jax.jit(shard_map(fn, mesh, reps, reps))(*arrays)


def _rankful(vals, axis="dp"):
    """Make per-rank-distinct inputs from replicated ones."""
    return [v + jax.lax.axis_index(axis).astype(v.dtype) for v in vals]


class TestBucketPlan:
    def test_cap_splits_buckets(self):
        entries = [(i, (1024,), "float32") for i in range(8)]  # 4KB each
        bs = comm.plan_buckets(entries, bucket_mb=8 / 1024.0)  # 8KB cap
        assert len(bs) == 4
        assert all(b.nbytes == 8192 for b in bs)
        # order preserved
        assert [k for b in bs for k in b.keys] == list(range(8))

    def test_dtype_separation(self):
        entries = [(0, (16,), "float32"), (1, (16,), "bfloat16"),
                   (2, (16,), "float32")]
        bs = comm.plan_buckets(entries, bucket_mb=4.0)
        assert len(bs) == 2
        by_dtype = {b.dtype: b.keys for b in bs}
        assert by_dtype["float32"] == (0, 2)
        assert by_dtype["bfloat16"] == (1,)

    def test_oversized_tensor_own_bucket(self):
        entries = [(0, (100,), "float32"), (1, (10_000,), "float32"),
                   (2, (100,), "float32")]
        bs = comm.plan_buckets(entries, bucket_mb=1 / 1024.0)  # 1KB cap
        assert (1,) in [b.keys for b in bs]


class TestCoalescedAllReduce:
    def test_fp32_bit_identical_to_per_tensor(self, devices8):
        mesh = _mesh8(devices8)
        arrays = _grads()

        def coalesced(*vals):
            g = {i: v for i, v in enumerate(_rankful(vals))}
            out = comm.all_reduce_coalesced(g, "dp", bucket_mb=0.01)
            return tuple(out[i] for i in range(len(vals)))

        def per_tensor(*vals):
            return tuple(jax.lax.psum(v, "dp") for v in _rankful(vals))

        got = _run_sync(mesh, coalesced, arrays)
        want = _run_sync(mesh, per_tensor, arrays)
        for a, b in zip(got, want):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_mean_matches_pmean(self, devices8):
        mesh = _mesh8(devices8)
        arrays = _grads(1)

        def coalesced(*vals):
            g = {i: v for i, v in enumerate(_rankful(vals))}
            out = comm.all_reduce_coalesced(g, "dp", op="mean")
            return tuple(out[i] for i in range(len(vals)))

        def per_tensor(*vals):
            return tuple(jax.lax.pmean(v, "dp") for v in _rankful(vals))

        got = _run_sync(mesh, coalesced, arrays)
        want = _run_sync(mesh, per_tensor, arrays)
        for a, b in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6)

    # loss-equivalence tolerance tiers: bf16 carries ~8 mantissa bits
    # (rel ~4e-3 after two casts); int8 blockwise-absmax quantizes each
    # element twice -> ~2/127 of the block absmax
    @pytest.mark.parametrize("transport,tol", [("bf16", 1e-2),
                                               ("int8", 2.5e-2)])
    def test_quantized_tolerance_tiers(self, devices8, transport, tol):
        mesh = _mesh8(devices8)
        arrays = _grads(2)

        def coalesced(*vals):
            g = {i: v for i, v in enumerate(_rankful(vals))}
            out = comm.all_reduce_coalesced(g, "dp", transport=transport)
            return tuple(out[i] for i in range(len(vals)))

        def per_tensor(*vals):
            return tuple(jax.lax.psum(v, "dp") for v in _rankful(vals))

        got = _run_sync(mesh, coalesced, arrays)
        want = _run_sync(mesh, per_tensor, arrays)
        for a, b in zip(got, want):
            b = np.asarray(b)
            rel = np.max(np.abs(np.asarray(a) - b)) / np.max(np.abs(b))
            assert rel < tol, (transport, rel)

    def test_list_input_returns_list(self, devices8):
        mesh = _mesh8(devices8)
        arrays = _grads(3, shapes=[(8,), (4, 4)])

        def f(*vals):
            out = comm.all_reduce_coalesced(list(vals), "dp")
            assert isinstance(out, list)
            return tuple(out)

        got = _run_sync(mesh, f, arrays)
        for a, v in zip(got, arrays):
            np.testing.assert_allclose(np.asarray(a), 8 * v, rtol=1e-6)

    def test_bad_transport_raises(self):
        with pytest.raises(ValueError, match="transport"):
            comm.all_reduce_coalesced({0: jnp.zeros(4)}, "dp",
                                      transport="fp8")


class TestReduceScatterCoalesced:
    def test_rs_ag_composes_to_allreduce(self, devices8):
        mesh = _mesh8(devices8)
        arrays = _grads(4)

        def f(*vals):
            g = {i: v for i, v in enumerate(_rankful(vals))}
            chunks, layout = comm.reduce_scatter_coalesced(g, "dp")
            out = comm.all_gather_coalesced(chunks, layout, "dp")
            return tuple(out[i] for i in range(len(vals)))

        def per_tensor(*vals):
            return tuple(jax.lax.psum(v, "dp") for v in _rankful(vals))

        got = _run_sync(mesh, f, arrays)
        want = _run_sync(mesh, per_tensor, arrays)
        for a, b in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6)

    def test_list_round_trip_returns_list(self, devices8):
        mesh = _mesh8(devices8)
        arrays = _grads(10, shapes=[(8,), (4, 4)])

        def f(*vals):
            chunks, layout = comm.reduce_scatter_coalesced(
                list(vals), "dp")
            out = comm.all_gather_coalesced(chunks, layout, "dp")
            assert isinstance(out, list)
            return tuple(out)

        got = _run_sync(mesh, f, arrays)
        for a, v in zip(got, arrays):
            np.testing.assert_allclose(np.asarray(a), 8 * v, rtol=1e-6)

    def test_quantized_rs_ag(self, devices8):
        mesh = _mesh8(devices8)
        arrays = _grads(5)

        def f(*vals):
            g = {i: v for i, v in enumerate(_rankful(vals))}
            chunks, layout = comm.reduce_scatter_coalesced(
                g, "dp", transport="int8")
            out = comm.all_gather_coalesced(chunks, layout, "dp",
                                            transport="int8")
            return tuple(out[i] for i in range(len(vals)))

        def per_tensor(*vals):
            return tuple(jax.lax.psum(v, "dp") for v in _rankful(vals))

        got = _run_sync(mesh, f, arrays)
        want = _run_sync(mesh, per_tensor, arrays)
        for a, b in zip(got, want):
            b = np.asarray(b)
            rel = np.max(np.abs(np.asarray(a) - b)) / np.max(np.abs(b))
            assert rel < 2.5e-2


class TestSplitCoalesced:
    GROUPS = [[0, 1, 2], [3, 4, 5, 6, 7]]  # unequal 3 + 5

    def test_split_all_reduce_coalesced_unequal(self, devices8):
        mesh = _mesh8(devices8)
        arrays = _grads(6, shapes=[(16,), (3, 3)])

        def coalesced(*vals):
            g = {i: v for i, v in enumerate(_rankful(vals))}
            out = comm.split_all_reduce_coalesced(g, "dp", self.GROUPS)
            return tuple(out[i] for i in range(len(vals)))

        def per_tensor(*vals):
            return tuple(comm.split_all_reduce(v, "dp", self.GROUPS)
                         for v in _rankful(vals))

        got = _run_sync(mesh, coalesced, arrays)
        want = _run_sync(mesh, per_tensor, arrays)
        for a, b in zip(got, want):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_split_equal_groups_quantized(self, devices8):
        mesh = _mesh8(devices8)
        groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
        arrays = _grads(7, shapes=[(64,)])

        def coalesced(*vals):
            g = {i: v for i, v in enumerate(_rankful(vals))}
            out = comm.split_all_reduce_coalesced(g, "dp", groups,
                                                  transport="int8")
            return tuple(out[i] for i in range(len(vals)))

        def per_tensor(*vals):
            return tuple(comm.split_all_reduce(v, "dp", groups)
                         for v in _rankful(vals))

        got = _run_sync(mesh, coalesced, arrays)
        want = _run_sync(mesh, per_tensor, arrays)
        for a, b in zip(got, want):
            b = np.asarray(b)
            rel = np.max(np.abs(np.asarray(a) - b)) / np.max(np.abs(b))
            assert rel < 2.5e-2

    def test_split_unequal_quantized_raises(self, devices8):
        mesh = _mesh8(devices8)
        arrays = _grads(8, shapes=[(16,)])

        def f(*vals):
            return tuple(comm.split_all_reduce_coalesced(
                {0: vals[0]}, "dp", self.GROUPS,
                transport="int8").values())

        with pytest.raises(ValueError, match="equal-size"):
            _run_sync(mesh, f, arrays)

    def test_split_reduce_scatter_coalesced_unequal(self, devices8):
        mesh = _mesh8(devices8)
        # one bucket of 30 elements (divisible by 3 and 5); rank r
        # contributes r everywhere; expect each rank's shard to hold its
        # group's sum in its first L//group_size rows (padded contract)
        x = np.repeat(np.arange(8, dtype=np.float32), 30)   # [240]

        def f(v):
            shards, layout = comm.split_reduce_scatter_coalesced(
                {0: v}, "dp", self.GROUPS)
            assert layout.buckets[0].numels == (30,)
            return shards[0]

        out = np.asarray(jax.jit(shard_map(
            f, mesh, (P("dp"),), P("dp")))(x)).reshape(8, -1)
        for g in self.GROUPS:
            gsum = sum(float(i) for i in g)
            chunk = 30 // len(g)
            for r in g:
                np.testing.assert_allclose(out[r, :chunk], gsum)
                np.testing.assert_allclose(out[r, chunk:], 0.0)


class TestPrediction:
    """dstates predicts the fused collective sequence; the lowered XLA
    program must contain exactly it (and trace-time CommStats agree)."""

    @pytest.mark.parametrize("transport", ["fp32", "bf16", "int8"])
    def test_prediction_matches_hlo_and_stats(self, devices8, transport):
        mesh = _mesh8(devices8)
        arrays = _grads(9)
        entries = [(i, a.shape, a.dtype) for i, a in enumerate(arrays)]
        pred = dstates.predict_grad_comm_collectives(
            entries, 8, bucket_mb=4.0, transport=transport)

        def f(*vals):
            out = comm.all_reduce_coalesced(
                {i: v for i, v in enumerate(vals)}, "dp",
                bucket_mb=4.0, transport=transport)
            return tuple(out[i] for i in range(len(vals)))

        reps = tuple(P() for _ in arrays)
        jf = jax.jit(shard_map(f, mesh, reps, reps))
        with comm.comm_stats() as s:
            lowered = jf.lower(*arrays)
        dstates.verify_grad_comm_emission(lowered.as_text(), pred)
        assert s.num_collectives == len(pred)
        np.testing.assert_allclose(
            s.total_wire_bytes, sum(p["wire_bytes"] for p in pred))

    def test_mismatch_raises(self):
        pred = [{"kind": "all_reduce", "payload_bytes": 4,
                 "wire_bytes": 7.0, "dtype": "float32"}]
        with pytest.raises(AssertionError, match="do not match"):
            dstates.verify_grad_comm_emission("no collectives here", pred)


class TestGraphExplicitGradComm:
    """Optimizer grad_comm wiring: the executable build runs fwd+bwd in a
    manual dp region and syncs micro-batch-accumulated grads once per
    step through fused (quantized) buckets."""

    def _train(self, devices8, grad_comm, zero=0, nmb=1, steps=4):
        mesh = create_mesh({"dp": 8}, devices8)
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            x = ht.parallel_placeholder("float32", (16, 8),
                                        pspec=P("dp", None), name="x")
            y = ht.parallel_placeholder("float32", (16, 1),
                                        pspec=P("dp", None), name="y")
            w = ht.parameter(np.linspace(-1, 1, 8).reshape(8, 1)
                             .astype(np.float32), name="w")
            b = ht.parameter(np.zeros((1,), np.float32), name="b")
            loss = ops.reduce_mean((ops.matmul(x, w) + b - y) ** 2)
            op = optim.AdamOptimizer(lr=1e-2, zero=zero,
                                     grad_comm=grad_comm).minimize(loss)
            rng = np.random.RandomState(0)
            X = rng.randn(16, 8).astype(np.float32)
            Y = rng.randn(16, 1).astype(np.float32)
            losses = []
            for _ in range(steps):
                out = g.run(loss, [loss, op], {x: X, y: Y},
                            num_micro_batches=nmb)
                losses.append(float(out[0]))
            return losses, g

    def test_fp32_explicit_matches_implicit(self, devices8):
        base, g0 = self._train(devices8, None)
        assert not g0._grad_comm_active
        got, g1 = self._train(devices8, "fp32")
        assert g1._grad_comm_active, g1._grad_comm_fallback
        np.testing.assert_allclose(got, base, rtol=1e-6)

    @pytest.mark.parametrize("transport,tol", [("bf16", 5e-3),
                                               ("int8", 5e-3)])
    def test_quantized_loss_curve_tolerance(self, devices8, transport,
                                            tol):
        base, _ = self._train(devices8, None)
        got, g = self._train(devices8, transport)
        assert g._grad_comm_active, g._grad_comm_fallback
        np.testing.assert_allclose(got, base, rtol=tol)

    def test_zero2_and_micro_batches(self, devices8):
        base, _ = self._train(devices8, None)
        z2, g2 = self._train(devices8, "fp32", zero=2)
        assert g2._grad_comm_active, g2._grad_comm_fallback
        np.testing.assert_allclose(z2, base, rtol=1e-6)
        mb, gm = self._train(devices8, "fp32", nmb=2)
        assert gm._grad_comm_active
        # micro-batched accumulation reorders the sums; close, not exact
        np.testing.assert_allclose(mb, base, rtol=1e-4)

    def test_fallback_on_mixed_mesh(self, devices8):
        mesh = create_mesh({"dp": 4, "tp": 2}, devices8)
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            x = ht.parallel_placeholder("float32", (8, 8),
                                        pspec=P("dp", None), name="x")
            y = ht.parallel_placeholder("float32", (8, 1),
                                        pspec=P("dp", None), name="y")
            w = ht.parameter(np.zeros((8, 1), np.float32), name="w")
            loss = ops.reduce_mean((ops.matmul(x, w) - y) ** 2)
            op = optim.AdamOptimizer(lr=1e-2,
                                     grad_comm="int8").minimize(loss)
            rng = np.random.RandomState(0)
            g.run(loss, [loss, op], {x: rng.randn(8, 8).astype(np.float32),
                                     y: rng.randn(8, 1).astype(np.float32)})
            assert not g._grad_comm_active
            assert "pure-dp" in g._grad_comm_fallback

    def test_fallback_on_non_loss_scalar_fetch(self, devices8):
        """A scalar fetch that is NOT the loss has unknown reduction
        semantics under manual dp (a sum would become sum/n) — the
        explicit path must fall back rather than silently pmean it."""
        mesh = create_mesh({"dp": 8}, devices8)
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            x = ht.parallel_placeholder("float32", (16, 8),
                                        pspec=P("dp", None), name="x")
            y = ht.parallel_placeholder("float32", (16, 1),
                                        pspec=P("dp", None), name="y")
            w = ht.parameter(np.zeros((8, 1), np.float32), name="w")
            err = (ops.matmul(x, w) - y) ** 2
            loss = ops.reduce_mean(err)
            total = ops.reduce_sum(err)     # global SUM, not a mean
            op = optim.AdamOptimizer(lr=1e-2,
                                     grad_comm="fp32").minimize(loss)
            rng = np.random.RandomState(0)
            X = rng.randn(16, 8).astype(np.float32)
            Y = rng.randn(16, 1).astype(np.float32)
            out = g.run(loss, [loss, total, op], {x: X, y: Y})
            assert not g._grad_comm_active
            assert "scalar fetch" in g._grad_comm_fallback
            # the implicit path must still produce the true global sum
            np.testing.assert_allclose(float(out[1]),
                                       16 * float(out[0]), rtol=1e-5)

    def test_fallback_on_sum_reduced_loss(self, devices8):
        """Grad sync is dp-MEAN (DDP semantics); a sum-reduced loss
        would silently train with 1/dp-scaled grads — must fall back."""
        mesh = create_mesh({"dp": 8}, devices8)
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            x = ht.parallel_placeholder("float32", (16, 8),
                                        pspec=P("dp", None), name="x")
            y = ht.parallel_placeholder("float32", (16, 1),
                                        pspec=P("dp", None), name="y")
            w = ht.parameter(np.zeros((8, 1), np.float32), name="w")
            loss = ops.reduce_sum((ops.matmul(x, w) - y) ** 2)
            op = optim.AdamOptimizer(lr=1e-2,
                                     grad_comm="fp32").minimize(loss)
            rng = np.random.RandomState(0)
            g.run(loss, [loss, op],
                  {x: rng.randn(16, 8).astype(np.float32),
                   y: rng.randn(16, 1).astype(np.float32)})
            assert not g._grad_comm_active
            assert "sum-reduced" in g._grad_comm_fallback

    def test_bad_grad_comm_value_raises(self):
        with pytest.raises(ValueError, match="grad_comm"):
            optim.AdamOptimizer(lr=1e-2, grad_comm="fp8")

    def test_introspection_tracks_executed_plan(self, devices8):
        """_grad_comm_active must reflect the plan actually run, not the
        last grad-comm-requesting build on the graph."""
        mesh = create_mesh({"dp": 8}, devices8)
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            x = ht.parallel_placeholder("float32", (16, 8),
                                        pspec=P("dp", None), name="x")
            y = ht.parallel_placeholder("float32", (16, 1),
                                        pspec=P("dp", None), name="y")
            w = ht.parameter(np.zeros((8, 1), np.float32), name="w")
            loss = ops.reduce_mean((ops.matmul(x, w) - y) ** 2)
            op_gc = optim.SGDOptimizer(lr=0.1,
                                       grad_comm="fp32").minimize(loss)
            op_plain = optim.SGDOptimizer(lr=0.1).minimize(loss)
            rng = np.random.RandomState(0)
            feed = {x: rng.randn(16, 8).astype(np.float32),
                    y: rng.randn(16, 1).astype(np.float32)}
            g.run(loss, [loss, op_gc], feed)
            assert g._grad_comm_active
            g.run(loss, [loss, op_plain], feed)
            assert not g._grad_comm_active
            g.run(loss, [loss, op_gc], feed)   # cached plan, re-executed
            assert g._grad_comm_active

    def test_grouped_layout_gather_raises(self, devices8):
        mesh = create_mesh({"dp": 8}, devices8)
        x = np.zeros((240,), np.float32)

        def f(v):
            shards, layout = comm.split_reduce_scatter_coalesced(
                {0: v}, "dp", [[0, 1, 2], [3, 4, 5, 6, 7]])
            comm.all_gather_coalesced(shards, layout, "dp")
            return v

        with pytest.raises(NotImplementedError, match="grouped"):
            jax.jit(shard_map(f, mesh, (P("dp"),), P("dp")))(x)


class TestGPTDPZeRO2GradComm:
    """Acceptance: a GPT DP+ZeRO2 run with grad_comm='int8' matches the
    fp32 loss curve within the documented tolerance (DESIGN.md §7)."""

    def _train_gpt(self, devices8, grad_comm, steps=3):
        from hetu_tpu.graph import ctor
        from hetu_tpu.models import GPTLMHeadModel, llama_config
        ctor._seed_counter[0] = 12345
        mesh = create_mesh({"dp": 8}, devices8)
        cfg = llama_config(vocab_size=64, hidden_size=32, num_layers=2,
                           num_heads=4, max_seq_len=16, sp=False)
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            ids = ht.parallel_placeholder("int32", (8, 16),
                                          pspec=P("dp", None), name="ids")
            labels = ht.parallel_placeholder("int32", (8, 16),
                                             pspec=P("dp", None),
                                             name="labels")
            model = GPTLMHeadModel(cfg)
            loss = model(ids, labels)
            train_op = optim.AdamOptimizer(
                lr=1e-2, zero=2, grad_comm=grad_comm).minimize(loss)
            rng = np.random.RandomState(0)
            IDS = rng.randint(0, 64, (8, 16)).astype(np.int32)
            L = np.roll(IDS, -1, axis=1)
            losses = []
            for _ in range(steps):
                out = g.run(loss, [loss, train_op], {ids: IDS, labels: L})
                losses.append(float(np.asarray(out[0])))
        return losses, g

    def test_int8_matches_fp32_loss_curve(self, devices8):
        base, g0 = self._train_gpt(devices8, None)
        q, g1 = self._train_gpt(devices8, "int8")
        assert not g0._grad_comm_active
        assert g1._grad_comm_active, g1._grad_comm_fallback
        np.testing.assert_allclose(q, base, rtol=5e-3)
