"""Embedding subsystem tests: cache policies (native vs python), the
HET-style cached embedding, the host PS, and CTR models."""
import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu import nn, ops, optim
from hetu_tpu.embedding import CachedEmbedding, CachePolicy, \
    HostParameterServer
from hetu_tpu.embedding.cache import _PyCache
from hetu_tpu.models.ctr import DCN, DeepFM, WDL, ctr_loss


class TestCachePolicy:
    def test_native_builds(self):
        from hetu_tpu.csrc.build import load_embed_cache_core
        assert load_embed_cache_core() is not None

    @pytest.mark.parametrize("policy", ["lru", "lfu", "lfuopt"])
    def test_basic_hit_miss(self, policy):
        c = CachePolicy(4, policy)
        slots, miss, ek, es = c.lookup(np.array([1, 2, 3]))
        assert miss.all() and len(ek) == 0
        assert len(set(slots.tolist())) == 3
        s2, m2, _, _ = c.lookup(np.array([1, 2, 3]))
        assert not m2.any()
        np.testing.assert_array_equal(slots, s2)

    def test_lru_evicts_least_recent(self):
        c = CachePolicy(2, "lru")
        c.lookup(np.array([1]))
        c.lookup(np.array([2]))
        c.lookup(np.array([1]))          # 1 is now most recent
        _, _, ek, _ = c.lookup(np.array([3]))
        assert ek.tolist() == [2]

    def test_lfu_evicts_least_frequent(self):
        c = CachePolicy(2, "lfu")
        for _ in range(3):
            c.lookup(np.array([1]))      # freq(1) = 3
        c.lookup(np.array([2]))          # freq(2) = 1
        _, _, ek, _ = c.lookup(np.array([3]))
        assert ek.tolist() == [2]

    def test_repeated_keys_in_one_batch(self):
        c = CachePolicy(4, "lru")
        slots, miss, _, _ = c.lookup(np.array([7, 7, 7, 8]))
        assert slots[0] == slots[1] == slots[2] != slots[3]
        assert miss.tolist() == [True, False, False, True]

    def test_eviction_returns_slot_for_reuse(self):
        c = CachePolicy(2, "lru")
        s1, _, _, _ = c.lookup(np.array([1, 2]))
        _, _, ek, es = c.lookup(np.array([3]))
        assert len(ek) == 1
        assert es[0] in s1  # reused one of the two slots
        assert len(c) == 2

    @pytest.mark.parametrize("policy", ["lru", "lfu", "lfuopt"])
    def test_batch_keys_pinned_against_eviction(self, policy):
        """Keys of the current batch must never be evicted within the
        same lookup, so every returned slot stays valid."""
        c = CachePolicy(2, policy)
        c.lookup(np.array([1, 2]))
        slots, _, ek, _ = c.lookup(np.array([3, 4]))
        assert sorted(ek.tolist()) == [1, 2]      # not 3!
        assert len(set(slots.tolist())) == 2
        # resident bookkeeping stays consistent under heavy churn
        resident = {}
        rng = np.random.RandomState(0)
        for _ in range(50):
            keys = np.unique(rng.randint(0, 40, 2))
            s, _, ek, _ = c.lookup(keys)
            for k in ek:
                resident.pop(int(k), None)
            for k, sl in zip(keys, s):
                resident[int(k)] = int(sl)
            assert len(resident) <= 2

    @pytest.mark.parametrize("policy", ["lru", "lfu", "lfuopt"])
    def test_oversized_batch_raises(self, policy):
        c = CachePolicy(2, policy)
        with pytest.raises(ValueError, match="cache limit"):
            c.lookup(np.array([1, 2, 3]))
        cp = CachePolicy(2, policy, use_native=False)
        with pytest.raises(ValueError, match="cache limit"):
            cp.lookup(np.array([1, 2, 3]))

    @pytest.mark.parametrize("policy", ["lru", "lfu", "lfuopt"])
    def test_native_matches_python(self, policy):
        rng = np.random.RandomState(0)
        nat = CachePolicy(8, policy, use_native=True)
        py = CachePolicy(8, policy, use_native=False)
        assert nat._lib is not None
        for _ in range(30):
            keys = rng.randint(0, 20, rng.randint(1, 6))
            sn, mn, ekn, _ = nat.lookup(keys)
            sp, mp, ekp, _ = py.lookup(keys)
            np.testing.assert_array_equal(mn, mp)
            np.testing.assert_array_equal(np.sort(ekn), np.sort(ekp))


class TestCachedEmbedding:
    def test_matches_full_embedding_training(self):
        """Cached embedding (cache smaller than vocab) must train to the
        same result as a plain embedding given identical data order."""
        N, D, B = 32, 8, 8
        rng = np.random.RandomState(0)
        batches = [rng.randint(0, N, B) for _ in range(12)]

        def run(cached):
            from hetu_tpu.graph import ctor
            ctor._seed_counter[0] = 99
            master = CachedEmbedding(N, D, cache_size=16, seed=1) \
                .master.copy()
            with ht.graph("define_and_run", create_new=True) as g:
                ids_ph = ht.placeholder("int32", (B,), name="ids")
                if cached:
                    emb = CachedEmbedding(N, D, cache_size=16, policy="lru",
                                          seed=1)
                    out = emb(ids_ph)
                else:
                    emb = None
                    w = ctor.parameter(ctor.ProvidedInitializer(master),
                                       (N, D), name="full")
                    out = ops.embedding_lookup(w, ids_ph)
                loss = ops.reduce_mean(out * out)
                train_op = optim.SGDOptimizer(lr=0.5).minimize(loss)
                losses = []
                for b in batches:
                    feed = emb.prepare_batch(b) if cached else \
                        b.astype(np.int32)
                    l, _ = g.run(loss, [loss, train_op], {ids_ph: feed})
                    losses.append(float(np.asarray(l)))
                if cached:
                    emb.flush()
                    table = emb.master.copy()
                else:
                    table = np.asarray(g.get_tensor_value(w))
            return losses, table

        lc, tc = run(True)
        lf, tf = run(False)
        np.testing.assert_allclose(lc, lf, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(tc, tf, rtol=1e-4, atol=1e-5)

    def test_staged_slot_gets_fresh_optimizer_state(self):
        """With attach_optimizer, a newly staged key must not inherit
        the evicted key's Adam m/v (slot-keyed state is zeroed)."""
        N, D = 8, 4
        with ht.graph("define_and_run", create_new=True) as g:
            emb = CachedEmbedding(N, D, cache_size=2, policy="lru", seed=3)
            opt = optim.AdamOptimizer(lr=0.1)
            emb.attach_optimizer(opt)
            ids_ph = ht.placeholder("int32", (2,), name="slots")
            loss = ops.reduce_mean(emb(ids_ph))
            train_op = opt.minimize(loss)
            # build momentum on keys 0,1
            for _ in range(3):
                g.run(loss, [train_op],
                      {ids_ph: emb.prepare_batch(np.array([0, 1]))})
            m = {k: np.asarray(v) for k, v in opt._state["m"].items()}
            tid = emb.cache_table.id
            assert np.abs(m[tid]).max() > 0
            # stage keys 2,3 -> evicts 0,1; their slots' m/v must be zero
            slots = emb.prepare_batch(np.array([2, 3]))
            m_after = np.asarray(opt._state["m"][tid])
            assert np.abs(m_after[slots]).max() == 0

    def test_eviction_preserves_learned_rows(self):
        """Rows evicted from the cache must carry their updates back to
        the master (no silent loss of training)."""
        N, D = 8, 4
        with ht.graph("define_and_run", create_new=True) as g:
            emb = CachedEmbedding(N, D, cache_size=2, policy="lru", seed=2)
            ids_ph = ht.placeholder("int32", (2,), name="slots")
            out = emb(ids_ph)
            loss = ops.reduce_mean(out)
            train_op = optim.SGDOptimizer(lr=1.0).minimize(loss)
            before = emb.master[0].copy()
            g.run(loss, [train_op], {ids_ph: emb.prepare_batch(
                np.array([0, 1]))})
            # touch two other keys twice -> evicts 0 and 1
            g.run(loss, [train_op], {ids_ph: emb.prepare_batch(
                np.array([2, 3]))})
            g.run(loss, [train_op], {ids_ph: emb.prepare_batch(
                np.array([4, 5]))})
            assert not np.allclose(emb.master[0], before)  # write-back


class TestHostPS:
    def test_pull_push_roundtrip(self):
        ps = HostParameterServer(optimizer="sgd", lr=1.0)
        ps.register("emb", 10, 4, seed=0)
        rows = ps.pull("emb", [1, 3])
        ps.push("emb", [1, 3], np.ones((2, 4)))
        rows2 = ps.pull("emb", [1, 3])
        np.testing.assert_allclose(rows - 1.0, rows2)

    def test_duplicate_keys_summed(self):
        ps = HostParameterServer(optimizer="sgd", lr=1.0)
        ps.register("emb", 4, 2, seed=0)
        r0 = ps.pull("emb", [2])[0].copy()
        ps.push("emb", [2, 2, 2], np.ones((3, 2)))
        np.testing.assert_allclose(ps.pull("emb", [2])[0], r0 - 3.0)

    @pytest.mark.parametrize("opt", ["adagrad", "adam"])
    def test_sparse_optimizers_converge(self, opt):
        ps = HostParameterServer(optimizer=opt, lr=0.1)
        ps.register("emb", 6, 3, seed=1)
        target = np.zeros(3)
        for _ in range(200):
            row = ps.pull("emb", [2])[0]
            ps.push("emb", [2], (row - target)[None, :])
        assert np.abs(ps.pull("emb", [2])[0]).max() < 1e-2

    def test_untouched_rows_unchanged(self):
        ps = HostParameterServer()
        ps.register("emb", 5, 2, seed=0)
        before = ps.tables["emb"].copy()
        ps.push("emb", [0], np.ones((1, 2)))
        np.testing.assert_array_equal(ps.tables["emb"][1:], before[1:])


class TestCTRModels:
    def _data(self, B=16, F=5, vocab=50, nd=4, seed=0):
        rng = np.random.RandomState(seed)
        ids = rng.randint(0, vocab, (B, F)).astype(np.int32)
        dense = rng.randn(B, nd).astype(np.float32)
        # learnable rule: label depends on a dense feature
        labels = (dense[:, 0] > 0).astype(np.float32)
        return ids, dense, labels

    @pytest.mark.parametrize("cls", [WDL, DeepFM, DCN])
    def test_trains(self, cls):
        from hetu_tpu.graph import ctor
        ctor._seed_counter[0] = 7
        ids, dense, labels = self._data()
        with ht.graph("define_and_run", create_new=True) as g:
            sp = ht.placeholder("int32", ids.shape, name="sp")
            dn = ht.placeholder("float32", dense.shape, name="dn")
            lb = ht.placeholder("float32", labels.shape, name="lb")
            model = cls(num_sparse_fields=5, vocab_size=50,
                        embedding_dim=8, num_dense=4, hidden=(32, 32))
            loss = ctr_loss(model(sp, dn), lb)
            train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            losses = []
            for _ in range(30):
                l, _ = g.run(loss, [loss, train_op],
                             {sp: ids, dn: dense, lb: labels})
                losses.append(float(np.asarray(l)))
        assert losses[-1] < losses[0] * 0.7, losses[::10]

    def test_wdl_with_cached_embedding(self):
        """CTR model over the HET-style cached embedding backend."""
        from hetu_tpu.graph import ctor
        ctor._seed_counter[0] = 11
        ids, dense, labels = self._data(vocab=40)
        with ht.graph("define_and_run", create_new=True) as g:
            emb = CachedEmbedding(40 * 1, 8, cache_size=64, policy="lfu")
            sp = ht.placeholder("int32", ids.shape, name="sp")
            dn = ht.placeholder("float32", dense.shape, name="dn")
            lb = ht.placeholder("float32", labels.shape, name="lb")
            model = WDL(num_sparse_fields=5, vocab_size=40,
                        embedding_dim=8, num_dense=4, hidden=(32,),
                        embedding=emb)
            loss = ctr_loss(model(sp, dn), lb)
            train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            losses = []
            for _ in range(20):
                slots = emb.prepare_batch(ids)
                l, _ = g.run(loss, [loss, train_op],
                             {sp: slots, dn: dense, lb: labels})
                losses.append(float(np.asarray(l)))
            emb.flush()
        assert losses[-1] < losses[0]
