"""GNN (GCN / DistGCN-1.5D) + graph export tests."""
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import ops, optim
from hetu_tpu.models.gnn import (GCN, DistGCN15D, GCNLayer, SparseGCNLayer,
                                 normalize_adjacency)
from hetu_tpu.utils.graph_io import (export_graph_json, export_onnx,
                                     graph_summary)


def _fix_seed(v=13):
    from hetu_tpu.graph import ctor
    ctor._seed_counter[0] = v


def _toy_graph(n=16, classes=3, feat=8, seed=0):
    """Community graph: nodes in the same class are densely connected."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, n)
    adj = np.zeros((n, n), np.float32)
    for i in range(n):
        for j in range(n):
            p = 0.8 if labels[i] == labels[j] else 0.05
            if i != j and rng.rand() < p:
                adj[i, j] = adj[j, i] = 1.0
    X = np.eye(n, feat, dtype=np.float32) \
        + 0.1 * rng.randn(n, feat).astype(np.float32)
    return adj, X, labels.astype(np.int32)


class TestGCN:
    def test_normalize_adjacency(self):
        adj = np.array([[0, 1], [1, 0]], np.float32)
        a = normalize_adjacency(adj)
        assert a.shape == (2, 2)
        np.testing.assert_allclose(a, a.T)
        # rows of a normalized adjacency act like an averaging operator
        assert a.sum() <= 2 * 2

    def test_gcn_learns_communities(self):
        _fix_seed()
        adj, X, labels = _toy_graph()
        a_hat = normalize_adjacency(adj)
        with ht.graph("define_and_run", create_new=True) as g:
            model = GCN(8, 16, 3)
            A = ht.placeholder("float32", a_hat.shape, name="A")
            xi = ht.placeholder("float32", X.shape, name="x")
            yi = ht.placeholder("int32", labels.shape, name="y")
            loss = model(A, xi, yi)
            train_op = optim.AdamOptimizer(lr=5e-2).minimize(loss)
            losses = [float(np.asarray(
                g.run(loss, [loss, train_op],
                      {A: a_hat, xi: X, yi: labels})[0]))
                for _ in range(60)]
        assert losses[-1] < losses[0] * 0.5, losses[::20]

    def test_train_mask(self):
        _fix_seed()
        adj, X, labels = _toy_graph()
        a_hat = normalize_adjacency(adj)
        mask = np.zeros(16, bool)
        mask[:8] = True
        with ht.graph("define_and_run", create_new=True) as g:
            model = GCN(8, 16, 3)
            A = ht.placeholder("float32", a_hat.shape, name="A")
            xi = ht.placeholder("float32", X.shape, name="x")
            yi = ht.placeholder("int32", labels.shape, name="y")
            mi = ht.placeholder("bool", mask.shape, name="m")
            loss = model(A, xi, yi, train_mask=mi)
            (l,) = g.run(loss, [loss],
                         {A: a_hat, xi: X, yi: labels, mi: mask})
        assert np.isfinite(float(np.asarray(l)))

    def test_sparse_matches_dense(self):
        _fix_seed()
        adj, X, _ = _toy_graph()
        a_hat = normalize_adjacency(adj)
        src, dst = np.nonzero(a_hat)
        ew = a_hat[src, dst].astype(np.float32)
        with ht.graph("define_and_run", create_new=True) as g:
            _fix_seed()
            dense = GCNLayer(8, 4, activation=None, name="d")
            sparse = SparseGCNLayer(8, 4, num_nodes=16, activation=None,
                                    name="s")
            A = ht.placeholder("float32", a_hat.shape, name="A")
            xi = ht.placeholder("float32", X.shape, name="x")
            si = ht.placeholder("int32", src.shape, name="src")
            di = ht.placeholder("int32", dst.shape, name="dst")
            wi = ht.placeholder("float32", ew.shape, name="ew")
            od = dense(A, xi)
            os_ = sparse(xi, si, di, wi)
            vd, vs = g.run(od, [od, os_],
                           {A: a_hat, xi: X, si: src.astype(np.int32),
                            di: dst.astype(np.int32), wi: ew})
            # same weight? different params (separate layers) -> compare
            # aggregation against numpy oracle instead
            wd = np.asarray(g.get_tensor_value(dense.weight))
            ws = np.asarray(g.get_tensor_value(sparse.weight))
        np.testing.assert_allclose(np.asarray(vd), a_hat @ (X @ wd),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(vs), a_hat @ (X @ ws),
                                   rtol=1e-4, atol=1e-5)

    def test_distgcn_15d_matches_single_device(self, devices8):
        """1.5-D sharded GCN == single-device GCN (same init)."""
        adj, X, labels = _toy_graph()
        a_hat = normalize_adjacency(adj)

        def run(mesh_shape, devs=None):
            _fix_seed(55)
            mesh = ht.create_mesh(mesh_shape, devs) if mesh_shape else None
            with ht.graph("define_and_run", create_new=True,
                          mesh=mesh) as g:
                model = DistGCN15D(8, 16, 3) if mesh_shape else GCN(8, 16, 3)
                A = ht.parallel_placeholder(
                    "float32", a_hat.shape,
                    pspec=P("dp", None) if mesh else None, name="A")
                xi = ht.parallel_placeholder(
                    "float32", X.shape,
                    pspec=P("dp", None) if mesh else None, name="x")
                yi = ht.parallel_placeholder(
                    "int32", labels.shape,
                    pspec=P("dp") if mesh else None, name="y")
                loss = model(A, xi, yi)
                train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
                return [float(np.asarray(
                    g.run(loss, [loss, train_op],
                          {A: a_hat, xi: X, yi: labels})[0]))
                    for _ in range(4)]

        l1 = run(None)
        l2 = run({"dp": 4}, devices8[:4])
        np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=1e-5)


class TestGraphIO:
    def _graph(self):
        from hetu_tpu.graph.ctor import NormalInitializer, parameter
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (2, 4), name="x")
            w = parameter(NormalInitializer(0.0, 0.1), (4, 3), name="w")
            y = ops.softmax(ops.matmul(x, w))
        return g, y

    def test_export_json(self, tmp_path):
        g, y = self._graph()
        p = tmp_path / "graph.json"
        spec = export_graph_json(g, [y], path=str(p))
        assert spec["format"].startswith("hetu_tpu.graph")
        types = [op["op_type"] for op in spec["ops"]]
        assert "matmul" in types and "softmax" in types
        import json
        loaded = json.load(open(p))
        assert loaded["ops"] == spec["ops"]
        # onnx mapping annotated
        mm = next(op for op in spec["ops"] if op["op_type"] == "matmul")
        assert mm["onnx_op"] == "MatMul"

    def test_graph_summary(self):
        g, y = self._graph()
        s = graph_summary(g, [y])
        assert "matmul" in s and "->" in s

    def test_onnx_gated(self, tmp_path):
        g, y = self._graph()
        try:
            import onnx  # noqa: F401
            have_onnx = True
        except ImportError:
            have_onnx = False
        if have_onnx:
            export_onnx(g, [y], str(tmp_path / "m.onnx"))
        else:
            with pytest.raises(ImportError, match="onnx"):
                export_onnx(g, [y], str(tmp_path / "m.onnx"))


def _onnx_stub():
    """A minimal stand-in for the ``onnx`` package (not baked into this
    image): just enough of helper/numpy_helper/TensorProto for
    export_onnx -> import_onnx to round-trip through OUR mapping logic.
    With the real package installed the same test runs against it."""
    import pickle
    import types
    from types import SimpleNamespace as NS

    onnx = types.ModuleType("onnx")
    helper = types.ModuleType("onnx.helper")
    numpy_helper = types.ModuleType("onnx.numpy_helper")
    checker = types.ModuleType("onnx.checker")
    onnx.TensorProto = NS(FLOAT=1, FLOAT16=10, BFLOAT16=16, INT32=6,
                          INT64=7, BOOL=9)
    _np_of = {1: "float32", 10: "float16", 6: "int32", 7: "int64",
              9: "bool"}

    def make_tensor_value_info(name, dt, shape):
        dims = [NS(dim_value=int(d)) for d in shape]
        return NS(name=name,
                  type=NS(tensor_type=NS(elem_type=dt,
                                         shape=NS(dim=dims))))

    def make_node(op, inputs, outputs, name="", **attrs):
        return NS(op_type=op, input=list(inputs), output=list(outputs),
                  name=name,
                  attribute=[NS(name=k, value=v) for k, v in attrs.items()])

    helper.make_tensor_value_info = make_tensor_value_info
    helper.make_node = make_node
    helper.make_graph = lambda nodes, name, inputs, outputs, \
        initializer=(): NS(node=list(nodes), name=name, input=list(inputs),
                           output=list(outputs),
                           initializer=list(initializer))
    helper.make_model = lambda g: NS(graph=g)
    helper.get_attribute_value = lambda a: a.value
    helper.tensor_dtype_to_np_dtype = \
        lambda dt: __import__("numpy").dtype(_np_of[dt])
    numpy_helper.from_array = lambda arr, name: NS(name=name, _arr=arr)
    numpy_helper.to_array = lambda init: init._arr
    checker.check_model = lambda m: None
    onnx.helper, onnx.numpy_helper, onnx.checker = (helper, numpy_helper,
                                                    checker)
    onnx.save = lambda m, path: pickle.dump(m, open(path, "wb"))
    onnx.load = lambda path: pickle.load(open(path, "rb"))
    return {"onnx": onnx, "onnx.helper": helper,
            "onnx.numpy_helper": numpy_helper, "onnx.checker": checker}


class TestOnnxRoundTrip:
    """export_onnx -> import_onnx -> same outputs (reference does both
    directions, hetu/v1/python/hetu/onnx/)."""

    def test_roundtrip_executes(self, tmp_path, monkeypatch):
        import numpy as np
        import sys
        from hetu_tpu.graph.ctor import NormalInitializer, parameter
        from hetu_tpu.utils.graph_io import export_onnx, import_onnx
        try:
            import onnx  # noqa: F401  (real package wins when present)
        except ImportError:
            for name, mod in _onnx_stub().items():
                monkeypatch.setitem(sys.modules, name, mod)
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (2, 4), name="x")
            w = parameter(NormalInitializer(0.0, 0.1), (3, 4), name="w")
            y = ops.softmax(ops.relu(ops.linear(x, w, None, trans_b=True)))
        wval = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        g.reset_variable(w, wval)
        X = np.random.RandomState(1).randn(2, 4).astype(np.float32)
        want = np.asarray(g.run(y, [y], {x: X})[0])

        path = str(tmp_path / "m.onnx")
        export_onnx(g, [y], path)
        with ht.graph("define_and_run", create_new=True) as g2:
            _, outs = import_onnx(path, graph=g2)
            assert len(outs) == 1
            ph = [t for op in g2.ops if op.op_type == "placeholder"
                  for t in op.outputs]
            got = np.asarray(g2.run(outs[0], [outs[0]], {ph[0]: X})[0])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestGraphImport:
    """Round-trip import (reference hetu/v1/python/hetu/onnx importers)."""

    def _graph(self):
        from hetu_tpu.graph.ctor import NormalInitializer, parameter
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (2, 4), name="x")
            w = parameter(NormalInitializer(0.0, 0.1), (4, 3), name="w")
            y = ops.softmax(ops.relu(ops.matmul(x, w)))
        return g, x, w, y

    def test_json_roundtrip_executes(self):
        from hetu_tpu.utils.graph_io import (export_graph_json,
                                             import_graph_json)
        import numpy as np
        g, x, w, y = self._graph()
        wval = np.random.RandomState(0).randn(4, 3).astype(np.float32)
        g.reset_variable(w, wval)
        spec = export_graph_json(g, [y])
        X = np.random.RandomState(1).randn(2, 4).astype(np.float32)
        (want,) = g.run(y, [y], {x: X})

        with ht.graph("define_and_run", create_new=True) as g2:
            g2b, tensors = import_graph_json(spec, graph=g2)
            # rebuilt tensors keyed by exported ids
            x2 = tensors[x.id]
            w2 = tensors[w.id]
            y2 = tensors[y.id]
            g2.reset_variable(w2, wval)
            (got,) = g2.run(y2, [y2], {x2: X})
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_import_rejects_foreign_format(self):
        from hetu_tpu.utils.graph_io import import_graph_json
        with pytest.raises(ValueError, match="not a hetu_tpu graph"):
            import_graph_json({"format": "other"})

    def test_onnx_import_gated(self, tmp_path):
        from hetu_tpu.utils.graph_io import export_onnx, import_onnx
        import numpy as np
        try:
            import onnx  # noqa: F401
        except ImportError:
            with pytest.raises(ImportError, match="onnx"):
                import_onnx(str(tmp_path / "m.onnx"))
            return
        g, x, w, y = self._graph()
        wval = np.random.RandomState(0).randn(4, 3).astype(np.float32)
        g.reset_variable(w, wval)
        p = str(tmp_path / "m.onnx")
        export_onnx(g, [y], p)
        X = np.random.RandomState(1).randn(2, 4).astype(np.float32)
        (want,) = g.run(y, [y], {x: X})
        with ht.graph("define_and_run", create_new=True) as g2:
            _, outs = import_onnx(p, graph=g2)
            # find the placeholder via the op list
            x2 = next(t for op in g2.ops if op.op_type == "placeholder"
                      for t in op.outputs)
            (got,) = g2.run(outs[0], [outs[0]], {x2: X})
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
