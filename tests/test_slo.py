"""SLO-driven traffic plane (ISSUE 17): priority classes, replica
autoscaling, and the host-RAM tier for cold KV pages.

Contracts covered:

- **class-aware scheduling** — rank-major service at the engine queue
  and the cluster front door, preemption victims lowest-class-first
  (asserted NON-vacuous: batch requests really are preempted under
  page pressure while interactive ones never are), FIFO within a
  class, and temperature-0 outputs bit-for-bit the solo ``generate()``
  regardless of class (class is policy, never computation);
- **shed order** — a full backlog displaces batch before turning away
  interactive; deadline sheds scan lowest-class-first; the
  ``class_inversions`` detector stays 0 throughout;
- **autoscaler** — scale-down drains through the router (no new
  placements) then fences via the EXISTING ``kill_replica`` path;
  scale-up readmits; co-completing requests are bitwise vs a static
  fleet; a chaos crash landing on the drain target mid-drain is
  absorbed without a double-drain;
- **host tier** — evict→refetch round-trips bit-for-bit vs a
  never-evicted engine for learned-MLA, rotary-MLA and int8-quantized
  page layouts, with both directions priced;
- **partial reclaim** (satellite): a lying reclaim hook degrades to a
  clean ``alloc() -> None`` (the preemption path), never a short
  grant, and the shortfall is counted.
"""
import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.fault import ChaosController, FaultEvent, FaultPlan, \
    check_cluster_invariants
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.models.generate import generate
from hetu_tpu.models.gpt import mla_state_from
from hetu_tpu.serving import Engine, EngineCluster
from hetu_tpu.serving.kv_pool import PagedKVPool
from hetu_tpu.serving.request import Request, RequestQueue
from hetu_tpu.serving.slo import (Autoscaler, ClassBacklog, SLO_CLASSES,
                                  class_rank)

CFG_KW = dict(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
              max_seq_len=64, sp=False, dropout=0.0)

# one packed-step shape for the whole module -> one compiled program
# (engines and clusters below share it via step_fn)
SHAPE_KW = dict(page_size=8, max_batch=4, chunk_size=8, prefill_rows=1,
                max_model_len=56)


@pytest.fixture(scope="module")
def model_state():
    cfg = GPTConfig(**CFG_KW)
    ht.set_seed(3)
    with ht.graph("eager", create_new=True):
        model = GPTLMHeadModel(cfg)
        model.logits(np.zeros((1, 4), np.int32))
        state = {k: np.asarray(v) for k, v in model.state_dict().items()}
    return state, cfg


@pytest.fixture(scope="module")
def shared_fn():
    from hetu_tpu.serving.decode import build_unified_step_fn
    cfg = GPTConfig(**CFG_KW)
    return build_unified_step_fn(
        cfg, SHAPE_KW["max_batch"], SHAPE_KW["chunk_size"],
        SHAPE_KW["prefill_rows"],
        -(-SHAPE_KW["max_model_len"] // SHAPE_KW["page_size"]),
        SHAPE_KW["page_size"], use_kernel=False)


def _solo(state, cfg, prompt, n_new):
    return np.asarray(generate(state, cfg,
                               np.asarray([prompt], np.int32), n_new,
                               temperature=0.0))[0, len(prompt):].tolist()


def _make_engine(state, cfg, **kw):
    clock = [0.0]
    kw.setdefault("time_fn", lambda: clock[0])
    kw.setdefault("debug", True)
    for k, v in SHAPE_KW.items():
        kw.setdefault(k, v)
    eng = Engine(state, cfg, **kw)
    eng._test_clock = clock
    return eng


def _make_cluster(state, cfg, fn=None, **kw):
    clock = [0.0]
    kw.setdefault("time_fn", lambda: clock[0])
    kw.setdefault("num_pages", 12)
    for k, v in SHAPE_KW.items():
        kw.setdefault(k, v)
    kw.setdefault("debug", True)
    kw.setdefault("ttl", 3600.0)
    # in-process fleet: death verdicts come from the serving flag, not
    # heartbeat TTL — kill_replica fences on the NEXT health sweep
    kw.setdefault("coordinator", False)
    cl = EngineCluster(state, cfg, step_fn=fn, **kw)
    cl._test_clock = clock
    return cl


def _drain(obj, limit=500, invariants=False):
    n = 0
    while obj.has_work:
        obj.step()
        obj._test_clock[0] += 1.0
        if invariants:
            check_cluster_invariants(obj)
        n += 1
        assert n < limit, "did not drain"
    return n


# ---------------------------------------------------------------------------
# units: classes, queue, backlog
# ---------------------------------------------------------------------------


def test_class_rank_and_validation():
    assert [class_rank(c) for c in SLO_CLASSES] == [0, 1, 2]
    with pytest.raises(ValueError):
        class_rank("platinum")
    with pytest.raises(ValueError):
        Request(req_id=0, prompt=[1], max_new_tokens=1,
                slo_class="platinum")


def test_request_queue_rank_major_with_per_class_arrival_gate():
    q = RequestQueue()
    mk = (lambda rid, c, t: Request(req_id=rid, prompt=[1],
                                    max_new_tokens=1, slo_class=c,
                                    arrival_time=t))
    q.push(mk(0, "batch", 0.0))
    q.push(mk(1, "interactive", 5.0))       # future
    q.push(mk(2, "standard", 0.0))
    # a FUTURE interactive must not gate an arrived lower class
    assert q.pop_ready(1.0).req_id == 2
    assert q.pop_ready(1.0).req_id == 0
    assert q.pop_ready(1.0) is None
    # once arrived, interactive outranks anything
    q.push(mk(3, "batch", 0.0))
    assert q.pop_ready(6.0).req_id == 1
    assert q.depth_by_class() == {"interactive": 0, "standard": 0,
                                  "batch": 1}


def test_class_backlog_shed_candidate_and_expired_head():
    class _C:
        def __init__(self, rid, c, arr):
            self.req_id, self.slo_class = rid, c
            self.arrival_time = self.submit_time = arr
    b = ClassBacklog()
    for rid, c, arr in ((0, "interactive", 0.0), (1, "batch", 0.0),
                        (2, "batch", 2.0), (3, "standard", 1.0)):
        b.push(_C(rid, c, arr))
    assert len(b) == 4 and bool(b)
    # iteration: rank-major 3-tuples (the chaos invariants' shape)
    assert [rid for _a, rid, _c in b] == [0, 3, 1, 2]
    # displacement victim: LATEST arrival of the LOWEST class
    assert b.shed_candidate().req_id == 2
    # deadline scan: lowest class first, arrival-gated
    assert b.expired_head(10.0, None) is None
    assert b.expired_head(10.0, 5.0).req_id == 1      # batch before std
    b.remove(b.shed_candidate())
    b.remove(b.expired_head(10.0, 5.0))
    assert b.expired_head(10.0, 5.0).req_id == 3      # std before inter
    assert b.depth_by_class() == {"interactive": 1, "standard": 1,
                                  "batch": 0}
    # heads are rank-major among ARRIVED entries only
    assert b.peek_ready(0.5).req_id == 0


# ---------------------------------------------------------------------------
# class-aware packing + preemption order (non-vacuous, bitwise)
# ---------------------------------------------------------------------------


def test_preemption_victims_lowest_class_first_bitwise(model_state,
                                                       shared_fn):
    """Page pressure on a mixed-class batch: the pool runs dry during
    decode growth and ONLY batch requests are preempted (asserted
    non-vacuous) — interactive requests keep their prefills, and every
    surviving output is still bit-for-bit solo ``generate()`` (class
    decides who waits, never what anyone computes)."""
    state, cfg = model_state
    # 8 usable pages = exactly the four 2-page prefills; the first
    # decode-growth past pos 16 MUST evict someone
    eng = _make_engine(state, cfg, num_pages=9, name="slo_preempt",
                       step_fn=shared_fn)
    classes = ["interactive", "batch", "interactive", "batch"]
    prompts, reqs = {}, []
    for i, c in enumerate(classes):
        p = [int(t) for t in range(2 + i, 14 + i)]    # 12 tokens: 2 pages
        r = eng.add_request(p, max_new_tokens=8, slo_class=c)
        prompts[r.req_id] = p
        reqs.append(r)
    _drain(eng)
    # pressure was real and fell class-ordered
    assert eng.counters["preempted_batch"].value >= 1, \
        "no batch preemption — the class-order claim is vacuous"
    assert eng.counters["preempted_interactive"].value == 0
    assert eng.counters["admitted_interactive"].value >= 2
    for r in reqs:
        assert eng.finished[r.req_id].out_tokens == \
            _solo(state, cfg, prompts[r.req_id], 8), r.req_id
    eng.pool.check_invariants(force=True)


# ---------------------------------------------------------------------------
# shed order at the cluster front door
# ---------------------------------------------------------------------------


def test_shed_order_displacement_and_deadline(model_state, shared_fn):
    state, cfg = model_state
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=1,
                       name="slo_shed", max_backlog=2,
                       max_queue_depth=1, request_deadline=5.0)
    # fill the bounded backlog with future batch arrivals
    for _ in range(3):
        cl.add_request([5, 6, 7], 3, arrival_time=100.0,
                       slo_class="batch")
    assert cl.counters["shed_batch"].value == 1        # backlog_full
    # an interactive arrival DISPLACES a queued batch entry
    r = cl.add_request([8, 9, 10], 3, arrival_time=100.0,
                       slo_class="interactive")
    assert not r.rejected
    assert cl.counters["shed_batch"].value == 2
    assert cl.shed and all(c.slo_class == "batch"
                           for c in cl.shed.values())
    assert cl._backlog.depth_by_class() == \
        {"interactive": 1, "standard": 0, "batch": 1}
    # a same-class arrival does NOT displace (FIFO keeps holding)
    r2 = cl.add_request([11, 12], 3, arrival_time=100.0,
                        slo_class="batch")
    assert r2.rejected and r2.reject_reason == "backlog_full"
    # deadline expiry under total backpressure: the single replica is
    # saturated by an interactive long-runner, so the queued batch
    # entry sheds past the deadline while interactive routes
    cl._test_clock[0] = 100.0
    _drain(cl)
    assert cl.counters["class_inversions"].value == 0
    assert cl.counters["shed_interactive"].value == 0
    ms = cl.metrics_summary()
    assert ms["shed_batch"] == ms["cluster_shed_batch"] == 3.0
    cl.close()


# ---------------------------------------------------------------------------
# autoscaler: bitwise vs static fleet, drain lifecycle, chaos overlay
# ---------------------------------------------------------------------------


def _mixed_trace(rng, n):
    out = []
    for i in range(n):
        size = int(rng.randint(4, 12))
        cls = SLO_CLASSES[int(rng.randint(3))]
        out.append(([int(t) for t in rng.randint(1, 90, size=size)],
                    cls, float(i)))
    return out


def test_autoscale_up_down_bitwise_vs_static_fleet(model_state,
                                                   shared_fn):
    """The autoscaler drains a replica on an idle fleet, readmits it
    under backlog pressure (both asserted non-vacuous), and the
    requests' outputs are token-for-token what the SAME trace produces
    on a static always-2-replica fleet — scaling is placement policy,
    never computation."""
    state, cfg = model_state
    rng = np.random.RandomState(11)
    trace = _mixed_trace(rng, 8)
    NEW = 6

    def run(autoscaler, idle_steps):
        cl = _make_cluster(state, cfg, shared_fn, num_replicas=2,
                           name="slo_auto", policy="load",
                           max_queue_depth=2, autoscaler=autoscaler)
        for _ in range(idle_steps):        # idle window: scale-down bait
            cl.step()
            cl._test_clock[0] += 1.0
        t0 = cl._test_clock[0]
        reqs = []
        for p, cls, arr in trace:
            reqs.append(cl.add_request(p, NEW, arrival_time=t0 + arr,
                                       slo_class=cls))
        _drain(cl, invariants=True)
        out = {r.req_id - reqs[0].req_id: list(r.out_tokens)
               for r in reqs}
        ms = cl.metrics_summary()
        cl.close()
        return out, ms

    auto = Autoscaler(min_replicas=1, backlog_high=4, backlog_low=0,
                      hysteresis_steps=2, cooldown_steps=3,
                      ttft_target=None)
    managed, ms = run(auto, idle_steps=10)
    static, ms_static = run(None, idle_steps=10)
    assert managed == static, "autoscaling changed a request's tokens"
    assert ms["scale_downs"] >= 1, "no scale-down — test is vacuous"
    assert ms["scale_ups"] >= 1, "no scale-up — test is vacuous"
    assert ms["class_inversions"] == 0
    assert ms_static["scale_ups"] == ms_static["scale_downs"] == 0
    assert auto.scale_up_events == ms["scale_ups"]


def test_chaos_death_during_scale_down_no_double_drain(model_state,
                                                       shared_fn):
    """Composition with the fault plane: the chaos plan crashes the
    exact replica the autoscaler is draining, mid-drain.  The death
    sweep re-routes its work (nothing lost, outputs fault-free), the
    controller clears its drain intent WITHOUT a second kill, and the
    scale-down is counted exactly once."""
    state, cfg = model_state
    prompts = [[int(t) for t in range(3 + i, 13 + i)] for i in range(3)]
    NEW = 8
    want = {}
    for i, p in enumerate(prompts):
        want[i] = _solo(state, cfg, p, NEW)

    plan = FaultPlan(events=[FaultEvent(step=4, kind="crash",
                                        target=1)])
    auto = Autoscaler(min_replicas=1, backlog_high=99, backlog_low=99,
                      hysteresis_steps=2, cooldown_steps=50,
                      ttft_target=None)
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=2,
                       name="slo_chaos", policy="load",
                       chaos=ChaosController(plan), autoscaler=auto)
    reqs = [cl.add_request(p, NEW, arrival_time=0.0)
            for p in prompts]
    # let the drain intent land, then verify chaos hits the victim
    for _ in range(3):
        cl.step()
        cl._test_clock[0] += 1.0
    assert cl.replicas[1].draining, "drain intent never landed"
    assert cl.replicas[1].engine.has_work, "victim idle — crash would " \
        "not land mid-drain"
    _drain(cl, invariants=True)
    assert set(cl.finished) == {r.req_id for r in reqs}
    for i, r in enumerate(reqs):
        assert r.out_tokens == want[i], i
    ms = cl.metrics_summary()
    assert ms["replica_deaths"] == 1
    assert ms["scale_downs"] == 1, "double-drain (or lost drain)"
    assert ms["readmits"] == 0
    assert not cl.replicas[1].draining
    assert not cl.replicas[1].alive
    cl.close()


def test_drain_deferred_while_handoff_inflight(model_state, shared_fn):
    """Regression for the interaction bug the protocol explorer
    surfaced (analysis/protocol.py, bug flag 'drain_inflight'): a
    chaos-delayed handoff is IN FLIGHT to a draining replica whose
    engine looks idle — finishing the drain at that instant kills the
    replica and fences its epoch, so the transfer lands stamped with a
    stale epoch (fence-regression).  The autoscaler must DEFER the
    kill until the handoff lands or re-routes, and count the
    deferral."""
    state, cfg = model_state
    auto = Autoscaler(min_replicas=1, backlog_high=99, backlog_low=99,
                      hysteresis_steps=2, cooldown_steps=50,
                      ttft_target=None)
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=2,
                       name="slo_drain_inflight", policy="load",
                       autoscaler=auto)
    try:
        # drain intent on an idle replica 1...
        auto._draining.add(1)
        cl.replicas[1].draining = True
        assert not cl.replicas[1].engine.has_work
        assert not any(k[0] == 1 for k in cl._placed)
        # ...with a delayed transfer pinned to it (destination chosen,
        # pages reserved, landing later — the shape _land_handoff sets
        # while a chaos net_delay holds the wire)
        cl._pending_handoffs.append(
            {"creq": None, "staged": None, "src": 0, "dst": 1,
             "dst_pages": (), "lands_at": 999.0, "attempt": 0,
             "not_before": float("-inf"), "epoch": 7})
        auto._finish_drains(cl, now=0.0)
        assert cl.replicas[1].alive and cl.replicas[1].serving, \
            "drain killed the replica under an in-flight handoff"
        assert cl.replicas[1].draining and 1 in auto._draining
        assert cl.counters["drains_deferred_inflight"].value == 1
        assert cl.counters["scale_downs"].value == 0
        # the transfer lands (or re-routes): the NEXT sweep completes
        # the drain exactly once
        cl._pending_handoffs.clear()
        auto._finish_drains(cl, now=1.0)
        # kill() stops serving NOW; the alive verdict lands via the
        # cluster's death sweep — the drain-completion fact here is
        # that heartbeats/serving stopped and the intent cleared
        assert not cl.replicas[1].serving
        assert not cl.replicas[1].draining and 1 not in auto._draining
        assert cl.counters["scale_downs"].value == 1
        assert cl.metrics_summary()["cluster_drains_deferred_inflight"] \
            == 1
    finally:
        cl.close()


# ---------------------------------------------------------------------------
# host tier: evict -> refetch bitwise across layouts
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mla_states(model_state):
    state, cfg = model_state
    lstate, lcfg = mla_state_from(state, cfg, kv_latent_dim=16)
    rcfg_base = GPTConfig(position="rotary", norm="rmsnorm",
                          activation="swiglu", **CFG_KW)
    ht.set_seed(7)
    with ht.graph("eager", create_new=True):
        rmodel = GPTLMHeadModel(rcfg_base)
        rmodel.logits(np.zeros((1, 4), np.int32))
        rstate_base = {k: np.asarray(v)
                       for k, v in rmodel.state_dict().items()}
    rstate, rcfg = mla_state_from(rstate_base, rcfg_base,
                                  kv_latent_dim=16, kv_rope_dim=4)
    return {"mla": (lstate, lcfg, None),
            "mla_rot": (rstate, rcfg, None),
            "int8": (lstate, lcfg, "int8")}


@pytest.mark.parametrize("layout", ["mla", "mla_rot", "int8"])
def test_host_tier_evict_refetch_bitwise(mla_states, layout):
    """The memory-hierarchy contract: a cold sweep pushes cached pages
    to host staging, a same-header request pulls them back through the
    priced transport, and the output is bit-for-bit a never-evicted
    run's — for latent, rotary-latent and int8-quantized page layouts
    (each prices at its true page_bytes)."""
    state, cfg, quant = mla_states[layout]
    header = list(range(1, 18))            # two full pages at ps=8
    tails = ([21, 22], [31, 32])

    def run(evict):
        eng = _make_engine(state, cfg, num_pages=16,
                           name=f"slo_host_{layout}_{int(evict)}",
                           host_tier=True, page_quant=quant)
        outs = []
        for tail in tails:
            r = eng.add_request(header + tail, max_new_tokens=5)
            _drain(eng)
            outs.append(list(eng.finished[r.req_id].out_tokens))
            if evict:
                # the cold sweep: every refcount-0 cached page -> host
                eng.prefix_cache.evict(16)
                assert eng.pool.cached_pages == 0
        eng.pool.check_invariants(force=True)
        eng.prefix_cache.check_invariants()
        return eng, outs

    eng, evicted_outs = run(evict=True)
    _, warm_outs = run(evict=False)
    assert evicted_outs == warm_outs, \
        "host-tier round-trip changed tokens"
    assert eng.host_tier.evictions >= 2, "sweep staged nothing"
    assert eng.host_tier.hits >= 2, "second request never refetched"
    assert eng.counters["host_hits"].value == eng.host_tier.hits
    assert eng.counters["prefix_cache_hits"].value >= 1, \
        "refetch did not re-enter the cache index"
    # both directions priced, byte accounting exact at THIS layout's
    # page_bytes (latent/quant pages are smaller than full-head)
    recs = eng.host_tier.records
    assert {r["dir"] for r in recs} == {"evict", "refetch"}
    for r in recs:
        assert r["payload_bytes"] == r["pages"] * eng.pool.page_bytes
        assert r["edge"]["tag"] == "host_offload"
        assert r["predicted_s"] > 0
    assert eng.gauges["host_pages"].value == eng.host_tier.host_pages


def test_host_tier_metrics_and_reset_robustness(model_state, shared_fn):
    """Host counters are always-present (uniform cluster merge) and the
    tier survives ``reset_metrics`` — instruments are looked up by key
    at use time, so post-reset evictions still count."""
    state, cfg = model_state
    eng = _make_engine(state, cfg, num_pages=16, name="slo_host_reset",
                       step_fn=shared_fn, host_tier=True)
    txt = eng.metrics_text()
    for key in ("host_evictions", "host_hits", "host_refetch_bytes",
                "host_pages"):
        assert key in txt, key
    header = list(range(1, 18))
    eng.add_request(header + [21, 22], max_new_tokens=4)
    _drain(eng)
    eng.reset_metrics()
    eng.prefix_cache.evict(16)
    assert eng.counters["host_evictions"].value >= 2, \
        "post-reset instruments lost the host tier"
    eng.add_request(header + [31, 32], max_new_tokens=4)
    _drain(eng)
    assert eng.counters["host_hits"].value >= 2


# ---------------------------------------------------------------------------
# satellite: partial reclaim degrades cleanly
# ---------------------------------------------------------------------------


def test_alloc_partial_reclaim_falls_through_to_none():
    """A reclaim hook that CLAIMS more than it delivers: ``alloc``
    trusts only the free list — clean ``None`` (the caller's preemption
    signal), no short grant, no exception — and counts the shortfall."""
    pool = PagedKVPool(num_layers=1, num_pages=4, page_size=8,
                       kv_heads=1, head_dim=4)
    got = pool.alloc(3)                     # usable = 3 (trash page 0)
    assert got is not None and len(got) == 3

    lies = []

    def lying_sweep(n):
        lies.append(n)
        return n                            # claims n, delivers 0

    pool.set_reclaim(lying_sweep)
    assert pool.alloc(2) is None
    assert lies == [2]
    assert pool.reclaim_shortfalls == 1
    pool.check_invariants()
    # a TRUTHFUL partial sweep is also a shortfall-free None
    pool.free(got[:1])

    def honest_partial(n):
        return 0                            # delivers nothing, says so

    pool.set_reclaim(honest_partial)
    assert pool.alloc(3) is None
    assert pool.reclaim_shortfalls == 1     # honesty is not a shortfall
    assert pool.alloc(1) is not None        # free list still coherent
    pool.check_invariants()


def test_engine_survives_lying_reclaim_via_preemption(model_state,
                                                      shared_fn):
    """End-to-end satellite check: with the cache's sweep replaced by a
    liar, page pressure falls through to recompute preemption and the
    outputs stay bitwise — the engine never sees a short grant."""
    state, cfg = model_state
    # prefix_cache off: with it on, the (lying) reclaim hook is the
    # ONLY route from cached pages back to the free list and the pool
    # would starve forever — here preemption itself frees pages, so the
    # engine makes progress while the liar is still consulted on every
    # shortfall
    eng = _make_engine(state, cfg, num_pages=9, name="slo_lying",
                       step_fn=shared_fn, prefix_cache=False)
    eng.pool.set_reclaim(lambda n: n)       # claims n, delivers 0
    prompts = {}
    for i in range(4):
        p = [int(t) for t in range(2 + i, 14 + i)]
        r = eng.add_request(p, max_new_tokens=8)
        prompts[r.req_id] = p
    _drain(eng)
    assert eng.pool.reclaim_shortfalls >= 1, "liar never consulted"
    assert eng.counters["preemptions"].value >= 1, \
        "no preemption — the fall-through claim is vacuous"
    for rid, p in prompts.items():
        assert eng.finished[rid].out_tokens == _solo(state, cfg, p, 8)
    eng.pool.check_invariants(force=True)
