"""Coordinator service + launcher tests.

Mirrors the reference control plane (heturpc.proto surface: rendezvous,
typed KV, barrier, heartbeat/failure detection) and the pssh launcher's
local-simulation mode (N processes on localhost, SURVEY.md §4)."""
import sys
import threading
import time

import pytest

from hetu_tpu.rpc import (CoordinatorClient, CoordinatorServer, HostSpec,
                          Launcher, load_hostfile)


def test_connect_assigns_dense_ranks():
    with CoordinatorServer(world_size=3) as srv:
        clients = [CoordinatorClient(srv.address, uid=f"w{i}")
                   for i in range(3)]
        ranks = sorted(c.connect() for c in clients)
        assert ranks == [0, 1, 2]
        assert clients[0].world_size == 3
        # reconnect with same uid keeps the rank (restart scenario)
        c2 = CoordinatorClient(srv.address, uid="w1")
        assert c2.connect() == clients[1].rank
        assert {c.get_hostname(r) for c in clients[:1]
                for r in ranks} != set()


def test_kv_store_roundtrip_and_blocking_get():
    with CoordinatorServer() as srv:
        a = CoordinatorClient(srv.address, uid="a")
        b = CoordinatorClient(srv.address, uid="b")
        a.connect(), b.connect()
        a.put("k/int", 7)
        a.put("k/json", {"x": [1, 2.5, "s"]})
        assert b.get("k/int") == 7
        assert b.get("k/json") == {"x": [1, 2.5, "s"]}
        assert b.get("missing") is None
        # blocking get: value published by another thread after a delay
        def later():
            time.sleep(0.2)
            a.put("k/late", "here")
        threading.Thread(target=later).start()
        assert b.get("k/late", timeout=5.0) == "here"
        b.remove("k/int")
        assert b.get("k/int") is None


def test_barrier_synchronizes_threads():
    with CoordinatorServer(world_size=4) as srv:
        hits = []

        def worker(i):
            c = CoordinatorClient(srv.address, uid=f"w{i}")
            c.connect()
            time.sleep(0.05 * i)
            c.barrier("sync")
            hits.append(time.time())

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        [t.start() for t in ts]
        [t.join(timeout=10) for t in ts]
        assert len(hits) == 4
        assert max(hits) - min(hits) < 1.0   # all released together


def test_barrier_timeout():
    with CoordinatorServer(world_size=2) as srv:
        c = CoordinatorClient(srv.address, uid="only")
        c.connect()
        with pytest.raises(RuntimeError, match="barrier timeout"):
            c.barrier("never", timeout=0.3)


def test_heartbeat_failure_detection():
    with CoordinatorServer(world_size=2) as srv:
        a = CoordinatorClient(srv.address, uid="a")
        b = CoordinatorClient(srv.address, uid="b")
        a.connect(), b.connect()
        stop_a = a.start_heartbeat_thread(interval=0.05)
        time.sleep(0.3)          # b never heartbeats after connect
        alive, dead = a.alive(ttl=0.2)
        assert a.rank in alive
        assert b.rank in dead
        assert srv.dead_ranks(ttl=0.2) == [b.rank]
        stop_a.set()
        b.exit()
        # exited ranks are not "dead"
        _, dead2 = a.alive(ttl=0.2)
        assert b.rank not in dead2


def test_per_client_ttl_and_server_ttl():
    """ISSUE 11 satellite: liveness TTL is configurable per client (a
    serving router wants a sub-second failure window, a training
    monitor wants a lax one — same coordinator) and per server
    (``dead_ranks()`` with no argument uses the instance TTL)."""
    with CoordinatorServer(world_size=2, ttl=0.2) as srv:
        fast = CoordinatorClient(srv.address, uid="fast", ttl=0.2)
        lax = CoordinatorClient(srv.address, uid="lax", ttl=30.0)
        fast.connect(), lax.connect()
        time.sleep(0.35)           # neither heartbeats after connect
        # the fast client's default TTL sees both ranks dead...
        alive_f, dead_f = fast.alive()
        assert set(dead_f) == {fast.rank, lax.rank}
        # ...the lax client's default TTL sees both alive...
        alive_l, dead_l = lax.alive()
        assert dead_l == [] and set(alive_l) == {fast.rank, lax.rank}
        # ...an explicit argument still overrides either default...
        assert lax.alive(ttl=0.2)[1] == sorted([fast.rank, lax.rank])
        # ...and the server-side monitor uses ITS configured default
        assert srv.dead_ranks() == sorted([fast.rank, lax.rank])
        assert srv.dead_ranks(ttl=30.0) == []


def test_ttl_boundary_heartbeat_exactly_at_ttl():
    """ISSUE 13 satellite: the liveness window is INCLUSIVE — a worker
    whose last heartbeat is exactly TTL old is still alive; one just
    past it is dead.  Asserted on injected stamps (no sleeps, no
    float-race on the boundary)."""
    with CoordinatorServer(world_size=1) as srv:
        c = CoordinatorClient(srv.address, uid="w0")
        c.connect()
        now = time.time()
        with srv.state.lock:
            srv.state.last_heartbeat[c.rank] = now - 5.0
        # a TTL comfortably past the stamp: alive; short of it: dead
        # (the margins absorb the microseconds between set and check)
        assert c.rank not in srv.dead_ranks(ttl=6.0)
        assert c.rank in srv.dead_ranks(ttl=4.0)
        alive, dead = c.alive(ttl=6.0)
        assert c.rank in alive
        alive, dead = c.alive(ttl=4.0)
        assert c.rank in dead


def test_clock_skewed_client_liveness_is_server_stamped():
    """A client with a skewed wall clock cannot poison liveness: the
    protocol never carries client time — heartbeats (and ANY
    authenticated request) are stamped with the SERVER's clock.
    Simulate a wildly skewed stamp, then show one authenticated
    request restores liveness to server-now."""
    with CoordinatorServer(world_size=1) as srv:
        c = CoordinatorClient(srv.address, uid="skewed")
        c.connect()
        with srv.state.lock:
            # as if the client had written its own (past) clock
            srv.state.last_heartbeat[c.rank] = time.time() - 3600.0
        assert c.rank in srv.dead_ranks(ttl=1.0)
        # any rank-authenticated request proves liveness, server-stamped
        c.barrier("poke", world_size=1, timeout=1.0)
        assert c.rank not in srv.dead_ranks(ttl=1.0)


def test_heartbeat_not_starved_by_long_barrier():
    """The heartbeat thread shares the client's single socket lock: a
    blocking barrier holds it for seconds, starving the heartbeat
    thread.  The server must keep the rank alive anyway — waiting at a
    barrier IS liveness (refreshed inside the barrier wait loop)."""
    with CoordinatorServer(world_size=2, ttl=0.3) as srv:
        a = CoordinatorClient(srv.address, uid="a", ttl=0.3)
        b = CoordinatorClient(srv.address, uid="b", ttl=0.3)
        a.connect(), b.connect()
        stop_a = a.start_heartbeat_thread(interval=0.05)
        done = []

        def long_barrier():
            a.barrier("starve", world_size=2, timeout=10.0)
            done.append(True)

        t = threading.Thread(target=long_barrier)
        t.start()
        # a's socket is now held by the barrier for >> TTL; the
        # heartbeat thread cannot send — yet a must stay alive
        deadline = time.time() + 1.2
        while time.time() < deadline:
            assert a.rank not in srv.dead_ranks(), \
                "long barrier starved the heartbeat into a false death"
            time.sleep(0.05)
        b.barrier("starve", world_size=2, timeout=10.0)
        t.join(timeout=10)
        assert done
        stop_a.set()


def test_coordinator_refusal_heartbeat_thread_recovers():
    """ISSUE 13: a coordinator refusing ops (fault window) must not
    kill the heartbeat thread — it backs off, retries, and the rank
    returns to alive once the window heals; an outage shorter than the
    TTL never produces a death verdict."""
    with CoordinatorServer(world_size=1, ttl=5.0) as srv:
        c = CoordinatorClient(srv.address, uid="w0", ttl=5.0)
        c.connect()
        stop = c.start_heartbeat_thread(interval=0.05)
        srv.refuse_for(0.4)
        # refused ops surface as coordinator errors to direct callers
        with pytest.raises(RuntimeError, match="refused"):
            c.put("k", 1)
        time.sleep(1.2)          # window heals; thread must still live
        with srv.state.lock:
            age = time.time() - srv.state.last_heartbeat[c.rank]
        assert age < 1.0, \
            f"heartbeat thread died during the refusal window (age {age:.2f}s)"
        assert c.rank not in srv.dead_ranks(ttl=1.0)
        # the healed window serves ops again
        c.put("k", 2)
        assert c.get("k") == 2
        stop.set()


def test_jax_coordinator_exchange():
    with CoordinatorServer(world_size=2) as srv:
        a = CoordinatorClient(srv.address, uid="a")
        a.connect()
        a.commit_jax_coordinator("10.0.0.1:9911")
        b = CoordinatorClient(srv.address, uid="b")
        b.connect()
        assert b.get_jax_coordinator(timeout=1.0) == "10.0.0.1:9911"


WORKER = r"""
import os, sys
sys.path.insert(0, {repo!r})
from hetu_tpu.rpc.launcher import worker_client
c = worker_client()
n = int(os.environ["HETU_TPU_NUM_WORKERS"])
c.put(f"hello/{{c.rank}}", os.environ["HETU_TPU_WORKER_RANK"])
c.barrier("all", world_size=n, timeout=30)
vals = [c.get(f"hello/{{r}}", timeout=10) for r in range(n)]
assert all(v is not None for v in vals), vals
c.exit()
"""


@pytest.mark.slow
def test_launcher_local_workers(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo="/root/repo"))
    with Launcher([sys.executable, str(script)], num_workers=3) as l:
        ok = l.monitor(poll=0.1, timeout=60)
    assert ok == 3


@pytest.mark.slow
def test_launcher_restart_policy(tmp_path):
    # worker crashes on first attempt (per-rank marker file), then succeeds
    script = tmp_path / "flaky.py"
    script.write_text(
        "import os, sys\n"
        f"marker = {str(tmp_path)!r} + '/died-' + "
        "os.environ['HETU_TPU_WORKER_RANK']\n"
        "if not os.path.exists(marker):\n"
        "    open(marker, 'w').close()\n"
        "    sys.exit(1)\n"
        f"sys.path.insert(0, '/root/repo')\n"
        "from hetu_tpu.rpc.launcher import worker_client\n"
        "c = worker_client()\n"
        "c.exit()\n")
    with Launcher([sys.executable, str(script)], num_workers=2,
                  max_restart_times=2) as l:
        ok = l.monitor(poll=0.1, timeout=60)
    assert ok == 2
    assert any(e["event"] == "restart" for e in l.events)


@pytest.mark.slow
def test_launcher_gives_up_after_budget(tmp_path):
    script = tmp_path / "dead.py"
    script.write_text("import sys; sys.exit(3)\n")
    with Launcher([sys.executable, str(script)], num_workers=1,
                  max_restart_times=1) as l:
        ok = l.monitor(poll=0.1, timeout=60)
    assert ok == 0
    assert any(e["event"] == "gave_up" for e in l.events)
    assert sum(1 for e in l.events if e["event"] == "restart") == 1


def test_load_hostfile(tmp_path):
    hf = tmp_path / "hosts.yaml"
    hf.write_text(
        "hosts:\n"
        "  - addr: localhost\n"
        "    initial_workers: 4\n"
        "  - addr: 10.0.0.2\n"
        "    initial_workers: 2\n"
        "max_restart_times: 3\n"
        "heartbeat_interval: 1.5\n")
    cfg = load_hostfile(str(hf))
    assert cfg["max_restart_times"] == 3
    assert cfg["heartbeat_interval"] == 1.5
    assert [h.addr for h in cfg["hosts"]] == ["localhost", "10.0.0.2"]
    assert sum(h.initial_workers for h in cfg["hosts"]) == 6
    assert isinstance(cfg["hosts"][0], HostSpec)
