"""AMP (autocast + GradScaler) and recompute/offload context tests.

Mirrors the reference's dtype suites (tests/test_bf16.py, test_fp16.py)
and the autocast/gradscaler stack (hetu/graph/autocast/*)."""
import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu import ops, optim
from hetu_tpu.models import GPTConfig, GPTLMHeadModel


def _tiny_cfg(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("hidden_size", 32)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_seq_len", 16)
    return GPTConfig(**kw)


def test_autocast_casts_matmul_down_and_loss_up():
    import jax.numpy as jnp
    with ht.graph("define_and_run", create_new=True) as g:
        x = ht.placeholder("float32", (4, 8), name="x")
        w = ht.parameter(np.ones((8, 8), np.float32), name="w")
        with ht.autocast("bfloat16"):
            y = ops.matmul(x, w)
        # matmul impl under autocast computes in bf16
        env = {w.id: g._materialize_var(w),
               x.id: jnp.ones((4, 8), jnp.float32)}
        (out,) = g._eval_targets([y], env)
        assert out.dtype == jnp.bfloat16


@pytest.mark.slow
def test_autocast_training_step_runs():
    with ht.graph("define_and_run", create_new=True) as g:
        cfg = _tiny_cfg(dtype="float32")
        ids = ht.placeholder("int32", (2, 16), name="ids")
        labels = ht.placeholder("int32", (2, 16), name="labels")
        with ht.autocast("bfloat16"):
            model = GPTLMHeadModel(cfg)
            loss = model(ids, labels)
        train_op = optim.AdamOptimizer(lr=1e-3).minimize(loss)
        IDS = np.random.RandomState(0).randint(0, 64, (2, 16)).astype(np.int32)
        out = g.run(loss, [loss, train_op], {ids: IDS, labels: IDS})
        assert np.isfinite(float(np.asarray(out[0])))


def test_grad_scaler_scales_and_recovers():
    scaler = ht.GradScaler(init_scale=1024.0, growth_interval=1)
    with ht.graph("define_and_run", create_new=True) as g:
        x = ht.placeholder("float32", (4, 8), name="x")
        w = ht.parameter(np.full((8, 4), 0.1, np.float32), name="w")
        y = ops.reduce_mean(ops.matmul(x, w))
        train_op = optim.SGDOptimizer(lr=0.1).minimize(
            y, grad_scaler=scaler)
        X = np.ones((4, 8), np.float32)
        w0 = np.asarray(g._materialize_var(w)).copy()
        out = g.run(y, [y, train_op], {x: X})
        w1 = np.asarray(g.get_tensor_value(w))
        assert not np.allclose(w0, w1)        # finite step applied
        assert scaler.scale == 2048.0         # grew after 1 good step
        # loss reported unscaled
        assert abs(float(np.asarray(out[0])) - float((X @ w0).mean())) < 1e-4


def test_grad_scaler_skips_nonfinite_step():
    scaler = ht.GradScaler(init_scale=64.0, growth_interval=1000)
    with ht.graph("define_and_run", create_new=True) as g:
        x = ht.placeholder("float32", (4,), name="x")
        w = ht.parameter(np.ones((4,), np.float32), name="w")
        y = ops.reduce_sum(ops.mul(x, w))
        train_op = optim.SGDOptimizer(lr=0.1).minimize(
            y, grad_scaler=scaler)
        w0 = np.asarray(g._materialize_var(w)).copy()
        X = np.array([1.0, np.inf, 1.0, 1.0], np.float32)
        g.run(y, [y, train_op], {x: X})
        w1 = np.asarray(g.get_tensor_value(w))
        assert np.allclose(w0, w1)            # update skipped
        assert scaler.scale == 32.0           # backed off


@pytest.mark.slow
def test_recompute_context_matches_baseline():
    def _train(ctx):
        from hetu_tpu.graph import ctor
        ctor._seed_counter[0] = 0  # identical param init across the two runs
        with ht.graph("define_and_run", create_new=True) as g:
            cfg = _tiny_cfg(dtype="float32")
            ids = ht.placeholder("int32", (2, 16), name="ids")
            labels = ht.placeholder("int32", (2, 16), name="labels")
            model = GPTLMHeadModel(cfg)
            loss = model(ids, labels)
            opt = optim.SGDOptimizer(lr=0.0)  # lr=0: loss deterministic
            train_op = opt.minimize(loss)
            IDS = np.random.RandomState(1).randint(
                0, 64, (2, 16)).astype(np.int32)
            if ctx is None:
                out = g.run(loss, [loss, train_op],
                            {ids: IDS, labels: IDS})
            else:
                with ctx(graph=g):
                    out = g.run(loss, [loss, train_op],
                                {ids: IDS, labels: IDS})
            return float(np.asarray(out[0]))

    # remat must not change the math: same init -> same loss
    base = _train(None)
    remat = _train(ht.recompute)
    assert abs(base - remat) < 1e-4


def test_disabled_scaler_is_inert_across_runs():
    # regression: a disabled scaler must not inject donated state that is
    # never returned (second run would hit deleted buffers on TPU)
    scaler = ht.GradScaler(enabled=False)
    with ht.graph("define_and_run", create_new=True) as g:
        x = ht.placeholder("float32", (4,), name="x")
        w = ht.parameter(np.ones((4,), np.float32), name="w")
        y = ops.reduce_sum(ops.mul(x, w))
        train_op = optim.SGDOptimizer(lr=0.1).minimize(y, grad_scaler=scaler)
        X = np.ones((4,), np.float32)
        g.run(y, [y, train_op], {x: X})
        g.run(y, [y, train_op], {x: X})  # must not raise


def test_plan_key_includes_remat_policy():
    with ht.graph("define_and_run", create_new=True) as g:
        x = ht.placeholder("float32", (4,), name="x")
        w = ht.parameter(np.ones((4,), np.float32), name="w")
        y = ops.reduce_sum(ops.mul(x, w))
        train_op = optim.SGDOptimizer(lr=0.1).minimize(y)
        X = np.ones((4,), np.float32)
        g.run(y, [y, train_op], {x: X})
        n = len(g._plan_pool)
        with ht.recompute(graph=g):
            g.run(y, [y, train_op], {x: X})
        assert len(g._plan_pool) == n + 1  # remat keyed a fresh plan
        g.run(y, [y, train_op], {x: X})
        assert len(g._plan_pool) == n + 1  # original plan reused


def test_cpu_offload_context_runs():
    with ht.graph("define_and_run", create_new=True) as g:
        x = ht.placeholder("float32", (4, 8), name="x")
        w = ht.parameter(np.ones((8, 4), np.float32) * 0.1, name="w")
        y = ops.reduce_mean(ops.relu(ops.matmul(x, w)))
        train_op = optim.SGDOptimizer(lr=0.1).minimize(y)
        with ht.cpu_offload(graph=g):
            out = g.run(y, [y, train_op], {x: np.ones((4, 8), np.float32)})
        assert np.isfinite(float(np.asarray(out[0])))
