"""ZeRO-3 on the flat layout: params sharded at rest (PR 19).

Pins down: working parameters live ONLY as each rank's P(dp) chunk of
the per-bucket flat fp32 master; the forward all-gathers every bucket
just-in-time in the weight dtype (tag ``param_gather``) and after the
chunk-local update only the 1/dp shard remains.  Losses are BITWISE the
``flat_state=True, zero=2`` run's on every transport — the gathered
weights are the same fp32 master chunks ZeRO-2's post-update regather
produced, just fetched one step later.  The analysis tripod sees all of
it: the gather is a priced ``param_gather`` edge family
(``param-gather-unpriced``), the at-rest side is policed by
``grad-allgather-under-zero2`` / ``replicated-state-under-shard``, the
memory pass predicts the at-rest saving, and the planner's DP search
gains ZeRO-3 as a searchable stage.  Adafactor joins the flat path with
factored row/col stats (1-D/small params fall back to the full second
moment) and exactly the declared extra psums per bucket.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import analysis, ops, optim
from hetu_tpu.parallel import create_mesh

UNEVEN = [(7, 5), (13,), (3,), (11, 3)]     # nothing divisible by dp=8


def _train(devices8, transport="fp32", zero=3, flat=True, steps=4,
           shapes=(), opt_cls=optim.AdamOptimizer, opt_kw=None):
    """Linear regression on the virtual-8 mesh (same harness as
    test_flat_zero2); returns (losses, graph, optimizer, w)."""
    mesh = create_mesh({"dp": 8}, devices8)
    with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
        x = ht.parallel_placeholder("float32", (16, 8),
                                    pspec=P("dp", None), name="x")
        y = ht.parallel_placeholder("float32", (16, 1),
                                    pspec=P("dp", None), name="y")
        rng = np.random.RandomState(7)
        w = ht.parameter((0.1 * rng.randn(8, 1)).astype(np.float32),
                         name="w")
        b = ht.parameter(np.zeros((1,), np.float32), name="b")
        extras = [ht.parameter(
            (0.1 * rng.randn(*s)).astype(np.float32), name=f"p{i}")
            for i, s in enumerate(shapes)]
        loss = ops.reduce_mean((ops.matmul(x, w) + b - y) ** 2)
        for p in extras:
            loss = loss + ops.reduce_mean(p ** 2)
        op = opt_cls(lr=1e-2, zero=zero, grad_comm=transport,
                     flat_state=flat, **(opt_kw or {})).minimize(loss)
        X = np.random.RandomState(0).randn(16, 8).astype(np.float32)
        Y = np.random.RandomState(1).randn(16, 1).astype(np.float32)
        losses = []
        for _ in range(steps):
            o = g.run(loss, [loss, op], {x: X, y: Y})
            losses.append(float(np.asarray(o[0])))
        if flat:
            assert g._grad_comm_active, g._grad_comm_fallback
        return losses, g, op.producer.attrs["optimizer"], w


class TestZero3LossEquivalence:
    @pytest.mark.parametrize("transport", ["fp32", "bf16", "int8"])
    def test_bitwise_matches_flat_zero2(self, devices8, transport):
        """ZeRO-3's just-in-time gather reads the SAME fp32 master
        chunks ZeRO-2's post-update regather broadcast — losses and
        params are bitwise equal on every transport."""
        l2, _, _, _ = _train(devices8, transport, zero=2)
        l3, g3, opt3, w = _train(devices8, transport, zero=3)
        assert l2 == l3                       # bitwise, not allclose
        # reading a param goes through the stale-refresh path: the
        # working copy rematerializes from the flat master exactly
        w3 = np.asarray(g3.get_tensor_value(w))
        assert w3.shape == (8, 1) and np.isfinite(w3).all()

    def test_uneven_params_and_padding(self, devices8):
        l2, _, _, _ = _train(devices8, "fp32", zero=2, shapes=UNEVEN)
        l3, _, opt3, _ = _train(devices8, "fp32", zero=3, shapes=UNEVEN)
        assert l2 == l3
        lay = opt3._flat_layout
        assert all(sz % 8 == 0 for sz in lay.padded_sizes)

    def test_matches_per_param_baseline(self, devices8):
        """Against the implicit all-reduce baseline the curve matches to
        fp32 reduction-order tolerance."""
        base, g0, _, _ = _train(devices8, None, zero=0, flat=False)
        assert not g0._grad_comm_active
        got, _, _, _ = _train(devices8, "fp32", zero=3)
        np.testing.assert_allclose(got, base, rtol=1e-5)

    def test_params_dropped_from_step_outputs(self, devices8):
        """After a step only the 1/dp master chunks are authoritative:
        trainables are not among the jitted step's var outputs, and the
        resident working copies stay dp-sharded."""
        _, g, opt, w = _train(devices8, "fp32", zero=3, steps=2)
        assert opt.zero == 3
        sh = g._var_data[w.id].sharding
        assert tuple(sh.spec)[:1] == ("dp",)   # dim-0 dp-sharded at rest


class TestZero3Emission:
    @pytest.mark.parametrize("transport", ["fp32", "bf16", "int8"])
    def test_param_gather_predicted_and_emitted(self, devices8,
                                                transport):
        _, g, _, _ = _train(devices8, transport, zero=3, steps=1)
        (handle,) = g.analysis_handles()
        gc = handle.meta["grad_comm"]
        assert gc["flat"] is True and gc["zero"] == 3
        assert handle.meta["allowed_gspmd"] == {}
        analysis.verify_grad_comm(handle)
        pred, _ = analysis.grad_comm_prediction(handle)
        gathers = [p for p in pred if p["kind"] == "all_gather"]
        # exactly the per-bucket weight gathers, all tagged param_gather
        # (no post-update param_comm regather remains)
        assert gathers and all(p.get("tag") == "param_gather"
                               for p in gathers)
        rep = analysis.analyze_handle(handle)
        pg = [r for r in rep.records if "param_gather" in r.scope]
        pc = [r for r in rep.records if "param_comm" in r.scope]
        assert len(pg) == len(gathers) and pc == []
        assert all(r.kind == "all_gather" for r in pg)

    def test_clean_under_all_rules(self, devices8):
        _, g, _, _ = _train(devices8, "fp32", zero=3, steps=1)
        (handle,) = g.analysis_handles()
        full = analysis.analyze_handle(handle, compile=True)
        assert full.findings == [], full.findings
        # the param_gather edge family is priced: payload bytes > 0
        em = full.meta["edge_match"]
        priced = [e for e in full.meta["edges"]
                  if e.tag == "param_gather"]
        assert priced and all(e.payload_bytes > 0 for e in priced)

    def test_param_gather_unpriced_fires_without_edge(self, devices8):
        """Misdeclaring the plan as zero=2 removes the priced
        param_gather edge while the program still emits the gathers —
        the new rule must fail it."""
        _, g, _, _ = _train(devices8, "fp32", zero=3, steps=1)
        (handle,) = g.analysis_handles()
        ctx = analysis.build_context(handle)
        assert analysis.run_rules(
            ctx, only=["param-gather-unpriced"]) == []
        handle.meta["grad_comm"]["zero"] = 2
        try:
            ctx2 = analysis.build_context(handle)
            fnds = analysis.run_rules(ctx2,
                                      only=["param-gather-unpriced"])
            assert fnds and all(f.rule == "param-gather-unpriced"
                                for f in fnds)
        finally:
            handle.meta["grad_comm"]["zero"] = 3

    def test_replicated_state_rule_learns_zero3(self, devices8):
        """Under zero>=3 the rule also polices the at-rest claim:
        resident param bytes at the full replicated size mean the
        saving never materializes."""
        _, g, _, _ = _train(devices8, "fp32", zero=3, steps=1)
        (handle,) = g.analysis_handles()
        ctx = analysis.build_context(
            handle, options={"param_bytes_threshold": 1})
        assert analysis.run_rules(
            ctx, only=["replicated-state-under-shard"]) == []
        # simulate the broken contract: full trainable set resident
        full = sum(p.nbytes for p in ctx.params if p.trainable)
        ctx.memory.by_kind["param"] = full
        fnds = analysis.run_rules(ctx,
                                  only=["replicated-state-under-shard"])
        assert len(fnds) == 1 and "sharded at rest" in fnds[0].message


class TestZero3Memory:
    def test_at_rest_param_bytes_drop(self, devices8):
        """The memory pass sees the params leave the at-rest set: the
        zero-2 plan keeps every trainable replicated per rank, the
        zero-3 plan keeps none (>=2x saving on the param class)."""
        _, g2, _, _ = _train(devices8, "fp32", zero=2, steps=1)
        (h2,) = g2.analysis_handles()
        m2 = analysis.predict_memory(h2)
        _, g3, _, _ = _train(devices8, "fp32", zero=3, steps=1)
        (h3,) = g3.analysis_handles()
        m3 = analysis.predict_memory(h3)
        p2 = int(m2.by_kind.get("param", 0))
        p3 = int(m3.by_kind.get("param", 0))
        assert p2 > 0 and p3 == 0             # params absent at rest
        assert p2 >= 2 * max(p3, 1) or p3 == 0
        assert m3.resident_bytes < m2.resident_bytes


class TestZero3Adafactor:
    SHAPES = [(8, 6), (8, 8), (13,), (6, 4), (3,)]
    KW = dict(min_dim_size_to_factor=4)

    def _run(self, devices8, zero, flat, **kw):
        return _train(devices8, "fp32", zero=zero, flat=flat,
                      shapes=self.SHAPES, steps=5,
                      opt_cls=optim.AdafactorOptimizer,
                      opt_kw={**self.KW, **kw})

    @pytest.mark.parametrize("kw", [{}, {"momentum": 0.9},
                                    {"clipping_threshold": None}])
    def test_flat_matches_optax_reference(self, devices8, kw):
        """The flat reimplementation follows optax.adafactor's exact
        chain; z2 and z3 stay bitwise to each other."""
        ref, _, _, _ = self._run(devices8, 0, False, **kw)
        l2, _, _, _ = self._run(devices8, 2, True, **kw)
        l3, _, _, _ = self._run(devices8, 3, True, **kw)
        assert l2 == l3, kw
        np.testing.assert_allclose(l2, ref, rtol=2e-4, atol=1e-6)

    def test_factored_lanes_keep_zero_v(self, devices8):
        """Factored matrices ride the replicated row/col EMAs; their
        lanes of the flat v slot stay exactly zero (1-D params keep the
        full second moment there)."""
        _, _, opt, _ = self._run(devices8, 2, True)
        lay = opt._flat_layout
        per = lay.unpack(opt._state["flat_v"])
        by_shape = {tuple(np.shape(v)): np.asarray(v)
                    for v in per.values()}
        assert np.all(by_shape[(8, 6)] == 0)        # factored
        assert np.abs(by_shape[(13,)]).max() > 0    # 1-D fallback
        assert any(np.abs(np.asarray(v)).max() > 0
                   for v in opt._state["fac_row"])

    def test_declared_psums_verify_exactly(self, devices8):
        _, g, opt, _ = self._run(devices8, 3, True)
        (handle,) = g.analysis_handles()
        extra = opt._flat_comm_extra()
        nb = len(opt._flat_layout.buckets)
        assert extra == {"all_reduce": 2 * nb}   # stats + clip psum
        assert handle.meta["grad_comm"]["opt_extra"] == extra
        analysis.verify_grad_comm(handle)
        full = analysis.analyze_handle(handle, compile=True)
        assert full.findings == [], full.findings


class TestZero3Planner:
    def test_dp_search_gains_zero3_stage(self):
        from hetu_tpu.planner import (ChipSpec, ClusterSpec,
                                      SearchEngine, Strategy,
                                      layer_memory,
                                      transformer_layer_spec)
        cluster = ClusterSpec(chip=ChipSpec(hbm_bytes=95e9), num_chips=8)
        layers = [transformer_layer_spec(8, 1024, 1024, 4096,
                                         name=f"blocks{i}")
                  for i in range(4)]
        eng = SearchEngine(cluster, layers, global_batch=64,
                           micro_batch=8)
        cands = eng._mem_variants(8, 1)
        assert any(st.zero == 3 for st in cands)
        # dp=1 has nothing to shard: zero stages collapse to 0
        assert all(st.zero == 0 for st in eng._mem_variants(1, 8))
        # the cost model prices the extra saving: zero-3 beats zero-2
        # on per-rank memory for the same layout
        m2 = layer_memory(layers[0], Strategy(dp=8, tp=1, zero=2),
                          cluster)
        m3 = layer_memory(layers[0], Strategy(dp=8, tp=1, zero=3),
                          cluster)
        assert m3 < m2
