"""Unified ragged prefill+decode serving step (ISSUE 6).

Covers the tentpole contracts the v1 bucketed engine could not offer:

- **chunked-prefill equivalence** — chunk sizes 16/64/∞ all produce
  bit-for-bit the solo ``generate()`` tokens at temperature 0;
- **no decode stall** — a long-prompt arrival never delays running
  decodes' next token (decodes ride every packed step by construction);
- **ragged kernel parity** — the Pallas kernel (interpret mode) against
  the dense reference across ragged shapes, decode rows included;
- **on-device sampling** — temperature/top-k/top-p inside the unified
  executable, seeded-deterministic regardless of batching/chunking,
  ``host_logit_fetches == 0`` on mixed traffic;
- **recompile guard (CI)** — the engine compiles ≤ 2 executables over a
  full mixed trace (admission, chunking, late arrivals, preemption), so
  the bucket grid can't silently come back;
- **TTFT/TBT histograms** — Prometheus bucket counts recorded per stage.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.models.generate import generate
from hetu_tpu.ops.ragged_paged_attention import (
    ragged_paged_attention_pallas, ragged_paged_attention_reference)
from hetu_tpu.serving import Engine

CFG_KW = dict(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
              max_seq_len=64, sp=False, dropout=0.0)


def _build_state(cfg, seed=3):
    ht.set_seed(seed)
    with ht.graph("eager", create_new=True):
        model = GPTLMHeadModel(cfg)
        model.logits(np.zeros((1, 4), np.int32))
        state = {k: np.asarray(v) for k, v in model.state_dict().items()}
    return state


def _solo(state, cfg, prompt, n_new):
    return np.asarray(generate(state, cfg,
                               np.asarray([prompt], np.int32), n_new,
                               temperature=0.0))[0, len(prompt):].tolist()


def _make_engine(state, cfg, **kw):
    clock = [0.0]
    kw.setdefault("time_fn", lambda: clock[0])
    kw.setdefault("debug", True)        # invariant checks on in tests
    eng = Engine(state, cfg, **kw)
    eng._test_clock = clock
    return eng


def _drain(eng, check=True):
    while eng.has_work:
        eng.step()
        eng._test_clock[0] += 1.0
        if check:
            eng.pool.check_invariants()


# ---------------------------------------------------------------------------
# ragged kernel vs dense reference (interpret mode)
# ---------------------------------------------------------------------------

RAGGED_CASES = [
    # (q_lens, ctx_lens, maxp, ps)   — mixed chunks + decodes + padding
    ([1, 5, 0, 6], [13, 10, 0, 6], 3, 8),
    ([1, 1, 1, 1], [9, 3, 17, 1], 3, 8),      # all-decode
    ([8, 8], [8, 24], 4, 8),                  # all-chunk, partial pages
    ([3, 0, 0, 7], [20, 0, 0, 7], 4, 8),      # sparse rows
]


@pytest.mark.parametrize("q_lens,ctx_lens,maxp,ps", RAGGED_CASES)
def test_ragged_kernel_matches_reference(q_lens, ctx_lens, maxp, ps):
    """Pallas ragged kernel (interpret mode on CPU) against the
    gather-dense reference across ragged shapes: decode rows, prefill
    chunks, padding rows, partial last pages, GQA group padding."""
    rng = np.random.RandomState(0)
    nh, kvh, hd, num_pages = 4, 2, 32, 12
    max_q = 8
    s = len(q_lens)
    cu = np.zeros(s + 1, np.int32)
    cu[1:] = np.cumsum(q_lens)
    t = max(int(cu[-1]), 1)
    q = jnp.asarray(rng.randn(t, nh, hd), jnp.float32)
    kp = jnp.asarray(rng.randn(num_pages, ps, kvh, hd), jnp.float32)
    vp = jnp.asarray(rng.randn(num_pages, ps, kvh, hd), jnp.float32)
    # non-contiguous per-row page ids; padding slots -> trash page 0
    perm = rng.permutation(np.arange(1, num_pages))
    pt = np.zeros((s, maxp), np.int32)
    k = 0
    for i in range(s):
        need = -(-ctx_lens[i] // ps)
        pt[i, :need] = perm[k:k + need]
        k += need
    args = (jnp.asarray(np.asarray(q_lens, np.int32)), jnp.asarray(cu),
            jnp.asarray(pt), jnp.asarray(np.asarray(ctx_lens, np.int32)))
    ref = ragged_paged_attention_reference(q, kp, vp, *args, max_q=max_q)
    got = ragged_paged_attention_pallas(q, kp, vp, *args, max_q=max_q,
                                        interpret=True)
    # only real rows are part of the contract
    mask = np.zeros(t, bool)
    for i in range(s):
        mask[int(cu[i]):int(cu[i]) + int(q_lens[i])] = True
    np.testing.assert_allclose(np.asarray(got)[mask],
                               np.asarray(ref)[mask],
                               rtol=2e-5, atol=2e-5)


def test_ragged_reference_matches_per_token_oracle():
    """The dense reference itself against a per-token numpy oracle
    (masked attention over each token's true causal history)."""
    rng = np.random.RandomState(1)
    nh, kvh, hd, ps, num_pages, maxp, max_q = 4, 2, 16, 8, 10, 3, 8
    q_lens = np.asarray([2, 1, 4], np.int32)
    ctx_lens = np.asarray([10, 7, 4], np.int32)
    cu = np.asarray([0, 2, 3, 7], np.int32)
    pt = np.asarray([[3, 6, 0], [2, 0, 0], [8, 0, 0]], np.int32)
    t = 7
    q = rng.randn(t, nh, hd).astype(np.float32)
    kp = rng.randn(num_pages, ps, kvh, hd).astype(np.float32)
    vp = rng.randn(num_pages, ps, kvh, hd).astype(np.float32)
    got = np.asarray(ragged_paged_attention_reference(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(q_lens), jnp.asarray(cu), jnp.asarray(pt),
        jnp.asarray(ctx_lens), max_q=max_q))
    g = nh // kvh
    for i in range(3):
        k = kp[pt[i]].reshape(-1, kvh, hd)
        v = vp[pt[i]].reshape(-1, kvh, hd)
        for j in range(int(q_lens[i])):
            pos = int(ctx_lens[i]) - int(q_lens[i]) + j
            kk = np.repeat(k[:pos + 1], g, axis=1)
            vv = np.repeat(v[:pos + 1], g, axis=1)
            qb = q[int(cu[i]) + j]
            sc = np.einsum("hd,lhd->hl", qb, kk) / np.sqrt(hd)
            p = np.exp(sc - sc.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            want = np.einsum("hl,lhd->hd", p, vv)
            np.testing.assert_allclose(got[int(cu[i]) + j], want,
                                       rtol=1e-5, atol=1e-5)


def test_kernel_backed_unified_step_end_to_end():
    """The whole unified executable with the Pallas ragged kernel
    (interpret mode) agrees with the dense-fallback executable on greedy
    tokens — the kernel really is a drop-in inside the serving jit."""
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", vocab_size=97, hidden_size=32,
                    num_layers=1, num_heads=4, max_seq_len=32, sp=False,
                    dropout=0.0)
    state = _build_state(cfg, seed=4)
    prompts = [[5, 17, 2, 9], [3, 2, 1]]
    outs = {}
    for uk in (False, True):
        eng = _make_engine(state, cfg, num_pages=5, page_size=8,
                           max_batch=2, chunk_size=4, use_kernel=uk)
        reqs = [eng.add_request(p, 4, arrival_time=0.0) for p in prompts]
        _drain(eng)
        outs[uk] = [r.out_tokens for r in reqs]
    assert outs[False] == outs[True]


# ---------------------------------------------------------------------------
# chunked-prefill equivalence
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("chunk_size", [16, 64, None])
def test_chunked_prefill_bit_for_bit(chunk_size):
    """Chunk sizes 16 / 64 / ∞ (whole prompt) all emit bit-for-bit the
    solo generate() tokens at temperature 0 — chunking changes when KV
    is computed, never its values."""
    cfg = GPTConfig(position="rotary", norm="rmsnorm",
                    activation="swiglu", **CFG_KW)
    state = _build_state(cfg, seed=7)
    rng = np.random.RandomState(2)
    prompts = [[int(t) for t in rng.randint(1, 90, size=n)]
               for n in (23, 4, 37)]
    want = [_solo(state, cfg, pr, 6) for pr in prompts]
    eng = _make_engine(state, cfg, num_pages=24, page_size=8,
                       max_batch=4, chunk_size=chunk_size)
    reqs = [eng.add_request(pr, 6, arrival_time=0.0) for pr in prompts]
    _drain(eng)
    for r, w in zip(reqs, want):
        assert r.out_tokens == w
    assert eng.compile_count == 1


def test_chunked_prefill_survives_late_arrival_and_preemption():
    """The hard determinism case in one trace: small pool (forces
    recompute eviction), small chunks (prompts span several steps), a
    late arrival mid-flight — everything still matches solo runs."""
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg, seed=11)
    prompts = [[5, 17, 2, 9, 33, 12, 8, 1], [1, 1, 4, 44],
               [3, 2, 1, 9, 6, 5, 4]]
    want = [_solo(state, cfg, pr, 10) for pr in prompts]
    eng = _make_engine(state, cfg, num_pages=7, page_size=8,
                       max_batch=4, chunk_size=4)
    reqs = [eng.add_request(pr, 10, arrival_time=float(2 * i))
            for i, pr in enumerate(prompts)]
    _drain(eng)
    assert eng.counters["preemptions"].value >= 1, \
        "trace should exercise eviction; shrink the pool if not"
    for r, w in zip(reqs, want):
        assert r.out_tokens == w
    assert eng.pool.used_pages == 0
    assert eng.compile_count == 1


# ---------------------------------------------------------------------------
# no decode stall
# ---------------------------------------------------------------------------

def test_long_prompt_never_stalls_running_decodes():
    """A long-prompt arrival may not add more than chunk-budget latency
    to running decodes: with the packed step, every running decode
    emits exactly one token per engine step THROUGHOUT the long
    prefill — zero added steps, the strongest form of the bound."""
    cfg = GPTConfig(position="rotary", norm="rmsnorm",
                    activation="silu", **CFG_KW)
    state = _build_state(cfg, seed=9)
    rng = np.random.RandomState(4)
    short = [[3, 2, 1], [9, 8, 7, 6]]
    long_prompt = [int(t) for t in rng.randint(1, 90, size=96)]
    eng = _make_engine(state, cfg, num_pages=40, page_size=8,
                       max_batch=4, chunk_size=8)
    shorts = [eng.add_request(pr, 30, arrival_time=0.0) for pr in short]
    # warm up: both shorts decoding
    while not all(r.n_generated >= 2 for r in shorts):
        eng.step()
        eng._test_clock[0] += 1.0
    long_req = eng.add_request(long_prompt, 4,
                               arrival_time=eng._test_clock[0])
    counts = {r.req_id: r.n_generated for r in shorts}
    stall_free_steps = 0
    while long_req.n_generated == 0:        # the whole prefill window
        eng.step()
        eng._test_clock[0] += 1.0
        for r in shorts:
            if r.state == "running" and not r.done:
                assert r.n_generated == counts[r.req_id] + 1, \
                    "running decode skipped a step during long prefill"
            counts[r.req_id] = r.n_generated
        stall_free_steps += 1
    # 96-token prompt in 8-token chunks: prefill really did span steps
    assert stall_free_steps >= 12
    _drain(eng)
    assert long_req.out_tokens == _solo(state, cfg, long_prompt, 4)
    for r, pr in zip(shorts, short):
        assert r.out_tokens == _solo(state, cfg, pr, 30)


# ---------------------------------------------------------------------------
# on-device sampling
# ---------------------------------------------------------------------------

def test_on_device_sampling_seeded_determinism():
    """Temperature/top-k/top-p sampling runs inside the unified
    executable keyed by (seed, position): the SAME request replayed
    under different batching/chunking produces identical tokens, and no
    step ever fetches host logits."""
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg, seed=21)
    prompt = [5, 17, 2, 9, 1]
    greedy_peer = [3, 2, 1]
    runs = []
    for kw in (dict(chunk_size=64, max_batch=4),
               dict(chunk_size=2, max_batch=2)):
        eng = _make_engine(state, cfg, num_pages=16, page_size=16, **kw)
        if kw["max_batch"] == 4:            # mixed greedy/sampled batch
            eng.add_request(greedy_peer, 8, arrival_time=0.0)
        req = eng.add_request(prompt, 8, temperature=0.7, top_p=0.9,
                              top_k=40, seed=123, arrival_time=0.0)
        _drain(eng)
        assert eng.host_logit_fetches == 0
        assert eng.metrics_summary()["host_logit_fetches"] == 0
        runs.append(list(req.out_tokens))
    assert runs[0] == runs[1]               # batching-independent replay
    # a different seed must (overwhelmingly) take a different path
    eng = _make_engine(state, cfg, num_pages=16, page_size=16,
                       max_batch=2)
    other = eng.add_request(prompt, 8, temperature=0.7, top_p=0.9,
                            top_k=40, seed=124, arrival_time=0.0)
    _drain(eng)
    assert len(other.out_tokens) == 8


def test_top_p_one_hot_under_cold_temperature():
    """top_p tight enough to keep only the head of the distribution at
    a cold temperature pins sampling to the argmax token — an end-to-end
    check that the nucleus cut really executes on device."""
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg, seed=2)
    prompt = [5, 17, 2, 9]
    want = _solo(state, cfg, prompt, 6)
    eng = _make_engine(state, cfg, num_pages=16, page_size=16,
                       max_batch=2)
    req = eng.add_request(prompt, 6, temperature=0.05, top_p=1e-6,
                          seed=5, arrival_time=0.0)
    _drain(eng)
    assert req.out_tokens == want           # nucleus of one == greedy


# ---------------------------------------------------------------------------
# recompile guard (CI) + latency histograms
# ---------------------------------------------------------------------------

@pytest.mark.lint_graph
def test_recompile_guard_full_mixed_trace():
    """CI guard for the compile-count contract: over a full mixed trace
    (short+long prompts, late arrivals, sampled rows, preemption) the
    engine compiles AT MOST 2 executables (unified step + optional
    warmup) — the O(prefill buckets x batch buckets) grid cannot
    silently come back."""
    cfg = GPTConfig(position="rotary", norm="rmsnorm",
                    activation="swiglu", **CFG_KW)
    state = _build_state(cfg, seed=17)
    rng = np.random.RandomState(5)
    eng = _make_engine(state, cfg, num_pages=9, page_size=8,
                       max_batch=4, chunk_size=8)
    for i in range(9):
        n = int(rng.randint(2, 30))
        pr = [int(t) for t in rng.randint(1, 90, size=n)]
        eng.add_request(pr, int(rng.randint(2, 8)),
                        temperature=0.5 if i % 3 == 0 else 0.0,
                        top_p=0.9 if i % 3 == 0 else 0.0,
                        seed=i, arrival_time=float(i))
    _drain(eng)
    assert eng.counters["preemptions"].value >= 1   # trace is adversarial
    assert eng.compile_count <= 2
    assert eng.compile_count == 1                   # no warmup used today
    # the jit cache saw exactly one shape signature
    fn = eng._compiled["unified"]
    if hasattr(fn, "_cache_size"):
        assert fn._cache_size() == 1
    assert len(eng.finished) == 9


def test_ttft_tbt_histogram_buckets():
    """Per-stage latency histograms: TTFT and TBT are Prometheus-style
    bucketed; with the synthetic 1s-per-step clock the bucket counts are
    exactly predictable."""
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg, seed=6)
    eng = _make_engine(state, cfg, num_pages=16, page_size=16,
                       max_batch=2, chunk_size=64,
                       latency_buckets=[0.5, 2.0, 8.0])
    eng.add_request([5, 17, 2], 5, arrival_time=0.0)
    eng.add_request([1, 9, 4, 2], 5, arrival_time=0.0)
    _drain(eng)
    m = eng.metrics_summary()
    assert m["ttft"]["count"] == 2
    assert m["tbt"]["count"] == 8               # 4 follow-up tokens each
    # synthetic clock: every step costs 0s on the frozen clock, so all
    # observations land in the first bucket; counts must close at +Inf
    tb = m["tbt_buckets"]
    assert tb["+Inf"] == 8
    assert sum(1 for _ in tb) == 4              # 3 bounds + Inf
    ft = m["ttft_buckets"]
    assert ft["+Inf"] == 2
    # the step_calls/executable_calls accounting rides the same path
    assert m["executable_calls"] == m["step_calls"] > 0
