"""Per-edge DS-transition attribution (ISSUE 5).

The edge pass must (a) deduce the right collective for every
producer -> consumer pspec transition, (b) explain 100% of what the
gated executable families emit (TP/SP, pipeline, MoE, grad-comm,
serving), and (c) fire ``unexplained-collective`` exactly once per
seeded violation: a stale pspec edge, an over-provisioned MoE capacity,
an untagged scan collective.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import analysis, ops, optim
from hetu_tpu.analysis import analyze_handle, collect_collectives
from hetu_tpu.analysis.edges import CommEdge, match_edges
from hetu_tpu.graph.graph import (DefineAndRunGraph, clear_executables,
                                  register_executable)
from hetu_tpu.parallel import comm, create_mesh, dstates
from hetu_tpu.parallel.comm import comm_tag, shard_map
from hetu_tpu.parallel.dstates import deduce_pspec_transition, pspec_to_ds

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sds(shape, dtype=np.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _register(name, fn, args, **meta):
    meta.setdefault("mesh_axes", {})
    meta.setdefault("params", [])
    meta.setdefault("allowed_gspmd", None)
    clear_executables(name)
    return register_executable(name, fn, args, meta)


def _fired(rep, rule):
    return [f for f in rep.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# pspec -> DS lowering + per-edge comm deduction
# ---------------------------------------------------------------------------

class TestPspecTransitions:
    MA = {"dp": 2, "tp": 4}

    def test_pspec_to_ds(self):
        ds = pspec_to_ds(P("tp", None), 2, self.MA)
        assert ds.device_num == 8
        assert ds.get_dim(0) == 4 and ds.get_dim(dstates.DUPLICATE) == 2
        repl = pspec_to_ds(None, 3, self.MA)
        assert repl.check_pure_duplicate()
        with pytest.raises(ValueError):
            pspec_to_ds(P("dp", "tp"), 1, self.MA)   # more entries than dims

    @pytest.mark.parametrize("src,ss,dst,ds_,want", [
        # same shape: true DS transitions via deduce_comm_kind
        (P("dp", None, "tp"), (4, 16, 64), P("dp", None, None),
         (4, 16, 64), "all_gather"),
        (P("dp", None, None), (4, 16, 64), P("dp", None, "tp"),
         (4, 16, 64), "scatter"),
        (P("dp", "tp", None), (4, 16, 64), P("dp", None, "tp"),
         (4, 16, 64), "reshard"),
        (None, (256, 64), P("tp", None), (256, 64), "scatter"),
        (P("tp", None), (256, 64), P(None, None), (256, 64), "all_gather"),
        # shape changed: mesh-axis movement heuristics
        (P("dp", None, "tp"), (4, 16, 64), P("dp", None, None),
         (4, 16, 32), "all_reduce"),                 # contracted away
        (P("dp", ("tp",), None), (4, 16, 64), P("dp", None, "tp"),
         (4, 16, 256), "reshard"),                   # SP colp boundary
        (P("dp", None, "tp"), (4, 16, 256), P("dp", ("tp",), None),
         (4, 16, 64), "reshard"),                    # SP rowp boundary
        (P("dp", None), (8, 64), P("dp", None, None), (8, 4, 16),
         "identity"),                                # batch flow
        (P("dp", None), (8, 64), P("dp", None), (8, 64), "identity"),
    ])
    def test_deduction_matrix(self, src, ss, dst, ds_, want):
        assert deduce_pspec_transition(src, ss, dst, ds_, self.MA) == want

    def test_dead_axes_are_spectators(self):
        # axes of size 1 never communicate: same transition, degenerate tp
        assert deduce_pspec_transition(
            P("dp", None, "tp"), (4, 16, 64), P("dp", None, None),
            (4, 16, 64), {"dp": 8, "tp": 1}) == "identity"


# ---------------------------------------------------------------------------
# ppermute accounting + scan scope propagation (satellites)
# ---------------------------------------------------------------------------

class TestPpermuteAndScanTags:
    def test_ppermute_wire_bytes_per_hop(self, devices8):
        assert comm.ring_wire_bytes("ppermute", 1024, 8) == 1024.0
        assert comm.ring_wire_bytes("ppermute", 1024, 1) == 0.0

    def test_pipeline_hop_chain_counted_and_tagged(self, devices8):
        """parallel/pipeline.py: the tick-scan ppermute chain keeps its
        pipeline/hop tag and counts hops x payload."""
        from hetu_tpu.parallel.pipeline import pipeline_spmd
        mesh = create_mesh({"pp": 4}, devices8[:4])
        S, d, M, B = 4, 16, 2, 8

        def stage_fn(p, v):
            return jnp.tanh(v @ p["w"][0])

        fn = jax.jit(lambda pr, x: pipeline_spmd(stage_fn, pr, x, M, mesh))
        h = _register("t_pphop/fwd", fn,
                      ({"w": _sds((S, 1, d, d))}, _sds((B, d))))
        recs = collect_collectives(h.jaxpr)
        pp = [r for r in recs if r.kind == "ppermute"]
        assert len(pp) == 1
        (hop,) = pp
        assert hop.count == M + S - 1                 # fill + drain hops
        assert hop.payload_bytes == (B // M) * d * 4  # one mb activation
        assert hop.wire_bytes == hop.payload_bytes    # per hop
        assert "pipeline/hop" in hop.scope
        ars = [r for r in recs if r.kind == "all_reduce"]
        assert len(ars) == 2
        assert all("pipeline/collect" in r.scope for r in ars)

    def test_outer_comm_tag_propagates_into_scan_body(self, devices8):
        """A comm_tag entered AROUND a lax.scan lands on the scan eqn
        only; the walk must join it onto body collectives so pipeline
        loops keep their attribution."""
        mesh = create_mesh({"dp": 8}, devices8)

        def f(xs):
            def body(c, x):
                return c + lax.psum(x, "dp"), None
            with comm_tag("outer_sync"):
                c, _ = lax.scan(body, jnp.zeros_like(xs[0]), xs)
            return c

        jf = jax.jit(shard_map(f, mesh, (P(),), P()))
        h = _register("t_scantag/f", jf, (_sds((5, 16)),))
        (rec,) = collect_collectives(h.jaxpr)
        assert rec.count == 5
        assert "outer_sync" in rec.scope

    def test_untagged_scan_collective_fires_unexplained_once(self,
                                                             devices8):
        """Seeded violation: a scan-body ppermute with no comm_tag and
        no pipeline edge — one unexplained-collective with provenance."""
        mesh = create_mesh({"pp": 4}, devices8[:4])
        perm = [(i, (i + 1) % 4) for i in range(4)]

        def f(xs):
            def body(c, x):
                return c + lax.ppermute(x, "pp", perm), None
            c, _ = lax.scan(body, jnp.zeros_like(xs[0]), xs)
            return c

        jf = jax.jit(shard_map(f, mesh, (P(),), P(), check_rep=False))
        h = _register("t_scanuntag/f", jf, (_sds((3, 16)),),
                      pspec_edges=[])          # edge claim: no comm at all
        rep = analyze_handle(h)
        fired = _fired(rep, "unexplained-collective")
        assert len(fired) == 1, rep.findings
        assert fired[0].subject == "ppermute:untagged"
        assert fired[0].source, "record provenance must carry file:line"
        assert "comm_tag" in fired[0].hint
        # same loop with the tag + a declared pipeline edge: silent
        def g(xs):
            def body(c, x):
                with comm_tag("pipeline/hop"):
                    return c + lax.ppermute(x, "pp", perm), None
            c, _ = lax.scan(body, jnp.zeros_like(xs[0]), xs)
            return c

        jg = jax.jit(shard_map(g, mesh, (P(),), P(), check_rep=False))
        hg = _register("t_scanuntag/ok", jg, (_sds((3, 16)),),
                       pipeline={"pp_axis": "pp", "hops": 3,
                                 "payload_bytes": 16 * 4})
        assert not _fired(analyze_handle(hg), "unexplained-collective")
        # a TAGGED edge must NOT absorb an untagged record of the same
        # kind: the rogue loop fires even when a pipeline edge exists
        hr = _register("t_scanuntag/rogue", jf, (_sds((3, 16)),),
                       pipeline={"pp_axis": "pp", "hops": 3,
                                 "payload_bytes": 16 * 4})
        fired_r = _fired(analyze_handle(hr), "unexplained-collective")
        assert len(fired_r) == 1, fired_r


# ---------------------------------------------------------------------------
# TP/SP + stale-pspec seeding (tentpole)
# ---------------------------------------------------------------------------

class TestTPEdgeAttribution:
    def _tp_train(self, devices8, name="t_tpedge"):
        from hetu_tpu.models import GPTLMHeadModel, llama_config
        ht.set_seed(11)
        mesh = create_mesh({"dp": 2, "tp": 4}, devices8)
        cfg = llama_config(vocab_size=128, hidden_size=32, num_layers=1,
                           num_heads=4, max_seq_len=16, sp=True,
                           dtype="bfloat16")
        g = DefineAndRunGraph(name)
        g.mesh = mesh
        clear_executables(name)
        with ht.graph(g):
            ids = ht.parallel_placeholder("int32", (4, 16),
                                          pspec=P("dp", None), name="ids")
            labels = ht.parallel_placeholder("int32", (4, 16),
                                             pspec=P("dp", None),
                                             name="labels")
            model = GPTLMHeadModel(cfg)
            loss = model(ids, labels)
            op = optim.AdamOptimizer(lr=1e-3).minimize(loss)
            rng = np.random.RandomState(0)
            IDS = rng.randint(0, 128, (4, 16)).astype(np.int32)
            g.run(loss, [loss, op], {ids: IDS, labels: IDS})
        (handle,) = g.analysis_handles()
        return handle

    def test_tp_sp_graph_fully_explained(self, devices8):
        handle = self._tp_train(devices8)
        edges = handle.meta["pspec_edges"]
        assert edges, "TP graph must yield pspec edges"
        kinds = {e["kind"] for e in edges}
        assert "all_reduce" in kinds          # row-parallel partials
        rep = analyze_handle(handle, compile=True)
        assert rep.findings == [], rep.findings
        cov = rep.meta["edge_coverage"]
        assert cov["total"] > 0 and cov["explained"] == cov["total"]
        # GSPMD inserted real collectives and every one is attributed
        assert sum(rep.meta["gspmd_collectives"].values()) > 0

    def test_stale_pspec_edge_fires_unexplained_with_provenance(
            self, devices8):
        """Seeded violation: the graph's edges went stale (annotations
        dropped after registration) — the emitted reshard has no
        covering edge and must surface with the GSPMD counts."""
        mesh = create_mesh({"dp": 8}, devices8)

        def f(x):
            x = lax.with_sharding_constraint(
                x, NamedSharding(mesh, P("dp", None)))
            h = x * 2.0
            # the smuggled constraint: a mid-graph gather no edge knows
            h = lax.with_sharding_constraint(h, NamedSharding(mesh, P()))
            return h.sum()

        # healthy: the edge is declared -> silent
        ok = _register(
            "t_stale/ok", jax.jit(f), (_sds((16, 8)),),
            mesh_axes={"dp": 8},
            pspec_edges=[{"kind": "all_gather", "tensor": "h",
                          "src_spec": "P(dp,None)", "dst_spec": "P()",
                          "axes": ("dp",), "payload_bytes": 16 * 8 * 4}])
        assert not _fired(analyze_handle(ok, compile=True),
                          "unexplained-collective")
        # stale: same program, the annotation/edge is gone
        stale = _register("t_stale/bad", jax.jit(f), (_sds((16, 8)),),
                          mesh_axes={"dp": 8}, pspec_edges=[])
        rep = analyze_handle(stale, compile=True)
        fired = _fired(rep, "unexplained-collective")
        assert len(fired) == 1, rep.findings
        assert fired[0].subject == "gspmd:all_gather"
        assert "no edge predicts this kind" in fired[0].message
        assert "pspec" in fired[0].hint

    def test_stale_tp_boundary_graph_edge(self, devices8):
        """TP-boundary-shaped graph: registration computes the row-
        parallel all_reduce edge; wiping it (stale pspec) surfaces the
        psum as unexplained."""
        from hetu_tpu.nn.parallel import RowParallelLinear
        ht.set_seed(12)
        mesh = create_mesh({"dp": 2, "tp": 4}, devices8)
        g = DefineAndRunGraph("t_tpstale")
        g.mesh = mesh
        clear_executables("t_tpstale")
        with ht.graph(g):
            x = ht.parallel_placeholder("float32", (4, 8, 16),
                                        pspec=P("dp", None, None),
                                        name="x")
            layer = RowParallelLinear(16, 32, bias=False, name="row")
            y = layer(x)
            loss = ops.reduce_mean(y ** 2)
            g.run([loss], feed_dict={
                x: np.random.RandomState(0).randn(4, 8, 16)
                .astype(np.float32)})
        (h,) = g.analysis_handles()
        assert any(e["kind"] == "all_reduce"
                   for e in h.meta["pspec_edges"])
        assert not _fired(analyze_handle(h, compile=True),
                          "unexplained-collective")
        h.meta["pspec_edges"] = []          # the annotations went stale
        h.meta["scalar_fetches"] = 0
        rep = analyze_handle(h, compile=True)
        fired = _fired(rep, "unexplained-collective")
        assert len(fired) == 1, rep.findings
        assert fired[0].subject == "gspmd:all_reduce"


# ---------------------------------------------------------------------------
# grad-comm records match their tagged edges 1:1
# ---------------------------------------------------------------------------

class TestGradCommEdges:
    def test_flat_int8_records_all_matched_by_tag(self, devices8):
        mesh = create_mesh({"dp": 8}, devices8)
        g = DefineAndRunGraph("t_gce")
        g.mesh = mesh
        clear_executables("t_gce")
        with ht.graph(g):
            x = ht.parallel_placeholder("float32", (16, 8),
                                        pspec=P("dp", None), name="x")
            y = ht.parallel_placeholder("float32", (16, 1),
                                        pspec=P("dp", None), name="y")
            w = ht.parameter(np.zeros((8, 1), np.float32), name="w")
            loss = ops.reduce_mean((ops.matmul(x, w) - y) ** 2)
            op = optim.AdamOptimizer(lr=1e-2, zero=2, grad_comm="int8",
                                     flat_state=True).minimize(loss)
            rng = np.random.RandomState(0)
            g.run(loss, [loss, op],
                  {x: rng.randn(16, 8).astype(np.float32),
                   y: rng.randn(16, 1).astype(np.float32)})
        (h,) = g.analysis_handles()
        rep = analyze_handle(h, compile=True)
        assert rep.findings == [], rep.findings
        cov = rep.meta["edge_coverage"]
        assert cov["explained"] == cov["total"] > 0
        em = rep.meta["edge_match"]
        # every explicit record found a TAGGED edge except the untagged
        # scalar pmean (fetch-origin edge)
        origins = {e.origin for _r, e in em.explained}
        assert "grad_comm" in origins and "param_comm" in origins
        for rec, edge in em.explained:
            if edge.origin == "param_comm":
                assert "param_comm" in rec.scope


# ---------------------------------------------------------------------------
# MoE: capacity rule + dropless/EP families
# ---------------------------------------------------------------------------

class TestMoECapacity:
    def _meta(self, capacity, mode="capacity"):
        return {"moe": [{"name": "moe.l0", "tokens": 64, "embed_dim": 32,
                         "num_experts": 8, "k": 2, "capacity_factor": 1.0,
                         "capacity": capacity, "dispatch_mode": mode,
                         "ep_axis": "ep", "dtype": "float32"}]}

    def test_capacity_tokens_helper(self):
        from hetu_tpu.ops.moe_dispatch import capacity_tokens
        assert capacity_tokens(64, 8, 2, 1.0) == 16
        assert capacity_tokens(64, 8, 2, 1.25) == 20
        assert capacity_tokens(10, 3, 1, 1.0) == 4    # ceil

    def test_overprovision_fires_exactly_once(self):
        from hetu_tpu.analysis import AnalysisContext, run_rules
        # predicted capacity 16; dispatch built with 48 -> 3x the bytes
        ctx = AnalysisContext(name="t_moe", meta=self._meta(48))
        fired = run_rules(ctx, only=["moe-capacity-overprovision"])
        assert len(fired) == 1, fired
        assert fired[0].subject == "moe.l0"
        assert "zero-padded" in fired[0].message
        assert "dropless" in fired[0].hint
        # exact capacity: silent
        ctx2 = AnalysisContext(name="t_moe2", meta=self._meta(16))
        assert not run_rules(ctx2, only=["moe-capacity-overprovision"])
        # dropless mode: exempt even with nonsense capacity
        ctx3 = AnalysisContext(name="t_moe3",
                               meta=self._meta(999, mode="dropless"))
        assert not run_rules(ctx3, only=["moe-capacity-overprovision"])

    def test_ep_capacity_moe_fully_explained(self, devices8):
        from hetu_tpu.nn.moe import make_moe_layer
        ht.set_seed(13)
        mesh = create_mesh({"ep": 8}, devices8)
        g = DefineAndRunGraph("t_moe_ep")
        g.mesh = mesh
        clear_executables("t_moe_ep")
        with ht.graph(g):
            x = ht.parallel_placeholder("float32", (16, 32),
                                        pspec=P(None, None), name="x")
            moe = make_moe_layer(32, 64, num_experts=8, gate_type="topk",
                                 k=2, capacity_factor=1.25, ep_axis="ep",
                                 name="moe_ep")
            out, aux = moe(x)
            loss = ops.reduce_mean(out ** 2) + 0.01 * aux
            g.run([loss], feed_dict={
                x: np.random.RandomState(1).randn(16, 32)
                .astype(np.float32)})
        (h,) = g.analysis_handles()
        (m,) = h.meta["moe"]
        from hetu_tpu.ops.moe_dispatch import capacity_tokens
        assert m["capacity"] == capacity_tokens(16, 8, 2, 1.25)
        rep = analyze_handle(h, compile=True)
        assert rep.findings == [], rep.findings
        cov = rep.meta["edge_coverage"]
        assert cov["explained"] == cov["total"] > 0

    def test_dropless_moe_trains_under_explicit_sync(self, devices8):
        """Satellite of the gate family: dropless MoE + explicit int8
        sync runs in the manual-dp region and explains everything."""
        from hetu_tpu.nn.moe import make_moe_layer
        ht.set_seed(14)
        mesh = create_mesh({"dp": 8}, devices8)
        g = DefineAndRunGraph("t_moe_flat")
        g.mesh = mesh
        clear_executables("t_moe_flat")
        with ht.graph(g):
            x = ht.parallel_placeholder("float32", (16, 32),
                                        pspec=P("dp", None), name="x")
            moe = make_moe_layer(32, 64, num_experts=4, gate_type="topk",
                                 k=2, dispatch_mode="dropless",
                                 name="moe")
            out, aux = moe(x)
            loss = ops.reduce_mean(out ** 2) + 0.01 * aux
            op = optim.AdamOptimizer(lr=1e-2, zero=1,
                                     grad_comm="int8").minimize(loss)
            g.run(loss, [loss, op],
                  {x: np.random.RandomState(2).randn(16, 32)
                   .astype(np.float32)})
            assert g._grad_comm_active, g._grad_comm_fallback
        (h,) = g.analysis_handles()
        (m,) = h.meta["moe"]
        assert m["dispatch_mode"] == "dropless" and m["capacity"] is None
        rep = analyze_handle(h, compile=True)
        assert rep.findings == [], rep.findings
        cov = rep.meta["edge_coverage"]
        assert cov["explained"] == cov["total"] > 0


# ---------------------------------------------------------------------------
# MPMD pipeline stages under the lint (gpt_mpmd-shaped)
# ---------------------------------------------------------------------------

class TestMPMDPipelineLint:
    def test_stage_programs_fully_explained(self, devices8):
        from hetu_tpu.models.gpt import GPTConfig
        from hetu_tpu.models.gpt_mpmd import MPMDGPT
        devs = np.array(devices8).reshape(2, 2, 2)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=16, dropout=0.0,
                        activation="gelu", norm="layernorm",
                        position="learned", sp=False)
        m = MPMDGPT(cfg, stage_layers=[[1, 1]],
                    meshes=[[Mesh(devs[0], ("dp", "tp")),
                             Mesh(devs[1], ("dp", "tp"))]], seed=3)
        names = m.register_analysis("t_mpmd", batch=4, seq=16)
        assert len(names) == 2
        last = analysis.get_executable(names[-1])
        assert last.meta["train"]             # fused loss+grads program
        assert last.meta["declared_edges"]
        for n in names:
            rep = analyze_handle(analysis.get_executable(n),
                                 compile=True)
            assert rep.findings == [], (n, rep.findings)
            cov = rep.meta["edge_coverage"]
            assert cov["explained"] == cov["total"] > 0, (n, cov)


# ---------------------------------------------------------------------------
# baseline gate mechanics for the new fields + CLI exit codes
# ---------------------------------------------------------------------------

class TestEdgeBaselineGate:
    def _rep(self, coverage=None, gspmd=None):
        from hetu_tpu.analysis import AnalysisReport, ExecutableReport
        rep = AnalysisReport()
        ex = ExecutableReport(name="exe")
        if coverage is not None:
            ex.meta["edge_coverage"] = coverage
        if gspmd is not None:
            ex.meta["gspmd_collectives"] = gspmd
        rep.add(ex)
        return rep

    def test_gspmd_count_regression_fails(self):
        base = self._rep(gspmd={"all_gather": 2}).to_dict()
        assert not self._rep(gspmd={"all_gather": 2}) \
            .check_against_baseline(base)
        probs = self._rep(gspmd={"all_gather": 3}) \
            .check_against_baseline(base)
        assert probs and "GSPMD-inserted all_gather" in probs[0]
        # improvement passes
        assert not self._rep(gspmd={"all_gather": 1}) \
            .check_against_baseline(base)

    def test_coverage_drop_fails(self):
        base = self._rep(coverage={"explained": 5, "total": 5}).to_dict()
        assert not self._rep(coverage={"explained": 5, "total": 5}) \
            .check_against_baseline(base)
        probs = self._rep(coverage={"explained": 4, "total": 5}) \
            .check_against_baseline(base)
        assert probs and "unexplained collectives regressed" in probs[0]

    def test_cli_exit_2_on_missing_baseline_before_build(self, tmp_path):
        """Exit code 2, and FAST: the check runs before the expensive
        executable build."""
        import io
        from hetu_tpu.analysis.cli import run_gate
        buf = io.StringIO()
        rc = run_gate(baseline_path=str(tmp_path / "nope.json"),
                      out=buf)
        assert rc == 2
        assert "--update-baseline" in buf.getvalue()


class TestMatchSemantics:
    def test_tagged_edge_requires_tag_untagged_falls_back(self):
        from hetu_tpu.analysis import CollectiveRecord
        rec_tagged = CollectiveRecord(
            kind="all_gather", axes=("dp",), dtype="bfloat16",
            payload_bytes=1024, wire_bytes=1.0,
            scope="param_comm/bucket0")
        rec_plain = CollectiveRecord(
            kind="all_reduce", axes=("dp",), dtype="float32",
            payload_bytes=4, wire_bytes=1.0, scope="")
        edges = [CommEdge(kind="all_gather", tag="param_comm"),
                 CommEdge(kind="all_reduce", origin="fetch")]
        m = match_edges([rec_tagged, rec_plain], "", "", edges,
                        train=True)
        assert not m.unexplained_records
        by_rec = {id(r): e for r, e in m.explained}
        assert by_rec[id(rec_tagged)].tag == "param_comm"
        assert by_rec[id(rec_plain)].origin == "fetch"
        # a record whose kind no edge covers stays unexplained
        rec_odd = CollectiveRecord(
            kind="all_to_all", axes=("dp",), dtype="int8",
            payload_bytes=8, wire_bytes=1.0, scope="")
        m2 = match_edges([rec_odd], "", "", edges, train=True)
        assert m2.unexplained_records == [rec_odd]

    def test_strict_allowed_gspmd_claim_stays_exact(self):
        """An executable with allowed_gspmd={} (the flat train step)
        keeps zero-tolerance GSPMD accounting even with generous
        edges."""
        lowered = ""
        compiled = "all-gather(x) all-gather(y)"
        edges = [CommEdge(kind="all_gather", count=10)]
        strict = match_edges([], lowered, compiled, edges, train=True,
                             allowed_gspmd={})
        assert strict.gspmd_unexplained.get("all_gather") == (2, 0)
        loose = match_edges([], lowered, compiled, edges, train=True,
                            allowed_gspmd=None)
        assert "all_gather" in loose.gspmd_explained

    def test_param_gather_replay_in_fused_scope_is_attributed(self):
        """Satellite regression (ISSUE 20): under ZeRO-3 lazy
        materialization a fused forward region re-emits the weight
        gather PAST the param_gather edge's count.  Those replays must
        be attributed (EdgeMatch.replayed), not flagged — while a rogue
        collective of any other tag still fires."""
        from hetu_tpu.analysis import CollectiveRecord
        edge = CommEdge(kind="all_gather", tag="param_gather", count=1)

        def _pg(scope):
            return CollectiveRecord(
                kind="all_gather", axes=("dp",), dtype="bfloat16",
                payload_bytes=4096, wire_bytes=1.0, scope=scope)
        first = _pg("step/param_gather/bucket0")
        replay = _pg("step/fwd/fused0/param_gather/bucket0")
        rogue = CollectiveRecord(
            kind="all_gather", axes=("dp",), dtype="float32",
            payload_bytes=64, wire_bytes=1.0, scope="step/fwd/rogue")
        m = match_edges([first, replay, rogue], "", "", [edge],
                        train=True)
        assert [r for r, _ in m.explained] == [first]
        assert [r for r, _ in m.replayed] == [replay]
        assert m.unexplained_records == [rogue]
        # replays count as explained coverage (the baseline ratio may
        # not silently drop when lazy materialization lands)
        assert m.coverage() == {"explained": 2, "total": 3}

    def test_replay_never_absorbs_other_kinds_or_tags(self):
        """The replay tier is the ONE bounded exception: same tag, a
        covered kind.  An out-of-scope record or an uncovered kind
        stays unexplained even when a param_gather edge is exhausted."""
        from hetu_tpu.analysis import CollectiveRecord
        edge = CommEdge(kind="all_gather", tag="param_gather", count=0)
        wrong_tag = CollectiveRecord(
            kind="all_gather", axes=("dp",), dtype="float32",
            payload_bytes=8, wire_bytes=1.0, scope="step/param_comm/b0")
        wrong_kind = CollectiveRecord(
            kind="all_to_all", axes=("dp",), dtype="float32",
            payload_bytes=8, wire_bytes=1.0,
            scope="step/param_gather/b0")
        m = match_edges([wrong_tag, wrong_kind], "", "", [edge],
                        train=True)
        assert m.replayed == []
        assert m.unexplained_records == [wrong_tag, wrong_kind]
