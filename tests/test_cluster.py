"""Serving cluster plane (ISSUE 11): prefix-aware router over N engine
replicas, disaggregated prefill/decode with priced KV-page streaming.

Contracts covered:

- **prefix-aware placement** — a shared-system-prompt burst routes to
  the replica whose cache holds the header (digest lookup), and the
  fleet-wide hit rate beats the seeded random-placement baseline;
- **disaggregated bit-for-bit** — prefill on one replica, KV pages
  streamed to a decode replica, outputs bit-for-bit the monolithic
  engine / solo ``generate()`` at temperature 0 under late arrivals,
  preemption and cache eviction (preemption asserted non-vacuous);
- **re-route on death** — a replica missing heartbeats is reported dead
  through the rpc coordinator and its queued/running requests drain to
  survivors: completion-set equality, no request lost;
- **handoff pricing gate** (lint_graph) — every cross-replica page move
  carries a priced edge claim; the ``kv-handoff-unpriced`` rule stays
  quiet on the real transport and fires when pricing is stripped;
- **aggregate metrics** — one replica-labeled Prometheus exposition,
  and counter sums that survive a per-replica ``reset_metrics`` without
  double-counting.
"""
import time

import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.models.generate import generate
from hetu_tpu.serving import Engine, EngineCluster
from hetu_tpu.serving.cluster import digest_match_pages
from hetu_tpu.serving.prefix_cache import token_chain_hashes

CFG_KW = dict(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
              max_seq_len=64, sp=False, dropout=0.0)

# every cluster in this file shares ONE packed-step shape, so one
# compiled program serves the whole module (the same mechanism the
# cluster itself uses across its replicas) — the suite stays inside
# the tier-1 wall-clock budget
SHAPE_KW = dict(page_size=8, max_batch=4, chunk_size=8, prefill_rows=1,
                max_model_len=56)


@pytest.fixture(scope="module")
def model_state():
    cfg = GPTConfig(**CFG_KW)
    ht.set_seed(3)
    with ht.graph("eager", create_new=True):
        model = GPTLMHeadModel(cfg)
        model.logits(np.zeros((1, 4), np.int32))
        state = {k: np.asarray(v) for k, v in model.state_dict().items()}
    return state, cfg


@pytest.fixture(scope="module")
def shared_fn():
    from hetu_tpu.serving.decode import build_unified_step_fn
    cfg = GPTConfig(**CFG_KW)
    return build_unified_step_fn(
        cfg, SHAPE_KW["max_batch"], SHAPE_KW["chunk_size"],
        SHAPE_KW["prefill_rows"],
        -(-SHAPE_KW["max_model_len"] // SHAPE_KW["page_size"]),
        SHAPE_KW["page_size"], use_kernel=False)


def _solo(state, cfg, prompt, n_new):
    return np.asarray(generate(state, cfg,
                               np.asarray([prompt], np.int32), n_new,
                               temperature=0.0))[0, len(prompt):].tolist()


def _make_cluster(state, cfg, fn=None, **kw):
    clock = [0.0]
    kw.setdefault("time_fn", lambda: clock[0])
    kw.setdefault("num_pages", 12)
    for k, v in SHAPE_KW.items():
        kw.setdefault(k, v)
    kw.setdefault("debug", True)
    kw.setdefault("ttl", 3600.0)        # health tests override
    cl = EngineCluster(state, cfg, step_fn=fn, **kw)
    cl._test_clock = clock
    return cl


def _drain(cl, limit=500):
    n = 0
    while cl.has_work:
        cl.step()
        cl._test_clock[0] += 1.0
        n += 1
        assert n < limit, "cluster did not drain"
    return n


# ---------------------------------------------------------------------------
# digest / router units
# ---------------------------------------------------------------------------


def test_digest_matches_chain_hashes(model_state, shared_fn):
    """A replica's exported digest is exactly the content-chained view
    of its cache: a request sharing k full pages matches k, a
    divergent request matches 0."""
    state, cfg = model_state
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=1, name="cl_digest",
                       coordinator=False)
    header = list(range(1, 25))          # 3 full pages at page_size 8
    cl.add_request(header + [30, 31], 4, arrival_time=0.0)
    _drain(cl)
    digest = cl.replicas[0].digest()
    assert digest, "finished request populated no cache"
    pool = cl.replicas[0].engine.pool
    ps, tag = pool.page_size, pool.layout_tag
    # the full prompt pages are cached: a same-header request matches
    got = digest_match_pages(header + [77, 78, 79], ps, digest,
                             layout=tag)
    assert got == 3
    # chain property: equal hashes imply equal prefixes, so a diverged
    # FIRST page kills every deeper match even if later pages agree
    diverged = [50] + header[1:] + [77]
    assert digest_match_pages(diverged, ps, digest, layout=tag) == 0
    # and the hash helper agrees with the digest's own stamps
    hs = token_chain_hashes(header + [77], ps, layout=tag)
    assert [digest.get(h) for h in hs] == [1, 2, 3]
    # layout-salted ROOT: unsalted hashes (and any OTHER layout's
    # hashes) share no keys with this digest — a latent replica and a
    # full-head replica can never cross-match in the router
    assert digest_match_pages(header + [77], ps, digest) == 0
    cl.close()


def test_router_backpressure(model_state, shared_fn):
    """Replicas at max_queue_depth are not placement candidates; when
    every replica is saturated the backlog holds (FIFO) and drains as
    capacity frees."""
    state, cfg = model_state
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=2, name="cl_bp",
                       coordinator=False, max_queue_depth=1)
    prompts = [[i + 1, i + 2, i + 3] for i in range(6)]
    reqs = [cl.add_request(p, 3, arrival_time=0.0) for p in prompts]
    cl.step()                            # routes at most 2 (one each)
    placed = sum(1 for r in reqs if r.replica is not None)
    assert placed == 2
    assert len(cl._backlog) == 4
    _drain(cl)
    assert set(cl.finished) == {r.req_id for r in reqs}
    cl.close()


def test_admit_rolls_back_deferred_pins():
    """A deferred (blocked) head must not keep cached-page pins charged
    against the budget: with nothing running, that would re-create the
    very deadlock the page-holder overtake exists to break."""
    from hetu_tpu.serving import (PagedKVPool, PrefixCache, Request,
                                  RequestQueue, Scheduler)
    pool = PagedKVPool(num_layers=1, num_pages=8, page_size=4,
                       kv_heads=1, head_dim=4, debug=True)
    cache = PrefixCache(pool)
    sched = Scheduler(pool, max_batch=4, chunk=4, prefix_cache=cache)
    # 2 cached pages (a finished donor's prompt), refcount 0
    donor = Request(req_id=0, prompt=list(range(8)), max_new_tokens=1)
    donor.pages = pool.alloc(2)
    donor.pos = 8
    cache.on_finish(donor)
    assert cache.evictable_pages == 2
    # an adopted page-holder: 2 pages attached, 23 accumulated tokens
    # -> needs 4 more; true budget = 3 free + 2 evictable = 5
    holder = Request(req_id=1, prompt=list(range(23)), max_new_tokens=4)
    holder.pages = pool.alloc(2)
    holder.pos = 8
    holder.arrival_time = 1.0
    # a fresh head that MATCHES the cached pages (pinning them) but
    # can never fit right now: needs 8 - 2 matched = 6 > 5
    head = Request(req_id=2, prompt=list(range(8)) + list(range(100, 120)),
                   max_new_tokens=1)
    q = RequestQueue()
    q.push(head)
    q.push(holder)
    admitted = sched.admit(q, [], now=2.0)
    # the holder overtakes: head's pins were rolled back, so the 4
    # pages it needs fit the 5-page true budget (a leaked pin would
    # leave budget 3 and defer it — deadlock, nothing running)
    assert admitted == [holder]
    assert len(q) == 1                     # head still queued, FIFO


def test_adopt_request_rejects_impossible_requests(model_state,
                                                   shared_fn):
    """adopt_request (and the cluster front door) apply add_request's
    could-never-run pool check — an impossible request must raise, not
    defer at admission forever."""
    state, cfg = model_state
    # 3 usable pages = 24 tokens, but max_model_len allows 56: a
    # 40-token request passes the length check and must be caught by
    # the pool-capacity check (never compiled/stepped — cheap)
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=1,
                       name="cl_never", coordinator=False, num_pages=4)
    eng = cl.replicas[0].engine
    with pytest.raises(ValueError, match="could never run"):
        eng.adopt_request(list(range(1, 31)), [7], max_new_tokens=10)
    with pytest.raises(ValueError, match="could never run"):
        cl.add_request(list(range(1, 31)), max_new_tokens=10)
    cl.close()


# ---------------------------------------------------------------------------
# prefix-aware placement
# ---------------------------------------------------------------------------


def _shared_prompt_trace(state, cfg, fn, policy, seed=0):
    """Warm ONE replica with a shared header, then burst same-header
    requests; returns (cluster, burst requests, hit-rate)."""
    cl = _make_cluster(state, cfg, fn, num_replicas=3, policy=policy,
                       name=f"cl_place_{policy}", coordinator=False,
                       seed=seed)
    rng = np.random.RandomState(7)
    header = rng.randint(1, 97, size=24).tolist()   # 3 full pages
    # warm: one request carries the header into some replica's cache
    warm = cl.add_request(header + [5, 6], 2, arrival_time=0.0)
    _drain(cl)
    holder = warm.replica
    burst = [cl.add_request(header + [10 + i], 2,
                            arrival_time=cl._test_clock[0])
             for i in range(6)]
    _drain(cl)
    ms = cl.metrics_summary()
    return cl, holder, burst, ms


def test_prefix_aware_placement_beats_random(model_state, shared_fn):
    state, cfg = model_state
    cl_p, holder, burst, ms_p = _shared_prompt_trace(state, cfg,
                                                     shared_fn, "prefix")
    # every burst request landed on the cache-holding replica...
    assert all(r.replica == holder for r in burst), \
        [(r.req_id, r.replica) for r in burst]
    # ...and hit its cached header (fleet-wide request hit rate)
    assert ms_p["prefix_cache_hit_rate"] > 0.8
    assert ms_p["prefix_cache_tokens_saved"] > 0
    cl_p.close()
    # the random baseline spreads the burst and must do strictly worse
    cl_r, _, burst_r, ms_r = _shared_prompt_trace(state, cfg,
                                                  shared_fn, "random")
    assert len({r.replica for r in burst_r}) > 1, \
        "random placement degenerated to one replica; weak baseline"
    assert ms_p["prefix_cache_hit_rate"] > ms_r["prefix_cache_hit_rate"]
    assert ms_p["prefix_cache_tokens_saved"] \
        > ms_r["prefix_cache_tokens_saved"]
    cl_r.close()
    # outputs identical either way (placement is invisible at temp 0)
    for a, b in zip(burst, burst_r):
        assert a.out_tokens == b.out_tokens


# ---------------------------------------------------------------------------
# disaggregated prefill/decode
# ---------------------------------------------------------------------------


def test_disaggregated_bitforbit_vs_monolithic(model_state, shared_fn):
    """The acceptance gate: prefill on dedicated replicas, pages
    streamed to decode replicas, outputs bit-for-bit the monolithic
    engine at temperature 0 on an adversarial trace — late arrivals,
    preemption (asserted non-vacuous), prefix-cache eviction
    pressure."""
    state, cfg = model_state
    rng = np.random.RandomState(11)
    lens = [26, 18, 28, 12, 22, 20]
    NEW = 12
    prompts = [rng.randint(1, 97, size=n).tolist() for n in lens]
    # monolithic reference: one engine, same trace (same shapes — it
    # rides the module's shared compiled program too)
    mono_clock = [0.0]
    mono = Engine(state, cfg, num_pages=12, name="cl_mono", debug=True,
                  time_fn=lambda: mono_clock[0], step_fn=shared_fn,
                  page_size=SHAPE_KW["page_size"],
                  max_batch=SHAPE_KW["max_batch"],
                  chunk_size=SHAPE_KW["chunk_size"],
                  prefill_rows=SHAPE_KW["prefill_rows"],
                  max_model_len=SHAPE_KW["max_model_len"])
    for i, p in enumerate(prompts):
        mono.add_request(p, NEW, arrival_time=float(i))
    while mono.has_work:
        mono.step()
        mono_clock[0] += 1.0
    want = {i: list(mono.finished[i].out_tokens)
            for i in range(len(prompts))}
    # ...which is itself the solo generate() answer (sanity)
    assert want[0] == _solo(state, cfg, prompts[0], NEW)

    # one decode replica and a pool a few pages short of the trace's
    # concurrent demand: adopted requests grow past it and preempt
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=2,
                       mode="disaggregated", num_prefill=1,
                       name="cl_disagg", coordinator=False)
    reqs = [cl.add_request(p, NEW, arrival_time=float(i))
            for i, p in enumerate(prompts)]
    _drain(cl)
    ms = cl.metrics_summary()
    # the adversarial trace really was adversarial
    assert ms["preemptions"] > 0, "no preemption: trace too easy"
    assert ms["cluster_handoffs"] == len(prompts)
    assert ms["handoff_payload_bytes"] > 0
    # every page move carried a positive alpha-beta prediction
    assert all(r["predicted_s"] > 0 for r in cl.transport.records)
    # bit-for-bit equality, request for request
    for r in reqs:
        assert r.out_tokens == want[r.req_id], \
            (r.req_id, r.out_tokens, want[r.req_id])
    # prefill replicas decoded nothing beyond the handoff token; decode
    # replicas prefilled only adopted/preempted work
    pre = cl.replicas[0].engine.metrics_summary()
    assert pre["requests_completed"] == len(prompts)
    for rep in cl.replicas[1:]:
        assert rep.engine.metrics_summary()["requests_completed"] \
            + pre["requests_completed"] >= len(prompts)
    cl.close()


def test_disaggregated_eos_on_first_token(model_state, shared_fn):
    """A request whose first sampled token is EOS finishes at the
    prefill replica — no handoff, no decode-stage orphan."""
    state, cfg = model_state
    prompt = [9, 8, 7, 6, 5, 4, 3, 2, 1]
    first = _solo(state, cfg, prompt, 1)[0]
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=2,
                       mode="disaggregated", num_prefill=1,
                       name="cl_eos", coordinator=False)
    r = cl.add_request(prompt, 8, eos_token_id=first, arrival_time=0.0)
    _drain(cl)
    assert r.out_tokens == [first]
    assert cl.metrics_summary()["cluster_handoffs"] == 0
    assert not cl._pending_handoffs and not cl._placed
    cl.close()


# ---------------------------------------------------------------------------
# replica death / re-route (coordinator heartbeat plane)
# ---------------------------------------------------------------------------


def test_reroute_on_replica_death(model_state, shared_fn):
    """A replica missing heartbeats is reported dead (rpc coordinator
    TTL) and its queued + running requests drain to the survivors: the
    completion set equals the submission set, outputs still exact."""
    state, cfg = model_state
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=2, name="cl_death",
                       coordinator=True, ttl=0.3,
                       heartbeat_interval=0.05, policy="load")
    rng = np.random.RandomState(5)
    prompts = [rng.randint(1, 97, size=10).tolist() for _ in range(6)]
    reqs = [cl.add_request(p, 12, arrival_time=0.0) for p in prompts]
    # a few steps: requests spread over both replicas and start running
    for _ in range(3):
        cl.step()
        cl._test_clock[0] += 1.0
    victims = [r for r in reqs if r.replica == 1]
    assert victims, "load placement left replica 1 empty; test is vacuous"
    cl.kill_replica(1)
    time.sleep(0.5)                      # heartbeat TTL lapses
    _drain(cl)
    # completion-set equality: nothing lost, nothing invented
    assert set(cl.finished) == {r.req_id for r in reqs}
    assert any(r.n_reroutes > 0 for r in victims)
    assert cl.metrics_summary()["cluster_reroutes"] >= len(victims)
    # re-routed requests replayed exactly (temp 0)
    for r in reqs:
        assert r.out_tokens == _solo(state, cfg, r.prompt, 12)
    assert cl.replicas[0].alive and not cl.replicas[1].alive
    cl.close()


# ---------------------------------------------------------------------------
# handoff pricing gate (analysis plane)
# ---------------------------------------------------------------------------


@pytest.mark.lint_graph
def test_handoff_edge_claim_fully_explained(model_state, shared_fn):
    """The cluster gate: run a disaggregated trace, then require the
    decode replica's handoff records to be 100%% explained by priced
    edge claims (kv-handoff-unpriced silent, non-vacuously), and that
    stripping the pricing makes the rule fire.  The full-analysis
    version of this gate runs in the CI lint-graph build
    (``gate_serving@r{i}/unified``, ANALYSIS_BASELINE.json); here the
    rule runs straight off the registered handle's meta so the test
    stays cheap."""
    from hetu_tpu.analysis import AnalysisContext, run_rules
    from hetu_tpu.graph.graph import clear_executables, get_executable
    state, cfg = model_state
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=2,
                       mode="disaggregated", num_prefill=1,
                       name="cl_gate", coordinator=False)
    rng = np.random.RandomState(2)
    for i in range(3):
        cl.add_request(rng.randint(1, 97, size=12).tolist(), 4,
                       arrival_time=float(i))
    _drain(cl)
    assert len(cl.transport.records) == 3       # non-vacuous
    handle = get_executable("cl_gate@r1/unified")
    assert callable(handle.meta.get("kv_handoff"))
    assert len(handle.meta["kv_handoff"]()) == 3
    ctx = AnalysisContext(name=handle.name, meta=handle.meta)
    assert run_rules(ctx, only=["kv-handoff-unpriced"]) == []
    # seed a violation: strip one record's pricing -> exactly one fire
    cl.transport.records[1]["predicted_s"] = None
    fired = run_rules(AnalysisContext(name=handle.name,
                                      meta=handle.meta),
                      only=["kv-handoff-unpriced"])
    assert len(fired) == 1 and fired[0].rule == "kv-handoff-unpriced"
    assert "unpriced" in fired[0].message
    assert fired[0].severity == "error"
    # ...and the prefill replica (no kv_handoff meta) is out of scope
    pre = get_executable("cl_gate@r0/unified")
    assert run_rules(AnalysisContext(name=pre.name, meta=pre.meta),
                     only=["kv-handoff-unpriced"]) == []
    cl.close()
    clear_executables("cl_gate@")


# ---------------------------------------------------------------------------
# aggregate metrics
# ---------------------------------------------------------------------------


def test_metrics_text_merges_with_replica_label(model_state, shared_fn):
    state, cfg = model_state
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=2, name="cl_prom",
                       coordinator=False)
    for i in range(4):
        cl.add_request([1 + i, 2, 3, 4], 3, arrival_time=0.0)
    _drain(cl)
    text = cl.metrics_text()
    assert 'replica="r0"' in text and 'replica="r1"' in text
    # every sample line carries the label; TYPE headers appear once
    # per metric and samples group under them (valid exposition)
    seen_types = []
    current = None
    for line in text.strip().splitlines():
        if line.startswith("# TYPE"):
            current = line.split()[2]
            assert current not in seen_types, f"duplicate TYPE {current}"
            seen_types.append(current)
        else:
            assert 'replica="r' in line, line
            name = line.split("{")[0]
            base = name
            for suf in ("_bucket", "_sum", "_count"):
                if name.endswith(suf):
                    base = name[: -len(suf)]
            assert base == current, (line, current)
    # both replicas' samples present for a shared counter
    tg = [ln for ln in text.splitlines()
          if ln.startswith("tokens_generated{")]
    assert len(tg) == 2
    cl.close()


def test_metrics_summary_survives_replica_reset(model_state, shared_fn):
    """Counter sums bank a replica's pre-reset epoch: reset_metrics on
    one replica must neither double-count nor lose tokens."""
    state, cfg = model_state
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=2, name="cl_sum",
                       coordinator=False)
    NEW = 4
    for i in range(4):
        cl.add_request([5 + i, 6, 7], NEW, arrival_time=0.0)
    _drain(cl)
    first = cl.metrics_summary()
    assert first["tokens_generated"] == 4 * NEW
    # replica 0 resets (a service rotating its scrape window)
    cl.replicas[0].engine.reset_metrics()
    assert cl.metrics_summary()["tokens_generated"] == 4 * NEW, \
        "reset lost the banked epoch"
    for i in range(4):
        cl.add_request([15 + i, 6, 7], NEW,
                       arrival_time=cl._test_clock[0])
    _drain(cl)
    after = cl.metrics_summary()
    assert after["tokens_generated"] == 8 * NEW, \
        "reset double-counted or dropped an epoch"
    assert after["requests_completed"] == 8
    cl.close()


def test_replicas_share_one_compiled_program(model_state, shared_fn):
    """N identically-shaped replicas compile ONCE: the cluster passes
    the first engine's jitted step fn to the rest."""
    state, cfg = model_state
    # deliberately NO injected step_fn: the cluster's own sharing is
    # under test, so it gets a fresh program with a fresh jit cache
    cl = _make_cluster(state, cfg, num_replicas=3, name="cl_share",
                       coordinator=False)
    fns = {id(r.engine._compiled["unified"]) for r in cl.replicas}
    assert len(fns) == 1
    cl.add_request([1, 2, 3, 4, 5], 3, arrival_time=0.0)
    _drain(cl)
    # identical pool shapes -> the fleet compiled exactly once
    for r in cl.replicas:
        assert r.engine.compile_count == 1
    cl.close()
