"""MoE / expert-parallel tests.

Mirrors the reference's v1 MoE capability
(``hetu/v1/python/hetu/layers/moe_layer.py``, gates in
``v1/python/hetu/layers/*Gate.py``): gating math checked against a numpy
oracle, end-to-end training on the single device, and EP equivalence on
the virtual 8-device mesh (single-device MoE == ep-sharded MoE).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import nn, ops, optim
from hetu_tpu.nn.moe import (BalanceGate, Experts, HashGate, KTop1Gate,
                             MoELayer, SAMGate, TopKGate,
                             balance_gating_impl, hash_gating_impl,
                             ktop1_gating_impl, make_moe_layer,
                             sam_gating_impl, topk_gating_impl)


# full-model training loops: excluded from the dev fast path
pytestmark = pytest.mark.slow


def _fix_seed():
    from hetu_tpu.graph import ctor
    ctor._seed_counter[0] = 777


class TestGatingMath:
    """Pure gating impls vs numpy oracle."""

    def test_top1_dispatch_matches_numpy(self):
        rng = np.random.RandomState(0)
        T, E, cf = 16, 4, 2.0
        logits = rng.randn(T, E).astype(np.float32)
        l_aux, combine, dispatch = topk_gating_impl(logits, 1, cf)
        combine, dispatch = np.asarray(combine), np.asarray(dispatch)
        C = dispatch.shape[-1]
        assert C == int(np.ceil(T / E * cf))
        # oracle: sequential greedy top-1 with capacity
        gates = np.exp(logits - logits.max(-1, keepdims=True))
        gates /= gates.sum(-1, keepdims=True)
        counts = np.zeros(E, int)
        for t in range(T):
            e = int(gates[t].argmax())
            if counts[e] < C:
                assert dispatch[t, e, counts[e]] == 1.0
                np.testing.assert_allclose(combine[t, e, counts[e]],
                                           gates[t, e], rtol=1e-5)
                assert dispatch[t].sum() == 1.0
                counts[e] += 1
            else:
                assert dispatch[t].sum() == 0.0  # dropped
        # every slot used at most once
        assert (dispatch.sum(0) <= 1.0).all()

    def test_top2_capacity_and_aux(self):
        rng = np.random.RandomState(1)
        T, E = 32, 8
        logits = rng.randn(T, E).astype(np.float32)
        l_aux, combine, dispatch = topk_gating_impl(logits, 2, 1.0)
        dispatch = np.asarray(dispatch)
        assert dispatch.shape[-1] == 2 * int(np.ceil(T / E))
        assert (dispatch.sum((0, 2)) <= dispatch.shape[-1]).all()
        # perfectly uniform gates would give l_aux ~= k (balance optimum)
        assert float(l_aux) > 0.0

    def test_ktop1_routes_within_prototypes(self):
        rng = np.random.RandomState(2)
        T, E, k = 16, 8, 2
        logits = rng.randn(T, E).astype(np.float32)
        _, _, dispatch = ktop1_gating_impl(logits, k, 2.0)
        dispatch = np.asarray(dispatch)
        # each token gets one expert from each prototype half
        per_token = dispatch.sum(-1)  # [T, E]
        assert (per_token[:, :4].sum(-1) <= 1.0).all()
        assert (per_token[:, 4:].sum(-1) <= 1.0).all()

    def test_hash_gate_deterministic_uniform(self):
        ids = np.arange(24, dtype=np.int32)
        _, combine, dispatch = hash_gating_impl(ids % 4, 4, 1.0)
        dispatch = np.asarray(dispatch)
        # perfect round-robin: every expert gets exactly T/E tokens, none drop
        assert dispatch.sum() == 24.0
        np.testing.assert_array_equal(dispatch.sum((0, 2)), [6, 6, 6, 6])

    def test_sam_gate_respects_groups(self):
        rng = np.random.RandomState(3)
        T, E, G = 16, 8, 4
        logits = rng.randn(T, E).astype(np.float32)
        _, _, dispatch = sam_gating_impl(logits, 2, 4.0, G)
        dispatch = np.asarray(dispatch)
        per_token_expert = dispatch.sum(-1)  # [T, E]
        Eg = E // G
        for t in range(T):
            chosen = np.where(per_token_expert[t] > 0)[0]
            if len(chosen):
                groups = set(chosen // Eg)
                assert len(groups) == 1  # all picks in the top-1 group

    def test_balance_gate_balances_load(self):
        rng = np.random.RandomState(4)
        T, E = 64, 4
        # adversarial scores: every token prefers expert 0
        scores = rng.randn(T, E).astype(np.float32)
        scores[:, 0] += 5.0
        _, _, dispatch = balance_gating_impl(scores, 1.25, n_iters=20)
        loads = np.asarray(dispatch).sum((0, 2))
        # Sinkhorn spreads the load instead of collapsing onto expert 0
        assert loads.max() - loads.min() <= T // E  # near-uniform
        assert loads[0] < T * 0.75


class TestMoELayer:
    def _data(self, T=32, d=16, seed=0):
        rng = np.random.RandomState(seed)
        return rng.randn(4, T // 4, d).astype(np.float32)

    @pytest.mark.parametrize("gate_type", ["topk", "ktop1", "sam", "balance"])
    def test_forward_backward(self, gate_type):
        _fix_seed()
        X = self._data()
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", X.shape, name="x")
            moe = make_moe_layer(16, 32, num_experts=4, gate_type=gate_type,
                                 k=2, capacity_factor=2.0, num_groups=2)
            out, l_aux = moe(x)
            loss = ops.reduce_mean(out * out) + 0.01 * l_aux
            train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            vals = []
            for _ in range(3):
                o = g.run(loss, [loss, train_op], {x: X})
                vals.append(float(np.asarray(o[0])))
        assert np.isfinite(vals).all()
        assert vals[-1] < vals[0]  # training decreases the objective

    def test_hash_gate_layer(self):
        _fix_seed()
        X = self._data()
        ids = np.arange(32, dtype=np.int32).reshape(4, 8)
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", X.shape, name="x")
            tid = ht.placeholder("int32", ids.shape, name="tid")
            moe = make_moe_layer(16, 32, num_experts=4, gate_type="hash")
            out, l_aux = moe(x, token_ids=tid)
            (o,) = g.run(out, [out], {x: X, tid: ids})
        assert np.asarray(o).shape == X.shape

    def test_dropless_dispatch_matches_dense_oracle(self):
        """dispatch_mode='dropless' (ops/moe_dispatch.py blocked
        group-GEMM): no token drops, so the output must equal the dense
        gate-weighted top-k expert computation exactly."""
        _fix_seed()
        X = self._data(T=24)
        with ht.graph("eager", create_new=True):
            moe = make_moe_layer(16, 32, num_experts=4, gate_type="topk",
                                 k=2, dispatch_mode="dropless")
            x = ht.parameter(X.reshape(-1, 16), name="x", trainable=False)
            out, l_aux = moe(x)
            o = np.asarray(out.get_data())
            xs = np.asarray(x.get_data())
            W = np.asarray(moe.gate.wg.get_data())
            gates = np.asarray(jax.nn.softmax(
                jnp.asarray(xs @ W.T), axis=-1))
            w1 = np.asarray(moe.experts.w1.get_data())
            b1 = np.asarray(moe.experts.b1.get_data())
            w2 = np.asarray(moe.experts.w2.get_data())
            b2 = np.asarray(moe.experts.b2.get_data())
            ref = np.zeros_like(xs)
            for t in range(xs.shape[0]):
                for e in np.argsort(-gates[t])[:2]:
                    h = np.asarray(jax.nn.gelu(
                        jnp.asarray(xs[t] @ w1[e] + b1[e, 0])))
                    ref[t] += gates[t, e] * (h @ w2[e] + b2[e, 0])
        np.testing.assert_allclose(o, ref, atol=1e-4)
        assert float(l_aux.get_data()) > 0

    def test_dropless_trains(self):
        _fix_seed()
        X = self._data()
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", X.shape, name="x")
            moe = make_moe_layer(16, 32, num_experts=4, gate_type="topk",
                                 k=2, dispatch_mode="dropless")
            out, l_aux = moe(x)
            loss = ops.reduce_mean(out * out) + 0.01 * l_aux
            train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            vals = []
            for _ in range(3):
                o = g.run(loss, [loss, train_op], {x: X})
                vals.append(float(np.asarray(o[0])))
        assert np.isfinite(vals).all()
        assert vals[-1] < vals[0]

    def test_dropless_rejects_bad_config(self):
        experts = Experts(4, 16, 32)
        with pytest.raises(ValueError, match="TopKGate"):
            MoELayer(HashGate(4), experts, dispatch_mode="dropless")
        with pytest.raises(ValueError, match="dispatch_mode"):
            make_moe_layer(16, 32, 4, dispatch_mode="bogus")

    def test_gate_gradient_flows(self):
        """The router weight must receive gradient through combine."""
        _fix_seed()
        X = self._data()
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", X.shape, name="x")
            moe = make_moe_layer(16, 32, num_experts=4, gate_type="topk", k=1,
                                 capacity_factor=2.0)
            out, l_aux = moe(x)
            loss = ops.reduce_mean(out * out) + 0.01 * l_aux
            wg = moe.gate.wg
            before = np.asarray(g.get_tensor_value(wg)).copy()
            train_op = optim.SGDOptimizer(lr=1.0).minimize(loss)
            g.run(loss, [train_op], {x: X})
            after = np.asarray(g.get_tensor_value(wg))
        assert np.abs(after - before).max() > 0


class TestMoEGPT:
    """MoE wired into the GPT family (v1 MoE-transformer capability)."""

    def test_moe_gpt_trains_and_matches_ep(self, devices8):
        import hetu_tpu as ht
        from hetu_tpu.models import GPTConfig, GPTLMHeadModel
        rng = np.random.RandomState(0)
        X = rng.randint(0, 64, (8, 16)).astype(np.int32)
        L = np.roll(X, -1, 1)

        def run(mesh_shape, ep_axis, devs=None):
            _fix_seed()
            mesh = ht.create_mesh(mesh_shape, devs) if mesh_shape else None
            cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=4, max_seq_len=16, num_experts=4,
                            moe_top_k=2, dtype="float32", sp=False,
                            ep_axis=ep_axis)
            with ht.graph("define_and_run", create_new=True,
                          mesh=mesh) as g:
                ids = ht.parallel_placeholder(
                    "int32", X.shape, pspec=P("dp", None) if mesh else None,
                    name="ids")
                labels = ht.parallel_placeholder(
                    "int32", X.shape, pspec=P("dp", None) if mesh else None,
                    name="labels")
                model = GPTLMHeadModel(cfg)
                loss = model(ids, labels)
                train_op = optim.AdamOptimizer(lr=1e-3).minimize(loss)
                return [float(np.asarray(
                    g.run(loss, [loss, train_op],
                          {ids: X, labels: L})[0])) for _ in range(3)]

        l1 = run(None, None)
        assert l1[-1] < l1[0]
        l2 = run({"dp": 2, "ep": 4}, "ep", devices8)
        np.testing.assert_allclose(l1, l2, rtol=2e-3, atol=1e-4)


class TestExpertParallel:
    """Single-device MoE == EP-sharded MoE (same init), mirroring the
    reference's loss-equivalence testing style."""

    def _run(self, mesh, ep_axis, devices=None, steps=3):
        _fix_seed()
        rng = np.random.RandomState(5)
        X = rng.randn(8, 8, 16).astype(np.float32)
        m = ht.create_mesh(mesh, devices) if mesh else None
        with ht.graph("define_and_run", create_new=True, mesh=m) as g:
            x = ht.parallel_placeholder("float32", X.shape,
                                        pspec=P("dp", None, None) if m
                                        else None, name="x")
            moe = make_moe_layer(16, 32, num_experts=4, gate_type="topk",
                                 k=2, capacity_factor=2.0, ep_axis=ep_axis)
            out, l_aux = moe(x)
            loss = ops.reduce_mean(out * out) + 0.01 * l_aux
            train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            losses = []
            for _ in range(steps):
                o = g.run(loss, [loss, train_op], {x: X})
                losses.append(float(np.asarray(o[0])))
        return losses

    def test_ep_matches_single_device(self, devices8):
        ref = self._run(None, None)
        ep = self._run({"dp": 2, "ep": 4}, "ep", devices=devices8)
        np.testing.assert_allclose(ref, ep, rtol=2e-4, atol=1e-5)

    def test_ep_without_dp(self, devices8):
        ref = self._run(None, None)
        ep = self._run({"dp": 1, "ep": 4}, "ep", devices=devices8[:4])
        np.testing.assert_allclose(ref, ep, rtol=2e-4, atol=1e-5)
