"""Silent-failure sentry + durable checkpoint generations (ISSUE 14).

The invariants: an anomalous step (NaN/Inf grads, grad-norm spike,
relative loss spike) is skipped ON-DEVICE with bitwise-zero residue —
the loss curve and params of clean steps are bit-for-bit the
anomaly-free run's, with no recompile and no extra host fetch; the
policy ladder rewinds to the newest checkpoint *generation* that
VERIFIES (blake2b manifest), falling back past corrupted
(``shard_corrupt``) and half-written (``kill_mid_write``) generations;
and every restore that skips the digest check fails the
``unverified-restore`` lint rule.
"""
import os
import threading
import time

import numpy as np
import jax
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu.elastic import FaultTolerantTrainer, TrainBuild, WorkerMonitor
from hetu_tpu.fault import FaultEvent, FaultPlan
from hetu_tpu.graph import ctor
from hetu_tpu.models import GPTConfig, GPTLMHeadModel, llama_config
from hetu_tpu.parallel import create_mesh
from hetu_tpu.obs.tracer import SpanTracer, install_tracer
from hetu_tpu.resilience import (corrupt_generation, list_generations,
                                 load_latest_generation, save_generation,
                                 verify_generation)
from hetu_tpu.utils.checkpoint import (WriterDeathError,
                                       arm_kill_mid_write,
                                       disarm_kill_mid_write,
                                       load_checkpoint, load_split,
                                       restore_records, save_checkpoint,
                                       save_split)

# one deterministic batch table for every data-cursor test: cursor c
# trains on TABLE[c], so "the run that never saw batch c" is exactly
# the reference a skip must reproduce bit-for-bit
TABLE = np.random.RandomState(42).randint(0, 64, (64, 8, 16)) \
    .astype(np.int32)


def _single_build(sentry=True, max_grad_norm=None, lr=1e-2):
    """Single-device implicit-path build (no mesh): graph, model, opt,
    step(cursor)."""
    ctor._seed_counter[0] = 123
    gctx = ht.graph("define_and_run", create_new=True)
    g = gctx.__enter__()
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=4, max_seq_len=16, sp=False, dropout=0.0)
    ids = ht.placeholder("int32", (4, 16))
    labels = ht.placeholder("int32", (4, 16))
    model = GPTLMHeadModel(cfg)
    loss = model(ids, labels)
    opt = ht.optim.AdamOptimizer(lr=lr, sentry=sentry,
                                 max_grad_norm=max_grad_norm)
    train_op = opt.minimize(loss)

    def step(cursor):
        b = TABLE[cursor][:4]
        out = g.run(loss, [loss, train_op],
                    {ids: b, labels: np.roll(b, -1, axis=1)})
        return float(np.asarray(out[0]))

    return g, model, opt, step, \
        (lambda: gctx.__exit__(None, None, None))


def _flat_build_fn(dp, devices, sentry=True, max_grad_norm=None):
    """dp-mesh flat ZeRO-2 build (the explicit reduce-scatter path)."""
    ctor._seed_counter[0] = 777
    mesh = create_mesh({"dp": dp}, devices[:dp])
    cfg = llama_config(vocab_size=64, hidden_size=32, num_layers=1,
                       num_heads=4, max_seq_len=16, sp=False)
    gctx = ht.graph("define_and_run", create_new=True, mesh=mesh)
    g = gctx.__enter__()
    ids = ht.parallel_placeholder("int32", (8, 16), pspec=P("dp", None),
                                  name="ids")
    labels = ht.parallel_placeholder("int32", (8, 16),
                                     pspec=P("dp", None), name="labels")
    model = GPTLMHeadModel(cfg)
    loss = model(ids, labels)
    opt = ht.optim.AdamOptimizer(lr=1e-2, zero=2, grad_comm="fp32",
                                 flat_state=True, sentry=sentry,
                                 max_grad_norm=max_grad_norm)
    train_op = opt.minimize(loss)

    def step_fn(cursor):
        b = TABLE[cursor]
        out = g.run(loss, [loss, train_op],
                    {ids: b, labels: np.roll(b, -1, axis=1)})
        assert g._grad_comm_active, g._grad_comm_fallback
        return float(np.asarray(out[0]))

    return TrainBuild(graph=g, model=model, optimizer=opt,
                      step_fn=step_fn,
                      close=lambda: gctx.__exit__(None, None, None))


def _params(model):
    return {k: np.asarray(v, np.float32)
            for k, v in model.state_dict().items()}


def _bitwise_equal(a, b):
    return set(a) == set(b) and \
        all(np.array_equal(a[k], b[k]) for k in a)


# ---------------------------------------------------------------------------
# the on-device sentry: verdicts, skip residue, honesty pins
# ---------------------------------------------------------------------------


def test_sentry_skip_is_bitwise_zero_residue():
    """A grad_nan injection skips the update ON-DEVICE: losses and
    final params of the clean steps are bit-for-bit the run that never
    saw the poisoned batch — and the whole run rides ONE compiled plan
    (injection is a feed value, never a retrace)."""
    g, model, opt, step, close = _single_build()
    losses = [step(0), step(1)]
    g.inject_numeric_fault("grad_nan")
    bad = step(2)                      # the poisoned attempt
    v = opt.sentry.last_verdict()
    assert v["anomaly"] and v["grad_nonfinite"] and v["consecutive"] == 1
    assert not v["loss_nonfinite"]     # only the grads were poisoned
    assert np.isnan(v["grad_norm"])
    losses.append(step(3))
    v2 = opt.sentry.last_verdict()
    assert not v2["anomaly"] and v2["consecutive"] == 0
    assert v2["grad_norm"] > 0
    assert len(g._plan_pool) == 1, "sentry/injection caused a retrace"
    p_chaos = _params(model)
    close()

    # reference: same sentry-on program, batches 0,1,3 only
    g2, model2, opt2, step2, close2 = _single_build()
    ref = [step2(c) for c in (0, 1, 3)]
    assert ref == losses, "clean-step losses are not bitwise equal"
    assert _bitwise_equal(p_chaos, _params(model2)), \
        "skipped step left residue in the params"
    close2()


def test_sentry_zero_extra_host_transfers():
    """Honesty pin: the verdict rides the existing step outputs — one
    host read per step alongside the loss fetch, executable called
    exactly once per attempt, compile count 1."""
    g, model, opt, step, close = _single_build()
    reads0 = opt.sentry.host_reads
    for c in range(3):
        step(c)
        opt.sentry.last_verdict()
    g.inject_numeric_fault("grad_spike")
    step(3)
    opt.sentry.last_verdict()
    assert opt.sentry.host_reads - reads0 == 4     # one per attempt
    assert len(g._plan_pool) == 1
    close()


def test_sentry_loss_spike_needs_warmup_and_fires():
    """The relative loss-spike verdict: silent during EMA warmup, fires
    once the loss jumps past factor * EMA, and the skipped step leaves
    the params bitwise unchanged."""
    g, model, opt, step, close = _single_build()
    step(0)
    # warmup: a spike injected before the EMA has history must NOT trip
    g.inject_numeric_fault("loss_spike")
    step(1)
    v = opt.sentry.last_verdict()
    assert not v["loss_spike"], "spike verdict fired during warmup"
    step(2), step(3)
    before = _params(model)
    g.inject_numeric_fault("loss_spike")
    spiked = step(4)
    v = opt.sentry.last_verdict()
    assert v["anomaly"] and v["loss_spike"] and not v["grad_spike"]
    assert spiked > 4 * opt.sentry.config.loss_spike_factor / 8.0
    assert _bitwise_equal(before, _params(model)), \
        "loss-spike step updated the params"
    close()


def test_sentry_flat_zero2_skip_and_step_counter(devices8):
    """The flat reduce-scatter path: grad_spike verdict from the
    psum-shared global norm, on-device skip freezes the flat buffers
    AND the step counter, clean steps bitwise vs the anomaly-free run,
    one compiled plan throughout."""
    b = _flat_build_fn(8, devices8, max_grad_norm=1.0)
    losses = [b.step_fn(0), b.step_fn(1)]
    assert int(np.asarray(b.optimizer._state["step"])) == 2
    b.graph.inject_numeric_fault("grad_spike")
    b.step_fn(2)
    v = b.optimizer.sentry.last_verdict()
    assert v["anomaly"] and v["grad_spike"] and not v["grad_nonfinite"]
    assert v["grad_norm"] > b.optimizer.sentry.config.grad_norm_max
    assert int(np.asarray(b.optimizer._state["step"])) == 2, \
        "skip advanced the optimizer step counter"
    losses.append(b.step_fn(3))
    assert int(np.asarray(b.optimizer._state["step"])) == 3
    assert len(b.graph._plan_pool) == 1
    p_chaos = _params(b.model)
    b.close()

    ref = _flat_build_fn(8, devices8, max_grad_norm=1.0)
    ref_losses = [ref.step_fn(c) for c in (0, 1, 3)]
    assert ref_losses == losses
    assert _bitwise_equal(p_chaos, _params(ref.model))
    assert int(np.asarray(ref.optimizer._state["step"])) == 3
    ref.close()


# ---------------------------------------------------------------------------
# checkpoint generations: manifest, verify, fallback, retention
# ---------------------------------------------------------------------------


def _ck_build():
    """Tiny single-device model+optimizer for checkpoint-plane tests."""
    gctx = ht.graph("define_and_run", create_new=True)
    g = gctx.__enter__()
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=4, max_seq_len=16, sp=False, dropout=0.0)
    ids = ht.placeholder("int32", (2, 16))
    labels = ht.placeholder("int32", (2, 16))
    model = GPTLMHeadModel(cfg)
    loss = model(ids, labels)
    opt = ht.optim.AdamOptimizer(lr=1e-2)
    train_op = opt.minimize(loss)
    rng = np.random.RandomState(0)
    feed = {ids: rng.randint(0, 64, (2, 16)),
            labels: rng.randint(0, 64, (2, 16))}

    def step():
        out = g.run(loss, [loss, train_op], feed)
        return float(np.asarray(out[0]))

    return g, model, opt, step, \
        (lambda: gctx.__exit__(None, None, None))


def test_generation_verify_detects_corruption_and_staleness(tmp_path):
    g, model, opt, step, close = _ck_build()
    step()
    root = str(tmp_path / "gens")
    d1 = save_generation(model, opt, root, step=1, keep=4)
    ok, problems = verify_generation(d1)
    assert ok, problems
    # an unmanifested straggler (a stale shard from another save) is
    # rejected wholesale — the stale-mix hazard the generations close
    stale = os.path.join(d1, "model_00099-of-00100.safetensors")
    with open(stale, "wb") as f:
        f.write(b"junk")
    ok, problems = verify_generation(d1)
    assert not ok and any("unmanifested" in p for p in problems)
    os.remove(stale)
    assert verify_generation(d1)[0]
    # flipped bytes -> digest mismatch
    corrupt_generation(root, step=1)
    ok, problems = verify_generation(d1)
    assert not ok and any("digest mismatch" in p for p in problems)
    close()


def test_restore_falls_back_past_corrupted_generation(tmp_path):
    """shard_corrupt on the newest generation: the verified restore
    falls back one generation and restores exactly its params."""
    g, model, opt, step, close = _ck_build()
    step()
    root = str(tmp_path / "gens")
    save_generation(model, opt, root, step=1, keep=4)
    want = _params(model)
    step()
    save_generation(model, opt, root, step=2, keep=4)
    corrupt_generation(root)          # newest = gen-2
    info = load_latest_generation(model, opt, root)
    assert info["generation"] == 1
    assert [f["generation"] for f in info["fallbacks"]] == [2]
    assert _bitwise_equal(want, _params(model)), \
        "fallback restore did not reproduce gen-1's params"
    close()


def test_kill_mid_write_previous_generation_survives(tmp_path):
    """The kill_mid_write chaos verdict: the writer dies between
    shards, the partial generation never commits a manifest, and the
    previous generation still verifies and restores."""
    g, model, opt, step, close = _ck_build()
    step()
    root = str(tmp_path / "gens")
    save_generation(model, opt, root, step=1, keep=4)
    want = _params(model)
    step()
    arm_kill_mid_write(after_files=1)
    try:
        with pytest.raises(WriterDeathError):
            save_generation(model, opt, root, step=2, keep=4)
    finally:
        disarm_kill_mid_write()
    d2 = os.path.join(root, "gen-2")
    assert os.path.isdir(d2), "the partial write left nothing at all"
    assert not os.path.exists(os.path.join(d2, "manifest.json"))
    ok, problems = verify_generation(d2)
    assert not ok and "no manifest" in problems[0]
    assert verify_generation(os.path.join(root, "gen-1"))[0]
    info = load_latest_generation(model, opt, root)
    assert info["generation"] == 1
    assert [f["generation"] for f in info["fallbacks"]] == [2]
    assert _bitwise_equal(want, _params(model))
    close()


def test_resave_same_step_death_keeps_committed_generation(tmp_path):
    """A re-save of a step that already has a COMMITTED generation
    (emergency flush, rewind replay) must not destroy it: if the fresh
    write dies mid-shard, the displaced generation is restored and
    still verifies/loads."""
    g, model, opt, step, close = _ck_build()
    step()
    root = str(tmp_path / "gens")
    save_generation(model, opt, root, step=1, keep=4)
    want = _params(model)
    step()
    arm_kill_mid_write(after_files=1)
    try:
        with pytest.raises(WriterDeathError):
            save_generation(model, opt, root, step=1, keep=4)
    finally:
        disarm_kill_mid_write()
    d1 = os.path.join(root, "gen-1")
    assert verify_generation(d1)[0], \
        "failed re-save destroyed the committed generation"
    info = load_latest_generation(model, opt, root)
    assert info["generation"] == 1 and not info["fallbacks"]
    assert _bitwise_equal(want, _params(model))
    # a SUCCESSFUL re-save retires the old one cleanly
    step()
    save_generation(model, opt, root, step=1, keep=4)
    assert verify_generation(d1)[0]
    assert not os.path.exists(d1 + ".prev")
    close()


def test_generation_retention_prunes_committed_only(tmp_path):
    g, model, opt, step, close = _ck_build()
    step()
    root = str(tmp_path / "gens")
    for s in (1, 2, 3, 4):
        save_generation(model, opt, root, step=s, keep=2)
    assert list_generations(root) == [3, 4]
    close()


def test_resave_fewer_shards_drops_stale_files(tmp_path):
    """Satellite regression (the load_split stale-mix hazard): a
    re-save with fewer shards into the same directory removes the old
    save's extra shard files, and the restore matches the LATEST save
    exactly."""
    d = str(tmp_path / "ck")
    a = {"w": np.arange(64, dtype=np.float32).reshape(8, 8),
         "b": np.ones((8,), np.float32)}
    save_split(a, d, num_shards=4)
    assert len([f for f in os.listdir(d)
                if f.endswith(".safetensors")]) == 4
    b = {"w": -np.arange(64, dtype=np.float32).reshape(8, 8),
         "b": 3 * np.ones((8,), np.float32)}
    save_split(b, d, num_shards=2)
    shard_files = [f for f in os.listdir(d)
                   if f.endswith(".safetensors")]
    assert len(shard_files) == 2, \
        f"stale shards survived the re-save: {sorted(shard_files)}"
    back = load_split(d)
    for k in b:
        np.testing.assert_array_equal(back[k], b[k])


def test_background_checkpoint_does_not_starve_heartbeat(tmp_path):
    """A background checkpoint write must not starve the coordinator
    heartbeat into a false death -> spurious re-plan (the PR 12
    refusal-window pin, applied to the writer thread).  The write is
    made deterministically slow through the chaos write hook (3 shards
    x 0.3 s >> the 0.4 s TTL)."""
    from hetu_tpu.rpc.coordinator import CoordinatorClient, \
        CoordinatorServer
    from hetu_tpu.utils.checkpoint import safetensors_io

    state = {f"w{i}": np.random.RandomState(i).randn(64, 64)
             .astype(np.float32) for i in range(3)}
    with CoordinatorServer(world_size=1, ttl=0.4) as srv:
        c = CoordinatorClient(srv.address, uid="w0", ttl=0.4)
        c.connect()
        stop = c.start_heartbeat_thread(interval=0.05)
        slow_calls = []

        def slow_hook(fname):
            slow_calls.append(fname)
            time.sleep(0.3)

        safetensors_io._WRITE_CHAOS[0] = slow_hook
        try:
            from hetu_tpu.utils.checkpoint import save_split_async
            h = save_split_async(state, str(tmp_path / "bg"),
                                 num_shards=3)
            while not h.done():
                assert not srv.dead_ranks(), \
                    "background checkpoint write starved the heartbeat"
                time.sleep(0.05)
            h.wait(timeout=60)
        finally:
            safetensors_io._WRITE_CHAOS[0] = None
            stop.set()
        assert len(slow_calls) >= 3, "the slow write never engaged"
        assert not srv.dead_ranks()
    back = load_split(str(tmp_path / "bg"))
    for k in state:
        np.testing.assert_array_equal(back[k], state[k])


# ---------------------------------------------------------------------------
# the unverified-restore rule
# ---------------------------------------------------------------------------


@pytest.mark.lint_graph
def test_unverified_restore_rule(tmp_path):
    """Repo-standard rule contract: silent on digest-checked restores
    (non-vacuously — records present), fires exactly once per raw
    load, honors verify_exempt, and a raising hook is itself a
    failure."""
    from hetu_tpu.analysis import AnalysisContext, run_rules
    g, model, opt, step, close = _ck_build()
    step()
    root = str(tmp_path / "gens")
    save_generation(model, opt, root, step=1, keep=4)
    n0 = len(restore_records(root))
    load_latest_generation(model, opt, root)          # verified
    load_checkpoint(model, opt, os.path.join(root, "gen-1"))  # raw!
    recs = restore_records(root)[n0:]
    assert [r["verified"] for r in recs] == [True, False]

    def hook():
        return recs

    ctx = AnalysisContext(name="trainer/plan0", meta={"restores": hook})
    fired = run_rules(ctx, only=["unverified-restore"])
    assert len(fired) == 1 and fired[0].rule == "unverified-restore"
    assert fired[0].severity == "error"
    assert "digest check" in fired[0].message
    assert "load_latest_generation" in fired[0].hint
    # the escape hatch: a deliberate raw load says so
    load_checkpoint(model, opt, os.path.join(root, "gen-1"),
                    verify_exempt=True)
    recs = restore_records(root)[n0:]
    assert len(run_rules(AnalysisContext(name="t", meta={
        "restores": lambda: recs}), only=["unverified-restore"])) == 1
    recs2 = [r for r in recs if r["verified"] or r["verify_exempt"]]
    assert run_rules(AnalysisContext(name="t", meta={
        "restores": lambda: recs2}), only=["unverified-restore"]) == []
    # a raising hook loses the audit -> error finding
    def broken():
        raise RuntimeError("boom")
    fired = run_rules(AnalysisContext(name="t",
                                      meta={"restores": broken}),
                      only=["unverified-restore"])
    assert len(fired) == 1 and "audit" in fired[0].message
    # executables without the meta key are out of scope
    assert run_rules(AnalysisContext(name="t", meta={}),
                     only=["unverified-restore"]) == []
    close()


# ---------------------------------------------------------------------------
# the trainer policy ladder + the ISSUE 14 acceptance drive
# ---------------------------------------------------------------------------


def test_trainer_ladder_skip_rewind_fallback_bitwise(devices8, tmp_path):
    """Numeric + durability chaos (no process death): grad_nan is
    skipped, shard_corrupt poisons the newest generation, the
    loss_spike rewind falls back PAST it, re-run steps replay their
    pinned data cursors — and the whole committed loss curve plus the
    final params are bit-for-bit the fault-free run over the same
    clean-batch sequence."""
    tracer = SpanTracer()
    install_tracer(tracer)
    try:
        tr = FaultTolerantTrainer(
            _flat_build_fn, devices8,
            checkpoint_dir=str(tmp_path / "ck"),
            checkpoint_every=2, keep_checkpoints=3, rewind_after=2)
        plan = FaultPlan(events=[
            FaultEvent(step=2, kind="grad_nan", target=0),
            FaultEvent(step=6, kind="shard_corrupt", target=0),
            FaultEvent(step=6, kind="loss_spike", target=0),
        ])
        losses = tr.train(8, fault_plan=plan)
    finally:
        install_tracer(None)
    ms = tr.metrics_summary()
    assert ms["sentry_anomalies"] == 2        # grad_nan + loss_spike
    assert ms["steps_skipped"] == 2
    assert ms["rewinds"] == 1
    assert ms["restore_fallbacks"] == 1, \
        "restore did not fall back past the corrupted generation"
    assert tr.recoveries[0]["kind"] == "numeric_rewind"
    assert tr.recoveries[0]["reason"] == "loss_spike"
    assert tr.recoveries[0]["resumed_from_step"] == 4   # gen-6 corrupt
    assert tr.recoveries[0].get("mttr_s", 0) > 0
    # honesty: one compiled plan, one executable call per attempt, one
    # verdict host-read per attempt (rides the loss fetch)
    assert len(tr.build.graph._plan_pool) == 1
    assert tr.build.optimizer.sentry.host_reads == tr.attempts
    # every sentry decision is a chaos-track instant
    names = [e.name for e in tracer.events()]
    for ev in ("fault", "sentry_skip", "sentry_rewind",
               "restore_fallback", "recovered"):
        assert ev in names, f"missing {ev} instant"
    chaos_tracks = {e.track for e in tracer.events()
                    if e.name in ("sentry_skip", "sentry_rewind",
                                  "restore_fallback")}
    assert chaos_tracks == {"chaos"}
    # the rule wiring: the trainer's registered plan exposes verified
    # restore records and the rule stays silent
    from hetu_tpu.analysis import AnalysisContext, run_rules
    handles = tr.build.graph.analysis_handles()
    assert handles and "restores" in handles[0].meta
    recs = handles[0].meta["restores"]()
    assert recs and all(r["verified"] for r in recs), "gate is vacuous"
    assert run_rules(AnalysisContext(name=handles[0].name,
                                     meta=handles[0].meta),
                     only=["unverified-restore"]) == []
    cursors = tr.committed_cursors()
    p_chaos = _params(tr.build.model)
    tr.close()

    # the fault-free reference: same program, the committed clean-batch
    # sequence — bit-for-bit, not allclose
    ref = _flat_build_fn(8, devices8)
    ref_losses = [ref.step_fn(c) for c in cursors]
    assert ref_losses == losses, "committed losses are not bitwise"
    assert _bitwise_equal(p_chaos, _params(ref.model)), \
        "chaos run's params diverged from the fault-free run"
    ref.close()


def test_acceptance_mixed_numeric_and_process_faults(devices8,
                                                     tmp_path):
    """The ISSUE 14 acceptance drive: grad_nan x2, loss_spike x1,
    shard_corrupt on the newest generation, one worker death — zero
    steps lost, the pre-death curve bit-for-bit the fault-free run's,
    the post-death (dp8 -> dp4) continuation exact to the flat-state
    contract, restore falls back past the corrupted generation."""
    mon = WorkerMonitor(4, devices8, ttl=0.3, heartbeat_interval=0.05)
    tr = FaultTolerantTrainer(
        _flat_build_fn, devices8, monitor=mon,
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every=2, keep_checkpoints=3, rewind_after=2)
    plan = FaultPlan(events=[
        FaultEvent(step=2, kind="grad_nan", target=0),
        FaultEvent(step=3, kind="grad_nan", target=1),
        FaultEvent(step=6, kind="shard_corrupt", target=0),
        FaultEvent(step=6, kind="loss_spike", target=0),
        FaultEvent(step=8, kind="worker_death", target=3),
    ])
    STEPS = 10
    losses = tr.train(STEPS, fault_plan=plan)
    mon.close()
    assert len(losses) == STEPS and all(np.isfinite(losses)), \
        "steps were lost"
    ms = tr.metrics_summary()
    assert ms["sentry_anomalies"] == 3
    assert ms["rewinds"] == 1 and ms["restore_fallbacks"] == 1
    assert ms["worker_recoveries"] == 1
    death = tr.recoveries[-1]
    assert death["kind"] == "worker_death" and death["dp"] == 4
    assert death["devices"] == 6
    cursors = tr.committed_cursors()
    assert len(cursors) == STEPS
    tr.close()

    ref = _flat_build_fn(8, devices8)
    ref_losses = [ref.step_fn(c) for c in cursors]
    ref.close()
    # pre-death steps (0..7): bit-for-bit; the dp8->dp4 tail continues
    # to the flat-state cross-dp contract (PR 12's loss_curve gate)
    assert losses[:8] == ref_losses[:8], \
        "pre-death curve is not bitwise the fault-free run's"
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-6)


def test_emergency_flush_shrinks_rewind_to_zero(devices8, tmp_path):
    """The fault plane's emergency-flush hook: on a death verdict the
    trainer flushes the survivor-visible state as an emergency
    generation BEFORE re-planning, so recovery resumes from the detect
    step instead of rewinding to the last periodic snapshot.  The
    flush is a normal generation: digest-verified on restore."""
    mon = WorkerMonitor(4, devices8, ttl=0.3, heartbeat_interval=0.05)
    tr = FaultTolerantTrainer(
        _flat_build_fn, devices8, monitor=mon,
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every=4, emergency_flush=True)
    plan = FaultPlan(events=[FaultEvent(step=3, kind="worker_death",
                                        target=2)])
    losses = tr.train(6, fault_plan=plan)
    mon.close()
    ms = tr.metrics_summary()
    assert ms["emergency_flushes"] == 1
    rec = tr.recoveries[0]
    assert rec["resumed_from_step"] == 3        # not the step-0 snapshot
    assert rec["rewound_steps"] == 0
    cursors = tr.committed_cursors()
    tr.close()
    ref = _flat_build_fn(8, devices8)
    ref_losses = [ref.step_fn(c) for c in cursors]
    ref.close()
    assert losses[:3] == ref_losses[:3]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-6)
