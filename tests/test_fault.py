"""Fault plane (ISSUE 13): deterministic chaos injection + fenced
retry/backoff recovery across the serving cluster and elastic trainer.

The invariant under EVERY seeded FaultPlan (crash, zombie, transport
drop/dup/delay, straggler, randomized fuzz): zero requests lost, zero
duplicated tokens, temp-0 outputs of surviving requests bit-for-bit
equal to the fault-free run.  Plus: a revived TTL-expired replica stays
quarantined until explicit re-admission (the revival race), backoff
retries replace the bare handoff spin loops, the whole-fleet
backpressure path sheds with a retriable rejection instead of growing
the backlog without bound, and an injected worker death in the elastic
trainer re-plans on the survivors and continues the exact checkpointed
loss curve.
"""
import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.fault import (ChaosController, FaultEvent, FaultPlan,
                            RetryPolicy, check_cluster_invariants)
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.models.generate import generate
from hetu_tpu.obs.tracer import SpanTracer
from hetu_tpu.serving import EngineCluster

CFG_KW = dict(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
              max_seq_len=64, sp=False, dropout=0.0)
SHAPE_KW = dict(page_size=8, max_batch=4, chunk_size=8, prefill_rows=1,
                max_model_len=56)


@pytest.fixture(scope="module")
def model_state():
    cfg = GPTConfig(**CFG_KW)
    ht.set_seed(3)
    with ht.graph("eager", create_new=True):
        model = GPTLMHeadModel(cfg)
        model.logits(np.zeros((1, 4), np.int32))
        state = {k: np.asarray(v) for k, v in model.state_dict().items()}
    return state, cfg


@pytest.fixture(scope="module")
def shared_fn():
    from hetu_tpu.serving.decode import build_unified_step_fn
    cfg = GPTConfig(**CFG_KW)
    return build_unified_step_fn(
        cfg, SHAPE_KW["max_batch"], SHAPE_KW["chunk_size"],
        SHAPE_KW["prefill_rows"],
        -(-SHAPE_KW["max_model_len"] // SHAPE_KW["page_size"]),
        SHAPE_KW["page_size"], use_kernel=False)


def _make_cluster(state, cfg, fn=None, **kw):
    clock = [0.0]
    kw.setdefault("time_fn", lambda: clock[0])
    kw.setdefault("num_pages", 12)
    for k, v in SHAPE_KW.items():
        kw.setdefault(k, v)
    kw.setdefault("debug", True)
    kw.setdefault("ttl", 3600.0)
    kw.setdefault("coordinator", False)
    cl = EngineCluster(state, cfg, step_fn=fn, **kw)
    cl._test_clock = clock
    return cl


def _drain(cl, limit=800, invariants=False):
    n = 0
    while cl.has_work:
        cl.step()
        if invariants:
            check_cluster_invariants(cl)
        cl._test_clock[0] += 1.0
        n += 1
        assert n < limit, "cluster did not drain"
    return n


def _trace(rng, n, vocab=97, lo=8, hi=20):
    return [rng.randint(1, vocab, size=rng.randint(lo, hi)).tolist()
            for _ in range(n)]


def _fault_free(state, cfg, fn, prompts, new, name, **kw):
    """The reference outputs every chaos run must reproduce."""
    cl = _make_cluster(state, cfg, fn, name=name, **kw)
    for i, p in enumerate(prompts):
        cl.add_request(p, new, arrival_time=float(i))
    _drain(cl)
    out = {rid: list(c.out_tokens) for rid, c in cl.finished.items()}
    cl.close()
    return out


# ---------------------------------------------------------------------------
# policy / plan units
# ---------------------------------------------------------------------------


def test_retry_policy_caps_and_is_deterministic():
    p = RetryPolicy(base=0.5, cap=4.0, jitter=0.25, deadline=10.0)
    d = [p.delay(a, key=7) for a in range(10)]
    # deterministic: a second evaluation is identical
    assert d == [p.delay(a, key=7) for a in range(10)]
    # capped: never above cap * (1 + jitter), grows from base scale
    assert max(d) <= 4.0 * 1.25 + 1e-9
    assert d[0] <= 0.5 * 1.25 + 1e-9
    assert d[5] > d[0]
    # jitter is keyed: a different request sees different jitter
    assert [p.delay(a, key=8) for a in range(10)] != d
    # deadlines
    assert p.deadline_for(2.0) == 12.0
    assert not p.expired(2.0, 11.0) and p.expired(2.0, 12.5)
    assert RetryPolicy(deadline=None).deadline_for(2.0) is None


def test_fault_plan_random_is_survivable_and_deterministic():
    for seed in range(6):
        plan = FaultPlan.random(seed, num_replicas=3, steps=50,
                                n_events=80)
        alive = {0, 1, 2}
        for ev in plan.events:
            if ev.kind in ("crash", "zombie"):
                alive.discard(ev.target)
            elif ev.kind == "readmit":
                alive.add(ev.target)
            assert alive, f"plan {seed} killed every replica"
    a = FaultPlan.random(3, 3, 50, n_events=40)
    b = FaultPlan.random(3, 3, 50, n_events=40)
    assert a.events == b.events and a.transport == b.transport
    assert FaultPlan.random(4, 3, 50, n_events=40).events != a.events


def test_fault_plan_rejects_unknown_kinds():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultEvent(0, "meteor", 0)
    with pytest.raises(ValueError, match="unknown transport verdict"):
        FaultPlan(transport={0: ("teleport", 0.0)})


# ---------------------------------------------------------------------------
# crash / zombie / revival race
# ---------------------------------------------------------------------------


def test_chaos_crash_bitforbit_and_trace(model_state, shared_fn):
    """A scheduled crash: the dead replica's work re-routes, outputs
    stay bit-for-bit the fault-free run's, and the tracer shows the
    full fail -> detect -> recover chain."""
    state, cfg = model_state
    rng = np.random.RandomState(0)
    prompts = _trace(rng, 6)
    NEW = 8
    want = _fault_free(state, cfg, shared_fn, prompts, NEW, "f_ref")

    plan = FaultPlan(events=[FaultEvent(step=3, kind="crash", target=1)])
    tracer = SpanTracer()
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=2,
                       name="f_crash", policy="load",
                       chaos=ChaosController(plan), tracer=tracer)
    reqs = [cl.add_request(p, NEW, arrival_time=float(i))
            for i, p in enumerate(prompts)]
    _drain(cl, invariants=True)
    assert set(cl.finished) == {r.req_id for r in reqs}   # nothing lost
    for r in reqs:
        assert r.out_tokens == want[r.req_id]
    ms = cl.metrics_summary()
    assert ms["replica_deaths"] == 1
    assert ms["requests_rerouted"] >= 1
    names = [e.name for e in tracer.events()]
    for evname in ("fault", "replica_dead", "reroute"):
        assert evname in names, f"missing {evname} instant"
    # fail -> detect -> recover ordering on the merged timeline
    assert names.index("fault") < names.index("replica_dead") \
        < names.index("reroute")
    cl.close()


def test_chaos_zombie_fenced_no_duplicate_tokens(model_state, shared_fn):
    """The zombie: heartbeats stall, the engine keeps stepping.  The
    cluster fences it — its late completions are dropped, its stream
    tokens ignored — and every request finishes exactly once with
    fault-free outputs."""
    state, cfg = model_state
    rng = np.random.RandomState(1)
    prompts = _trace(rng, 6)
    NEW = 8
    want = _fault_free(state, cfg, shared_fn, prompts, NEW, "f_zref")

    plan = FaultPlan(events=[FaultEvent(step=4, kind="zombie",
                                        target=1)])
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=2,
                       name="f_zombie", policy="load",
                       chaos=ChaosController(plan))
    reqs = [cl.add_request(p, NEW, arrival_time=float(i))
            for i, p in enumerate(prompts)]
    _drain(cl, invariants=True)
    z = cl.replicas[1]
    assert z.serving and not z.alive, "zombie state lost"
    assert set(cl.finished) == {r.req_id for r in reqs}
    for r in reqs:
        assert r.out_tokens == want[r.req_id], \
            "zombie double-delivery corrupted a request"
        assert len(r.out_tokens) == NEW            # no duplicated token
    # the zombie really kept finishing work that had to be dropped
    assert cl.metrics_summary()["stale_completions_dropped"] > 0
    cl.close()


def test_revived_replica_stays_quarantined_until_readmit(model_state,
                                                         shared_fn):
    """The revival race: a TTL-expired replica that resumes
    heartbeating must NOT re-enter the candidate set by itself; after
    explicit re-admission it serves again under the new fence epoch."""
    state, cfg = model_state
    plan = FaultPlan(events=[FaultEvent(step=2, kind="zombie", target=1),
                             FaultEvent(step=6, kind="revive", target=1)])
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=2,
                       name="f_revive", policy="load",
                       chaos=ChaosController(plan))
    rng = np.random.RandomState(2)
    prompts = _trace(rng, 5)
    reqs = [cl.add_request(p, 6, arrival_time=float(i))
            for i, p in enumerate(prompts)]
    _drain(cl, invariants=True)
    assert not cl.replicas[1].alive, \
        "revived replica re-admitted itself (revival race)"
    assert set(cl.finished) == {r.req_id for r in reqs}
    fence_at_death = cl._fence[1]
    # explicit re-admission: stale state aborted, replica serves again
    cl.readmit_replica(1)
    assert cl.replicas[1].alive
    assert not cl.replicas[1].engine.has_work, "stale work survived"
    assert cl.metrics_summary()["readmits"] == 1
    late = cl.add_request([4, 5, 6, 7], 4,
                          arrival_time=cl._test_clock[0])
    # force it onto the readmitted replica by loading r0's queue
    _drain(cl, invariants=True)
    assert late.out_tokens == \
        _solo(state, cfg, late.prompt, 4)
    assert cl._fence[1] == fence_at_death   # epoch advances on death only
    cl.close()


def _solo(state, cfg, prompt, n_new):
    return np.asarray(generate(state, cfg,
                               np.asarray([prompt], np.int32), n_new,
                               temperature=0.0))[0, len(prompt):].tolist()


# ---------------------------------------------------------------------------
# transport chaos (disaggregated handoffs)
# ---------------------------------------------------------------------------


def _disagg(state, cfg, fn, name, plan=None, n=3, **kw):
    chaos = ChaosController(plan) if plan is not None else None
    return _make_cluster(state, cfg, fn, num_replicas=n,
                         mode="disaggregated", num_prefill=1,
                         name=name, chaos=chaos, **kw)


def test_transport_drop_retries_with_backoff(model_state, shared_fn):
    state, cfg = model_state
    rng = np.random.RandomState(3)
    prompts = _trace(rng, 5)
    NEW = 8
    want = _fault_free(state, cfg, shared_fn, prompts, NEW, "f_dref",
                       num_replicas=3, mode="disaggregated",
                       num_prefill=1)
    # drop the first two injection attempts outright
    plan = FaultPlan(transport={0: ("drop", 0.0), 1: ("drop", 0.0)})
    cl = _disagg(state, cfg, shared_fn, "f_drop", plan)
    reqs = [cl.add_request(p, NEW, arrival_time=float(i))
            for i, p in enumerate(prompts)]
    _drain(cl, invariants=True)
    ms = cl.metrics_summary()
    assert ms["handoff_retries"] >= 2          # the drops really hit
    assert set(cl.finished) == {r.req_id for r in reqs}
    for r in reqs:
        assert r.out_tokens == want[r.req_id]
    cl.close()


def test_transport_dup_deduped_by_request_epoch(model_state, shared_fn):
    """A delivery whose ack was lost gets re-sent; the (request id,
    staging epoch) dedup drops the duplicate — the request is adopted
    exactly once, tokens are not duplicated."""
    state, cfg = model_state
    rng = np.random.RandomState(4)
    prompts = _trace(rng, 5)
    NEW = 8
    want = _fault_free(state, cfg, shared_fn, prompts, NEW, "f_dupref",
                       num_replicas=3, mode="disaggregated",
                       num_prefill=1)
    plan = FaultPlan(transport={0: ("dup", 0.0), 2: ("dup", 0.0)})
    cl = _disagg(state, cfg, shared_fn, "f_dup", plan)
    reqs = [cl.add_request(p, NEW, arrival_time=float(i))
            for i, p in enumerate(prompts)]
    _drain(cl, invariants=True)
    ms = cl.metrics_summary()
    assert ms["duplicate_deliveries_dropped"] >= 2
    assert set(cl.finished) == {r.req_id for r in reqs}
    for r in reqs:
        assert r.out_tokens == want[r.req_id]
        assert len(r.out_tokens) == NEW
    cl.close()


def test_destination_death_restages_handoff(model_state, shared_fn):
    """A delayed (in-flight) handoff whose pinned destination dies
    mid-transfer is re-staged to a surviving decode replica; outputs
    stay exact.  (PR 11 only survived SOURCE death.)"""
    state, cfg = model_state
    rng = np.random.RandomState(5)
    prompts = _trace(rng, 4)
    NEW = 8
    want = _fault_free(state, cfg, shared_fn, prompts, NEW, "f_rsref",
                       num_replicas=3, mode="disaggregated",
                       num_prefill=1)
    # every early handoff floats on the wire for 3 clock units; the
    # first decode replica (the least-loaded pick) dies underneath
    plan = FaultPlan(
        events=[FaultEvent(step=3, kind="crash", target=1)],
        transport={i: ("delay", 3.0) for i in range(4)})
    cl = _disagg(state, cfg, shared_fn, "f_restage", plan)
    reqs = [cl.add_request(p, NEW, arrival_time=float(i))
            for i, p in enumerate(prompts)]
    _drain(cl, invariants=True)
    ms = cl.metrics_summary()
    assert ms["handoffs_restaged"] >= 1, \
        "no destination death was in flight; test is vacuous"
    assert set(cl.finished) == {r.req_id for r in reqs}
    for r in reqs:
        assert r.out_tokens == want[r.req_id]
    cl.close()


def test_decode_fleet_empty_degrades_to_monolithic(model_state,
                                                   shared_fn):
    """Every decode replica dead: staged handoffs degrade to local
    end-to-end serving on the survivors instead of trapping requests."""
    state, cfg = model_state
    rng = np.random.RandomState(6)
    prompts = _trace(rng, 3)
    NEW = 6
    want = _fault_free(state, cfg, shared_fn, prompts, NEW, "f_mref")
    plan = FaultPlan(events=[FaultEvent(step=2, kind="crash", target=1)])
    cl = _disagg(state, cfg, shared_fn, "f_mono", plan, n=2)
    reqs = [cl.add_request(p, NEW, arrival_time=float(i))
            for i, p in enumerate(prompts)]
    _drain(cl, invariants=True)
    assert set(cl.finished) == {r.req_id for r in reqs}
    for r in reqs:
        assert r.out_tokens == want[r.req_id]
    # the prefill replica really served end-to-end after the death
    assert cl.replicas[0].engine.metrics_summary()["tokens_generated"] \
        > len(prompts)
    cl.close()


# ---------------------------------------------------------------------------
# load shedding / bounded backlog
# ---------------------------------------------------------------------------


def test_load_shedding_past_deadline_is_retriable(model_state,
                                                  shared_fn):
    """Whole fleet backpressured past the deadline: the request is
    SHED with a retriable rejection (bounded wait), and a later
    resubmission completes normally."""
    state, cfg = model_state
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=1,
                       name="f_shed", max_queue_depth=1,
                       request_deadline=3.0)
    long = cl.add_request(list(range(1, 17)), 12, arrival_time=0.0)
    waiters = [cl.add_request([30 + i, 2, 3], 4, arrival_time=0.0)
               for i in range(3)]
    _drain(cl, invariants=True)
    assert long.req_id in cl.finished
    shed = [w for w in waiters if w.rejected]
    assert shed, "no request was shed under saturation past deadline"
    for w in shed:
        assert w.reject_reason == "backpressured_past_deadline"
        assert w.req_id in cl.shed and w.req_id not in cl.finished
    assert cl.metrics_summary()["requests_shed"] == len(shed)
    # nothing lost: every submission is accounted exactly once
    assert set(cl.finished) | set(cl.shed) == \
        {r.req_id for r in [long] + waiters}
    # the rejection is retriable: resubmit now that the fleet is idle
    retry = cl.add_request(shed[0].prompt, 4,
                           arrival_time=cl._test_clock[0])
    _drain(cl, invariants=True)
    assert retry.out_tokens == _solo(state, cfg, shed[0].prompt, 4)
    cl.close()


def test_bounded_backlog_sheds_at_front_door(model_state, shared_fn):
    state, cfg = model_state
    cl = _make_cluster(state, cfg, shared_fn, num_replicas=1,
                       name="f_bound", max_backlog=2)
    reqs = [cl.add_request([i + 1, 2, 3], 3, arrival_time=100.0)
            for i in range(5)]
    over = [r for r in reqs if r.rejected]
    assert len(over) == 3 and all(
        r.reject_reason == "backlog_full" for r in over)
    assert cl.metrics_summary()["requests_shed"] == 3
    cl._test_clock[0] = 100.0
    _drain(cl, invariants=True)
    assert set(cl.finished) == {r.req_id for r in reqs
                                if not r.rejected}
    cl.close()


# ---------------------------------------------------------------------------
# the seeded chaos fuzz (~300 events)
# ---------------------------------------------------------------------------


def test_chaos_fuzz_invariants_hold(model_state, shared_fn):
    """A randomized ~300-event FaultPlan over a disaggregated cluster:
    cluster invariants hold after EVERY step, nothing is lost, and all
    surviving (= all, no shedding configured) outputs are bit-for-bit
    the fault-free run's."""
    state, cfg = model_state
    rng = np.random.RandomState(9)
    prompts = _trace(rng, 10)
    NEW = 6
    want = _fault_free(state, cfg, shared_fn, prompts, NEW, "f_fzref",
                       num_replicas=3, mode="disaggregated",
                       num_prefill=1)
    plan = FaultPlan.random(seed=1234, num_replicas=3, steps=60,
                            n_events=300, protect=(0,))
    assert plan.n_events >= 200, plan.describe()
    cl = _disagg(state, cfg, shared_fn, "f_fuzz", plan)
    reqs = [cl.add_request(p, NEW, arrival_time=float(i))
            for i, p in enumerate(prompts)]
    _drain(cl, limit=1500, invariants=True)
    assert set(cl.finished) == {r.req_id for r in reqs}, "request lost"
    for r in reqs:
        assert r.out_tokens == want[r.req_id], \
            (r.req_id, plan.describe())
        assert len(r.out_tokens) == NEW
    # the plan actually exercised the machinery
    assert cl.chaos.injected, "no fault ever fired"
    cl.close()


# ---------------------------------------------------------------------------
# fast chaos smoke (tier-1 gate) + unfenced-handoff rule
# ---------------------------------------------------------------------------


@pytest.mark.lint_graph
def test_chaos_smoke_gate(model_state, shared_fn):
    """The tier-1 chaos gate: one crash + one drop + one dup over a
    small disaggregated trace — invariants after every step, nothing
    lost, outputs exact, and the merged trace carries fault / detect /
    recover instants for the injected events."""
    state, cfg = model_state
    rng = np.random.RandomState(12)
    prompts = _trace(rng, 4)
    NEW = 6
    want = _fault_free(state, cfg, shared_fn, prompts, NEW, "f_smref",
                       num_replicas=3, mode="disaggregated",
                       num_prefill=1)
    plan = FaultPlan(
        events=[FaultEvent(step=4, kind="crash", target=2)],
        transport={0: ("drop", 0.0), 1: ("dup", 0.0)})
    tracer = SpanTracer()
    cl = _disagg(state, cfg, shared_fn, "f_smoke", plan, tracer=tracer)
    reqs = [cl.add_request(p, NEW, arrival_time=float(i))
            for i, p in enumerate(prompts)]
    _drain(cl, invariants=True)
    assert set(cl.finished) == {r.req_id for r in reqs}
    for r in reqs:
        assert r.out_tokens == want[r.req_id]
    names = [e.name for e in tracer.events()]
    assert "fault" in names                       # injected
    assert "replica_dead" in names                # detected
    assert "handoff_retry" in names               # recovery: backoff
    assert "duplicate_dropped" in names           # recovery: dedup
    ms = cl.metrics_summary()
    assert ms["replica_deaths"] == 1
    assert ms["handoff_retries"] >= 1
    assert ms["duplicate_deliveries_dropped"] >= 1
    cl.close()


@pytest.mark.lint_graph
def test_unfenced_handoff_rule(model_state, shared_fn):
    """Repo-standard rule contract: silent on the real (fenced)
    transport — non-vacuously, records and adoptions present — fires
    exactly once per stripped fence token, and honors the
    fence_exempt exemption."""
    from hetu_tpu.analysis import AnalysisContext, run_rules
    from hetu_tpu.graph.graph import clear_executables, get_executable
    state, cfg = model_state
    cl = _disagg(state, cfg, shared_fn, "f_rule")
    rng = np.random.RandomState(13)
    for i in range(3):
        cl.add_request(rng.randint(1, 97, size=12).tolist(), 4,
                       arrival_time=float(i))
    _drain(cl)
    handle = get_executable("f_rule@r1/unified")
    records = handle.meta["kv_handoff"]()
    adoptions = handle.meta["adoptions"]()
    assert records and adoptions, "gate is vacuous"
    assert all(isinstance(r["epoch"], int) for r in records)
    ctx = AnalysisContext(name=handle.name, meta=handle.meta)
    assert run_rules(ctx, only=["unfenced-handoff"]) == []
    # strip one r1-bound record's fence token -> exactly one fire
    victim = next(i for i, r in enumerate(cl.transport.records)
                  if r["dst"] == 1)
    saved = cl.transport.records[victim].pop("epoch")
    fired = run_rules(AnalysisContext(name=handle.name,
                                      meta=handle.meta),
                      only=["unfenced-handoff"])
    assert len(fired) == 1 and fired[0].rule == "unfenced-handoff"
    assert "fence token" in fired[0].message
    assert fired[0].severity == "error"
    # exemption: the same record flagged as a local same-pool move
    cl.transport.records[victim]["fence_exempt"] = True
    assert run_rules(AnalysisContext(name=handle.name,
                                     meta=handle.meta),
                     only=["unfenced-handoff"]) == []
    del cl.transport.records[victim]["fence_exempt"]
    cl.transport.records[victim]["epoch"] = saved
    # an adoption without the token fires too
    avict = next(i for i, a in enumerate(cl._adoptions)
                 if a["dst"] == 1)
    cl._adoptions[avict] = {k: v for k, v in cl._adoptions[avict].items()
                            if k != "epoch"}
    fired = run_rules(AnalysisContext(name=handle.name,
                                      meta=handle.meta),
                      only=["unfenced-handoff"])
    assert len(fired) == 1 and "adoption" in fired[0].message
    # executables with neither meta key are out of scope
    pre = get_executable("f_rule@r0/unified")
    assert run_rules(AnalysisContext(name=pre.name, meta=pre.meta),
                     only=["unfenced-handoff"]) == []
    cl.close()
    clear_executables("f_rule@")


# ---------------------------------------------------------------------------
# elastic trainer: injected worker death -> re-plan -> exact loss curve
# ---------------------------------------------------------------------------


def _gpt_build_fn(dp, devices):
    from jax.sharding import PartitionSpec as P

    from hetu_tpu.elastic import TrainBuild
    from hetu_tpu.graph import ctor
    from hetu_tpu.models import GPTLMHeadModel, llama_config
    from hetu_tpu.parallel import create_mesh
    ctor._seed_counter[0] = 777          # identical init on any layout
    mesh = create_mesh({"dp": dp}, devices[:dp])
    cfg = llama_config(vocab_size=64, hidden_size=32, num_layers=1,
                       num_heads=4, max_seq_len=16, sp=False)
    gctx = ht.graph("define_and_run", create_new=True, mesh=mesh)
    g = gctx.__enter__()
    ids = ht.parallel_placeholder("int32", (8, 16), pspec=P("dp", None),
                                  name="ids")
    labels = ht.parallel_placeholder("int32", (8, 16),
                                     pspec=P("dp", None), name="labels")
    model = GPTLMHeadModel(cfg)
    loss = model(ids, labels)
    opt = ht.optim.AdamOptimizer(lr=1e-2, zero=2, grad_comm="fp32",
                                 flat_state=True)
    train_op = opt.minimize(loss)
    rng = np.random.RandomState(0)
    IDS = rng.randint(0, 64, (8, 16)).astype(np.int32)
    feed = {ids: IDS, labels: np.roll(IDS, -1, axis=1)}

    def step_fn(step):
        out = g.run(loss, [loss, train_op], feed)
        return float(np.asarray(out[0]))

    return TrainBuild(graph=g, model=model, optimizer=opt,
                      step_fn=step_fn,
                      close=lambda: gctx.__exit__(None, None, None))


def test_trainer_death_recovery_continues_loss_curve(devices8,
                                                     tmp_path):
    """The end-to-end drive of the dp8->dp4 checkpoint round-trip: a
    worker death injected mid-run is detected through the coordinator,
    the trainer re-plans on the survivors (dp 8 -> 4), restores the
    flat-state snapshot, and the final loss curve equals the
    fault-free run's exactly."""
    from hetu_tpu.elastic import FaultTolerantTrainer, WorkerMonitor
    STEPS = 8
    ref_build = _gpt_build_fn(8, devices8)
    ref = [ref_build.step_fn(i) for i in range(STEPS)]
    ref_build.close()

    mon = WorkerMonitor(4, devices8, ttl=0.3, heartbeat_interval=0.05)
    tr = FaultTolerantTrainer(_gpt_build_fn, devices8, monitor=mon,
                              checkpoint_dir=str(tmp_path / "ck"),
                              checkpoint_every=2)
    plan = FaultPlan(events=[FaultEvent(step=5, kind="worker_death",
                                        target=3)])
    losses = tr.train(STEPS, fault_plan=plan)
    mon.close()
    tr.close()
    np.testing.assert_allclose(losses, ref, rtol=1e-6)
    assert len(tr.recoveries) == 1
    rec = tr.recoveries[0]
    assert rec["dead"] == [3] and rec["dp"] == 4
    assert rec["devices"] == 6
    assert rec["resumed_from_step"] == 4      # the step-4 snapshot
    assert rec.get("mttr_s", 0) > 0


@pytest.mark.slow
def test_mpmd_trainer_chaos_straggler_seam(devices8):
    """The mpmd trainer's chaos seam: a FaultPlan straggler event slows
    a device mid-run; the retune re-plans around it (the injected
    ratios reach the solver) and training completes."""
    from hetu_tpu.elastic.mpmd_trainer import ElasticMPMDTrainer
    from hetu_tpu.elastic.strategy import StrategyModel
    from hetu_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_heads=4, max_seq_len=16, sp=False, dropout=0.0)
    solver = StrategyModel(num_devices=4, num_layers=4,
                           num_micro_batches=2,
                           tp_candidates=[1], pp_candidates=[2])
    rng = np.random.RandomState(0)

    def provider(step):
        ids = rng.randint(0, 64, (8, 16)).astype(np.int32)
        return ids, np.roll(ids, -1, axis=1)

    trainer = ElasticMPMDTrainer(cfg, solver, provider,
                                 devices=devices8[:4],
                                 switch_threshold=0.01)
    plan = FaultPlan(events=[FaultEvent(step=2, kind="straggler",
                                        target=0, ratio=4.0)])
    tracer = SpanTracer()
    from hetu_tpu.obs.tracer import install_tracer
    install_tracer(tracer)
    try:
        losses = trainer.run(6, retune_every=2, fault_plan=plan)
    finally:
        install_tracer(None)
    assert len(losses) == 6
    assert all(np.isfinite(losses))
    names = [e.name for e in tracer.events()]
    assert "fault" in names, "straggler injection left no trace"
    # the injected straggler changed the layout (a 4x-slow device on a
    # 2-stage pipeline forces an asymmetric split or mb shift)
    assert trainer.history, "retune never re-planned around the fault"
