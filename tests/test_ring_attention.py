"""Ring-attention (CP) tests on the virtual mesh.

Oracle: single-device reference SDPA.  Mirrors the reference's CP
correctness expectations (AttnCommRing, ops/ParallelAttention.h:342):
ring output == dense attention, fwd and bwd, and the full GPT model under
dp x cp x tp matches its single-device trajectory.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import optim
from hetu_tpu.graph import ctor
from hetu_tpu.models import GPTLMHeadModel, llama_config
from hetu_tpu.ops.attention import sdpa_reference
from hetu_tpu.parallel.ring_attention import ring_attention_sharded


def _mk(b=2, s=256, h=2, d=64, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
                 for _ in range(3))


class TestRingProfiling:
    @pytest.mark.slow
    def test_breakdown_rows_and_metrics(self, devices8):
        """Per-round comm/attn/corr/grad decomposition (reference
        ParallelAttention.h:411-413 event profiling) produces one row per
        ring round and records the CP table through utils.metrics."""
        from hetu_tpu.parallel.ring_attention import profile_ring_breakdown
        from hetu_tpu.utils.metrics import Metrics
        mesh = ht.create_mesh({"cp": 4}, devices8[:4])
        q, k, v = _mk(s=128)
        rec = Metrics()
        rows = profile_ring_breakdown(q, k, v, mesh, causal=True,
                                      split_pattern="sym", reps=1,
                                      metrics=rec)
        assert len(rows) == 4
        for r, row in enumerate(rows):
            assert row["round"] == r
            for key in ("comm_s", "attn_s", "corr_s", "grad_s"):
                assert row[key] > 0.0
        assert len(rec.series("ring_attn_s")) == 4
        assert len(rec.series("ring_grad_s")) == 4

    @pytest.mark.slow
    def test_env_gated_hook_fires_once_per_shape(self, devices8,
                                                 monkeypatch, tmp_path):
        import importlib
        # the package re-exports the ring_attention FUNCTION under the
        # same name, so ``import ... as ra`` grabs the function
        ra = importlib.import_module("hetu_tpu.parallel.ring_attention")
        monkeypatch.setenv("HETU_TPU_RING_PROFILE", "1")
        monkeypatch.setenv("HETU_TPU_RING_PROFILE_BWD", "0")
        jsonl = tmp_path / "ring.jsonl"
        monkeypatch.setenv("HETU_TPU_RING_PROFILE_FILE", str(jsonl))
        ra._RING_PROFILED.clear()
        mesh = ht.create_mesh({"cp": 4}, devices8[:4])
        q, k, v = _mk(s=128)
        ring_attention_sharded(q, k, v, mesh, batch_axis=None,
                               head_axis=None)
        assert len(ra._RING_PROFILED) == 1
        lines = [l for l in jsonl.read_text().splitlines() if l.strip()]
        assert len(lines) == 4                   # one record per round
        # second call, same shape: no re-profile (and no duplicate rows)
        ring_attention_sharded(q, k, v, mesh, batch_axis=None,
                               head_axis=None)
        assert len(ra._RING_PROFILED) == 1


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_fwd_matches_dense(self, causal, devices8):
        mesh = ht.create_mesh({"cp": 4}, devices8[:4])
        q, k, v = _mk()
        out = ring_attention_sharded(q, k, v, mesh, causal=causal,
                                     batch_axis=None, head_axis=None)
        ref = sdpa_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.slow
    def test_bwd_matches_dense(self, devices8):
        mesh = ht.create_mesh({"cp": 4}, devices8[:4])
        q, k, v = _mk()

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention_sharded(
                q, k, v, mesh, causal=True, batch_axis=None,
                head_axis=None) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(sdpa_reference(q, k, v, causal=True) ** 2)

        g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3,
                                       err_msg=f"d{name}")

    def test_with_dp_and_tp_axes(self, devices8):
        """CP combined with batch + head sharding (reference TP head split
        + CP, ParallelAttention.cc:940)."""
        mesh = ht.create_mesh({"dp": 2, "cp": 2, "tp": 2}, devices8)
        q, k, v = _mk(b=2, s=128, h=2)
        out = ring_attention_sharded(q, k, v, mesh, causal=True)
        ref = sdpa_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.slow
class TestGPTWithCP:
    def test_gpt_cp_matches_single_device(self, devices8):
        def train(mesh_shape, cp_axis=None, steps=3):
            ctor._seed_counter[0] = 777
            mesh = ht.create_mesh(mesh_shape) if mesh_shape else None
            cfg = llama_config(vocab_size=64, hidden_size=32, num_layers=2,
                               num_heads=4, max_seq_len=32, sp=False,
                               cp_axis=cp_axis)
            with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
                ids = ht.parallel_placeholder(
                    "int32", (4, 32),
                    pspec=P("dp", None) if mesh else None, name="ids")
                lbl = ht.parallel_placeholder(
                    "int32", (4, 32),
                    pspec=P("dp", None) if mesh else None, name="lbl")
                m = GPTLMHeadModel(cfg)
                loss = m(ids, lbl)
                op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
                rng = np.random.RandomState(0)
                I = rng.randint(0, 64, (4, 32)).astype(np.int32)
                L = np.roll(I, -1, 1)
                return [float(np.asarray(
                    g.run(loss, [loss, op], {ids: I, lbl: L})[0]))
                    for _ in range(steps)]

        base = train(None)
        cp = train({"dp": 2, "cp": 2, "tp": 2}, cp_axis="cp")
        np.testing.assert_allclose(base, cp, rtol=3e-3, atol=1e-4)


class TestRingRegressions:
    def test_bfloat16_ring(self, devices8):
        """lax.switch branch dtypes must agree for bf16 inputs."""
        mesh = ht.create_mesh({"cp": 4}, devices8[:4])
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(2, 128, 2, 64), jnp.bfloat16)
                   for _ in range(3))
        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis=None, head_axis=None)
        ref = sdpa_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=5e-2, atol=5e-2)

    def test_parallel_attention_requires_cp_axis(self):
        import pytest as _pytest
        from hetu_tpu import ops as _ops
        mesh = ht.create_mesh({"dp": 4})
        with ht.graph("define_and_run", create_new=True, mesh=mesh):
            x = ht.placeholder("float32", (2, 8, 2, 4), name="q")
            with _pytest.raises(ValueError, match="parallel_attention"):
                _ops.parallel_attention(x, x, x)


@pytest.mark.slow
class TestSymSplitPattern:
    """SYM causal load balancing (reference SplitPattern::SYM,
    ParallelAttention.h:19, .cc:140-200)."""

    def test_sym_fwd_matches_dense(self, devices8):
        from hetu_tpu.parallel.ring_attention import pair_score_area
        mesh = ht.create_mesh({"cp": 4}, devices8[:4])
        q, k, v = _mk()
        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis=None, head_axis=None,
                                     split_pattern="sym")
        ref = sdpa_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_sym_bwd_matches_dense(self, devices8):
        mesh = ht.create_mesh({"cp": 4}, devices8[:4])
        q, k, v = _mk(s=128)

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention_sharded(
                q, k, v, mesh, causal=True, batch_axis=None,
                head_axis=None, split_pattern="sym") ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(sdpa_reference(q, k, v, causal=True) ** 2)

        g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3,
                                       err_msg=f"d{name}")

    def test_sym_balances_per_round_work(self):
        """Per-(rank, round) score area: NORMAL causal is cp x imbalanced,
        SYM is exactly uniform (the point of the pattern)."""
        from hetu_tpu.parallel.ring_attention import pair_score_area
        for cp in (2, 4, 8):
            normal = pair_score_area(cp, "normal").sum(axis=1)
            sym = pair_score_area(cp, "sym").sum(axis=1)
            assert normal.max() / normal.min() >= 2 * cp - 1
            np.testing.assert_allclose(sym, sym[0])
            # same total work overall
            np.testing.assert_allclose(normal.sum(), sym.sum())

    def test_sym_roundtrip_indices(self):
        from hetu_tpu.parallel.ring_attention import sym_shard, sym_unshard
        x = jnp.arange(2 * 32 * 3).reshape(2, 32, 3).astype(jnp.float32)
        y = sym_unshard(sym_shard(x, 4), 4)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
class TestVarlenRing:
    """Per-rank variable seq lens (_seq_len_list) + packed segments in
    the ring (reference ParallelAttention.cc:1061 varlen path)."""

    def test_unequal_per_rank_lengths_match_oracle(self, devices8):
        cp, s_local = 4, 64
        mesh = ht.create_mesh({"cp": cp}, devices8[:4])
        q, k, v = _mk(s=cp * s_local)
        lens = np.array([64, 32, 48, 16], np.int32)  # valid per rank

        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis=None, head_axis=None,
                                     seq_lens=lens)
        # oracle: same padding expressed as segment ids (-1 -> unique neg)
        pos = np.arange(cp * s_local)
        valid = (pos % s_local) < lens[pos // s_local]
        segs = np.where(valid, 0, -1 - pos).astype(np.int32)  # pads unique
        segs = np.broadcast_to(segs, (q.shape[0], cp * s_local))
        ref = sdpa_reference(q, k, v, causal=True,
                             segment_ids=jnp.asarray(segs))
        ov = np.asarray(out)[:, valid]
        rv = np.asarray(ref)[:, valid]
        np.testing.assert_allclose(ov, rv, rtol=1e-4, atol=1e-4)

    def test_unequal_lengths_bwd(self, devices8):
        cp, s_local = 4, 32
        mesh = ht.create_mesh({"cp": cp}, devices8[:4])
        q, k, v = _mk(s=cp * s_local)
        lens = np.array([32, 16, 24, 8], np.int32)
        pos = np.arange(cp * s_local)
        valid = (pos % s_local) < lens[pos // s_local]
        segs = np.where(valid, 0, -1 - pos).astype(np.int32)
        segs = np.broadcast_to(segs, (q.shape[0], cp * s_local))
        vm = jnp.asarray(valid[None, :, None, None], jnp.float32)

        def loss_ring(q, k, v):
            o = ring_attention_sharded(q, k, v, mesh, causal=True,
                                       batch_axis=None, head_axis=None,
                                       seq_lens=lens)
            return jnp.sum((o * vm) ** 2)

        def loss_ref(q, k, v):
            o = sdpa_reference(q, k, v, causal=True,
                               segment_ids=jnp.asarray(segs))
            return jnp.sum((o * vm) ** 2)

        g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g1, g2):
            av = np.asarray(a)[:, valid]
            bv = np.asarray(b)[:, valid]
            np.testing.assert_allclose(av, bv, rtol=1e-3, atol=1e-3,
                                       err_msg=f"d{name}")

    def test_packed_segments_cross_rank(self, devices8):
        """Docs packed across rank boundaries: same doc attends causally
        across ranks, different docs never attend."""
        cp, s_local = 4, 32
        s = cp * s_local
        mesh = ht.create_mesh({"cp": cp}, devices8[:4])
        q, k, v = _mk(s=s)
        # three docs: [0, 100) / [100, 180) / [180, 256) — boundaries NOT
        # on rank boundaries
        doc = np.zeros(s, np.int32)
        doc[100:180] = 1
        doc[180:] = 2
        segs = np.broadcast_to(doc, (q.shape[0], s)).copy()

        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis=None, head_axis=None,
                                     segment_ids=jnp.asarray(segs))
        ref = sdpa_reference(q, k, v, causal=True,
                             segment_ids=jnp.asarray(segs))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_sym_packed_segments_match_oracle(self, devices8):
        """SYM + packed docs (reference supports _seq_len_list/varlen
        under SplitPattern::SYM, ParallelAttention.h:342): the segment
        mask is order-independent so it composes with the SYM classes."""
        cp, s = 4, 256
        mesh = ht.create_mesh({"cp": cp}, devices8[:4])
        q, k, v = _mk(s=s)
        doc = np.zeros(s, np.int32)
        doc[100:180] = 1
        doc[180:] = 2
        segs = np.broadcast_to(doc, (q.shape[0], s)).copy()

        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis=None, head_axis=None,
                                     split_pattern="sym",
                                     segment_ids=jnp.asarray(segs))
        ref = sdpa_reference(q, k, v, causal=True,
                             segment_ids=jnp.asarray(segs))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_sym_unequal_per_rank_lengths_match_oracle(self, devices8):
        """SYM + per-rank _seq_len_list: rank-local tail positions (in
        the SYM head+tail chunk layout) are padding."""
        from hetu_tpu.parallel.ring_attention import sym_indices
        cp, s_local = 4, 64
        s = cp * s_local
        mesh = ht.create_mesh({"cp": cp}, devices8[:4])
        q, k, v = _mk(s=s)
        lens = np.array([64, 32, 48, 16], np.int32)

        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     batch_axis=None, head_axis=None,
                                     split_pattern="sym", seq_lens=lens)
        # oracle: valid mask is defined in the SYM (reordered) frame;
        # map it back to global token order through sym_indices
        pos_r = np.arange(s)
        valid_r = (pos_r % s_local) < lens[pos_r // s_local]
        fwd = sym_indices(s, cp)
        valid = np.empty(s, bool)
        valid[fwd] = valid_r
        segs = np.where(valid, 0, -1 - np.arange(s)).astype(np.int32)
        segs = np.broadcast_to(segs, (q.shape[0], s))
        ref = sdpa_reference(q, k, v, causal=True,
                             segment_ids=jnp.asarray(segs))
        ov = np.asarray(out)[:, valid]
        rv = np.asarray(ref)[:, valid]
        np.testing.assert_allclose(ov, rv, rtol=1e-4, atol=1e-4)

    def test_sym_varlen_bwd(self, devices8):
        cp, s = 4, 128
        mesh = ht.create_mesh({"cp": cp}, devices8[:4])
        q, k, v = _mk(s=s)
        doc = np.zeros(s, np.int32)
        doc[50:] = 1
        segs = np.broadcast_to(doc, (q.shape[0], s)).copy()

        def loss_ring(q, k, v):
            o = ring_attention_sharded(q, k, v, mesh, causal=True,
                                       batch_axis=None, head_axis=None,
                                       split_pattern="sym",
                                       segment_ids=jnp.asarray(segs))
            return jnp.sum(o ** 2)

        def loss_ref(q, k, v):
            o = sdpa_reference(q, k, v, causal=True,
                               segment_ids=jnp.asarray(segs))
            return jnp.sum(o ** 2)

        g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3,
                                       err_msg=f"d{name}")


@pytest.mark.slow
class TestRingRoundProfiling:
    """Per-round ring timing (reference AttnCommRing optional profiling,
    ParallelAttention.h:411-413)."""

    def test_round_times_measured(self, devices8):
        from hetu_tpu.parallel.ring_attention import profile_ring_rounds
        mesh = ht.create_mesh({"cp": 4}, devices8[:4])
        q, k, v = _mk(s=128)
        for pattern in ("normal", "sym"):
            times = profile_ring_rounds(q, k, v, mesh, causal=True,
                                        split_pattern=pattern, reps=2)
            assert len(times) == 4
            assert all(t > 0 and np.isfinite(t) for t in times), times
