"""End-to-end example scripts must run and self-check on the virtual
mesh (reference examples/{gpt,hydraulis,malleus} smoke coverage)."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(script, *argv, timeout=600):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    # a sitecustomize may pin a hardware platform over the env var (and a
    # wedged TPU runtime HANGS on init); pin cpu through the live jax
    # config before the script runs, like tests/conftest.py does
    code = (
        "import sys, runpy\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        f"sys.argv = [{script!r}, *{list(argv)!r}]\n"
        f"runpy.run_path({os.path.join(REPO, 'examples', script)!r}, "
        "run_name='__main__')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, \
        f"{script} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    return proc.stdout


@pytest.mark.slow
class TestExamples:
    def test_train_gpt_dp_tp(self):
        out = _run_example(
            "train_gpt.py", "--dp", "2", "--tp", "2", "--steps", "4",
            "--hidden", "64", "--layers", "2", "--heads", "4",
            "--seq-len", "32", "--vocab-size", "128",
            "--global-batch", "8", "--log-every", "2")
        assert "step" in out

    def test_train_gpt_pp_from_ds_config(self, tmp_path):
        import json
        sys.path.insert(0, REPO)
        from hetu_tpu.utils.ds_config import generate_gpt_3d_config
        cfg = generate_gpt_3d_config(num_layers=4, dp=2, tp=2, pp=2,
                                     zero=True)
        p = str(tmp_path / "pp2.json")
        json.dump(cfg, open(p, "w"))
        out = _run_example(
            "train_gpt.py", "--ds-config", p, "--steps", "4",
            "--hidden", "64", "--layers", "4", "--heads", "4",
            "--seq-len", "32", "--vocab-size", "128",
            "--global-batch", "8", "--log-every", "2")
        assert "step" in out

    def test_train_gpt_auto_parallel(self):
        """--auto-parallel: the planner picks (dp, tp, pp, zero,
        micro-batch) for the visible 8 devices and training runs under
        the selected plan (the closed Galvatron loop)."""
        out = _run_example(
            "train_gpt.py", "--auto-parallel", "--steps", "4",
            "--hidden", "64", "--layers", "2", "--heads", "4",
            "--seq-len", "32", "--vocab-size", "128",
            "--global-batch", "8", "--log-every", "2")
        assert "step" in out

    def test_train_hydraulis(self):
        out = _run_example("train_hydraulis.py", "--steps", "5")
        assert "hydraulis e2e OK" in out

    def test_train_malleus(self):
        out = _run_example("train_malleus.py", "--steps", "12")
        assert "malleus e2e OK" in out

    def test_train_malleus_calibrated(self):
        out = _run_example("train_malleus.py", "--steps", "12",
                           "--calibrate")
        assert "calibrated:" in out and "malleus e2e OK" in out

    def test_generate_gpt(self):
        out = _run_example("generate_gpt.py", "--steps", "120",
                           "--hidden", "48")
        assert "self-check OK" in out
