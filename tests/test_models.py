"""Model-family tests: BERT, CNN/ResNet, RNN/LSTM/GRU + ds-config
generators (the reference's tests/hetu_bert.py, test_cifar10.py,
test_rnn.py coverage)."""
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import ops, optim
from hetu_tpu.models import (GRU, LSTM, RNN, BertConfig, BertForPreTraining,
                             BertForSequenceClassification, ResNet,
                             RNNLanguageModel, SimpleCNN, resnet18)
from hetu_tpu.nn.parallel import config2ds
from hetu_tpu.utils.ds_config import (generate_gpt_3d_config,
                                      generate_gpt_hetero_3d_config,
                                      iter_block_entries)


def _fix_seed(v=9):
    from hetu_tpu.graph import ctor
    ctor._seed_counter[0] = v


def _bert_cfg(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("hidden_size", 32)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_seq_len", 16)
    return BertConfig(**kw)


@pytest.mark.slow
class TestBert:
    def test_pretraining_loss_decreases(self):
        _fix_seed()
        rng = np.random.RandomState(0)
        B, S = 4, 16
        ids = rng.randint(0, 64, (B, S)).astype(np.int32)
        seg = (np.arange(S)[None, :] >= S // 2).astype(np.int32) \
            * np.ones((B, 1), np.int32)
        mlm = ids.copy()
        mlm[:, ::3] = -100  # ignore unmasked positions
        nsp = rng.randint(0, 2, (B,)).astype(np.int32)
        with ht.graph("define_and_run", create_new=True) as g:
            model = BertForPreTraining(_bert_cfg())
            i = ht.placeholder("int32", (B, S), name="ids")
            t = ht.placeholder("int32", (B, S), name="seg")
            ml = ht.placeholder("int32", (B, S), name="mlm")
            ns = ht.placeholder("int32", (B,), name="nsp")
            loss = model(i, token_type_ids=t, mlm_labels=ml, nsp_labels=ns)
            train_op = optim.AdamOptimizer(lr=1e-3).minimize(loss)
            losses = []
            for _ in range(8):
                l, _ = g.run(loss, [loss, train_op],
                             {i: ids, t: seg, ml: mlm, ns: nsp})
                losses.append(float(np.asarray(l)))
        assert losses[-1] < losses[0]

    def test_sequence_classification(self):
        _fix_seed()
        rng = np.random.RandomState(1)
        ids = rng.randint(0, 64, (8, 16)).astype(np.int32)
        labels = rng.randint(0, 2, (8,)).astype(np.int32)
        with ht.graph("define_and_run", create_new=True) as g:
            model = BertForSequenceClassification(_bert_cfg(), 2)
            i = ht.placeholder("int32", ids.shape, name="ids")
            lb = ht.placeholder("int32", labels.shape, name="lb")
            loss = model(i, labels=lb)
            train_op = optim.AdamOptimizer(lr=1e-3).minimize(loss)
            losses = [float(np.asarray(g.run(loss, [loss, train_op],
                                             {i: ids, lb: labels})[0]))
                      for _ in range(8)]
        assert losses[-1] < losses[0]

    def test_bert_tp_matches_single_device(self, devices8):
        _fix_seed(77)
        rng = np.random.RandomState(2)
        ids = rng.randint(0, 64, (4, 16)).astype(np.int32)
        labels = rng.randint(0, 2, (4,)).astype(np.int32)

        def run(mesh_shape, devs=None):
            _fix_seed(77)
            mesh = ht.create_mesh(mesh_shape, devs) if mesh_shape else None
            with ht.graph("define_and_run", create_new=True,
                          mesh=mesh) as g:
                model = BertForSequenceClassification(_bert_cfg(), 2)
                i = ht.parallel_placeholder(
                    "int32", ids.shape,
                    pspec=P("dp", None) if mesh else None, name="ids")
                lb = ht.parallel_placeholder(
                    "int32", labels.shape,
                    pspec=P("dp") if mesh else None, name="lb")
                loss = model(i, labels=lb)
                train_op = optim.AdamOptimizer(lr=1e-3).minimize(loss)
                return [float(np.asarray(
                    g.run(loss, [loss, train_op], {i: ids, lb: labels})[0]))
                    for _ in range(3)]

        l1 = run(None)
        l2 = run({"dp": 2, "tp": 2}, devices8[:4])
        np.testing.assert_allclose(l1, l2, rtol=2e-3, atol=1e-4)


@pytest.mark.slow
class TestCNN:
    def test_simple_cnn_trains(self):
        _fix_seed()
        rng = np.random.RandomState(0)
        X = rng.randn(8, 3, 32, 32).astype(np.float32)
        y = rng.randint(0, 10, (8,)).astype(np.int32)
        with ht.graph("define_and_run", create_new=True) as g:
            model = SimpleCNN()
            xi = ht.placeholder("float32", X.shape, name="x")
            yi = ht.placeholder("int32", y.shape, name="y")
            loss = model(xi, yi)
            train_op = optim.AdamOptimizer(lr=1e-3).minimize(loss)
            losses = [float(np.asarray(g.run(loss, [loss, train_op],
                                             {xi: X, yi: y})[0]))
                      for _ in range(6)]
        assert losses[-1] < losses[0]

    def test_resnet_forward_and_train(self):
        _fix_seed()
        rng = np.random.RandomState(1)
        X = rng.randn(4, 3, 32, 32).astype(np.float32)
        y = rng.randint(0, 10, (4,)).astype(np.int32)
        with ht.graph("define_and_run", create_new=True) as g:
            model = ResNet(10, stages=(1, 1), widths=(8, 16))
            xi = ht.placeholder("float32", X.shape, name="x")
            yi = ht.placeholder("int32", y.shape, name="y")
            logits = model(xi)
            assert tuple(logits.shape) == (4, 10)
            loss = model(xi, yi)
            train_op = optim.AdamOptimizer(lr=1e-3).minimize(loss)
            losses = [float(np.asarray(g.run(loss, [loss, train_op],
                                             {xi: X, yi: y})[0]))
                      for _ in range(5)]
        assert losses[-1] < losses[0]

    def test_resnet18_structure(self):
        with ht.graph("define_and_run", create_new=True):
            m = resnet18()
            assert len(m.blocks) == 8  # (2+2+2+2)


class TestRNN:
    @pytest.mark.parametrize("cell", ["rnn", "gru", "lstm"])
    def test_lm_trains(self, cell):
        _fix_seed()
        # learnable pattern: next token = current + 1 (mod V)
        V, B, S = 16, 4, 12
        ids = np.stack([np.arange(s, s + S) % V for s in range(B)]) \
            .astype(np.int32)
        labels = (ids + 1) % V
        with ht.graph("define_and_run", create_new=True) as g:
            model = RNNLanguageModel(V, 32, cell=cell)
            i = ht.placeholder("int32", ids.shape, name="ids")
            lb = ht.placeholder("int32", labels.shape, name="lb")
            loss = model(i, lb)
            train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            losses = [float(np.asarray(g.run(loss, [loss, train_op],
                                             {i: ids, lb: labels})[0]))
                      for _ in range(30)]
        assert losses[-1] < losses[0] * 0.6, (cell, losses[::10])

    def test_lstm_state_shapes(self):
        with ht.graph("define_and_run", create_new=True) as g:
            lstm = LSTM(8, 16)
            x = ht.placeholder("float32", (2, 5, 8), name="x")
            ys, carry = lstm(x)
            assert tuple(ys.shape) == (2, 5, 16)
            X = np.random.RandomState(0).randn(2, 5, 8).astype(np.float32)
            (out,) = g.run(ys, [ys], {x: X})
        assert np.asarray(out).shape == (2, 5, 16)


class TestDSConfigGenerator:
    def test_3d_config_parses_via_config2ds(self):
        cfg = generate_gpt_3d_config(num_layers=8, dp=2, tp=2, pp=2)
        assert len(cfg["devices"]) == 8
        n_entries = 0
        for rng_, name, entry in iter_block_entries(cfg):
            ds_union, dgs = config2ds(entry)
            assert ds_union.get(0).device_num == len(dgs[0]) == 4
            n_entries += 1
        assert n_entries == 2 * 6  # 2 stages x 6 leaf entries
        # stage ranges cover all layers disjointly
        ranges = [b["range"] for b in cfg["gpt"]["blocks"].values()]
        covered = sorted(x for lo, hi in ranges for x in range(lo, hi + 1))
        assert covered == list(range(8))

    def test_3d_config_shapes(self):
        cfg = generate_gpt_3d_config(num_layers=4, dp=4, tp=2, pp=1,
                                     zero=True)
        qkv = next(e for r, n, e in iter_block_entries(cfg)
                   if n == "attn.qkv")
        assert qkv["split"] == {"1": [2]}
        assert qkv["dup"] == [4]
        assert qkv["zero"] is True

    def test_invalid_product_raises(self):
        with pytest.raises(AssertionError):
            generate_gpt_3d_config(num_layers=4, dp=2, tp=2, pp=2,
                                   num_devices=4)

    def test_hetero_config(self):
        stages = [
            {"dp": 2, "tp": 2, "devices": [0, 1, 2, 3], "layers": [0, 3]},
            {"dp": 1, "tp": 2, "devices": [4, 5], "layers": [4, 7]},
        ]
        cfg = generate_gpt_hetero_3d_config(8, stages)
        assert cfg["hetero"] and len(cfg["devices"]) == 6
        b0 = cfg["gpt"]["blocks"]["blocks0-3"]
        b1 = cfg["gpt"]["blocks"]["blocks4-7"]
        assert b0["attn"]["qkv"]["dup"] == [2]
        assert b1["attn"]["qkv"]["dup"] == [1]
        for _, _, entry in iter_block_entries(cfg):
            config2ds(entry)  # parses


@pytest.mark.slow
class TestPackedVarlen:
    """Packed (cu_seqlens-style) training through the model surface
    (reference ops/Attention.h:286 varlen path; Hydraulis packing)."""

    def test_no_cross_document_leakage(self):
        """With segment_ids, a document's logits must not depend on the
        OTHER documents packed into the same row (either direction)."""
        from hetu_tpu.graph import ctor
        from hetu_tpu.models import GPTLMHeadModel, llama_config
        cfg_kw = dict(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, max_seq_len=32, sp=False)
        segs = np.zeros((1, 32), np.int32)
        segs[0, 16:] = 1  # doc0 = [0,16), doc1 = [16,32)

        def logits_for(tokens):
            ctor._seed_counter[0] = 321
            with ht.graph("define_and_run", create_new=True) as g:
                ids = ht.placeholder("int32", (1, 32), name="ids")
                seg = ht.placeholder("int32", (1, 32), name="seg")
                m = GPTLMHeadModel(llama_config(**cfg_kw))
                out = m(ids, segment_ids=seg)
                (val,) = g.run(out, [out], {ids: tokens, seg: segs})
            return np.asarray(val)

        rng = np.random.RandomState(0)
        base = rng.randint(0, 64, (1, 32)).astype(np.int32)
        v1 = logits_for(base)
        # change doc1's content -> doc0 logits unchanged
        alt = base.copy()
        alt[0, 16:] = rng.randint(0, 64, 16)
        v2 = logits_for(alt)
        np.testing.assert_allclose(v1[0, :16], v2[0, :16],
                                   rtol=1e-5, atol=1e-5)
        # change doc0's content -> doc1 logits unchanged
        alt2 = base.copy()
        alt2[0, :16] = rng.randint(0, 64, 16)
        v3 = logits_for(alt2)
        np.testing.assert_allclose(v1[0, 16:], v3[0, 16:],
                                   rtol=1e-5, atol=1e-5)
        # sanity: WITHOUT segment ids doc1 logits DO depend on doc0
        def logits_noseg(tokens):
            ctor._seed_counter[0] = 321
            with ht.graph("define_and_run", create_new=True) as g:
                ids = ht.placeholder("int32", (1, 32), name="ids")
                m = GPTLMHeadModel(llama_config(**cfg_kw))
                out = m(ids)
                (val,) = g.run(out, [out], {ids: tokens})
            return np.asarray(val)
        u1 = logits_noseg(base)
        u3 = logits_noseg(alt2)
        assert np.abs(u1[0, 16:] - u3[0, 16:]).max() > 1e-3

    def test_packed_training_with_cp_mesh(self, devices8):
        """Packed segment ids flow through parallel_attention's KV ring."""
        from hetu_tpu.models import GPTLMHeadModel, llama_config
        mesh = ht.create_mesh({"dp": 2, "cp": 2, "tp": 2}, devices8)
        cfg = llama_config(vocab_size=64, hidden_size=32, num_layers=2,
                           num_heads=4, max_seq_len=64, sp=False,
                           cp_axis="cp")
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            ids = ht.parallel_placeholder("int32", (4, 64),
                                          pspec=P("dp", None), name="ids")
            lbl = ht.parallel_placeholder("int32", (4, 64),
                                          pspec=P("dp", None), name="lbl")
            seg = ht.parallel_placeholder("int32", (4, 64),
                                          pspec=P("dp", None), name="seg")
            m = GPTLMHeadModel(cfg)
            loss = m(ids, lbl, segment_ids=seg)
            op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
            rng = np.random.RandomState(0)
            I = rng.randint(0, 64, (4, 64)).astype(np.int32)
            S = np.zeros((4, 64), np.int32)
            S[:, 40:] = 1
            L = np.where(S == np.roll(S, -1, 1), np.roll(I, -1, 1), -100)
            losses = [float(np.asarray(g.run(
                loss, [loss, op],
                {ids: I, lbl: L.astype(np.int32), seg: S})[0]))
                for _ in range(3)]
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses
