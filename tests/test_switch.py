"""Hot-switch tests (reference SwitchExecGraph, switch_exec_graph.h:459).

Train under one strategy, live-migrate params+optimizer states to another
mesh/sharding, verify bit-exact values, correct new placements, and that
training continues with the same trajectory.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.parallel import (SwitchExecGraph, SwitchMode, SwitchPlan,
                               switch_state)


# full-model training loops: excluded from the dev fast path
pytestmark = pytest.mark.slow


def _mesh(devices8, dp, tp):
    return Mesh(np.array(devices8).reshape(dp, tp), ("dp", "tp"))


class TestSwitchPlan:
    def test_split_to_replicated(self, devices8):
        mesh = _mesh(devices8, 8, 1)
        src = NamedSharding(mesh, P("dp", None))
        dst = NamedSharding(mesh, P(None, None))
        plan = SwitchPlan((8, 4), 4, src, dst)
        # every device needs all 8 rows; 1 row is local, 7 are moved
        assert plan.local_bytes == 8 * 4 * 4
        assert plan.moved_bytes == 8 * 7 * 4 * 4

    def test_resharding_transfer_counts(self, devices8):
        mesh_a = _mesh(devices8, 4, 2)
        src = NamedSharding(mesh_a, P("dp", "tp"))
        dst = NamedSharding(mesh_a, P("tp", "dp"))
        plan = SwitchPlan((8, 8), 4, src, dst)
        total = plan.local_bytes + plan.moved_bytes
        assert total == 8 * 8 * 4  # every element lands exactly once

    def test_identity_is_all_local(self, devices8):
        mesh = _mesh(devices8, 4, 2)
        sh = NamedSharding(mesh, P("dp", "tp"))
        plan = SwitchPlan((8, 8), 4, sh, sh)
        assert plan.moved_bytes == 0
        assert plan.local_bytes == 8 * 8 * 4


class TestSwitchState:
    def test_values_preserved(self, devices8):
        mesh_a = _mesh(devices8, 8, 1)
        mesh_b = _mesh(devices8, 2, 4)
        x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        xa = jax.device_put(x, NamedSharding(mesh_a, P("dp", None)))
        out = switch_state({"x": xa},
                           {"x": NamedSharding(mesh_b, P(None, "tp"))})
        np.testing.assert_array_equal(np.asarray(out["x"]), np.asarray(x))
        assert out["x"].sharding.spec == P(None, "tp")

    def test_dtype_transfer(self, devices8):
        mesh = _mesh(devices8, 8, 1)
        x = jnp.ones((8, 4), jnp.float32)
        xa = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        out = switch_state({"x": xa},
                           {"x": NamedSharding(mesh, P("dp", None))},
                           dtype=jnp.bfloat16)
        assert out["x"].dtype == jnp.bfloat16


class TestGraphHotSwitch:
    def _build(self, mesh, seed=0):
        from hetu_tpu.graph import ctor
        ctor._seed_counter[0] = seed  # deterministic param init
        cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=16, dropout=0.0,
                        dtype="float32")
        g_ctx = ht.graph("define_and_run", create_new=True, mesh=mesh)
        g = g_ctx.__enter__()
        model = GPTLMHeadModel(cfg)
        ids = ht.parallel_placeholder("int32", (4, 16), pspec=P("dp"))
        labels = ht.parallel_placeholder("int32", (4, 16), pspec=P("dp"))
        loss = model(ids, labels)
        opt = ht.optim.AdamOptimizer(lr=1e-3)
        train_op = opt.minimize(loss)
        return g_ctx, g, model, opt, ids, labels, loss, train_op

    def test_hot_switch_mid_training(self, devices8):
        mesh_a = _mesh(devices8, 4, 2)
        mesh_b = _mesh(devices8, 2, 4)
        g_ctx, g, model, opt, ids, labels, loss, train_op = \
            self._build(mesh_a)
        try:
            rng = np.random.RandomState(0)
            feed = {ids: rng.randint(0, 96, (4, 16)),
                    labels: rng.randint(0, 96, (4, 16))}
            losses = []
            for _ in range(3):
                l, _ = g.run(loss, [loss, train_op], feed)
                losses.append(float(l))
            params_before = {n: np.asarray(p.numpy(), np.float32)
                             for n, p in model.named_parameters()}
            sid_before = g.cur_strategy_id

            prof = g.switch_strategy(mesh_b, optimizer=opt)
            assert g.cur_strategy_id == sid_before + 1
            assert prof.num_tensors > 0

            # params bit-identical after migration
            for n, p in model.named_parameters():
                np.testing.assert_array_equal(
                    np.asarray(p.numpy(), np.float32), params_before[n])
            # arrays actually live on the new mesh
            qkv = dict(model.named_parameters())[
                "transformer.h.0.attn.qkv.weight"]
            arr = g.get_tensor_value(qkv)
            assert arr.sharding.mesh.shape["tp"] == 4

            # training continues and loss keeps the trajectory
            for _ in range(3):
                l, _ = g.run(loss, [loss, train_op], feed)
                losses.append(float(l))
            assert losses[-1] < losses[0]
        finally:
            g_ctx.__exit__(None, None, None)

    def test_switch_matches_no_switch_trajectory(self, devices8):
        """Loss sequence with a mid-run switch == without any switch."""
        rng = np.random.RandomState(1)
        ids_v = rng.randint(0, 96, (4, 16))
        lab_v = rng.randint(0, 96, (4, 16))

        def run_steps(switch_at=None, n=6):
            mesh_a = _mesh(jax.devices()[:8], 4, 2)
            mesh_b = _mesh(jax.devices()[:8], 2, 4)
            g_ctx, g, model, opt, ids, labels, loss, train_op = \
                self._build(mesh_a, seed=7)
            try:
                out = []
                feed = {ids: ids_v, labels: lab_v}
                for i in range(n):
                    if switch_at is not None and i == switch_at:
                        g.switch_strategy(mesh_b, optimizer=opt)
                    l, _ = g.run(loss, [loss, train_op], feed)
                    out.append(float(l))
                return out
            finally:
                g_ctx.__exit__(None, None, None)

        base = run_steps(None)
        switched = run_steps(switch_at=3)
        np.testing.assert_allclose(base, switched, rtol=2e-4, atol=2e-5)

    def test_missing_axis_dropped_and_persisted(self, devices8):
        """Switching to a mesh lacking an axis drops it from pspecs AND
        persists the fixed spec so later runs don't crash."""
        mesh_a = _mesh(devices8, 4, 2)
        # scale-down: 4 of the 8 devices, and no tp axis at all
        mesh_b = Mesh(np.array(devices8[:4]).reshape(4,), ("dp",))
        g_ctx, g, model, opt, ids, labels, loss, train_op = \
            self._build(mesh_a)
        try:
            rng = np.random.RandomState(0)
            feed = {ids: rng.randint(0, 96, (4, 16)),
                    labels: rng.randint(0, 96, (4, 16))}
            g.run(loss, [loss, train_op], feed)
            g.switch_strategy(mesh_b, optimizer=opt)
            qkv = dict(model.named_parameters())[
                "transformer.h.0.attn.qkv.weight"]
            assert "tp" not in str(qkv.pspec)
            g.run(loss, [loss, train_op], feed)  # must not raise
        finally:
            g_ctx.__exit__(None, None, None)

    def test_optimizer_mode_requires_optimizer(self, devices8):
        mesh_a = _mesh(devices8, 4, 2)
        g_ctx, g, model, opt, ids, labels, loss, train_op = \
            self._build(mesh_a)
        try:
            with pytest.raises(ValueError):
                g.switch_strategy(_mesh(devices8, 2, 4), optimizer=None,
                                  mode=SwitchMode.ORIGIN_PARAM_AND_OPTIMIZER)
        finally:
            g_ctx.__exit__(None, None, None)

    def test_zero_state_resharded(self, devices8):
        """ZeRO optimizer states follow the new mesh's dp extent."""
        mesh_a = _mesh(devices8, 4, 2)
        mesh_b = _mesh(devices8, 2, 4)
        cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=16, dropout=0.0,
                        dtype="float32")
        with ht.graph("define_and_run", create_new=True, mesh=mesh_a) as g:
            model = GPTLMHeadModel(cfg)
            ids = ht.parallel_placeholder("int32", (4, 16), pspec=P("dp"))
            labels = ht.parallel_placeholder("int32", (4, 16), pspec=P("dp"))
            loss = model(ids, labels)
            opt = ht.optim.AdamOptimizer(lr=1e-3, zero=True)
            train_op = opt.minimize(loss)
            rng = np.random.RandomState(0)
            feed = {ids: rng.randint(0, 96, (4, 16)),
                    labels: rng.randint(0, 96, (4, 16))}
            g.run(loss, [loss, train_op], feed)
            m_before = {tid: np.asarray(jax.device_get(a), np.float32)
                        for tid, a in opt._state["m"].items()}
            g.switch_strategy(mesh_b, optimizer=opt)
            for tid, a in opt._state["m"].items():
                np.testing.assert_allclose(
                    np.asarray(jax.device_get(a), np.float32),
                    m_before[tid], rtol=1e-6)
            g.run(loss, [loss, train_op], feed)
