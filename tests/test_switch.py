"""Hot-switch tests (reference SwitchExecGraph, switch_exec_graph.h:459).

Train under one strategy, live-migrate params+optimizer states to another
mesh/sharding, verify bit-exact values, correct new placements, and that
training continues with the same trajectory.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.parallel import (SwitchExecGraph, SwitchMode, SwitchPlan,
                               switch_state)


# full-model training loops: excluded from the dev fast path
pytestmark = pytest.mark.slow


def _mesh(devices8, dp, tp):
    return Mesh(np.array(devices8).reshape(dp, tp), ("dp", "tp"))


class TestSwitchPlan:
    def test_split_to_replicated(self, devices8):
        mesh = _mesh(devices8, 8, 1)
        src = NamedSharding(mesh, P("dp", None))
        dst = NamedSharding(mesh, P(None, None))
        plan = SwitchPlan((8, 4), 4, src, dst)
        # every device needs all 8 rows; 1 row is local, 7 are moved
        assert plan.local_bytes == 8 * 4 * 4
        assert plan.moved_bytes == 8 * 7 * 4 * 4

    def test_resharding_transfer_counts(self, devices8):
        mesh_a = _mesh(devices8, 4, 2)
        src = NamedSharding(mesh_a, P("dp", "tp"))
        dst = NamedSharding(mesh_a, P("tp", "dp"))
        plan = SwitchPlan((8, 8), 4, src, dst)
        total = plan.local_bytes + plan.moved_bytes
        assert total == 8 * 8 * 4  # every element lands exactly once

    def test_identity_is_all_local(self, devices8):
        mesh = _mesh(devices8, 4, 2)
        sh = NamedSharding(mesh, P("dp", "tp"))
        plan = SwitchPlan((8, 8), 4, sh, sh)
        assert plan.moved_bytes == 0
        assert plan.local_bytes == 8 * 8 * 4


class TestSwitchState:
    def test_values_preserved(self, devices8):
        mesh_a = _mesh(devices8, 8, 1)
        mesh_b = _mesh(devices8, 2, 4)
        x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        xa = jax.device_put(x, NamedSharding(mesh_a, P("dp", None)))
        out = switch_state({"x": xa},
                           {"x": NamedSharding(mesh_b, P(None, "tp"))})
        np.testing.assert_array_equal(np.asarray(out["x"]), np.asarray(x))
        assert out["x"].sharding.spec == P(None, "tp")

    def test_dtype_transfer(self, devices8):
        mesh = _mesh(devices8, 8, 1)
        x = jnp.ones((8, 4), jnp.float32)
        xa = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        out = switch_state({"x": xa},
                           {"x": NamedSharding(mesh, P("dp", None))},
                           dtype=jnp.bfloat16)
        assert out["x"].dtype == jnp.bfloat16


class TestGraphHotSwitch:
    def _build(self, mesh, seed=0):
        from hetu_tpu.graph import ctor
        ctor._seed_counter[0] = seed  # deterministic param init
        cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=16, dropout=0.0,
                        dtype="float32")
        g_ctx = ht.graph("define_and_run", create_new=True, mesh=mesh)
        g = g_ctx.__enter__()
        model = GPTLMHeadModel(cfg)
        ids = ht.parallel_placeholder("int32", (4, 16), pspec=P("dp"))
        labels = ht.parallel_placeholder("int32", (4, 16), pspec=P("dp"))
        loss = model(ids, labels)
        opt = ht.optim.AdamOptimizer(lr=1e-3)
        train_op = opt.minimize(loss)
        return g_ctx, g, model, opt, ids, labels, loss, train_op

    def test_hot_switch_mid_training(self, devices8):
        mesh_a = _mesh(devices8, 4, 2)
        mesh_b = _mesh(devices8, 2, 4)
        g_ctx, g, model, opt, ids, labels, loss, train_op = \
            self._build(mesh_a)
        try:
            rng = np.random.RandomState(0)
            feed = {ids: rng.randint(0, 96, (4, 16)),
                    labels: rng.randint(0, 96, (4, 16))}
            losses = []
            for _ in range(3):
                l, _ = g.run(loss, [loss, train_op], feed)
                losses.append(float(l))
            params_before = {n: np.asarray(p.numpy(), np.float32)
                             for n, p in model.named_parameters()}
            sid_before = g.cur_strategy_id

            prof = g.switch_strategy(mesh_b, optimizer=opt)
            assert g.cur_strategy_id == sid_before + 1
            assert prof.num_tensors > 0

            # params bit-identical after migration
            for n, p in model.named_parameters():
                np.testing.assert_array_equal(
                    np.asarray(p.numpy(), np.float32), params_before[n])
            # arrays actually live on the new mesh
            qkv = dict(model.named_parameters())[
                "transformer.h.0.attn.qkv.weight"]
            arr = g.get_tensor_value(qkv)
            assert arr.sharding.mesh.shape["tp"] == 4

            # training continues and loss keeps the trajectory
            for _ in range(3):
                l, _ = g.run(loss, [loss, train_op], feed)
                losses.append(float(l))
            assert losses[-1] < losses[0]
        finally:
            g_ctx.__exit__(None, None, None)

    def test_switch_matches_no_switch_trajectory(self, devices8):
        """Loss sequence with a mid-run switch == without any switch."""
        rng = np.random.RandomState(1)
        ids_v = rng.randint(0, 96, (4, 16))
        lab_v = rng.randint(0, 96, (4, 16))

        def run_steps(switch_at=None, n=6):
            mesh_a = _mesh(jax.devices()[:8], 4, 2)
            mesh_b = _mesh(jax.devices()[:8], 2, 4)
            g_ctx, g, model, opt, ids, labels, loss, train_op = \
                self._build(mesh_a, seed=7)
            try:
                out = []
                feed = {ids: ids_v, labels: lab_v}
                for i in range(n):
                    if switch_at is not None and i == switch_at:
                        g.switch_strategy(mesh_b, optimizer=opt)
                    l, _ = g.run(loss, [loss, train_op], feed)
                    out.append(float(l))
                return out
            finally:
                g_ctx.__exit__(None, None, None)

        base = run_steps(None)
        switched = run_steps(switch_at=3)
        np.testing.assert_allclose(base, switched, rtol=2e-4, atol=2e-5)

    def test_missing_axis_dropped_and_persisted(self, devices8):
        """Switching to a mesh lacking an axis drops it from pspecs AND
        persists the fixed spec so later runs don't crash."""
        mesh_a = _mesh(devices8, 4, 2)
        # scale-down: 4 of the 8 devices, and no tp axis at all
        mesh_b = Mesh(np.array(devices8[:4]).reshape(4,), ("dp",))
        g_ctx, g, model, opt, ids, labels, loss, train_op = \
            self._build(mesh_a)
        try:
            rng = np.random.RandomState(0)
            feed = {ids: rng.randint(0, 96, (4, 16)),
                    labels: rng.randint(0, 96, (4, 16))}
            g.run(loss, [loss, train_op], feed)
            g.switch_strategy(mesh_b, optimizer=opt)
            qkv = dict(model.named_parameters())[
                "transformer.h.0.attn.qkv.weight"]
            assert "tp" not in str(qkv.pspec)
            g.run(loss, [loss, train_op], feed)  # must not raise
        finally:
            g_ctx.__exit__(None, None, None)

    def test_optimizer_mode_requires_optimizer(self, devices8):
        mesh_a = _mesh(devices8, 4, 2)
        g_ctx, g, model, opt, ids, labels, loss, train_op = \
            self._build(mesh_a)
        try:
            with pytest.raises(ValueError):
                g.switch_strategy(_mesh(devices8, 2, 4), optimizer=None,
                                  mode=SwitchMode.ORIGIN_PARAM_AND_OPTIMIZER)
        finally:
            g_ctx.__exit__(None, None, None)

    def test_zero_state_resharded(self, devices8):
        """ZeRO optimizer states follow the new mesh's dp extent."""
        mesh_a = _mesh(devices8, 4, 2)
        mesh_b = _mesh(devices8, 2, 4)
        cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=16, dropout=0.0,
                        dtype="float32")
        with ht.graph("define_and_run", create_new=True, mesh=mesh_a) as g:
            model = GPTLMHeadModel(cfg)
            ids = ht.parallel_placeholder("int32", (4, 16), pspec=P("dp"))
            labels = ht.parallel_placeholder("int32", (4, 16), pspec=P("dp"))
            loss = model(ids, labels)
            opt = ht.optim.AdamOptimizer(lr=1e-3, zero=True)
            train_op = opt.minimize(loss)
            rng = np.random.RandomState(0)
            feed = {ids: rng.randint(0, 96, (4, 16)),
                    labels: rng.randint(0, 96, (4, 16))}
            g.run(loss, [loss, train_op], feed)
            m_before = {tid: np.asarray(jax.device_get(a), np.float32)
                        for tid, a in opt._state["m"].items()}
            g.switch_strategy(mesh_b, optimizer=opt)
            for tid, a in opt._state["m"].items():
                np.testing.assert_allclose(
                    np.asarray(jax.device_get(a), np.float32),
                    m_before[tid], rtol=1e-6)
            g.run(loss, [loss, train_op], feed)


class TestFlatSwitch:
    """Live dp-resize on the FLAT layout (ISSUE 19): ``switch_strategy``
    repacks param->(bucket, offset) state through ``FlatStateLayout``'s
    index instead of bailing out to per-param state, ZeRO-3's at-rest
    shards ride along bitwise, and the SwitchProfile accounts the repack
    wire bytes.  A dp resize changes only the P(dp) chunking — the
    bucket plan is dp-independent — so flat ZeRO-2 and ZeRO-3 stay
    bitwise through the switch on every transport."""

    SHAPES = [(7, 5), (13,), (3,)]

    def _run(self, devices8, zero, transport, dp_seq, flat=True):
        from hetu_tpu import ops, optim
        from hetu_tpu.parallel import create_mesh
        mesh = create_mesh({"dp": dp_seq[0]}, devices8[:dp_seq[0]])
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            x = ht.parallel_placeholder("float32", (16, 8),
                                        pspec=P("dp", None), name="x")
            y = ht.parallel_placeholder("float32", (16, 1),
                                        pspec=P("dp", None), name="y")
            rng = np.random.RandomState(7)
            w = ht.parameter((0.1 * rng.randn(8, 1)).astype(np.float32),
                             name="w")
            b = ht.parameter(np.zeros((1,), np.float32), name="b")
            extras = [ht.parameter(
                (0.01 * rng.randn(*s)).astype(np.float32), name=f"e{i}")
                for i, s in enumerate(self.SHAPES)]
            pred = ops.matmul(x, w) + b
            loss = ops.reduce_mean((pred - y) ** 2)
            for e in extras:
                loss = loss + 0.01 * ops.reduce_mean(e * e)
            op = optim.AdamOptimizer(lr=1e-2, zero=zero,
                                     grad_comm=transport,
                                     flat_state=flat).minimize(loss)
            X = np.random.RandomState(0).randn(16, 8).astype(np.float32)
            Y = np.random.RandomState(1).randn(16, 1).astype(np.float32)
            opt = op.producer.attrs["optimizer"]
            losses, prof, cur_dp = [], None, dp_seq[0]
            for dp in dp_seq:
                if dp != cur_dp:
                    prof = g.switch_strategy(
                        create_mesh({"dp": dp}, devices8[:dp]),
                        optimizer=opt)
                    cur_dp = dp
                l, _ = g.run(loss, [loss, op], {x: X, y: Y})
                losses.append(float(l))
            if flat:
                assert g._grad_comm_active, g._grad_comm_fallback
            wv = np.asarray(jax.device_get(g.get_tensor_value(w)))
            return losses, prof, wv, opt

    @pytest.mark.parametrize("transport", ["fp32", "bf16", "int8"])
    def test_dp8_to_dp4_zero2_zero3_bitwise(self, devices8, transport):
        seq = (8, 8, 8, 4, 4, 4)
        l2, p2, w2, _ = self._run(devices8, 2, transport, seq)
        l3, p3, w3, o3 = self._run(devices8, 3, transport, seq)
        assert l2 == l3, (transport, l2, l3)
        np.testing.assert_array_equal(w2, w3)
        # the repack stayed flat — no per-param bailout
        assert o3.flat_state and o3._flat_layout.device_num == 4
        assert "flat_master" in o3._state

    @pytest.mark.parametrize("zero", [2, 3])
    def test_dp4_to_dp8_grows_the_shards(self, devices8, zero):
        l, prof, _, opt = self._run(devices8, zero, "fp32", (4, 4, 8, 8))
        assert prof is not None and opt._flat_layout.device_num == 8
        assert all(np.isfinite(v) for v in l)
        # every padded bucket re-chunks under the new dp extent
        assert all(sz % 8 == 0 for sz in opt._flat_layout.padded_sizes)

    def test_switch_profile_accounts_repack_bytes(self, devices8):
        _, prof, _, opt = self._run(devices8, 3, "fp32", (8, 8, 4, 4))
        d = prof.as_dict()
        assert "repack_bytes" in d and d["repack_bytes"] > 0
        # exactly every fp32 state byte (master + each slot, padding
        # dropped) moved through the repack
        nslots = 1 + sum(1 for k in opt._state
                         if k.startswith("flat_") and k != "flat_master")
        unpadded = sum(n for (_, _, n, _) in
                       opt._flat_layout.index.values()) * 4
        assert d["repack_bytes"] == unpadded * nslots

    def test_matches_per_param_trajectory(self, devices8):
        seq = (8, 8, 8, 4, 4, 4)
        base, _, wp, _ = self._run(devices8, 0, "fp32", seq, flat=False)
        got, _, w3, _ = self._run(devices8, 3, "fp32", seq)
        np.testing.assert_allclose(got, base, rtol=2e-5, atol=1e-7)
        np.testing.assert_allclose(w3, wp, rtol=2e-5, atol=1e-7)


class TestFlatSwitchRewind:
    def test_generation_rewind_across_switch(self, devices8, tmp_path):
        """The sentry/generation plane keeps BITWISE rewind across a dp
        resize: a generation written at dp=8 under flat ZeRO-3 restores
        bit-identical params after the graph has switched to dp=4 and
        kept training (the restore re-grafts the flat state through the
        per-param index at the new dp)."""
        from hetu_tpu.graph import ctor
        from hetu_tpu.parallel import create_mesh
        from hetu_tpu.resilience import (load_latest_generation,
                                         save_generation,
                                         verify_generation)
        ctor._seed_counter[0] = 777
        mesh8 = create_mesh({"dp": 8}, devices8)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=4, max_seq_len=16, dropout=0.0,
                        dtype="float32")
        with ht.graph("define_and_run", create_new=True,
                      mesh=mesh8) as g:
            ids = ht.parallel_placeholder("int32", (8, 16),
                                          pspec=P("dp", None))
            labels = ht.parallel_placeholder("int32", (8, 16),
                                             pspec=P("dp", None))
            model = GPTLMHeadModel(cfg)
            loss = model(ids, labels)
            opt = ht.optim.AdamOptimizer(lr=1e-2, zero=3,
                                         grad_comm="fp32",
                                         flat_state=True)
            train_op = opt.minimize(loss)
            rng = np.random.RandomState(0)
            IDS = rng.randint(0, 64, (8, 16)).astype(np.int32)
            feed = {ids: IDS, labels: np.roll(IDS, -1, axis=1)}
            for _ in range(2):
                g.run(loss, [loss, train_op], feed)
            root = str(tmp_path / "gens")
            d = save_generation(model, opt, root, step=2, keep=4)
            assert verify_generation(d)[0]
            want = {n: np.asarray(p.numpy(), np.float32)
                    for n, p in model.named_parameters()}

            prof = g.switch_strategy(
                create_mesh({"dp": 4}, devices8[:4]), optimizer=opt)
            assert prof is not None
            diverged = []
            for _ in range(2):
                l, _ = g.run(loss, [loss, train_op], feed)
                diverged.append(float(l))

            info = load_latest_generation(model, opt, root)
            assert info["generation"] == 2
            for n, p in model.named_parameters():
                np.testing.assert_array_equal(
                    np.asarray(p.numpy(), np.float32), want[n],
                    err_msg=f"{n} not bitwise after rewind")
            # the rewound run keeps training at the NEW dp and the flat
            # state re-grafts there — still no per-param bailout
            cont = []
            for _ in range(2):
                l, _ = g.run(loss, [loss, train_op], feed)
                cont.append(float(l))
            assert opt.flat_state and opt._flat_layout.device_num == 4
            assert "flat_master" in opt._state
            assert g._grad_comm_active, g._grad_comm_fallback
            assert all(np.isfinite(v) for v in cont)
            # the continuation replays the exact post-switch trajectory
            # (same restored state, same data, same dp-4 math): the
            # deterministic replay IS the bitwise-rewind evidence
            assert cont == diverged
