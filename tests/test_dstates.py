"""Unit tests for the DistributedStates sharding spec.

Covers the collective-deduction predicate table the reference defines at
``hetu/graph/distributed_states.h:110-115`` and the device<->shard mapping
(``distributed_states.cc:360-420``), plus our DS <-> jax.sharding lowering.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from hetu_tpu.parallel import (DUPLICATE, PARTIAL, DistributedStates,
                               DistributedStatesUnion, deduce_comm_kind,
                               ds_from_partition_spec, ds_to_mesh_and_spec,
                               ds_to_named_sharding, create_mesh)


class TestBasics:
    def test_construction_and_get_dim(self):
        ds = DistributedStates(8, {0: 2, 1: 4})
        assert ds.get_dim(0) == 2
        assert ds.get_dim(1) == 4
        assert ds.get_dim(DUPLICATE) == 1
        assert ds.get_dim(5) == 1
        assert ds.order == [0, 1]

    def test_device_num_mismatch(self):
        with pytest.raises(ValueError):
            DistributedStates(8, {0: 2, 1: 2})

    def test_pure_duplicate(self):
        ds = DistributedStates.pure_duplicate(4)
        assert ds.check_pure_duplicate()
        assert ds.get_dim(DUPLICATE) == 4

    def test_custom_order(self):
        ds = DistributedStates(8, {0: 2, DUPLICATE: 4}, order=[-1, 0])
        assert ds.order == [-1, 0]

    def test_equality_and_hash(self):
        a = DistributedStates(4, {0: 2, DUPLICATE: 2})
        b = DistributedStates(4, {0: 2, -1: 2})
        assert a == b
        assert hash(a) == hash(b)


class TestPredicates:
    """The check_* table (distributed_states.h:110-115)."""

    def test_allreduce(self):
        # partial over 4 -> duplicate over 4: allreduce
        src = DistributedStates(4, {PARTIAL: 4})
        dst = DistributedStates(4, {DUPLICATE: 4})
        assert src.check_allreduce(dst)
        assert deduce_comm_kind(src, dst) == "all_reduce"

    def test_allreduce_with_dp(self):
        # dp split on dim0 + tp partial -> dp split + dup (the classic
        # row-parallel-linear output reduction)
        src = DistributedStates(8, {0: 2, PARTIAL: 4}, order=[0, -2])
        dst = DistributedStates(8, {0: 2, DUPLICATE: 4}, order=[0, -1])
        assert src.check_allreduce(dst)
        assert deduce_comm_kind(src, dst) == "all_reduce"

    def test_allgather(self):
        # split dim1 over 4 -> duplicate: allgather
        src = DistributedStates(4, {1: 4})
        dst = DistributedStates(4, {DUPLICATE: 4})
        assert src.check_allgather(dst)
        assert deduce_comm_kind(src, dst) == "all_gather"

    def test_allgather_partial_dims(self):
        # dp2 x tp2 split dims (0,1) -> gather dim1 within TP groups,
        # keeping dp split: the SP allgather before a column-parallel matmul
        src = DistributedStates(4, {0: 2, 1: 2}, order=[0, 1])
        dst = DistributedStates(4, {0: 2, DUPLICATE: 2}, order=[0, -1])
        assert src.check_allgather(dst)
        assert deduce_comm_kind(src, dst) == "all_gather"

    def test_reducescatter(self):
        # partial over 4 -> split dim0 over 4: reduce-scatter (ZeRO grad path)
        src = DistributedStates(4, {PARTIAL: 4})
        dst = DistributedStates(4, {0: 4})
        assert src.check_reducescatter(dst)
        assert deduce_comm_kind(src, dst) == "reduce_scatter"

    def test_scatter(self):
        src = DistributedStates(4, {DUPLICATE: 4})
        dst = DistributedStates(4, {0: 4})
        assert src.check_scatter(dst)
        assert deduce_comm_kind(src, dst) == "scatter"

    def test_identity(self):
        a = DistributedStates(4, {0: 4})
        assert deduce_comm_kind(a, a) == "identity"

    def test_generic_reshard(self):
        # split dim0 -> split dim1 has no single collective
        src = DistributedStates(4, {0: 4})
        dst = DistributedStates(4, {1: 4})
        assert deduce_comm_kind(src, dst) == "reshard"

    def test_no_false_positive_allreduce(self):
        src = DistributedStates(4, {0: 4})
        dst = DistributedStates(4, {DUPLICATE: 4})
        assert not src.check_allreduce(dst)


class TestDeviceMapping:
    def test_map_device_to_state_index(self):
        # order [0, 1]: dim0 outermost (stride 4), dim1 innermost
        ds = DistributedStates(8, {0: 2, 1: 4})
        idx = ds.map_device_to_state_index(5)  # 5 = 1*4 + 1
        assert idx[0] == 1 and idx[1] == 1
        idx = ds.map_device_to_state_index(3)
        assert idx[0] == 0 and idx[1] == 3

    def test_loop_sizes(self):
        ds = DistributedStates(8, {0: 2, 1: 4})
        assert ds.get_loop_sizes() == [4, 1]

    def test_group_indices_by_dim(self):
        ds = DistributedStates(8, {0: 2, 1: 4})
        # TP group (dim 1) containing device 5: {4,5,6,7}
        assert ds.get_group_indices_by_dim(1, 5) == [4, 5, 6, 7]
        # DP group (dim 0) containing device 5: {1, 5}
        assert ds.get_group_indices_by_dim(0, 5) == [1, 5]

    def test_dup_group_index(self):
        ds = DistributedStates(8, {0: 2, DUPLICATE: 4}, order=[0, -1])
        assert ds.get_dup_group_index(0) == 0
        assert ds.get_dup_group_index(3) == 0
        assert ds.get_dup_group_index(4) == 1

    def test_local_slice(self):
        ds = DistributedStates(8, {0: 2, 1: 4})
        sl = ds.local_slice((8, 16), 5)
        assert sl == (slice(4, 8), slice(4, 8))
        assert ds.local_shape((8, 16)) == (4, 4)


class TestJaxLowering:
    def test_ds_to_named_sharding_roundtrip(self, devices8):
        ds = DistributedStates(8, {0: 2, 1: 4})
        sharding = ds_to_named_sharding(ds, devices8)
        x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
        arr = jax.device_put(x, sharding)
        # each device must hold exactly the slice local_slice predicts
        for shard in arr.addressable_shards:
            dev_index = devices8.index(shard.device)
            sl = ds.local_slice((8, 16), dev_index)
            np.testing.assert_array_equal(np.asarray(shard.data), x[sl])

    def test_ds_to_named_sharding_with_dup(self, devices8):
        # dp2 x dup4, order [0, -1]: devices {0..3} and {4..7} hold halves
        ds = DistributedStates(8, {0: 2, DUPLICATE: 4}, order=[0, -1])
        sharding = ds_to_named_sharding(ds, devices8)
        x = np.arange(4 * 2, dtype=np.float32).reshape(4, 2)
        arr = jax.device_put(x, sharding)
        for shard in arr.addressable_shards:
            dev_index = devices8.index(shard.device)
            sl = ds.local_slice((4, 2), dev_index)
            np.testing.assert_array_equal(np.asarray(shard.data), x[sl])

    def test_ds_from_partition_spec(self):
        mesh = create_mesh({"dp": 2, "tp": 4})
        ds = ds_from_partition_spec(mesh, P("dp", "tp"))
        assert ds.get_dim(0) == 2 and ds.get_dim(1) == 4
        ds_combined = ds_from_partition_spec(mesh, P(("dp", "tp"),))
        assert ds_combined.get_dim(0) == 8
        ds2 = ds_from_partition_spec(mesh, P("dp", None))
        assert ds2.get_dim(0) == 2
        assert ds2.get_dim(DUPLICATE) == 4
        ds3 = ds_from_partition_spec(mesh, P(None, "tp"),
                                     partial_axes=["dp"])
        assert ds3.get_dim(1) == 4
        assert ds3.get_dim(PARTIAL) == 2


class TestUnion:
    def test_union(self):
        u = DistributedStatesUnion(
            [DistributedStates(4, {0: 4}), DistributedStates(4, {0: 2, -1: 2})],
            hetero_dim=0)
        assert u.is_hetero()
        assert u.size() == 2
        assert u.get(0).get_dim(0) == 4


class TestAlgebraMatchesXLA:
    """The DS algebra's deduced collective must match the collective XLA
    actually inserts for the equivalent GSPMD resharding — keeps the
    parity table load-bearing instead of decorative (the runtime path is
    GSPMD propagation; the reference's SubstituteCommOp makes the same
    decisions explicitly, executable_graph.cc:1006)."""

    def _hlo(self, fn, args, in_specs, out_spec, mesh):
        import jax
        from jax.sharding import NamedSharding
        in_sh = [NamedSharding(mesh, s) for s in in_specs]
        f = jax.jit(fn, in_shardings=in_sh,
                    out_shardings=NamedSharding(mesh, out_spec))
        return f.lower(*args).compile().as_text()

    def test_partial_to_dup_is_all_reduce(self, devices8):
        import jax.numpy as jnp
        mesh = create_mesh({"tp": 4}, devices8[:4])
        src = DistributedStates(4, {PARTIAL: 4})
        dst = DistributedStates(4, {DUPLICATE: 4})
        assert deduce_comm_kind(src, dst) == "all_reduce"
        # row-parallel matmul: contracted dim sharded -> partial result;
        # replicated output forces the resolving collective
        x = np.ones((8, 8), np.float32)
        w = np.ones((8, 8), np.float32)
        hlo = self._hlo(lambda a, b: a @ b, (x, w),
                        [P(None, "tp"), P("tp", None)], P(None, None), mesh)
        assert "all-reduce" in hlo, hlo[-800:]

    def test_split_to_dup_is_all_gather(self, devices8):
        mesh = create_mesh({"tp": 4}, devices8[:4])
        src = DistributedStates(4, {0: 4})
        dst = DistributedStates(4, {DUPLICATE: 4})
        assert deduce_comm_kind(src, dst) == "all_gather"
        x = np.ones((8, 8), np.float32)
        hlo = self._hlo(lambda a: a * 2.0, (x,), [P("tp", None)],
                        P(None, None), mesh)
        assert "all-gather" in hlo, hlo[-800:]

    def test_partial_to_split_is_reduce_scatter(self, devices8):
        mesh = create_mesh({"tp": 4}, devices8[:4])
        src = DistributedStates(4, {PARTIAL: 4})
        dst = DistributedStates(4, {0: 4})
        assert deduce_comm_kind(src, dst) == "reduce_scatter"
        x = np.ones((8, 8), np.float32)
        w = np.ones((8, 8), np.float32)
        hlo = self._hlo(lambda a, b: a @ b, (x, w),
                        [P(None, "tp"), P("tp", None)], P("tp", None), mesh)
        # the SPMD partitioner may lower reduce-scatter as
        # all-reduce + local slice when RS isn't profitable on the
        # backend — both realize the algebra's reduce_scatter edge
        assert "reduce-scatter" in hlo or "all-reduce" in hlo, hlo[-800:]

    def test_dup_to_split_needs_no_collective(self, devices8):
        mesh = create_mesh({"tp": 4}, devices8[:4])
        src = DistributedStates(4, {DUPLICATE: 4})
        dst = DistributedStates(4, {0: 4})
        # algebra: a local slice ("scatter" without comm); XLA: no
        # collective op in the program either
        assert deduce_comm_kind(src, dst) == "scatter"
        x = np.ones((8, 8), np.float32)
        hlo = self._hlo(lambda a: a * 2.0, (x,), [P(None, None)],
                        P("tp", None), mesh)
        for coll in ("all-reduce", "all-gather", "reduce-scatter",
                     "collective-permute", "all-to-all"):
            assert coll not in hlo, (coll, hlo[-800:])
