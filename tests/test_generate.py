"""KV-cache generation vs. full-forward oracle (models/generate.py)."""
import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.models.generate import generate


def _build_state(cfg, seed=3):
    ht.set_seed(seed)
    with ht.graph("eager", create_new=True):
        model = GPTLMHeadModel(cfg)
        # touch a forward so every parameter materializes
        ids = np.zeros((1, 4), np.int32)
        model.logits(ids)
        state = {k: np.asarray(v) for k, v in model.state_dict().items()}
    return model, state


def _oracle_greedy(model, prompt, n_new):
    """Append argmax tokens using the full (uncached) model forward."""
    ids = prompt.copy()
    with ht.graph("eager", create_new=True):
        for _ in range(n_new):
            lg = np.asarray(model.logits(ids).get_data())
            nxt = lg[:, -1].argmax(-1).astype(np.int32)
            ids = np.concatenate([ids, nxt[:, None]], axis=1)
    return ids


CONFIGS = {
    "gpt2ish": dict(position="learned", norm="layernorm", activation="gelu",
                    tie_embeddings=False),
    "llamaish": dict(position="rotary", norm="rmsnorm", activation="swiglu",
                     tie_embeddings=True),
    "gqa": dict(position="rotary", norm="rmsnorm", activation="silu",
                num_kv_heads=2, tie_embeddings=False),
}


@pytest.mark.parametrize("kind", list(CONFIGS))
def test_generate_matches_full_forward(kind):
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=32, sp=False, dropout=0.0,
                    **CONFIGS[kind])
    model, state = _build_state(cfg)
    prompt = np.array([[5, 17, 2, 9], [1, 1, 4, 88]], np.int32)
    want = _oracle_greedy(model, prompt, 6)
    got = np.asarray(generate(state, cfg, prompt, 6, temperature=0.0))
    np.testing.assert_array_equal(got, want)


def test_generate_sampling_shapes_and_determinism():
    cfg = GPTConfig(vocab_size=61, hidden_size=32, num_layers=1,
                    num_heads=4, max_seq_len=24, sp=False,
                    position="learned", activation="gelu")
    _, state = _build_state(cfg, seed=9)
    prompt = np.array([[3, 1, 4]], np.int32)
    a = np.asarray(generate(state, cfg, prompt, 8, temperature=0.8,
                            top_k=10, seed=42))
    b = np.asarray(generate(state, cfg, prompt, 8, temperature=0.8,
                            top_k=10, seed=42))
    assert a.shape == (1, 11)
    np.testing.assert_array_equal(a, b)
    assert (a[:, :3] == prompt).all()
    assert (a < cfg.vocab_size).all() and (a >= 0).all()


def test_generate_rejects_overflow():
    cfg = GPTConfig(vocab_size=31, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=8, sp=False,
                    position="learned")
    _, state = _build_state(cfg, seed=1)
    with pytest.raises(ValueError, match="exceeds"):
        generate(state, cfg, np.zeros((1, 6), np.int32), 4)


def test_generate_moe_matches_full_forward():
    """MoE decode (dense top-k expert mix) vs the training stack's
    full forward; high capacity_factor so training drops no tokens."""
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=32, sp=False, dropout=0.0,
                    position="learned", activation="gelu",
                    num_experts=4, moe_top_k=2, moe_capacity_factor=8.0)
    model, state = _build_state(cfg, seed=5)
    prompt = np.array([[5, 17, 2, 9]], np.int32)
    want = _oracle_greedy(model, prompt, 5)
    got = np.asarray(generate(state, cfg, prompt, 5, temperature=0.0))
    np.testing.assert_array_equal(got, want)


def test_dispatched_prefill_matches_dense_all_experts():
    """Capacity-free blocked group-GEMM prefill == the dense all-experts
    mix, bit-for-bit routing (shared _moe_route) and allclose outputs —
    while touching ~k/E of the expert FLOPs (reference moe_layer.py:45
    dispatch without its capacity drop)."""
    import jax
    import jax.numpy as jnp
    from hetu_tpu.models.generate import (_moe_act, _moe_mlp_dispatched,
                                          _moe_route)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64, sp=False,
                    position="learned", activation="gelu",
                    num_experts=8, moe_top_k=2)
    rng = np.random.RandomState(3)
    b, s, d, f, E = 2, 24, 32, 64, 8
    x = jnp.asarray(rng.randn(b, s, d), jnp.float32)
    wg = jnp.asarray(rng.randn(E, d), jnp.float32)
    w1 = jnp.asarray(rng.randn(E, d, f) * 0.1, jnp.float32)
    b1 = jnp.asarray(rng.randn(E, 1, f) * 0.1, jnp.float32)
    w2 = jnp.asarray(rng.randn(E, f, d) * 0.1, jnp.float32)
    b2 = jnp.asarray(rng.randn(E, 1, d) * 0.1, jnp.float32)

    # dense all-experts oracle (the old prefill path)
    gates, topv, topi = _moe_route(cfg, wg, x)
    weights = jnp.zeros_like(gates)
    for j in range(cfg.moe_top_k):
        weights = weights + topv[..., j:j + 1] * jax.nn.one_hot(
            topi[..., j], E, dtype=gates.dtype)
    act = _moe_act(cfg)
    h = act(jnp.einsum("bsd,edf->bsef", x, w1) + b1[:, 0])
    y = jnp.einsum("bsef,efd->bsed", h, w2) + b2[:, 0]
    want = jnp.einsum("bse,bsed->bsd", weights, y)

    got = _moe_mlp_dispatched(cfg, x, wg, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # works under jit too (static-shape dispatch arithmetic)
    got_jit = jax.jit(lambda x: _moe_mlp_dispatched(
        cfg, x, wg, w1, b1, w2, b2))(x)
    np.testing.assert_allclose(np.asarray(got_jit), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_dispatched_prefill_flops_bound():
    """The padded assignment count (what the group-GEMM multiplies) is
    bounded by T*k + E*B — i.e. prefill FLOPs scale with top-k, not E."""
    from hetu_tpu.models.generate import _moe_block_size
    T, k, E = 4096, 2, 64
    B = _moe_block_size(T * k, E)
    n_pad_max = T * k + E * (B - 1) + B
    dense_cost = T * E          # all-experts path multiplies T*E blocks
    assert n_pad_max < 0.1 * dense_cost * k, \
        (n_pad_max, dense_cost)


def test_generate_zero_tokens_returns_prompt():
    cfg = GPTConfig(vocab_size=31, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=8, sp=False,
                    position="learned")
    _, state = _build_state(cfg, seed=2)
    prompt = np.array([[1, 2]], np.int32)
    np.testing.assert_array_equal(
        np.asarray(generate(state, cfg, prompt, 0)), prompt)
    with pytest.raises(ValueError, match=">= 0"):
        generate(state, cfg, prompt, -1)


def test_training_mlp_respects_silu_activation():
    """ParallelMLP must apply the CONFIGURED activation (silu configs
    used to silently train with gelu)."""
    import hetu_tpu.ops as ops_mod
    cfg = GPTConfig(vocab_size=31, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=8, sp=False,
                    position="learned", activation="silu")
    ht.set_seed(4)
    with ht.graph("eager", create_new=True):
        from hetu_tpu.models.gpt import ParallelMLP
        mlp = ParallelMLP(cfg)
        x = np.random.RandomState(0).randn(2, 4, 16).astype(np.float32)
        got = np.asarray(mlp(x).get_data())
        w_up = np.asarray(mlp.up.weight.get_data())
        b_up = np.asarray(mlp.up.bias.get_data()) if mlp.up.bias is not None \
            else 0.0
        w_dn = np.asarray(mlp.down.weight.get_data())
        b_dn = np.asarray(mlp.down.bias.get_data()) \
            if mlp.down.bias is not None else 0.0
        h = x @ w_up.T + b_up
        want = (h * (1.0 / (1.0 + np.exp(-h)))) @ w_dn.T + b_dn  # silu
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_generate_compile_cache_reuse():
    """Repeated generate() calls with the same shapes/config must reuse
    one compiled program (params/prompt/seed flow as arguments)."""
    import importlib
    gen_mod = importlib.import_module("hetu_tpu.models.generate")
    cfg = GPTConfig(vocab_size=41, hidden_size=16, num_layers=1,
                    num_heads=2, max_seq_len=16, sp=False,
                    position="learned")
    _, state = _build_state(cfg, seed=6)
    gen_mod._DECODE_CACHE.clear()
    prompt = np.array([[1, 2, 3]], np.int32)
    a = np.asarray(generate(state, cfg, prompt, 4, seed=0))
    n_after_first = len(gen_mod._DECODE_CACHE)
    b = np.asarray(generate(state, cfg, prompt + 1, 4, seed=1))
    assert n_after_first == 1
    assert len(gen_mod._DECODE_CACHE) == 1   # second call hit the cache
    assert a.shape == b.shape == (1, 7)


def test_generate_moe_with_tensor_name_keys():
    """MoE decode must also resolve tensor-name state keys
    ('h0.moe.gate.wg', no 'mlp.' segment — the checkpoint-file naming)."""
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=32, sp=False, dropout=0.0,
                    position="learned", activation="gelu",
                    num_experts=4, moe_top_k=2, moe_capacity_factor=8.0)
    model, state = _build_state(cfg, seed=5)
    renamed = {k.replace(".mlp.moe.", ".moe."): v for k, v in state.items()}
    prompt = np.array([[5, 17, 2, 9]], np.int32)
    want = np.asarray(generate(state, cfg, prompt, 4, temperature=0.0))
    got = np.asarray(generate(renamed, cfg, prompt, 4, temperature=0.0))
    np.testing.assert_array_equal(got, want)


def test_generate_moe_bf16_matches_full_forward():
    """bf16 MoE decode: gate logits in model dtype (a full-f32 gate
    matmul could resolve near-ties differently than training)."""
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=32, sp=False, dropout=0.0,
                    position="learned", activation="gelu",
                    dtype="bfloat16",
                    num_experts=4, moe_top_k=2, moe_capacity_factor=8.0)
    model, state = _build_state(cfg, seed=8)
    prompt = np.array([[5, 17, 2, 9]], np.int32)
    want = _oracle_greedy(model, prompt, 4)
    got = np.asarray(generate(state, cfg, prompt, 4, temperature=0.0))
    np.testing.assert_array_equal(got, want)
