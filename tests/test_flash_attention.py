"""Pallas flash-attention kernel tests (interpret mode on CPU).

Oracle: the jnp reference SDPA (itself validated against torch in
test_ops.py::TestAttention).  Covers fwd/bwd, causal/full, packed
segment-ids (varlen), LSE output, GQA-shaped inputs, odd block sizes.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hetu_tpu.ops.attention import sdpa_reference
from hetu_tpu.ops.pallas.flash_attention import (flash_attention,
                                                flash_attention_with_lse)


def _mk(b=2, s=128, h=2, d=64, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, s, h, d), dtype)
                 for _ in range(3))


class TestFlashForward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = _mk()
        out = flash_attention(q, k, v, causal=causal)
        ref = sdpa_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_odd_seq_blocks(self):
        # seq 96 -> block sizes fall back to smaller powers of two
        q, k, v = _mk(s=96)
        out = flash_attention(q, k, v, causal=True)
        ref = sdpa_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_segment_ids_packing(self):
        q, k, v = _mk()
        b, s = q.shape[0], q.shape[1]
        segs = jnp.asarray(np.repeat(np.arange(4), s // 4)[None].repeat(b, 0))
        out = flash_attention(q, k, v, causal=True, segment_ids=segs)
        ref = sdpa_reference(q, k, v, causal=True, segment_ids=segs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_fully_masked_rows_empty_contract(self):
        """Rows that see no valid kv position (ring varlen padding, -1 seg
        ids everywhere) must emit out=0, lse=-inf — the contract
        ring_attention's _merge/backward guards rely on — in BOTH the
        single-kv-block fast path and the multi-block accumulate path."""
        for s in (128, 384):  # 128 -> single-kv fast path; 384 -> 3 blocks
            # of 128 through the accumulate/_finalize path
            q, k, v = _mk(s=s)
            b = q.shape[0]
            # first half of each batch row is a real doc, second half pad
            seg = np.zeros((b, s), np.int32)
            seg[:, s // 2:] = -1
            # pad ids differ between q and kv so pad rows match NOTHING
            # (with shared ids, pad attends pad; use distinct sentinel)
            segs = jnp.asarray(seg)
            kv_seg = jnp.asarray(np.where(seg < 0, -2, seg))
            out, lse = flash_attention_with_lse(
                q, k, v, causal=False, segment_ids=(segs, kv_seg))
            out = np.asarray(out)
            lse = np.asarray(lse)
            assert np.all(out[:, s // 2:] == 0.0), f"s={s}"
            assert np.all(np.isneginf(lse[:, :, s // 2:])), f"s={s}"
            # valid rows still match the reference on valid kv
            ref = sdpa_reference(q[:, : s // 2], k[:, : s // 2],
                                 v[:, : s // 2], causal=False)
            np.testing.assert_allclose(out[:, : s // 2], np.asarray(ref),
                                       rtol=1e-4, atol=1e-4)

    def test_lse(self):
        q, k, v = _mk()
        out, lse = flash_attention_with_lse(q, k, v, causal=True)
        assert lse.shape == (2, 2, 128)
        # oracle LSE from dense logits
        d = q.shape[-1]
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(1.0 * d)
        qi = jnp.arange(128)[:, None]
        ki = jnp.arange(128)[None, :]
        logits = jnp.where(ki <= qi, logits, -jnp.inf)
        ref_lse = jax.nn.logsumexp(logits, axis=-1)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.slow
class TestFlashBackward:
    def test_grads_match_reference(self):
        q, k, v = _mk()

        def loss_fa(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(sdpa_reference(q, k, v, causal=True) ** 2)

        g1 = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3,
                                       err_msg=f"d{name}")

    def test_grads_with_segments(self):
        q, k, v = _mk(s=64)
        segs = jnp.asarray(np.repeat(np.arange(2), 32)[None].repeat(2, 0))

        def loss_fa(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=True, segment_ids=segs) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(
                sdpa_reference(q, k, v, causal=True, segment_ids=segs) ** 2)

        g1 = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3,
                                       err_msg=f"d{name}")


@pytest.mark.slow
class TestSplitBackwardPath:
    """The long-sequence fallback (split dq / dkv kernels) must stay
    correct even though short tests route to the fused kernel."""

    def test_split_path_matches_reference(self, monkeypatch):
        from hetu_tpu.ops.pallas import flash_attention as fa
        monkeypatch.setattr(fa, "_FUSED_DKV_VMEM_BYTES", 0)  # force split
        q, k, v = _mk()
        segs = jnp.asarray(
            np.repeat(np.arange(2), 64)[None].repeat(2, 0))

        def loss_fa(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, segment_ids=segs) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(sdpa_reference(
                q, k, v, causal=True, segment_ids=segs) ** 2)

        g1 = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3,
                                       err_msg=f"d{name}")

    def test_fused_and_split_agree(self, monkeypatch):
        from hetu_tpu.ops.pallas import flash_attention as fa
        q, k, v = _mk(s=256)

        def grads(q, k, v):
            return jax.grad(lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, causal=True) ** 2),
                argnums=(0, 1, 2))(q, k, v)

        g_fused = grads(q, k, v)
        monkeypatch.setattr(fa, "_FUSED_DKV_VMEM_BYTES", 0)
        g_split = grads(q, k, v)
        for name, a, b in zip("qkv", g_fused, g_split):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"d{name}")


class TestReviewRegressions:
    def test_segment_ids_under_jit(self):
        """segment_ids must be a traced arg (works inside jit/graph step)."""
        q, k, v = _mk(s=64)
        segs = jnp.asarray(np.repeat(np.arange(2), 32)[None].repeat(2, 0))
        f = jax.jit(lambda q, k, v, s: flash_attention(
            q, k, v, causal=True, segment_ids=s))
        out = f(q, k, v, segs)
        ref = sdpa_reference(q, k, v, causal=True, segment_ids=segs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)
        # and grads under jit
        g = jax.jit(jax.grad(lambda q, k, v, s: jnp.sum(
            flash_attention(q, k, v, segment_ids=s) ** 2)))(q, k, v, segs)
        assert np.all(np.isfinite(np.asarray(g)))

    def test_irregular_seq_len(self):
        """Sequences with no power-of-two block fall back to one full block."""
        q, k, v = _mk(s=72)
        out = flash_attention(q, k, v, causal=True)
        ref = sdpa_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_bfloat16(self):
        q, k, v = _mk(s=128, dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, causal=True)
        ref = sdpa_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=5e-2, atol=5e-2)
