"""Metrics recorder (v1 metrics.py capability) + Adafactor optimizer."""
import numpy as np
import pytest

import hetu_tpu as ht
from hetu_tpu import ops, optim
from hetu_tpu.utils.metrics import Metrics, load_jsonl


class TestMetrics:
    def test_log_smooth_summary_roundtrip(self, tmp_path):
        p = str(tmp_path / "run.jsonl")
        with Metrics(log_file=p, window=3) as rec:
            for s in range(10):
                rec.log(s, loss=float(10 - s), lr=0.1)
            assert rec.last("loss") == 1.0
            assert rec.smoothed("loss") == pytest.approx(2.0)  # mean(3,2,1)
            summ = rec.summary()
            assert summ["loss"]["count"] == 10
            assert summ["loss"]["min"] == 1.0 and summ["loss"]["max"] == 10.0
        rows = load_jsonl(p)
        assert len(rows) == 10 and rows[-1]["loss"] == 1.0

    def test_csv_export_with_sparse_keys(self, tmp_path):
        rec = Metrics()
        rec.log(0, loss=2.0)
        rec.log(1, loss=1.5, val_loss=1.8)
        csv = str(tmp_path / "m.csv")
        rec.to_csv(csv)
        lines = open(csv).read().strip().splitlines()
        assert lines[0] == "step,loss,val_loss"
        assert lines[1].startswith("0,2.0,")   # missing val_loss -> blank
        assert lines[1].endswith(",")


class TestAdafactor:
    def _data(self):
        rng = np.random.RandomState(0)
        X = rng.randn(16, 8).astype(np.float32)
        Y = rng.randint(0, 4, (16,)).astype(np.int32)
        return X, Y

    def test_matches_raw_optax(self):
        import jax
        import jax.numpy as jnp
        import optax
        X, Y = self._data()
        W0 = np.full((4, 8), 0.05, np.float32)

        # ours, through the graph machinery
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (16, 8), name="x")
            y = ht.placeholder("int32", (16,), name="y")
            w = ht.parameter(W0.copy(), name="w")
            loss = ops.softmax_cross_entropy(
                ops.matmul(x, w, trans_b=True), y)
            train_op = optim.AdafactorOptimizer(lr=0.05).minimize(loss)
            for _ in range(5):
                g.run(loss, [loss, train_op], {x: X, y: Y})
            ours = np.asarray(g.get_tensor_value(w))

        # oracle: raw optax on the same math
        def loss_fn(w):
            logits = jnp.asarray(X) @ w.T
            lp = jax.nn.log_softmax(logits, -1)
            return -jnp.mean(jnp.take_along_axis(
                lp, jnp.asarray(Y)[:, None], 1))
        tx = optax.adafactor(learning_rate=0.05)
        w_ref = jnp.asarray(W0)
        st = tx.init(w_ref)
        for _ in range(5):
            grad = jax.grad(loss_fn)(w_ref)
            upd, st = tx.update(grad, st, w_ref)
            w_ref = w_ref + upd
        np.testing.assert_allclose(ours, np.asarray(w_ref),
                                   rtol=1e-5, atol=1e-6)

    def test_factored_state_is_small(self):
        """The point of Adafactor: O(rows+cols) second moments."""
        import jax
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (4, 256), name="x")
            w = ht.parameter(np.zeros((256, 256), np.float32), name="w")
            loss = ops.reduce_mean(ops.matmul(x, w) ** 2.0)
            opt = optim.AdafactorOptimizer(lr=0.01)
            train_op = opt.minimize(loss)
            g.run(loss, [loss, train_op],
                  {x: np.ones((4, 256), np.float32)})
            state_bytes = sum(
                a.size * a.dtype.itemsize
                for a in jax.tree_util.tree_leaves(opt._state)
                if hasattr(a, "size"))
            # full Adam m+v would be 2*256*256*4 = 512KB; factored is KBs
            assert state_bytes < 64 * 1024, state_bytes

    def test_with_schedule_and_clip_trains(self):
        X, Y = self._data()
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (16, 8), name="x")
            y = ht.placeholder("int32", (16,), name="y")
            w = ht.parameter(np.full((4, 8), 0.05, np.float32), name="w")
            loss = ops.softmax_cross_entropy(
                ops.matmul(x, w, trans_b=True), y)
            sched = optim.cosine_schedule(0.1, 2, 50)
            opt = optim.AdafactorOptimizer(lr=sched, max_grad_norm=1.0)
            train_op = opt.minimize(loss)
            losses = [float(np.asarray(
                g.run(loss, [loss, train_op], {x: X, y: Y})[0]))
                for _ in range(10)]
            assert losses[-1] < losses[0]

    def test_checkpoint_roundtrip(self, tmp_path):
        """Adafactor's structured optax state must survive
        save_checkpoint/load_checkpoint (leaf-serialized)."""
        import jax
        from hetu_tpu.models import GPTConfig, GPTLMHeadModel
        from hetu_tpu.utils.checkpoint import (save_checkpoint,
                                               load_checkpoint)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=4, max_seq_len=16, dropout=0.0,
                        sp=False)
        rng = np.random.RandomState(0)
        I = rng.randint(0, 64, (2, 16)).astype(np.int32)

        def build(seed):
            ht.set_seed(seed)
            cm = ht.graph("define_and_run", create_new=True)
            g = cm.__enter__()
            g._cm = cm  # keep the context manager for exit
            model = GPTLMHeadModel(cfg)
            ids = ht.placeholder("int32", (2, 16), name="ids")
            lbl = ht.placeholder("int32", (2, 16), name="lbl")
            loss = model(ids, lbl)
            opt = optim.AdafactorOptimizer(lr=0.02)
            op = opt.minimize(loss)
            feed = {ids: I, lbl: np.roll(I, -1, 1)}
            return g, model, opt, loss, op, feed

        g, model, opt, loss, op, feed = build(3)
        for _ in range(3):
            g.run(loss, [loss, op], feed)
        d = str(tmp_path / "af")
        save_checkpoint(model, opt, d, step=3)
        ref = [float(np.asarray(g.run(loss, [loss, op], feed)[0]))
               for _ in range(2)]
        g._cm.__exit__(None, None, None)

        # fresh graph/optimizer: restore and continue — trajectory must
        # match the uninterrupted run (state really round-tripped)
        g2, model2, opt2, loss2, op2, feed2 = build(99)
        load_checkpoint(model2, opt2, d)
        got = [float(np.asarray(g2.run(loss2, [loss2, op2], feed2)[0]))
               for _ in range(2)]
        g2._cm.__exit__(None, None, None)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_restore_into_wrong_optimizer_raises(self, tmp_path):
        """@@leaf state restored into an optimizer without that slot
        must fail loudly, not silently reinitialize."""
        import pytest
        from hetu_tpu.models import GPTConfig, GPTLMHeadModel
        from hetu_tpu.utils.checkpoint import (save_checkpoint,
                                               load_checkpoint)
        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=1,
                        num_heads=2, max_seq_len=8, dropout=0.0, sp=False)
        I = np.random.RandomState(0).randint(0, 32, (2, 8)).astype(np.int32)
        with ht.graph("define_and_run", create_new=True) as g:
            ht.set_seed(1)
            model = GPTLMHeadModel(cfg)
            ids = ht.placeholder("int32", (2, 8), name="ids")
            lbl = ht.placeholder("int32", (2, 8), name="lbl")
            loss = model(ids, lbl)
            opt = optim.AdafactorOptimizer(lr=0.02)
            op = opt.minimize(loss)
            g.run(loss, [loss, op], {ids: I, lbl: np.roll(I, -1, 1)})
            d = str(tmp_path / "wrong")
            save_checkpoint(model, opt, d, step=1)
        with ht.graph("define_and_run", create_new=True) as g2:
            ht.set_seed(1)
            model2 = GPTLMHeadModel(cfg)
            ids = ht.placeholder("int32", (2, 8), name="ids")
            lbl = ht.placeholder("int32", (2, 8), name="lbl")
            loss2 = model2(ids, lbl)
            opt2 = optim.AdamOptimizer(lr=1e-3)   # mismatched type
            op2 = opt2.minimize(loss2)
            load_checkpoint(model2, opt2, d)
            with pytest.raises(ValueError, match="different optimizer"):
                g2.run(loss2, [loss2, op2], {ids: I, lbl: np.roll(I, -1, 1)})

    def test_load_then_save_without_step_preserves_state(self, tmp_path):
        """Checkpoint copy workflow: load -> save with NO training step
        in between must not drop the structured (Adafactor) state."""
        from hetu_tpu.models import GPTConfig, GPTLMHeadModel
        from hetu_tpu.utils.checkpoint import (save_checkpoint,
                                               load_checkpoint)
        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=1,
                        num_heads=2, max_seq_len=8, dropout=0.0, sp=False)
        I = np.random.RandomState(0).randint(0, 32, (2, 8)).astype(np.int32)
        with ht.graph("define_and_run", create_new=True) as g:
            ht.set_seed(1)
            model = GPTLMHeadModel(cfg)
            ids = ht.placeholder("int32", (2, 8), name="ids")
            lbl = ht.placeholder("int32", (2, 8), name="lbl")
            loss = model(ids, lbl)
            opt = optim.AdafactorOptimizer(lr=0.02)
            op = opt.minimize(loss)
            for _ in range(2):
                g.run(loss, [loss, op], {ids: I, lbl: np.roll(I, -1, 1)})
            d1 = str(tmp_path / "a")
            save_checkpoint(model, opt, d1, step=2)
            ref = [float(np.asarray(
                g.run(loss, [loss, op], {ids: I, lbl: np.roll(I, -1, 1)})[0]))
                for _ in range(2)]
        with ht.graph("define_and_run", create_new=True) as g2:
            ht.set_seed(1)
            model2 = GPTLMHeadModel(cfg)
            ids = ht.placeholder("int32", (2, 8), name="ids")
            lbl = ht.placeholder("int32", (2, 8), name="lbl")
            loss2 = model2(ids, lbl)
            opt2 = optim.AdafactorOptimizer(lr=0.02)
            op2 = opt2.minimize(loss2)
            load_checkpoint(model2, opt2, d1)
            d2 = str(tmp_path / "b")
            save_checkpoint(model2, opt2, d2, step=2)  # copy, no step
        with ht.graph("define_and_run", create_new=True) as g3:
            ht.set_seed(1)
            model3 = GPTLMHeadModel(cfg)
            ids = ht.placeholder("int32", (2, 8), name="ids")
            lbl = ht.placeholder("int32", (2, 8), name="lbl")
            loss3 = model3(ids, lbl)
            opt3 = optim.AdafactorOptimizer(lr=0.02)
            op3 = opt3.minimize(loss3)
            load_checkpoint(model3, opt3, d2)
            got = [float(np.asarray(
                g3.run(loss3, [loss3, op3],
                       {ids: I, lbl: np.roll(I, -1, 1)})[0]))
                for _ in range(2)]
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_load_over_trained_optimizer_then_save(self, tmp_path):
        """load_checkpoint onto an optimizer that ALREADY trained, then
        save with no step: the LOADED state (not the stale pre-load
        state) must be what gets written."""
        from hetu_tpu.models import GPTConfig, GPTLMHeadModel
        from hetu_tpu.utils.checkpoint import (save_checkpoint,
                                               load_checkpoint)
        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=1,
                        num_heads=2, max_seq_len=8, dropout=0.0, sp=False)
        I = np.random.RandomState(0).randint(0, 32, (2, 8)).astype(np.int32)

        def build(seed):
            ht.set_seed(seed)
            cm = ht.graph("define_and_run", create_new=True)
            g = cm.__enter__()
            g._cm = cm
            model = GPTLMHeadModel(cfg)
            ids = ht.placeholder("int32", (2, 8), name="ids")
            lbl = ht.placeholder("int32", (2, 8), name="lbl")
            loss = model(ids, lbl)
            opt = optim.AdafactorOptimizer(lr=0.02)
            op = opt.minimize(loss)
            feed = {ids: I, lbl: np.roll(I, -1, 1)}
            return g, model, opt, loss, op, feed

        g, model, opt, loss, op, feed = build(3)
        for _ in range(3):
            g.run(loss, [loss, op], feed)
        d1 = str(tmp_path / "src")
        save_checkpoint(model, opt, d1, step=3)
        ref = [float(np.asarray(g.run(loss, [loss, op], feed)[0]))
               for _ in range(2)]
        g._cm.__exit__(None, None, None)

        # second run: train DIFFERENT steps first, then load d1 and
        # immediately re-save — the copy must carry d1's state
        g2, model2, opt2, loss2, op2, feed2 = build(77)
        g2.run(loss2, [loss2, op2], feed2)   # optimizer now has state
        load_checkpoint(model2, opt2, d1)
        d2 = str(tmp_path / "copy")
        save_checkpoint(model2, opt2, d2, step=3)
        g2._cm.__exit__(None, None, None)

        g3, model3, opt3, loss3, op3, feed3 = build(55)
        load_checkpoint(model3, opt3, d2)
        got = [float(np.asarray(g3.run(loss3, [loss3, op3], feed3)[0]))
               for _ in range(2)]
        g3._cm.__exit__(None, None, None)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_hot_switch_carries_optax_state(self, devices8):
        """graph.switch_strategy with Adafactor: the structured optax
        state must follow the params onto the new mesh and training must
        continue the same trajectory as an unswitched run."""
        from jax.sharding import PartitionSpec as P
        from hetu_tpu.models import GPTConfig, GPTLMHeadModel
        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=1,
                        num_heads=2, max_seq_len=8, dropout=0.0, sp=False)
        I = np.random.RandomState(0).randint(0, 32, (4, 8)).astype(np.int32)

        def run(switch_at=None, steps=6):
            ht.set_seed(11)
            mesh = ht.create_mesh({"dp": 4, "tp": 2}, devices8)
            with ht.graph("define_and_run", create_new=True,
                          mesh=mesh) as g:
                model = GPTLMHeadModel(cfg)
                ids = ht.parallel_placeholder("int32", (4, 8),
                                              pspec=P("dp", None),
                                              name="ids")
                lbl = ht.parallel_placeholder("int32", (4, 8),
                                              pspec=P("dp", None),
                                              name="lbl")
                loss = model(ids, lbl)
                opt = optim.AdafactorOptimizer(lr=0.02, momentum=0.9)
                op = opt.minimize(loss)
                feed = {ids: I, lbl: np.roll(I, -1, 1)}
                out = []
                for s in range(steps):
                    if s == switch_at:
                        g.switch_strategy(
                            ht.create_mesh({"dp": 2, "tp": 4}, devices8),
                            optimizer=opt)
                    out.append(float(np.asarray(
                        g.run(loss, [loss, op], feed)[0])))
                return out

        # momentum=0.9 gives the optax state param-shaped leaves, which
        # must follow their params' shardings on switch (not replicate)
        base = run(switch_at=None)
        switched = run(switch_at=3)
        np.testing.assert_allclose(switched, base, rtol=2e-4, atol=1e-5)
