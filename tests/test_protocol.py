"""Serving-protocol verifier tests (DESIGN.md §23).

Covers the four layers of ISSUE 18's tentpole:

* the lifecycle state machines (page / request / fence) over hand-built
  minimal event streams — clean streams replay clean, each violation
  class fires exactly once with provenance and a subtrace;
* the typed event stream + the four lifecycle lint rules through the
  standard ``AnalysisContext`` idiom (seeded fire-once tests, like every
  other rule in tests/test_analysis.py);
* mutation tests: ONE recorded clean chaos fuzz trace, ~8 seeded
  single-event mutations (drop a free, duplicate an adopt, decrement a
  refcount, regress an epoch, stage-to-host without evict, write
  post-finish, ...) — each flagged EXACTLY once with the right rule and
  provenance;
* the bounded interleaving explorer: the clean model is violation-free
  over an exhaustively-explored config, and each seeded interaction-bug
  class (including the real autoscaler drain-vs-inflight-handoff bug
  this PR fixes) is FOUND and attributed to the right rule;
* the vacuity meta-test over :data:`TRACE_RULE_EVENT_KINDS`: every
  trace-replay rule's input vocabulary actually occurs in the frozen
  gate executables' traces (ANALYSIS_BASELINE.json ``protocol.kinds``)
  — a rule whose event kinds never appear is vacuously green.
"""
import json
import os

import pytest

from hetu_tpu.analysis import events as pe
from hetu_tpu.analysis.events import Event
from hetu_tpu.analysis.protocol import (
    RULE_FENCE, RULE_PAGE, RULE_REFCOUNT, RULE_REQUEST, ExploreConfig,
    FenceMachine, PageMachine, RequestMachine, explore, fuzz_trace,
    replay)
from hetu_tpu.analysis.rules import (TRACE_RULE_EVENT_KINDS,
                                     AnalysisContext, run_rules)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# explorer config small enough for tier-1 (exhausts in <1s) while still
# covering both replicas, a handoff, chaos, eviction and a drain
SMALL = ExploreConfig(n_requests=1, tokens_per_request=2, max_evicts=1)


def E(kind, key, step=0, epoch=None, prov="test", **attrs):
    return Event(kind=kind, key=key, step=step, epoch=epoch,
                 attrs=attrs, provenance=prov, seq=step)


# ---------------------------------------------------------------------------
# lifecycle state machines over hand-built streams
# ---------------------------------------------------------------------------


class TestMachines:
    def test_clean_page_lifecycle_replays_clean(self):
        evs = [E(pe.PAGE_ALLOC, "p1", 0, page=1),
               E(pe.PAGE_CACHE, "p1", 1, page=1),
               E(pe.PAGE_SHARE, "p1", 2, page=1),
               E(pe.PAGE_UNSHARE, "p1", 3, page=1),
               E(pe.PAGE_UNCACHE, "p1", 4, page=1)]
        assert replay(evs) == []

    def test_clean_request_and_fence_lifecycle(self):
        evs = [E(pe.FENCE_BUMP, "r0", 0, epoch=1),
               E(pe.REQ_QUEUED, "req:1", 1),
               E(pe.REQ_ADMIT, "req:1", 2),
               E(pe.REQ_WRITE, "req:1", 3, tap_step=0),
               E(pe.REQ_PREEMPT, "req:1", 4),
               E(pe.REQ_ADMIT, "req:1", 5),
               E(pe.REQ_STAGE, "req:1", 6, epoch=1),
               E(pe.REQ_ADOPT, "req:1", 7, epoch=1),
               E(pe.REQ_FINISH, "req:1", 8),
               E(pe.FENCE_COMPLETE, "r0", 9, epoch=1),
               E(pe.FENCE_BUMP, "r0", 10, epoch=2),
               E(pe.FENCE_STALE_DROP, "r0", 11, epoch=1)]
        assert replay(evs) == []

    def test_double_alloc_fires_once_with_subtrace(self):
        evs = [E(pe.PAGE_ALLOC, "p1", 0, page=1, prov="pool[0]"),
               E(pe.PAGE_ALLOC, "p1", 1, page=1, prov="pool[1]"),
               # poisoned subject: the cascade is suppressed
               E(pe.PAGE_ALLOC, "p1", 2, page=1, prov="pool[2]")]
        vs = replay(evs)
        assert len(vs) == 1
        assert vs[0].rule == RULE_PAGE
        assert vs[0].subject == "p1"
        assert vs[0].provenance == "pool[1]"
        assert "only a free page" in vs[0].message
        assert vs[0].subtrace and "pool[1]" in vs[0].format_subtrace()

    def test_trash_page_is_immutable(self):
        vs = replay([E(pe.PAGE_ALLOC, "p0", 0, page=0)])
        assert len(vs) == 1 and vs[0].rule == RULE_PAGE
        assert "trash" in vs[0].message

    def test_unshare_below_zero_is_refcount_leak(self):
        evs = [E(pe.PAGE_ALLOC, "p2", 0, page=2),
               E(pe.PAGE_CACHE, "p2", 1, page=2),
               E(pe.PAGE_UNSHARE, "p2", 2, page=2)]
        vs = replay(evs)
        assert len(vs) == 1 and vs[0].rule == RULE_REFCOUNT

    def test_terminal_open_share_is_refcount_leak(self):
        evs = [E(pe.PAGE_ALLOC, "p2", 0, page=2),
               E(pe.PAGE_CACHE, "p2", 1, page=2),
               E(pe.PAGE_SHARE, "p2", 2, page=2)]
        # live traces end mid-flight: non-strict replay is clean
        assert replay(evs, strict_terminal=False) == []
        vs = replay(evs)          # complete trace: conservation enforced
        assert len(vs) == 1 and vs[0].rule == RULE_REFCOUNT
        assert "ends the trace" in vs[0].message

    def test_fence_regression_and_stale_completion(self):
        vs = replay([E(pe.FENCE_BUMP, "r0", 0, epoch=2),
                     E(pe.FENCE_BUMP, "r0", 1, epoch=1)])
        assert len(vs) == 1 and vs[0].rule == RULE_FENCE
        assert "monotone" in vs[0].message
        vs2 = replay([E(pe.FENCE_BUMP, "r0", 0, epoch=2),
                      E(pe.FENCE_COMPLETE, "r0", 1, epoch=1)])
        assert len(vs2) == 1 and vs2[0].rule == RULE_FENCE
        assert "stale" in vs2[0].message

    def test_double_adopt_and_post_finish_write(self):
        evs = [E(pe.REQ_STAGE, "creq:1", 0, epoch=3),
               E(pe.REQ_ADOPT, "creq:1", 1, epoch=3),
               E(pe.REQ_ADOPT, "creq:1", 2, epoch=3)]
        vs = replay(evs)
        assert len(vs) == 1 and vs[0].rule == RULE_REQUEST
        assert "TWICE" in vs[0].message
        vs2 = replay([E(pe.REQ_FINISH, "req:1", 0),
                      E(pe.REQ_WRITE, "req:1", 1, tap_step=7)])
        assert len(vs2) == 1 and vs2[0].rule == RULE_REQUEST
        assert "AFTER" in vs2[0].message

    def test_machines_are_independent_instances(self):
        pm, rm, fm = PageMachine(), RequestMachine(), FenceMachine()
        for m in (pm, rm, fm):
            assert m.violations == []


# ---------------------------------------------------------------------------
# the four lifecycle rules through the AnalysisContext idiom
# ---------------------------------------------------------------------------


class TestLifecycleRules:
    def test_page_lifecycle_rule_fires_once_per_seed(self):
        # seeded: double alloc in the pool event log
        ctx = AnalysisContext(
            name="t_plc",
            serving={"pool_log": [(1, "alloc", 2), (2, "alloc", 2),
                                  (3, "alloc", 2)]})
        fired = run_rules(ctx, only=[RULE_PAGE])
        assert len(fired) == 1 and fired[0].severity == "error"
        assert fired[0].subject == "p2"
        assert "only a free page" in fired[0].message
        assert "subtrace" in fired[0].hint     # --explain payload
        assert fired[0].source.startswith("pool[")
        # clean log: silent
        ctx2 = AnalysisContext(
            name="t_plc2",
            serving={"pool_log": [(1, "alloc", 2), (2, "free", 2)]})
        assert not run_rules(ctx2, only=[RULE_PAGE])

    def test_request_lifecycle_rule_fires_once_per_seed(self):
        log = [{"ev": pe.REQ_QUEUED, "key": "req:1", "seq": 1},
               {"ev": pe.REQ_ADMIT, "key": "req:1", "seq": 2},
               {"ev": pe.REQ_FINISH, "key": "req:1", "seq": 3},
               {"ev": pe.REQ_FINISH, "key": "req:1", "seq": 4}]
        ctx = AnalysisContext(name="t_rlc", serving={"protocol": log})
        fired = run_rules(ctx, only=[RULE_REQUEST])
        assert len(fired) == 1
        assert "delivered twice" in fired[0].message
        assert fired[0].source.startswith("engine[")
        assert not run_rules(
            AnalysisContext(name="t_rlc2",
                            serving={"protocol": log[:3]}),
            only=[RULE_REQUEST])

    def test_fence_regression_rule_fires_once_per_seed(self):
        log = [{"ev": pe.FENCE_BUMP, "key": "r0", "seq": 1, "epoch": 2},
               {"ev": pe.FENCE_BUMP, "key": "r0", "seq": 2, "epoch": 1}]
        ctx = AnalysisContext(name="t_fr", meta={"protocol": log})
        fired = run_rules(ctx, only=[RULE_FENCE])
        assert len(fired) == 1 and "monotone" in fired[0].message
        assert fired[0].source.startswith("cluster[")
        assert not run_rules(
            AnalysisContext(name="t_fr2", meta={"protocol": log[:1]}),
            only=[RULE_FENCE])

    def test_refcount_leak_rule_fires_once_per_seed(self):
        ctx = AnalysisContext(
            name="t_rc",
            serving={"pool_log": [(1, "alloc", 3), (2, "cache", 3),
                                  (3, "unshare", 3), (4, "unshare", 3)]})
        fired = run_rules(ctx, only=[RULE_REFCOUNT])
        assert len(fired) == 1 and "negative" in fired[0].message
        # live trace ending with an open share: NOT flagged here
        # (terminal conservation belongs to complete traces — the
        # explorer and the fuzz gate)
        ctx2 = AnalysisContext(
            name="t_rc2",
            serving={"pool_log": [(1, "alloc", 3), (2, "cache", 3),
                                  (3, "share", 3)]})
        assert not run_rules(ctx2, only=[RULE_REFCOUNT])

    def test_one_replay_shared_across_the_four_rules(self):
        ctx = AnalysisContext(
            name="t_shared",
            serving={"pool_log": [(1, "alloc", 2), (2, "alloc", 2)]})
        fired = run_rules(ctx, only=[RULE_PAGE, RULE_REQUEST,
                                     RULE_FENCE, RULE_REFCOUNT])
        assert len(fired) == 1 and fired[0].rule == RULE_PAGE
        assert getattr(ctx, "_protocol_violations", None) is not None


# ---------------------------------------------------------------------------
# mutation tests: one recorded clean trace, single-event corruptions
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def clean_trace():
    ev = fuzz_trace(seed=0, n_events=300)
    assert len(ev) >= 250
    assert replay(ev) == [], "the recorded chaos trace must be clean"
    return ev


def _one(violations, rule):
    assert len(violations) == 1, \
        [f"{v.rule}({v.subject}): {v.message}" for v in violations]
    v = violations[0]
    assert v.rule == rule, (v.rule, rule, v.message)
    assert v.provenance, "violations must carry provenance"
    assert v.subtrace, "violations must carry the event subtrace"
    return v


class TestMutations:
    def test_drop_a_free(self, clean_trace):
        ev = clean_trace
        i = next(i for i, e in enumerate(ev)
                 if e.kind == pe.PAGE_FREE
                 and any(e2.kind == pe.PAGE_ALLOC and e2.key == e.key
                         for e2 in ev[i + 1:]))
        v = _one(replay(ev[:i] + ev[i + 1:]), RULE_PAGE)
        assert v.subject == ev[i].key
        assert "only a free page" in v.message
        assert v.provenance.startswith("fuzz[")

    def test_duplicate_a_free(self, clean_trace):
        ev = clean_trace
        i = next(i for i, e in enumerate(ev)
                 if e.kind == pe.PAGE_FREE)
        v = _one(replay(ev[:i + 1] + [ev[i]] + ev[i + 1:]), RULE_PAGE)
        assert v.subject == ev[i].key and "free of page" in v.message

    def test_duplicate_an_adopt(self, clean_trace):
        ev = clean_trace
        i = next(i for i, e in enumerate(ev)
                 if e.kind == pe.REQ_ADOPT)
        v = _one(replay(ev[:i + 1] + [ev[i]] + ev[i + 1:]),
                 RULE_REQUEST)
        assert v.subject == ev[i].key and "TWICE" in v.message

    def test_decrement_a_refcount(self, clean_trace):
        # one extra unshare at end of trace: the refcount it decrements
        # was already conserved to zero
        ev = clean_trace
        extra = next(e for e in ev if e.kind == pe.PAGE_UNSHARE)
        v = _one(replay(list(ev) + [extra]), RULE_REFCOUNT)
        assert v.subject == extra.key

    def test_regress_an_epoch(self, clean_trace):
        ev = list(clean_trace)
        bumps = {}
        for i, e in enumerate(ev):
            if e.kind == pe.FENCE_BUMP:
                bumps.setdefault(e.key, []).append(i)
        key, idxs = next((k, v) for k, v in bumps.items()
                         if len(v) >= 2)
        last, first = ev[idxs[-1]], ev[idxs[0]]
        ev[idxs[-1]] = Event(kind=last.kind, key=last.key,
                             step=last.step, epoch=first.epoch,
                             attrs=last.attrs,
                             provenance="mut[epoch-regress]",
                             seq=last.seq)
        v = _one(replay(ev), RULE_FENCE)
        assert v.subject == key and "monotone" in v.message
        assert v.provenance == "mut[epoch-regress]"

    def test_stage_to_host_without_evict(self, clean_trace):
        # a host-stage naming a page that was never cached (never went
        # through the evict path)
        bad = E(pe.HOST_STAGE, "hh:mut", step=len(clean_trace),
                prov="mut[host-stage]", page=1)
        v = _one(replay(list(clean_trace) + [bad]), RULE_PAGE)
        assert "only a cached page is staged" in v.message
        assert v.provenance == "mut[host-stage]"

    def test_refetch_without_stage(self, clean_trace):
        bad = E(pe.HOST_REFETCH, "hh:mut", step=len(clean_trace),
                prov="mut[refetch]")
        v = _one(replay(list(clean_trace) + [bad]), RULE_PAGE)
        assert "never staged" in v.message

    def test_write_post_finish(self, clean_trace):
        ev = clean_trace
        fin = next(e for e in ev if e.kind == pe.REQ_FINISH)
        bad = E(pe.REQ_WRITE, fin.key, step=len(ev),
                prov="mut[post-finish-write]", tap_step=999)
        v = _one(replay(list(ev) + [bad]), RULE_REQUEST)
        assert v.subject == fin.key and "AFTER" in v.message
        assert v.provenance == "mut[post-finish-write]"

    def test_duplicate_a_finish(self, clean_trace):
        ev = clean_trace
        i = next(i for i, e in enumerate(ev)
                 if e.kind == pe.REQ_FINISH)
        v = _one(replay(ev[:i + 1] + [ev[i]] + ev[i + 1:]),
                 RULE_REQUEST)
        assert "delivered twice" in v.message


# ---------------------------------------------------------------------------
# the bounded interleaving explorer
# ---------------------------------------------------------------------------


class TestExplorer:
    def test_clean_model_exhausts_with_zero_violations(self):
        res = explore(SMALL, stop_at_first=False)
        assert res.ok, [v.message for v in res.violations]
        # the memoized DAG count recovers the true path count — far
        # beyond what leaf-enumeration could visit in tier-1 time
        assert res.interleavings > 10_000
        assert res.states > 500
        assert res.events_checked > res.states
        assert res.max_depth > 10

    @pytest.mark.parametrize("bug,rule", [
        ("drain_inflight", RULE_FENCE),
        ("double_adopt", RULE_REQUEST),
        ("stale_accept", RULE_FENCE),
        ("free_shared", RULE_PAGE),
    ])
    def test_seeded_interaction_bugs_are_found(self, bug, rule):
        res = explore(bug=bug)          # default cfg, stop at first
        assert len(res.violations) == 1, \
            [f"{v.rule}: {v.message}" for v in res.violations]
        v = res.violations[0]
        assert v.rule == rule, (bug, v.rule, v.message)
        assert v.provenance.startswith("explore:")
        assert v.subtrace

    def test_fuzz_traces_replay_clean_across_seeds(self):
        for seed in (0, 1, 2):
            ev = fuzz_trace(seed=seed, n_events=300)
            assert len(ev) >= 250, (seed, len(ev))
            assert replay(ev) == [], seed

    def test_fuzz_trace_covers_the_vocabulary(self):
        kinds = set(pe.kind_counts(fuzz_trace(seed=0, n_events=300)))
        # every plane is represented: pages, host tier, requests,
        # adoption, fencing, wire, chaos
        for k in (pe.PAGE_ALLOC, pe.PAGE_FREE, pe.PAGE_SHARE,
                  pe.HOST_STAGE, pe.HOST_REFETCH, pe.REQ_ADMIT,
                  pe.REQ_ADOPT, pe.REQ_PREEMPT, pe.REQ_SHED,
                  pe.REQ_FINISH, pe.FENCE_BUMP, pe.FENCE_COMPLETE,
                  pe.WIRE_INJECT, pe.CHAOS_INJECT):
            assert k in kinds, k
        assert len(kinds) >= 18

    def test_fuzz_bug_flag_is_caught_by_replay(self):
        # the fuzz walk drives the SAME model as the explorer: a seeded
        # bug eventually corrupts the trace and strict replay flags it
        found = 0
        for seed in range(5):
            ev = fuzz_trace(seed=seed, n_events=300, bug="free_shared")
            if any(v.rule in (RULE_PAGE, RULE_REFCOUNT)
                   for v in replay(ev)):
                found += 1
        assert found >= 1

    @pytest.mark.slow
    def test_default_config_exhausts(self):
        # the full default bound (BENCH_PROTOCOL.json's headline run):
        # ~365k distinct states, tens of trillions of interleavings
        res = explore(stop_at_first=False)
        assert res.ok, [v.message for v in res.violations]
        assert res.states > 100_000
        assert res.interleavings > 10 ** 12


# ---------------------------------------------------------------------------
# vacuity meta-test: every trace rule sees real events in the gate
# ---------------------------------------------------------------------------


def _baseline_kind_union():
    path = os.path.join(REPO, "ANALYSIS_BASELINE.json")
    with open(path) as f:
        data = json.load(f)
    kinds = set()
    per_exe = {}
    for name, exe in data.get("executables", {}).items():
        got = set((exe.get("protocol") or {}).get("kinds", {}))
        per_exe[name] = got
        kinds |= got
    return kinds, per_exe


@pytest.mark.parametrize("rule_name",
                         sorted(TRACE_RULE_EVENT_KINDS))
def test_trace_rule_is_not_vacuous_over_gate_traces(rule_name):
    """Each trace rule's registered gate executables' frozen traces
    contain >= 1 event of a kind the rule inspects — otherwise the
    rule's green on the gate is vacuous (it never saw its input)."""
    kinds = TRACE_RULE_EVENT_KINDS[rule_name]
    if kinds is None:
        pytest.skip(f"{rule_name} replays a record plane (meta hook), "
                    f"not the event stream")
    seen, _ = _baseline_kind_union()
    assert seen, "baseline carries no protocol.kinds — re-freeze it"
    assert seen & set(kinds), \
        (f"{rule_name} inspects {kinds} but no gate executable's "
         f"frozen trace contains any of them — the rule is vacuous "
         f"over the gate")


def test_vacuity_registry_matches_rule_registry():
    from hetu_tpu.analysis.rules import RULES
    unknown = set(TRACE_RULE_EVENT_KINDS) - set(RULES)
    assert not unknown, f"registry names unregistered rules: {unknown}"
    for name, kinds in TRACE_RULE_EVENT_KINDS.items():
        if kinds is not None:
            assert kinds, name
            assert all(k in pe.ALL_KINDS for k in kinds), (name, kinds)


# ---------------------------------------------------------------------------
# tier-1 gate: explorer + fuzz ride the lint_graph marker
# ---------------------------------------------------------------------------


@pytest.mark.lint_graph
def test_protocol_gate_explorer_and_fuzz():
    """The tier-1 protocol gate (ISSUE 18): the bounded explorer
    exhausts a two-replica config with ZERO violations on the clean
    model, and a seeded ~300-event chaos fuzz trace replays through
    the lifecycle machines with strict terminal conservation.  The
    full default-config exhaustion lives in bench.py protocol_lint
    (BENCH_PROTOCOL.json)."""
    res = explore(SMALL, stop_at_first=False)
    assert res.ok, [f"{v.rule}: {v.message}" for v in res.violations]
    assert res.interleavings > 10_000
    ev = fuzz_trace(seed=0, n_events=300)
    assert len(ev) >= 250
    assert replay(ev) == []
