"""Define-and-run graph + training-loop tests (reference tests/test_model.py,
test_simple_model.py pattern: loss must decrease; optimizer parity vs torch).
"""
import numpy as np
import pytest
import torch

import hetu_tpu as ht
from hetu_tpu import nn, ops, optim


def _make_data(seed=0, n=32, d=8, classes=4):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    Y = rng.randint(0, classes, (n,))
    return X, Y


class TestEager:
    def test_eager_module(self):
        with ht.graph("eager", create_new=True):
            lin = nn.Linear(4, 2)
            x = np.ones((3, 4), np.float32)
            y = lin(x)
            w = lin.weight.numpy()
            b = lin.bias.numpy()
            np.testing.assert_allclose(y.numpy(), x @ w.T + b, rtol=1e-5)


class TestDefineAndRun:
    def test_training_loss_decreases(self):
        X, Y = _make_data()
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (32, 8), name="x")
            y = ht.placeholder("int32", (32,), name="y")
            model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(),
                                  nn.Linear(32, 4))
            loss = ops.softmax_cross_entropy(model(x), y)
            train_op = optim.AdamOptimizer(lr=0.03).minimize(loss)
            losses = [float(g.run(loss, [loss, train_op], {x: X, y: Y})[0])
                      for _ in range(25)]
        assert losses[-1] < losses[0] * 0.5, losses

    def test_micro_batches_match_full_batch(self):
        """num_micro_batches grad accumulation == one big batch (SGD)."""
        X, Y = _make_data(n=16)
        results = {}
        for nmb in (1, 4):
            with ht.graph("define_and_run", create_new=True) as g:
                np.random.seed(42)
                x = ht.placeholder("float32", (16, 8), name="x")
                y = ht.placeholder("int32", (16,), name="y")
                w = ht.parameter(np.full((4, 8), 0.1, np.float32), name="w")
                logits = ops.matmul(x, w, trans_b=True)
                loss = ops.softmax_cross_entropy(logits, y)
                train_op = optim.SGDOptimizer(lr=0.1).minimize(loss)
                for _ in range(3):
                    g.run(loss, [loss, train_op], {x: X, y: Y},
                          num_micro_batches=nmb)
                results[nmb] = np.asarray(g.get_tensor_value(w))
        np.testing.assert_allclose(results[1], results[4], rtol=1e-4,
                                   atol=1e-5)

    def test_run_level_grad_then_update(self):
        """RunLevel.GRAD accumulates without updating; UPDATE flushes
        (reference graph.h:29-35 run levels)."""
        X, Y = _make_data(n=16)
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (16, 8), name="x")
            y = ht.placeholder("int32", (16,), name="y")
            w = ht.parameter(np.full((4, 8), 0.1, np.float32), name="w")
            loss = ops.softmax_cross_entropy(ops.matmul(x, w, trans_b=True), y)
            train_op = optim.SGDOptimizer(lr=0.1).minimize(loss)
            w0 = np.asarray(g.get_tensor_value(w)).copy()
            g.run(loss, [loss, train_op], {x: X, y: Y}, run_level="grad")
            w1 = np.asarray(g.get_tensor_value(w))
            np.testing.assert_array_equal(w0, w1)  # no update yet
            g.run(loss, [loss, train_op], {x: X, y: Y}, run_level="update")
            w2 = np.asarray(g.get_tensor_value(w))
            assert not np.allclose(w0, w2)

    def test_plan_pool_caching(self):
        X, Y = _make_data(n=8)
        batch = ht.SymbolicDim("batch")
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (batch, 8), name="x")
            w = ht.parameter(np.eye(8, dtype=np.float32), name="w")
            out = ops.matmul(x, w)
            g.run([out], feed_dict={x: X})
            assert len(g._plan_pool) == 1
            g.run([out], feed_dict={x: X})
            assert len(g._plan_pool) == 1  # same plan reused
            g.run([out], feed_dict={x: X[:4]})  # different shape -> new plan
            assert len(g._plan_pool) == 2

    def test_feed_shape_mismatch_raises(self):
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (8, 4), name="x")
            out = ops.reduce_sum(x)
            with pytest.raises(ValueError, match="expected"):
                g.run([out], feed_dict={x: np.ones((8, 5), np.float32)})

    def test_symbolic_dim_arithmetic_dag(self):
        """IntSymbol-style arithmetic (reference core/symbol.h operator
        overloads): symbols compose into a lazily-evaluated DAG that
        tracks rebinding of its leaves."""
        seq = ht.SymbolicDim("seq")
        cp = ht.SymbolicDim("cp", 4)
        local = seq // cp
        doubled = 2 * local + 1
        assert not local.is_bound and not doubled.is_bound
        seq.set(256)
        assert local.get() == 64
        assert doubled.get() == 129
        seq.set(512)                       # leaf rebinding propagates
        assert local.get() == 128 and doubled.get() == 257
        assert (seq % 3).get() == 2
        assert (seq - 12).get() == 500
        # provisional override (graph.py binds unbound dims this way)
        e = ht.SymbolicDim("x") + 1
        assert not e.is_bound
        e.set(16)
        assert e.get() == 16 and e.is_bound
        e.clear_override()
        assert not e.is_bound
        assert "seq//cp" in local.name

    def test_symbolic_derived_in_placeholder_shape(self):
        """A derived dim works as a placeholder dim: binding the leaf
        from the feed shape sizes every dependent dimension."""
        seq = ht.SymbolicDim("seq")
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (2, seq, 4), name="x")
            y = ht.placeholder("float32", (2, seq // 2, 4), name="y")
            out = ops.concat([x, y], axis=1)
            for s in (4, 8):
                X = np.ones((2, s, 4), np.float32)
                Y = np.ones((2, s // 2, 4), np.float32)
                (val,) = g.run([out], feed_dict={x: X, y: Y})
                assert np.asarray(val).shape == (2, s + s // 2, 4)

    def test_symbolic_derived_feed_mismatch_raises(self):
        """A feed inconsistent with a derived dim's expression must raise
        rather than silently overriding the arithmetic."""
        seq = ht.SymbolicDim("seq")
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (2, seq, 4), name="x")
            y = ht.placeholder("float32", (2, seq // 2, 4), name="y")
            out = ops.concat([x, y], axis=1)
            X = np.ones((2, 8, 4), np.float32)
            bad = np.ones((2, 3, 4), np.float32)      # seq//2 == 4, not 3
            with pytest.raises(ValueError, match="derived dim"):
                g.run([out], feed_dict={x: X, y: bad})

    def test_symbolic_derived_leaf_not_fed(self):
        """Feeding only the derived-dim placeholder (its leaf bound by
        nothing but make_op's advisory 16) must work — the consistency
        check only fires when the leaves were bound by THIS feed pass."""
        seq = ht.SymbolicDim("seq")
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (2, seq, 4), name="x")
            y = ht.placeholder("float32", (2, seq // 2, 4), name="y")
            _ = ops.concat([x, y], axis=1)
            ysum = ops.reduce_sum(y)
            (val,) = g.run([ysum], feed_dict={y: np.ones((2, 4, 4),
                                                         np.float32)})
            assert float(np.asarray(val)) == 32.0

    def test_symbolic_derived_with_shape_buckets(self):
        """Independent bucket padding legitimately breaks dim arithmetic
        (x pads 10->12 while y pads 5->8): derived dims fall back to
        provisional bindings instead of rejecting the feed."""
        seq = ht.SymbolicDim("seq")
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (2, seq, 4), name="x")
            y = ht.placeholder("float32", (2, seq // 2, 4), name="y")
            xs = ops.reduce_sum(x)
            ys = ops.reduce_sum(y)
            g.set_shape_buckets(4)
            X = np.ones((2, 10, 4), np.float32)
            Y = np.ones((2, 5, 4), np.float32)
            xv, yv = g.run([xs, ys], feed_dict={x: X, y: Y})
            # pads are zero so the sums see only real elements
            assert float(np.asarray(xv)) == 80.0
            assert float(np.asarray(yv)) == 40.0

    def test_symbolic_nested_derived_stale_intermediate(self):
        """A nested derived dim must evaluate through FRESH intermediate
        values: make_op's advisory binding on the intermediate (here
        half=16 while seq is unbound) must not poison a later consistent
        feed of (seq, quarter)."""
        seq = ht.SymbolicDim("seq")
        half = seq // 2
        quarter = half // 2
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (2, seq, 4), name="x")
            y = ht.placeholder("float32", (2, half, 4), name="y")
            z = ht.placeholder("float32", (2, quarter, 4), name="z")
            _ = ops.reduce_sum(y)       # make_op advisory-binds half
            out = ops.concat([x, z], axis=1)
            X = np.ones((2, 64, 4), np.float32)
            Z = np.ones((2, 16, 4), np.float32)   # 64//2//2 == 16: valid
            (val,) = g.run([out], feed_dict={x: X, z: Z})
            assert np.asarray(val).shape == (2, 80, 4)

    def test_symbolic_derived_conflicting_feeds_raise(self):
        """Two placeholders sharing an unbound derived dim must agree —
        last-feed-wins silent override is exactly what the check bans."""
        seq = ht.SymbolicDim("seq")
        half = seq // 2
        with ht.graph("define_and_run", create_new=True) as g:
            a = ht.placeholder("float32", (half, 4), name="a")
            b = ht.placeholder("float32", (half, 4), name="b")
            out = ops.add(a, b)
            with pytest.raises(ValueError, match="conflicting feeds"):
                g.run([out], feed_dict={a: np.ones((3, 4), np.float32),
                                        b: np.ones((5, 4), np.float32)})

    def test_symbolic_seq_len(self):
        """Symbolic dims bound from feeds (reference IntSymbol shape plans)."""
        sym = ht.SymbolicDim("seq")
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (2, sym, 4), name="x")
            out = ops.reduce_sum(x, axis=1)
            for s in (3, 7):
                X = np.ones((2, s, 4), np.float32)
                (val,) = g.run([out], feed_dict={x: X})
                np.testing.assert_allclose(np.asarray(val),
                                           np.full((2, 4), float(s)))
        assert len(g._plan_pool) == 2


class TestOptimizerParity:
    def _run_hetu(self, opt_fn, steps=5):
        X, Y = _make_data(n=16)
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (16, 8), name="x")
            y = ht.placeholder("int32", (16,), name="y")
            w = ht.parameter(np.full((4, 8), 0.05, np.float32), name="w")
            loss = ops.softmax_cross_entropy(ops.matmul(x, w, trans_b=True), y)
            train_op = opt_fn().minimize(loss)
            for _ in range(steps):
                g.run(loss, [loss, train_op], {x: X, y: Y})
            return np.asarray(g.get_tensor_value(w))

    def _run_torch(self, opt_fn, steps=5):
        X, Y = _make_data(n=16)
        w = torch.full((4, 8), 0.05, requires_grad=True)
        opt = opt_fn([w])
        for _ in range(steps):
            opt.zero_grad()
            loss = torch.nn.functional.cross_entropy(
                torch.tensor(X) @ w.T, torch.tensor(Y))
            loss.backward()
            opt.step()
        return w.detach().numpy()

    def test_sgd_matches_torch(self):
        ours = self._run_hetu(lambda: optim.SGDOptimizer(lr=0.1))
        ref = self._run_torch(lambda p: torch.optim.SGD(p, lr=0.1))
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_sgd_momentum_matches_torch(self):
        ours = self._run_hetu(lambda: optim.SGDOptimizer(lr=0.1, momentum=0.9))
        ref = self._run_torch(lambda p: torch.optim.SGD(p, lr=0.1, momentum=0.9))
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_adam_matches_torch(self):
        ours = self._run_hetu(lambda: optim.AdamOptimizer(lr=0.01))
        ref = self._run_torch(lambda p: torch.optim.Adam(p, lr=0.01))
        np.testing.assert_allclose(ours, ref, rtol=1e-3, atol=1e-4)

    def test_grad_clip_matches_torch(self):
        ours = self._run_hetu(
            lambda: optim.SGDOptimizer(lr=0.5, max_grad_norm=0.05))

        def torch_clipped(steps=5):
            X, Y = _make_data(n=16)
            w = torch.full((4, 8), 0.05, requires_grad=True)
            opt = torch.optim.SGD([w], lr=0.5)
            for _ in range(steps):
                opt.zero_grad()
                loss = torch.nn.functional.cross_entropy(
                    torch.tensor(X) @ w.T, torch.tensor(Y))
                loss.backward()
                torch.nn.utils.clip_grad_norm_([w], 0.05)
                opt.step()
            return w.detach().numpy()
        np.testing.assert_allclose(ours, torch_clipped(), rtol=1e-4,
                                   atol=1e-5)

    def test_lr_schedule_matches_torch_lambda(self):
        sched = optim.linear_schedule(0.2, warmup_steps=2, total_steps=10,
                                      min_lr=0.0)
        ours = self._run_hetu(lambda: optim.SGDOptimizer(lr=sched), steps=6)

        def torch_sched(steps=6):
            X, Y = _make_data(n=16)
            w = torch.full((4, 8), 0.05, requires_grad=True)
            opt = torch.optim.SGD([w], lr=1.0)
            # torch's epoch counter is 0-based pre-step; ours is 1-based
            lam = torch.optim.lr_scheduler.LambdaLR(
                opt, lambda e: float(np.asarray(sched(e + 1))))
            for _ in range(steps):
                opt.zero_grad()
                loss = torch.nn.functional.cross_entropy(
                    torch.tensor(X) @ w.T, torch.tensor(Y))
                loss.backward()
                opt.step()
                lam.step()
            return w.detach().numpy()
        np.testing.assert_allclose(ours, torch_sched(), rtol=1e-4,
                                   atol=1e-5)

    def test_schedule_shapes(self):
        import jax.numpy as jnp
        cos = optim.cosine_schedule(1.0, warmup_steps=10, total_steps=110,
                                    min_lr=0.1)
        assert float(cos(0)) == 0.0
        np.testing.assert_allclose(float(cos(10)), 1.0, rtol=1e-6)
        np.testing.assert_allclose(float(cos(60)), 0.55, rtol=1e-6)
        np.testing.assert_allclose(float(cos(110)), 0.1, rtol=1e-6)
        step = optim.step_decay_schedule(1.0, 0.5, every=10)
        np.testing.assert_allclose(float(step(25)), 0.25, rtol=1e-6)
        import pytest
        with pytest.raises(ValueError, match="exceed"):
            optim.cosine_schedule(1.0, 10, 10)

    def test_adam_with_schedule_trains(self):
        sched = optim.cosine_schedule(0.05, 1, 20)
        ours = self._run_hetu(lambda: optim.AdamOptimizer(
            lr=sched, max_grad_norm=1.0), steps=8)
        assert np.all(np.isfinite(ours))

    def test_adamw_decoupled_matches_torch(self):
        ours = self._run_hetu(
            lambda: optim.AdamWOptimizer(lr=0.01, weight_decay=0.1))
        ref = self._run_torch(
            lambda p: torch.optim.AdamW(p, lr=0.01, weight_decay=0.1))
        np.testing.assert_allclose(ours, ref, rtol=1e-3, atol=1e-4)


class TestModule:
    def test_named_parameters_and_state_dict(self):
        with ht.graph("define_and_run", create_new=True) as g:
            class Net(nn.Module):
                def __init__(self):
                    super().__init__()
                    self.fc1 = nn.Linear(4, 8)
                    self.fc2 = nn.Linear(8, 2)

                def forward(self, x):
                    return self.fc2(ops.relu(self.fc1(x)))

            net = Net()
            names = dict(net.named_parameters()).keys()
            assert set(names) == {"fc1.weight", "fc1.bias", "fc2.weight",
                                  "fc2.bias"}
            sd = net.state_dict()
            assert sd["fc1.weight"].shape == (8, 4)
            sd2 = {k: np.zeros_like(v) for k, v in sd.items()}
            net.load_state_dict(sd2)
            assert np.all(net.state_dict()["fc1.weight"] == 0)

    def test_train_eval_mode(self):
        with ht.graph("eager", create_new=True):
            m = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.9))
            m.eval()
            x = np.ones((2, 4), np.float32)
            y1 = m(x).numpy()
            y2 = m(x).numpy()
            np.testing.assert_array_equal(y1, y2)  # dropout off in eval


class TestReviewRegressions:
    """Regressions from code-review findings on the M1 frontend."""

    def test_derived_dim_override_cleared_across_runs(self):
        """ADVICE r5: a provisional override on a DerivedDim installed by
        an earlier bind pass (unbound leaves) must not survive a later
        pass that rebinds only the leaf symbols — the derived dim has to
        re-evaluate from its expression, even when the later feed does
        not mention it."""
        seq = ht.SymbolicDim("seq")
        half = seq // 2
        with ht.graph("define_and_run", create_new=True) as g:
            a = ht.placeholder("float32", (seq, 2), name="a")
            b = ht.placeholder("float32", (half, 2), name="b")
            # pass 1: only the derived dim is fed while its leaf is
            # unbound -> provisional override half=8
            g._bind_symbolic_dims({b: np.zeros((8, 2), np.float32)})
            assert half.get() == 8
            # pass 2: only the leaf is fed; the stale override must be
            # cleared so half re-evaluates to 10//2
            g._bind_symbolic_dims({a: np.zeros((10, 2), np.float32)})
            assert seq.get() == 10
            assert half.get() == 5, \
                "stale provisional override shadowed the expression"

    def test_eval_then_train_plan_no_collision(self):
        X, Y = _make_data(n=8, d=4, classes=2)
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (32, 8), name="x")
            y = ht.placeholder("int32", (32,), name="y")
            w = ht.parameter(np.full((4, 8), 0.1, np.float32), name="w")
            loss = ops.softmax_cross_entropy(ops.matmul(x, w, trans_b=True), y)
            op = optim.SGDOptimizer(lr=0.5).minimize(loss)
            X, Y = _make_data(n=32)
            g.run([loss], feed_dict={x: X, y: Y})  # eval plan first
            w0 = np.asarray(g.get_tensor_value(w)).copy()
            g.run(loss, [loss, op], {x: X, y: Y})  # train plan, same shapes
            w1 = np.asarray(g.get_tensor_value(w))
            assert not np.allclose(w0, w1), "train run silently did nothing"
            g.run([loss], feed_dict={x: X, y: Y})  # eval again
            np.testing.assert_array_equal(
                w1, np.asarray(g.get_tensor_value(w)))

    def test_dropout_masks_vary(self):
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (4, 64), name="x")
            d1 = ops.dropout(x, 0.5, training=True)
            d2 = ops.dropout(x, 0.5, training=True)
            X = np.ones((4, 64), np.float32)
            a1, a2 = g.run([d1, d2], feed_dict={x: X})
            b1, _ = g.run([d1, d2], feed_dict={x: X})
        assert not np.allclose(np.asarray(a1), np.asarray(a2)), \
            "identical masks across layers"
        assert not np.allclose(np.asarray(a1), np.asarray(b1)), \
            "identical masks across steps"

    def test_batchnorm_running_stats(self):
        with ht.graph("eager", create_new=True):
            bn = nn.BatchNorm2d(3)
            x = (np.random.RandomState(0).randn(4, 3, 5, 5) * 2 + 1).astype(
                np.float32)
            bn(x)
            assert not np.allclose(bn.running_mean, 0)
            sd = bn.state_dict()
        with ht.graph("eager", create_new=True):
            bn2 = nn.BatchNorm2d(3)
            bn2.load_state_dict(sd)  # buffers restored too
            np.testing.assert_allclose(bn2.running_mean, bn.running_mean)
            bn2.eval()
            out = bn2(x).numpy()
            # eval-mode output uses running stats, not batch stats
            mean = np.asarray(sd["running_mean"]).reshape(1, 3, 1, 1)
            var = np.asarray(sd["running_var"]).reshape(1, 3, 1, 1)
            ref = (x - mean) / np.sqrt(var + 1e-5)
            np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_topk_axis(self):
        x = np.random.RandomState(0).randn(5, 3).astype(np.float32)
        vals, idx = ops.topk(x, 2, axis=0)
        ref = np.sort(x, axis=0)[::-1][:2]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)

    def test_scalar_feed_with_micro_batches(self):
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (8, 4), name="x")
            s = ht.placeholder("float32", (), name="scale")
            w = ht.parameter(np.ones((4, 2), np.float32), name="w")
            loss = ops.reduce_sum(ops.matmul(x, w)) * s
            op = optim.SGDOptimizer(lr=0.01).minimize(loss)
            g.run(loss, [loss, op],
                  {x: np.ones((8, 4), np.float32), s: np.float32(2.0)},
                  num_micro_batches=4)


class TestDefineByRunGraph:
    """Lazy-trace graph type (reference DefineByRunGraph,
    define_by_run_graph.h:9): ops record symbolically, values
    materialize on demand with caching."""

    def test_get_or_compute_lazy_and_cached(self):
        import hetu_tpu as ht
        from hetu_tpu import ops
        from hetu_tpu.graph.ctor import ConstantInitializer, parameter
        with ht.graph("define_by_run", create_new=True) as g:
            w = parameter(ConstantInitializer(2.0), (3,), name="w")
            y = w * 3.0
            z = y + 1.0
            # nothing computed yet
            assert y.id not in g._computed
            val = g.get_or_compute(z)
            np.testing.assert_allclose(np.asarray(val), [7.0, 7.0, 7.0])
            # intermediate cached too; new ops don't recompute it
            zz = z * 2.0
            np.testing.assert_allclose(np.asarray(g.get_or_compute(zz)),
                                       [14.0] * 3)
            assert z.id in g._computed

    def test_feed_and_invalidate(self):
        import hetu_tpu as ht
        from hetu_tpu import ops
        with ht.graph("define_by_run", create_new=True) as g:
            x = ht.placeholder("float32", (2,), name="x")
            y = x * 10.0
            g.feed(x, np.array([1.0, 2.0], np.float32))
            np.testing.assert_allclose(np.asarray(g.get_or_compute(y)),
                                       [10.0, 20.0])
            g.invalidate()
            g.feed(x, np.array([3.0, 4.0], np.float32))
            np.testing.assert_allclose(np.asarray(g.get_or_compute(y)),
                                       [30.0, 40.0])


class TestScannedMicroBatchLoop:
    """The executor scans micro-batches at runtime (one traced fwd+bwd
    body) instead of unrolling M program copies (VERDICT r1 weak #3;
    reference loops at runtime, executable_graph.cc:1424)."""

    def _build_and_time(self, nmb, batch=64):
        import time
        X = np.random.RandomState(0).randn(batch, 8).astype(np.float32)
        Y = (np.arange(batch) % 4).astype(np.int32)
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (batch, 8), name="x")
            y = ht.placeholder("int32", (batch,), name="y")
            w = ht.parameter(np.full((4, 8), 0.1, np.float32), name="w")
            loss = ops.softmax_cross_entropy(ops.matmul(x, w, trans_b=True), y)
            train_op = optim.AdamOptimizer(lr=0.01).minimize(loss)
            t0 = time.perf_counter()
            g.run(loss, [loss, train_op], {x: X, y: Y},
                  num_micro_batches=nmb)
            compile_s = time.perf_counter() - t0
            l, _ = g.run(loss, [loss, train_op], {x: X, y: Y},
                         num_micro_batches=nmb)
        return compile_s, float(np.asarray(l))

    def test_trace_time_flat_in_num_micro_batches(self):
        t2, _ = self._build_and_time(2)
        t32, _ = self._build_and_time(32)
        # an unrolled loop would scale ~16x; the scanned body stays flat
        # (generous bound for CI noise)
        assert t32 < t2 * 3 + 1.0, (t2, t32)

    def test_scanned_grads_equal_unrolled_math(self):
        """M=2 vs M=32 vs full batch: identical updates (mean loss)."""
        outs = {}
        for nmb in (1, 2, 32):
            X = np.random.RandomState(1).randn(64, 8).astype(np.float32)
            Y = (np.arange(64) % 4).astype(np.int32)
            with ht.graph("define_and_run", create_new=True) as g:
                x = ht.placeholder("float32", (64, 8), name="x")
                y = ht.placeholder("int32", (64,), name="y")
                w = ht.parameter(np.full((4, 8), 0.1, np.float32), name="w")
                loss = ops.softmax_cross_entropy(
                    ops.matmul(x, w, trans_b=True), y)
                train_op = optim.SGDOptimizer(lr=0.1).minimize(loss)
                for _ in range(2):
                    g.run(loss, [loss, train_op], {x: X, y: Y},
                          num_micro_batches=nmb)
                outs[nmb] = np.asarray(g.get_tensor_value(w))
        np.testing.assert_allclose(outs[1], outs[2], rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(outs[2], outs[32], rtol=1e-4, atol=1e-6)


class TestShapeBuckets:
    """Bucketed shape plans (reference DeduceShapePlan,
    define_and_run_graph.cc:273): varying seq lens round up to bucket
    boundaries so the plan pool stays small."""

    def test_20_random_lens_trigger_few_compiles(self):
        rng = np.random.RandomState(0)
        seq = ht.SymbolicDim("seq")
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (2, seq, 8), name="x")
            y = ht.placeholder("int32", (2, seq), name="y")
            w = ht.parameter(np.full((4, 8), 0.1, np.float32), name="w")
            logits = ops.matmul(x, w, trans_b=True)
            loss = ops.softmax_cross_entropy(logits, y, ignore_index=-100)
            g.set_shape_buckets([32, 64, 96, 128], pad_values={y: -100})
            losses = {}
            for _ in range(20):
                s = int(rng.randint(5, 129))
                X = rng.randn(2, s, 8).astype(np.float32)
                Y = (np.arange(2 * s).reshape(2, s) % 4).astype(np.int32)
                (lv,) = g.run([loss], feed_dict={x: X, y: Y})
                losses[s] = (float(np.asarray(lv)), X, Y)
            assert len(g._plan_pool) <= 4, len(g._plan_pool)

        # padded/masked losses equal the exact-shape computation
        for s, (lv, X, Y) in losses.items():
            z = X @ np.full((4, 8), 0.1, np.float32).T
            lp = z - np.log(np.sum(np.exp(z), -1, keepdims=True))
            ref = float(np.mean(-np.take_along_axis(
                lp, Y[..., None], axis=-1)))
            np.testing.assert_allclose(lv, ref, rtol=1e-5,
                                       err_msg=f"seq {s}")

    def test_alignment_buckets_and_overflow(self):
        seq = ht.SymbolicDim("seq")
        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (1, seq), name="x")
            out = ops.reduce_sum(x)
            g.set_shape_buckets(16)
            for s in (3, 9, 16, 17, 30):
                (v,) = g.run([out], feed_dict={
                    x: np.ones((1, s), np.float32)})
                assert float(np.asarray(v)) == s  # zero-padded sum
            assert len(g._plan_pool) == 2  # buckets 16 and 32

        with ht.graph("define_and_run", create_new=True) as g:
            x = ht.placeholder("float32", (1, seq), name="x")
            out = ops.reduce_sum(x)
            g.set_shape_buckets([8])
            with pytest.raises(ValueError, match="exceeds"):
                g.run([out], feed_dict={x: np.ones((1, 9), np.float32)})


def test_set_seed_reproducible_init():
    """ht.set_seed resets the init-key stream (reference per-device RNG,
    hetu/impl/random/)."""
    import numpy as np
    import hetu_tpu as ht

    def build():
        ht.set_seed(123)
        with ht.graph("define_and_run", create_new=True) as g:
            w = ht.parameter(ht.NormalInitializer(stddev=1.0), (8, 8),
                             name="w")
            g._materialize_var(w)
            return np.asarray(g._var_data[w.id])

    a, b = build(), build()
    np.testing.assert_array_equal(a, b)


def test_set_seed_dropout_stream_decoupled_from_numpy():
    """set_seed must reproduce dropout seeds without touching (or being
    disturbed by) numpy's process-global RNG."""
    import numpy as np
    import hetu_tpu as ht

    def seed_of():
        with ht.graph("define_and_run", create_new=True) as g:
            return g._rng_seed

    ht.set_seed(5)
    a = seed_of()
    np.random.seed(999)       # user reseeds global numpy...
    np.random.rand(10)        # ...and draws from it
    ht.set_seed(5)
    b = seed_of()
    assert a == b             # framework stream unaffected
    np.random.seed(42)
    u1 = np.random.rand()
    np.random.seed(42)
    ht.set_seed(7)            # must not disturb the global stream
    u2 = np.random.rand()
    assert u1 == u2


def test_as_strided_out_of_bounds_raises():
    import numpy as np
    import pytest
    from hetu_tpu import ops
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    with pytest.raises(ValueError, match="exceeds storage"):
        ops.as_strided(x, (5, 4), (2, 1), storage_offset=18)
    with pytest.raises(ValueError, match="exceeds storage"):
        ops.as_strided(x, (2, 2), (-3, 1), storage_offset=0)
