"""Static step-time model tests (ISSUE 10): the FLOP/HBM walker's
pinned contracts, the two new lint rules firing exactly once with
hints, the shared comm-pricing formulas, and agreement with XLA's own
``compiled.cost_analysis()`` on a toy matmul chain.

Walker contracts demonstrated here:
(a) ``dot_general`` FLOPs are exact contraction math (2·|out|·K), for
    plain and batched dots;
(b) scan bodies multiply by the trip count in the native inventory and
    count ONCE in the XLA-comparable one (XLA's while convention);
(c) ``shard_map`` region costs are per-device block costs — the
    predicted FLOPs of a dp8-sharded matmul are global/8;
(d) a conditional charges its most expensive branch, not the sum.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hetu_tpu.analysis import analyze_handle, predict_cost
from hetu_tpu.analysis.cost import (CostReport, cost_walk, price_edges)
from hetu_tpu.analysis.edges import CommEdge
from hetu_tpu.graph.graph import clear_executables, register_executable
from hetu_tpu.planner.cost_model import (ClusterSpec, all_reduce_time,
                                         all_to_all_time, collective_time)


def _sds(shape, dtype=np.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _register(name, fn, args, **meta):
    meta.setdefault("mesh_axes", {})
    meta.setdefault("params", [])
    meta.setdefault("allowed_gspmd", None)
    clear_executables(name)
    return register_executable(name, fn, args, meta)


def _fired(rep, rule):
    return [f for f in rep.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# (a) dot_general contraction math
# ---------------------------------------------------------------------------

class TestDotFlops:
    def test_matmul_flops_exact(self):
        h = _register("t_cost/mm", jax.jit(lambda a, b: a @ b),
                      (_sds((64, 128)), _sds((128, 32))))
        r = predict_cost(h)
        assert r.flops == 2 * 64 * 128 * 32

    def test_batched_dot_flops_exact(self):
        f = jax.jit(lambda a, b: jnp.einsum("bij,bjk->bik", a, b))
        h = _register("t_cost/bmm", f, (_sds((4, 16, 32)),
                                        _sds((4, 32, 8))))
        r = predict_cost(h)
        assert r.flops == 2 * 4 * 16 * 32 * 8

    def test_matmul_chain_agrees_with_xla_cost_analysis(self):
        """The headline contract on a program XLA prices exactly:
        predicted FLOPs AND bytes accessed match cost_analysis()."""
        f = jax.jit(lambda x, a, b: (x @ a) @ b)
        h = _register("t_cost/chain", f, (_sds((64, 128)),
                                          _sds((128, 256)),
                                          _sds((256, 32))))
        r = predict_cost(h, xla=True)
        assert r.xla is not None and r.xla["flops"] > 0
        # flops: exact (converts/fusion noise zero on an f32 chain)
        assert r.cmp_flops == r.xla["flops"]
        # bytes: operand+result of each dot, exactly XLA's accounting
        assert r.cmp_bytes == r.xla["bytes_accessed"]
        assert r.xla_within() is True


# ---------------------------------------------------------------------------
# (b) scan trip multiplication
# ---------------------------------------------------------------------------

class TestScanTrips:
    def _scan_handle(self, trips):
        def f(x, w):
            def body(c, _):
                return c @ w, ()
            out, _ = jax.lax.scan(body, x, None, length=trips)
            return out
        return _register(f"t_cost/scan{trips}", jax.jit(f),
                         (_sds((32, 64)), _sds((64, 64))))

    def test_native_flops_multiply_by_trips(self):
        one_dot = 2 * 32 * 64 * 64
        r5 = predict_cost(self._scan_handle(5))
        assert r5.flops == 5 * one_dot
        # ...and the body is priced once, then multiplied — not
        # re-walked into accumulating temps (the attribution entry
        # carries count=5, flops=one body)
        dots = [e for e in r5.entries if e.prim == "dot_general"]
        assert len(dots) == 1 and dots[0].count == 5
        assert dots[0].flops == one_dot

    def test_cmp_flops_count_body_once(self):
        """XLA's cost_analysis counts a while/scan body ONCE — the
        comparable inventory must follow or every scanned program
        would fail the ±10% cross-check by ×trips."""
        r5 = predict_cost(self._scan_handle(5), xla=True)
        one_dot = 2 * 32 * 64 * 64
        assert r5.cmp_flops < 2 * one_dot        # body once, not x5
        assert abs(r5.cmp_flops - r5.xla["flops"]) \
            <= 0.1 * r5.xla["flops"] + 64


# ---------------------------------------------------------------------------
# (c) shard_map mesh-axis division
# ---------------------------------------------------------------------------

class TestShardMapDivision:
    def test_per_device_flops_divide_by_mesh_axis(self, devices8):
        from jax.sharding import Mesh, PartitionSpec as P
        from hetu_tpu.parallel.comm import shard_map
        mesh = Mesh(np.array(devices8), ("dp",))
        f = jax.jit(shard_map(lambda x, w: x @ w, mesh,
                              in_specs=(P("dp", None), P(None, None)),
                              out_specs=P("dp", None)))
        h = _register("t_cost/smap", f, (_sds((64, 128)),
                                         _sds((128, 128))),
                      mesh_axes={"dp": 8})
        r = predict_cost(h)
        assert r.flops == 2 * 64 * 128 * 128 / 8

    def test_gspmd_scale_divides_by_whole_mesh(self, devices8):
        # outside a manual region, global avals divide by prod(mesh)
        h = _register("t_cost/gspmd", jax.jit(lambda a, b: a @ b),
                      (_sds((64, 128)), _sds((128, 128))),
                      mesh_axes={"dp": 2, "tp": 4})
        r = predict_cost(h)
        assert r.flops == 2 * 64 * 128 * 128 / 8


# ---------------------------------------------------------------------------
# (d) conditionals charge the max branch
# ---------------------------------------------------------------------------

class TestCondMaxBranch:
    def test_cond_charges_most_expensive_branch(self):
        def f(i, x, w):
            return jax.lax.switch(i, [
                lambda x, w: jnp.sum(x),            # cheap
                lambda x, w: jnp.sum(x @ w),        # the dot branch
                lambda x, w: jnp.sum(x * 2.0),      # cheap
            ], x, w)
        h = _register("t_cost/switch", jax.jit(f),
                      (_sds((), np.int32), _sds((64, 128)),
                       _sds((128, 128))))
        r = predict_cost(h)
        dot = 2 * 64 * 128 * 128
        assert r.flops >= dot                 # the dot branch is charged
        assert r.flops < 1.5 * dot            # ...but not summed x3


# ---------------------------------------------------------------------------
# comm pricing: one implementation, transport-aware
# ---------------------------------------------------------------------------

class TestCommPricing:
    def test_linter_and_solver_share_the_formulas(self):
        """price_edges must route through planner.cost_model.
        collective_time — measured link overrides change BOTH."""
        cluster = ClusterSpec(num_chips=8)
        edge = CommEdge(kind="all_reduce", axes=("dp",),
                        payload_bytes=1 << 20)
        [c] = price_edges([edge], {"dp": 8}, cluster)
        assert c.time_s == all_reduce_time(float(1 << 20), 8, cluster)
        # measured alpha-beta override: same number on both sides
        cal = ClusterSpec(num_chips=8,
                          link_alpha_beta={"all_reduce": (1e-5, 2e-9)})
        [cm] = price_edges([edge], {"dp": 8}, cal)
        want = 1e-5 + 2e-9 * (1 << 20)
        assert abs(cm.time_s - want) < 1e-12
        assert abs(all_reduce_time(float(1 << 20), 8, cal) - want) \
            < 1e-12
        # kinds without a fit keep the ring model
        assert all_to_all_time(1e6, 8, cal) \
            == all_to_all_time(1e6, 8, cluster)

    def test_quantized_transport_prices_real_wire_bytes(self):
        """An int8 bucket edge carries 1/4 the payload of fp32 — the
        alpha-beta time must reflect the narrow wire, not the compute
        dtype (EQuARX pricing)."""
        cluster = ClusterSpec(num_chips=8)
        fp32 = CommEdge(kind="all_reduce", axes=("dp",),
                        payload_bytes=256 << 20)
        int8 = CommEdge(kind="all_reduce", axes=("dp",),
                        payload_bytes=64 << 20)
        [c32], [c8] = (price_edges([e], {"dp": 8}, cluster)
                       for e in (fp32, int8))
        # bandwidth term dominates at 256 MB: int8 must be ~4x cheaper
        assert c8.time_s < 0.3 * c32.time_s

    def test_collective_time_kind_dispatch(self):
        cluster = ClusterSpec(num_chips=8)
        assert collective_time("identity", 1e6, 8, cluster) == 0.0
        assert collective_time("scatter", 1e6, 8, cluster) == 0.0
        assert collective_time("all_reduce", 1e6, 8, cluster) > 0
        assert collective_time("reshard", 1e6, 8, cluster) > 0


# ---------------------------------------------------------------------------
# the two new rules: seeded, fire exactly once, hints carried
# ---------------------------------------------------------------------------

class TestCostRules:
    def _comm_heavy(self, name, overlap):
        # trivial compute + one declared 1 GB all_reduce x4: exposed
        # comm dwarfs the roofline and the step is far above the
        # CI-toy threshold
        edge = {"kind": "all_reduce", "axes": ("dp",),
                "payload_bytes": 1 << 30, "count": 4,
                "origin": "grad_comm"}
        return _register(name, jax.jit(lambda x: x + 1.0),
                         (_sds((8, 8)),),
                         mesh_axes={"dp": 8},
                         declared_edges=[edge],
                         comm_overlap=overlap)

    def test_comm_bound_plan_fires_once_with_hint(self):
        rep = analyze_handle(self._comm_heavy("t_cost/bound", False))
        fired = _fired(rep, "comm-bound-plan")
        assert len(fired) == 1
        assert "comm-bound" in fired[0].message
        assert "int8" in fired[0].hint       # names the transport remedy
        assert "bucket" in fired[0].hint     # ...and the bucket remedy

    def test_overlap_scheduled_plan_is_exempt(self):
        """Same wire bytes, but the plan declares the coalesced
        overlap-schedulable sync: the grad_comm edges hide under the
        roofline and the rule must stay silent."""
        rep = analyze_handle(self._comm_heavy("t_cost/olap", True))
        assert _fired(rep, "comm-bound-plan") == []
        cost = rep.meta["cost"]
        assert cost.overlap and cost.overlapped_comm_s > 0
        assert cost.exposed_comm_s == 0.0

    def test_tiny_steps_are_exempt(self):
        # big RELATIVE comm share but a microseconds step: CI-scale toy
        edge = {"kind": "all_reduce", "axes": ("dp",),
                "payload_bytes": 1 << 10, "count": 1}
        h = _register("t_cost/tiny", jax.jit(lambda x: x + 1.0),
                      (_sds((8, 8)),), mesh_axes={"dp": 8},
                      declared_edges=[edge])
        rep = analyze_handle(h)
        assert _fired(rep, "comm-bound-plan") == []

    def test_predicted_step_regression_fires_once(self):
        h = _register("t_cost/reg", jax.jit(lambda a, b: a @ b),
                      (_sds((64, 128)), _sds((128, 128))))
        base = predict_cost(h).step_time_s
        rep = analyze_handle(h, options={
            "baseline_step_time_s": {"t_cost/reg": base / 2.0}})
        fired = _fired(rep, "predicted-step-regression")
        assert len(fired) == 1
        assert "regressed" in fired[0].message
        assert "--update-baseline" in fired[0].hint
        # within tolerance: silent
        rep_ok = analyze_handle(h, options={
            "baseline_step_time_s": {"t_cost/reg": base}})
        assert _fired(rep_ok, "predicted-step-regression") == []


# ---------------------------------------------------------------------------
# report plumbing
# ---------------------------------------------------------------------------

class TestCostReportPlumbing:
    def test_cost_dict_shape_and_baseline_gate(self):
        h = _register("t_cost/dict", jax.jit(lambda a, b: a @ b),
                      (_sds((64, 128)), _sds((128, 128))))
        rep = analyze_handle(h)
        d = rep.to_dict(records=False)
        assert d["cost"]["flops"] == 2 * 64 * 128 * 128
        assert d["cost"]["step_time_us"] > 0
        assert d["cost"]["bound"] in ("compute", "hbm", "comm")
        # losing the accounting fails the baseline gate
        from hetu_tpu.analysis.report import AnalysisReport
        ar = AnalysisReport()
        ar.add(rep)
        base = ar.to_dict()
        del rep.meta["cost"]
        problems = ar.check_against_baseline(base)
        assert any("step-time accounting" in p for p in problems)

    def test_flop_growth_fails_baseline(self):
        h = _register("t_cost/grow", jax.jit(lambda a, b: a @ b),
                      (_sds((64, 128)), _sds((128, 128))))
        from hetu_tpu.analysis.report import AnalysisReport
        ar = AnalysisReport()
        rep = ar.add(analyze_handle(h))
        base = ar.to_dict()
        base["executables"]["t_cost/grow"]["cost"]["flops"] /= 2
        problems = ar.check_against_baseline(base)
        assert any("predicted flops regressed" in p for p in problems)

    def test_predicted_cost_stats_carries_step_components(self):
        from hetu_tpu.analysis import predicted_cost_stats
        h = _register("t_cost/stats", jax.jit(lambda a, b: a @ b),
                      (_sds((64, 128)), _sds((128, 128))))
        s = predicted_cost_stats(h)
        assert s["step_time_s"] > 0
        assert s["flops"] == 2 * 64 * 128 * 128
        assert s["bound"] in ("compute", "hbm", "comm")
        assert s["comm_time_s"] == 0.0       # no edge claim -> no comm


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------

class TestAttribution:
    def test_top_entries_carry_provenance_and_rank_by_time(self):
        f = jax.jit(lambda x, a, b: jnp.tanh(x @ a) @ b)
        h = _register("t_cost/attr", f, (_sds((64, 256)),
                                         _sds((256, 256)),
                                         _sds((256, 64))))
        r = predict_cost(h)
        top = r.top(3)
        assert top and top[0].prim == "dot_general"
        # the big dot ranks first, and entries know their source file
        assert any(e.source for e in r.entries if e.prim == "dot_general")
        d = r.to_dict(entries=True)
        assert d["top_entries"][0]["prim"] == "dot_general"
