"""Serving subsystem: paged KV pool, paged attention, continuous batching.

The load-bearing contract: at temperature 0, the paged engine —
batching, paging, late admission, preemption and all — produces
BIT-FOR-BIT the tokens of a solo dense-cache ``generate()`` run.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import hetu_tpu as ht
from hetu_tpu.models import GPTConfig, GPTLMHeadModel
from hetu_tpu.models.generate import generate
from hetu_tpu.ops.paged_attention import (paged_attention_pallas,
                                          paged_attention_reference)
from hetu_tpu.serving import (Engine, PagedKVPool, RequestQueue, TRASH_PAGE)
from hetu_tpu.utils.metrics import (Counter, Gauge, Histogram,
                                    NULL_INSTRUMENT, make_instrument)


def _build_state(cfg, seed=3):
    ht.set_seed(seed)
    with ht.graph("eager", create_new=True):
        model = GPTLMHeadModel(cfg)
        model.logits(np.zeros((1, 4), np.int32))
        state = {k: np.asarray(v) for k, v in model.state_dict().items()}
    return state


def _solo(state, cfg, prompt, n_new):
    return np.asarray(generate(state, cfg,
                               np.asarray([prompt], np.int32), n_new,
                               temperature=0.0))[0, len(prompt):].tolist()


def _make_engine(state, cfg, **kw):
    clock = [0.0]
    kw.setdefault("time_fn", lambda: clock[0])
    kw.setdefault("debug", True)        # invariant checks on in tests
    eng = Engine(state, cfg, **kw)
    eng._test_clock = clock
    return eng


def _drain(eng, check=True):
    while eng.has_work:
        eng.step()
        eng._test_clock[0] += 1.0
        if check:
            eng.pool.check_invariants()


# ---------------------------------------------------------------------------
# page allocator
# ---------------------------------------------------------------------------

def test_pool_alloc_free_invariants():
    pool = PagedKVPool(num_layers=2, num_pages=9, page_size=8,
                       kv_heads=2, head_dim=16, debug=True)
    assert pool.num_usable == 8 and pool.free_pages == 8
    a = pool.alloc(3)
    b = pool.alloc(4)
    assert len(a) == 3 and len(b) == 4
    assert TRASH_PAGE not in a + b          # trash page never issued
    assert len(set(a + b)) == 7             # no double allocation
    pool.check_invariants()
    # OOM: no partial grant, state untouched
    assert pool.alloc(2) is None
    assert pool.free_pages == 1
    pool.free(a)
    pool.check_invariants()
    assert pool.free_pages == 4
    with pytest.raises(ValueError, match="double free"):
        pool.free([a[0]])
    assert pool.pages_for(1) == 1 and pool.pages_for(8) == 1 \
        and pool.pages_for(9) == 2


def test_pool_rejects_degenerate_shapes():
    with pytest.raises(ValueError, match="num_pages"):
        PagedKVPool(1, 1, 8, 2, 16)


def test_pool_reset_never_reissues_trash_page():
    """Regression: reset() must rebuild the free-list EXCLUDING the
    reserved trash page 0 — a range(num_pages) rebuild would hand page 0
    to the next request and real KV writes would land in the padding
    sink.  Alloc-after-reset can never return page 0."""
    pool = PagedKVPool(num_layers=2, num_pages=9, page_size=8,
                       kv_heads=2, head_dim=16, debug=True)
    pool.alloc(5)
    pool.reset()
    assert pool.free_pages == pool.num_usable == 8
    assert pool.used_pages == 0
    # drain the ENTIRE pool: page 0 must never surface
    got = pool.alloc(pool.num_usable)
    assert got is not None and TRASH_PAGE not in got
    assert sorted(got) == list(range(1, pool.num_pages))
    pool.check_invariants()
    # reset with live allocations: old handles are forgotten, page 0
    # still reserved, invariants hold
    pool.reset(clear_pages=True)
    pool.check_invariants()
    assert float(jnp.sum(jnp.abs(pool.k_pages[0]))) == 0.0
    again = pool.alloc(pool.num_usable)
    assert TRASH_PAGE not in again
    pool.check_invariants()


def test_pool_tp_sharding_spec(devices8):
    from hetu_tpu.parallel import create_mesh
    mesh = create_mesh({"tp": 2}, devices8[:2])
    pool = PagedKVPool(num_layers=1, num_pages=4, page_size=8,
                       kv_heads=4, head_dim=8, mesh=mesh)
    assert pool.sharding is not None
    spec = pool.sharding.spec
    assert tuple(spec) == (None, None, "tp", None)
    assert pool.k_pages[0].sharding == pool.sharding


# ---------------------------------------------------------------------------
# paged attention op
# ---------------------------------------------------------------------------

def _scatter_dense_to_pages(k_dense, page_table, ps, num_pages):
    """[B, S, kvh, hd] dense -> pages, via each request's page table."""
    b, s, kvh, hd = k_dense.shape
    pages = np.zeros((num_pages, ps, kvh, hd), k_dense.dtype)
    for bi in range(b):
        for t in range(s):
            pages[page_table[bi, t // ps], t % ps] = k_dense[bi, t]
    return pages


def test_paged_attention_matches_dense_sdpa():
    """Gather-via-page-table attention == dense attention over the same
    (ragged) histories, for GQA and non-contiguous page tables."""
    rng = np.random.RandomState(0)
    B, nh, kvh, hd, ps = 3, 8, 2, 16, 8
    seq_lens = np.array([13, 5, 24], np.int32)
    maxp = 3
    # non-contiguous, per-request page ids; tail slots -> trash
    page_table = np.array([[4, 9, 0], [2, 0, 0], [7, 1, 5]], np.int32)
    num_pages = 12
    S = maxp * ps
    k_dense = rng.randn(B, S, kvh, hd).astype(np.float32)
    v_dense = rng.randn(B, S, kvh, hd).astype(np.float32)
    q = jnp.asarray(rng.randn(B, nh, hd), jnp.float32)
    kp = jnp.asarray(_scatter_dense_to_pages(k_dense, page_table, ps,
                                             num_pages))
    vp = jnp.asarray(_scatter_dense_to_pages(v_dense, page_table, ps,
                                             num_pages))

    got = paged_attention_reference(q, kp, vp, jnp.asarray(page_table),
                                    jnp.asarray(seq_lens))

    # dense oracle, one request at a time over its true history
    g = nh // kvh
    for bi in range(B):
        L = seq_lens[bi]
        k = np.repeat(k_dense[bi, :L], g, axis=1)       # [L, nh, hd]
        v = np.repeat(v_dense[bi, :L], g, axis=1)
        qb = np.asarray(q)[bi]                          # [nh, hd]
        s = np.einsum("hd,lhd->hl", qb, k) / np.sqrt(hd)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = np.einsum("hl,lhd->hd", p, v)
        np.testing.assert_allclose(np.asarray(got)[bi], want,
                                   rtol=1e-5, atol=1e-5)


def test_paged_attention_pallas_matches_reference():
    """The Pallas kernel (interpret mode on CPU) against the gather-dense
    reference — including a partial last page and a GQA group dim that
    needs sublane padding."""
    rng = np.random.RandomState(1)
    B, nh, kvh, hd, ps, num_pages, maxp = 2, 4, 2, 32, 8, 10, 4
    q = jnp.asarray(rng.randn(B, nh, hd), jnp.float32)
    kp = jnp.asarray(rng.randn(num_pages, ps, kvh, hd), jnp.float32)
    vp = jnp.asarray(rng.randn(num_pages, ps, kvh, hd), jnp.float32)
    pt = jnp.asarray([[3, 1, 8, 0], [5, 0, 0, 0]], jnp.int32)
    sl = jnp.asarray([19, 8], jnp.int32)
    ref = paged_attention_reference(q, kp, vp, pt, sl)
    got = paged_attention_pallas(q, kp, vp, pt, sl, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_paged_attention_rejects_bad_shapes():
    q = jnp.zeros((2, 4, 16))
    kp = jnp.zeros((4, 8, 2, 16))
    pt = jnp.zeros((2, 2), jnp.int32)
    sl = jnp.zeros((2,), jnp.int32)
    with pytest.raises(ValueError, match="head_dim"):
        paged_attention_reference(jnp.zeros((2, 4, 8)), kp, kp, pt, sl)
    with pytest.raises(ValueError, match="divisible"):
        paged_attention_reference(jnp.zeros((2, 3, 16)), kp, kp, pt, sl)
    with pytest.raises(ValueError, match="seq_lens"):
        paged_attention_reference(q, kp, kp, pt, jnp.zeros((3,),
                                                           jnp.int32))


# ---------------------------------------------------------------------------
# continuous batching
# ---------------------------------------------------------------------------

CFG_KW = dict(vocab_size=97, hidden_size=32, num_layers=2, num_heads=4,
              max_seq_len=64, sp=False, dropout=0.0)


def test_engine_matches_solo_generate_mixed_lengths():
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg)
    prompts = [[5, 17, 2, 9], [1, 1, 4, 88, 7, 3, 2], [3, 2, 1]]
    want = [_solo(state, cfg, pr, 8) for pr in prompts]
    eng = _make_engine(state, cfg, num_pages=16, page_size=16,
                       max_batch=4)
    reqs = [eng.add_request(pr, 8, arrival_time=0.0) for pr in prompts]
    _drain(eng)
    for i, r in enumerate(reqs):
        assert r.out_tokens == want[i], \
            f"req {i}: {r.out_tokens} != solo {want[i]}"
    assert eng.pool.used_pages == 0            # everything returned


def test_late_arriving_request_identical_to_solo():
    """A request admitted MID-FLIGHT (others already decoding) produces
    exactly its solo-run tokens — continuous batching changes when a
    token is computed, never what it is."""
    cfg = GPTConfig(position="rotary", norm="rmsnorm",
                    activation="swiglu", **CFG_KW)
    state = _build_state(cfg, seed=5)
    early = [[5, 17, 2, 9, 1, 1], [7, 3, 2, 9]]
    late = [42, 13, 8]
    want_late = _solo(state, cfg, late, 10)
    want_early = [_solo(state, cfg, pr, 14) for pr in early]

    eng = _make_engine(state, cfg, num_pages=24, page_size=8,
                       max_batch=4)
    reqs = [eng.add_request(pr, 14, arrival_time=0.0) for pr in early]
    late_req = eng.add_request(late, 10, arrival_time=4.0)  # mid-decode
    _drain(eng)
    assert late_req.first_token_time >= 4.0    # really arrived late
    assert late_req.out_tokens == want_late
    for r, w in zip(reqs, want_early):
        assert r.out_tokens == w


def test_oom_eviction_preserves_determinism():
    """Pool too small for all requests at once: the scheduler preempts
    (recompute eviction), invariants hold every step, and every request
    still reproduces its solo tokens."""
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg, seed=11)
    prompts = [[5, 17, 2, 9, 33, 12, 8, 1], [1, 1, 4, 44], [3, 2, 1, 9]]
    want = [_solo(state, cfg, pr, 12) for pr in prompts]
    eng = _make_engine(state, cfg, num_pages=7, page_size=8,
                       max_batch=4)
    reqs = [eng.add_request(pr, 12, arrival_time=float(i))
            for i, pr in enumerate(prompts)]
    _drain(eng)
    assert eng.counters["preemptions"].value >= 1, \
        "test should exercise eviction; enlarge prompts if not"
    for i, r in enumerate(reqs):
        assert r.out_tokens == want[i]
    assert eng.pool.used_pages == 0


def test_engine_rejects_impossible_request():
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg)
    eng = _make_engine(state, cfg, num_pages=4, page_size=8,
                       max_batch=2)
    with pytest.raises(ValueError, match="exceeds max_model_len"):
        eng.add_request(list(range(1, 30)), 40)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.add_request([1, 2], 0)
    with pytest.raises(ValueError, match="empty"):
        eng.add_request([], 4)


def test_engine_streaming_and_eos():
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg, seed=2)
    prompt = [5, 17, 2, 9]
    full = _solo(state, cfg, prompt, 10)
    eos = full[3]                               # stop after 4 tokens
    streamed = []
    eng = _make_engine(state, cfg, num_pages=16, page_size=16,
                       max_batch=2)
    req = eng.add_request(prompt, 10, eos_token_id=eos,
                          stream_cb=lambda r, t: streamed.append(t))
    _drain(eng)
    assert req.out_tokens == full[:4]
    assert streamed == req.out_tokens           # every token streamed


def test_engine_single_unified_executable():
    """Requests with assorted prompt lengths and a fluctuating live set
    run through ONE compiled executable — the unified ragged
    prefill+decode step.  There is no bucket grid to grow."""
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg, seed=4)
    eng = _make_engine(state, cfg, num_pages=32, page_size=8,
                       max_batch=4, chunk_size=8)
    rng = np.random.RandomState(0)
    for i in range(7):
        pr = [int(t) for t in rng.randint(1, 90, size=rng.randint(2, 14))]
        eng.add_request(pr, 6, arrival_time=float(i))
    _drain(eng)
    assert eng.compile_count == 1
    assert set(eng._compiled) == {"unified"}
    assert eng.executable_calls == eng.metrics_summary()["step_calls"]
    assert eng.executable_calls >= 1


def test_engine_metrics_advance_and_disable():
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg, seed=6)
    eng = _make_engine(state, cfg, num_pages=16, page_size=16,
                       max_batch=2)
    eng.add_request([5, 17, 2], 5, arrival_time=0.0)
    eng.add_request([1, 9, 4, 2], 5, arrival_time=0.0)
    _drain(eng)
    m = eng.metrics_summary()
    assert m["tokens_generated"] == 10
    assert m["prefill_tokens"] == 7
    assert m["requests_completed"] == 2
    assert m["decode_steps"] >= 4
    assert m["ttft"]["count"] == 2
    assert m["tpot"]["count"] == 8
    assert m["request_latency"]["p50"] > 0
    # disabled engines run on the shared no-op instrument
    eng2 = _make_engine(state, cfg, num_pages=16, page_size=16,
                        max_batch=2, metrics=False)
    eng2.add_request([5, 17, 2], 3, arrival_time=0.0)
    _drain(eng2, check=False)
    assert eng2.counters["tokens_generated"] is NULL_INSTRUMENT
    assert eng2.metrics_summary()["tokens_generated"] == 0.0


def test_admission_respects_step_page_budget():
    """Two requests that EACH fit the free pool but not TOGETHER: the
    scheduler must admit one and hold the other (regression: admit()
    compared every candidate against the same pool.free_pages and
    over-admitted, crashing _prefill's reservation assert)."""
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg, seed=13)
    want = [_solo(state, cfg, pr, 3)
            for pr in ([5, 17, 2, 9, 33, 12, 8, 1, 7],
                       [1, 1, 4, 44, 9, 2, 6, 3, 5])]
    # 4 usable pages of 4 tokens; each 9-token prompt needs 3 pages
    eng = _make_engine(state, cfg, num_pages=5, page_size=4,
                       max_batch=4)
    reqs = [eng.add_request([5, 17, 2, 9, 33, 12, 8, 1, 7], 3,
                            arrival_time=0.0),
            eng.add_request([1, 1, 4, 44, 9, 2, 6, 3, 5], 3,
                            arrival_time=0.0)]
    _drain(eng)
    for r, w in zip(reqs, want):
        assert r.out_tokens == w


def test_prompt_filling_entire_page_table():
    """A request filling its entire (non-power-of-two-wide) page table:
    chunked prefill must scatter exactly the real tokens' KV (v1
    regression: the bucketed prefill's clamped pt_row[j] gather
    silently overwrote the last real page with padding KV — the
    per-token write plan makes phantom pages impossible by
    construction, but the full-table scenario stays covered)."""
    cfg = GPTConfig(position="rotary", norm="rmsnorm",
                    activation="silu", num_kv_heads=2, **CFG_KW)
    state = _build_state(cfg, seed=14)
    # 12 usable pages of 4 tokens (maxp=12, not a power of two);
    # 45-token prompt + 3 new = 48 tokens = exactly 12 pages
    prompt = [int(t) for t in
              np.random.RandomState(3).randint(1, 90, size=45)]
    want = _solo(state, cfg, prompt, 3)
    eng = _make_engine(state, cfg, num_pages=13, page_size=4,
                       max_batch=2)
    assert eng.max_pages_per_seq == 12
    req = eng.add_request(prompt, 3, arrival_time=0.0)
    _drain(eng)
    assert req.out_tokens == want


def test_requeue_preserves_fifo_for_equal_arrivals():
    """A request pushed back (didn't fit) must keep its place ahead of
    same-arrival-time peers (regression: the heap tiebreaker was
    insertion order, so a re-push overtook)."""
    from hetu_tpu.serving.request import Request
    q = RequestQueue()
    a = Request(req_id=0, prompt=[1], max_new_tokens=1, arrival_time=0.0)
    b = Request(req_id=1, prompt=[1], max_new_tokens=1, arrival_time=0.0)
    q.push(a)
    q.push(b)
    got = q.pop_ready(1.0)
    assert got is a
    q.push(a)                                  # didn't fit: push back
    assert q.pop_ready(1.0) is a               # still first, no overtake


def test_learned_positions_bound_by_wpe_table():
    """max_model_len must never exceed the learned-position table (an
    out-of-range wpe gather clamps silently instead of failing)."""
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", vocab_size=97, hidden_size=32,
                    num_layers=1, num_heads=4, max_seq_len=20, sp=False,
                    dropout=0.0)
    state = _build_state(cfg, seed=15)
    eng = _make_engine(state, cfg, num_pages=8, page_size=8,
                       max_batch=2)
    assert eng.max_model_len == 20             # not rounded up to 24
    with pytest.raises(ValueError, match="exceeds max_model_len"):
        eng.add_request(list(range(1, 16)), 10)


def test_request_queue_arrival_order_gating():
    from hetu_tpu.serving.request import Request
    q = RequestQueue()
    a = Request(req_id=0, prompt=[1], max_new_tokens=1, arrival_time=5.0)
    b = Request(req_id=1, prompt=[1], max_new_tokens=1, arrival_time=1.0)
    q.push(a)
    q.push(b)
    assert q.pop_ready(0.5) is None             # nothing has arrived
    assert q.pop_ready(2.0) is b                # earliest arrival first
    assert q.pop_ready(2.0) is None             # a hasn't arrived yet
    assert q.pop_ready(5.0) is a
    assert not q


def test_sampling_on_device_skips_logits_roundtrip():
    """ALL sampling modes run inside the unified executable: an
    all-greedy workload AND a mixed greedy/temperature batch both fetch
    only [rows] int32s — host_logit_fetches stays 0 — while greedy rows
    remain bit-for-bit with solo generate()."""
    cfg = GPTConfig(position="learned", norm="layernorm",
                    activation="gelu", **CFG_KW)
    state = _build_state(cfg, seed=21)
    prompts = [[5, 17, 2, 9], [3, 2, 1]]
    want = [_solo(state, cfg, pr, 6) for pr in prompts]

    eng = _make_engine(state, cfg, num_pages=16, page_size=16,
                       max_batch=4)
    reqs = [eng.add_request(pr, 6, arrival_time=0.0) for pr in prompts]
    _drain(eng)
    assert eng.host_logit_fetches == 0          # argmax stayed on device
    assert eng.metrics_summary()["host_logit_fetches"] == 0
    for r, w in zip(reqs, want):
        assert r.out_tokens == w

    eng2 = _make_engine(state, cfg, num_pages=16, page_size=16,
                        max_batch=4)
    g_req = eng2.add_request(prompts[0], 6, arrival_time=0.0)
    s_req = eng2.add_request(prompts[1], 6, temperature=1.0, seed=3,
                             arrival_time=0.0)
    _drain(eng2)
    assert eng2.host_logit_fetches == 0         # sampled row too
    assert g_req.out_tokens == want[0]          # greedy peer untouched
    assert len(s_req.out_tokens) == 6


# ---------------------------------------------------------------------------
# metrics instruments (satellite)
# ---------------------------------------------------------------------------

def test_metrics_instruments():
    c = Counter("tok")
    c.inc()
    c.inc(4)
    assert c.value == 5
    g = Gauge("occ")
    g.set(0.75)
    assert g.value == 0.75
    h = Histogram("ttft")
    for v in [1.0, 2.0, 3.0, 4.0, 100.0]:
        h.observe(v)
    assert h.count == 5 and h.mean == 22.0
    assert h.percentile(50) == 3.0
    assert h.percentile(100) == 100.0
    # linear interpolation between ranks (rank 3.96 over [1,2,3,4,100]),
    # not the old nearest-index snap to 100.0
    assert h.summary()["p99"] == pytest.approx(96.16)
    # factory + no-op fallback
    assert isinstance(make_instrument("histogram", "x"), Histogram)
    n = make_instrument("counter", "x", enabled=False)
    assert n is NULL_INSTRUMENT
    n.inc(); n.observe(3.0); n.set(1.0)         # all swallow silently
    assert n.value == 0.0 and n.percentile(99) == 0.0
    assert n.summary()["p90"] == 0.0            # indexable, not {}
    assert n.bucket_counts() == {"+Inf": 0}
    with pytest.raises(ValueError, match="unknown instrument"):
        make_instrument("summary")


def test_histogram_buckets_count_overflow_in_inf_and_sum():
    """Observations ABOVE the last bucket bound must still land in
    +Inf, count and sum (dropping the overflow tail would hide exactly
    the tail latencies a histogram exists to expose)."""
    h = Histogram("ttft", buckets=[0.1, 1.0])
    for v in [0.05, 0.5, 0.7, 5.0]:             # 5.0 > last bound
        h.observe(v)
    assert h.count == 4
    assert h.total == pytest.approx(6.25)       # overflow in the sum
    bc = h.bucket_counts()
    assert bc["0.1"] == 1
    assert bc["1.0"] == 3                       # cumulative
    assert bc["+Inf"] == 4                      # overflow counted
    # cumulative counts always close at the observation count
    assert bc["+Inf"] == h.count
    # percentiles still see the overflow observation
    assert h.percentile(100) == 5.0
    # bucketless histogram: everything is +Inf, count still closes
    h2 = Histogram("tpot")
    h2.observe(3.0)
    assert h2.bucket_counts() == {"+Inf": 1}
