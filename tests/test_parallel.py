"""Distributed-layer tests on the virtual 8-device mesh.

The key correctness invariant (the reference checks this via loss-curve
equivalence across configs, e.g. examples/malleus/test_accuracy.py): the
SAME model trained under different parallel layouts produces the SAME
losses/params.  Here we check it exactly, per-step, on simulated devices.
"""
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import nn, ops, optim
from hetu_tpu.models import GPTConfig, GPTLMHeadModel, llama_config
from hetu_tpu.nn.parallel import config2ds, parallel_data_provider
from hetu_tpu.parallel import DistributedStates


def _fix_seed():
    from hetu_tpu.graph import ctor
    ctor._seed_counter[0] = 12345


def _train_gpt(mesh_shape, steps=4, seed=0, sp=True, devices=None):
    """Build + train a tiny LLaMA under the given mesh; return losses+params."""
    _fix_seed()
    mesh = ht.create_mesh(mesh_shape, devices) if mesh_shape else None
    cfg = llama_config(vocab_size=64, hidden_size=32, num_layers=2,
                       num_heads=4, max_seq_len=16, sp=sp)
    with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
        ids = ht.parallel_placeholder("int32", (8, 16), pspec=P("dp", None)
                                      if mesh else None, name="ids")
        labels = ht.parallel_placeholder("int32", (8, 16),
                                         pspec=P("dp", None) if mesh else None,
                                         name="labels")
        model = GPTLMHeadModel(cfg)
        loss = model(ids, labels)
        train_op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
        rng = np.random.RandomState(seed)
        IDS = rng.randint(0, 64, (8, 16)).astype(np.int32)
        L = np.roll(IDS, -1, axis=1)
        losses = []
        for _ in range(steps):
            out = g.run(loss, [loss, train_op], {ids: IDS, labels: L})
            losses.append(float(np.asarray(out[0])))
        params = {t.name: np.asarray(g.get_tensor_value(t))
                  for t in g._var_tensors.values()}
    return losses, params


@pytest.mark.slow
class TestStrategyEquivalence:
    """Same model, different layouts -> identical training trajectories."""

    def test_tp_matches_single_device(self, devices8):
        l1, p1 = _train_gpt(None)
        l2, p2 = _train_gpt({"dp": 1, "tp": 4}, devices=devices8[:4])
        np.testing.assert_allclose(l1, l2, rtol=2e-3, atol=1e-4)
        for k in p1:
            np.testing.assert_allclose(p1[k], p2[k], rtol=2e-2, atol=2e-3,
                                       err_msg=k)

    def test_dp_tp_matches_single_device(self, devices8):
        l1, _ = _train_gpt(None)
        l3, _ = _train_gpt({"dp": 2, "tp": 4}, devices=devices8)
        np.testing.assert_allclose(l1, l3, rtol=2e-3, atol=1e-4)

    def test_sp_matches_no_sp(self, devices8):
        l_sp, _ = _train_gpt({"dp": 2, "tp": 4}, sp=True, devices=devices8)
        l_nosp, _ = _train_gpt({"dp": 2, "tp": 4}, sp=False, devices=devices8)
        np.testing.assert_allclose(l_sp, l_nosp, rtol=2e-3, atol=1e-4)


class TestParallelLayers:
    def test_column_row_composition(self, devices8):
        """col-parallel -> row-parallel == dense reference."""
        _fix_seed()
        mesh = ht.create_mesh({"dp": 2, "tp": 4}, devices8)
        rng = np.random.RandomState(0)
        X = rng.randn(4, 8, 16).astype(np.float32)
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            x = ht.parallel_placeholder("float32", (4, 8, 16),
                                        pspec=P("dp", None, None), name="x")
            col = nn.ColumnParallelLinear(16, 32, bias=True)
            row = nn.RowParallelLinear(32, 16, bias=True)
            y = row(ops.gelu(col(x)))
            (out,) = g.run([y], feed_dict={x: X})
            w1 = np.asarray(g.get_tensor_value(col.weight))
            b1 = np.asarray(g.get_tensor_value(col.bias))
            w2 = np.asarray(g.get_tensor_value(row.weight))
            b2 = np.asarray(g.get_tensor_value(row.bias))
        import jax
        ref = np.asarray(jax.nn.gelu(X @ w1.T + b1)) @ w2.T + b2
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)

    def test_vocab_parallel_embedding(self, devices8):
        _fix_seed()
        mesh = ht.create_mesh({"dp": 2, "tp": 4}, devices8)
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            emb = nn.VocabParallelEmbedding(64, 16)
            ids = ht.parallel_placeholder("int32", (2, 8),
                                          pspec=P("dp", None), name="ids")
            out_t = emb(ids)
            IDS = np.random.RandomState(0).randint(0, 64, (2, 8)).astype(np.int32)
            (out,) = g.run([out_t], feed_dict={ids: IDS})
            table = np.asarray(g.get_tensor_value(emb.weight))
        np.testing.assert_allclose(np.asarray(out), table[IDS], rtol=1e-5)

    def test_vocab_parallel_ce(self, devices8):
        """vocab-parallel CE == dense CE (reference
        VocabParallelCrossEntropyLoss parity)."""
        mesh = ht.create_mesh({"dp": 2, "tp": 4}, devices8)
        rng = np.random.RandomState(0)
        logits_np = rng.randn(4, 8, 64).astype(np.float32)
        labels_np = rng.randint(0, 64, (4, 8)).astype(np.int32)
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            lg = ht.parallel_placeholder("float32", (4, 8, 64),
                                         pspec=P("dp", None, "tp"), name="lg")
            lb = ht.parallel_placeholder("int32", (4, 8),
                                         pspec=P("dp", None), name="lb")
            loss = nn.vocab_parallel_cross_entropy(lg, lb)
            (val,) = g.run([loss], feed_dict={lg: logits_np, lb: labels_np})
        import torch
        ref = torch.nn.functional.cross_entropy(
            torch.tensor(logits_np).reshape(-1, 64),
            torch.tensor(labels_np).reshape(-1).long()).numpy()
        np.testing.assert_allclose(np.asarray(val), ref, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
class TestZeRO:
    def test_zero_shards_optimizer_state(self, devices8):
        """ZeRO: Adam m/v shards over dp (reference `zero` ds flag ->
        state partitioning)."""
        mesh = ht.create_mesh({"dp": 8}, devices8)
        with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
            x = ht.parallel_placeholder("float32", (8, 16),
                                        pspec=P("dp", None), name="x")
            y = ht.parallel_placeholder("int32", (8,), pspec=P("dp"), name="y")
            w = ht.parallel_parameter(np.zeros((16, 16), np.float32),
                                      (16, 16), pspec=P(), name="w")
            loss = ops.softmax_cross_entropy(ops.matmul(x, w, trans_b=True), y)
            opt = optim.AdamOptimizer(lr=0.01, zero=True)
            train_op = opt.minimize(loss)
            rng = np.random.RandomState(0)
            X = rng.randn(8, 16).astype(np.float32)
            Y = rng.randint(0, 16, (8,)).astype(np.int32)
            g.run(loss, [loss, train_op], {x: X, y: Y})
            m = opt._state["m"][w.id]
            # state sharded over dp on dim 0
            spec = m.sharding.spec
            assert spec and spec[0] == "dp", f"m not dp-sharded: {spec}"
            # the registration path itself must have run (XLA sharding
            # propagation can mask a broken _ensure_state loop by
            # choosing dp layouts on its own — assert the explicit
            # device_put/constraint machinery engaged)
            assert w.id in opt._shardings, "state sharding not registered"

    def test_zero_levels_loss_equivalent_and_memory(self, devices8):
        """ZeRO-{0,1,2,3} execution (reference zero ds flag,
        distributed_states.h:69; grad RS / param AG, Communication.h:583):
        identical loss trajectories, shrinking per-device footprints."""
        def train(zero, steps=4):
            from hetu_tpu.graph import ctor
            ctor._seed_counter[0] = 1234
            mesh = ht.create_mesh({"dp": 8}, devices8)
            with ht.graph("define_and_run", create_new=True,
                          mesh=mesh) as g:
                x = ht.parallel_placeholder("float32", (16, 32),
                                            pspec=P("dp", None), name="x")
                y = ht.parallel_placeholder("int32", (16,), pspec=P("dp"),
                                            name="y")
                w1 = ht.parallel_parameter(
                    np.random.RandomState(7).randn(32, 64).astype(np.float32)
                    * 0.1, (32, 64), pspec=P(), name="w1")
                w2 = ht.parallel_parameter(
                    np.random.RandomState(8).randn(64, 16).astype(np.float32)
                    * 0.1, (64, 16), pspec=P(), name="w2")
                h = ops.relu(ops.matmul(x, w1))
                loss = ops.softmax_cross_entropy(ops.matmul(h, w2), y)
                opt = optim.AdamOptimizer(lr=0.05, zero=zero)
                op = opt.minimize(loss)
                rng = np.random.RandomState(0)
                X = rng.randn(16, 32).astype(np.float32)
                Y = rng.randint(0, 16, (16,)).astype(np.int32)
                losses = [float(np.asarray(
                    g.run(loss, [loss, op], {x: X, y: Y})[0]))
                    for _ in range(steps)]
                state_bytes = sum(
                    arr.addressable_shards[0].data.nbytes
                    for tree in (opt._state["m"], opt._state["v"])
                    for arr in tree.values())
                param_bytes = sum(
                    g._var_data[t].addressable_shards[0].data.nbytes
                    for t in (w1.id, w2.id))
            return losses, state_bytes, param_bytes

        l0, s0, p0 = train(0)
        l1, s1, p1 = train(1)
        l2, s2, p2 = train(2)
        l3, s3, p3 = train(3)
        for lz in (l1, l2, l3):
            np.testing.assert_allclose(l0, lz, rtol=2e-4, atol=1e-5)
        # optimizer state memory shrinks 8x at zero>=1
        assert s1 <= s0 // 8 + 64 and s2 <= s0 // 8 + 64 \
            and s3 <= s0 // 8 + 64, (s0, s1, s2, s3)
        # parameter memory shrinks only at zero-3 (FSDP at rest)
        assert p1 == p0 and p2 == p0, (p0, p1, p2)
        assert p3 <= p0 // 8 + 64, (p0, p3)


class TestConfigIR:
    def test_parse_layout_roundtrip(self):
        """parse_layout inverts generate_gpt_3d_config — the pp-capable
        entry path (reference examples/gpt/train_hetu.py:256-335)."""
        from hetu_tpu.utils.ds_config import (generate_gpt_3d_config,
                                              parse_layout)
        for dp, tp, pp in [(1, 1, 1), (2, 2, 2), (4, 1, 2), (1, 2, 4)]:
            cfg = generate_gpt_3d_config(num_layers=8, dp=dp, tp=tp, pp=pp,
                                         zero=True)
            got = parse_layout(cfg)
            assert got == (dp, tp, pp, True), (got, (dp, tp, pp))
        cfg = generate_gpt_3d_config(num_layers=4, dp=2, tp=2, pp=1,
                                     zero=False)
        assert parse_layout(cfg) == (2, 2, 1, False)

    def test_config2ds_homogeneous(self):
        cfg = {"type": "variable", "split": {"0": [4]}, "dup": [2],
               "device_group_union": [[0, 1, 2, 3, 4, 5, 6, 7]],
               "zero": True}
        union, dgs = config2ds(cfg)
        assert not union.is_hetero()
        ds = union.get(0)
        assert ds.get_dim(0) == 4 and ds.get_dim(-1) == 2
        assert ds.zero
        assert ds.order == [-1, 0]

    def test_config2ds_hetero(self):
        # two hetero pipelines of 4 devices each: dp2xdup4 vs dp4xdup2
        cfg = {"type": "placeholder", "split": {"0": [2, 4]}, "dup": [4, 2],
               "device_group_union": [[0, 1, 2, 3], [4, 5, 6, 7]]}
        union, dgs = config2ds(cfg)
        assert union.is_hetero() and union.hetero_dim == 0
        assert union.get(0).get_dim(0) == 2
        assert union.get(1).get_dim(0) == 4

    def test_parallel_data_provider(self):
        ds = DistributedStates(8, {0: 2, 1: 4})
        data = np.arange(64, dtype=np.float32).reshape(8, 8)
        local = parallel_data_provider(data, ds, 5)
        np.testing.assert_array_equal(local, data[4:8, 2:4])
