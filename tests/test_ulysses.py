"""Ulysses all-to-all sequence-parallel attention (TPU-native extension;
the reference has ring CP only — SURVEY.md §2.3)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import optim
from hetu_tpu.graph import ctor
from hetu_tpu.models import llama_config, GPTLMHeadModel
from hetu_tpu.ops.attention import sdpa_reference
from hetu_tpu.parallel.ulysses import ulysses_attention_sharded


def _qkv(b=2, s=64, h=8, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(b, s, h, d).astype(np.float32)
    return mk(), mk(), mk()


class TestUlyssesOracle:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, devices8, causal):
        mesh = ht.create_mesh({"cp": 4}, devices8[:4])
        q, k, v = _qkv()
        out = ulysses_attention_sharded(q, k, v, mesh, causal=causal,
                                        batch_axis=None, head_axis=None)
        ref = sdpa_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_with_dp_and_tp(self, devices8):
        mesh = ht.create_mesh({"dp": 2, "cp": 2, "tp": 2}, devices8)
        q, k, v = _qkv()
        out = ulysses_attention_sharded(q, k, v, mesh, causal=True)
        ref = sdpa_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_packed_segments(self, devices8):
        mesh = ht.create_mesh({"cp": 4}, devices8[:4])
        q, k, v = _qkv(seed=3)
        segs = np.repeat(np.arange(4), 16)[None, :].repeat(2, 0)  # 4 docs
        out = ulysses_attention_sharded(q, k, v, mesh, causal=True,
                                        batch_axis=None, head_axis=None,
                                        segment_ids=segs)
        ref = sdpa_reference(q, k, v, causal=True, segment_ids=segs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_indivisible_heads_padded(self, devices8):
        """heads % cp != 0 is handled by zero-padding the head dim up to
        the next cp multiple (the GQA head-divisibility relaxation) —
        results still match the dense oracle exactly."""
        mesh = ht.create_mesh({"cp": 4}, devices8[:4])
        q, k, v = _qkv(h=6)
        out = ulysses_attention_sharded(q, k, v, mesh, causal=True,
                                        batch_axis=None, head_axis=None)
        ref = sdpa_reference(q, k, v, causal=True)
        assert out.shape == q.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_indivisible_heads_padded_with_tp(self, devices8):
        """Padding accounts for the tp head split too (per-TP-rank head
        count must divide cp)."""
        mesh = ht.create_mesh({"cp": 2, "tp": 2}, devices8[:4])
        q, k, v = _qkv(h=6)
        out = ulysses_attention_sharded(q, k, v, mesh, causal=True,
                                        batch_axis=None)
        ref = sdpa_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_gqa_kv_head_error(self, devices8):
        """Un-repeated GQA kv heads (kv_heads % cp != 0) must raise the
        descriptive ValueError, not an opaque all_to_all shape error."""
        mesh = ht.create_mesh({"cp": 4}, devices8[:4])
        q, _, _ = _qkv(h=8)
        _, k, v = _qkv(h=2)
        with pytest.raises(Exception, match="kv heads|repeat GQA"):
            jax.block_until_ready(ulysses_attention_sharded(
                q, k, v, mesh, batch_axis=None, head_axis=None))


@pytest.mark.slow
class TestGPTWithUlysses:
    def test_gpt_ulysses_matches_single_device(self, devices8):
        def train(mesh_shape, cp_axis=None, steps=3):
            ctor._seed_counter[0] = 4242
            mesh = ht.create_mesh(mesh_shape) if mesh_shape else None
            cfg = llama_config(vocab_size=64, hidden_size=32, num_layers=2,
                               num_heads=4, max_seq_len=32, sp=False,
                               cp_axis=cp_axis, cp_impl="ulysses")
            with ht.graph("define_and_run", create_new=True, mesh=mesh) as g:
                ids = ht.parallel_placeholder(
                    "int32", (4, 32),
                    pspec=P("dp", None) if mesh else None, name="ids")
                lbl = ht.parallel_placeholder(
                    "int32", (4, 32),
                    pspec=P("dp", None) if mesh else None, name="lbl")
                m = GPTLMHeadModel(cfg)
                loss = m(ids, lbl)
                op = optim.AdamOptimizer(lr=1e-2).minimize(loss)
                rng = np.random.RandomState(0)
                I = rng.randint(0, 64, (4, 32)).astype(np.int32)
                L = np.roll(I, -1, 1)
                return [float(np.asarray(
                    g.run(loss, [loss, op], {ids: I, lbl: L})[0]))
                    for _ in range(steps)]

        base = train(None)
        uly = train({"dp": 2, "cp": 2, "tp": 2}, cp_axis="cp")
        np.testing.assert_allclose(base, uly, rtol=3e-3, atol=1e-4)
