"""Static-analysis pass: collective inventory, lint rules, CI gate.

Each lint rule is demonstrated on a SEEDED violation (must fire exactly
once) plus a clean control (must stay silent).  The general pass must
also reproduce PR 1's grad-comm emission assertions unchanged: the
registered train-step handle's lowered program contains exactly the
collective sequence ``dstates.predict_update_step_collectives`` derives
from the gradient set.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import hetu_tpu as ht
from hetu_tpu import analysis, ops, optim
from hetu_tpu.analysis import (AnalysisContext, analyze_handle,
                               collect_collectives, run_rules)
from hetu_tpu.graph.graph import (DefineAndRunGraph, clear_executables,
                                  get_executable, register_executable)
from hetu_tpu.parallel import comm, create_mesh, dstates
from hetu_tpu.parallel.comm import shard_map
from hetu_tpu.serving.kv_pool import PagedKVPool

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sds(shape, dtype=np.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _register(name, fn, args, **meta):
    meta.setdefault("mesh_axes", {})
    meta.setdefault("params", [])
    meta.setdefault("allowed_gspmd", None)
    clear_executables(name)
    return register_executable(name, fn, args, meta)


def _rules_fired(report, rule):
    return [f for f in report.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# collective inventory
# ---------------------------------------------------------------------------

class TestInventory:
    def test_inventory_kinds_axes_bytes_and_tags(self, devices8):
        mesh = create_mesh({"dp": 8}, devices8)

        def f(x):
            with comm.comm_tag("my_sync"):
                s = jax.lax.psum(x, "dp")
            g = jax.lax.all_gather(x, "dp", axis=0, tiled=True)
            return s, g

        jf = jax.jit(shard_map(f, mesh, (P(),), (P(), P())))
        h = _register("t_inv/f", jf, (_sds((64,)),))
        recs = collect_collectives(h.jaxpr)
        assert [r.kind for r in recs] == ["all_reduce", "all_gather"]
        ar, ag = recs
        assert ar.axes == ("dp",) and ar.dtype == "float32"
        assert ar.payload_bytes == 64 * 4
        assert ar.wire_bytes == comm.ring_wire_bytes("all_reduce", 256, 8)
        assert "my_sync" in ar.scope          # comm_tag attribution
        assert ag.payload_bytes == 8 * 64 * 4  # gathered size
        assert ar.source.endswith(".py:" + str(ar.source.split(":")[-1]))

    def test_scan_trip_counts_multiply(self, devices8):
        mesh = create_mesh({"dp": 8}, devices8)

        def body(c, x):
            return c + jax.lax.psum(x, "dp"), None

        def f(xs):
            c, _ = jax.lax.scan(body, jnp.zeros_like(xs[0]), xs)
            return c

        jf = jax.jit(shard_map(f, mesh, (P(),), P()))
        h = _register("t_inv/scan", jf, (_sds((5, 16)),))
        recs = collect_collectives(h.jaxpr)
        assert len(recs) == 1 and recs[0].count == 5


# ---------------------------------------------------------------------------
# seeded rule violations (each fires exactly once)
# ---------------------------------------------------------------------------

class TestSeededViolations:
    def test_replicated_large_param_on_train_gpt_shaped_graph(self,
                                                              devices8):
        """examples/train_gpt.py-shaped graph with the embedding FORCED
        to full replication on a tp-capable mesh."""
        from hetu_tpu.models import GPTLMHeadModel, llama_config
        ht.set_seed(7)
        mesh = create_mesh({"dp": 2, "tp": 4}, devices8)
        cfg = llama_config(vocab_size=256, hidden_size=64, num_layers=1,
                           num_heads=4, max_seq_len=16, sp=False)
        g = DefineAndRunGraph("t_repl")
        g.mesh = mesh
        clear_executables("t_repl")
        with ht.graph(g):
            ids = ht.parallel_placeholder("int32", (4, 16),
                                          pspec=P("dp", None), name="ids")
            labels = ht.parallel_placeholder("int32", (4, 16),
                                             pspec=P("dp", None),
                                             name="labels")
            model = GPTLMHeadModel(cfg)
            loss = model(ids, labels)
            # seed the violation: strip the vocab-parallel sharding
            wte = model.transformer.wte.weight
            wte.pspec = P(None, None)
            train_op = optim.AdamOptimizer(lr=1e-3).minimize(loss)
            rng = np.random.RandomState(0)
            IDS = rng.randint(0, 256, (4, 16)).astype(np.int32)
            g.run(loss, [loss, train_op], {ids: IDS, labels: IDS})
        (handle,) = g.analysis_handles()
        rep = analyze_handle(
            handle, options={"param_bytes_threshold": 32 * 1024})
        fired = [f for f in rep.findings
                 if f.rule == "replicated-large-param"]
        assert len(fired) == 1, rep.findings
        assert fired[0].subject == wte.name
        assert "replicated" in fired[0].message

    def test_donation_miss_fires_once_and_fix_silences(self):
        """A dropped donation on a buffer that round-trips through the
        executable (the serving pages pattern)."""
        def f(pages, delta):
            return pages.at[0].add(delta)

        args = (_sds((64, 256)), _sds((256,)))
        h = _register("t_don/miss", jax.jit(f), args)
        rep = analyze_handle(h, options={"donation_bytes_threshold": 1024})
        fired = _rules_fired(rep, "donation-miss")
        assert len(fired) == 1
        assert "not donated" in fired[0].message
        h2 = _register("t_don/fixed", jax.jit(f, donate_argnums=(0,)),
                       args)
        rep2 = analyze_handle(h2,
                              options={"donation_bytes_threshold": 1024})
        assert not _rules_fired(rep2, "donation-miss")
        # two independent un-donated round-trip buffers -> one finding
        # PER ARGUMENT, with distinct subjects
        g2 = jax.jit(lambda a, b: (a * 2, b * 3))
        h3 = _register("t_don/two", g2, (_sds((64, 256)), _sds((64, 256))))
        rep3 = analyze_handle(h3,
                              options={"donation_bytes_threshold": 1024})
        fired3 = _rules_fired(rep3, "donation-miss")
        assert len(fired3) == 2
        assert len({f.subject for f in fired3}) == 2

    def test_wide_collective_fires_once_scales_exempt(self, devices8):
        mesh = create_mesh({"dp": 8}, devices8)

        def f(x):
            y = (x @ x).astype(jnp.float32)     # bf16 compute
            return jax.lax.psum(y, "dp")        # fp32 transport

        jf = jax.jit(shard_map(f, mesh, (P(),), P()))
        h = _register("t_wide/f", jf, (_sds((64, 64), jnp.bfloat16),))
        rep = analyze_handle(h, options={"wide_bytes_threshold": 1024})
        fired = _rules_fired(rep, "wide-collective")
        assert len(fired) == 1
        assert "float32 all_reduce" in fired[0].message

        # int8 transport's fp32 absmax sidecars are tagged "scales" and
        # exempt: bf16 compute + quantized sync stays clean
        def q(x):
            y = (x @ x).astype(jnp.float32)
            out = comm.all_reduce_coalesced({0: y}, "dp",
                                            transport="int8")
            return out[0]

        jq = jax.jit(shard_map(q, mesh, (P(),), P()))
        hq = _register("t_wide/q", jq, (_sds((64, 64), jnp.bfloat16),))
        repq = analyze_handle(hq, options={"wide_bytes_threshold": 64})
        assert not _rules_fired(repq, "wide-collective"), repq.findings

        # the exemption is the exact "scales" path segment — a user
        # scope merely CONTAINING the substring must still fire
        def r(x):
            y = (x @ x).astype(jnp.float32)
            with jax.named_scope("loss_rescales"):
                return jax.lax.psum(y, "dp")

        jr = jax.jit(shard_map(r, mesh, (P(),), P()))
        hr = _register("t_wide/r", jr, (_sds((64, 64), jnp.bfloat16),))
        repr_ = analyze_handle(hr, options={"wide_bytes_threshold": 1024})
        assert len(_rules_fired(repr_, "wide-collective")) == 1

    def test_unreduced_psum_scalar_fires_once(self, devices8):
        mesh = create_mesh({"dp": 8}, devices8)

        def bad(x):
            return jnp.mean(x)                  # local mean, no pmean!

        jf = jax.jit(shard_map(bad, mesh, (P("dp"),), P(),
                               check_rep=False))
        h = _register("t_scalar/bad", jf, (_sds((16, 4)),))
        rep = analyze_handle(h)
        fired = _rules_fired(rep, "unreduced-psum-scalar")
        assert len(fired) == 1
        assert "local value" in fired[0].message

        def good(x):
            return jax.lax.pmean(jnp.mean(x), "dp")

        jg = jax.jit(shard_map(good, mesh, (P("dp"),), P(),
                               check_rep=False))
        hg = _register("t_scalar/good", jg, (_sds((16, 4)),))
        assert not _rules_fired(analyze_handle(hg),
                                "unreduced-psum-scalar")

    def test_implicit_reshard_fires_once(self, devices8):
        mesh = create_mesh({"dp": 8}, devices8)

        def f(x):
            x = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P("dp", None)))
            h = x * 2.0
            # forces a GSPMD all-gather no DS transition predicts
            h = jax.lax.with_sharding_constraint(
                h, NamedSharding(mesh, P()))
            return h.sum()

        h = _register("t_resh/f", jax.jit(f), (_sds((16, 8)),),
                      allowed_gspmd={})
        rep = analyze_handle(h, compile=True)
        fired = _rules_fired(rep, "implicit-reshard")
        assert len(fired) == 1
        assert fired[0].subject == "all_gather"
        # same program with the reshard predicted: silent
        h2 = _register("t_resh/ok", jax.jit(f), (_sds((16, 8)),),
                       allowed_gspmd={"all_gather": 1})
        assert not _rules_fired(analyze_handle(h2, compile=True),
                                "implicit-reshard")

    def test_grad_allgather_under_zero2_fires_once(self):
        """Seeded regression to the pre-flat path: a ZeRO-2 plan whose
        records show an fp32 gradient all-gather (or, under the flat
        reduce-scatter-only contract, ANY gradient all-gather)."""
        from hetu_tpu.analysis import CollectiveRecord

        def rec(kind, dtype, scope):
            return CollectiveRecord(kind=kind, axes=("dp",), dtype=dtype,
                                    payload_bytes=1 << 20,
                                    wire_bytes=1.0, scope=scope)

        ctx = AnalysisContext(
            name="t_z2", meta={"grad_comm": {"zero": 2, "flat": True}},
            records=[
                rec("all_gather", "float32", "grad_comm/bucket0"),  # !!
                rec("all_gather", "float32", "grad_comm/bucket0/scales"),
                rec("all_gather", "bfloat16", "param_comm/bucket0"),
                rec("reduce_scatter", "float32", "grad_comm/bucket0"),
            ])
        fired = run_rules(ctx, only=["grad-allgather-under-zero2"])
        assert len(fired) == 1, fired
        assert fired[0].subject == "all_gather:float32"
        assert fired[0].severity == "error"
        # flat contract: even a quantized gradient regather fires
        ctx2 = AnalysisContext(
            name="t_z2b", meta={"grad_comm": {"zero": 2, "flat": True}},
            records=[rec("all_gather", "int8", "grad_comm/bucket0")])
        assert len(run_rules(ctx2,
                             only=["grad-allgather-under-zero2"])) == 1
        # the legacy (non-flat) ZeRO-2 quantized path regathers in int8
        # by design: silent
        ctx3 = AnalysisContext(
            name="t_z2c", meta={"grad_comm": {"zero": 2, "flat": False}},
            records=[rec("all_gather", "int8", "grad_comm/bucket0")])
        assert not run_rules(ctx3, only=["grad-allgather-under-zero2"])
        # not a ZeRO-2 plan (and not flat): silent
        ctx4 = AnalysisContext(
            name="t_z2d", meta={"grad_comm": {"zero": 0}},
            records=[rec("all_gather", "float32", "grad_comm/bucket0")])
        assert not run_rules(ctx4, only=["grad-allgather-under-zero2"])
        # a flat ZeRO-1 plan declares the same reduce-scatter-only
        # contract: in scope despite zero < 2
        ctx5 = AnalysisContext(
            name="t_z2e", meta={"grad_comm": {"zero": 1, "flat": True}},
            records=[rec("all_gather", "int8", "grad_comm/bucket0")])
        assert len(run_rules(ctx5,
                             only=["grad-allgather-under-zero2"])) == 1

    def test_trash_page_write_fires_once_per_seed(self):
        # seed 1: the pre-fix reset() bug — free-list rebuilt WITH page 0
        pool = PagedKVPool(num_layers=1, num_pages=4, page_size=8,
                           kv_heads=1, head_dim=4)
        pool._free = list(range(pool.num_pages - 1, -1, -1))  # includes 0
        ctx = AnalysisContext(name="t_trash",
                              serving={"pool": pool, "tap": []})
        fired = [f for f in run_rules(ctx, only=["trash-page-write"])]
        assert len(fired) == 1 and fired[0].subject == "free-list"

        # seed 2: a LIVE decode row whose page table targets page 0
        pool2 = PagedKVPool(num_layers=1, num_pages=4, page_size=8,
                            kv_heads=1, head_dim=4)
        tap = [{"kind": "decode", "n_live": 1,
                "pos": np.array([4], np.int32),
                "page_tables": np.array([[0, 0]], np.int32)}]
        ctx2 = AnalysisContext(name="t_trash2",
                               serving={"pool": pool2, "tap": tap})
        fired2 = run_rules(ctx2, only=["trash-page-write"])
        assert len(fired2) == 1 and "LIVE row 0" in fired2[0].message

        # clean pool + padding-only tap: silent
        tap_ok = [{"kind": "decode", "n_live": 1,
                   "pos": np.array([4, 0], np.int32),
                   "page_tables": np.array([[2, 0], [0, 0]], np.int32)}]
        ctx3 = AnalysisContext(name="t_trash3",
                               serving={"pool": pool2, "tap": tap_ok})
        assert not run_rules(ctx3, only=["trash-page-write"])

    def test_kv_handoff_unpriced_fires_once_per_seed(self):
        """Serving-cluster handoff contract (ISSUE 11): a cross-replica
        KV-page move whose record lacks the priced edge claim fires
        exactly once; a fully-priced record (what LocalPageTransport
        writes) is silent, and executables without kv_handoff meta are
        out of scope."""
        priced = {"src": 0, "dst": 1, "pages": 3, "payload_bytes": 3072,
                  "edge": {"kind": "ppermute", "payload_bytes": 3072,
                           "count": 1, "tag": "kv_handoff"},
                  "predicted_s": 1.2e-6, "wall_s": 0.001}
        # seed 1: no predicted time at all
        bad = dict(priced, predicted_s=None)
        ctx = AnalysisContext(name="t_handoff",
                              meta={"kv_handoff": [priced, bad]})
        fired = run_rules(ctx, only=["kv-handoff-unpriced"])
        assert len(fired) == 1 and fired[0].severity == "error"
        assert "handoff@1" in fired[0].subject
        # seed 2: edge payload disagrees with the bytes actually moved
        lying = dict(priced, edge=dict(priced["edge"],
                                       payload_bytes=1))
        ctx2 = AnalysisContext(name="t_handoff2",
                               meta={"kv_handoff": [lying]})
        fired2 = run_rules(ctx2, only=["kv-handoff-unpriced"])
        assert len(fired2) == 1 and "1 B" in fired2[0].message
        # exemptions: a priced record, a callable hook, and no meta
        ctx3 = AnalysisContext(name="t_handoff3",
                               meta={"kv_handoff": lambda: [priced]})
        assert not run_rules(ctx3, only=["kv-handoff-unpriced"])
        ctx4 = AnalysisContext(name="t_handoff4", meta={})
        assert not run_rules(ctx4, only=["kv-handoff-unpriced"])

    def test_host_offload_unpriced_fires_once_per_seed(self):
        """Host-tier contract (ISSUE 17): a device↔host page move whose
        record lacks the priced edge claim — or whose byte accounting
        disagrees with pages x page_bytes — fires exactly once; a
        fully-priced record (what HostTier._price writes) is silent,
        ``host_offload_exempt`` records are skipped, and executables
        without host_offload meta are out of scope."""
        priced = {"dir": "evict", "pages": 1, "payload_bytes": 2048,
                  "page_bytes": 2048, "chain_hash": 7,
                  "edge": {"kind": "ppermute", "payload_bytes": 2048,
                           "count": 1, "tag": "host_offload"},
                  "predicted_s": 1.1e-6, "wall_s": 0.0}
        # seed 1: no predicted time at all
        bad = dict(priced, dir="refetch", predicted_s=None)
        ctx = AnalysisContext(name="t_host",
                              meta={"host_offload": [priced, bad]})
        fired = run_rules(ctx, only=["host-offload-unpriced"])
        assert len(fired) == 1 and fired[0].severity == "error"
        assert "host_offload@1" in fired[0].subject
        assert "refetch" in fired[0].subject
        # seed 2: record payload disagrees with pages x page_bytes —
        # the tier moved bytes the claim does not cover (a quantized
        # pool priced at the full-precision page size, say)
        lying = dict(priced, payload_bytes=4096,
                     edge=dict(priced["edge"], payload_bytes=4096))
        ctx2 = AnalysisContext(name="t_host2",
                               meta={"host_offload": [lying]})
        fired2 = run_rules(ctx2, only=["host-offload-unpriced"])
        assert len(fired2) == 1 and "2048" in fired2[0].message
        # seed 3: edge payload disagrees with the record's
        ctx3 = AnalysisContext(
            name="t_host3",
            meta={"host_offload":
                  [dict(priced, edge=dict(priced["edge"],
                                          payload_bytes=1))]})
        fired3 = run_rules(ctx3, only=["host-offload-unpriced"])
        assert len(fired3) == 1 and "1 B" in fired3[0].message
        # exemptions: a priced record, an exempt bad record, a callable
        # hook, a raising hook (accounting lost = error), and no meta
        ctx4 = AnalysisContext(
            name="t_host4",
            meta={"host_offload":
                  [priced, dict(bad, host_offload_exempt=True)]})
        assert not run_rules(ctx4, only=["host-offload-unpriced"])
        ctx5 = AnalysisContext(name="t_host5",
                               meta={"host_offload": lambda: [priced]})
        assert not run_rules(ctx5, only=["host-offload-unpriced"])

        def boom():
            raise RuntimeError("accounting lost")
        ctx6 = AnalysisContext(name="t_host6",
                               meta={"host_offload": boom})
        fired6 = run_rules(ctx6, only=["host-offload-unpriced"])
        assert len(fired6) == 1 and "lost" in fired6[0].message
        ctx7 = AnalysisContext(name="t_host7", meta={})
        assert not run_rules(ctx7, only=["host-offload-unpriced"])

    def test_cow_page_write_fires_once_per_seed(self):
        """Copy-on-write contract: a unified-step tap record whose KV
        write plan targets a CACHED page (in the refcount snapshot —
        read-only whatever the sharer count) fires exactly once per
        offending row; writes to exclusively-owned pages, READS of
        cached pages, and trash-page padding stay silent."""
        pool = PagedKVPool(num_layers=1, num_pages=8, page_size=8,
                           kv_heads=1, head_dim=4)
        # seeded violation: row 0 writes tokens at pos 8..11 -> page-
        # table slot 1 -> page 2, which the snapshot says is shared
        # (refcount 2 = cache + one live sharer).  Four tokens hit it;
        # the rule reports the ROW once, not four findings.
        tap = [{"kind": "unified", "rows": [(0, 8, 4)],
                "page_tables": np.array([[3, 2, 0]], np.int32),
                "refcounts": {2: 2}}]
        ctx = AnalysisContext(name="t_cow",
                              serving={"pool": pool, "tap": tap})
        fired = run_rules(ctx, only=["cow-page-write"])
        assert len(fired) == 1
        assert "page 2" in fired[0].message
        assert "refcount 2" in fired[0].message
        assert fired[0].hint and "copy-on-write" in fired[0].hint

        # a cached page with ZERO live sharers (refcount 1) is still
        # read-only — the index serves it to future lookups
        tap_rc1 = [{"kind": "unified", "rows": [(0, 8, 4)],
                    "page_tables": np.array([[3, 2, 0]], np.int32),
                    "refcounts": {2: 1}}]
        ctx_rc1 = AnalysisContext(name="t_cow1",
                                  serving={"pool": pool, "tap": tap_rc1})
        assert len(run_rules(ctx_rc1, only=["cow-page-write"])) == 1

        # clean: the write cursor starts PAST the shared page (pos 8
        # writes page-table slot 1 = page 3, exclusively owned — never
        # in the cached-page snapshot); page 2 is only READ
        tap_ok = [{"kind": "unified", "rows": [(0, 8, 4)],
                   "page_tables": np.array([[2, 3, 0]], np.int32),
                   "refcounts": {2: 2}}]
        ctx2 = AnalysisContext(name="t_cow2",
                               serving={"pool": pool, "tap": tap_ok})
        assert not run_rules(ctx2, only=["cow-page-write"])

        # trash-page padding is exempt even at refcount > 1
        tap_pad = [{"kind": "unified", "rows": [(0, 0, 2)],
                    "page_tables": np.array([[0, 0, 0]], np.int32),
                    "refcounts": {0: 5}}]
        ctx3 = AnalysisContext(name="t_cow3",
                               serving={"pool": pool, "tap": tap_pad})
        assert not run_rules(ctx3, only=["cow-page-write"])

        # records without a refcount snapshot (cache off) are skipped
        tap_off = [{"kind": "unified", "rows": [(0, 8, 4)],
                    "page_tables": np.array([[3, 2, 0]], np.int32)}]
        ctx4 = AnalysisContext(name="t_cow4",
                               serving={"pool": pool, "tap": tap_off})
        assert not run_rules(ctx4, only=["cow-page-write"])


# ---------------------------------------------------------------------------
# the general pass reproduces PR 1's grad-comm assertions
# ---------------------------------------------------------------------------

class TestGradCommThroughGeneralPass:
    def _train(self, devices8, transport):
        mesh = create_mesh({"dp": 8}, devices8)
        g = DefineAndRunGraph(f"t_gc_{transport}")
        g.mesh = mesh
        clear_executables(g.name)
        with ht.graph(g):
            x = ht.parallel_placeholder("float32", (16, 8),
                                        pspec=P("dp", None), name="x")
            y = ht.parallel_placeholder("float32", (16, 1),
                                        pspec=P("dp", None), name="y")
            w = ht.parameter(np.zeros((8, 1), np.float32), name="w")
            b = ht.parameter(np.zeros((1,), np.float32), name="b")
            loss = ops.reduce_mean((ops.matmul(x, w) + b - y) ** 2)
            op = optim.AdamOptimizer(lr=1e-2,
                                     grad_comm=transport).minimize(loss)
            rng = np.random.RandomState(0)
            g.run(loss, [loss, op], {x: rng.randn(16, 8).astype(np.float32),
                                     y: rng.randn(16, 1)
                                     .astype(np.float32)})
        assert g._grad_comm_active
        (handle,) = g.analysis_handles()
        return handle

    @pytest.mark.parametrize("transport", ["fp32", "bf16", "int8"])
    def test_emission_matches_prediction(self, devices8, transport):
        handle = self._train(devices8, transport)
        # PR 1's verify_grad_comm_emission, unchanged, via the new pass
        analysis.verify_grad_comm(handle)
        # and the jaxpr inventory agrees with the prediction kind-for-kind
        pred, extra = analysis.grad_comm_prediction(handle)
        want = dict(extra)
        for p in pred:
            want[p["kind"]] = want.get(p["kind"], 0) + 1
        rep = analyze_handle(handle)
        assert rep.collective_counts() == want
        # gradient-sync records carry the bucket attribution tag
        tagged = [r for r in rep.records if "grad_comm/bucket" in r.scope]
        assert len(tagged) == len(pred)

    def test_emission_drift_detected(self, devices8):
        handle = self._train(devices8, "fp32")
        gc = dict(handle.meta["grad_comm"])
        gc["transport"] = "int8"     # claim a different transport
        handle.meta["grad_comm"] = gc
        with pytest.raises(AssertionError, match="do not match"):
            analysis.verify_grad_comm(handle)

    def test_clean_train_step_has_no_findings(self, devices8):
        handle = self._train(devices8, "int8")
        rep = analyze_handle(handle, compile=True)
        assert rep.findings == [], rep.findings

    def test_cached_plan_reregisters_after_registry_clear(self, devices8):
        """clear_executables() must not make a LIVE cached plan vanish
        from analysis forever: its next run re-registers it under the
        original name."""
        mesh = create_mesh({"dp": 8}, devices8)
        g = DefineAndRunGraph("t_rereg")
        g.mesh = mesh
        clear_executables("t_rereg")
        with ht.graph(g):
            x = ht.parallel_placeholder("float32", (16, 4),
                                        pspec=P("dp", None), name="x")
            w = ht.parameter(np.zeros((4, 1), np.float32), name="w")
            loss = ops.reduce_mean(ops.matmul(x, w) ** 2)
            op = optim.SGDOptimizer(lr=0.1,
                                    grad_comm="fp32").minimize(loss)
            X = np.ones((16, 4), np.float32)
            g.run(loss, [loss, op], {x: X})
            assert [h.name for h in g.analysis_handles()] \
                == ["t_rereg/plan0"]
            clear_executables("t_rereg")
            assert g.analysis_handles() == []
            g.run(loss, [loss, op], {x: X})    # cached plan, re-executed
            assert [h.name for h in g.analysis_handles()] \
                == ["t_rereg/plan0"]


# ---------------------------------------------------------------------------
# serving executables are registered + analyzable
# ---------------------------------------------------------------------------

class TestServingAnalysis:
    def test_engine_registers_clean_executables(self):
        from hetu_tpu.models import GPTConfig, GPTLMHeadModel
        from hetu_tpu.serving import Engine
        ht.set_seed(3)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=64)
        with ht.graph("eager", create_new=True):
            model = GPTLMHeadModel(cfg)
            model.logits(np.zeros((1, 4), np.int32))
            state = {k: np.asarray(v) for k, v in
                     model.state_dict().items()}
        clear_executables("t_serve")
        clock = [0.0]
        eng = Engine(state, cfg, num_pages=8, page_size=8, max_batch=2,
                     name="t_serve", time_fn=lambda: clock[0])
        eng.add_request([1, 2, 3], max_new_tokens=3)
        while eng.has_work:
            eng.step()
            clock[0] += 1.0
        names = [h.name for h in
                 analysis.iter_executables("t_serve")]
        assert names == ["t_serve/unified"]    # ONE executable, no grid
        report = analysis.analyze_registered("t_serve", compile=True)
        assert report.findings == [], report.findings
        # the page buffers are donated (donation-miss stays quiet even
        # at a 1-byte threshold)
        for h in analysis.iter_executables("t_serve"):
            rep = analyze_handle(h,
                                 options={"donation_bytes_threshold": 1})
            assert not _rules_fired(rep, "donation-miss"), h.name
        # inventory: single-device serving program does no communication
        assert all(not rep.records
                   for rep in report.executables.values())
        # lifecycle: a new same-name engine owns the namespace — its
        # construction drops the old engine's handle (stale dead-pool
        # snapshots) and registers its own; unregister empties it
        eng2 = Engine(state, cfg, num_pages=8, page_size=8, max_batch=2,
                      name="t_serve", time_fn=lambda: clock[0])
        handles = analysis.iter_executables("t_serve")
        assert [h.name for h in handles] == ["t_serve/unified"]
        eng2.add_request([4, 2], max_new_tokens=2)
        while eng2.has_work:
            eng2.step()
            clock[0] += 1.0
        for h in analysis.iter_executables("t_serve"):
            assert h.meta["serving"]()["pool"] is eng2.pool
        eng2.unregister_analysis()
        assert analysis.iter_executables("t_serve") == []


# ---------------------------------------------------------------------------
# baseline gate mechanics + the CLI (the CI lint-graph target)
# ---------------------------------------------------------------------------

class TestBaselineGate:
    def _report(self, counts, findings=()):
        from hetu_tpu.analysis import (AnalysisReport, CollectiveRecord,
                                       ExecutableReport, Finding)
        rep = AnalysisReport()
        ex = ExecutableReport(name="exe")
        for kind, n in counts.items():
            for _ in range(n):
                ex.records.append(CollectiveRecord(
                    kind=kind, axes=("dp",), dtype="float32",
                    payload_bytes=100, wire_bytes=175.0))
        ex.findings = [Finding(rule=r, subject=s, message="m",
                               executable="exe") for r, s in findings]
        rep.add(ex)
        return rep

    def test_count_and_byte_regressions_fail(self):
        base = self._report({"all_reduce": 1}).to_dict()
        assert not self._report({"all_reduce": 1}) \
            .check_against_baseline(base)
        assert self._report({"all_reduce": 2}) \
            .check_against_baseline(base)      # count regression
        assert self._report({"all_reduce": 1, "all_gather": 1}) \
            .check_against_baseline(base)      # new kind
        # fewer collectives: pass (improvement)
        base2 = self._report({"all_reduce": 3}).to_dict()
        assert not self._report({"all_reduce": 2}) \
            .check_against_baseline(base2)

    def test_new_finding_fails_known_finding_passes(self):
        base = self._report({}, findings=[("donation-miss", "arg0")]) \
            .to_dict()
        ok = self._report({}, findings=[("donation-miss", "arg0")])
        assert not ok.check_against_baseline(base)
        bad = self._report({}, findings=[("donation-miss", "arg0"),
                                         ("wide-collective",
                                          "all_reduce:float32")])
        problems = bad.check_against_baseline(base)
        assert problems and "wide-collective" in problems[0]

    def test_missing_baseline_entry_fails(self):
        rep = self._report({"all_reduce": 1})
        assert rep.check_against_baseline(None)
        assert rep.check_against_baseline({"executables": {}})


@pytest.mark.lint_graph
def test_lint_graph_gate_passes_on_clean_tree():
    """The tier-1 CI gate: `python -m hetu_tpu.analysis --check` against
    the checked-in ANALYSIS_BASELINE.json must pass on a clean tree —
    now over all five gated executable families (dp/ZeRO-2 flat train,
    serving prefill/decode, TP/SP, pipeline MPMD+SPMD, dropless MoE),
    with the per-edge pass explaining 100% of emitted collectives.

    One subprocess exercises the whole CLI surface: --format json (CI
    artifact), --explain (hint mode), exit code 0.
    """
    import json as _json
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)       # the CLI sets its own device count
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "hetu_tpu.analysis", "--check",
         "--format", "json", "--explain"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, \
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert "lint-graph gate OK" in proc.stdout
    payload, _ = _json.JSONDecoder().raw_decode(
        proc.stdout[proc.stdout.index("{"):])
    exes = payload["executables"]
    for family in ("gate_train", "gate_serving", "gate_tp", "gate_pipe",
                   "gate_moe"):
        assert any(n.startswith(family) for n in exes), sorted(exes)
    for name, ex in exes.items():
        cov = ex["edge_coverage"]
        assert cov["explained"] == cov["total"], (name, cov)
        assert ex["findings"] == [], (name, ex["findings"])
        # ISSUE 8: the memory gate rides the same tier-1 marker — every
        # gated executable carries the static peak-HBM accounting with
        # the XLA cross-check inside ±10% (abs floor for sub-64KB
        # programs, enforced by the CLI itself via exit code 0 above)
        mem = ex.get("memory")
        assert mem and mem["peak_bytes"] > 0, (name, mem)
        assert mem.get("xla_total_bytes", 0) > 0, (name, mem)
        delta = abs(mem["peak_bytes"] - mem["xla_total_bytes"])
        assert delta <= max(0.1 * mem["xla_total_bytes"], 1 << 16) \
            or abs(mem.get("xla_delta_pct") or 0) <= 10.0, (name, mem)
        # ISSUE 10: the step-time gate rides the same tier-1 marker —
        # every gated executable carries the cost accounting with the
        # XLA cost_analysis cross-check (±10% / absolute floors,
        # enforced by the CLI itself via exit code 0 above) and the
        # baseline pins its cost.* keys
        cost = ex.get("cost")
        assert cost and cost["flops"] > 0, (name, cost)
        assert cost["hbm_bytes"] > 0 and cost["step_time_us"] > 0, \
            (name, cost)
        assert cost["bound"] in ("compute", "hbm", "comm"), (name, cost)
        assert cost.get("xla_flops", 0) > 0, (name, cost)
        assert cost.get("xla_bytes_accessed", 0) > 0, (name, cost)
        assert cost.get("xla_flops_delta_pct") is not None, (name, cost)
        # ISSUE 18: the serving-protocol gate rides the same tier-1
        # marker — every gated executable carries protocol coverage
        # (events/kinds/violations/lost_hooks), the lifecycle machines
        # replay every trace with ZERO violations, and no record plane
        # silently fell out of the stream
        proto = ex.get("protocol")
        assert proto is not None, (name, "protocol section missing")
        assert proto["violations"] == 0, (name, proto)
        assert proto["lost_hooks"] == [], (name, proto)
        if name.startswith("gate_serving"):
            # serving gates MUST emit a real event stream — an empty
            # one means the taps/pool logs vanished and every trace
            # rule went vacuously green
            assert proto["events"] > 0, (name, proto)
            assert proto["kinds"], (name, proto)
        else:
            # train/TP/pipe/MoE gates pin an EMPTY stream: a train plan
            # that suddenly emits serving events is itself a surprise
            assert proto["events"] == 0, (name, proto)
    # the serving family's union vocabulary covers every plane the
    # trace rules inspect (the per-rule version of this is the vacuity
    # meta-test in tests/test_protocol.py)
    union = set()
    for name, ex in exes.items():
        union |= set(ex["protocol"]["kinds"])
    for kind in ("page.write", "page.share", "page.unshare",
                 "host.stage", "host.refetch", "wire.inject",
                 "req.adopt", "req.write", "fence.complete"):
        assert kind in union, (kind, sorted(union))
    # --explain printed the per-executable edge sections after the JSON
    assert "predicted edges" in proc.stdout
    assert "=== gate_tp/plan0 ===" in proc.stdout
