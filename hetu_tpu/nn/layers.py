"""Standard nn layers (reference ``python/hetu/nn/modules/``: Linear/Conv/
Norm/Embedding/Dropout/Activation/Loss layer tree)."""
from __future__ import annotations

import math
from typing import Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from .. import ops
from ..core.dtype import canonicalize_dtype
from ..graph.ctor import (ConstantInitializer, HeUniformInitializer,
                          NormalInitializer, UniformInitializer,
                          XavierUniformInitializer, parameter)
from .module import Module


class Linear(Module):
    """y = x W^T + b, weight stored [out_features, in_features]
    (reference nn/modules/linear.py convention)."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 dtype=None, name: str = "linear"):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        bound = 1.0 / math.sqrt(in_features)
        self.weight = parameter(
            HeUniformInitializer(), (out_features, in_features), dtype=dtype,
            name=f"{name}.weight")
        if bias:
            self.bias = parameter(UniformInitializer(bound),
                                  (out_features,), dtype=dtype,
                                  name=f"{name}.bias")
        else:
            self.register_parameter("bias", None)

    def forward(self, x):
        return ops.linear(x, self.weight, self.bias, trans_b=True)

    def extra_repr(self):
        return f"in={self.in_features}, out={self.out_features}"


class Embedding(Module):
    def __init__(self, num_embeddings: int, embedding_dim: int, dtype=None,
                 name: str = "embedding"):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = parameter(NormalInitializer(0.0, 1.0),
                                (num_embeddings, embedding_dim), dtype=dtype,
                                name=f"{name}.weight")

    def forward(self, ids):
        return ops.embedding_lookup(self.weight, ids)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class LayerNorm(Module):
    def __init__(self, normalized_shape: Union[int, Sequence[int]],
                 eps: float = 1e-5, dtype=None, name: str = "ln"):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.eps = eps
        self.weight = parameter(ConstantInitializer(1.0),
                                self.normalized_shape, dtype=dtype,
                                name=f"{name}.weight")
        self.bias = parameter(ConstantInitializer(0.0),
                              self.normalized_shape, dtype=dtype,
                              name=f"{name}.bias")

    def forward(self, x):
        return ops.layer_norm(x, self.weight, self.bias, self.eps)


class RMSNorm(Module):
    def __init__(self, dim: int, eps: float = 1e-6, dtype=None,
                 name: str = "rmsnorm"):
        super().__init__()
        self.eps = eps
        self.weight = parameter(ConstantInitializer(1.0), (dim,), dtype=dtype,
                                name=f"{name}.weight")

    def forward(self, x):
        return ops.rms_norm(x, self.weight, self.eps)


class BatchNorm2d(Module):
    """BatchNorm with running statistics.

    Training normalizes with batch stats; in eager graphs running stats are
    updated in place each forward (torch semantics).  Under define-and-run,
    stats update eagerly only when the forward executes eagerly; for jitted
    training loops call :meth:`update_stats` with fetched batch stats, or
    keep BN models on the eager graph (the reference CNN workloads do the
    equivalent — BN lives in its v1 CNN examples).
    """

    def __init__(self, num_features: int, eps: float = 1e-5,
                 momentum: float = 0.1, dtype=None, name: str = "bn"):
        super().__init__()
        self.eps, self.momentum = eps, momentum
        self.weight = parameter(ConstantInitializer(1.0), (num_features,),
                                dtype=dtype, name=f"{name}.weight")
        self.bias = parameter(ConstantInitializer(0.0), (num_features,),
                              dtype=dtype, name=f"{name}.bias")
        self.register_buffer("running_mean", np.zeros(num_features, np.float32))
        self.register_buffer("running_var", np.ones(num_features, np.float32))

    def update_stats(self, batch_mean, batch_var) -> None:
        m = self.momentum
        self._buffers["running_mean"] = (
            (1 - m) * self._buffers["running_mean"] + m * np.asarray(batch_mean))
        self._buffers["running_var"] = (
            (1 - m) * self._buffers["running_var"] + m * np.asarray(batch_var))
        object.__setattr__(self, "running_mean", self._buffers["running_mean"])
        object.__setattr__(self, "running_var", self._buffers["running_var"])

    def forward(self, x):
        if self.training:
            out = ops.batch_norm(x, self.weight, self.bias,
                                 training=True, eps=self.eps)
            mean_t, var_t = ops.batch_norm_stats(x)
            if mean_t._data is not None:  # eager: update running stats now
                self.update_stats(mean_t.numpy(), var_t.numpy())
            return out
        return ops.batch_norm(x, self.weight, self.bias,
                              self._buffers["running_mean"],
                              self._buffers["running_var"],
                              training=False, eps=self.eps)


class Conv2d(Module):
    def __init__(self, in_channels: int, out_channels: int,
                 kernel_size: Union[int, Sequence[int]], stride=1, padding=0,
                 bias: bool = True, dtype=None, name: str = "conv"):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.stride, self.padding = stride, padding
        self.weight = parameter(HeUniformInitializer(),
                                (out_channels, in_channels, *k), dtype=dtype,
                                name=f"{name}.weight")
        if bias:
            bound = 1.0 / math.sqrt(in_channels * k[0] * k[1])
            self.bias = parameter(UniformInitializer(bound), (out_channels,),
                                  dtype=dtype, name=f"{name}.bias")
        else:
            self.register_parameter("bias", None)

    def forward(self, x):
        return ops.conv2d(x, self.weight, self.bias, self.stride, self.padding)


class MaxPool2d(Module):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def forward(self, x):
        return ops.max_pool(x, self.kernel_size, self.stride, self.padding)


class AvgPool2d(Module):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def forward(self, x):
        return ops.avg_pool(x, self.kernel_size, self.stride, self.padding)


class Dropout(Module):
    def __init__(self, p: float = 0.5):
        super().__init__()
        self.p = p

    def forward(self, x):
        return ops.dropout(x, self.p, training=self.training)


class Identity(Module):
    def forward(self, x):
        return x


class ReLU(Module):
    def forward(self, x):
        return ops.relu(x)


class GeLU(Module):
    def forward(self, x):
        return ops.gelu(x)


GELU = GeLU


class SiLU(Module):
    def forward(self, x):
        return ops.silu(x)


class Tanh(Module):
    def forward(self, x):
        return ops.tanh(x)


class Sigmoid(Module):
    def forward(self, x):
        return ops.sigmoid(x)


class LeakyReLU(Module):
    def __init__(self, alpha: float = 0.01):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return ops.leaky_relu(x, self.alpha)


class Softmax(Module):
    def __init__(self, axis: int = -1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return ops.softmax(x, self.axis)


class NLLLoss(Module):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, log_probs, target):
        return ops.nll_loss(log_probs, target, self.reduction)


class CrossEntropyLoss(Module):
    def __init__(self, reduction: str = "mean", ignore_index=None):
        super().__init__()
        self.reduction = reduction
        self.ignore_index = ignore_index

    def forward(self, logits, target):
        return ops.softmax_cross_entropy(logits, target, self.reduction,
                                         self.ignore_index)


class MSELoss(Module):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, pred, target):
        return ops.mse_loss(pred, target, self.reduction)


class BCELoss(Module):
    def __init__(self, reduction: str = "mean", with_logits: bool = False):
        super().__init__()
        self.reduction = reduction
        self.with_logits = with_logits

    def forward(self, pred, target):
        return ops.binary_cross_entropy(pred, target, self.reduction,
                                        self.with_logits)


class KLDivLoss(Module):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, log_probs, target):
        return ops.kl_div(log_probs, target, self.reduction)
