"""Model-parallel layers (TP / SP / vocab-parallel).

TPU-native re-expression of the reference's ds-annotation-driven parallel
modules (``python/hetu/nn/modules/parallel_multi_ds.py:7-14``:
HtMultiColumnParallelLinear / HtMultiRowParallelLinear /
HtMultiParallelEmbedding / HtMultiVocabParallelEmbedding /
HtMultiParallelLayerNorm / HtMultiParallelRMSNorm).

Instead of DistributedStates + deduced NCCL collectives, layers annotate
parameters and activations with ``PartitionSpec``s over a named mesh
(axes ``dp``/``tp``/...); GSPMD inserts the collectives the reference's
``SubstituteCommOp`` would (allreduce after row-parallel matmul, allgather
at SP boundaries, masked-gather+psum for vocab-parallel lookup/CE).
The DS spec remains available per layer (``.ds()``) for parity with the
reference's JSON ``ds_parallel_config`` IR (see :func:`config2ds`).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import PartitionSpec as P

from .. import ops
from ..graph.ctor import (ConstantInitializer, HeUniformInitializer,
                          Initializer, NormalInitializer, UniformInitializer,
                          XavierNormalInitializer, parallel_parameter)
from ..parallel.dstates import (DUPLICATE, NULL_HETERO_DIM, DistributedStates,
                                DistributedStatesUnion)
from .module import Module


def sharded(t, pspec, tag: Optional[str] = None):
    """Annotate an activation with a sharding constraint.

    Returns a NEW tensor (identity op) carrying the annotation, so other
    consumers of ``t`` keep their own layout — annotating in place would
    silently reshard every consumer.

    ``tag`` names the boundary for the static analyzer's per-edge
    attribution (``--explain`` prints it as the edge's consumer site:
    "tp_row_reduce", "sp_gather", ...); purely provenance, no effect on
    lowering.
    """
    out = ops.functional._op("sharding_constraint", lambda x: x, [t],
                             attrs={"_edge_tag": tag} if tag else None)
    out.pspec = pspec
    return out


def _norm_out_spec(out, sp, dp_axis, tp_axis, seq_axis):
    """Post-norm activation spec: SP shards seq over tp (within each cp
    shard when CP is active); plain CP keeps seq on cp only."""
    if out.ndim < 2:
        return out
    if sp:
        seq_entry = (seq_axis, tp_axis) if seq_axis else tp_axis
        return sharded(out, P(dp_axis, seq_entry,
                              *([None] * (out.ndim - 2))),
                       tag="sp_norm_scatter")
    if seq_axis:
        return sharded(out, P(dp_axis, seq_axis,
                              *([None] * (out.ndim - 2))),
                       tag="cp_seq_split")
    return out


class ColumnParallelLinear(Module):
    """Y = X W^T, W [out, in] split along out across ``tp_axis``.

    Output stays split on the feature dim (gather=False) or is gathered
    (gather=True), mirroring the reference's gather_output flag.
    With ``sp=True`` the input is expected sequence-sharded over tp and
    GSPMD folds the allgather into the matmul (Megatron-SP).
    """

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 gather_output: bool = False, dp_axis: str = "dp",
                 tp_axis: str = "tp", seq_axis: Optional[str] = None,
                 dtype=None, init: Optional[Initializer] = None,
                 name: str = "colp"):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.gather_output = gather_output
        self.dp_axis, self.tp_axis = dp_axis, tp_axis
        self.seq_axis = seq_axis  # CP: keep seq dim sharded (dim 1 of 3D)
        self.weight = parallel_parameter(
            init or XavierNormalInitializer(), (out_features, in_features),
            pspec=P(tp_axis, None), dtype=dtype, name=f"{name}.weight")
        if bias:
            self.bias = parallel_parameter(
                ConstantInitializer(0.0), (out_features,), pspec=P(tp_axis),
                dtype=dtype, name=f"{name}.bias")
        else:
            self.register_parameter("bias", None)

    def ds(self, num_devices: int, tp: int) -> DistributedStates:
        return DistributedStates(num_devices,
                                 {0: tp, DUPLICATE: num_devices // tp},
                                 order=[-1, 0])

    def forward(self, x):
        out = ops.linear(x, self.weight, self.bias, trans_b=True)
        spec = [self.dp_axis] + [None] * (out.ndim - 2)
        spec.append(None if self.gather_output else self.tp_axis)
        if self.seq_axis and out.ndim >= 3:
            spec[1] = self.seq_axis
        return sharded(out, P(*spec),
                       tag="tp_col_gather" if self.gather_output
                       else "tp_col_split")


class RowParallelLinear(Module):
    """Y = X W^T, W [out, in] split along in; input feature-sharded; the
    partial(-2) output is reduced (psum) by GSPMD — or reduce-scattered to
    sequence shards when ``sp=True`` (Megatron-SP)."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True,
                 sp: bool = False, dp_axis: str = "dp", tp_axis: str = "tp",
                 seq_axis: Optional[str] = None,
                 dtype=None, init: Optional[Initializer] = None,
                 name: str = "rowp"):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.sp = sp
        self.dp_axis, self.tp_axis = dp_axis, tp_axis
        self.seq_axis = seq_axis
        self.weight = parallel_parameter(
            init or XavierNormalInitializer(), (out_features, in_features),
            pspec=P(None, tp_axis), dtype=dtype, name=f"{name}.weight")
        if bias:
            # bias is applied after the reduction -> replicated over tp
            self.bias = parallel_parameter(
                ConstantInitializer(0.0), (out_features,), pspec=P(),
                dtype=dtype, name=f"{name}.bias")
        else:
            self.register_parameter("bias", None)

    def ds(self, num_devices: int, tp: int) -> DistributedStates:
        return DistributedStates(num_devices,
                                 {1: tp, DUPLICATE: num_devices // tp},
                                 order=[-1, 1])

    def forward(self, x):
        # constrain input to feature-sharded so the matmul contracts the
        # sharded dim (partial result) and GSPMD places the psum here
        in_spec = [self.dp_axis] + [None] * (x.ndim - 2) + [self.tp_axis]
        if self.seq_axis and x.ndim >= 3:
            in_spec[1] = self.seq_axis
        x = sharded(x, P(*in_spec), tag="tp_row_input")
        out = ops.linear(x, self.weight, None, trans_b=True)
        if self.sp:
            # reduce-scatter onto sequence shards (dim 1 of [b, s, h]);
            # with CP the seq dim carries both axes (cp outer, tp inner)
            seq_entry = (self.seq_axis, self.tp_axis) if self.seq_axis \
                else self.tp_axis
            out_spec = [self.dp_axis, seq_entry] + [None] * (out.ndim - 2)
        else:
            out_spec = [self.dp_axis] + [None] * (out.ndim - 1)
            if self.seq_axis and out.ndim >= 3:
                out_spec[1] = self.seq_axis
        out = sharded(out, P(*out_spec),
                      tag="sp_row_scatter" if self.sp
                      else "tp_row_reduce")
        if self.bias is not None:
            out = sharded(out + self.bias, P(*out_spec))
        return out


class ParallelEmbedding(Module):
    """Embedding split along the hidden dim (reference
    HtMultiParallelEmbedding)."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 dp_axis: str = "dp", tp_axis: str = "tp", dtype=None,
                 init: Optional[Initializer] = None, name: str = "embed"):
        super().__init__()
        self.num_embeddings, self.embedding_dim = num_embeddings, embedding_dim
        self.dp_axis, self.tp_axis = dp_axis, tp_axis
        self.weight = parallel_parameter(
            init or NormalInitializer(0.0, 0.02),
            (num_embeddings, embedding_dim), pspec=P(None, tp_axis),
            dtype=dtype, name=f"{name}.weight")

    def forward(self, ids):
        out = ops.embedding_lookup(self.weight, ids)
        spec = [self.dp_axis] + [None] * (out.ndim - 2) + [self.tp_axis]
        return sharded(out, P(*spec), tag="tp_embed_split")


class VocabParallelEmbedding(Module):
    """Embedding split along the vocab dim (reference
    HtMultiVocabParallelEmbedding): each shard holds a vocab range; GSPMD
    lowers the lookup to masked local gather + psum over tp."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 dp_axis: str = "dp", tp_axis: str = "tp",
                 seq_axis: Optional[str] = None, dtype=None,
                 init: Optional[Initializer] = None, name: str = "vocab_embed"):
        super().__init__()
        self.num_embeddings, self.embedding_dim = num_embeddings, embedding_dim
        self.dp_axis, self.tp_axis = dp_axis, tp_axis
        self.seq_axis = seq_axis
        self.weight = parallel_parameter(
            init or NormalInitializer(0.0, 0.02),
            (num_embeddings, embedding_dim), pspec=P(tp_axis, None),
            dtype=dtype, name=f"{name}.weight")

    def ds(self, num_devices: int, tp: int) -> DistributedStates:
        return DistributedStates(num_devices,
                                 {0: tp, DUPLICATE: num_devices // tp},
                                 order=[-1, 0])

    def forward(self, ids):
        out = ops.embedding_lookup(self.weight, ids)
        spec = [self.dp_axis] + [None] * (out.ndim - 1)
        if self.seq_axis and out.ndim >= 3:
            spec[1] = self.seq_axis
        return sharded(out, P(*spec), tag="vocab_embed_reduce")


class ParallelLayerNorm(Module):
    """LayerNorm with sequence-parallel support (reference
    HtMultiParallelLayerNorm with ``sp`` flag, parallel_multi_ds.py:156-170):
    with sp=True activations stay sequence-sharded across the TP group."""

    def __init__(self, normalized_shape, sp: bool = False,
                 dp_axis: str = "dp", tp_axis: str = "tp",
                 seq_axis: Optional[str] = None, eps: float = 1e-5,
                 dtype=None, name: str = "ln"):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.sp, self.eps = sp, eps
        self.dp_axis, self.tp_axis = dp_axis, tp_axis
        self.seq_axis = seq_axis
        self.weight = parallel_parameter(ConstantInitializer(1.0),
                                         tuple(normalized_shape), pspec=P(),
                                         dtype=dtype, name=f"{name}.weight")
        self.bias = parallel_parameter(ConstantInitializer(0.0),
                                       tuple(normalized_shape), pspec=P(),
                                       dtype=dtype, name=f"{name}.bias")

    def forward(self, x):
        out = ops.layer_norm(x, self.weight, self.bias, self.eps)
        return _norm_out_spec(out, self.sp, self.dp_axis, self.tp_axis,
                              self.seq_axis)


class ParallelRMSNorm(Module):
    """RMSNorm with sequence-parallel support (HtMultiParallelRMSNorm)."""

    def __init__(self, dim: int, sp: bool = False, dp_axis: str = "dp",
                 tp_axis: str = "tp", seq_axis: Optional[str] = None,
                 eps: float = 1e-6, dtype=None,
                 name: str = "rmsnorm"):
        super().__init__()
        self.sp, self.eps = sp, eps
        self.dp_axis, self.tp_axis = dp_axis, tp_axis
        self.seq_axis = seq_axis
        self.weight = parallel_parameter(ConstantInitializer(1.0), (dim,),
                                         pspec=P(), dtype=dtype,
                                         name=f"{name}.weight")

    def forward(self, x):
        out = ops.rms_norm(x, self.weight, self.eps)
        return _norm_out_spec(out, self.sp, self.dp_axis, self.tp_axis,
                              self.seq_axis)


def vocab_parallel_cross_entropy(logits, target, dp_axis: str = "dp",
                                 tp_axis: str = "tp",
                                 seq_axis: Optional[str] = None,
                                 reduction: str = "mean",
                                 ignore_index: Optional[int] = None):
    """CE over vocab-sharded logits (reference
    ops/VocabParallelCrossEntropyLoss.cc): keep logits sharded on the vocab
    dim through the log-softmax so the max/sum reductions become psums over
    tp instead of materializing the full vocab."""
    spec = [dp_axis] + [None] * (logits.ndim - 2) + [tp_axis]
    if seq_axis and logits.ndim >= 3:
        spec[1] = seq_axis
    logits = sharded(logits, P(*spec), tag="vocab_ce_shard")
    loss = ops.softmax_cross_entropy(logits, target, reduction=reduction,
                                     ignore_index=ignore_index)
    return loss


# ---------------------------------------------------------------------------
# host-side data slicing + JSON ds config IR (reference config2ds)
# ---------------------------------------------------------------------------

def parallel_data_provider(global_data: np.ndarray, ds: DistributedStates,
                           device_index: int) -> np.ndarray:
    """Slice the local shard of a global host array
    (reference parallel_data_provider, parallel_multi_ds.py:16)."""
    return global_data[ds.local_slice(global_data.shape, device_index)]


def config2ds(config: Dict) -> Tuple[DistributedStatesUnion, List[List[int]]]:
    """Parse one reference-style JSON ds config entry into a DS union +
    device-id groups (reference config2ds, parallel_multi_ds.py:88-122).

    Keys: ``type`` (placeholder|variable), ``split`` {dim: [per-union counts]},
    ``dup`` [counts], ``device_group_union`` [[ids...]], ``zero``.
    """
    ds_list, dg_list = [], []
    if config["type"] == "placeholder":
        hetero_dim = 0
    elif config["type"] == "variable":
        hetero_dim = -1
    else:
        raise ValueError(f"unsupported type {config['type']!r}")
    hetero_sum = len(config["device_group_union"])
    if hetero_sum == 1:
        hetero_dim = NULL_HETERO_DIM
    for i in range(hetero_sum):
        num_devices = len(config["device_group_union"][i]) * hetero_sum
        split = {int(k): v[i] for k, v in config.get("split", {}).items()}
        states = {DUPLICATE: config["dup"][i], **split}
        zero = False
        if config["type"] == "placeholder":
            order = sorted(split.keys()) + [-1]
        else:
            order = [-1] + sorted(split.keys())
            zero = bool(config.get("zero", False))
        ds_list.append(DistributedStates(num_devices, states, order, zero))
        dg_list.append(list(config["device_group_union"][i]))
    return DistributedStatesUnion(ds_list, hetero_dim), dg_list
