"""Mixture-of-Experts with expert parallelism (EP).

TPU-native re-expression of the reference's v1 MoE stack
(``hetu/v1/python/hetu/layers/moe_layer.py:45`` ``MoELayer``/``Expert``,
gates ``TopGate.py``/``KTop1Gate.py``/``HashGate.py``/``SAMGate.py``/
``BalanceGate.py``, HetuMoE).

Instead of the reference's layout_transform + AllToAll CUDA ops, dispatch
is expressed as dense one-hot einsums (GShard style) so the whole layer is
three large batched matmuls on the MXU; expert parallelism comes from
sharding the expert dim of the dispatched activations and the stacked
expert weights over an ``ep`` mesh axis — GSPMD then lowers the
dispatch/combine einsums to the same all-to-alls the reference issues
explicitly (``v1/python/hetu/gpu_ops/AllToAll.py``).

Gate families (parity with the reference):
- :class:`TopKGate`     — GShard top-1/top-k with capacity + balance loss
                          (``TopGate.py`` topkgating)
- :class:`KTop1Gate`    — k prototypes, top-1 over E/k experts each
                          (``KTop1Gate.py`` ktop1gating)
- :class:`HashGate`     — static hash routing, no learned gate
                          (``HashGate.py`` hashgating)
- :class:`SAMGate`      — switch-aware: top-1 expert *group* then top-k
                          inside the group + alignment loss (``SAMGate.py``)
- :class:`BalanceGate`  — BASE-layer balanced assignment via Sinkhorn
                          iterations (``BalanceGate.py``)
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import ops
from ..graph.ctor import (ConstantInitializer, Initializer,
                          NormalInitializer, XavierNormalInitializer,
                          parallel_parameter)
from ..ops.moe_dispatch import capacity_tokens
from .module import Module
from .parallel import sharded


# ---------------------------------------------------------------------------
# gating maths (pure jnp; static shapes, no data-dependent control flow)
# ---------------------------------------------------------------------------

def _balance_loss(gates, mask):
    """l_aux = E * sum_e mean_t(gates) * mean_t(mask) (TopGate.py
    balance_loss)."""
    num_experts = gates.shape[-1]
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask.astype(gates.dtype), axis=0)
    return jnp.sum(me * ce) * num_experts


def _positions_in_expert(mask, offset=None):
    """Per-token slot index within its expert: exclusive running count of
    earlier tokens routed to the same expert. [T, E] -> [T]."""
    pos = jnp.cumsum(mask, axis=0) - 1
    if offset is not None:
        pos = pos + offset
    return jnp.sum(pos * mask, axis=1)


def _dispatch_combine(masks, gate_vals, capacity):
    """Build dispatch [T, E, C] (0/1) and combine [T, E, C] (gate-weighted)
    tensors from per-choice expert masks and gate values.

    masks: list of [T, E] one-hot masks (choice order = priority order)
    gate_vals: list of [T] gate weights per choice
    """
    T, E = masks[0].shape
    dispatch = jnp.zeros((T, E, capacity), masks[0].dtype)
    combine = jnp.zeros((T, E, capacity), gate_vals[0].dtype)
    counts = jnp.zeros((1, E), masks[0].dtype)
    for mask, gv in zip(masks, gate_vals):
        loc = _positions_in_expert(mask, offset=counts)           # [T]
        counts = counts + jnp.sum(mask, axis=0, keepdims=True)
        keep = (loc < capacity).astype(mask.dtype)                # capacity drop
        slot = jax.nn.one_hot(loc.astype(jnp.int32), capacity,
                              dtype=mask.dtype)                   # [T, C]
        d = (mask * keep[:, None])[:, :, None] * slot[:, None, :]
        dispatch = dispatch + d
        combine = combine + gv[:, None, None] * d.astype(gv.dtype)
    return dispatch, combine


def topk_gating_impl(logits, k, capacity_factor):
    """GShard-style top-k gating (reference TopGate.py topkgating).

    Returns (l_aux, combine [T,E,C], dispatch [T,E,C])."""
    T, E = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    capacity = capacity_tokens(T, E, k, capacity_factor)
    _, topk_idx = lax.top_k(gates, k)                             # [T, k]
    masks, gate_vals, l_aux = [], [], 0.0
    for i in range(k):
        m = jax.nn.one_hot(topk_idx[:, i], E, dtype=jnp.float32)
        masks.append(m)
        gate_vals.append(jnp.sum(gates * m, axis=1))
        l_aux = l_aux + _balance_loss(gates, m)
    dispatch, combine = _dispatch_combine(masks, gate_vals, capacity)
    return l_aux, combine, dispatch


def ktop1_gating_impl(logits, k, capacity_factor):
    """k prototypes each routing top-1 over E/k experts (KTop1Gate.py)."""
    T, E = logits.shape
    assert E % k == 0, "num_experts must divide into k prototypes"
    Ep = E // k
    proto = jax.nn.softmax(
        logits.astype(jnp.float32).reshape(T, k, Ep), axis=-1)    # [T,k,Ep]
    capacity = capacity_tokens(T, E, k, capacity_factor)
    masks, gate_vals, l_aux = [], [], 0.0
    for i in range(k):
        g = proto[:, i, :]                                        # [T, Ep]
        idx = jnp.argmax(g, axis=-1) + i * Ep                     # global id
        m = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        masks.append(m)
        gate_vals.append(jnp.max(g, axis=-1))
        l_aux = l_aux + _balance_loss(g, m[:, i * Ep:(i + 1) * Ep])
    dispatch, combine = _dispatch_combine(masks, gate_vals, capacity)
    return l_aux, combine, dispatch


def hash_gating_impl(indices, num_experts, capacity_factor):
    """Static hash routing (HashGate.py hashgating): expert id is given
    per token (e.g. ``token_id % E``); gate weight is 1."""
    T = indices.shape[0]
    capacity = capacity_tokens(T, num_experts, 1, capacity_factor)
    m = jax.nn.one_hot(indices, num_experts, dtype=jnp.float32)
    dispatch, combine = _dispatch_combine([m], [jnp.ones((T,), jnp.float32)],
                                          capacity)
    return jnp.zeros((), jnp.float32), combine, dispatch


def sam_gating_impl(logits, k, capacity_factor, num_groups):
    """Switch-aware gating (SAMGate.py samgating): pick the top-1 expert
    *group* (groups = EP ranks, each holding E/G local experts), then the
    top-k experts inside that group; balance loss + alignment loss pushing
    mass onto the chosen group."""
    T, E = logits.shape
    assert E % num_groups == 0
    Eg = E // num_groups
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    grouped = gates.reshape(T, num_groups, Eg)
    group_sum = jnp.sum(grouped, axis=-1)                         # [T, G]
    top_group = jnp.argmax(group_sum, axis=-1)                    # [T]
    group_mask = jax.nn.one_hot(top_group, num_groups,
                                dtype=jnp.float32)                # [T, G]
    # top-k inside the chosen group
    local = jnp.einsum("tge,tg->te", grouped, group_mask)         # [T, Eg]
    capacity = capacity_tokens(T, E, k, capacity_factor)
    _, topk_local = lax.top_k(local, k)
    base = top_group * Eg
    masks, gate_vals, l_aux = [], [], 0.0
    for i in range(k):
        idx = base + topk_local[:, i]
        m = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        masks.append(m)
        gate_vals.append(jnp.sum(gates * m, axis=1))
        l_aux = l_aux + _balance_loss(gates, m)
    # alignment: reward concentration on the selected group
    l_align = jnp.sum(group_sum * group_mask) / T
    l_aux = l_aux - l_align
    dispatch, combine = _dispatch_combine(masks, gate_vals, capacity)
    return l_aux, combine, dispatch


def balance_gating_impl(scores, capacity_factor, n_iters=10):
    """BASE-layer balanced assignment (BalanceGate.py): Sinkhorn-normalize
    the token-expert score matrix so every expert receives ~T/E tokens,
    then greedily assign; gate weight = sigmoid(score)."""
    T, E = scores.shape
    s = scores.astype(jnp.float32)
    logp = jax.nn.log_softmax(s, axis=-1)

    def body(_, lp):
        lp = lp - jax.nn.logsumexp(lp, axis=0, keepdims=True)  # col balance
        lp = lp - jax.nn.logsumexp(lp, axis=1, keepdims=True)  # row stochast.
        return lp

    logp = lax.fori_loop(0, n_iters, body, logp)
    idx = jnp.argmax(logp, axis=-1)
    m = jax.nn.one_hot(idx, E, dtype=jnp.float32)
    capacity = capacity_tokens(T, E, 1, capacity_factor)
    gv = jax.nn.sigmoid(jnp.sum(s * m, axis=1))
    dispatch, combine = _dispatch_combine([m], [gv], capacity)
    return jnp.zeros((), jnp.float32), combine, dispatch


# ---------------------------------------------------------------------------
# gate modules
# ---------------------------------------------------------------------------

class _GateBase(Module):
    """Learned router: Linear(d_model -> num_experts) + a gating impl."""

    def __init__(self, embed_dim: int, num_experts: int,
                 capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0,
                 init: Optional[Initializer] = None, dtype=None,
                 name: str = "gate"):
        super().__init__()
        self.embed_dim, self.num_experts = embed_dim, num_experts
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.wg = parallel_parameter(
            init or XavierNormalInitializer(), (num_experts, embed_dim),
            pspec=P(), dtype=dtype, name=f"{name}.wg")

    def _cf(self):
        return self.capacity_factor if self.training \
            else self.eval_capacity_factor

    def logits(self, x):
        return ops.linear(x, self.wg, None, trans_b=True)


class TopKGate(_GateBase):
    """GShard top-k gate with capacity + balance aux loss (TopGate.py)."""

    def __init__(self, embed_dim, num_experts, k: int = 1, **kw):
        super().__init__(embed_dim, num_experts, **kw)
        self.k = k

    def forward(self, x):
        cf, k = self._cf(), self.k
        return ops.functional._op(
            "topk_gate", lambda lg: topk_gating_impl(lg, k, cf),
            [self.logits(x)], num_outputs=3)


class KTop1Gate(_GateBase):
    """k prototypes x top-1 gate (KTop1Gate.py)."""

    def __init__(self, embed_dim, num_experts, k: int = 2, **kw):
        super().__init__(embed_dim, num_experts, **kw)
        self.k = k

    def forward(self, x):
        cf, k = self._cf(), self.k
        return ops.functional._op(
            "ktop1_gate", lambda lg: ktop1_gating_impl(lg, k, cf),
            [self.logits(x)], num_outputs=3)


class HashGate(Module):
    """Static hash routing (HashGate.py): no learned parameters."""

    def __init__(self, num_experts: int, capacity_factor: float = 1.0):
        super().__init__()
        self.num_experts, self.capacity_factor = num_experts, capacity_factor

    def forward(self, x, token_ids):
        E, cf = self.num_experts, self.capacity_factor
        return ops.functional._op(
            "hash_gate",
            lambda ids: hash_gating_impl(ids.reshape(-1) % E, E, cf),
            [token_ids], num_outputs=3)


class SAMGate(_GateBase):
    """Switch-aware top-group-then-top-k gate (SAMGate.py)."""

    def __init__(self, embed_dim, num_experts, k: int = 2,
                 num_groups: int = 1, **kw):
        super().__init__(embed_dim, num_experts, **kw)
        self.k, self.num_groups = k, num_groups

    def forward(self, x):
        cf, k, G = self._cf(), self.k, self.num_groups
        return ops.functional._op(
            "sam_gate", lambda lg: sam_gating_impl(lg, k, cf, G),
            [self.logits(x)], num_outputs=3)


class BalanceGate(_GateBase):
    """BASE-layer balanced-assignment gate (BalanceGate.py); router weights
    act as expert centroids."""

    def __init__(self, embed_dim, num_experts, n_iters: int = 10, **kw):
        super().__init__(embed_dim, num_experts, **kw)
        self.n_iters = n_iters

    def forward(self, x):
        cf, n = self._cf(), self.n_iters
        return ops.functional._op(
            "balance_gate", lambda sc: balance_gating_impl(sc, cf, n),
            [self.logits(x)], num_outputs=3)


# ---------------------------------------------------------------------------
# experts + MoE layer
# ---------------------------------------------------------------------------

class Experts(Module):
    """E feed-forward experts with stacked weights [E, ...] so all experts
    run as one batched matmul on the MXU (reference Expert,
    moe_layer.py:7 — one FFN per expert, here fused)."""

    def __init__(self, num_experts: int, embed_dim: int, ffn_dim: int,
                 activation: str = "relu", ep_axis: Optional[str] = None,
                 dtype=None, init: Optional[Initializer] = None,
                 name: str = "experts"):
        super().__init__()
        self.num_experts = num_experts
        self.activation = activation
        self.ep_axis = ep_axis
        espec = P(ep_axis, None, None) if ep_axis else P()
        self.w1 = parallel_parameter(
            init or NormalInitializer(0.0, 0.02),
            (num_experts, embed_dim, ffn_dim), pspec=espec,
            dtype=dtype, name=f"{name}.w1")
        self.w2 = parallel_parameter(
            init or NormalInitializer(0.0, 0.02),
            (num_experts, ffn_dim, embed_dim), pspec=espec,
            dtype=dtype, name=f"{name}.w2")
        self.b1 = parallel_parameter(
            ConstantInitializer(0.0), (num_experts, 1, ffn_dim),
            pspec=espec, dtype=dtype, name=f"{name}.b1")
        self.b2 = parallel_parameter(
            ConstantInitializer(0.0), (num_experts, 1, embed_dim),
            pspec=espec, dtype=dtype, name=f"{name}.b2")

    def forward(self, dispatched):
        """dispatched: [E, C, d] -> [E, C, d]."""
        act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
               "silu": jax.nn.silu}[self.activation]

        def _impl(x, w1, b1, w2, b2):
            h = act(jnp.einsum("ecd,edf->ecf", x, w1) + b1)
            return jnp.einsum("ecf,efd->ecd", h, w2) + b2

        return ops.functional._op(
            "experts_ffn", _impl,
            [dispatched, self.w1, self.b1, self.w2, self.b2])


def _dropless_impl(xt, logits, w1, b1, w2, b2, *, k, act_name):
    """Capacity-free top-k dispatch through the blocked group-GEMM
    (ops/moe_dispatch.py): no token ever dropped, FLOPs ~k/E of dense."""
    from ..ops.moe_dispatch import blocked_group_gemm
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
           "silu": jax.nn.silu}[act_name]
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = lax.top_k(gates, k)
    out = blocked_group_gemm(xt.astype(jnp.float32), topi, topv,
                             w1, b1, w2, b2, act)
    l_aux = jnp.zeros((), jnp.float32)
    for i in range(k):
        m = jax.nn.one_hot(topi[:, i], gates.shape[-1], dtype=jnp.float32)
        l_aux = l_aux + _balance_loss(gates, m)
    return out.astype(xt.dtype), l_aux


class MoELayer(Module):
    """Gated mixture-of-experts layer (reference MoELayer,
    moe_layer.py:45).

    Dataflow (T = tokens, E = experts, C = capacity, d = embed):
      gate(x)             -> l_aux, combine [T,E,C], dispatch [T,E,C]
      dispatch^T . x      -> [E, C, d]     (sharding: E over ``ep_axis``)
      experts             -> [E, C, d]     (batched matmuls)
      combine . expert_out-> [T, d]

    With ``ep_axis`` set, the [E, C, d] tensors are sharded over the EP
    mesh axis while x is token-sharded — GSPMD inserts the two all-to-alls
    the reference programs by hand (alltoall_op before/after experts).

    ``dispatch_mode``:
      - ``"capacity"`` (default) — GShard capacity dispatch above; tokens
        beyond an expert's capacity are dropped.
      - ``"dropless"``  — capacity-free blocked group-GEMM
        (ops/moe_dispatch.py): every (token, expert) assignment computes.
        Needs a :class:`TopKGate` (uses its logits/k directly) and runs
        as a local (data-parallel) expert compute — ``ep_axis`` sharding
        of the blocked groups is not supported.
    """

    def __init__(self, gate: Module, experts: Experts,
                 ep_axis: Optional[str] = None,
                 dp_axis: Optional[str] = "dp",
                 dispatch_mode: str = "capacity"):
        super().__init__()
        if dispatch_mode not in ("capacity", "dropless"):
            raise ValueError(f"dispatch_mode must be 'capacity' or "
                             f"'dropless', got {dispatch_mode!r}")
        if dispatch_mode == "dropless":
            if not isinstance(gate, TopKGate):
                raise ValueError("dropless dispatch needs a TopKGate "
                                 "(top-k ids/weights feed the group-GEMM)")
            if ep_axis:
                raise ValueError("dropless dispatch is a local expert "
                                 "compute; ep_axis sharding is not "
                                 "supported (use dispatch_mode='capacity')")
        self.gate = gate
        self.experts = experts
        self.ep_axis, self.dp_axis = ep_axis, dp_axis
        self.dispatch_mode = dispatch_mode

    def _record_analysis_meta(self, xt, capacity: Optional[int],
                              payload=None) -> None:
        """Expose this layer's dispatch bounds to the static analyzer
        (graph meta ``moe``): the capacity-factor prediction bounds the
        EP dispatch/combine all-to-all payload, and the
        ``moe-capacity-overprovision`` rule flags dispatch tensors sized
        beyond it (dropless mode carries no capacity and is exempt)."""
        from ..graph.graph import get_default_graph
        g = get_default_graph()
        if not hasattr(g, "_moe_meta"):
            return
        try:
            T, d = (int(s) for s in xt.concrete_shape())
        except (TypeError, ValueError):
            return
        gate = self.gate
        g._moe_meta.append({
            "name": getattr(self.experts.w1, "name", "moe"),
            "tokens": T,
            "embed_dim": d,
            "num_experts": self.experts.num_experts,
            "k": getattr(gate, "k", 1),
            "capacity_factor": getattr(gate, "capacity_factor", 1.0)
            if getattr(gate, "training", True)
            else getattr(gate, "eval_capacity_factor", 1.0),
            "capacity": capacity,
            "dispatch_mode": self.dispatch_mode,
            "ep_axis": self.ep_axis,
            # the all-to-all moves the DISPATCHED tensor, whose dtype
            # is the einsum promotion of (fp32 gate masks, xt) — not
            # the layer weight dtype
            "dtype": np.dtype((payload if payload is not None
                               else xt).dtype.to_jnp()).name,
        })

    def forward(self, x, token_ids=None):
        """x: [..., d] -> (out [..., d], l_aux)."""
        orig_shape = x.shape
        d = orig_shape[-1]
        xt = ops.reshape(x, (-1, d))                              # [T, d]
        if self.dispatch_mode == "dropless":
            self._record_analysis_meta(xt, capacity=None)
            k, act = self.gate.k, self.experts.activation
            out, l_aux = ops.functional._op(
                "moe_dropless",
                lambda x_, lg, w1, b1, w2, b2:
                    _dropless_impl(x_, lg, w1, b1, w2, b2,
                                   k=k, act_name=act),
                [xt, self.gate.logits(xt), self.experts.w1,
                 self.experts.b1, self.experts.w2, self.experts.b2],
                num_outputs=2)
            if self.dp_axis:
                out = sharded(out, P(self.dp_axis, None))
            # batch-agnostic unflatten: under the explicit grad-comm
            # manual region the leading (dp-sharded) dim is LOCAL, so
            # the captured global batch size must not be baked in
            out = ops.reshape(out, (-1, *orig_shape[1:]))
            return out, l_aux
        if isinstance(self.gate, HashGate):
            if token_ids is None:
                raise ValueError("HashGate needs token_ids")
            l_aux, combine, dispatch = self.gate(xt, token_ids)
        else:
            l_aux, combine, dispatch = self.gate(xt)
        dispatched = ops.einsum("tec,td->ecd", dispatch, xt)      # [E, C, d]
        self._record_analysis_meta(xt, capacity=int(dispatch.shape[-1]),
                                   payload=dispatched)
        if self.ep_axis:
            dispatched = sharded(dispatched, P(self.ep_axis, None, None))
        eout = self.experts(dispatched)                           # [E, C, d]
        if self.ep_axis:
            eout = sharded(eout, P(self.ep_axis, None, None))
        out = ops.einsum("tec,ecd->td", combine, eout)            # [T, d]
        if self.dp_axis:
            out = sharded(out, P(self.dp_axis, None))
        out = ops.reshape(out, (-1, *orig_shape[1:]))
        return out, l_aux


def make_moe_layer(embed_dim: int, ffn_dim: int, num_experts: int,
                   gate_type: str = "topk", k: int = 2,
                   capacity_factor: float = 1.0,
                   eval_capacity_factor: Optional[float] = None,
                   activation: str = "gelu",
                   ep_axis: Optional[str] = None,
                   num_groups: int = 1, dtype=None,
                   dispatch_mode: str = "capacity",
                   name: str = "moe") -> MoELayer:
    """Convenience ctor mirroring the reference example wiring
    (``v1/examples/moe/``)."""
    if eval_capacity_factor is None:
        eval_capacity_factor = capacity_factor
    kw = dict(capacity_factor=capacity_factor,
              eval_capacity_factor=eval_capacity_factor, dtype=dtype,
              name=f"{name}.gate")
    if gate_type == "topk":
        gate = TopKGate(embed_dim, num_experts, k=k, **kw)
    elif gate_type == "ktop1":
        gate = KTop1Gate(embed_dim, num_experts, k=k, **kw)
    elif gate_type == "hash":
        gate = HashGate(num_experts, capacity_factor)
    elif gate_type == "sam":
        gate = SAMGate(embed_dim, num_experts, k=k, num_groups=num_groups,
                       **kw)
    elif gate_type == "balance":
        gate = BalanceGate(embed_dim, num_experts, **kw)
    else:
        raise ValueError(f"unknown gate_type {gate_type!r}")
    experts = Experts(num_experts, embed_dim, ffn_dim,
                      activation=activation, ep_axis=ep_axis, dtype=dtype,
                      name=f"{name}.experts")
    return MoELayer(gate, experts, ep_axis=ep_axis,
                    dispatch_mode=dispatch_mode)
