from .module import Module, Sequential, ModuleList, ModuleDict
from .layers import (Linear, Embedding, LayerNorm, RMSNorm, BatchNorm2d,
                     Conv2d, MaxPool2d, AvgPool2d, Dropout, Identity, ReLU,
                     GeLU, GELU, SiLU, Tanh, Sigmoid, LeakyReLU, Softmax,
                     NLLLoss, CrossEntropyLoss, MSELoss, BCELoss, KLDivLoss)
from .parallel import (ColumnParallelLinear, RowParallelLinear,
                       ParallelEmbedding, VocabParallelEmbedding,
                       ParallelLayerNorm, ParallelRMSNorm,
                       vocab_parallel_cross_entropy, parallel_data_provider,
                       config2ds, sharded)
from .moe import (MoELayer, Experts, TopKGate, KTop1Gate, HashGate, SAMGate,
                  BalanceGate, make_moe_layer)
from .lora import (LoRAColumnParallelLinear, LoRARowParallelLinear,
                   LoRAEmbedding, mark_only_lora_trainable, merge_lora)
# Reference-compatible aliases (parallel_multi_ds.py exports)
HtMultiColumnParallelLinear = ColumnParallelLinear
HtMultiRowParallelLinear = RowParallelLinear
HtMultiParallelEmbedding = ParallelEmbedding
HtMultiVocabParallelEmbedding = VocabParallelEmbedding
HtMultiParallelLayerNorm = ParallelLayerNorm
HtMultiParallelRMSNorm = ParallelRMSNorm

__all__ = [
    "Module", "Sequential", "ModuleList", "ModuleDict",
    "Linear", "Embedding", "LayerNorm", "RMSNorm", "BatchNorm2d", "Conv2d",
    "MaxPool2d", "AvgPool2d", "Dropout", "Identity", "ReLU", "GeLU", "GELU",
    "SiLU", "Tanh", "Sigmoid", "LeakyReLU", "Softmax",
    "NLLLoss", "CrossEntropyLoss", "MSELoss", "BCELoss", "KLDivLoss",
    "ColumnParallelLinear", "RowParallelLinear", "ParallelEmbedding",
    "VocabParallelEmbedding", "ParallelLayerNorm", "ParallelRMSNorm",
    "vocab_parallel_cross_entropy", "parallel_data_provider", "config2ds",
    "sharded",
    "HtMultiColumnParallelLinear", "HtMultiRowParallelLinear",
    "HtMultiParallelEmbedding", "HtMultiVocabParallelEmbedding",
    "HtMultiParallelLayerNorm", "HtMultiParallelRMSNorm",
    "MoELayer", "Experts", "TopKGate", "KTop1Gate", "HashGate", "SAMGate",
    "BalanceGate", "make_moe_layer",
    "LoRAColumnParallelLinear", "LoRARowParallelLinear", "LoRAEmbedding",
    "mark_only_lora_trainable", "merge_lora",
]
