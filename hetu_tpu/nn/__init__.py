from .module import Module, Sequential, ModuleList, ModuleDict
from .layers import (Linear, Embedding, LayerNorm, RMSNorm, BatchNorm2d,
                     Conv2d, MaxPool2d, AvgPool2d, Dropout, Identity, ReLU,
                     GeLU, GELU, SiLU, Tanh, Sigmoid, LeakyReLU, Softmax,
                     NLLLoss, CrossEntropyLoss, MSELoss, BCELoss, KLDivLoss)

__all__ = [
    "Module", "Sequential", "ModuleList", "ModuleDict",
    "Linear", "Embedding", "LayerNorm", "RMSNorm", "BatchNorm2d", "Conv2d",
    "MaxPool2d", "AvgPool2d", "Dropout", "Identity", "ReLU", "GeLU", "GELU",
    "SiLU", "Tanh", "Sigmoid", "LeakyReLU", "Softmax",
    "NLLLoss", "CrossEntropyLoss", "MSELoss", "BCELoss", "KLDivLoss",
]
