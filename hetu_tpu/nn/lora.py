"""LoRA: low-rank adaptation of (parallel) linear and embedding layers.

Counterpart of the reference's LoRA-parallel modules
(``python/hetu/nn/modules/parallel_lora.py``:
LoRAColumnParallelLinear:180, LoRARowParallelLinear:251,
LoRAParallelEmbedding:104, LoRAModel:339 with mark-only-lora-trainable).

Sharding follows the base layer: for a column-parallel base (W split on
out), B is split on out and A replicated; for a row-parallel base (W
split on in), A is split on in and B replicated — so the adapter matmuls
ride the same mesh axes with no extra collectives.
"""
from __future__ import annotations

import math
from typing import Optional

from jax.sharding import PartitionSpec as P

from .. import ops
from ..graph.ctor import (ConstantInitializer, HeUniformInitializer,
                          NormalInitializer, parallel_parameter)
from .module import Module
from .parallel import ColumnParallelLinear, RowParallelLinear, sharded


class LoRALayerMixin:
    """Adds lora_A/lora_B around a frozen base weight."""

    def init_lora(self, in_features: int, out_features: int, rank: int,
                  alpha: float, a_pspec, b_pspec, dtype, name: str):
        self.rank = rank
        self.scaling = alpha / rank
        self.merged = False
        # reference init: A ~ kaiming-uniform, B = 0 (adapter starts as
        # identity)
        self.lora_A = parallel_parameter(
            HeUniformInitializer(), (rank, in_features), pspec=a_pspec,
            dtype=dtype, name=f"{name}.lora_A")
        self.lora_B = parallel_parameter(
            ConstantInitializer(0.0), (out_features, rank), pspec=b_pspec,
            dtype=dtype, name=f"{name}.lora_B")

    def lora_delta(self, x):
        """x @ A^T @ B^T * scaling."""
        h = ops.linear(x, self.lora_A, None, trans_b=True)
        return ops.linear(h, self.lora_B, None, trans_b=True) * self.scaling


class LoRAColumnParallelLinear(ColumnParallelLinear, LoRALayerMixin):
    """Column-parallel linear + LoRA (parallel_lora.py:180): B is split
    on the out dim like the base weight, A is replicated."""

    def __init__(self, in_features: int, out_features: int, rank: int = 8,
                 alpha: float = 16.0, bias: bool = True,
                 gather_output: bool = False, dp_axis: str = "dp",
                 tp_axis: str = "tp", dtype=None, name: str = "lora_colp",
                 **kw):
        super().__init__(in_features, out_features, bias=bias,
                         gather_output=gather_output, dp_axis=dp_axis,
                         tp_axis=tp_axis, dtype=dtype, name=name, **kw)
        self.weight.trainable = False
        if self.bias is not None:
            self.bias.trainable = False
        self.init_lora(in_features, out_features, rank, alpha,
                       a_pspec=P(), b_pspec=P(tp_axis, None), dtype=dtype,
                       name=name)

    def forward(self, x):
        out = super().forward(x)
        if not self.merged:
            out = out + self.lora_delta(x)
        return out


class LoRARowParallelLinear(RowParallelLinear, LoRALayerMixin):
    """Row-parallel linear + LoRA (parallel_lora.py:251): A is split on
    the in dim like the base weight, B is replicated."""

    def __init__(self, in_features: int, out_features: int, rank: int = 8,
                 alpha: float = 16.0, bias: bool = True, sp: bool = False,
                 dp_axis: str = "dp", tp_axis: str = "tp", dtype=None,
                 name: str = "lora_rowp", **kw):
        super().__init__(in_features, out_features, bias=bias, sp=sp,
                         dp_axis=dp_axis, tp_axis=tp_axis, dtype=dtype,
                         name=name, **kw)
        self.weight.trainable = False
        if self.bias is not None:
            self.bias.trainable = False
        self.init_lora(in_features, out_features, rank, alpha,
                       a_pspec=P(None, tp_axis), b_pspec=P(), dtype=dtype,
                       name=name)

    def forward(self, x):
        out = super().forward(x)
        if not self.merged:
            out = out + self.lora_delta(x)
        return out


class LoRAEmbedding(Module):
    """Embedding + low-rank delta (parallel_lora.py:104): frozen base
    table, delta = one_hot(ids) @ A^T @ B^T expressed as two lookups."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 rank: int = 8, alpha: float = 16.0, dtype=None,
                 name: str = "lora_embed"):
        super().__init__()
        self.num_embeddings, self.embedding_dim = num_embeddings, \
            embedding_dim
        self.scaling = alpha / rank
        self.merged = False
        self.weight = parallel_parameter(
            NormalInitializer(0.0, 0.02), (num_embeddings, embedding_dim),
            dtype=dtype, name=f"{name}.weight")
        self.weight.trainable = False
        # reference init for embeddings: A = 0, B ~ normal (delta starts 0)
        self.lora_A = parallel_parameter(
            ConstantInitializer(0.0), (num_embeddings, rank), dtype=dtype,
            name=f"{name}.lora_A")
        self.lora_B = parallel_parameter(
            NormalInitializer(0.0, 0.02), (rank, embedding_dim),
            dtype=dtype, name=f"{name}.lora_B")

    def forward(self, ids):
        out = ops.embedding_lookup(self.weight, ids)
        if not self.merged:
            a = ops.embedding_lookup(self.lora_A, ids)
            out = out + ops.matmul(a, self.lora_B) * self.scaling
        return out


def mark_only_lora_trainable(model: Module, bias: str = "none") -> None:
    """Freeze everything except lora_A/lora_B (LoRAModel's freeze
    behavior, parallel_lora.py:339).  ``bias``: 'none' | 'all'."""
    for name, p in model.named_parameters():
        leaf = name.rsplit(".", 1)[-1]
        if leaf in ("lora_A", "lora_B"):
            p.trainable = True
        elif leaf == "bias" and bias == "all":
            p.trainable = True
        else:
            p.trainable = False


def merge_lora(model: Module, graph=None) -> None:
    """Fold every adapter into its base weight (W += B A * scaling) and
    mark it merged, so inference runs at base-model cost."""
    import numpy as np
    for mod in model.modules():
        if isinstance(mod, (LoRAColumnParallelLinear,
                            LoRARowParallelLinear)) and not mod.merged:
            g = graph or mod.weight.graph
            W = np.asarray(g.get_tensor_value(mod.weight))
            A = np.asarray(g.get_tensor_value(mod.lora_A))
            B = np.asarray(g.get_tensor_value(mod.lora_B))
            g.reset_variable(mod.weight, W + (B @ A) * mod.scaling)
            mod.merged = True
        elif isinstance(mod, LoRAEmbedding) and not mod.merged:
            g = graph or mod.weight.graph
            W = np.asarray(g.get_tensor_value(mod.weight))
            A = np.asarray(g.get_tensor_value(mod.lora_A))
            B = np.asarray(g.get_tensor_value(mod.lora_B))
            g.reset_variable(mod.weight, W + (A @ B) * mod.scaling)
            mod.merged = True
