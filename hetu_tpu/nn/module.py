"""PyTorch-like Module system.

Mirrors the reference's ``python/hetu/nn/modules/module.py`` (573 LoC
Module with named params/buffers/state_dict and container types), built on
our graph Tensors: parameters are trainable graph variables, forward builds
symbolic ops (define-and-run) or executes immediately (eager).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

from ..graph.tensor import Tensor


class Module:
    def __init__(self):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_modules", OrderedDict())
        object.__setattr__(self, "training", True)

    # -- attribute routing ---------------------------------------------------

    def __setattr__(self, name: str, value: Any) -> None:
        if isinstance(value, Tensor) and value.trainable:
            self._parameters[name] = value
            object.__setattr__(self, name, value)
        elif isinstance(value, Module):
            self._modules[name] = value
            object.__setattr__(self, name, value)
        else:
            object.__setattr__(self, name, value)

    def register_parameter(self, name: str, param: Optional[Tensor]) -> None:
        self._parameters[name] = param
        object.__setattr__(self, name, param)

    def register_buffer(self, name: str, buf) -> None:
        self._buffers[name] = buf
        object.__setattr__(self, name, buf)

    def add_module(self, name: str, module: "Module") -> None:
        self._modules[name] = module
        object.__setattr__(self, name, module)

    # -- iteration -----------------------------------------------------------

    def named_parameters(self, prefix: str = "", recurse: bool = True
                         ) -> Iterator[Tuple[str, Tensor]]:
        for name, p in self._parameters.items():
            if p is not None:
                yield (f"{prefix}{name}", p)
        if recurse:
            for mname, m in self._modules.items():
                if m is not None:
                    yield from m.named_parameters(f"{prefix}{mname}.", True)

    def parameters(self, recurse: bool = True) -> Iterator[Tensor]:
        for _, p in self.named_parameters(recurse=recurse):
            yield p

    def named_modules(self, prefix: str = "") -> Iterator[Tuple[str, "Module"]]:
        yield prefix.rstrip("."), self
        for mname, m in self._modules.items():
            if m is not None:
                yield from m.named_modules(f"{prefix}{mname}.")

    def modules(self) -> Iterator["Module"]:
        for _, m in self.named_modules():
            yield m

    def named_buffers(self, prefix: str = "", recurse: bool = True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}{name}", b)
        if recurse:
            for mname, m in self._modules.items():
                if m is not None:
                    yield from m.named_buffers(f"{prefix}{mname}.", True)

    # -- state dict ----------------------------------------------------------

    def state_dict(self) -> "OrderedDict[str, np.ndarray]":
        out = OrderedDict()
        for name, p in self.named_parameters():
            out[name] = p.numpy()
        for name, b in self.named_buffers():
            out[name] = np.asarray(b)
        return out

    def _set_buffer_by_path(self, path: str, value) -> bool:
        parts = path.split(".")
        mod = self
        for p in parts[:-1]:
            mod = mod._modules.get(p)
            if mod is None:
                return False
        if parts[-1] in mod._buffers:
            mod._buffers[parts[-1]] = np.asarray(value)
            object.__setattr__(mod, parts[-1], mod._buffers[parts[-1]])
            return True
        return False

    def load_state_dict(self, state: Dict[str, Any], strict: bool = True):
        missing, loaded = [], set()
        for name, p in self.named_parameters():
            if name in state:
                p.graph.reset_variable(p, state[name])
                loaded.add(name)
            elif strict:
                missing.append(name)
        for name, _ in self.named_buffers():
            if name in state and self._set_buffer_by_path(name, state[name]):
                loaded.add(name)
            elif strict and name not in state:
                missing.append(name)
        unexpected = [k for k in state if k not in loaded]
        if strict and (missing or unexpected):
            raise KeyError(f"missing={missing} unexpected={unexpected}")
        return missing, unexpected

    # -- modes ---------------------------------------------------------------

    def train(self, mode: bool = True) -> "Module":
        object.__setattr__(self, "training", mode)
        for m in self._modules.values():
            if m is not None:
                m.train(mode)
        return self

    def eval(self) -> "Module":
        return self.train(False)

    def apply(self, fn: Callable[["Module"], None]) -> "Module":
        for m in self._modules.values():
            if m is not None:
                m.apply(fn)
        fn(self)
        return self

    # -- call ----------------------------------------------------------------

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        lines = [f"{type(self).__name__}({self.extra_repr()}"]
        for name, m in self._modules.items():
            sub = repr(m).split("\n")
            lines.append(f"  ({name}): " + "\n  ".join(sub))
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else \
            f"{type(self).__name__}({self.extra_repr()})"


class Sequential(Module):
    def __init__(self, *modules: Module):
        super().__init__()
        if len(modules) == 1 and isinstance(modules[0], OrderedDict):
            for name, m in modules[0].items():
                self.add_module(name, m)
        else:
            for i, m in enumerate(modules):
                self.add_module(str(i), m)

    def forward(self, x):
        for m in self._modules.values():
            x = m(x)
        return x

    def __iter__(self):
        return iter(self._modules.values())

    def __len__(self):
        return len(self._modules)

    def __getitem__(self, idx: int):
        return list(self._modules.values())[idx]


class ModuleList(Module):
    def __init__(self, modules=()):
        super().__init__()
        for i, m in enumerate(modules):
            self.add_module(str(i), m)

    def append(self, module: Module) -> "ModuleList":
        self.add_module(str(len(self._modules)), module)
        return self

    def __iter__(self):
        return iter(self._modules.values())

    def __len__(self):
        return len(self._modules)

    def __getitem__(self, idx):
        items = list(self._modules.values())
        return items[idx]


class ModuleDict(Module):
    def __init__(self, modules: Optional[Dict[str, Module]] = None):
        super().__init__()
        if modules:
            for name, m in modules.items():
                self.add_module(name, m)

    def __getitem__(self, key: str) -> Module:
        return self._modules[key]

    def __setitem__(self, key: str, module: Module) -> None:
        self.add_module(key, module)

    def keys(self):
        return self._modules.keys()

    def items(self):
        return self._modules.items()

    def values(self):
        return self._modules.values()
