from .gpt import (GPTConfig, GPTModel, GPTLMHeadModel, llama_config,
                  LLamaLMHeadModel, LLamaModel)
from .gpt_pipeline import GPTPipelineModel, block_fn

__all__ = ["GPTConfig", "GPTModel", "GPTLMHeadModel", "llama_config",
           "LLamaLMHeadModel", "LLamaModel", "GPTPipelineModel", "block_fn"]
