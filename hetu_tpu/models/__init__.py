from .gpt import (GPTConfig, GPTModel, GPTLMHeadModel, llama_config,
                  LLamaLMHeadModel, LLamaModel)

__all__ = ["GPTConfig", "GPTModel", "GPTLMHeadModel", "llama_config",
           "LLamaLMHeadModel", "LLamaModel"]
