from .bert import (BertConfig, BertForPreTraining,
                   BertForSequenceClassification, BertModel)
from .cnn import BasicBlock, ResNet, SimpleCNN, resnet18, resnet34
from .ctr import DCN, DeepFM, WDL, ctr_loss
from .gnn import GCN, DistGCN15D, GCNLayer, SparseGCNLayer, \
    normalize_adjacency
from .gpt import (GPTConfig, GPTModel, GPTLMHeadModel, draft_config,
                  draft_state_from, llama_config, LLamaLMHeadModel,
                  LLamaModel, mla_config, mla_state_from)
from .generate import generate
from .gpt_pipeline import GPTPipelineModel, block_fn
from .rnn import GRU, LSTM, RNN, RNNLanguageModel

__all__ = ["GPTConfig", "GPTModel", "GPTLMHeadModel", "llama_config",
           "draft_config", "draft_state_from", "mla_config",
           "mla_state_from",
           "LLamaLMHeadModel", "LLamaModel", "GPTPipelineModel", "block_fn",
           "BertConfig", "BertModel", "BertForPreTraining",
           "BertForSequenceClassification",
           "SimpleCNN", "ResNet", "BasicBlock", "resnet18", "resnet34",
           "WDL", "DeepFM", "DCN", "ctr_loss",
           "RNN", "GRU", "LSTM", "RNNLanguageModel",
           "GCN", "DistGCN15D", "GCNLayer", "SparseGCNLayer",
           "normalize_adjacency", "generate"]
