"""Autoregressive generation with a static KV cache.

Inference companion to the training stack: takes a trained
:class:`~hetu_tpu.models.gpt.GPTLMHeadModel`'s ``state_dict()`` and
decodes with XLA-friendly machinery — a preallocated ``[b, max_len]``
KV cache updated by ``lax.dynamic_update_slice`` and a ``lax.scan``
token loop, so the whole decode compiles to ONE program with static
shapes (no per-token retracing, no growing sequence).

The reference is a training system (its examples stop at loss curves);
this module covers the inference half a switching user expects.  Single
program = single device or GSPMD-sharded under an outer ``jit`` with
sharded weights — the weight layouts are exactly the training layouts
(W [out, in], ``y = x @ W.T``; see nn/parallel.py).

Supported configs: learned or rotary positions, layernorm/rmsnorm,
gelu/swiglu/silu/relu MLPs, GQA (kv_heads < num_heads), tied or untied
lm_head.  Dropout is ignored (inference).  MoE blocks decode via a
dense per-token top-k expert mix (no capacity buckets — every token
reaches its chosen experts).
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .gpt import GPTConfig


def _norm_apply(cfg: GPTConfig, w, b, x):
    xf = x.astype(jnp.float32)
    if cfg.norm == "rmsnorm":
        xf = xf * lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + 1e-6)
        return (xf * w).astype(x.dtype)
    m = jnp.mean(xf, -1, keepdims=True)
    v = jnp.var(xf, -1, keepdims=True)
    out = (xf - m) * lax.rsqrt(v + 1e-5) * w + (b if b is not None else 0.0)
    return out.astype(x.dtype)


def _act(cfg: GPTConfig, h):
    if cfg.activation == "swiglu":
        x1, x2 = jnp.split(h, 2, axis=-1)  # silu(x1) * x2, as ops.swiglu
        return jax.nn.silu(x1) * x2
    if cfg.activation == "gelu":
        return jax.nn.gelu(h)
    if cfg.activation == "silu":
        return jax.nn.silu(h)
    return jax.nn.relu(h)


def _rotary_tables(cfg: GPTConfig, max_len: int):
    # MLA rotates only the decoupled rope slice (width cfg.rope_dim);
    # full-head rotates the whole head
    d = cfg.rope_dim if cfg.is_mla else cfg.head_dim
    inv = 1.0 / (10000.0 ** (np.arange(0, d, 2, dtype=np.float32) / d))
    ang = np.outer(np.arange(max_len, dtype=np.float32), inv)
    emb = np.concatenate([ang, ang], axis=-1)
    return jnp.asarray(np.cos(emb)), jnp.asarray(np.sin(emb))  # [L, d]


def _rope(x, cos, sin):
    # x: [b, s, h, d]; cos/sin: [s, d] (already position-gathered)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    c = cos[None, :, None, :].astype(x.dtype)
    s = sin[None, :, None, :].astype(x.dtype)
    return x * c + rot * s


class _Params:
    """state_dict view normalizing the two naming conventions: module
    paths (``transformer.h.0.attn.qkv.weight``, Module.state_dict) and
    tensor names (``h0.attn.qkv.weight``, checkpoint files)."""

    @staticmethod
    def _norm(key: str) -> str:
        if key.startswith("transformer."):
            key = key[len("transformer."):]
        if key.startswith("h."):                    # h.0.attn -> h0.attn
            rest = key[2:]
            idx, _, tail = rest.partition(".")
            key = f"h{idx}.{tail}"
        return key

    def __init__(self, state: Dict[str, Any], cfg: GPTConfig):
        self.s = {self._norm(k): jnp.asarray(v) for k, v in state.items()}
        self.cfg = cfg

    def __call__(self, name: str):
        return self.s.get(name)

    def layer(self, i: int, part: str):
        return self.s.get(f"h{i}.{part}")


def _attn_step(cfg: GPTConfig, p: _Params, i: int, x, k_cache, v_cache,
               pos, cos, sin):
    """One attention pass for s_new tokens starting at position ``pos``
    against caches holding everything before them.  Returns
    (out [b, s_new, H], new caches)."""
    b, s_new, _ = x.shape
    c = cfg
    hd, nh, nkv = c.head_dim, c.num_heads, c.kv_heads
    qkv = x @ p.layer(i, "attn.qkv.weight").T
    qb = p.layer(i, "attn.qkv.bias")
    if qb is not None:
        qkv = qkv + qb
    q_size, kv_size = nh * hd, nkv * hd
    q = qkv[..., :q_size].reshape(b, s_new, nh, hd)
    k = qkv[..., q_size:q_size + kv_size].reshape(b, s_new, nkv, hd)
    v = qkv[..., q_size + kv_size:].reshape(b, s_new, nkv, hd)
    if c.position == "rotary":
        idx = pos + jnp.arange(s_new)
        q = _rope(q, cos[idx], sin[idx])
        k = _rope(k, cos[idx], sin[idx])
    k_cache = lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype),
                                       (0, pos, 0, 0))
    v_cache = lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype),
                                       (0, pos, 0, 0))
    L = k_cache.shape[1]
    kk = jnp.repeat(k_cache, nh // nkv, axis=2) if nkv != nh else k_cache
    vv = jnp.repeat(v_cache, nh // nkv, axis=2) if nkv != nh else v_cache
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        kk.astype(jnp.float32)) / math.sqrt(hd)
    kpos = jnp.arange(L)[None, None, None, :]
    qpos = (pos + jnp.arange(s_new))[None, None, :, None]
    scores = jnp.where(kpos <= qpos, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum("bhqk,bkhd->bqhd", probs,
                      vv.astype(jnp.float32)).astype(x.dtype)
    attn = attn.reshape(b, s_new, nh * hd)
    out = attn @ p.layer(i, "attn.out.weight").T
    ob = p.layer(i, "attn.out.bias")
    if ob is not None:
        out = out + ob
    return out, k_cache, v_cache


def _mla_attn_step(cfg: GPTConfig, p: _Params, i: int, x, c_cache, r_cache,
                   pos, cos, sin):
    """MLA twin of :func:`_attn_step` over LATENT caches: ``c_cache``
    [b, max_len, 1, d_c] holds the shared compressed KV stream,
    ``r_cache`` [b, max_len, 1, d_r] the decoupled rotated key (width 0
    for learned positions).  Weight absorption (FlashMLA-ETAP): scores
    are ``(q_nope @ k_up) . c`` per query head and the attention output
    stays latent until one ``v_up`` einsum per QUERY token — no cached
    token is ever decompressed.  The serving unified step mirrors these
    contractions exactly; that alignment is the temp-0 bitwise
    contract."""
    b, s_new, _ = x.shape
    c = cfg
    hd, nh = c.head_dim, c.num_heads
    d_c, d_r = c.kv_latent_dim, c.rope_dim
    q = x @ p.layer(i, "attn.q.weight").T
    qb = p.layer(i, "attn.q.bias")
    if qb is not None:
        q = q + qb
    q = q.reshape(b, s_new, nh, hd + d_r)
    kv = x @ p.layer(i, "attn.kv_a.weight").T
    kvb = p.layer(i, "attn.kv_a.bias")
    if kvb is not None:
        kv = kv + kvb
    c_kv = kv[..., :d_c]                                  # [b, s, d_c]
    k_up = p.layer(i, "attn.k_up.weight")                 # [nh, hd, d_c]
    v_up = p.layer(i, "attn.v_up.weight")
    q_abs = jnp.einsum("bshd,hdc->bshc", q[..., :hd].astype(jnp.float32),
                       k_up.astype(jnp.float32))
    c_cache = lax.dynamic_update_slice(
        c_cache, c_kv[:, :, None, :].astype(c_cache.dtype), (0, pos, 0, 0))
    if d_r:
        idx = pos + jnp.arange(s_new)
        q_rope = _rope(q[..., hd:], cos[idx], sin[idx])
        k_rope = _rope(kv[..., d_c:][:, :, None, :], cos[idx], sin[idx])
        r_cache = lax.dynamic_update_slice(
            r_cache, k_rope.astype(r_cache.dtype), (0, pos, 0, 0))
        q_cat = jnp.concatenate([q_abs, q_rope.astype(jnp.float32)], -1)
        k_cat = jnp.concatenate([c_cache, r_cache], -1)[:, :, 0]
    else:
        q_cat, k_cat = q_abs, c_cache[:, :, 0]            # [b, L, d_c]
    L = c_cache.shape[1]
    scores = jnp.einsum("bshc,bkc->bhsk", q_cat,
                        k_cat.astype(jnp.float32)) / math.sqrt(hd + d_r)
    kpos = jnp.arange(L)[None, None, None, :]
    qpos = (pos + jnp.arange(s_new))[None, None, :, None]
    scores = jnp.where(kpos <= qpos, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    o_lat = jnp.einsum("bhsk,bkc->bshc", probs,
                       c_cache[:, :, 0].astype(jnp.float32))
    attn = jnp.einsum("bshc,hdc->bshd", o_lat,
                      v_up.astype(jnp.float32)).astype(x.dtype)
    attn = attn.reshape(b, s_new, nh * hd)
    out = attn @ p.layer(i, "attn.out.weight").T
    ob = p.layer(i, "attn.out.bias")
    if ob is not None:
        out = out + ob
    return out, c_cache, r_cache


def _moe_params(p: _Params, i: int):
    def moe_p(part):
        # module-path keys say "mlp.moe.*" (MoEMLP wraps the layer);
        # tensor-name keys say "moe.*" (parallel_parameter names)
        v = p.layer(i, f"mlp.moe.{part}")
        return v if v is not None else p.layer(i, f"moe.{part}")
    return (moe_p("gate.wg"), moe_p("experts.w1"), moe_p("experts.b1"),
            moe_p("experts.w2"), moe_p("experts.b2"))


def _moe_route(cfg: GPTConfig, wg, x):
    """Top-k routing shared by the dense and dispatched paths — identical
    gate arithmetic so the two can never route differently.  dtype
    fidelity with training (nn/moe.py): gate LOGITS in model dtype (a
    full-f32 matmul could break near-ties), softmax in fp32."""
    gates = jax.nn.softmax(
        (x @ wg.T.astype(x.dtype)).astype(jnp.float32), axis=-1)
    topv, topi = lax.top_k(gates, cfg.moe_top_k)           # [b, s, k]
    return gates, topv, topi


def _moe_act(cfg: GPTConfig):
    return {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
            "silu": jax.nn.silu}[
        "silu" if cfg.activation == "swiglu" else cfg.activation]


def _moe_mlp(cfg: GPTConfig, p: _Params, i: int, x):
    """Dense per-token top-k expert mix for decode (no capacity buckets:
    every token reaches its chosen experts — exact vs. training when
    training ran uncongested).  All E experts run batched: one einsum on
    the MXU beats gather/scatter at decode (s_new=1).  The prefill pass
    (s_new > 1) routes through :func:`_moe_mlp_dispatched` instead, whose
    FLOPs scale with k/E rather than running every expert on every token
    (reference moe_layer.py:45 dispatches via layout_transform+AllToAll)."""
    wg, w1, b1, w2, b2 = _moe_params(p, i)
    if x.shape[1] > 1:
        return _moe_mlp_dispatched(cfg, x, wg, w1, b1, w2, b2)
    gates, topv, topi = _moe_route(cfg, wg, x)
    weights = jnp.zeros_like(gates)
    for j in range(cfg.moe_top_k):
        weights = weights + topv[..., j:j + 1] * jax.nn.one_hot(
            topi[..., j], gates.shape[-1], dtype=gates.dtype)
    act = _moe_act(cfg)
    h = act(jnp.einsum("bsd,edf->bsef", x, w1) + b1[:, 0])
    y = jnp.einsum("bsef,efd->bsed", h, w2) + b2[:, 0]
    return jnp.einsum("bse,bsed->bsd", weights,
                      y.astype(jnp.float32)).astype(x.dtype)


def _moe_block_size(n_assign: int, num_experts: int) -> int:
    """Back-compat alias of ops.moe_dispatch.pick_block_size (the FLOPs
    bound test reads it here)."""
    from ..ops.moe_dispatch import pick_block_size
    return pick_block_size(n_assign, num_experts)


def _moe_mlp_dispatched(cfg: GPTConfig, x, wg, w1, b1, w2, b2):
    """Capacity-FREE dispatched MoE for prefill (blocked group-GEMM,
    ops/moe_dispatch.py): FLOPs ~k/E of the dense all-experts path with
    NO dropped tokens — exact equivalence, asserted in tests.  The
    reference reaches the same dataflow with layout_transform + AllToAll
    ops (v1 moe_layer.py:45) but drops over-capacity tokens."""
    from ..ops.moe_dispatch import blocked_group_gemm
    b, s, d = x.shape
    gates, topv, topi = _moe_route(cfg, wg, x)
    out = blocked_group_gemm(
        x.reshape(b * s, d), topi.reshape(b * s, -1),
        topv.reshape(b * s, -1), w1, b1, w2, b2, _moe_act(cfg))
    return out.reshape(b, s, d).astype(x.dtype)

def _lm_head(p: _Params, x):
    """LM-head projection for already-normed hidden states ``x`` [b, H]
    -> fp32 logits [b, V].  Split out of :func:`_forward` so the serving
    engine can project at the last TRUE token of a padded prefill."""
    head = p("lm_head.weight")
    w = head if head is not None else p("wte.weight")
    return x.astype(jnp.float32) @ w.T.astype(jnp.float32)


def _forward(cfg: GPTConfig, p: _Params, ids, caches, pos, cos, sin,
             return_hidden: bool = False):
    """Stack forward for ``ids`` [b, s_new] at absolute position ``pos``;
    returns (logits of the LAST position [b, V], new caches), plus the
    final-norm hidden states [b, s_new, H] when ``return_hidden`` (the
    serving prefill projects logits at the last true token of a padded
    prompt instead of the last padded position)."""
    c = cfg
    x = p("wte.weight")[ids].astype(jnp.bfloat16 if c.dtype == "bfloat16"
                                    else jnp.float32)
    if c.position == "learned":
        idx = pos + jnp.arange(ids.shape[1])
        x = x + p("wpe")[idx].astype(x.dtype)
    new_caches = []
    for i in range(c.num_layers):
        k_cache, v_cache = caches[i]
        h = _norm_apply(c, p.layer(i, "ln_1.weight"),
                        p.layer(i, "ln_1.bias"), x)
        step = _mla_attn_step if c.is_mla else _attn_step
        a, k_cache, v_cache = step(c, p, i, h, k_cache, v_cache,
                                   pos, cos, sin)
        x = x + a
        h = _norm_apply(c, p.layer(i, "ln_2.weight"),
                        p.layer(i, "ln_2.bias"), x)
        if c.is_moe_layer(i):
            h = _moe_mlp(c, p, i, h)
        else:
            h = _act(c, h @ p.layer(i, "mlp.up.weight").T +
                     (p.layer(i, "mlp.up.bias") if p.layer(i, "mlp.up.bias")
                      is not None else 0.0))
            h = h @ p.layer(i, "mlp.down.weight").T
            db = p.layer(i, "mlp.down.bias")
            if db is not None:
                h = h + db
        x = x + h
        new_caches.append((k_cache, v_cache))
    x = _norm_apply(c, p("ln_f.weight"), p("ln_f.bias"), x)
    logits = _lm_head(p, x[:, -1])                 # [b, V]
    if return_hidden:
        return logits, new_caches, x
    return logits, new_caches


def decode_step(cfg: GPTConfig, p: _Params, tokens, caches, pos, cos, sin,
                return_hidden: bool = False):
    """Single decode step against dense ``[b, max_len, kvh, hd]`` caches:
    ``tokens`` [b, s_new] at absolute position ``pos`` -> (last-position
    logits [b, V], updated caches).

    The one entry point both inference paths share: ``generate()``'s
    ``lax.scan`` calls it with s_new=1, and the serving engine's prefill
    executable (``hetu_tpu/serving/decode.py``) calls it over the whole
    padded prompt (``return_hidden=True``, to re-project logits at the
    last TRUE token) before scattering the dense caches into KV pages.
    """
    return _forward(cfg, p, tokens, caches, pos, cos, sin, return_hidden)


def generate(state: Dict[str, Any], cfg: GPTConfig, prompt_ids,
             max_new_tokens: int, temperature: float = 0.0,
             top_k: int = 0, seed: int = 0) -> jax.Array:
    """Decode ``max_new_tokens`` tokens after ``prompt_ids`` [b, s0].

    ``temperature == 0`` -> greedy; otherwise softmax sampling, with
    optional ``top_k`` truncation.  Returns [b, s0 + max_new_tokens].
    The token loop is a single ``lax.scan`` (one compile, static shapes).
    """
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    if max_new_tokens == 0:
        return prompt_ids
    p = _Params(state, cfg)
    b, s0 = prompt_ids.shape
    max_len = s0 + max_new_tokens
    if cfg.position == "learned" and max_len > cfg.max_seq_len:
        raise ValueError(f"max_len {max_len} exceeds learned-position "
                         f"table {cfg.max_seq_len}")
    key = (_dataclasses.astuple(cfg), b, s0, int(max_new_tokens),
           float(temperature), int(top_k))
    fn = _DECODE_CACHE.get(key)
    if fn is None:
        fn = _build_decode_fn(cfg, b, s0, int(max_new_tokens),
                              float(temperature), int(top_k))
        if len(_DECODE_CACHE) >= 16:
            _DECODE_CACHE.pop(next(iter(_DECODE_CACHE)))
        _DECODE_CACHE[key] = fn
    return fn(p.s, prompt_ids, jax.random.PRNGKey(seed))


# the decode program is cached by (config, shapes, sampling params) —
# params/prompt/rng flow as ARGUMENTS, so repeated generate() calls hit
# the same compiled program instead of retracing per call
_DECODE_CACHE: Dict[Any, Any] = {}
import dataclasses as _dataclasses  # noqa: E402


def _build_decode_fn(cfg: GPTConfig, b: int, s0: int, max_new_tokens: int,
                     temperature: float, top_k: int):
    max_len = s0 + max_new_tokens
    cdt = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    cos, sin = (_rotary_tables(cfg, max_len) if cfg.position == "rotary"
                else (None, None))

    def pick(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        lg = logits / temperature
        if top_k > 0:
            kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
        return jax.random.categorical(key, lg).astype(jnp.int32)

    @jax.jit
    def run(params, prompt_ids, key0):
        p = _Params.__new__(_Params)
        p.s, p.cfg = params, cfg
        if cfg.is_mla:
            # one shared latent stream + (optional) decoupled rope key —
            # mirrors the paged pool's latent k/v page shapes
            shapes = ((b, max_len, 1, cfg.kv_latent_dim),
                      (b, max_len, 1, cfg.rope_dim))
        else:
            shapes = ((b, max_len, cfg.kv_heads, cfg.head_dim),) * 2
        caches = [(jnp.zeros(shapes[0], cdt), jnp.zeros(shapes[1], cdt))
                  for _ in range(cfg.num_layers)]
        logits, cs = decode_step(cfg, p, prompt_ids, caches, 0, cos, sin)
        key, sub = jax.random.split(key0)
        tok = pick(logits, sub)

        def step(carry, _):
            cs, tok, pos, key = carry
            logits, cs = decode_step(cfg, p, tok[:, None], cs, pos, cos, sin)
            key, sub = jax.random.split(key)
            nxt = pick(logits, sub)
            return (cs, nxt, pos + 1, key), tok

        (_, last, _, _), toks = lax.scan(
            step, (cs, tok, jnp.int32(s0), key), None,
            length=max_new_tokens - 1) if max_new_tokens > 1 else \
            ((None, tok, None, None), jnp.zeros((0, b), jnp.int32))
        seq = jnp.concatenate([toks, last[None]], axis=0)  # [T, b]
        return jnp.concatenate([prompt_ids, seq.T], axis=1)

    return run
